"""Device-compute cost plane — roofline attribution + padding waste.

Six planes price every *gap* around device work (compile, shuffle host
drop, spill, queueing); this one opens up the busy time itself.  The
doctor's gated verdict has been ``device_compute`` at ~50% since r12,
and ROADMAP item 4 (Pallas-native operator core) needs a measured
target list, not a hunch.  Three joined ledgers provide it:

- **static-cost store** — at every JIT-cache first call (the
  ``compile_watch.wrap_miss`` choke point: inline miss, AOT warmup and
  persistent-cache load alike) ``capture()`` runs XLA cost analysis on
  the *lowered* program (``Lowered.cost_analysis()`` — trace-only, no
  second backend compile, no device work) and stores flops / bytes
  accessed / IO working set per (program, bucket), bounded at
  ``spark.rapids.tpu.obs.cost.maxRecords``;
- **dispatch ledger** — every ``aot.note_demand`` forwards (program,
  bucket, effective rows) here; rows are read only when the host
  already knows them without a flush (the ``_rows_if_resolved``
  discipline from obs/stats.py), so padding waste = 1 - rows/capacity
  prices the AOT lattice's ``bucketRatio`` with zero round trips;
- **roofline join** — ``query_summary()`` apportions the flush-observer
  busy window (obs/timeline.py, PR 7) over the query's dispatches by
  each program's roofline time estimate max(flops/peak_flops,
  bytes/peak_bw), yielding per-program achieved FLOP/s, achieved GB/s,
  arithmetic intensity and a ``compute_bound``/``memory_bound``
  verdict against the conf-declared peaks.

The doctor (obs/doctor.py) decomposes its ``device_compute`` share
into compute_bound / memory_bound / padding_waste sub-causes from this
plane's summary; obs/profile.py replaces its hand-maintained static
``_INTENSITY`` factors with ``measured_intensity()`` when the store
has live records for an operator class.

``stable_digest()`` covers only the MODEL — version, declared peaks,
ridge intensity, verdict + waste rules — never timings or the
execution-shape-dependent program set, so it is stable across pipeline
parallelism {1,4} x superstage on/off (the plane-determinism
acceptance contract every plane pins).

Hot-path discipline (this file is on the SYNC001/OBS002 lint scope):
no numpy, no device pulls, no formatted flight-record args;
``note_dispatch`` is plain int arithmetic on an interned-key dict and
``capture`` runs at most once per (program, bucket) for the life of
the process.
"""
from __future__ import annotations

import hashlib
import json
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import flight
from . import overhead as _overhead

MODEL_VERSION = 1

#: roofline verdict constants (interned: flight/registry label values)
VERDICT_COMPUTE = "compute_bound"
VERDICT_MEMORY = "memory_bound"

#: capture-origin constants (which path paid for the first call)
ORIGIN_MISS = "miss"
ORIGIN_WARMUP = "warmup"
ORIGIN_PERSISTENT = "persistent"

#: capture-source constants: live XLA cost analysis vs the
#: deterministic static fallback (profile._INTENSITY model) used when
#: lowering is unavailable (non-jit callable, exotic kernel)
SOURCE_XLA = "xla"
SOURCE_STATIC = "static"

_ENABLED = True
#: conf-declared peak rates (roofline ceilings); defaults match the
#: conf defaults in config.py (a TPU v4-class part)
_PEAK_FLOPS = 275.0e12
_PEAK_BYTES = 1200.0e9
_MAX_RECORDS = 256

_LOCK = threading.Lock()
_TLS = threading.local()

#: (program, bucket) -> {"flops", "bytes", "io_bytes", "origin",
#: "source"} — the bounded static-cost store.  First capture wins;
#: a later live capture upgrades a static-fallback record.
_COSTS: Dict[Tuple[str, int], Dict[str, Any]] = {}
_DROPPED = 0
#: capture attempts by source ("xla"/"static") plus skips of
#: already-costed pairs
_CAPTURES = {SOURCE_XLA: 0, SOURCE_STATIC: 0, "skipped": 0}

#: (program, bucket) -> [dispatches, rows_known_dispatches, rows_sum]
#: — process-wide dispatch ledger; ``begin_query()`` snapshots the
#: cells so summaries stay per-query.  Item updates are GIL-atomic;
#: only first-touch takes the lock (the obs/profile.py discipline).
_DISPATCH: Dict[Tuple[str, int], List[int]] = {}
_DISPATCH_DROPPED = 0

#: last query_summary() roll-up (achieved rates for the Prometheus
#: gauges + Service.stats())
_LAST: Dict[str, Any] = {}

#: the wrap_miss cache name "hash_aggregate" is shared by the three
#: aggregate program variants (grouped / whole-stage / global) — one
#: trace cache, three auditor names.  Coverage accounting maps the
#: cache onto every program it compiles (mirrors the PR 10 auditor's
#: REQUIRED_PROGRAMS naming).
_CACHE_COVERS = {
    "hash_aggregate": ("hash_aggregate_grouped",
                       "hash_aggregate_whole_stage",
                       "hash_aggregate_global"),
}

#: operator class -> the JIT caches whose measured per-row cost prices
#: it (substring match discipline identical to profile._INTENSITY, so
#: measured and static factors answer the same lookup)
_CLASS_CACHES = (
    ("sort", ("mesh_sort",)),
    ("topn", ("mesh_sort",)),
    ("join", ("join_probe", "join_spec_probe", "mesh_join")),
    ("aggregate", ("hash_aggregate", "mesh_aggregate")),
    ("agg", ("hash_aggregate", "mesh_aggregate")),
    ("exchange", ("pallas_hash_partition", "exchange_stats")),
    ("filter", ("fused_project",)),
    ("project", ("fused_project",)),
    ("scan", ("fused_project",)),
    ("limit", ("fused_project",)),
    ("range", ("fused_project",)),
)


# ---------------------------------------------------------------------------
# static-cost capture (JIT-cache first calls — cold path by definition)
# ---------------------------------------------------------------------------

def _leaves_of(args, kwargs) -> list:
    try:
        import jax
        return jax.tree_util.tree_leaves((args, kwargs))
    except Exception:  # noqa: BLE001 — capture never fails the call
        return []


def _has_tracer(leaves) -> bool:
    """True when the call is itself being traced (the program auditor
    runs make_jaxpr through wrapped callables) — nothing real to cost,
    and lowering tracer args would raise."""
    try:
        import jax
        return any(isinstance(leaf, jax.core.Tracer) for leaf in leaves)
    except Exception:  # noqa: BLE001
        return False


def _bucket_of(leaves) -> int:
    """Leading-dim capacity of the widest array argument — the bucket
    the program was compiled for.  Derived from the call args, so the
    attribution is identical for miss/warmup/persistent origins (the
    demand ledger's thread-local is stale during warmup)."""
    best = 0
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape and len(shape) >= 1:
            try:
                n = int(shape[0])
            except (TypeError, ValueError):
                continue
            if n > best:
                best = n
    return best


def _static_fallback(cache: str, bucket: int) -> Tuple[float, float]:
    """Deterministic (flops, bytes) estimate from the static operator-
    class intensity table (obs/profile.py) — the fallback for programs
    whose lowering refuses cost analysis.  8 flops and 16 bytes per
    row per intensity unit: coarse on purpose, it only has to rank."""
    from . import profile as _profile
    factor = float(_profile._intensity(cache))
    rows = float(max(bucket, 1))
    return factor * rows * 8.0, factor * rows * 16.0


def capture(cache: str, fn: Callable, args: tuple, kwargs: dict,
            origin: str = ORIGIN_MISS) -> bool:
    """Capture XLA static cost analysis for one freshly first-called
    program into the (program, bucket) store.  Runs on the compile
    path (seconds-scale already) — the analysis itself is a host-side
    pass over the *unoptimized lowered* HLO: no second backend
    compile, no device work, no flush.  Returns False only when the
    call must be retried later (traced args); True when handled."""
    if not _ENABLED or getattr(_TLS, "capturing", False):
        return True
    leaves = _leaves_of(args, kwargs)
    if _has_tracer(leaves):
        return False
    bucket = _bucket_of(leaves)
    key = (cache, bucket)
    with _LOCK:
        prior = _COSTS.get(key)
    if prior is not None and prior["source"] == SOURCE_XLA:
        _CAPTURES["skipped"] += 1
        return True
    _TLS.capturing = True
    try:
        flops, byts, io_bytes, source = 0.0, 0.0, 0.0, SOURCE_STATIC
        try:
            lowered = fn.lower(*args, **kwargs)
            ca = lowered.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            flops = float(ca.get("flops", 0.0) or 0.0)
            byts = float(ca.get("bytes accessed", 0.0) or 0.0)
            # per-operand + output splits ("bytes accessed0{}",
            # "bytes accessedout{}") bound the program's HBM-touched
            # working set; the allocator-truth peak stays with the
            # memplane
            io_bytes = float(sum(
                v for k, v in ca.items()
                if k.startswith("bytes accessed")
                and k != "bytes accessed"))
            source = SOURCE_XLA
        except Exception:  # noqa: BLE001 — cost capture never fails
            flops, byts = _static_fallback(cache, bucket)
            io_bytes = byts
        rec = {"flops": flops, "bytes": byts, "io_bytes": io_bytes,
               "origin": origin, "source": source}
        global _DROPPED
        with _LOCK:
            prior = _COSTS.get(key)
            if prior is not None and prior["source"] == SOURCE_XLA:
                _CAPTURES["skipped"] += 1
                return True
            if prior is None and len(_COSTS) >= _MAX_RECORDS:
                _DROPPED += 1
                return True
            _COSTS[key] = rec
        _CAPTURES[source] += 1
        flight.record(flight.EV_COST, name=cache, a=bucket,
                      b=int(flops))
        try:
            from .registry import COST_CAPTURES
            COST_CAPTURES.labels(source=source).inc()
        except Exception:  # noqa: BLE001 — metrics never fail capture
            pass
        return True
    finally:
        _TLS.capturing = False


def wrap_capture(cache: str, fn: Callable) -> Callable:
    """First-call cost capture for JIT caches that do not route
    through ``compile_watch.wrap_miss`` (the speculative join probes,
    the exchange stats sketch).  Warm calls pay one list-index check —
    the wrap_miss overhead contract."""
    done = [False]

    def _capturing(*args, **kwargs):
        out = fn(*args, **kwargs)
        if not done[0] and capture(cache, fn, args, kwargs,
                                   origin=ORIGIN_MISS):
            done[0] = True
        return out

    return _capturing


# ---------------------------------------------------------------------------
# dispatch ledger (hot path: one call per batch per program)
# ---------------------------------------------------------------------------

def rows_if_resolved(batch) -> Optional[int]:
    """The batch's host row count IF knowable without a flush: a plain
    int, an already-memoized lazy count, or a resolved staged value.
    Anything still device-pending is skipped, never pulled (the
    zero-round-trip contract every plane carries)."""
    try:
        r = batch.rows_lazy
    except Exception:  # noqa: BLE001 — shape-only callers lack rows
        return None
    if isinstance(r, int):
        return r
    v = getattr(r, "_val", None)
    if v is not None:
        return int(v)
    st = getattr(r, "_staged", None)
    if st is not None and st.resolved:
        return int(r)
    return None


def note_dispatch(cache: str, capacity: int,
                  rows: Optional[int] = None) -> None:
    """One program dispatch at a bucketed capacity (forwarded from
    ``aot.note_demand``).  ``rows`` is the effective row count when
    the host already knows it; padded-capacity waste accrues only over
    rows-known dispatches so the fraction is exact, never guessed."""
    if not _ENABLED:
        return
    _mt0 = _overhead.clock()
    key = (cache, int(capacity))
    cell = _DISPATCH.get(key)
    if cell is None:
        global _DISPATCH_DROPPED
        with _LOCK:
            if len(_DISPATCH) >= _MAX_RECORDS:
                _DISPATCH_DROPPED += 1
                return
            cell = _DISPATCH.setdefault(key, [0, 0, 0])
    cell[0] += 1
    if rows is not None:
        cell[1] += 1
        cell[2] += int(rows)
    _overhead.note(_overhead.P_COST, _mt0)


# ---------------------------------------------------------------------------
# roofline model
# ---------------------------------------------------------------------------

def ridge_intensity() -> float:
    """flops/byte at the roofline ridge: programs below it cannot
    reach peak FLOP/s no matter how good the kernel is."""
    return _PEAK_FLOPS / _PEAK_BYTES if _PEAK_BYTES > 0 else 0.0


def roofline_verdict(flops: float, byts: float) -> str:
    """compute_bound when arithmetic intensity clears the ridge;
    memory_bound below it (including the degenerate zero-flop
    program, which can only be waiting on bytes)."""
    if byts <= 0.0:
        return VERDICT_COMPUTE if flops > 0.0 else VERDICT_MEMORY
    return (VERDICT_COMPUTE
            if flops / byts >= ridge_intensity() else VERDICT_MEMORY)


def _t_est_s(flops: float, byts: float) -> float:
    """Roofline execution-time estimate: the binding ceiling's wall
    seconds.  Floor keeps zero-cost records from vanishing out of the
    busy apportionment."""
    t = max(flops / _PEAK_FLOPS if _PEAK_FLOPS > 0 else 0.0,
            byts / _PEAK_BYTES if _PEAK_BYTES > 0 else 0.0)
    return t if t > 0.0 else 1e-12


# ---------------------------------------------------------------------------
# per-query window
# ---------------------------------------------------------------------------

def begin_query() -> Dict[Tuple[str, int], Tuple[int, int, int]]:
    """Snapshot the dispatch ledger so ``query_summary`` can delta a
    per-query window out of the process-wide cells (the FLUSH_COUNT
    discipline: exact when queries run serially)."""
    if not _ENABLED:
        return {}
    with _LOCK:
        return {k: (c[0], c[1], c[2]) for k, c in _DISPATCH.items()}


def query_summary(marker, busy_ms: Optional[float] = None
                  ) -> Dict[str, Any]:
    """Join the window's dispatches with the static-cost store and the
    flush-observer busy window into the per-query costplane artifact.
    Pure host arithmetic over dicts already in hand — zero flushes."""
    marker = marker or {}
    with _LOCK:
        deltas = []
        for key, cell in _DISPATCH.items():
            prev = marker.get(key, (0, 0, 0))
            d = cell[0] - prev[0]
            if d <= 0:
                continue
            deltas.append((key, d, cell[1] - prev[1],
                           cell[2] - prev[2]))
        costs = {k: dict(v) for k, v in _COSTS.items()}
    busy_s = (busy_ms or 0.0) / 1e3
    entries: List[Dict[str, Any]] = []
    uncosted = 0
    weights: List[float] = []
    for (cache, bucket), d, known, rows_sum in sorted(deltas):
        rec = costs.get((cache, bucket))
        waste = None
        if known > 0 and bucket > 0:
            waste = max(0.0, 1.0 - rows_sum / float(known * bucket))
        if rec is None:
            uncosted += d
            entries.append({
                "program": cache, "bucket": bucket, "dispatches": d,
                "flops": None, "bytes": None, "intensity": None,
                "verdict": None, "source": None, "origin": None,
                "est_share_pct": None, "achieved_gflops": None,
                "achieved_gbps": None,
                "padding_waste_pct":
                    None if waste is None else round(100.0 * waste, 3),
                "rows_known": known})
            weights.append(0.0)
            continue
        flops, byts = rec["flops"], rec["bytes"]
        entries.append({
            "program": cache, "bucket": bucket, "dispatches": d,
            "flops": flops, "bytes": byts,
            "intensity":
                round(flops / byts, 4) if byts > 0 else None,
            "verdict": roofline_verdict(flops, byts),
            "source": rec["source"], "origin": rec["origin"],
            "est_share_pct": None, "achieved_gflops": None,
            "achieved_gbps": None,
            "padding_waste_pct":
                None if waste is None else round(100.0 * waste, 3),
            "rows_known": known})
        weights.append(d * _t_est_s(flops, byts))
    wsum = sum(weights)
    compute_share = memory_share = 0.0
    total_flops = total_bytes = 0.0
    waste_w = waste_wsum = 0.0
    for e, w in zip(entries, weights):
        if e["verdict"] is None:
            continue
        share = w / wsum if wsum > 0 else 0.0
        e["est_share_pct"] = round(100.0 * share, 3)
        total_flops += e["flops"] * e["dispatches"]
        total_bytes += e["bytes"] * e["dispatches"]
        if e["verdict"] == VERDICT_COMPUTE:
            compute_share += share
        else:
            memory_share += share
        if busy_s > 0.0 and share > 0.0:
            prog_busy = busy_s * share
            e["achieved_gflops"] = round(
                e["flops"] * e["dispatches"] / prog_busy / 1e9, 3)
            e["achieved_gbps"] = round(
                e["bytes"] * e["dispatches"] / prog_busy / 1e9, 3)
        if e["padding_waste_pct"] is not None:
            waste_w += share * (e["padding_waste_pct"] / 100.0)
            waste_wsum += share
    entries.sort(key=lambda e: (-(e["est_share_pct"] or 0.0),
                                e["program"], e["bucket"]))
    if waste_wsum > 0.0:
        padding_pct = round(100.0 * waste_w / waste_wsum, 3)
    else:
        # no time-weighted evidence (nothing costed): fall back to the
        # capacity-weighted ledger view over rows-known dispatches
        cap_rows = sum(key[1] * kn for key, _d, kn, _rs in deltas)
        row_sum = sum(rs for _key, _d, _kn, rs in deltas)
        padding_pct = (round(100.0 * (1.0 - row_sum / cap_rows), 3)
                       if cap_rows > 0 else None)
    verdict = None
    comp_pct, mem_pct = 0.0, 0.0
    if compute_share > 0.0 or memory_share > 0.0:
        verdict = (VERDICT_COMPUTE if compute_share >= memory_share
                   else VERDICT_MEMORY)
        # the two shares partition the costed busy weight: round one,
        # derive the other, so the published pair sums to exactly 100
        comp_pct = round(100.0 * compute_share, 3)
        mem_pct = round(100.0 - comp_pct, 3)
    out = {
        "programs": entries,
        "busy_ms": busy_ms,
        "achieved_gflops":
            round(total_flops / busy_s / 1e9, 3) if busy_s > 0 else None,
        "achieved_gbps":
            round(total_bytes / busy_s / 1e9, 3) if busy_s > 0 else None,
        "padding_waste_pct": padding_pct,
        "verdict": verdict,
        "compute_share_pct": comp_pct,
        "memory_share_pct": mem_pct,
        "uncosted_dispatches": uncosted,
        "costed_records": len(costs),
        "peak_tflops": round(_PEAK_FLOPS / 1e12, 3),
        "peak_gbps": round(_PEAK_BYTES / 1e9, 3),
        "ridge_intensity": round(ridge_intensity(), 3),
        "model_version": MODEL_VERSION,
        "digest": stable_digest(),
    }
    with _LOCK:
        _LAST.clear()
        _LAST.update({k: out[k] for k in
                      ("achieved_gflops", "achieved_gbps",
                       "padding_waste_pct", "verdict")})
    try:
        from .registry import COST_VERDICTS
        for e in entries:
            if e["verdict"] is not None:
                COST_VERDICTS.labels(verdict=e["verdict"]).inc()
    except Exception:  # noqa: BLE001 — metrics never fail the summary
        pass
    return out


# ---------------------------------------------------------------------------
# profile integration: measured per-class intensity
# ---------------------------------------------------------------------------

def _per_row_cost(caches) -> Optional[float]:
    tot, n = 0.0, 0
    for (cache, bucket), rec in _COSTS.items():
        if cache in caches and bucket > 0 \
                and rec["source"] == SOURCE_XLA:
            tot += (rec["flops"] + rec["bytes"]) / bucket
            n += 1
    return tot / n if n else None


def measured_intensity(name: str) -> Optional[float]:
    """Measured per-output-row FLOP+byte weight for an operator class,
    normalized to the project program — the live replacement for
    obs/profile.py's static ``_INTENSITY`` factors.  None when the
    class (or the project baseline) has no live capture yet; the
    caller falls back to the static table, keeping member shares
    deterministic for uncompiled members."""
    if not _ENABLED:
        return None
    low = name.lower()
    caches = None
    for key, cs in _CLASS_CACHES:
        if key in low:
            caches = cs
            break
    if caches is None:
        return None
    with _LOCK:
        cls = _per_row_cost(caches)
        base = _per_row_cost(("fused_project",))
    if cls is None or base is None or base <= 0.0:
        return None
    return cls / base


# ---------------------------------------------------------------------------
# coverage (mirrors the PR 10 auditor's REQUIRED_PROGRAMS gate)
# ---------------------------------------------------------------------------

def costed_programs() -> List[str]:
    """Auditor-named programs with at least one static-cost record
    (the shared hash_aggregate trace cache covers its three program
    variants — see _CACHE_COVERS)."""
    out = set()
    with _LOCK:
        caches = {cache for cache, _b in _COSTS}
    for cache in caches:
        out.update(_CACHE_COVERS.get(cache, (cache,)))
    return sorted(out)


def coverage_gaps(required=None) -> List[str]:
    """REQUIRED_PROGRAMS members with no captured static cost —
    the costplane twin of program_audit.coverage_gaps."""
    if required is None:
        from ..analysis import program_audit as _pa
        required = _pa.REQUIRED_PROGRAMS
    return sorted(set(required) - set(costed_programs()))


# ---------------------------------------------------------------------------
# surfaces
# ---------------------------------------------------------------------------

def stable_digest() -> str:
    """sha256 over the timing-independent cost MODEL only: version,
    declared peak rates, ridge, verdict + waste rules.  The captured
    program set and every achieved rate are execution-shape dependent
    (superstage on/off compiles different programs) and are excluded —
    same conf x same model -> same digest across pipeline parallelism
    {1,4} x superstage on/off."""
    payload = {
        "model_version": MODEL_VERSION,
        "peak_flops": _PEAK_FLOPS,
        "peak_bytes": _PEAK_BYTES,
        "ridge_intensity": ridge_intensity(),
        "verdict_rule": "intensity_vs_ridge",
        "waste_rule": "1_minus_rows_over_capacity_rows_known_only",
    }
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def process_waste_pct() -> float:
    """Capacity-weighted padding waste over every rows-known dispatch
    since process start (the tpu_cost_padding_waste_pct gauge)."""
    with _LOCK:
        cap_rows = sum(k * c[1] for (_p, k), c in _DISPATCH.items())
        rows = sum(c[2] for c in _DISPATCH.values())
    if cap_rows <= 0:
        return 0.0
    return round(100.0 * (1.0 - rows / cap_rows), 3)


def record_count() -> int:
    with _LOCK:
        return len(_COSTS)


def dropped_count() -> int:
    with _LOCK:
        return _DROPPED + _DISPATCH_DROPPED


def last_achieved(key: str) -> float:
    with _LOCK:
        v = _LAST.get(key)
    return float(v) if isinstance(v, (int, float)) else 0.0


def static_costs() -> Dict[Tuple[str, int], Dict[str, Any]]:
    """Snapshot of the (program, bucket) static-cost store (tests,
    auditor-style coverage gates)."""
    with _LOCK:
        return {k: dict(v) for k, v in _COSTS.items()}


def stats_section() -> Dict[str, Any]:
    """Process-lifetime roll-up for Service.stats()["cost"] and the
    diagnostic bundle."""
    with _LOCK:
        records = len(_COSTS)
        dropped = _DROPPED + _DISPATCH_DROPPED
        captures = dict(_CAPTURES)
        last = dict(_LAST)
    return {
        "enabled": _ENABLED,
        "records": records,
        "dropped": dropped,
        "captures": captures,
        "programs_costed": costed_programs(),
        "padding_waste_pct": process_waste_pct(),
        "peak_tflops": round(_PEAK_FLOPS / 1e12, 3),
        "peak_gbps": round(_PEAK_BYTES / 1e9, 3),
        "ridge_intensity": round(ridge_intensity(), 3),
        "last_query": last or None,
        "model_version": MODEL_VERSION,
        "digest": stable_digest(),
    }


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def enabled(conf) -> bool:
    from ..config import OBS_COST_ENABLED
    return bool(conf.get(OBS_COST_ENABLED)) and _ENABLED


def configure(conf) -> None:
    """Apply the ``spark.rapids.tpu.obs.cost.*`` conf group."""
    global _ENABLED, _PEAK_FLOPS, _PEAK_BYTES, _MAX_RECORDS
    from ..config import (OBS_COST_ENABLED, OBS_COST_MAX_RECORDS,
                          OBS_COST_PEAK_HBM_GBPS, OBS_COST_PEAK_TFLOPS)
    _ENABLED = bool(conf.get(OBS_COST_ENABLED))
    tflops = float(conf.get(OBS_COST_PEAK_TFLOPS))
    gbps = float(conf.get(OBS_COST_PEAK_HBM_GBPS))
    if tflops > 0:
        _PEAK_FLOPS = tflops * 1e12
    if gbps > 0:
        _PEAK_BYTES = gbps * 1e9
    cap = int(conf.get(OBS_COST_MAX_RECORDS))
    if cap > 0:
        _MAX_RECORDS = cap


def reset() -> None:
    """Test hook: drop the cost store, dispatch ledger and counters."""
    global _DROPPED, _DISPATCH_DROPPED
    with _LOCK:
        _COSTS.clear()
        _DISPATCH.clear()
        _LAST.clear()
        _DROPPED = 0
        _DISPATCH_DROPPED = 0
        for k in _CAPTURES:
            _CAPTURES[k] = 0
