"""Exchange-boundary data statistics + the per-query StatsProfile.

The AQE re-optimization barrier (ROADMAP item 3) is exchange
materialization — the one point where a whole stage's output is known
and the plan downstream can still change.  This module collects, AT
that barrier and in the SAME dispatch window as the partition split:

- per-partition rows and (nominal-width) bytes,
- per-partition null-key counts,
- min/max of the leading key column (canonical order words, decoded
  back to values for integral keys),
- an approximate distinct-key count from an on-device HLL-style
  register sketch (scatter-max of trailing-zero ranks), and
- a skew verdict (max/median partition-row ratio vs
  ``spark.rapids.tpu.obs.stats.skewFactor``).

Zero-extra-flush contract: the sketch program is enqueued lazily right
after the split's own device work and its outputs are STAGED through
the pending pool (columnar/pending.py), so the exchange's existing
finalize flush resolves them for free; per-partition rows are read from
the split offsets the finalize already pulled.  A speculative batch
whose fit flag failed re-stages its statistics from the exact batch
BEFORE ``finalize_split`` forces the redo flush — still zero added
round trips.  ``tests/test_stats.py`` asserts the FLUSH_COUNT delta.

TPU notes: the chip cannot bitcast 64-bit types (canon.py:55), so the
sketch derives its register index from the hash's high u32 and the
rank from the low u32's lowest set bit (an exact power of two, so the
f32 log2 is exact) — no 64-bit bitcasts anywhere.  The scatter-max
runs once per map batch at register-file size, far off the
searchsorted-vs-scatter tradeoff that shapes the split itself
(shuffle/partitioners.py).

The per-query ``StatsProfile`` joins these exchange/scan entries with
the superstage time attribution (obs/profile.py) and the dispatch
p50/p95 summary; its ``stable_digest()`` covers only the
data-dependent entries (never timings), so it is sha-stable across
pipeline parallelism and superstage on/off.
"""
from __future__ import annotations

import functools
import hashlib
import itertools
import json
import logging
import os
import threading
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from . import flight
from . import overhead as _overhead

_LOG = logging.getLogger("spark_rapids_tpu.obs.stats")

#: canonical-order sign flip for integral key words (kernels/canon.py)
_SIGN64 = 0x8000000000000000
#: null-key sentinel word — must match the partitioners' routing
_NULL_SENTINEL = 0x9E3779B97F4A7C15

#: flight-recorder names (interned constants; OBS002 discipline)
_EV_EXCHANGE = "exchange"
_EV_SCAN = "scan"

# False after the sketch program failed once on this backend: the
# exchange keeps rows/bytes stats and drops the sketch (same fallback
# shape as HashPartitioner._SPLIT_JIT's False sentinel).
_SKETCH_OK = True
_SKETCH_LOCK = threading.Lock()


def enabled(conf=None) -> bool:
    from ..config import get_active, OBS_STATS_ENABLED
    return bool((conf or get_active()).get(OBS_STATS_ENABLED))


def sketch_registers(conf=None) -> int:
    from ..config import get_active, OBS_STATS_SKETCH_REGISTERS
    m = int((conf or get_active()).get(OBS_STATS_SKETCH_REGISTERS))
    m = max(64, m)
    return 1 << (m.bit_length() - 1)   # round down to a power of two


def sample_every(conf=None) -> int:
    """Sketch-sampling period: stage the stats program for the first
    map batch of each exchange and every Nth after; 1 means exact
    (every batch).  Rows/bytes/skew stay EXACT regardless — they come
    free from the split offsets.  The test harness forces exact mode
    via ``SPARK_RAPIDS_TPU_OBS_STATS_EXACT`` (tests/conftest.py) so
    stats digests stay deterministic under test."""
    if os.environ.get("SPARK_RAPIDS_TPU_OBS_STATS_EXACT"):
        return 1
    from ..config import get_active, OBS_STATS_SAMPLE_EVERY
    return max(1, int((conf or get_active()).get(OBS_STATS_SAMPLE_EVERY)))


# ---------------------------------------------------------------------------
# on-device sketch program (enqueued with the split; never pulled here)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(5, 6))
def _stats_prog(h, pids, valid, word0, num_rows, nparts: int, m: int):
    """One fused stats program per map batch: HLL registers + null
    counts + key-word min/max, all per partition.

    rho = 1 + trailing-zero count of the hash's low 32 bits (the
    lowest set bit is an exact power of two, so its f32 log2 is exact);
    register index = high 32 bits masked to m (a power of two)."""
    cap = h.shape[0]
    live = jnp.arange(cap) < num_rows
    lv = live & valid
    pid_c = jnp.clip(pids, 0, nparts - 1).astype(jnp.int32)
    j = ((h >> jnp.uint64(32)).astype(jnp.uint32)
         & jnp.uint32(m - 1)).astype(jnp.int32)
    low = (h & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    lowbit = low & (~low + jnp.uint32(1))
    rho = jnp.int32(1) + jnp.log2(
        jnp.maximum(lowbit, jnp.uint32(1)).astype(jnp.float32)
    ).astype(jnp.int32)
    rho = jnp.where(low == 0, jnp.int32(33), rho)
    rho = jnp.where(lv, rho, jnp.int32(0))
    regs = jnp.zeros((nparts, m), jnp.int32).at[pid_c, j].max(rho)
    nulls = jnp.zeros(nparts, jnp.int32).at[pid_c].add(
        (live & ~valid).astype(jnp.int32))
    big = jnp.uint64(0xFFFFFFFFFFFFFFFF)
    wmin = jnp.full(nparts, big, jnp.uint64).at[pid_c].min(
        jnp.where(lv, word0, big))
    wmax = jnp.zeros(nparts, jnp.uint64).at[pid_c].max(
        jnp.where(lv, word0, jnp.uint64(0)))
    return regs, nulls, wmin, wmax


# device-compute cost plane first-call capture: exchange_stats has no
# wrap_miss site (one module-level jit, not a keyed cache), so the
# plane's own wrapper supplies the static-cost record.  The program
# auditor keeps lowering the unwrapped jit via _audit_specs below.
_stats_prog_jit = _stats_prog
from . import costplane as _costplane  # noqa: E402
_stats_prog = _costplane.wrap_capture("exchange_stats", _stats_prog_jit)


class ExchangeBatchStats:
    """Staged (unresolved) stats of one map batch: resolves for free in
    the exchange's own finalize flush."""

    __slots__ = ("regs", "nulls", "wmin", "wmax", "key_dtype")

    def __init__(self, regs, nulls, wmin, wmax, key_dtype):
        self.regs = regs
        self.nulls = nulls
        self.wmin = wmin
        self.wmax = wmax
        self.key_dtype = key_dtype

    @property
    def resolved(self) -> bool:
        return all(h.resolved for h in
                   (self.regs, self.nulls, self.wmin, self.wmax))


def _rows_if_resolved(batch) -> Optional[int]:
    """The batch's host row count IF knowable without a flush."""
    r = batch.rows_lazy
    if isinstance(r, int):
        return r
    if r._val is not None:
        return r._val
    st = r._staged
    if st is not None and st.resolved:
        return int(r)
    return None


def stage_exchange_batch(partitioner, batch, m: int, acc=None,
                         force: bool = False
                         ) -> Optional[ExchangeBatchStats]:
    """Enqueue the stats program for one map batch (hash exchanges
    only) and stage its outputs.  Lazy device work in the split's own
    dispatch window — nothing here pulls.

    When ``acc`` is passed, its sampling gate decides whether this
    batch is sketched at all (every Nth; ``sample_every``): the skip
    path costs one counter tick and none of the expression/hash/
    program staging below.  ``force`` bypasses the gate — the
    speculative-redo path uses it to replace a sketch that was already
    staged (and counted) for a batch whose table-path assumptions
    failed, keeping ``acc.sketched`` consistent."""
    global _SKETCH_OK
    from ..shuffle.partitioners import HashPartitioner
    if not _SKETCH_OK or not isinstance(partitioner, HashPartitioner) \
            or not partitioner.key_exprs or batch.capacity == 0:
        return None
    if acc is not None and not force and not acc.want_sketch():
        return None
    _mt0 = _overhead.clock()
    try:
        from ..columnar import pending
        from ..columnar.column import StringColumn
        from ..expr import core as ec
        from ..kernels import basic as bk
        from ..kernels import canon
        word_lists: List = []
        valid = None
        word0 = None
        key_dtype = None
        for e in partitioner.key_exprs:
            bound = e.bind(batch.schema)
            col = ec.eval_as_column(bound, batch)
            if isinstance(col, StringColumn):
                nr = _rows_if_resolved(batch)
                if nr is None:
                    return None   # a host count here would add a flush
            else:
                nr = batch.rows_dev
            words = canon.value_words(col, nr)
            if word0 is None:
                word0 = words[0]
                key_dtype = col.dtype
            for w in words:
                word_lists.append(jnp.where(col.validity, w,
                                            jnp.uint64(_NULL_SENTINEL)))
            valid = col.validity if valid is None \
                else (valid & col.validity)
        h = bk.hash_words(word_lists)
        pids = (h % jnp.uint64(partitioner.num_partitions)
                ).astype(jnp.int32)
        from ..compile import aot as _aot
        _aot.note_demand("exchange_stats", batch.capacity,
                         _rows_if_resolved(batch))
        regs, nulls, wmin, wmax = _stats_prog(
            h, pids, valid, word0, batch.rows_dev,
            partitioner.num_partitions, m)
        st = ExchangeBatchStats(
            pending.stage(regs), pending.stage(nulls),
            pending.stage(wmin), pending.stage(wmax), key_dtype)
        _overhead.note(_overhead.P_STATS, _mt0)
        return st
    except Exception:  # noqa: BLE001 — stats must never fail the query
        with _SKETCH_LOCK:
            if _SKETCH_OK:
                _SKETCH_OK = False
                _LOG.warning("exchange stats sketch failed; disabled "
                             "for this process", exc_info=True)
        _overhead.note(_overhead.P_STATS, _mt0)
        return None


# ---------------------------------------------------------------------------
# per-exchange accumulator (lives on the exec node; finalize is serial
# under the exchange's materialization lock)
# ---------------------------------------------------------------------------

class ExchangeAcc:
    def __init__(self, nparts: int, m: int, row_width: float, kind: str,
                 partitioner_name: str, every: int = 1):
        self.kind = kind
        self.partitioner = partitioner_name
        self.nparts = nparts
        self.m = m
        self.row_width = row_width
        self.sample_every = max(1, int(every))
        self._sampler = itertools.count()
        self.rows = np.zeros(nparts, np.int64)
        self.nulls = np.zeros(nparts, np.int64)
        self.regs: Optional[np.ndarray] = None
        self.wmin = np.full(nparts, np.uint64(0xFFFFFFFFFFFFFFFF),
                            np.uint64)
        self.wmax = np.zeros(nparts, np.uint64)
        self.key_dtype = None
        self.batches = 0
        self.sketched = 0

    def want_sketch(self) -> bool:
        """Sampling gate (stage_exchange_batch): sketch the first
        batch and every Nth after.  ``next`` on an itertools.count is
        a single GIL-atomic tick, so concurrent pipelined map
        producers need no lock — each staged batch draws exactly one
        ticket."""
        if self.sample_every <= 1:
            return True
        return next(self._sampler) % self.sample_every == 0

    def absorb(self, offsets: np.ndarray,
               handles: Optional[ExchangeBatchStats]):
        """Merge one finalized map batch: rows come free from the split
        offsets the finalize already pulled; sketch/null/min-max merge
        from the staged handles IF the finalize flush resolved them
        (register max / count add / word min-max are commutative, so
        accumulation order — hence pipeline parallelism — cannot change
        the result)."""
        self.batches += 1
        self.rows += np.diff(offsets).astype(np.int64)
        if handles is None or not handles.resolved:
            return
        self.sketched += 1
        self.key_dtype = handles.key_dtype
        regs = handles.regs.np
        self.regs = regs.copy() if self.regs is None \
            else np.maximum(self.regs, regs)
        self.nulls += handles.nulls.np.astype(np.int64)
        self.wmin = np.minimum(self.wmin, handles.wmin.np)
        self.wmax = np.maximum(self.wmax, handles.wmax.np)


def exchange_acc(node, nparts: int, m: int, row_width: float, kind: str,
                 partitioner_name: str,
                 every: Optional[int] = None) -> ExchangeAcc:
    acc = getattr(node, "_stats_acc", None)
    if acc is None:
        acc = node._stats_acc = ExchangeAcc(
            nparts, m, row_width, kind, partitioner_name,
            every if every is not None else sample_every())
    return acc


def hll_estimate(regs: np.ndarray) -> float:
    """Standard HLL estimator with the small-range linear-counting
    correction, over one register vector (union = elementwise max)."""
    m = int(regs.shape[0])
    alpha = 0.7213 / (1.0 + 1.079 / m)
    inv = np.power(2.0, -regs.astype(np.float64))
    est = alpha * m * m / float(inv.sum())
    zeros = int((regs == 0).sum())
    if est <= 2.5 * m and zeros:
        est = m * float(np.log(m / zeros))
    return float(est)


def _decode_word(word: int, key_dtype) -> Optional[int]:
    """Canonical order word -> key value for integral-ish keys (the
    sign-flip encoding in kernels/canon.py); None for other dtypes
    (their words are order-preserving but not trivially invertible)."""
    if key_dtype is None or not getattr(key_dtype, "is_integral", False):
        return None
    return int(np.array([np.uint64(word) ^ np.uint64(_SIGN64)],
                        np.uint64).view(np.int64)[0])


def _skew_verdict(rows: np.ndarray, factor: float) -> Dict:
    mx = int(rows.max()) if rows.size else 0
    med = float(np.median(rows)) if rows.size else 0.0
    if med > 0.0:
        ratio = mx / med
    else:
        ratio = float("inf") if mx > 0 else 1.0
    return {"max_rows": mx, "median_rows": med,
            "ratio": round(ratio, 4) if np.isfinite(ratio) else None,
            "skewed": bool(rows.size > 1 and
                           (not np.isfinite(ratio) or ratio > factor))}


def finish_exchange(node, conf=None) -> Optional[Dict]:
    """Close a shuffle exchange's accumulator into its stats entry and
    publish the registry/flight views.  Called once, at the end of the
    map-side materialization barrier."""
    acc: Optional[ExchangeAcc] = getattr(node, "_stats_acc", None)
    if acc is None:
        return None
    _mt0 = _overhead.clock()
    from ..config import get_active, OBS_STATS_SKEW_FACTOR
    from .registry import (STATS_EXCHANGES, STATS_LAST_DISTINCT_KEYS,
                           STATS_LAST_SKEW_RATIO, STATS_PARTITION_ROWS,
                           STATS_SKEWED_EXCHANGES)
    factor = float((conf or get_active()).get(OBS_STATS_SKEW_FACTOR))
    skew = _skew_verdict(acc.rows, factor)
    # exact: every finalized batch carried a resolved sketch.  Under
    # sampling (obs.stats.sampleEvery > 1) only every Nth did — the
    # sketch-derived fields then come from the sampled subset and the
    # entry says so via its "sample" block.  rows/bytes/skew are from
    # the split offsets and stay exact regardless; null counts are
    # per-row tallies that cannot be extrapolated honestly, so they
    # stay exact-mode-only.
    exact = acc.regs is not None and acc.sketched == acc.batches
    have_sketch = acc.regs is not None and acc.sketched > 0
    distinct = hll_estimate(acc.regs.max(axis=0)) if have_sketch else None
    entry = {
        "kind": acc.kind,
        "partitioner": acc.partitioner,
        "partitions": [
            {"rows": int(r),
             "bytes": int(round(r * acc.row_width)),
             "nulls": int(n) if exact else None}
            for r, n in zip(acc.rows, acc.nulls)],
        "rows": int(acc.rows.sum()),
        "est_bytes": int(round(float(acc.rows.sum()) * acc.row_width)),
        "null_count": int(acc.nulls.sum()) if exact else None,
        "key_min": _decode_word(int(acc.wmin.min()), acc.key_dtype)
        if have_sketch and acc.rows.sum() else None,
        "key_max": _decode_word(int(acc.wmax.max()), acc.key_dtype)
        if have_sketch and acc.rows.sum() else None,
        "distinct_est": round(distinct, 1) if distinct is not None
        else None,
        "skew": skew,
    }
    if have_sketch and not exact:
        entry["sample"] = {"every": acc.sample_every,
                           "sketched": acc.sketched,
                           "batches": acc.batches}
    node._stats_entry = entry
    STATS_EXCHANGES.labels(kind=acc.kind).inc()
    for r in acc.rows:
        STATS_PARTITION_ROWS.observe(float(r))
    ratio = skew["ratio"]
    STATS_LAST_SKEW_RATIO.set(ratio if ratio is not None else 0.0)
    if distinct is not None:
        STATS_LAST_DISTINCT_KEYS.set(distinct)
    if skew["skewed"]:
        STATS_SKEWED_EXCHANGES.inc()
    permille = min(int((ratio or 0.0) * 1000), 10_000_000)
    dist_i = int(distinct or 0)
    flight.record(flight.EV_STATS, _EV_EXCHANGE, permille, dist_i)
    _overhead.note(_overhead.P_STATS, _mt0)
    return entry


# ---------------------------------------------------------------------------
# scan + broadcast entries (host-side bookkeeping; zero device work)
# ---------------------------------------------------------------------------

def _row_width(schema) -> float:
    from .profile import _nominal_row_bytes
    return _nominal_row_bytes(schema)


def note_scan(node, part_rows: List[int]):
    """Per-partition output sizes of a scan (exact, from the slicing
    arithmetic the scan already does)."""
    width = _row_width(getattr(node, "output_schema", None))
    node._stats_entry = {
        "kind": "scan",
        "partitions": [{"rows": int(r),
                        "bytes": int(round(r * width))}
                       for r in part_rows],
        "rows": int(sum(part_rows)),
    }
    flight.record(flight.EV_STATS, _EV_SCAN, len(part_rows),
                  int(sum(part_rows)))


def count_scan_partitions(node, parts):
    """Wrap a scan's partition iterators to accumulate per-partition
    output rows host-side as batches stream: file scans learn their
    sizes only at read time, and the counts are host metadata on
    already-materialized batches, so this costs zero device work.
    build_profile materializes the entry from the accumulated rows."""
    rows = [0] * len(parts)
    node._stats_scan_rows = rows

    def wrap(i, it):
        for b in it:
            n = getattr(b, "num_rows", None)
            if isinstance(n, int):
                rows[i] += n
            yield b
    return [wrap(i, it) for i, it in enumerate(parts)]


def _finish_scan(node) -> Optional[Dict]:
    rows = getattr(node, "_stats_scan_rows", None)
    if rows is None:
        return None
    width = _row_width(getattr(node, "output_schema", None))
    return {
        "kind": "scan",
        "partitions": [{"rows": int(r),
                        "bytes": int(round(r * width))}
                       for r in rows],
        "rows": int(sum(rows)),
    }


def note_broadcast(node, batch):
    """Defer the broadcast's row stat to profile-build time: the
    single-batch build path costs zero round trips (exec/exchange.py)
    and forcing a count here would break that.  Unconditional (no conf
    gate): build threads have no reliable ambient conf, so the
    session's conf decides at build_profile time instead."""
    node._stats_broadcast = batch


def _finish_broadcast(node) -> Optional[Dict]:
    batch = getattr(node, "_stats_broadcast", None)
    if batch is None:
        return None
    rows = _rows_if_resolved(batch)
    width = _row_width(getattr(node, "output_schema", None))
    from .registry import STATS_EXCHANGES
    STATS_EXCHANGES.labels(kind="broadcast").inc()
    return {
        "kind": "broadcast",
        "partitions": [{"rows": int(rows) if rows is not None else None,
                        "bytes": int(round(rows * width))
                        if rows is not None else None}],
        "rows": int(rows) if rows is not None else None,
    }


# ---------------------------------------------------------------------------
# the per-query artifact
# ---------------------------------------------------------------------------

class StatsProfile:
    """Per-query stats artifact: exchange/scan data statistics,
    superstage time attribution, and the dispatch-duration summary.
    Persisted in the event-log record (tools/report.py --stats) and on
    ``session.last_stats_profile``."""

    VERSION = 1

    def __init__(self, data: Dict):
        self.data = data

    def to_dict(self) -> Dict:
        return self.data

    def get(self, key, default=None):
        return self.data.get(key, default)

    def __getitem__(self, key):
        return self.data[key]

    def stable_digest(self) -> str:
        """sha256 over the DATA-dependent entries only (shuffle
        exchanges + scans; no timings, no flush counts), so the digest
        is stable across pipeline parallelism and superstage on/off —
        the determinism surface tests/test_stats.py pins.  Broadcast
        entries are excluded: their row stat is read best-effort from
        whatever the query's own flushes happened to resolve (the
        zero-round-trip contract forbids forcing it), which is
        execution-shape dependent.  ``node_index`` is dropped too —
        preorder positions shift when the carve pass wraps regions,
        without changing any data statistic."""

        def _strip(e):
            return {k: v for k, v in e.items() if k != "node_index"}
        det = {"exchanges": [_strip(e)
                             for e in self.data.get("exchanges", [])
                             if e.get("kind") != "broadcast"],
               "scans": [_strip(e) for e in self.data.get("scans", [])]}
        blob = json.dumps(det, sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()


def build_profile(phys, query_id=None, flushes: Optional[int] = None,
                  dispatch_marker: Optional[Dict[str, int]] = None
                  ) -> StatsProfile:
    """Harvest the per-node stats state of an executed plan into one
    StatsProfile.  Read-only over resolved values: never forces a
    flush (the profile is built AFTER the query's flush window) —
    and, since r17, after the query's recorded wall clock stops: the
    session defers this call to event-log write time."""
    from . import profile as _profile
    _mt0 = _overhead.clock()
    exchanges: List[Dict] = []
    scans: List[Dict] = []
    stages: List[Dict] = []
    for idx, node in enumerate(phys.collect_nodes()):
        entry = getattr(node, "_stats_entry", None)
        if entry is None and getattr(node, "_stats_broadcast", None) \
                is not None:
            entry = _finish_broadcast(node)
        if entry is None:
            entry = _finish_scan(node)
        if entry is not None:
            e = dict(entry)
            e["node_index"] = idx
            e["node"] = node.name
            (scans if e["kind"] == "scan" else exchanges).append(e)
        if getattr(node, "lowering", None) is not None and \
                getattr(node, "members", None):
            sp = getattr(node, "_stage_profile", None)
            shares = _profile.member_shares(node)
            device_ns = sp.device_ns if sp is not None else 0
            stages.append({
                "node_index": idx,
                "node": node.name,
                "members": [f"{i}:{m.name}"
                            for i, m in enumerate(node.members)],
                "device_ms": round(device_ns / 1e6, 3),
                "flushes": sp.flushes if sp is not None else 0,
                "member_share": shares,
                "member_device_ms": {
                    k: round(v * device_ns / 1e6, 3)
                    for k, v in shares.items()},
            })
    prof = StatsProfile({
        "version": StatsProfile.VERSION,
        "query_id": query_id,
        "flushes": flushes,
        "exchanges": exchanges,
        "scans": scans,
        "superstages": stages,
        "dispatches": _profile.dispatch_summary(dispatch_marker),
    })
    _overhead.note(_overhead.P_STATS, _mt0)
    return prof


# ---------------------------------------------------------------------------
# program audit registration (analysis/program_audit.py): exact=False —
# the stats program intentionally uses float log2 for the distinct-
# count sketch; it produces observability estimates, never query data.
# ---------------------------------------------------------------------------

def _audit_specs():
    from ..analysis.program_audit import AuditSpec

    def _build():
        import jax
        import numpy as np
        cap = 128
        args = (jax.ShapeDtypeStruct((cap,), np.uint64),
                jax.ShapeDtypeStruct((cap,), np.int32),
                jax.ShapeDtypeStruct((cap,), np.bool_),
                jax.ShapeDtypeStruct((cap,), np.uint64),
                jax.ShapeDtypeStruct((), np.int32),
                4, 64)
        return _stats_prog_jit, args, {"static_argnums": (5, 6)}

    return [AuditSpec(
        "exchange_stats", "exchange_stats", _build, exact=False,
        notes="exchange-boundary stats sketch (float log2 is "
              "intentional: estimates, not query data)",
        budgets={"gather": 4, "scatter": 8, "transpose": 2, "sort": 2})]
