"""Process-wide metrics registry — counters, gauges, fixed-bucket
histograms (the GpuMetric -> Spark-SQL-UI role lifted to a serving
process: one registry every subsystem writes into, scraped as a whole).

Instruments are get-or-create by name (re-registering returns the
existing family), optionally labeled, and cheap on the hot path: a
counter ``inc`` is one lock-free float add under a per-child lock;
gauges for arena/queue state are *collect-time callbacks* so the memory
and service layers pay nothing per operation.  ``snapshot()`` returns a
plain dict for tests; ``obs.prom`` renders the Prometheus text format.

Stdlib-only; the default instrument callbacks lazy-import engine layers
at collect time to stay import-cycle-free.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

COUNTER, GAUGE, HISTOGRAM = "counter", "gauge", "histogram"

#: wait-time buckets (seconds) shared by the semaphore/queue histograms
WAIT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class _Child:
    """One sample series (a family's instance for one label set)."""
    __slots__ = ("labels", "_lock", "_value", "_fn",
                 "buckets", "_bucket_counts", "_sum", "_count")

    def __init__(self, labels: Tuple[Tuple[str, str], ...],
                 buckets: Optional[Sequence[float]] = None):
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None
        self.buckets = tuple(buckets) if buckets is not None else None
        if self.buckets is not None:
            self._bucket_counts = [0] * (len(self.buckets) + 1)  # +Inf
            self._sum = 0.0
            self._count = 0

    # -- counter/gauge -----------------------------------------------------
    def inc(self, by: float = 1.0):
        with self._lock:
            self._value += by

    def dec(self, by: float = 1.0):
        with self._lock:
            self._value -= by

    def set(self, v: float):
        with self._lock:
            self._value = float(v)

    def set_function(self, fn: Callable[[], float]):
        """Collect-time callback: the series' value is ``fn()`` at each
        scrape/snapshot instead of a stored number (zero hot-path
        cost for state another subsystem already tracks)."""
        self._fn = fn

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return 0.0
        with self._lock:
            return self._value

    # -- histogram ---------------------------------------------------------
    def observe(self, v: float):
        with self._lock:
            i = 0
            for b in self.buckets:
                if v <= b:
                    break
                i += 1
            self._bucket_counts[i] += 1
            self._sum += v
            self._count += 1

    def hist_snapshot(self) -> Dict:
        """Cumulative bucket counts keyed by upper bound + sum/count."""
        with self._lock:
            counts = list(self._bucket_counts)
            total, s = self._count, self._sum
        cum, out = 0, {}
        for b, c in zip(self.buckets, counts):
            cum += c
            out[b] = cum
        out["+Inf"] = total
        return {"buckets": out, "sum": s, "count": total}


class Family:
    """A named metric family: type + help + labeled children."""

    def __init__(self, name: str, typ: str, help: str = "",
                 label_names: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.type = typ
        self.help = help
        self.label_names = tuple(label_names)
        self._buckets = tuple(buckets) if buckets is not None else None
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}

    def labels(self, **kv) -> _Child:
        assert set(kv) == set(self.label_names), \
            f"{self.name}: expected labels {self.label_names}, got {kv}"
        key = tuple(str(kv[n]) for n in self.label_names)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = _Child(tuple(zip(self.label_names, key)),
                                   self._buckets)
                    self._children[key] = child
        return child

    def _default(self) -> _Child:
        assert not self.label_names, \
            f"{self.name} is labeled; use .labels(...)"
        return self.labels()

    # unlabeled families delegate straight to their single child
    def inc(self, by: float = 1.0):
        self._default().inc(by)

    def dec(self, by: float = 1.0):
        self._default().dec(by)

    def set(self, v: float):
        self._default().set(v)

    def set_function(self, fn: Callable[[], float]):
        self._default().set_function(fn)

    def observe(self, v: float):
        self._default().observe(v)

    def hist_snapshot(self) -> Dict:
        return self._default().hist_snapshot()

    @property
    def value(self) -> float:
        return self._default().value

    def children(self) -> List[_Child]:
        with self._lock:
            return [self._children[k]
                    for k in sorted(self._children)]


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, Family] = {}

    def _get_or_create(self, name: str, typ: str, help: str,
                       label_names: Sequence[str],
                       buckets: Optional[Sequence[float]] = None) -> Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = Family(name, typ, help, label_names, buckets)
                self._families[name] = fam
            else:
                assert fam.type == typ, \
                    f"{name} re-registered as {typ}, was {fam.type}"
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Family:
        return self._get_or_create(name, COUNTER, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = (),
              fn: Optional[Callable[[], float]] = None) -> Family:
        fam = self._get_or_create(name, GAUGE, help, labels)
        if fn is not None:
            fam.set_function(fn)
        return fam

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = WAIT_BUCKETS,
                  labels: Sequence[str] = ()) -> Family:
        return self._get_or_create(name, HISTOGRAM, help, labels, buckets)

    def families(self) -> List[Family]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    def snapshot(self) -> Dict:
        """Deterministic plain-dict view (sorted names/labels) for
        tests and the report tool."""
        out: Dict = {}
        for fam in self.families():
            if fam.type == HISTOGRAM:
                if fam.label_names:
                    out[fam.name] = {
                        _label_key(c.labels): c.hist_snapshot()
                        for c in fam.children()}
                else:
                    out[fam.name] = fam._default().hist_snapshot()
            elif fam.label_names:
                out[fam.name] = {_label_key(c.labels): c.value
                                 for c in fam.children()}
            else:
                out[fam.name] = fam.value
        return out


def _label_key(labels: Tuple[Tuple[str, str], ...]) -> str:
    return ",".join(f"{k}={v}" for k, v in labels)


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


# ---------------------------------------------------------------------------
# Default engine instruments.  Gauges over state other layers already
# track are collect-time callbacks (lazy imports: no cycle, no hot-path
# cost); counters the layers push into are bound here once so call
# sites skip label resolution.
# ---------------------------------------------------------------------------

def _catalog():
    from ..memory.catalog import BufferCatalog
    return BufferCatalog.get()


ARENA_DEVICE_BYTES = _REGISTRY.gauge(
    "tpu_arena_device_bytes",
    "Logical live bytes on the device tier of the buffer catalog",
    fn=lambda: _catalog().device_bytes)
ARENA_DEVICE_PEAK_BYTES = _REGISTRY.gauge(
    "tpu_arena_device_peak_bytes",
    "High-water mark of device-tier live bytes since catalog reset",
    fn=lambda: _catalog().device_peak_bytes)
ARENA_DEVICE_LIMIT_BYTES = _REGISTRY.gauge(
    "tpu_arena_device_limit_bytes",
    "Device-tier byte budget enforced by the arena",
    fn=lambda: _catalog().device_limit)
ARENA_HOST_BYTES = _REGISTRY.gauge(
    "tpu_arena_host_bytes",
    "Bytes of spilled buffers on the host tier",
    fn=lambda: _catalog().host_bytes)
ARENA_DISK_BYTES = _REGISTRY.gauge(
    "tpu_arena_disk_bytes",
    "Bytes of spilled buffers on the disk tier",
    fn=lambda: _catalog().disk_bytes)

SPILL_BYTES = _REGISTRY.counter(
    "tpu_spill_bytes_total",
    "Bytes moved down the spill tiers since catalog reset",
    labels=("direction",))
SPILL_BYTES.labels(direction="device_to_host").set_function(
    lambda: _catalog().spilled_device_to_host)
SPILL_BYTES.labels(direction="host_to_disk").set_function(
    lambda: _catalog().spilled_host_to_disk)

SEM_WAIT_SECONDS = _REGISTRY.histogram(
    "tpu_semaphore_wait_seconds",
    "Time tasks spent blocked on the device semaphore "
    "(only blocked acquires observe; immediate grants are free)")

QUEUE_WAIT_SECONDS = _REGISTRY.histogram(
    "tpu_service_queue_wait_seconds",
    "Admission-to-start wait of service queries")

SERVICE_QUEUE_DEPTH = _REGISTRY.gauge(
    "tpu_service_queue_depth",
    "Queries waiting in the service admission queue")
SERVICE_QUEUED_BYTES = _REGISTRY.gauge(
    "tpu_service_queued_bytes",
    "Estimated bytes of queries waiting in the admission queue")
SERVICE_INFLIGHT = _REGISTRY.gauge(
    "tpu_service_inflight_queries",
    "Queries admitted and not yet finished")

SERVICE_EVENTS = _REGISTRY.counter(
    "tpu_service_queries_total",
    "Service lifecycle transitions (submitted/admitted/shed/completed/"
    "failed/cancelled/deadline_exceeded/retries)",
    labels=("event",))

COMPILE_CACHE = _REGISTRY.counter(
    "tpu_compile_cache_requests_total",
    "Engine JIT compile-cache lookups by cache and outcome",
    labels=("cache", "outcome"))

AOT_BUCKET_DEMAND = _REGISTRY.counter(
    "tpu_aot_bucket_demand_total",
    "JIT-cache lookups by (program, capacity bucket, outcome) — the "
    "demand mix the admission-aware warmup daemon pre-compiles "
    "against (compile/aot.py; bucket cardinality is bounded by the "
    "geometric lattice)",
    labels=("cache", "bucket", "outcome"))

AOT_WARMUP_COMPILES = _REGISTRY.counter(
    "tpu_aot_warmup_compiles_total",
    "Background warmup compiles by program: (program, bucket) pairs "
    "pre-compiled off the query critical path by the service warmup "
    "daemon (service/warmup.py), attributed to the 'warmup' "
    "pseudo-victim by obs/compile_watch.py",
    labels=("program",))

AOT_HINT_COMPILES = _REGISTRY.counter(
    "tpu_aot_hint_warmup_compiles_total",
    "Background warmup compiles whose (program, bucket) pair arrived "
    "ONLY through a predictive-scheduler pre-warm hint "
    "(service/scheduler.py -> service/warmup.py note_hint) — never "
    "organically demanded before the compile; counted separately "
    "from the admission-driven tpu_aot_warmup_compiles_total",
    labels=("program",))

COMPILE_PERSISTENT_HITS = _REGISTRY.counter(
    "tpu_compile_persistent_hits_total",
    "First calls satisfied by the persistent executable cache "
    "(compile/aot.py manifest + JAX persistent compilation cache): "
    "the program was compiled by an earlier process run and "
    "deserialized here, so it is NOT counted in tpu_compile_seconds",
    labels=("cache",))

COMPILE_SUPERSTAGES = _REGISTRY.counter(
    "tpu_compile_superstages_total",
    "Superstage compiler carve outcomes: carved (region wrapped), "
    "ejected (unfusable member split a region), fallback (stage setup "
    "failed, re-ran with per-operator dispatch), spec_redo (a member's "
    "speculative fit flag failed and the exact path recomputed)",
    labels=("event",))

COMPILE_SUPERSTAGE_FLUSHES = _REGISTRY.counter(
    "tpu_compile_superstage_flushes_total",
    "Host round trips (pending-pool flushes) observed while draining "
    "superstage output partitions — the quantity the compiler exists "
    "to minimize (approximate under concurrent queries: the flush "
    "counter is process-wide)")

SHUFFLE_BYTES = _REGISTRY.counter(
    "tpu_shuffle_bytes_total",
    "Shuffle bytes moved through the map-output catalog",
    labels=("direction",))
SHUFFLE_WRITE_BYTES = SHUFFLE_BYTES.labels(direction="write")
SHUFFLE_READ_BYTES = SHUFFLE_BYTES.labels(direction="read")


# -- shuffle-transport observability plane (obs/netplane.py) ----------------
# Fetch/RTT buckets sized to a LAN TCP hop: sub-ms loopback to tens of
# seconds for a stalled peer.
_NET_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def _netplane_mod():
    from . import netplane
    return netplane


SHUFFLE_HOST_DROP_SECONDS = _REGISTRY.counter(
    "tpu_shuffle_host_drop_seconds_total",
    "Measured host-drop phase time of shuffle exchanges: serialize "
    "(device->host pull into the staged block), wire (TCP transfer "
    "incl. bounce hop), deserialize (host->device upload on read); "
    "dwell is derived per query as the lifecycle remainder "
    "(obs/netplane.py)",
    labels=("phase",))
SHUFFLE_FETCH_SECONDS = _REGISTRY.histogram(
    "tpu_shuffle_fetch_seconds",
    "Remote shuffle fetch latency by peer (metadata request to last "
    "table landed, shuffle/iterator.py)",
    buckets=_NET_BUCKETS,
    labels=("peer",))
SHUFFLE_CONN_EVENTS = _REGISTRY.counter(
    "tpu_shuffle_conn_events_total",
    "Shuffle connection-pool transitions (shuffle/tcp.py): dial = new "
    "socket, reuse = pooled socket served a request, reset = "
    "connection torn down with pending transactions errored",
    labels=("event",))
SHUFFLE_BOUNCE_DWELL_SECONDS = _REGISTRY.histogram(
    "tpu_shuffle_bounce_dwell_seconds",
    "Bounce-buffer hold time, acquire to release (shuffle/bounce.py)",
    buckets=_NET_BUCKETS)
SHUFFLE_BOUNCE_FREE = _REGISTRY.gauge(
    "tpu_shuffle_bounce_free",
    "Free bounce buffers across live shuffle servers",
    fn=lambda: _netplane_mod().bounce_free())
SHUFFLE_BOUNCE_TOTAL = _REGISTRY.gauge(
    "tpu_shuffle_bounce_total",
    "Total bounce buffers across live shuffle servers",
    fn=lambda: _netplane_mod().bounce_total())
SHUFFLE_PENDING_FETCHES = _REGISTRY.gauge(
    "tpu_shuffle_pending_fetches",
    "Shuffle fetches issued and not yet completed or errored — a "
    "nonzero steady state means waiters are stuck on a torn-down "
    "connection (shuffle/client.py)",
    fn=lambda: _netplane_mod().pending_fetches())
SHUFFLE_EDGES_TRACKED = _REGISTRY.gauge(
    "tpu_shuffle_edges_tracked",
    "Distinct (shuffle, map, reduce) edges held in the bounded "
    "transfer matrix",
    fn=lambda: _netplane_mod().edges_tracked())
SHUFFLE_EDGES_EVICTED = _REGISTRY.counter(
    "tpu_shuffle_edges_evicted_total",
    "Edge records dropped because the transfer matrix hit "
    "spark.rapids.tpu.obs.net.maxEdges")
SHUFFLE_PEER_RTT_SECONDS = _REGISTRY.histogram(
    "tpu_shuffle_peer_rtt_seconds",
    "Heartbeat round-trip time by executor peer "
    "(shuffle/heartbeat.py)",
    buckets=_NET_BUCKETS,
    labels=("peer",))
SHUFFLE_COMPRESSION_BYTES = _REGISTRY.counter(
    "tpu_shuffle_compression_bytes_total",
    "Shuffle codec traffic by codec and side: raw = uncompressed "
    "payload, compressed = encoded payload (ratio = raw/compressed; "
    "shuffle/compression.py)",
    labels=("codec", "direction"))


# -- HBM memory observability plane (obs/memplane.py) -----------------------
#: provenance sites a registration can be attributed to (mirrors
#: memplane.SITES; a fixed tuple here keeps the gauge children stable)
MEM_SITES = ("superstage", "exchange", "broadcast", "scan_cache",
             "stream_state", "operator", "other")
# Tier-move buckets: a device->host pull of one batch is ~1-100ms, a
# compressed disk write of a big sorted run can take seconds.
_MEM_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.075, 0.1,
                0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _memplane_mod():
    from . import memplane
    return memplane


MEM_SPILL_SECONDS = _REGISTRY.histogram(
    "tpu_mem_spill_seconds",
    "Wall duration of each buffer-catalog tier move by direction: "
    "device_to_host serialize, host_to_disk write, unspill = the "
    "whole read-back path incl. a disk hop when present "
    "(obs/memplane.py spill ledger)",
    buckets=_MEM_BUCKETS,
    labels=("direction",))
MEM_SPILL_SKIPPED = _REGISTRY.counter(
    "tpu_mem_spill_skipped_total",
    "spill_device_to_fit calls that could not free the requested "
    "bytes because only pinned (refcount>0) entries remained on the "
    "device tier — OOM forensics: 'nothing spillable' vs 'spill too "
    "slow'",
    labels=("reason",))
MEM_LEAKED_TOTAL = _REGISTRY.counter(
    "tpu_mem_leaked_entries_total",
    "Catalog entries still owned by a query at its terminal state "
    "outside the expected survivor set (scan cache, live shuffle "
    "materializations); each is reported with its registration "
    "call-site tag in the event log and diag bundle")
MEM_LIVE_BYTES = _REGISTRY.gauge(
    "tpu_mem_live_bytes",
    "Live device-tier bytes by provenance site; the sites sum to "
    "tpu_arena_device_bytes at every scrape (obs/memplane.py)",
    labels=("site",))
for _site in MEM_SITES:
    MEM_LIVE_BYTES.labels(site=_site).set_function(
        lambda s=_site: _memplane_mod().live_site_bytes(s))
MEM_HEADROOM_BYTES = _REGISTRY.gauge(
    "tpu_mem_headroom_bytes",
    "Admission headroom forecast: free device bytes plus spillable-"
    "at-zero-refcount bytes (obs/memplane.py headroom())",
    fn=lambda: _memplane_mod().headroom()["headroom_bytes"])
MEM_PINNED_BYTES = _REGISTRY.gauge(
    "tpu_mem_pinned_bytes",
    "Device-tier bytes pinned by refcount>0 entries (unspillable)",
    fn=lambda: _memplane_mod().headroom()["pinned_bytes"])
MEM_SPILLABLE_BYTES = _REGISTRY.gauge(
    "tpu_mem_spillable_bytes",
    "Device-tier bytes in refcount==0 entries (reclaimable by a "
    "synchronous spill)",
    fn=lambda: _memplane_mod().headroom()["spillable_bytes"])
MEM_LEDGER_DROPPED = _REGISTRY.counter(
    "tpu_mem_ledger_dropped_total",
    "Spill-ledger records dropped past "
    "spark.rapids.tpu.obs.mem.maxLedger (fixed memory)")
MEM_LEDGER_DROPPED.set_function(
    lambda: _memplane_mod().ledger_dropped())


def _pipeline_mod():
    from ..exec import pipeline
    return pipeline


PIPELINE_QUEUE_DEPTH = _REGISTRY.gauge(
    "tpu_pipeline_queue_depth",
    "Prefetched batches buffered across all live morsel-pipeline drains",
    fn=lambda: _pipeline_mod().buffered_items())
PIPELINE_BUFFERED_BYTES = _REGISTRY.gauge(
    "tpu_pipeline_buffered_bytes",
    "Bytes of prefetched batches buffered across all live pipeline "
    "drains (bounded by exec.pipelineBufferBytes per drain)",
    fn=lambda: _pipeline_mod().buffered_bytes())
PIPELINE_WORKERS_BUSY = _REGISTRY.gauge(
    "tpu_pipeline_workers_busy",
    "Pipeline-pool workers currently serving a drain",
    fn=lambda: _pipeline_mod().busy_workers())
PIPELINE_WORKER_BUSY_SECONDS = _REGISTRY.histogram(
    "tpu_pipeline_worker_busy_seconds",
    "Per-batch produce time on pipeline producers (partition pull + "
    "sink, device dispatch under the semaphore)")
PIPELINE_OVERLAP_RATIO = _REGISTRY.gauge(
    "tpu_pipeline_overlap_ratio",
    "Summed produce time / wall time of the last finished parallel "
    "drain (>1 means host staging overlapped device compute)")
PIPELINE_BATCHES = _REGISTRY.counter(
    "tpu_pipeline_batches_total",
    "Batches produced through drain_parallel, by producer "
    "(worker = pool thread, inline = consumer-assist)",
    labels=("source",))
PIPELINE_DRAINS = _REGISTRY.counter(
    "tpu_pipeline_drains_total",
    "drain_parallel invocations by mode (parallel vs serial fallback)",
    labels=("mode",))


# -- runtime stats plane (obs/stats.py + obs/profile.py) --------------------
# Buckets sized to the remote-dispatch cost model: one fused flush is a
# ~65-100ms round trip, so the interesting resolution is 10ms-10s.
_DISPATCH_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.075, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
#: per-partition row-count buckets for the exchange skew histogram
_PARTITION_ROW_BUCKETS = (0.0, 1.0, 100.0, 1_000.0, 10_000.0,
                          100_000.0, 1_000_000.0, 10_000_000.0,
                          100_000_000.0)

STATS_FLUSH_SECONDS = _REGISTRY.histogram(
    "tpu_stats_flush_seconds",
    "Wall duration of each fused pending-pool flush (one device round "
    "trip; columnar/pending.py) as observed by the stats plane",
    buckets=_DISPATCH_BUCKETS)
STATS_ATTRIBUTED_DEVICE_SECONDS = _REGISTRY.counter(
    "tpu_stats_attributed_device_seconds_total",
    "Flush wall time accrued by attribution target (attributed=yes: a "
    "superstage/exchange/collect scope owned the flush; no: the flush "
    "fired outside any declared scope)",
    labels=("attributed",))
STATS_DISPATCH_SECONDS = _REGISTRY.histogram(
    "tpu_stats_dispatch_seconds",
    "Wall duration of explicit dispatch sites the stats plane times "
    "(flush, superstage chain_step, exchange split, speculative join "
    "spec_probe/spec_redo)",
    buckets=_DISPATCH_BUCKETS,
    labels=("site",))
STATS_EXCHANGES = _REGISTRY.counter(
    "tpu_stats_exchanges_total",
    "Exchange materializations the stats plane profiled, by kind "
    "(shuffle/broadcast) — each contributes per-partition rows/bytes, "
    "null counts, min/max and an HLL distinct-key estimate",
    labels=("kind",))
STATS_SKEWED_EXCHANGES = _REGISTRY.counter(
    "tpu_stats_skewed_exchanges_total",
    "Exchanges whose max/median partition-row ratio exceeded "
    "spark.rapids.tpu.obs.stats.skewFactor")
STATS_LAST_SKEW_RATIO = _REGISTRY.gauge(
    "tpu_stats_last_skew_ratio",
    "max/median partition-row ratio of the most recently profiled "
    "shuffle exchange (1.0 = perfectly balanced)")
STATS_LAST_DISTINCT_KEYS = _REGISTRY.gauge(
    "tpu_stats_last_distinct_keys",
    "HLL distinct-key estimate of the most recently profiled hash "
    "exchange")
STATS_PARTITION_ROWS = _REGISTRY.histogram(
    "tpu_stats_partition_rows",
    "Rows per reduce partition across profiled shuffle exchanges",
    buckets=_PARTITION_ROW_BUCKETS)


# -- serving-grade performance plane (obs/timeline, compile_watch, slo) -----
# Compile buckets span the real range: a warm-trace re-jit is ~10ms, a
# cold XLA compile of a fused superstage is seconds to minutes.
_COMPILE_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

COMPILE_SECONDS = _REGISTRY.histogram(
    "tpu_compile_seconds",
    "Wall duration of each compile-cache miss's first call (jit trace "
    "+ XLA compile) by cache — the inline-compile cost ROADMAP item "
    "4's AOT cache exists to remove (obs/compile_watch.py)",
    buckets=_COMPILE_BUCKETS,
    labels=("cache",))

DEVICE_BUSY_SECONDS = _REGISTRY.counter(
    "tpu_device_busy_seconds_total",
    "Device-busy wall time by device id: fused pending-pool flush "
    "windows on the dispatch device, plus mesh SPMD dispatch windows "
    "attributed to every participating device (obs/timeline.py)",
    labels=("device",))

#: idle-gap taxonomy of the utilization timeline (docs/observability.md;
#: shuffle_host = active shuffle host-drop work windows from
#: obs/netplane.py and mem_spill = active tier-move work windows from
#: obs/memplane.py, both classified ahead of the generic drain causes)
TIMELINE_GAP_CAUSES = ("inline_compile", "sem_wait", "admission_queue",
                       "shuffle_host", "mem_spill", "host_staging",
                       "pipeline_starvation", "idle")


def _timeline_mod():
    from . import timeline
    return timeline


DEVICE_UTIL_PCT = _REGISTRY.gauge(
    "tpu_device_util_pct",
    "Process-wide device utilization percent: merged busy intervals / "
    "wall window since the first observed dispatch (obs/timeline.py)",
    fn=lambda: _timeline_mod().process_util_pct())
DEVICE_IDLE_PCT = _REGISTRY.gauge(
    "tpu_device_idle_pct",
    "Idle share of the process wall window by attributed cause; busy "
    "pct + all idle-cause pcts sum to 100 (obs/timeline.py)",
    labels=("cause",))
for _cause in TIMELINE_GAP_CAUSES:
    DEVICE_IDLE_PCT.labels(cause=_cause).set_function(
        lambda c=_cause: _timeline_mod().process_gap_pct(c))

DOCTOR_VERDICTS = _REGISTRY.counter(
    "tpu_doctor_verdicts_total",
    "Primary-bottleneck verdicts issued by the cross-plane query "
    "doctor (obs/doctor.py), by cause; one increment per diagnosed "
    "query, exactly one cause each — the cause set is device_compute "
    "plus the TIMELINE_GAP_CAUSES taxonomy",
    labels=("cause",))

def _costplane_mod():
    from . import costplane
    return costplane


COST_CAPTURES = _REGISTRY.counter(
    "tpu_cost_captures_total",
    "Static-cost captures by the device-compute cost plane "
    "(obs/costplane.py) at JIT-cache first calls, by source: live XLA "
    "cost analysis (xla) vs the deterministic static-intensity "
    "fallback (static)",
    labels=("source",))
COST_RECORDS = _REGISTRY.gauge(
    "tpu_cost_records",
    "Retained (program, bucket) static-cost records in the bounded "
    "store (spark.rapids.tpu.obs.cost.maxRecords)",
    fn=lambda: float(_costplane_mod().record_count()))
COST_RECORDS_DROPPED = _REGISTRY.gauge(
    "tpu_cost_records_dropped",
    "Static-cost records and dispatch-ledger keys dropped at the "
    "maxRecords bound (fixed memory — the flight-recorder discipline)",
    fn=lambda: float(_costplane_mod().dropped_count()))
COST_PADDING_WASTE_PCT = _REGISTRY.gauge(
    "tpu_cost_padding_waste_pct",
    "Capacity-weighted padded-compute waste percent over every "
    "rows-known dispatch since process start: 100 * (1 - effective "
    "rows / padded bucket capacity) — the price of the AOT lattice's "
    "bucketRatio (obs/costplane.py)",
    fn=lambda: float(_costplane_mod().process_waste_pct()))
COST_VERDICTS = _REGISTRY.counter(
    "tpu_cost_roofline_verdicts_total",
    "Per-program roofline verdicts issued at query end by the "
    "device-compute cost plane: compute_bound when arithmetic "
    "intensity clears the conf-declared ridge, memory_bound below it",
    labels=("verdict",))
COST_ACHIEVED_GFLOPS = _REGISTRY.gauge(
    "tpu_cost_achieved_gflops",
    "Last query's achieved GFLOP/s: total captured static flops "
    "dispatched / flush-observer busy window (obs/costplane.py)",
    fn=lambda: _costplane_mod().last_achieved("achieved_gflops"))
COST_ACHIEVED_GBPS = _REGISTRY.gauge(
    "tpu_cost_achieved_gbps",
    "Last query's achieved GB/s: total captured static bytes "
    "accessed dispatched / flush-observer busy window "
    "(obs/costplane.py)",
    fn=lambda: _costplane_mod().last_achieved("achieved_gbps"))

SLO_LATENCY_SECONDS = _REGISTRY.histogram(
    "tpu_slo_latency_seconds",
    "Per-tenant service latency by phase: end_to_end (queue wait + "
    "execution), queue_wait, exec (obs/slo.py)",
    labels=("tenant", "phase"))
SLO_BREACHES = _REGISTRY.counter(
    "tpu_slo_breaches_total",
    "Queries past spark.rapids.tpu.obs.slo.targetMs by tenant, each "
    "attributed to exactly one cause (shed/predicted_breach/deadline/"
    "inline_compile/slow_exec; predicted_breach = the admission "
    "scheduler shed the query BEFORE it burned device time)",
    labels=("tenant", "cause"))
SLO_BURN_MS = _REGISTRY.counter(
    "tpu_slo_burn_ms_total",
    "Cumulative ms of SLO overshoot per tenant (the error-budget burn "
    "counter: breach count says how often, burn says how badly)",
    labels=("tenant",))


# -- longitudinal fleet plane (obs/history.py + obs/anomaly.py) -------------
# Write buckets sized to a host JSONL append: single-digit µs for the
# in-memory enqueue, tens of µs to low ms for the fsync-free file write.
_HISTORY_WRITE_BUCKETS = (0.00001, 0.00005, 0.0001, 0.00025, 0.0005,
                          0.001, 0.0025, 0.005, 0.01, 0.05, 0.1)


def _anomaly_mod():
    from . import anomaly
    return anomaly


HISTORY_ROWS = _REGISTRY.counter(
    "tpu_history_rows_total",
    "Query-history rows appended by the persistent history store "
    "(obs/history.py), by terminal outcome — one row per terminal "
    "query when the plane is enabled",
    labels=("outcome",))
HISTORY_DROPPED = _REGISTRY.counter(
    "tpu_history_dropped_total",
    "History rows dropped because the bounded writer queue was full "
    "(the store never blocks or fails the query path)")
HISTORY_WRITE_SECONDS = _REGISTRY.histogram(
    "tpu_history_write_seconds",
    "Wall duration of each background JSONL row append (serialize + "
    "write + rotation check; obs/history.py writer thread — off the "
    "query path by construction)",
    buckets=_HISTORY_WRITE_BUCKETS)

ANOMALY_CHECKS = _REGISTRY.counter(
    "tpu_anomaly_checks_total",
    "Per-(fingerprint, key) EWMA folds performed by the online "
    "anomaly sentinel (obs/anomaly.py) — one per gated key per "
    "history row once the store is enabled")
ANOMALY_EVENTS = _REGISTRY.counter(
    "tpu_anomaly_events_total",
    "Anomaly lifecycle events by kind: breach = K consecutive "
    "sigma-outliers opened an anomaly, recovery = K consecutive "
    "in-band runs closed it (obs/anomaly.py)",
    labels=("kind",))
ANOMALY_ACTIVE = _REGISTRY.gauge(
    "tpu_anomaly_active",
    "Currently open (breached, not yet recovered) anomalies across "
    "all fingerprints and keys",
    fn=lambda: float(_anomaly_mod().active_count()))
ANOMALY_FP = _REGISTRY.counter(
    "tpu_anomaly_fp_total",
    "Anomaly breach-opens that closed again without a confirmed level "
    "shift (the recovery arrived from the frozen baseline, not a "
    "re-baselining) — transient false positives; on stationary soak "
    "traffic their rate over breaches is the sentinel's "
    "false-positive accounting (obs/anomaly.py, gated by the soak "
    "bench key anomaly_fp_rate)")


# -- soak plane: burn-rate monitors (obs/burn.py) + load harness
#    (service/soak.py) -------------------------------------------------------

def _burn_mod():
    from . import burn
    return burn


def _soak_mod():
    from ..service import soak
    return soak


BURN_RATE = _REGISTRY.gauge(
    "tpu_burn_rate",
    "Multi-window SLO burn rate per tenant (obs/burn.py): fraction of "
    "the obs.burn.budgetPct error budget consumed inside the window "
    "over the fraction allowed — 1.0 burns the budget exactly as fast "
    "as permitted, >1 is an incident.  window=fast catches spikes, "
    "window=slow confirms sustained burn (the SRE multi-window "
    "alerting shape)",
    labels=("tenant", "window"))
BURN_STEADY_STATE = _REGISTRY.gauge(
    "tpu_burn_steady_state",
    "1 while the EWMA-slope steady-state detector declares the "
    "service stationary (obs/burn.py); drops to 0 when a fault or "
    "load shift breaks the latency slope streak")
BURN_LEAK_DRIFT_BYTES = _REGISTRY.gauge(
    "tpu_burn_leak_drift_bytes",
    "Leak-drift regression over the sampled memplane live-bytes "
    "floor: min of the newest half of samples minus min of the oldest "
    "half (obs/burn.py) — exactly 0 on a clean soak run, gated exact "
    "by ci/perf_gate.py",
    fn=lambda: float(_burn_mod().leak_drift_bytes()))
SOAK_QPS = _REGISTRY.gauge(
    "tpu_soak_qps",
    "Achieved completions/second of the live (or last) soak run "
    "(service/soak.py harness state)",
    fn=lambda: float(_soak_mod().stats_section()["qps_actual"]))
SOAK_INFLIGHT = _REGISTRY.gauge(
    "tpu_soak_inflight",
    "Queries submitted by the soak harness and not yet terminal",
    fn=lambda: float(_soak_mod().stats_section()["inflight"]))
SOAK_SUBMITTED = _REGISTRY.gauge(
    "tpu_soak_submitted_total",
    "Soak-harness submissions accepted by the service this run",
    fn=lambda: float(_soak_mod().stats_section()["submitted"]))
SOAK_COMPLETED = _REGISTRY.gauge(
    "tpu_soak_completed_total",
    "Soak-harness queries completed this run",
    fn=lambda: float(_soak_mod().stats_section()["completed"]))
SOAK_SHED = _REGISTRY.gauge(
    "tpu_soak_shed_total",
    "Soak-harness submissions shed by admission control this run",
    fn=lambda: float(_soak_mod().stats_section()["shed"]))
SOAK_ACTIVE_FAULTS = _REGISTRY.gauge(
    "tpu_soak_active_faults",
    "Injected fault windows currently open (service/faults.py)",
    fn=lambda: float(len(_soak_mod().stats_section()["active_faults"])))


# -- observability self-metering (obs/overhead.py) --------------------------

def _overhead_mod():
    from . import overhead
    return overhead


OBS_SELF_SECONDS = _REGISTRY.counter(
    "tpu_obs_self_seconds_total",
    "Host time the observability layer spent inside its own hot-path "
    "entry points, by plane (obs/overhead.py self-meter): stats "
    "staging, timeline note_flush, netplane put/get accounting, "
    "memplane register/sweep, costplane dispatch accounting, history "
    "row build, doctor assembly.  Collect-time callbacks over "
    "preallocated ns counters — scrapes pay the read, the record path "
    "pays two clock reads and two list writes.  The flight recorder "
    "is exempt by construction",
    labels=("plane",))
for _plane in ("stats", "timeline", "net", "mem", "cost", "history",
               "doctor", "burn"):
    OBS_SELF_SECONDS.labels(plane=_plane).set_function(
        lambda p=_plane: _overhead_mod().plane_seconds(p))


# -- plan cache + predictive scheduler (cache/plan_cache.py,
#    service/scheduler.py) --------------------------------------------------

def _plan_cache_mod():
    from ..cache import plan_cache
    return plan_cache


PLAN_CACHE_EVENTS = _REGISTRY.counter(
    "tpu_plan_cache_events_total",
    "Fingerprint-keyed plan-cache lifecycle events "
    "(cache/plan_cache.py): hit = repeat logical shape replayed its "
    "stored certificates (verify + PV-FLUSH skipped), miss = cold "
    "plan + store, validation_miss = rebuilt plan's fingerprint "
    "diverged from the stored one (fell back to the cold path), "
    "invalidated = conf-fingerprint change dropped the entry, "
    "evicted = LRU bound pushed the entry out",
    labels=("event",))
PLAN_CACHE_ENTRIES = _REGISTRY.gauge(
    "tpu_plan_cache_entries",
    "Plan shapes currently resident in the bounded plan cache",
    fn=lambda: float(_plan_cache_mod().entry_count()))
SCHED_PREDICTIONS = _REGISTRY.counter(
    "tpu_sched_predictions_total",
    "Admission-time exec_ms predictions by the predictive scheduler "
    "(service/scheduler.py), by source: baseline = a frozen EWMA "
    "baseline for the query's fingerprint existed, none = no cache "
    "entry or no frozen baseline yet (query admitted unranked)",
    labels=("source",))


def compile_cache_event(cache: str, hit: bool, dur_ns: int = 0,
                        signature=None):
    """One compile-cache lookup (called from the exec/kernels JIT
    caches; compile paths, not per-batch hot paths).  A miss whose
    compile duration is already known may pass ``dur_ns``/``signature``
    to feed the compile-telemetry plane directly; callers that only
    learn the duration at the jitted callable's first invocation use
    ``compile_watch.wrap_miss`` instead."""
    COMPILE_CACHE.labels(cache=cache,
                         outcome="hit" if hit else "miss").inc()
    if dur_ns > 0:
        from . import compile_watch
        compile_watch.note_compile(cache, dur_ns, signature)


def superstage_event(event: str, n: int = 1):
    """One superstage compiler event (carve/eject/fallback/spec_redo —
    plan-time and stage-setup paths, not per-batch hot paths)."""
    COMPILE_SUPERSTAGES.labels(event=event).inc(n)
