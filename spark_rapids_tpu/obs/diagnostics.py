"""Automatic failure-diagnostic bundles.

When a query dies — failure, device OOM, deadline expiry,
cancellation, or a stall-watchdog trigger — the service calls
``write_bundle()`` and one self-contained JSON artifact lands in the
conf'd directory (``spark.rapids.tpu.obs.diagnostics.dir``):

- the flight-recorder tail (obs/flight.py): the query's own events
  plus the recent merged tail of every thread, captured with tracing
  fully disabled;
- every thread's Python stack at capture time;
- the metrics-registry snapshot (obs/registry.py);
- the arena live/peak/spill map down to per-buffer tier/bytes/priority
  and device-semaphore holders;
- shuffle client/server state and service queue depths;
- the physical plan tree with per-node verifier verdicts;
- the conf dump with secret-looking values redacted.

The directory rotates (oldest ``diag-*.json`` beyond
``…diagnostics.maxBundles`` deleted) so an incident loop cannot fill
the disk.  ``tools/diagnose.py`` renders a bundle human-readable.

Capture never raises into the failing query's unwind path: every
section is best-effort and records its own error string instead.
"""
from __future__ import annotations

import datetime
import io
import json
import os
import re
import sys
import threading
import traceback
from typing import Any, Dict, List, Optional

from . import flight as _flight

#: conf keys whose values never belong in an artifact that gets
#: attached to tickets and mailed around
_REDACT_RE = re.compile(
    r"secret|password|passwd|token|credential|apikey|api[._-]key|auth",
    re.IGNORECASE)

#: minimum flight-recorder events preserved per bundle (acceptance
#: floor: the last 64 events for the failing query when available)
FLIGHT_TAIL_EVENTS = 256


def thread_stacks() -> List[Dict[str, Any]]:
    """Every live thread's Python stack (sys._current_frames)."""
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        t = by_ident.get(ident)
        stack = traceback.format_stack(frame)
        out.append({
            "ident": ident,
            "name": t.name if t else "<unknown>",
            "daemon": bool(t.daemon) if t else None,
            "stack": [line.rstrip("\n") for line in stack],
        })
    out.sort(key=lambda d: d["name"])
    return out


def arena_map() -> Dict[str, Any]:
    """Arena live/peak/spill map: catalog totals, per-buffer entries,
    and device-semaphore state."""
    out: Dict[str, Any] = {}
    try:
        from ..memory.catalog import BufferCatalog
        cat = BufferCatalog.get()
        out["stats"] = dict(cat.stats())
        entries = []
        with cat._lock:
            for e in cat._entries.values():
                entries.append({
                    "buffer_id": e.buffer_id,
                    "tier": getattr(e.tier, "name", str(e.tier)),
                    "nbytes": e.nbytes,
                    "priority": e.priority,
                    # allocation provenance (obs/memplane.py): who
                    # registered this buffer and from where
                    "owner_query": e.owner_query,
                    "site": e.owner_site,
                    "op": e.owner_op,
                    "tag": e.owner_tag,
                })
        entries.sort(key=lambda d: (-d["nbytes"], d["buffer_id"]))
        out["entries"] = entries
    except Exception as exc:
        out["error"] = repr(exc)
    try:
        from ..memory.arena import DeviceManager
        dm = DeviceManager._instance
        if dm is not None:
            sem = dm.semaphore
            out["semaphore"] = {
                "permits": getattr(sem, "permits", None),
                "available": sem.available(),
                "holders": sorted(sem.holder_idents()),
            }
    except Exception as exc:
        out["semaphore_error"] = repr(exc)
    return out


def shuffle_state() -> Dict[str, Any]:
    """In-process shuffle manager occupancy (blocks/bytes) — the
    client/server side state that matters for a stalled fetch."""
    out: Dict[str, Any] = {}
    try:
        from ..shuffle.manager import ShuffleManager
        mgr = ShuffleManager._instance
        if mgr is None:
            return {"active": False}
        with mgr.catalog._lock:
            blocks = len(mgr.catalog._store)
        out.update({
            "active": True,
            "blocks": blocks,
            "buffered_bytes": mgr.catalog.nbytes(),
            "next_shuffle_id": mgr._next_shuffle,
        })
    except Exception as exc:
        out["error"] = repr(exc)
    try:
        from ..shuffle.inprocess import EndpointRegistry
        reg = EndpointRegistry._instance
        if reg is not None:
            out["endpoints"] = len(getattr(reg, "_endpoints", {}))
    except Exception as exc:
        out["endpoints_error"] = repr(exc)
    try:
        # transport observability plane: host-drop phase totals, pool
        # state, pending fetches and the hottest matrix edges — the
        # evidence for a stalled/slow fetch incident
        from . import netplane as _netplane
        out["netplane"] = _netplane.stats_section()
        out["netplane"]["top_edges"] = _netplane.edge_matrix(limit=10)
    except Exception as exc:
        out["netplane_error"] = repr(exc)
    return out


def redacted_conf(conf) -> Dict[str, Any]:
    """The conf's explicit settings with secret-looking values masked."""
    try:
        settings = dict(getattr(conf, "_settings", {}) or {})
    except Exception:
        return {}
    return {k: ("***" if _REDACT_RE.search(str(k)) else v)
            for k, v in sorted(settings.items())}


def _plan_section(phys) -> Dict[str, Any]:
    """Plan tree with per-node verifier verdicts."""
    out: Dict[str, Any] = {}
    try:
        out["tree"] = phys.tree_string()
    except Exception as exc:
        return {"error": repr(exc)}
    try:
        from ..analysis.plan_verify import verify_plan
        rep = verify_plan(phys)
        out["verify"] = {
            "ok": rep.ok,
            "violations": [{"node_index": v.node_index,
                            "rule": v.rule,
                            "message": v.message}
                           for v in rep.violations]}
    except Exception as exc:
        out["verify_error"] = repr(exc)
    return out


def collect_bundle(trigger: str,
                   query_id: Optional[str] = None,
                   error: Optional[BaseException] = None,
                   handle=None,
                   service=None,
                   conf=None) -> Dict[str, Any]:
    """Assemble one diagnostic bundle dict.  Every section is
    best-effort; a section that fails records its own error instead of
    propagating into the caller's unwind path."""
    if query_id is None and handle is not None:
        query_id = getattr(handle, "query_id", None)
    bundle: Dict[str, Any] = {
        "version": 1,
        "trigger": trigger,
        "captured_at": datetime.datetime.now(
            datetime.timezone.utc).isoformat(),
        "query_id": query_id,
    }
    if error is not None:
        bundle["error"] = {
            "type": type(error).__name__,
            "message": str(error),
            "traceback": traceback.format_exception(
                type(error), error, error.__traceback__),
        }
    try:
        bundle["flight"] = {
            "occupancy": _flight.occupancy(),
            "query_events": _flight.snapshot(query_id=query_id)
            if query_id else [],
            "recent_events": _flight.snapshot(last=FLIGHT_TAIL_EVENTS),
        }
    except Exception as exc:
        bundle["flight"] = {"error": repr(exc)}
    try:
        bundle["threads"] = thread_stacks()
    except Exception as exc:
        bundle["threads"] = [{"error": repr(exc)}]
    try:
        from .registry import MetricsRegistry
        bundle["metrics"] = MetricsRegistry.get().snapshot()
    except Exception as exc:
        bundle["metrics"] = {"error": repr(exc)}
    bundle["arena"] = arena_map()
    try:
        # memory plane: live owner decomposition, spill ledger tail,
        # headroom — the evidence for an OOM/spill-storm incident
        from . import memplane as _memplane
        mem: Dict[str, Any] = _memplane.stats_section()
        mem["ledger_tail"] = _memplane.ledger(limit=100)
        bundle["memory"] = mem
    except Exception as exc:
        bundle["memory"] = {"error": repr(exc)}
    try:
        # cost plane: static-cost store occupancy, process padding
        # waste, last achieved rates — the roofline evidence
        from . import costplane as _costplane
        bundle["cost"] = _costplane.stats_section()
    except Exception as exc:
        bundle["cost"] = {"error": repr(exc)}
    bundle["shuffle"] = shuffle_state()
    if service is not None:
        try:
            bundle["service"] = service.snapshot()
        except Exception as exc:
            bundle["service"] = {"error": repr(exc)}
    if handle is not None:
        try:
            bundle["query"] = {
                "status": getattr(handle, "status", None),
                "tenant": getattr(handle, "tenant", None),
                "attempts": getattr(
                    getattr(handle, "metrics", None), "attempts", None),
                "record": handle.metrics.to_record()
                if getattr(handle, "metrics", None) is not None else None,
            }
        except Exception as exc:
            bundle["query"] = {"error": repr(exc)}
        phys = getattr(handle, "_last_phys", None)
        if phys is not None:
            bundle["plan"] = _plan_section(phys)
        tok = getattr(handle, "token", None)
        if tok is not None:
            try:
                bundle["cancel"] = {
                    "cancelled": bool(tok.cancelled),
                    "reason": getattr(tok, "reason", None),
                    "observed": dict(getattr(tok, "observed", {}) or {}),
                }
            except Exception as exc:
                bundle["cancel"] = {"error": repr(exc)}
    if conf is None and handle is not None:
        conf = getattr(handle, "conf", None)
    if conf is None:
        try:
            from ..config import get_active
            conf = get_active()
        except Exception:
            conf = None
    if conf is not None:
        bundle["conf"] = redacted_conf(conf)
    return bundle


def _rotate(directory: str, max_bundles: int) -> List[str]:
    """Delete oldest ``diag-*.json`` beyond ``max_bundles`` (by name —
    the UTC timestamp prefix makes lexical order chronological).
    Returns the deleted paths."""
    if max_bundles <= 0:
        return []
    try:
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith("diag-") and n.endswith(".json"))
    except OSError:
        return []
    deleted = []
    for n in names[:-max_bundles] if len(names) > max_bundles else []:
        p = os.path.join(directory, n)
        try:
            os.remove(p)
            deleted.append(p)
        except OSError:
            pass
    return deleted


def write_bundle(bundle: Dict[str, Any], directory: str,
                 max_bundles: int = 20) -> str:
    """Serialize one bundle into ``directory`` and rotate.  Filename:
    ``diag-<utc-compact>-<query_id>-<trigger>.json``."""
    os.makedirs(directory, exist_ok=True)
    ts = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y%m%dT%H%M%S.%f")
    qid = re.sub(r"[^A-Za-z0-9._-]", "_",
                 str(bundle.get("query_id") or "noquery"))
    trig = re.sub(r"[^A-Za-z0-9._-]", "_",
                  str(bundle.get("trigger") or "unknown"))
    path = os.path.join(directory, f"diag-{ts}-{qid}-{trig}.json")
    tmp = path + ".tmp"
    with io.open(tmp, "w", encoding="utf-8") as f:
        json.dump(bundle, f, indent=1, default=repr)
        f.write("\n")
    os.replace(tmp, path)
    _rotate(directory, max_bundles)
    return path


def capture(trigger: str, directory: str, max_bundles: int = 20,
            **kwargs) -> Optional[str]:
    """collect + write, returning the bundle path; never raises (the
    caller is a failing query's unwind path)."""
    try:
        bundle = collect_bundle(trigger, **kwargs)
        return write_bundle(bundle, directory, max_bundles)
    except Exception:
        return None
