"""Online anomaly sentinel — the perf-regression gate made live.

The offline sentinel (``analysis/regression.py`` + ``ci/perf_gate.py``)
only fires when someone hand-runs a bench round; at serving scale
regressions arrive via live traffic between rounds.  This module folds
every history row (``obs/history.py``) into per-(fingerprint, key)
EWMA mean/variance state and flags *sustained* drift:

- **model**: for each watched key, an exponentially weighted mean and
  variance (``ewmaAlpha``).  The first ``warmupMinRuns`` rows of a
  fingerprint only train the model (fresh plans never alarm on
  compile-warmup noise); at warm-up end the mean is frozen as the
  fingerprint's **trend baseline**.
- **outlier**: a run is an outlier when it is BOTH beyond ``sigma``
  EWMA standard deviations from the baseline AND classified a
  regression by the shared band/direction core (``analysis/bands.py``
  — the exact semantics the offline gate applies to ``BENCH_r*``
  rounds).  Outliers do NOT update the model: a level shift stays
  visible instead of being absorbed.
- **breach / recovery**: ``breachRuns`` consecutive outliers open an
  anomaly (one ``breach`` event, ``tpu_anomaly_events_total``,
  ``tpu_anomaly_active``); the same count of consecutive in-band runs
  closes it with a ``recovery`` event.  :func:`fold` returns the
  event dicts — the *caller* (service/server.py) owns the side
  effects: event-log lines, the rate-limited diag bundle.
- **trend**: per fingerprint, drift of the recent window's p50 vs the
  frozen baseline plus the doctor-cause mix shift ("exec_ms p50
  drifted +42% over last 50 runs, primary cause shifted
  host_staging→shuffle_host"), surfaced through the doctor's
  ``stats_section()["trend"]``.

Pure host arithmetic over history rows (lint scope HYG002: no wall
clocks — rate limiting uses the monotonic clock): zero extra device
flushes by construction.
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..analysis.bands import REGRESSION, band_status
from .registry import ANOMALY_CHECKS, ANOMALY_EVENTS, ANOMALY_FP

#: watched history-row keys: (key, direction, band_pct, abs_floor) —
#: direction/band semantics are the offline gate's (analysis/bands.py);
#: floors guard near-zero baselines (an exec_ms baseline of 2ms must
#: not alarm at 3ms)
WATCH_KEYS: Tuple[Tuple[str, str, float, float], ...] = (
    ("exec_ms", "lower", 25.0, 50.0),
    ("queue_ms", "lower", 50.0, 50.0),
    ("host_drop_tax_ms", "lower", 50.0, 5.0),
    ("spill_ms", "lower", 50.0, 5.0),
    ("device_util_pct", "higher", 25.0, 0.0),
    ("flushes", "exact", 0.0, 0.0),
)

#: recent-window length the trend drift is computed over
_TREND_WINDOW = 50

_ENABLED = True
_ALPHA = 0.15
_MIN_N = 8
_K = 3
_SIGMA = 3.0
_BUNDLE_INTERVAL_S = 300.0
_MAX_FPS = 1024

_LOCK = threading.Lock()
_LAST_BUNDLE_MONO: Optional[float] = None
_FP_OVERFLOW = 0

#: false-positive accounting: outliers never train the model and the
#: baseline stays frozen, so a breach that recovers did NOT reflect a
#: confirmed level shift — it was transient.  On stationary traffic
#: (a soak run's steady window) the fp/breach ratio is the sentinel's
#: false-positive rate (the soak gate's ``anomaly_fp_rate``).
_BREACH_TOTAL = 0
_FP_TOTAL = 0


class _KeyState:
    """EWMA state of one (fingerprint, key) series."""

    __slots__ = ("count", "mean", "var", "baseline", "streak_bad",
                 "streak_good", "active", "last", "recent")

    def __init__(self):
        self.count = 0
        self.mean = 0.0
        self.var = 0.0
        self.baseline: Optional[float] = None  # frozen at warm-up end
        self.streak_bad = 0
        self.streak_good = 0
        self.active = False
        self.last = 0.0
        self.recent: deque = deque(maxlen=_TREND_WINDOW)


class _FpState:
    __slots__ = ("keys", "runs", "warmup_causes", "recent_causes")

    def __init__(self):
        self.keys: Dict[str, _KeyState] = {}
        self.runs = 0
        self.warmup_causes: Dict[str, int] = {}
        self.recent_causes: deque = deque(maxlen=_TREND_WINDOW)


_FPS: Dict[str, _FpState] = {}


def enabled() -> bool:
    return _ENABLED


def active_count() -> int:
    """Open (breached, unrecovered) anomalies — the
    ``tpu_anomaly_active`` gauge."""
    with _LOCK:
        return sum(1 for fp in _FPS.values()
                   for ks in fp.keys.values() if ks.active)


def _fold_key(fp: str, key: str, direction: str, band: float,
              floor: float, cur: float, ks: _KeyState,
              events: List[Dict]) -> None:
    global _BREACH_TOTAL, _FP_TOTAL
    ks.count += 1
    ks.last = cur
    ks.recent.append(cur)
    ANOMALY_CHECKS.inc()
    if ks.count <= _MIN_N:
        # warm-up: train only
        if ks.count == 1:
            ks.mean, ks.var = cur, 0.0
        else:
            diff = cur - ks.mean
            incr = _ALPHA * diff
            ks.mean += incr
            ks.var = (1.0 - _ALPHA) * (ks.var + diff * incr)
        if ks.count == _MIN_N:
            ks.baseline = ks.mean
        return
    base = ks.baseline if ks.baseline is not None else ks.mean
    std = math.sqrt(max(ks.var, 0.0))
    is_reg = band_status(cur, base, direction, band, floor) == REGRESSION
    outlier = is_reg and (direction == "exact"
                          or abs(cur - base) > _SIGMA * std)
    if outlier:
        ks.streak_bad += 1
        ks.streak_good = 0
        if not ks.active and ks.streak_bad >= _K:
            ks.active = True
            drift = (0.0 if base == 0
                     else (cur - base) / abs(base) * 100.0)
            events.append({
                "kind": "breach", "fingerprint": fp, "key": key,
                "direction": direction, "baseline": round(base, 3),
                "current": round(cur, 3),
                "drift_pct": round(drift, 1),
                "sigma": round(abs(cur - base) / std, 1)
                if std > 0 else None,
                "runs": ks.count,
            })
            ANOMALY_EVENTS.labels(kind="breach").inc()
            _BREACH_TOTAL += 1
        return
    # in-band (or improved): train the model, count toward recovery
    diff = cur - ks.mean
    incr = _ALPHA * diff
    ks.mean += incr
    ks.var = (1.0 - _ALPHA) * (ks.var + diff * incr)
    ks.streak_bad = 0
    if ks.active:
        ks.streak_good += 1
        if ks.streak_good >= _K:
            ks.active = False
            ks.streak_good = 0
            events.append({
                "kind": "recovery", "fingerprint": fp, "key": key,
                "direction": direction,
                "baseline": round(base, 3),
                "current": round(cur, 3), "runs": ks.count,
                "false_positive": True,
            })
            ANOMALY_EVENTS.labels(kind="recovery").inc()
            # the baseline never re-trained while breached, so this
            # recovery closed a breach with NO confirmed level shift:
            # a transient false positive (soak fp accounting)
            _FP_TOTAL += 1
            ANOMALY_FP.inc()


def fold(row: Dict) -> List[Dict]:
    """Fold one history row into the sentinel.  Returns the anomaly
    lifecycle events this row caused (usually none); the caller owns
    event-log/bundle side effects."""
    global _FP_OVERFLOW
    if not _ENABLED or not isinstance(row, dict):
        return []
    fp = str(row.get("fingerprint") or "unknown")
    events: List[Dict] = []
    with _LOCK:
        st = _FPS.get(fp)
        if st is None:
            if len(_FPS) >= _MAX_FPS:
                _FP_OVERFLOW += 1
                return []
            st = _FPS[fp] = _FpState()
        st.runs += 1
        cause = row.get("doctor_cause")
        if cause:
            if st.runs <= _MIN_N:
                st.warmup_causes[cause] = \
                    st.warmup_causes.get(cause, 0) + 1
            st.recent_causes.append(cause)
        for key, direction, band, floor in WATCH_KEYS:
            val = row.get(key)
            if val is None or not isinstance(val, (int, float)):
                continue
            ks = st.keys.get(key)
            if ks is None:
                ks = st.keys[key] = _KeyState()
            _fold_key(fp, key, direction, band, floor, float(val),
                      ks, events)
    return events


def should_bundle() -> bool:
    """Rate limit for anomaly-triggered diag bundles: at most one per
    ``bundleIntervalSeconds`` process-wide (monotonic clock)."""
    global _LAST_BUNDLE_MONO
    if _BUNDLE_INTERVAL_S <= 0:
        return False
    now = time.monotonic()
    with _LOCK:
        if (_LAST_BUNDLE_MONO is not None
                and now - _LAST_BUNDLE_MONO < _BUNDLE_INTERVAL_S):
            return False
        _LAST_BUNDLE_MONO = now
        return True


# ---------------------------------------------------------------------------
# read-side views
# ---------------------------------------------------------------------------

def baseline(fingerprint: str, key: str) -> Optional[Tuple[float, float]]:
    """Frozen EWMA ``(mean, variance)`` of one (fingerprint, key)
    series, or None while the series is still warming up (the first
    ``warmupMinRuns`` rows train silently and must never drive
    decisions).  The one public read path onto the sentinel's model:
    the predictive admission scheduler (service/scheduler.py) predicts
    ``exec_ms`` through this accessor, and the sentinel's own fold
    reads the identical ``_KeyState`` under the identical ``_LOCK`` —
    snapshot under the lock, decide outside it."""
    with _LOCK:
        st = _FPS.get(str(fingerprint))
        if st is None:
            return None
        ks = st.keys.get(key)
        if ks is None or ks.baseline is None:
            return None
        return (ks.baseline, ks.var)


def _mode(counts: Dict[str, int]) -> Optional[str]:
    return max(counts, key=counts.get) if counts else None


def _pctl(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def trend_section() -> Dict[str, Dict]:
    """Per-fingerprint trend summary (the doctor's ``trend`` section
    and the dashboard's drift column): recent-window p50 drift vs the
    frozen warm-up baseline per watched key, active anomalies, and
    the doctor-cause shift."""
    with _LOCK:
        snap = {fp: ({k: (ks.baseline, sorted(ks.recent), ks.active,
                          ks.last)
                      for k, ks in st.keys.items()},
                     st.runs, dict(st.warmup_causes),
                     list(st.recent_causes))
                for fp, st in _FPS.items()}
    out: Dict[str, Dict] = {}
    for fp, (keys, runs, warm_causes, recent_causes) in snap.items():
        drifts: Dict[str, Dict] = {}
        active: List[str] = []
        for k, (baseline, recent, is_active, last) in keys.items():
            if is_active:
                active.append(k)
            if baseline is None or baseline == 0 or not recent:
                continue
            p50 = _pctl(recent, 0.5)
            drifts[k] = {
                "baseline": round(baseline, 3),
                "recent_p50": round(p50, 3),
                "drift_pct": round(
                    (p50 - baseline) / abs(baseline) * 100.0, 1),
                "last": round(last, 3),
            }
        cause_from = _mode(warm_causes)
        recent_counts: Dict[str, int] = {}
        for c in recent_causes:
            recent_counts[c] = recent_counts.get(c, 0) + 1
        cause_to = _mode(recent_counts)
        entry: Dict = {"runs": runs, "active": sorted(active),
                       "drift": drifts}
        if cause_from and cause_to and cause_from != cause_to:
            entry["cause_shift"] = {"from": cause_from, "to": cause_to}
        out[fp] = entry
    return out


def stats_section() -> Dict:
    """The ``anomaly`` section of ``Service.stats().snapshot()``."""
    with _LOCK:
        fps = len(_FPS)
        overflow = _FP_OVERFLOW
        checks = sum(ks.count for st in _FPS.values()
                     for ks in st.keys.values())
        breaches, fp_count = _BREACH_TOTAL, _FP_TOTAL
    return {
        "enabled": _ENABLED,
        "fingerprints": fps,
        "fingerprint_overflow": overflow,
        "checks": checks,
        "active": active_count(),
        "min_runs": _MIN_N,
        "breach_runs": _K,
        "sigma": _SIGMA,
        "breach_total": breaches,
        "fp_total": fp_count,
        "fp_rate_pct": fp_rate_pct(),
    }


def fp_rate_pct() -> float:
    """False positives over breach-opens, percent (0.0 with no
    breaches — a clean stationary run).  The soak gate's
    ``anomaly_fp_rate`` bench key."""
    with _LOCK:
        if _BREACH_TOTAL <= 0:
            return 0.0
        return round(100.0 * _FP_TOTAL / _BREACH_TOTAL, 2)


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def configure(conf) -> None:
    """Apply the ``spark.rapids.tpu.obs.anomaly.*`` conf group (called
    by QueryService.__init__; last-configured service wins — the plane
    is process-wide like the rest of the registry)."""
    global _ENABLED, _ALPHA, _MIN_N, _K, _SIGMA
    global _BUNDLE_INTERVAL_S, _MAX_FPS
    from ..config import (OBS_ANOMALY_BREACH_RUNS,
                          OBS_ANOMALY_BUNDLE_INTERVAL_S,
                          OBS_ANOMALY_ENABLED, OBS_ANOMALY_EWMA_ALPHA,
                          OBS_ANOMALY_SIGMA,
                          OBS_ANOMALY_WARMUP_MIN_RUNS,
                          OBS_HISTORY_MAX_FINGERPRINTS)
    _ENABLED = bool(conf.get(OBS_ANOMALY_ENABLED))
    _ALPHA = min(max(float(conf.get(OBS_ANOMALY_EWMA_ALPHA)), 0.01), 1.0)
    _MIN_N = max(2, int(conf.get(OBS_ANOMALY_WARMUP_MIN_RUNS)))
    _K = max(1, int(conf.get(OBS_ANOMALY_BREACH_RUNS)))
    _SIGMA = max(0.5, float(conf.get(OBS_ANOMALY_SIGMA)))
    _BUNDLE_INTERVAL_S = float(conf.get(OBS_ANOMALY_BUNDLE_INTERVAL_S))
    _MAX_FPS = max(1, int(conf.get(OBS_HISTORY_MAX_FINGERPRINTS)))


def reset() -> None:
    """Test hook: drop all sentinel state."""
    global _FP_OVERFLOW, _LAST_BUNDLE_MONO, _BREACH_TOTAL, _FP_TOTAL
    with _LOCK:
        _FPS.clear()
        _FP_OVERFLOW = 0
        _LAST_BUNDLE_MONO = None
        _BREACH_TOTAL = 0
        _FP_TOTAL = 0
