"""Compile telemetry — where multi-second inline XLA compiles land.

Seven engine JIT caches (fused project, staged compute, hash
aggregate, the three mesh SPMD programs, the Pallas hash-partition
kernel) already report hit/miss counts to Prometheus.  What they could
not answer is the question the AOT shape-bucketed compile cache
(ROADMAP item 4) will be built and judged against: *how long does each
miss actually cost, and did a query block on it?*

``wrap_miss(cache, fn, signature)`` is the single instrumentation
point: a cache miss wraps the freshly created callable so its FIRST
call — where ``jax.jit`` traces, lowers and compiles — is wall-timed
and recorded; afterwards the wrapper degenerates to one flag read per
call.  Each recorded compile carries:

- the cache name and a compact shape/dtype signature (from the cache
  key the miss was stored under);
- the wall duration (the same number lands in the
  ``tpu_compile_seconds{cache=...}`` histogram, the bounded top-N
  record store rendered by ``Service.stats()``, and — via the
  process-wide ns counter the session deltas around each execution —
  the victim query's event-log record, so all three surfaces agree
  exactly);
- an origin: ``inline`` means a query context (an active
  ``CancelToken``) was blocked on the compile, in which case the
  duration is also observed onto the token as ``inline_compile_ms``
  for the service's per-query metrics; ``warm`` means no query was
  waiting; ``warmup`` means the AOT warmup daemon compiled it in the
  background (``compile/aot.py warmup_scope`` — the scope outranks
  any ambient CancelToken, so a background compile can NEVER land on
  a tenant query's inline_compile_ms, and the utilization timeline
  classifies its window as process-idle, not ``inline_compile``);
  ``persistent`` means the first call was satisfied by the persistent
  executable cache (manifest hit from an earlier process run) — a
  deserialization, not a compile, so it is counted in
  ``tpu_compile_persistent_hits_total`` and kept OUT of the
  ``tpu_compile_seconds`` histogram and the inline/total ns counters;
- the capacity bucket the compile served (the thread's last
  ``aot.note_demand`` for that cache), rendered per-bucket by
  ``tools/report.py``.

Hot-path discipline (this file is on the SYNC001/OBS002 lint scope):
the warm path is one list-index check; recording happens once per
compile (seconds-scale events) and allocates one small dict there.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from . import flight
from .registry import COMPILE_SECONDS

_SIG_MAX = 160          #: stored signature strings are truncated here
_RECORD_CAP = 256       #: bounded record store (slowest kept on evict)

_ENABLED = True
_TOP_N = 20

_LOCK = threading.Lock()
_SEQ = 0                #: compile sequence — advances once per recorded
                        #: compile (warmup included); read lock-free by
                        #: obs/profile.py's dispatch_cold routing
_TOTAL_NS = 0           #: process-wide compile ns (session window deltas;
                        #: warmup + persistent loads deliberately excluded)
_INLINE_NS = 0          #: subset recorded under an active query context
_WARMUP_NS = 0          #: background warmup compiles (the pseudo-victim)
_PERSISTENT_NS = 0      #: persistent-cache deserializations (not compiles)
_PERSISTENT_HITS = 0
_RECORDS: List[Dict] = []


def _store(rec: Dict) -> None:
    _RECORDS.append(rec)
    if len(_RECORDS) > _RECORD_CAP:
        # evict the cheapest compile: the store's job is the
        # slowest-compiles table, so the tail worth keeping is
        # the expensive one
        _RECORDS.sort(key=lambda r: -r["dur_ms"])
        del _RECORDS[_RECORD_CAP:]


def note_compile(cache: str, dur_ns: int, signature: Optional[str] = None,
                 ) -> None:
    """Record one finished compile: histogram, bounded record store,
    process counters, the victim token's ``inline_compile_ms``, and a
    flight breadcrumb (constant name + plain ints — OBS002).

    Origin resolution order is the PR 13 bugfix: the warmup scope is
    checked BEFORE the cancellation token, so a background warmup
    compile running while tenant queries are in flight lands under
    the ``warmup`` pseudo-victim instead of charging whichever query
    context happens to be ambient on the thread."""
    global _SEQ, _TOTAL_NS, _INLINE_NS, _WARMUP_NS
    if not _ENABLED:
        return
    from ..compile import aot
    from ..service.cancellation import current_token, observe
    warmup = aot.in_warmup()
    tok = None if warmup else current_token()
    inline = tok is not None
    origin = "warmup" if warmup else ("inline" if inline else "warm")
    bucket = aot.last_demand(cache)
    COMPILE_SECONDS.labels(cache=cache).observe(dur_ns / 1e9)
    sig = "" if signature is None else str(signature)[:_SIG_MAX]
    rec = {"cache": cache, "dur_ms": round(dur_ns / 1e6, 3),
           "signature": sig, "inline": inline, "origin": origin,
           "bucket": bucket,
           "query_id": tok.query_id if inline else None,
           "end_ns": time.perf_counter_ns()}
    with _LOCK:
        _SEQ += 1
        if warmup:
            _WARMUP_NS += dur_ns
        else:
            _TOTAL_NS += dur_ns
            if inline:
                _INLINE_NS += dur_ns
        _store(rec)
    if inline:
        observe("inline_compile_ms", dur_ns / 1e6)
    flight.record(flight.EV_COMPILE, cache, dur_ns // 1_000_000,
                  1 if inline else 0)


def note_persistent_hit(cache: str, dur_ns: int,
                        signature: Optional[str] = None) -> None:
    """Record a first call satisfied by the persistent executable
    cache: an earlier process compiled this (program, signature, conf
    fingerprint) and this call deserialized it.  Counted under
    ``tpu_compile_persistent_hits_total`` and the record store (so the
    report can show the load), but NOT in ``tpu_compile_seconds`` or
    the inline/total ns counters — nothing was compiled."""
    global _PERSISTENT_NS, _PERSISTENT_HITS
    if not _ENABLED:
        return
    from ..compile import aot
    from .registry import COMPILE_PERSISTENT_HITS
    COMPILE_PERSISTENT_HITS.labels(cache=cache).inc()
    sig = "" if signature is None else str(signature)[:_SIG_MAX]
    rec = {"cache": cache, "dur_ms": round(dur_ns / 1e6, 3),
           "signature": sig, "inline": False, "origin": "persistent",
           "bucket": aot.last_demand(cache), "query_id": None,
           "end_ns": time.perf_counter_ns()}
    with _LOCK:
        _PERSISTENT_NS += dur_ns
        _PERSISTENT_HITS += 1
        _store(rec)
    flight.record(flight.EV_COMPILE, "persistent_hit",
                  dur_ns // 1_000_000, 0)


def wrap_miss(cache: str, fn: Callable, signature=None) -> Callable:
    """Wrap a compile-cache miss's freshly built callable so its first
    call (where jit traces + compiles) is timed into ``note_compile``
    — or, when the AOT manifest proves an earlier process already
    compiled it into the persistent cache, into
    ``note_persistent_hit``.  Warm calls afterwards pay one list-index
    check."""
    if not _ENABLED:
        # compile telemetry off: the cost plane still needs the
        # first-call choke point — but when it is off too, the old
        # identity-passthrough contract holds exactly
        from . import costplane as _costplane
        if _costplane._ENABLED:
            return _costplane.wrap_capture(cache, fn)
        return fn
    compiled = [False]

    def _timed(*args, **kwargs):
        if compiled[0]:
            return fn(*args, **kwargs)
        from ..compile import aot
        key = aot.first_call_key(cache, signature)
        t0 = time.perf_counter_ns()
        out = fn(*args, **kwargs)
        compiled[0] = True
        dur_ns = time.perf_counter_ns() - t0
        persistent = aot.persistent_ready(key)
        if persistent:
            note_persistent_hit(cache, dur_ns, signature)
        else:
            note_compile(cache, dur_ns, signature)
            if key is not None:
                aot.manifest_add(key, cache, signature,
                                 aot.last_demand(cache), dur_ns / 1e6)
        try:
            # device-compute cost plane: static cost analysis of the
            # just-compiled program — one trace-only lowering pass per
            # (program, bucket), same hook for miss/warmup/persistent
            from . import costplane as _costplane
            _costplane.capture(
                cache, fn, args, kwargs,
                origin=_costplane.ORIGIN_PERSISTENT if persistent
                else _costplane.ORIGIN_WARMUP if aot.in_warmup()
                else _costplane.ORIGIN_MISS)
        except Exception:  # noqa: BLE001 — capture never fails the call
            pass
        return out

    return _timed


# ---------------------------------------------------------------------------
# accessors (cold paths: session window deltas, Service.stats())
# ---------------------------------------------------------------------------

def compile_seq() -> int:
    """Lock-free read of the compile sequence number: dispatch windows
    snapshot it to learn whether a compile landed inside them
    (dispatch_cold routing in obs/profile.py).  An int read is atomic
    under the GIL — no torn values, worst case one late tick."""
    return _SEQ


def total_ns() -> int:
    """Process-wide compile wall ns.  The session deltas this around
    each execution for the engine record's ``inline_compile_ms`` (the
    FLUSH_COUNT discipline: exact when queries run serially)."""
    with _LOCK:
        return _TOTAL_NS


def inline_ns() -> int:
    with _LOCK:
        return _INLINE_NS


def warmup_ns() -> int:
    """Background warmup compile ns (the pseudo-victim's bill)."""
    with _LOCK:
        return _WARMUP_NS


def persistent_hits() -> int:
    with _LOCK:
        return _PERSISTENT_HITS


def records_since(marker: int) -> List[Dict]:
    """Compiles recorded after a ``begin_query()`` marker (store index
    snapshot).  Evictions only drop pre-existing cheap entries, so a
    per-query slice right after the query is reliable."""
    with _LOCK:
        return [dict(r) for r in _RECORDS[marker:]]


def begin_query() -> int:
    with _LOCK:
        return len(_RECORDS)


def stats_section(top_n: Optional[int] = None) -> Dict:
    """The ``compile`` section of ``Service.stats().snapshot()``: the
    top-N slowest compiles plus cumulative counters."""
    n = top_n if top_n is not None else _TOP_N
    with _LOCK:
        recs = sorted(_RECORDS, key=lambda r: -r["dur_ms"])[:n]
        tot, inl = _TOTAL_NS, _INLINE_NS
        wrm, pns, phits = _WARMUP_NS, _PERSISTENT_NS, _PERSISTENT_HITS
    return {
        "total_compile_ms": round(tot / 1e6, 3),
        "inline_compile_ms": round(inl / 1e6, 3),
        "warmup_compile_ms": round(wrm / 1e6, 3),
        "persistent_hits": phits,
        "persistent_load_ms": round(pns / 1e6, 3),
        "compiles": len(recs),
        "top": [dict(r) for r in recs],
    }


def configure(conf) -> None:
    """Apply the ``spark.rapids.tpu.obs.compile.*`` conf group."""
    global _ENABLED, _TOP_N
    from ..config import OBS_COMPILE_ENABLED, OBS_COMPILE_TOP_N
    _ENABLED = bool(conf.get(OBS_COMPILE_ENABLED))
    _TOP_N = int(conf.get(OBS_COMPILE_TOP_N))


def reset() -> None:
    """Test hook: drop records and counters."""
    global _TOTAL_NS, _INLINE_NS, _WARMUP_NS, _PERSISTENT_NS
    global _PERSISTENT_HITS
    with _LOCK:
        _TOTAL_NS = 0
        _INLINE_NS = 0
        _WARMUP_NS = 0
        _PERSISTENT_NS = 0
        _PERSISTENT_HITS = 0
        del _RECORDS[:]
