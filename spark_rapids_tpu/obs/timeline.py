"""Device-utilization timeline — busy/idle reconstruction with gap blame.

The stats plane (PR 7) times every pending-pool flush; this module
keeps those timings as *intervals* instead of bare durations, so the
engine can finally answer the question the mesh-scaling and AOT-cache
roadmap items hinge on: what fraction of wall-clock is the device
actually busy, and what eats the idle gaps?

Busy intervals come from two sources:

- ``note_flush(dur_ns)`` — chained from the flush observer
  (obs/profile.py): a fused pending-pool flush ran on the dispatch
  device for ``[now - dur, now]``;
- ``device_busy_wrap(fn, device_ids)`` — mesh SPMD programs
  (parallel/mesh.py) wrap their jitted callable so each call window is
  attributed to EVERY participating device id, which is what makes the
  8-device multichip smoke show per-chip occupancy instead of one
  blended number.

Both feed the ``tpu_device_busy_seconds_total{device=...}`` counter and
a bounded process-wide interval list.  Idle gaps between busy intervals
are classified post-hoc (cold path only) by joining evidence streams:

- ``inline_compile``      — compile_watch record windows;
- ``sem_wait``            — flight EV_SEM_ACQUIRE (a = waited ns, so
                            the wait interval is ``[ts - a, ts]``);
- ``admission_queue``     — flight EV_STATE admitted -> running spans;
- ``shuffle_host``        — active shuffle host-drop work windows
                            (serialize/wire/deserialize from
                            obs/netplane.py): the device sat idle while
                            an exchange paid the host-drop tax;
- ``host_staging``        — remainder inside a morsel-pipeline drain
                            window (EV_PIPELINE dispatch -> drain_end,
                            paired per thread) whose recorded
                            staging/compute overlap ratio was healthy
                            (>= 0.5): the host kept the pipeline fed
                            and the residual idleness is staging
                            throughput.  In per-query summaries the
                            unexplained remainder also lands here —
                            the query was running, the device was not,
                            and nothing else claimed the time;
- ``pipeline_starvation`` — drain-window remainder whose overlap ratio
                            was poor (< 0.5): producers sat idle and
                            under-fed the device;
- ``idle``                — process-summary remainder outside any
                            query evidence (import, datagen, the time
                            between queries).

Classification subtracts the evidence streams in that priority order,
so every idle nanosecond lands in exactly one bucket and
``util_pct + sum(gap shares) == 100`` by construction (asserted in
tests and ci/obs_smoke.py).

Agreement contract: a summary's ``busy_ms`` is the UNMERGED sum of the
interval durations recorded in the window — identical arithmetic to
summing the flush observer's dispatch durations, which is the <=1%
acceptance criterion.  ``util_pct`` uses the MERGED intervals so
overlapping mesh windows cannot push utilization past 100.

Hot-path discipline (this file is on the SYNC001/OBS002 lint scope):
``note_flush`` is one perf_counter read, one bounded list append and
one cached counter-child inc; classification allocates only on the
cold summary paths.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import flight
from . import overhead as _overhead
from .registry import DEVICE_BUSY_SECONDS, TIMELINE_GAP_CAUSES

_ENABLED = True
_CAP = 1 << 16          #: bounded interval store (conf maxIntervals)

#: (start_ns, end_ns) busy intervals, append-only and GIL-atomic like
#: profile._DISPATCH; readers slice, never mutate.
_INTERVALS: List[Tuple[int, int]] = []
_DROPPED = 0
_FIRST_NS: Optional[int] = None

#: cached counter child for the single-dispatch-device flush path
_BUSY0 = DEVICE_BUSY_SECONDS.labels(device="0")

#: process_summary memo for collect-time gauge scrapes (7 children per
#: scrape would otherwise recompute the classification 7 times)
_MEMO: List = [0, None]
_MEMO_TTL_NS = 200_000_000

#: drain overlap ratio (permille, from EV_PIPELINE drain_end b) at or
#: above which drain-window idleness blames staging throughput rather
#: than pipeline starvation
_HEALTHY_OVERLAP_PERMILLE = 500


def note_flush(dur_ns: int) -> None:
    """One pending-pool flush ended now, having run ``dur_ns`` on the
    dispatch device (chained from profile._on_flush)."""
    global _FIRST_NS, _DROPPED
    if not _ENABLED:
        return
    end = time.perf_counter_ns()
    start = end - dur_ns
    if _FIRST_NS is None:
        _FIRST_NS = start
    if len(_INTERVALS) < _CAP:
        _INTERVALS.append((start, end))
    else:
        _DROPPED += 1
    _BUSY0.inc(dur_ns / 1e9)
    # self-meter (obs/overhead.py): this call's own host time — the
    # end stamp above doubles as the meter's start stamp
    _overhead.note(_overhead.P_TIMELINE, end)


def device_busy_wrap(fn, device_ids: Sequence):
    """Wrap a mesh SPMD callable so each call window counts as busy
    time on every participating device id (parallel/mesh.py)."""
    if not _ENABLED:
        return fn
    children = tuple(DEVICE_BUSY_SECONDS.labels(device=str(d))
                     for d in device_ids)

    def _timed(*args, **kwargs):
        global _FIRST_NS, _DROPPED
        t0 = time.perf_counter_ns()
        out = fn(*args, **kwargs)
        t1 = time.perf_counter_ns()
        if _FIRST_NS is None:
            _FIRST_NS = t0
        if len(_INTERVALS) < _CAP:
            _INTERVALS.append((t0, t1))
        else:
            _DROPPED += 1
        secs = (t1 - t0) / 1e9
        for child in children:
            child.inc(secs)
        return out

    return _timed


def begin_query() -> Tuple[int, int]:
    """Marker for a per-query summary window: (interval store index,
    start ns).  The FLUSH_COUNT discipline — exact when queries run
    serially, which is how the bench and the report use it."""
    return (len(_INTERVALS), time.perf_counter_ns())


# ---------------------------------------------------------------------------
# interval arithmetic (cold paths only)
# ---------------------------------------------------------------------------

def _merge(segs: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    if not segs:
        return []
    segs = sorted(segs)
    out = [segs[0]]
    for s, e in segs[1:]:
        ls, le = out[-1]
        if s <= le:
            if e > le:
                out[-1] = (ls, e)
        else:
            out.append((s, e))
    return out

def _clip(segs: List[Tuple[int, int]], t0: int,
          t1: int) -> List[Tuple[int, int]]:
    out = []
    for s, e in segs:
        s2, e2 = max(s, t0), min(e, t1)
        if e2 > s2:
            out.append((s2, e2))
    return out


def _subtract(base: List[Tuple[int, int]],
              cover: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """base minus cover; both merged+sorted.  Returns what remains."""
    if not base or not cover:
        return list(base)
    out = []
    ci = 0
    for s, e in base:
        cur = s
        while ci < len(cover) and cover[ci][1] <= cur:
            ci += 1
        j = ci
        while j < len(cover) and cover[j][0] < e:
            cs, ce = cover[j]
            if cs > cur:
                out.append((cur, cs))
            cur = max(cur, ce)
            if cur >= e:
                break
            j += 1
        if cur < e:
            out.append((cur, e))
    return out


def _total(segs: List[Tuple[int, int]]) -> int:
    return sum(e - s for s, e in segs)


# ---------------------------------------------------------------------------
# evidence streams for gap classification
# ---------------------------------------------------------------------------

def _compile_segs(t0: int, t1: int) -> List[Tuple[int, int]]:
    from . import compile_watch
    segs = []
    for rec in compile_watch.records_since(0):
        # warmup compiles belong to the background pseudo-victim and
        # persistent-cache loads are deserializations, not compiles:
        # neither is inline_compile evidence, so their windows fall
        # through to the remaining causes / process-idle (pre-r13
        # records carry no origin and default to compile evidence)
        if rec.get("origin") in ("warmup", "persistent"):
            continue
        end = rec["end_ns"]
        start = end - int(rec["dur_ms"] * 1e6)
        if end > t0 and start < t1:
            segs.append((start, end))
    return segs


def _flight_evidence(t0: int, t1: int):
    """(sem_wait segs, admission segs, drain windows) from the flight
    recorder tail, clipped to [t0, t1].  Drain windows pair EV_PIPELINE
    "dispatch" with the next "drain_end" on the same thread and carry
    that drain's overlap permille."""
    sem: List[Tuple[int, int]] = []
    admission: List[Tuple[int, int]] = []
    drains: List[Tuple[int, int, int]] = []
    admitted_at: Dict[str, int] = {}
    drain_open: Dict[str, int] = {}
    for ev in flight.snapshot():
        ts = ev["ts_ns"]
        kind = ev["kind"]
        if kind == flight.EV_SEM_ACQUIRE:
            waited = ev["a"]
            if waited > 0:
                sem.append((ts - waited, ts))
        elif kind == flight.EV_STATE:
            qid = ev["query_id"]
            if ev["name"] == "admitted":
                admitted_at[str(qid)] = ts
            elif ev["name"] == "running":
                start = admitted_at.pop(str(qid), None)
                if start is not None:
                    admission.append((start, ts))
        elif kind == flight.EV_PIPELINE:
            # name constants from exec/pipeline.py (_N_DISPATCH /
            # _N_DRAIN_END; drain_end b = overlap ratio x1000)
            if ev["name"] == "dispatch":
                drain_open[ev["thread"]] = ts
            elif ev["name"] == "drain_end":
                start = drain_open.pop(ev["thread"], None)
                if start is not None:
                    drains.append((start, ts, ev["b"]))
    sem = _clip(_merge(sem), t0, t1)
    admission = _clip(_merge(admission), t0, t1)
    drains = [(max(s, t0), min(e, t1), r) for s, e, r in drains
              if e > t0 and s < t1]
    return sem, admission, drains


def _summarize(idx: int, t0: int, t1: int, is_query: bool) -> Dict:
    """Busy/idle breakdown of [t0, t1] over intervals[idx:].  See the
    module docstring for the taxonomy and the priority order."""
    segs = _INTERVALS[idx:]
    window_ns = max(t1 - t0, 1)
    busy_raw_ns = _total(segs)          # matches summed flush durations
    merged = _clip(_merge(list(segs)), t0, t1)
    idle = _subtract([(t0, t1)], merged)

    gaps_ns = {cause: 0 for cause in TIMELINE_GAP_CAUSES}

    compile_segs = _clip(_merge(_compile_segs(t0, t1)), t0, t1)
    taken = _subtract(idle, compile_segs)
    gaps_ns["inline_compile"] = _total(idle) - _total(taken)
    idle = taken

    sem, admission, drains = _flight_evidence(t0, t1)
    taken = _subtract(idle, sem)
    gaps_ns["sem_wait"] = _total(idle) - _total(taken)
    idle = taken
    taken = _subtract(idle, admission)
    gaps_ns["admission_queue"] = _total(idle) - _total(taken)
    idle = taken

    # shuffle host-drop work (obs/netplane.py serialize/wire/
    # deserialize windows) outranks the generic drain causes: an idle
    # device under an exchange materialization is paying the host-drop
    # tax, not waiting on pipeline staging (lazy import: netplane is
    # initialized after timeline in obs/__init__)
    from . import netplane
    shuffle_segs = _clip(_merge(netplane.active_segments(t0, t1)), t0, t1)
    taken = _subtract(idle, shuffle_segs)
    gaps_ns["shuffle_host"] = _total(idle) - _total(taken)
    idle = taken

    # spill/unspill tier-move work (obs/memplane.py windows) likewise
    # outranks the generic drain causes: an idle device during a
    # serialize/deserialize is paying the memory tax, not waiting on
    # pipeline staging (and the shuffle_host subtraction above already
    # claimed any window that was both)
    from . import memplane
    spill_segs = _clip(_merge(memplane.active_segments(t0, t1)), t0, t1)
    taken = _subtract(idle, spill_segs)
    gaps_ns["mem_spill"] = _total(idle) - _total(taken)
    idle = taken

    healthy = _merge([(s, e) for s, e, r in drains
                      if r >= _HEALTHY_OVERLAP_PERMILLE])
    starved = _merge([(s, e) for s, e, r in drains
                      if r < _HEALTHY_OVERLAP_PERMILLE])
    taken = _subtract(idle, healthy)
    gaps_ns["host_staging"] = _total(idle) - _total(taken)
    idle = taken
    taken = _subtract(idle, starved)
    gaps_ns["pipeline_starvation"] = _total(idle) - _total(taken)
    idle = taken

    # remainder: inside a query window the device sat idle while the
    # query ran — host staging by elimination; process-wide it is
    # genuinely idle time (between queries, import, datagen)
    rest = _total(idle)
    gaps_ns["host_staging" if is_query else "idle"] += rest

    util_pct = _total(merged) / window_ns * 100.0
    return {
        "busy_ms": round(busy_raw_ns / 1e6, 3),
        "window_ms": round(window_ns / 1e6, 3),
        "util_pct": round(util_pct, 3),
        "intervals": len(segs),
        "dropped": _DROPPED,
        "gaps": {cause: round(ns / window_ns * 100.0, 3)
                 for cause, ns in gaps_ns.items()},
    }


def query_summary(marker: Tuple[int, int]) -> Dict:
    """Summary of the window since a ``begin_query()`` marker (the
    per-query utilization lane in tools/report.py)."""
    idx, t0 = marker
    return _summarize(idx, t0, time.perf_counter_ns(), is_query=True)


def process_summary() -> Dict:
    """Process-wide summary since the first observed dispatch; memoized
    briefly so a Prometheus scrape of the 7 gauge children classifies
    once, not 7 times."""
    now = time.perf_counter_ns()
    memo_ts, memo = _MEMO
    if memo is not None and now - memo_ts < _MEMO_TTL_NS:
        return memo
    if _FIRST_NS is None:
        out = {"busy_ms": 0.0, "window_ms": 0.0, "util_pct": 0.0,
               "intervals": 0, "dropped": 0,
               "gaps": {cause: 0.0 for cause in TIMELINE_GAP_CAUSES}}
    else:
        out = _summarize(0, _FIRST_NS, now, is_query=False)
    _MEMO[0] = now
    _MEMO[1] = out
    return out


def process_util_pct() -> float:
    """Collect-time callback for the tpu_device_util_pct gauge."""
    return process_summary()["util_pct"]


def process_gap_pct(cause: str) -> float:
    """Collect-time callback for tpu_device_idle_pct{cause=...}."""
    return process_summary()["gaps"].get(cause, 0.0)


def configure(conf) -> None:
    """Apply the ``spark.rapids.tpu.obs.timeline.*`` conf group."""
    global _ENABLED, _CAP
    from ..config import OBS_TIMELINE_ENABLED, OBS_TIMELINE_MAX_INTERVALS
    _ENABLED = bool(conf.get(OBS_TIMELINE_ENABLED))
    cap = int(conf.get(OBS_TIMELINE_MAX_INTERVALS))
    if cap > 0:
        _CAP = cap


def reset() -> None:
    """Test hook: drop intervals and the process window origin."""
    global _FIRST_NS, _DROPPED
    del _INTERVALS[:]
    _FIRST_NS = None
    _DROPPED = 0
    _MEMO[0] = 0
    _MEMO[1] = None
