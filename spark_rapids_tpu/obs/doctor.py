"""Cross-plane query doctor: one verdict from six observability planes.

PRs 7-11 each added a per-query telemetry plane (dispatch stats,
utilization timeline, compile watch, shuffle netplane, memplane,
PV-FLUSH prediction) but left the *join* to the operator: deciding
whether a query is shuffle-host-bound, compile-bound or spill-bound
meant reading six report sections side by side.  The doctor is that
join — the profiling-tools role of the reference plugin (workload
qualification + profile analysis) applied to our own planes.

``diagnose()`` consumes the artifacts the session already collected at
end of query (timeline summary, ``inline_compile_ms``, netplane and
memplane roll-ups, observed vs predicted flushes) and produces a
:class:`QueryDiagnosis`:

- **contribution shares summing to 100**: the timeline's gap taxonomy
  (PR 8) already satisfies ``util_pct + sum(gap shares) == 100`` by
  construction; the doctor re-labels ``util_pct`` as the
  ``device_compute`` cause and carries the gap causes through, so the
  breakdown stays a partition of the query's wall window.
- **exactly one primary bottleneck**: the largest share, ties broken
  by the fixed taxonomy priority order (never by dict order).
- **Amdahl headroom per candidate fix**: eliminating a cause with
  share ``s`` bounds end-to-end speedup at ``1 / (1 - s/100)`` —
  "eliminating ``shuffle_host`` bounds speedup at <=1.31x".
- **ranked ROADMAP mapping**: every cause maps to one of ROADMAP
  open items 1-4, so the verdict names the planned fix, not just the
  symptom.
- **cross-plane evidence**: each candidate cites the corroborating
  plane counter (``host_drop_tax_ms`` for ``shuffle_host``,
  ``spill_ms`` for ``mem_spill``, ``inline_compile_ms`` for
  ``inline_compile``, observed-vs-predicted flushes for
  ``device_compute``), so a share is never asserted without the raw
  number behind it.

Pure post-query host arithmetic over already-collected summaries:
zero extra device flushes by construction, no hot-path presence at
all.  ``stable_digest()`` covers only timing-independent structure
(primary cause + the fixed cause->roadmap table), so it is stable
across pipeline parallelism and superstage on/off whenever the
dominant cause is — the doctor-determinism acceptance criterion.

``diagnose_bench()`` applies the same model to a ``BENCH_r*.json``
record (``util_gap_breakdown`` + ``device_util_pct`` keys), which is
how ``ci/perf_gate.py`` prints a verdict for a regressed benchmark.
"""
from __future__ import annotations

import hashlib
import json
import threading
from typing import Dict, List, Optional

from . import overhead as _overhead
from .registry import DOCTOR_VERDICTS, TIMELINE_GAP_CAUSES

#: model version — bumped whenever the share model or the
#: cause->roadmap table changes (part of stable_digest()).  v2: the
#: device_compute share decomposes into compute_bound / memory_bound /
#: padding_waste sub-causes from the cost plane (obs/costplane.py).
MODEL_VERSION = 2

#: verdict taxonomy, in PRIORITY ORDER: ``device_compute`` (the busy
#: share, re-labeled from the timeline's ``util_pct``) first, then the
#: PR 8 idle-gap causes in their registry order.  Ties on share are
#: broken by position here, so the primary verdict is deterministic.
#: Each entry: (cause, roadmap item 1-4 or None, one-line fix).
#: ``idle`` is process-only (query windows fold the remainder into
#: ``host_staging``) and maps to no fix.
TAXONOMY = (
    ("device_compute", 4,
     "Pallas-native operator core: make the busy share itself cheaper "
     "(fewer fusion breakers, kernel-level join/agg)"),
    ("inline_compile", 3,
     "AOT compile service (compile/aot.py): widen the bucket lattice "
     "coverage / seed the persistent cache so first-touch compiles "
     "land on the warmup daemon, not the query path"),
    ("sem_wait", 1,
     "mesh-sharded multi-query execution: stop serializing on the "
     "single-device dispatch semaphore"),
    ("admission_queue", 3,
     "admission-aware warmup + capacity tuning: drain the queue wait "
     "before the query window opens"),
    ("shuffle_host", 1,
     "HBM-resident ICI shuffle: keep exchange payloads on-device "
     "instead of the host bounce path"),
    ("mem_spill", 2,
     "adaptive query execution from live stats: right-size partitions "
     "so working sets fit the device tier"),
    ("host_staging", 4,
     "wider superstages / Pallas scan path: fewer host->device "
     "staging handoffs per batch"),
    ("pipeline_starvation", 2,
     "adaptive partition coalescing: keep producer morsels large "
     "enough to feed the device pipeline"),
    ("idle", None, ""),
)

_CAUSE_ORDER = {c: i for i, (c, _item, _fix) in enumerate(TAXONOMY)}
_CAUSE_ROADMAP = {c: item for c, item, _fix in TAXONOMY}
_CAUSE_FIX = {c: fix for c, _item, fix in TAXONOMY}

_ENABLED = True
_LOCK = threading.Lock()
_VERDICT_COUNTS: Dict[str, int] = {}
_LAST: Optional[Dict] = None


class QueryDiagnosis:
    """The doctor's verdict for one query window.

    ``data`` keys: ``query_id``, ``primary_cause``,
    ``primary_share_pct``, ``shares`` (cause -> pct, summing to 100),
    ``headroom`` (ranked candidate list of ``{cause, share_pct,
    bound_x, roadmap_item, fix, evidence}``), ``flushes``,
    ``predicted_flushes``, ``model_version``.
    """

    def __init__(self, data: Dict):
        self.data = data

    @property
    def primary_cause(self) -> str:
        return self.data["primary_cause"]

    @property
    def primary_share_pct(self) -> float:
        return self.data["primary_share_pct"]

    @property
    def headroom(self) -> List[Dict]:
        return self.data["headroom"]

    def to_dict(self) -> Dict:
        return dict(self.data)

    def stable_digest(self) -> str:
        """sha256 over the timing-independent verdict structure.

        Follows the StatsProfile discipline exactly: timings are
        excluded (StatsProfile.stable_digest drops dispatch
        durations; here the measured shares, bounds and the primary
        cause they select are all wall-time observations and move
        with execution config), and what remains is the cause+
        headroom MODEL — the taxonomy with its cause->roadmap
        mapping and Amdahl bound rule — keyed by the query's
        data-dependent identity (the StatsProfile digest when the
        stats plane ran).  Same query x same model -> same digest
        across pipeline parallelism {1,4} x superstage on/off, the
        doctor-determinism acceptance contract.
        """
        payload = {
            "model_version": MODEL_VERSION,
            "taxonomy": [(c, item) for c, item, _fix in TAXONOMY],
            "headroom_model": "amdahl:1/(1-share/100)",
            "device_compute_submodel":
                "roofline_split+padding_waste,residue_folded",
            "stats_digest": self.data.get("stats_digest"),
        }
        blob = json.dumps(payload, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()

    def verdict_line(self) -> str:
        """One-line human verdict ("shuffle_host 23.7% -> <=1.31x …)."""
        d = self.data
        item = _CAUSE_ROADMAP.get(d["primary_cause"])
        where = f" (ROADMAP item {item})" if item else ""
        return (f"primary bottleneck {d['primary_cause']} at "
                f"{d['primary_share_pct']:.1f}% — eliminating it bounds "
                f"speedup at <={d['headroom'][0]['bound_x']:.2f}x"
                f"{where}") if d["headroom"] else \
            f"primary bottleneck {d['primary_cause']}"


def _amdahl_bound(share_pct: float) -> float:
    """Upper bound on end-to-end speedup from eliminating a phase
    that occupies ``share_pct`` of the wall window (Amdahl's law)."""
    s = max(0.0, min(share_pct, 100.0)) / 100.0
    if s >= 1.0:
        return float("inf")
    return 1.0 / (1.0 - s)


def _normalized_shares(util_pct: float, gaps: Dict[str, float]
                       ) -> Dict[str, float]:
    """Busy + gap shares as one partition summing to exactly 100.

    The timeline rounds each component to 3 decimals, so the raw sum
    can drift by a few millipercent; the residue is folded into the
    largest component so downstream consumers can assert the
    sum-to-100 invariant exactly (to float epsilon).
    """
    shares = {"device_compute": max(0.0, float(util_pct))}
    for cause in TIMELINE_GAP_CAUSES:
        shares[cause] = max(0.0, float(gaps.get(cause, 0.0)))
    total = sum(shares.values())
    if total <= 0.0:
        # no observed window at all (e.g. a metadata-only query):
        # attribute everything to host staging
        shares["host_staging"] = 100.0
        return shares
    top = max(shares, key=lambda c: (shares[c], -_CAUSE_ORDER[c]))
    shares[top] = round(shares[top] + (100.0 - total), 6)
    return shares


def _compile_mix(compiles: Optional[List[Dict]]) -> str:
    """Bucket/origin breakdown of the query window's compile records
    (compile/aot.py dimensions).  Placeholder-tolerant: pre-r13 records
    carry neither key and fold into ``inline``/``-`` so old event logs
    keep diagnosing."""
    if not compiles:
        return ""
    origins: Dict[str, int] = {}
    buckets: Dict[str, int] = {}
    for r in compiles:
        o = r.get("origin") or "inline"
        origins[o] = origins.get(o, 0) + 1
        b = r.get("bucket")
        bk = "-" if b is None else str(b)
        buckets[bk] = buckets.get(bk, 0) + 1
    omix = ",".join(f"{o}={n}" for o, n in sorted(origins.items()))
    bmix = ",".join(f"{b}={n}" for b, n in sorted(buckets.items()))
    return f" origins[{omix}] buckets[{bmix}]"


def _cost_mix(costplane: Optional[Dict]) -> str:
    """Cost-plane corroboration for the device_compute evidence line:
    the roofline verdict, achieved rates and the padding-waste tax.
    Empty string when the cost plane was off or captured nothing."""
    if not costplane or not costplane.get("costed_records"):
        return ""
    verdict = costplane.get("verdict") or "?"
    gf = costplane.get("achieved_gflops")
    gb = costplane.get("achieved_gbps")
    waste = costplane.get("padding_waste_pct")
    gf_s = "?" if gf is None else f"{float(gf):.1f}"
    gb_s = "?" if gb is None else f"{float(gb):.1f}"
    w_s = "?" if waste is None else f"{float(waste):.1f}"
    return (f" roofline[{verdict} achieved={gf_s}GF/s,{gb_s}GB/s "
            f"padding_waste={w_s}%]")


def _device_compute_breakdown(share: float, costplane: Optional[Dict]
                              ) -> Optional[Dict[str, float]]:
    """Split the ``device_compute`` share into exact sub-causes.

    ``padding_waste`` is the share fraction spent computing padded
    rows (share x waste/100); the remainder splits between
    ``compute_bound`` and ``memory_bound`` by the cost plane's busy
    apportionment.  Components are rounded to 3 decimals with the
    residue folded into the largest, so the sub-shares sum EXACTLY to
    the rounded ``device_compute`` share published in ``shares``.
    Returns ``None`` when the cost plane was off or costed nothing —
    pre-r14 records keep their old (breakdown-free) shape.
    """
    if not costplane or not costplane.get("costed_records"):
        return None
    waste = costplane.get("padding_waste_pct")
    wf = float(waste) / 100.0 if isinstance(waste, (int, float)) else 0.0
    wf = min(max(wf, 0.0), 1.0)
    comp = float(costplane.get("compute_share_pct") or 0.0)
    memr = float(costplane.get("memory_share_pct") or 0.0)
    target = round(max(0.0, float(share)), 3)
    padding = target * wf
    rest = target - padding
    denom = comp + memr
    cb = rest * comp / denom if denom > 0.0 else rest
    out = {"compute_bound": round(cb, 3),
           "memory_bound": round(rest - cb, 3),
           "padding_waste": round(padding, 3)}
    residue = round(target - sum(out.values()), 3)
    if residue:
        top = max(out, key=lambda k: (out[k], k))
        out[top] = round(out[top] + residue, 3)
    return out


def _evidence(cause: str, *, inline_compile_ms: float,
              netplane: Optional[Dict], memplane: Optional[Dict],
              flushes: int, predicted_flushes: Optional[int],
              sem_wait_ms: float, busy_ms: float,
              compiles: Optional[List[Dict]] = None,
              costplane: Optional[Dict] = None,
              declared_transfers: Optional[Dict] = None) -> str:
    """Corroborating raw counter from the owning plane, as a string."""
    if cause == "device_compute":
        pred = ("?" if predicted_flushes is None
                else str(int(predicted_flushes)))
        return (f"busy_ms={busy_ms:.1f} over flushes={int(flushes)} "
                f"(predicted={pred}){_cost_mix(costplane)}")
    if cause == "inline_compile":
        return (f"inline_compile_ms={inline_compile_ms:.1f}"
                f"{_compile_mix(compiles)}")
    if cause == "sem_wait":
        return f"sem_wait_ms={sem_wait_ms:.1f}"
    if cause == "shuffle_host" and netplane:
        edges = netplane.get("edges", 0)
        if not isinstance(edges, (int, float)):
            edges = len(edges or [])
        return (f"host_drop_tax_ms={netplane.get('host_drop_tax_ms', 0)} "
                f"over edges={int(edges)} "
                f"skew={netplane.get('edge_skew', 0)}")
    if cause == "host_staging" and declared_transfers:
        top = sorted(declared_transfers.items(),
                     key=lambda kv: (-int(kv[1]), kv[0]))[:3]
        mix = ", ".join(f"{site}={int(n)}" for site, n in top)
        total = sum(int(n) for n in declared_transfers.values())
        return f"declared_transfers={total} ({mix})"
    if cause == "mem_spill" and memplane:
        spill = memplane.get("spill", {}) or {}
        moves = sum(int(v.get("count", 0)) for v in spill.values()
                    if isinstance(v, dict))
        return (f"spill_ms={memplane.get('spill_ms', 0)} over "
                f"{moves} tier moves, "
                f"peak_device_bytes={memplane.get('peak_device_bytes', 0)}")
    return ""


def diagnose(timeline_summary: Dict, *,
             inline_compile_ms: float = 0.0,
             netplane: Optional[Dict] = None,
             memplane: Optional[Dict] = None,
             flushes: int = 0,
             predicted_flushes: Optional[int] = None,
             sem_wait_ms: float = 0.0,
             stats_profile=None,
             query_id: Optional[str] = None,
             compiles: Optional[List[Dict]] = None,
             costplane: Optional[Dict] = None,
             declared_transfers: Optional[Dict] = None) -> QueryDiagnosis:
    """Join the per-query plane summaries into one verdict.

    Called by the session AFTER every plane summary is already
    collected — reads dictionaries only, never touches the device.
    """
    _mt0 = _overhead.clock()
    util_pct = float(timeline_summary.get("util_pct", 0.0))
    gaps = timeline_summary.get("gaps", {}) or {}
    shares = _normalized_shares(util_pct, gaps)

    # exactly one primary: max share, fixed taxonomy order as the
    # deterministic tie-break
    primary = min(shares, key=lambda c: (-shares[c], _CAUSE_ORDER[c]))

    candidates = []
    for cause, _item, _fix in TAXONOMY:
        share = shares.get(cause, 0.0)
        if share <= 0.0 or cause == "idle":
            continue
        candidates.append({
            "cause": cause,
            "share_pct": round(share, 3),
            "bound_x": round(_amdahl_bound(share), 3),
            "roadmap_item": _CAUSE_ROADMAP[cause],
            "fix": _CAUSE_FIX[cause],
            "evidence": _evidence(
                cause, inline_compile_ms=inline_compile_ms,
                netplane=netplane, memplane=memplane, flushes=flushes,
                predicted_flushes=predicted_flushes,
                sem_wait_ms=sem_wait_ms,
                busy_ms=float(timeline_summary.get("busy_ms", 0.0)),
                compiles=compiles, costplane=costplane,
                declared_transfers=declared_transfers),
        })
    # ranked: largest modeled headroom first, taxonomy order on ties
    candidates.sort(key=lambda c: (-c["share_pct"],
                                   _CAUSE_ORDER[c["cause"]]))

    data = {
        "query_id": query_id,
        "model_version": MODEL_VERSION,
        "primary_cause": primary,
        "primary_share_pct": round(shares[primary], 3),
        "shares": {c: round(v, 3) for c, v in shares.items()},
        "headroom": candidates,
        "flushes": int(flushes),
        "predicted_flushes": predicted_flushes,
    }
    breakdown = _device_compute_breakdown(
        shares.get("device_compute", 0.0), costplane)
    if breakdown is not None:
        data["device_compute_breakdown"] = breakdown
    if stats_profile is not None:
        try:
            data["stats_digest"] = stats_profile.stable_digest()
        except Exception:  # noqa: BLE001 — diagnosis never fails a query
            pass
    diag = QueryDiagnosis(data)
    _record_verdict(diag)
    _overhead.note(_overhead.P_DOCTOR, _mt0)
    return diag


def diagnose_bench(record: Dict) -> Optional[QueryDiagnosis]:
    """Build a verdict from a parsed ``BENCH_r*.json`` key set.

    Returns ``None`` when the record predates the timeline keys
    (rounds before r08 have no ``util_gap_breakdown``) — the perf
    gate's placeholder tolerance.
    """
    gaps = record.get("util_gap_breakdown")
    util = record.get("device_util_pct")
    if not isinstance(gaps, dict) or util is None:
        return None
    tl = {"util_pct": float(util), "gaps": gaps,
          "busy_ms": float(record.get("device_busy_ms", 0.0))}
    net = {"host_drop_tax_ms": record.get("host_drop_tax_ms", 0),
           "edge_skew": record.get("shuffle_edge_skew", 0),
           "edges": []}
    mem = {"spill_ms": record.get("spill_ms", 0), "spill": {},
           "peak_device_bytes": record.get("peak_device_bytes", 0)}
    # cost-plane keys land in r14 records; older rounds diagnose
    # without the device_compute breakdown (placeholder tolerance)
    cp = None
    verdict = record.get("roofline_verdict")
    if verdict is not None:
        v = str(verdict)
        cp = {"costed_records": 1, "verdict": v,
              "compute_share_pct": 100.0 if v == "compute_bound" else 0.0,
              "memory_share_pct": 0.0 if v == "compute_bound" else 100.0,
              "padding_waste_pct": record.get("padding_waste_pct"),
              "achieved_gbps": record.get("achieved_GBps"),
              "achieved_gflops": None}
    return diagnose(
        tl,
        inline_compile_ms=float(record.get("inline_compile_ms") or 0.0),
        netplane=net, memplane=mem,
        flushes=int(record.get("flushes") or 0),
        predicted_flushes=record.get("predicted_flushes"),
        query_id=record.get("metric"), costplane=cp)


def _record_verdict(diag: QueryDiagnosis) -> None:
    global _LAST
    cause = diag.primary_cause
    DOCTOR_VERDICTS.labels(cause=cause).inc()
    with _LOCK:
        _VERDICT_COUNTS[cause] = _VERDICT_COUNTS.get(cause, 0) + 1
        _LAST = {"query_id": diag.data.get("query_id"),
                 "primary_cause": cause,
                 "primary_share_pct": diag.primary_share_pct}


def stats_section() -> Dict:
    """The ``doctor`` block of ``Service.stats()``."""
    with _LOCK:
        out = {"enabled": bool(_ENABLED),
               "verdicts": dict(_VERDICT_COUNTS)}
        if _LAST is not None:
            out["last"] = dict(_LAST)
    try:
        # longitudinal view: the anomaly sentinel's per-fingerprint
        # drift ledger rides along so one doctor read shows both the
        # per-query verdict mix and the fleet trend behind it
        from . import anomaly as _anomaly
        trend = _anomaly.trend_section()
        if trend:
            out["trend"] = trend
    except Exception:  # noqa: BLE001 — trend is advisory
        pass
    return out


def enabled(conf=None) -> bool:
    """Plane gate: module default, overridden per-session by conf."""
    if conf is not None:
        from ..config import OBS_DOCTOR_ENABLED
        return bool(conf.get(OBS_DOCTOR_ENABLED))
    return _ENABLED


def is_enabled() -> bool:
    return _ENABLED


def configure(conf) -> None:
    """Apply the ``spark.rapids.tpu.obs.doctor.*`` conf group."""
    global _ENABLED
    from ..config import OBS_DOCTOR_ENABLED
    _ENABLED = bool(conf.get(OBS_DOCTOR_ENABLED))


def reset() -> None:
    """Test hook: drop verdict counts and the last-verdict cache."""
    global _LAST
    with _LOCK:
        _VERDICT_COUNTS.clear()
        _LAST = None
