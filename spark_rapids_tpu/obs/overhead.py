"""Observability self-metering — the tax collector's own books.

Eight default-on planes instrument every query; ROADMAP item 9 records
what that buildout grew to cost (``stats_overhead_pct`` 0.07 in r12 ->
18.2 in r15).  This module prices the observability layer itself, per
plane, so the tax is *attributed* — not just measured as one global
on-vs-off delta the bench can report but nobody can act on.

Design (the interning discipline, enforced by lint rule OBS003):

- plane ids are interned module-level ints (``P_STATS`` ...) indexing
  PREALLOCATED nanosecond/call counter lists — recording is two list
  writes and two ``perf_counter_ns`` reads, no dict/list/str
  allocation anywhere on the record path;
- each plane's hot-path entry points bracket their body with
  ``t0 = clock()`` / ``note(P_X, t0)``: stats staging
  (obs/stats.py), timeline note_flush, netplane put/get accounting,
  memplane register/sweep, costplane dispatch accounting, history row
  build, doctor assembly.  The flight recorder is exempt by
  construction — it IS the allocation-free baseline the others are
  measured against;
- unsynchronized ``+=`` on the counter cells races benignly under
  concurrent producers (a lost update shaves nanoseconds off a meter,
  never off a query) — the profile._DISPATCH discipline, chosen over
  a lock because a lock here would bill its own cost to every plane;
- ``clock()`` returns 0 when the meter is disabled, and ``note``
  treats 0 as "skip", so the disabled path is one module-global read.

Surfaces: ``tpu_obs_self_seconds_total{plane=...}`` (collect-time
callbacks — scrapes pay the cost, the note path pays nothing),
``stats()["obs_overhead"]`` via :func:`stats_section`, and the bench's
per-plane ``obs_self_ms`` breakdown via :func:`snapshot` /
:func:`delta_ms` around the headline run.
"""
from __future__ import annotations

import time
from typing import Dict, Sequence, Tuple

#: plane name order — the label set of tpu_obs_self_seconds_total and
#: the key order of every snapshot/section built from the counters
PLANES = ("stats", "timeline", "net", "mem", "cost", "history",
          "doctor", "burn")

P_STATS = 0
P_TIMELINE = 1
P_NET = 2
P_MEM = 3
P_COST = 4
P_HISTORY = 5
P_DOCTOR = 6
P_BURN = 7

_N = len(PLANES)

_ENABLED = True

#: preallocated per-plane counters (ns / record calls); fixed length,
#: never reallocated — readers index, writers +=
_NS = [0] * _N
_CALLS = [0] * _N


def clock() -> int:
    """Stamp the start of one metered plane-hot-path call.  Returns 0
    when the meter is off, which ``note`` treats as "skip"."""
    if not _ENABLED:
        return 0
    return time.perf_counter_ns()


def note(plane: int, t0: int) -> None:
    """Close the metered window opened by ``clock()`` (or by any
    ``perf_counter_ns`` stamp the caller already took) and bill it to
    ``plane``.  Two list writes; no allocation (OBS003)."""
    if t0 and _ENABLED:
        _NS[plane] += time.perf_counter_ns() - t0
        _CALLS[plane] += 1


def is_enabled() -> bool:
    return _ENABLED


# ---------------------------------------------------------------------------
# cold-path readers (registry callbacks, stats(), bench windows)
# ---------------------------------------------------------------------------

def plane_seconds(plane: str) -> float:
    """Collect-time callback for tpu_obs_self_seconds_total{plane}."""
    return _NS[PLANES.index(plane)] / 1e9


def snapshot() -> Tuple[int, ...]:
    """Value snapshot of the per-plane ns counters (bench windows —
    the FLUSH_COUNT process-wide-counter-delta discipline)."""
    return tuple(_NS)


def delta_ms(since: Sequence[int]) -> Dict[str, float]:
    """Per-plane self-cost in ms accrued since a ``snapshot()``."""
    return {PLANES[i]: round((_NS[i] - since[i]) / 1e6, 3)
            for i in range(_N)}


def total_ms() -> float:
    return round(sum(_NS) / 1e6, 3)


def stats_section() -> Dict:
    """The ``obs_overhead`` block of ``Service.stats()``: where the
    observability tax lives, by plane."""
    total_ns = sum(_NS)
    return {
        "enabled": bool(_ENABLED),
        "total_ms": round(total_ns / 1e6, 3),
        "planes": {
            PLANES[i]: {"ms": round(_NS[i] / 1e6, 3),
                        "calls": _CALLS[i]}
            for i in range(_N)},
    }


def configure(conf) -> None:
    """Apply the ``spark.rapids.tpu.obs.overhead.*`` conf group."""
    global _ENABLED
    from ..config import OBS_OVERHEAD_ENABLED
    _ENABLED = bool(conf.get(OBS_OVERHEAD_ENABLED))


def reset() -> None:
    """Test hook: zero the counters (lengths never change)."""
    for i in range(_N):
        _NS[i] = 0
        _CALLS[i] = 0
