"""Shuffle-transport observability plane — the fifth plane, covering
the one layer the trace/flight/stats/perf planes cannot see into: what
happens to a batch between the map-side split and the reduce-side read.

Our shuffle drops every exchange to host (SpillableBatch staging, an
optional TCP hop through bounce buffers, then a host->device upload on
read); the reference keeps shuffle data on-device over UCX.  ROADMAP
item 2 (HBM-resident ICI shuffle) needs a measured baseline before we
lower exchanges to ``all_to_all`` — and the same instruments to prove
the win afterwards.  Three pillars:

- **per-edge transfer matrix** — bounded (shuffle_id, map partition ->
  reduce partition) accumulation of rows/bytes/batches, fed by the
  shuffle catalog's put/append/get paths; per-peer fetch-latency
  histograms, connection-pool dial/reuse/reset counters (shuffle/tcp),
  bounce-buffer occupancy and dwell gauges (shuffle/bounce).
- **host-drop tax accounting** — every staged block's life splits into
  four phases: ``serialize`` (device->host pull into the spillable
  batch / TableMeta build), ``dwell`` (host residency between the
  serialize end and the read), ``wire`` (TCP transfer incl. the bounce
  hop), ``deserialize`` (host->device upload on read).  serialize,
  wire and deserialize are measured; dwell is the block-lifecycle
  remainder, so the four phases sum to the exchange wall time by
  construction.  ``host_drop_tax_ms`` (the per-query roll-up bench.py
  and the event log carry) is the ACTIVE portion — serialize + wire +
  deserialize — because dwell overlaps useful compute.  The active
  windows also feed the PR 8 timeline as the ``shuffle_host`` gap
  cause, so ``util_gap_breakdown`` distinguishes shuffle host-staging
  from generic pipeline drains.
- **cross-boundary correlation** — (query_id, span_id) ride the
  shuffle metadata/transfer requests (shuffle/transport.py dataclasses,
  optional trailing fields on the TCP wire) so server serve spans and
  client fetch spans join into one Perfetto trace; EV_NET flight
  events mark the same boundaries allocation-free.

Hot-path discipline (this file is on the SYNC001/OBS002 lint scope):
no numpy, no device pulls, no formatted flight-record args; the note_*
paths run once per staged block / wire transaction — hundreds per
exchange at most — and never force a flush (the zero-extra-flush
acceptance criterion is an exact FLUSH_COUNT delta, tested).
"""
from __future__ import annotations

import itertools
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

from . import flight
from . import overhead as _overhead
from .registry import (SHUFFLE_BOUNCE_DWELL_SECONDS, SHUFFLE_CONN_EVENTS,
                       SHUFFLE_EDGES_EVICTED, SHUFFLE_FETCH_SECONDS,
                       SHUFFLE_HOST_DROP_SECONDS)

# host-drop phase constants (interned: flight records pass them verbatim)
PH_SERIALIZE = "serialize"
PH_DWELL = "dwell"
PH_WIRE = "wire"
PH_DESERIALIZE = "deserialize"
PHASES = (PH_SERIALIZE, PH_DWELL, PH_WIRE, PH_DESERIALIZE)

_ENABLED = True
_MAX_EDGES = 1 << 16      #: edge-matrix bound (conf obs.net.maxEdges)
_SEG_CAP = 1 << 16        #: active-window / edge-log bound

_LOCK = threading.Lock()

#: the transfer matrix: (shuffle_id, map_id, reduce_id) -> [rows,
#: bytes, batches].  Bounded: past _MAX_EDGES new edges are counted as
#: evicted instead of growing without limit.
_EDGES: Dict[Tuple[int, int, int], List[int]] = {}
_EVICTED = 0

#: append-only per-block log for per-query summaries (skew, heat
#: table); GIL-atomic appends like profile._DISPATCH, readers slice.
_EDGE_LOG: List[Tuple[int, int, int, int, int]] = []

#: active host-drop work windows (start_ns, end_ns) — serialize, wire
#: and deserialize only (dwell is passive) — the timeline's
#: ``shuffle_host`` gap evidence.  Append-only, bounded.
_ACTIVE: List[Tuple[int, int]] = []
_ACTIVE_DROPPED = 0

#: measured phase totals (ns / bytes) and the block-lifecycle wall
_PHASE_NS = {PH_SERIALIZE: 0, PH_WIRE: 0, PH_DESERIALIZE: 0}
_WALL_NS = 0
_STAGED_BYTES = 0
_WIRE_BYTES = 0

#: serialize-start stamp per staged block: the dwell clock
_BORN: Dict[Tuple[int, int, int], int] = {}

_PENDING_FETCHES = 0
_CONN_EVENTS = {"dial": 0, "reuse": 0, "reset": 0}

#: codec traffic through the host boundary (shuffle/compression.py):
#: raw vs compressed bytes, so per-query records and the report can
#: print the effective compression ratio next to the wire bytes it
#: explains
_COMP_RAW = 0
_COMP_BYTES = 0
_COMP_CODECS: set = set()

#: per-peer fetch aggregate: peer -> [count, total_ns, bytes, max_ns]
#: (the offline-report view of what tpu_shuffle_fetch_seconds observes)
_FETCH_PEERS: Dict[str, List[int]] = {}

#: span-id sequence for cross-boundary correlation (itertools.count is
#: GIL-atomic in CPython)
_SPAN_SEQ = itertools.count(1)

#: weakrefs to live BounceBufferManagers / heartbeat managers so the
#: collect-time gauges and stats() read state those layers already hold
_BOUNCE_MGRS: List = []
_HEARTBEAT_MGRS: List = []


def next_span_id() -> int:
    """Fresh correlation id for one fetch; rides the metadata/transfer
    requests so the server's serve span joins the client's fetch span."""
    return next(_SPAN_SEQ)


def _note_active(start_ns: int, end_ns: int):
    global _ACTIVE_DROPPED
    if end_ns <= start_ns:
        return
    if len(_ACTIVE) < _SEG_CAP:
        _ACTIVE.append((start_ns, end_ns))
    else:
        _ACTIVE_DROPPED += 1


def note_serialize(shuffle_id: int, map_id: int, reduce_id: int,
                   rows: int, nbytes: int, dur_ns: int) -> None:
    """One block landed on host: device->host serialize finished now,
    having taken ``dur_ns``.  Records the matrix edge, starts the
    block's dwell clock, and opens the serialize phase accounting."""
    global _EVICTED, _STAGED_BYTES
    if not _ENABLED:
        return
    now = time.perf_counter_ns()
    key = (shuffle_id, map_id, reduce_id)
    with _LOCK:
        cell = _EDGES.get(key)
        if cell is None:
            if len(_EDGES) >= _MAX_EDGES:
                _EVICTED += 1
                SHUFFLE_EDGES_EVICTED.inc()
            else:
                cell = _EDGES[key] = [0, 0, 0]
        if cell is not None:
            cell[0] += rows
            cell[1] += nbytes
            cell[2] += 1
        _PHASE_NS[PH_SERIALIZE] += dur_ns
        _STAGED_BYTES += nbytes
        if key not in _BORN:
            _BORN[key] = now - dur_ns
    if len(_EDGE_LOG) < _SEG_CAP:
        _EDGE_LOG.append((shuffle_id, map_id, reduce_id, rows, nbytes))
    _note_active(now - dur_ns, now)
    SHUFFLE_HOST_DROP_SECONDS.labels(phase=PH_SERIALIZE).inc(dur_ns / 1e9)
    flight.record(flight.EV_NET, PH_SERIALIZE, nbytes, dur_ns // 1_000_000)
    # self-meter: the now stamp above doubles as the meter start
    _overhead.note(_overhead.P_NET, now)


def note_wire(nbytes: int, dur_ns: int) -> None:
    """One wire transaction (TCP send incl. the bounce-buffer hop)
    moved ``nbytes`` in ``dur_ns``."""
    global _WIRE_BYTES
    if not _ENABLED:
        return
    now = time.perf_counter_ns()
    with _LOCK:
        _PHASE_NS[PH_WIRE] += dur_ns
        _WIRE_BYTES += nbytes
    _note_active(now - dur_ns, now)
    SHUFFLE_HOST_DROP_SECONDS.labels(phase=PH_WIRE).inc(dur_ns / 1e9)
    flight.record(flight.EV_NET, PH_WIRE, nbytes, dur_ns // 1_000_000)
    _overhead.note(_overhead.P_NET, now)


def note_deserialize(shuffle_id: int, map_id: int, reduce_id: int,
                     nbytes: int, dur_ns: int) -> None:
    """One staged block was read back (host->device upload took
    ``dur_ns``); closes the block's lifecycle, so the dwell phase —
    wall minus the measured phases — is final for this block."""
    global _WALL_NS
    if not _ENABLED:
        return
    now = time.perf_counter_ns()
    key = (shuffle_id, map_id, reduce_id)
    with _LOCK:
        _PHASE_NS[PH_DESERIALIZE] += dur_ns
        born = _BORN.pop(key, None)
        # a re-read (retry) block's clock was already consumed: cover
        # at least the upload itself so phases can't exceed the wall
        _WALL_NS += (now - born) if born is not None else dur_ns
    _note_active(now - dur_ns, now)
    SHUFFLE_HOST_DROP_SECONDS.labels(phase=PH_DESERIALIZE).inc(dur_ns / 1e9)
    flight.record(flight.EV_NET, PH_DESERIALIZE, nbytes,
                  dur_ns // 1_000_000)
    _overhead.note(_overhead.P_NET, now)


def note_fetch(peer: str, dur_ns: int, nbytes: int) -> None:
    """One remote fetch (metadata request -> last table landed)
    completed against ``peer`` (cold path: once per peer per read)."""
    if not _ENABLED:
        return
    _mt0 = _overhead.clock()
    with _LOCK:
        cell = _FETCH_PEERS.get(peer)
        if cell is None:
            cell = _FETCH_PEERS[peer] = [0, 0, 0, 0]
        cell[0] += 1
        cell[1] += dur_ns
        cell[2] += nbytes
        cell[3] = max(cell[3], dur_ns)
    SHUFFLE_FETCH_SECONDS.labels(peer=peer).observe(dur_ns / 1e9)
    flight.record(flight.EV_NET, "fetch", nbytes, dur_ns // 1_000_000)
    _overhead.note(_overhead.P_NET, _mt0)


def note_conn(event: str) -> None:
    """Connection-pool transition from shuffle/tcp.py: ``dial`` (new
    socket), ``reuse`` (pooled socket served a request batch), or
    ``reset`` (connection torn down, pending transactions errored)."""
    if not _ENABLED:
        return
    with _LOCK:
        _CONN_EVENTS[event] = _CONN_EVENTS.get(event, 0) + 1
    SHUFFLE_CONN_EVENTS.labels(event=event).inc()


def note_compression(codec: str, raw_bytes: int,
                     compressed_bytes: int) -> None:
    """One codec transaction (compress or decompress) moved
    ``raw_bytes`` of table data into/out of ``compressed_bytes`` on the
    wire/spill side; both directions accumulate, so the ratio stays
    compressed/raw either way."""
    global _COMP_RAW, _COMP_BYTES
    if not _ENABLED:
        return
    with _LOCK:
        _COMP_RAW += raw_bytes
        _COMP_BYTES += compressed_bytes
        _COMP_CODECS.add(codec)


def note_bounce_dwell(dur_ns: int) -> None:
    """One bounce buffer went acquire->release in ``dur_ns``."""
    if not _ENABLED:
        return
    SHUFFLE_BOUNCE_DWELL_SECONDS.observe(dur_ns / 1e9)


def fetch_begun() -> None:
    global _PENDING_FETCHES
    with _LOCK:
        _PENDING_FETCHES += 1


def fetch_done() -> None:
    global _PENDING_FETCHES
    with _LOCK:
        _PENDING_FETCHES -= 1


def fetch_peer_stats() -> Dict[str, Dict]:
    """Per-peer fetch-latency aggregate (process-lifetime): the report's
    offline stand-in for the tpu_shuffle_fetch_seconds histogram."""
    with _LOCK:
        items = [(p, list(c)) for p, c in _FETCH_PEERS.items()]
    return {
        p: {"count": c[0],
            "avg_ms": round(c[1] / c[0] / 1e6, 3) if c[0] else 0.0,
            "max_ms": round(c[3] / 1e6, 3),
            "bytes": c[2]}
        for p, c in items
    }


def pending_fetches() -> int:
    """Collect-time callback for the tpu_shuffle_pending_fetches gauge
    — the instrument that surfaced the client.close() drop bug."""
    return _PENDING_FETCHES


def edges_tracked() -> int:
    """Collect-time callback for the tpu_shuffle_edges_tracked gauge."""
    return len(_EDGES)


def register_bounce(mgr) -> None:
    """Track a live BounceBufferManager (weakly) for the occupancy
    gauges."""
    _BOUNCE_MGRS.append(weakref.ref(mgr))


def register_heartbeat(mgr) -> None:
    """Track a live RapidsShuffleHeartbeatManager (weakly) for the
    per-peer last-seen ages in stats()."""
    _HEARTBEAT_MGRS.append(weakref.ref(mgr))


def _live(refs: List) -> List:
    out = []
    dead = False
    for r in refs:
        obj = r()
        if obj is None:
            dead = True
        else:
            out.append(obj)
    if dead:
        refs[:] = [r for r in refs if r() is not None]
    return out


def bounce_free() -> int:
    return sum(m.num_free for m in _live(_BOUNCE_MGRS))


def bounce_total() -> int:
    return sum(m.num_total for m in _live(_BOUNCE_MGRS))


# ---------------------------------------------------------------------------
# timeline evidence (cold path, called from obs/timeline._summarize)
# ---------------------------------------------------------------------------

def active_segments(t0: int, t1: int) -> List[Tuple[int, int]]:
    """Host-drop work windows overlapping [t0, t1] — the timeline's
    ``shuffle_host`` gap-cause evidence."""
    if not _ENABLED:
        return []
    return [(s, e) for s, e in _ACTIVE[:] if e > t0 and s < t1]


# ---------------------------------------------------------------------------
# per-query roll-up (cold paths)
# ---------------------------------------------------------------------------

def begin_query() -> Dict[str, int]:
    """Value/length snapshot marker for a per-query summary."""
    with _LOCK:
        return {
            "ser_ns": _PHASE_NS[PH_SERIALIZE],
            "wire_ns": _PHASE_NS[PH_WIRE],
            "deser_ns": _PHASE_NS[PH_DESERIALIZE],
            "wall_ns": _WALL_NS,
            "staged_bytes": _STAGED_BYTES,
            "wire_bytes": _WIRE_BYTES,
            "comp_raw": _COMP_RAW,
            "comp_bytes": _COMP_BYTES,
            "edge_log_len": len(_EDGE_LOG),
        }


def _skew(entries: List[Tuple[int, int, int, int, int]]) -> float:
    """max/mean bytes-per-reduce-partition ratio, worst shuffle wins
    (1.0 = perfectly balanced; 0.0 = no shuffle traffic)."""
    per: Dict[Tuple[int, int], int] = {}
    for sid, _mid, rid, _rows, nbytes in entries:
        k = (sid, rid)
        per[k] = per.get(k, 0) + nbytes
    by_shuffle: Dict[int, List[int]] = {}
    for (sid, _rid), b in per.items():
        by_shuffle.setdefault(sid, []).append(b)
    worst = 0.0
    for vals in by_shuffle.values():
        mean = sum(vals) / len(vals)
        if mean > 0:
            worst = max(worst, max(vals) / mean)
    return round(worst, 3)


def query_summary(marker: Optional[Dict[str, int]] = None) -> Dict:
    """Host-drop roll-up since a ``begin_query()`` marker: the four-
    phase split (summing to ``exchange_wall_ms`` by construction), the
    active-work tax, wire throughput and the per-edge skew verdict."""
    m = marker or {}
    with _LOCK:
        ser = _PHASE_NS[PH_SERIALIZE] - m.get("ser_ns", 0)
        wire = _PHASE_NS[PH_WIRE] - m.get("wire_ns", 0)
        deser = _PHASE_NS[PH_DESERIALIZE] - m.get("deser_ns", 0)
        wall = _WALL_NS - m.get("wall_ns", 0)
        staged = _STAGED_BYTES - m.get("staged_bytes", 0)
        wire_b = _WIRE_BYTES - m.get("wire_bytes", 0)
        comp_raw = _COMP_RAW - m.get("comp_raw", 0)
        comp_b = _COMP_BYTES - m.get("comp_bytes", 0)
        codecs = sorted(_COMP_CODECS)
        lo = m.get("edge_log_len", 0)
    entries = _EDGE_LOG[lo:]
    dwell = max(wall - ser - wire - deser, 0)
    mbps = (wire_b / 1e6) / (wire / 1e9) if wire > 0 else 0.0
    return {
        "phases_ms": {
            PH_SERIALIZE: round(ser / 1e6, 3),
            PH_DWELL: round(dwell / 1e6, 3),
            PH_WIRE: round(wire / 1e6, 3),
            PH_DESERIALIZE: round(deser / 1e6, 3),
        },
        "exchange_wall_ms": round(max(wall, ser + wire + deser) / 1e6, 3),
        "host_drop_tax_ms": round((ser + wire + deser) / 1e6, 3),
        "staged_bytes": staged,
        "wire_bytes": wire_b,
        "wire_MBps": round(mbps, 3),
        "compression": {
            "raw_bytes": comp_raw,
            "compressed_bytes": comp_b,
            # effective ratio raw/compressed (e.g. 3.2 = wire carries
            # ~31% of the raw bytes); 1.0 when no codec traffic
            "ratio": round(comp_raw / comp_b, 3) if comp_b else 1.0,
            "codecs": codecs,
        },
        "edge_skew": _skew(entries),
        "edges": len({(s, mp, r) for s, mp, r, _w, _b in entries}),
        "blocks": len(entries),
    }


def query_edges(marker: Optional[Dict[str, int]] = None,
                limit: int = 0) -> List[Dict]:
    """Per-edge rows for the report's heat table, biggest bytes first,
    aggregated over the edge log since ``marker``."""
    lo = (marker or {}).get("edge_log_len", 0)
    agg: Dict[Tuple[int, int, int], List[int]] = {}
    for sid, mid, rid, rows, nbytes in _EDGE_LOG[lo:]:
        cell = agg.setdefault((sid, mid, rid), [0, 0, 0])
        cell[0] += rows
        cell[1] += nbytes
        cell[2] += 1
    out = [{"shuffle_id": k[0], "map_id": k[1], "reduce_id": k[2],
            "rows": v[0], "bytes": v[1], "batches": v[2]}
           for k, v in agg.items()]
    out.sort(key=lambda e: (-e["bytes"], e["shuffle_id"], e["map_id"],
                            e["reduce_id"]))
    return out[:limit] if limit else out


def edge_matrix(limit: int = 0) -> List[Dict]:
    """Process-wide matrix view (diag bundles / stats), biggest first."""
    with _LOCK:
        items = [(k, list(v)) for k, v in _EDGES.items()]
    out = [{"shuffle_id": k[0], "map_id": k[1], "reduce_id": k[2],
            "rows": v[0], "bytes": v[1], "batches": v[2]}
           for k, v in items]
    out.sort(key=lambda e: (-e["bytes"], e["shuffle_id"], e["map_id"],
                            e["reduce_id"]))
    return out[:limit] if limit else out


def stats_section() -> Dict:
    """The ``shuffle`` block of ``Service.stats()``."""
    with _LOCK:
        conn = dict(_CONN_EVENTS)
        edges_tracked = len(_EDGES)
        evicted = _EVICTED
        pending = _PENDING_FETCHES
    summary = query_summary(None)
    peers: Dict[str, Dict] = {}
    for mgr in _live(_HEARTBEAT_MGRS):
        try:
            peers.update(mgr.peer_stats())
        except Exception:
            pass
    return {
        "enabled": bool(_ENABLED),
        "edges_tracked": edges_tracked,
        "edges_evicted": evicted,
        "host_drop": {"phases_ms": summary["phases_ms"],
                      "exchange_wall_ms": summary["exchange_wall_ms"],
                      "host_drop_tax_ms": summary["host_drop_tax_ms"]},
        "staged_bytes": summary["staged_bytes"],
        "wire_bytes": summary["wire_bytes"],
        "wire_MBps": summary["wire_MBps"],
        "compression": summary["compression"],
        "edge_skew": summary["edge_skew"],
        "connections": conn,
        "pending_fetches": pending,
        "bounce": {"free": bounce_free(), "total": bounce_total()},
        "peers": peers,
        "fetch_peers": fetch_peer_stats(),
        "top_edges": edge_matrix(limit=5),
    }


def is_enabled() -> bool:
    return _ENABLED


def configure(conf) -> None:
    """Apply the ``spark.rapids.tpu.obs.net.*`` conf group."""
    global _ENABLED, _MAX_EDGES, _SEG_CAP
    from ..config import (OBS_NET_ENABLED, OBS_NET_MAX_EDGES,
                          OBS_NET_MAX_INTERVALS)
    _ENABLED = bool(conf.get(OBS_NET_ENABLED))
    edges = int(conf.get(OBS_NET_MAX_EDGES))
    if edges > 0:
        _MAX_EDGES = edges
    cap = int(conf.get(OBS_NET_MAX_INTERVALS))
    if cap > 0:
        _SEG_CAP = cap


def reset() -> None:
    """Test hook: drop the matrix, logs, phase totals and registrations."""
    global _EVICTED, _WALL_NS, _STAGED_BYTES, _WIRE_BYTES
    global _PENDING_FETCHES, _ACTIVE_DROPPED, _COMP_RAW, _COMP_BYTES
    with _LOCK:
        _EDGES.clear()
        _BORN.clear()
        _EVICTED = 0
        for ph in _PHASE_NS:
            _PHASE_NS[ph] = 0
        _WALL_NS = 0
        _STAGED_BYTES = 0
        _WIRE_BYTES = 0
        _PENDING_FETCHES = 0
        _ACTIVE_DROPPED = 0
        _COMP_RAW = 0
        _COMP_BYTES = 0
        _COMP_CODECS.clear()
        _CONN_EVENTS.clear()
        _CONN_EVENTS.update({"dial": 0, "reuse": 0, "reset": 0})
        _FETCH_PEERS.clear()
    del _EDGE_LOG[:]
    del _ACTIVE[:]
    del _BOUNCE_MGRS[:]
    del _HEARTBEAT_MGRS[:]
