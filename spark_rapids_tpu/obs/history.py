"""Persistent query-history store — the longitudinal substrate of the
fleet observability layer (the Spark History Server role for a serving
process).

Every terminal query (completed, failed, cancelled, shed) folds into
ONE compact row joining what the service measured (QueryMetrics:
latency phases, retries, outcome) with what the engine planes already
collected for that query_id (plan fingerprint, predicted/observed
flushes, device_util_pct + gap breakdown, host-drop tax, spill,
roofline verdict, doctor verdict).  Rows flow three ways:

- **persistence**: appended as JSONL to ``history-NNNNNN.jsonl``
  segments under ``spark.rapids.tpu.obs.history.dir`` by a background
  writer thread behind a bounded queue — a full queue DROPS the row
  (counted in ``tpu_history_dropped_total``) rather than ever
  blocking or failing the query path.  Rows are serialized ONCE,
  caller-side in :func:`record` (so the writer thread never touches
  the dict), and the writer drains the queue in batches: one blocking
  get, then everything already waiting, ONE segment ``open`` per
  batch (the r16 regression was one open per row — 385us -> 3920us
  write p99 under contention).  Segments rotate by size and by
  row-timestamp age and are retained up to ``retention.maxSegments``.
  An empty dir (the default) keeps the store in-memory only.
- **fleet aggregates**: bounded per-fingerprint accounting (count,
  outcome mix, latency reservoir, tenants, doctor causes) feeding
  ``Service.stats()``, the dashboard and the doctor trend section.
- **the sentinel**: ``record()`` returns the row so the caller can
  hand it to ``obs/anomaly.py`` — the history store itself never
  emits events.

The engine side deposits its artifacts through :func:`note_query`
*before* the service's terminal transition calls :func:`record` (the
session executes strictly before the worker marks the query terminal,
and both key by the same ``query_id``), so the join needs no
session-global state and is safe under concurrent workers.

Wall-clock discipline (lint scope HYG002): this module never calls
``time.time()`` — row timestamps are the ``submitted_ts`` the server
already stamped, age rotation compares row timestamps to each other,
and write durations use the monotonic ``perf_counter_ns``.  Zero
extra device flushes by construction: pure host dict/file work.
"""
from __future__ import annotations

import glob
import json
import os
import queue as _queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from . import overhead as _overhead
from .registry import HISTORY_DROPPED, HISTORY_ROWS, HISTORY_WRITE_SECONDS

#: cap on deposited engine artifacts awaiting their terminal join
#: (orphans from crashed attempts age out oldest-first)
_ARTIFACT_CAP = 4096
#: per-fingerprint latency reservoir length (nearest-rank percentiles)
_RESERVOIR = 256
#: recent rows kept for the dashboard's in-memory view
_RECENT_CAP = 512

_ENABLED = True
_DIR = ""
_MAX_SEG_BYTES = 4 * 1024 * 1024
_MAX_SEG_AGE_S = 0
_MAX_SEGMENTS = 8
_QUEUE_DEPTH = 1024
_MAX_FPS = 1024

_LOCK = threading.Lock()
_ARTIFACTS: Dict[str, Dict] = {}
_RECENT: deque = deque(maxlen=_RECENT_CAP)
_WRITE_NS: deque = deque(maxlen=4096)
_ROWS = 0
_DROPPED = 0
_FP_OVERFLOW = 0

_Q: Optional[_queue.Queue] = None
_WRITER: Optional[threading.Thread] = None

# active-segment state, owned by the writer thread
_SEG_PATH: Optional[str] = None
_SEG_BYTES = 0
_SEG_FIRST_TS: Optional[float] = None


class _FpAgg:
    """One fingerprint's bounded fleet aggregate."""

    __slots__ = ("count", "outcomes", "exec_ms", "total_ms", "tenants",
                 "causes", "burn_ms", "last_ts")

    def __init__(self):
        self.count = 0
        self.outcomes: Dict[str, int] = {}
        self.exec_ms: deque = deque(maxlen=_RESERVOIR)
        self.total_ms: deque = deque(maxlen=_RESERVOIR)
        self.tenants: Dict[str, int] = {}
        self.causes: Dict[str, int] = {}
        self.burn_ms = 0.0
        self.last_ts = 0.0


_AGGS: Dict[str, _FpAgg] = {}


def enabled() -> bool:
    return _ENABLED


# ---------------------------------------------------------------------------
# engine-side artifact deposit
# ---------------------------------------------------------------------------

def note_query(query_id: str, artifacts: Dict) -> None:
    """Deposit the engine planes' per-query artifacts (fingerprint,
    flushes, util, roofline, doctor verdict) for the terminal join.
    Called by the session right after query execution; bounded, never
    raises into the query path."""
    if not _ENABLED or not query_id:
        return
    with _LOCK:
        _ARTIFACTS[str(query_id)] = dict(artifacts)
        while len(_ARTIFACTS) > _ARTIFACT_CAP:
            _ARTIFACTS.pop(next(iter(_ARTIFACTS)))


# ---------------------------------------------------------------------------
# terminal-state fold
# ---------------------------------------------------------------------------

def _build_row(m, art: Dict) -> Dict:
    err = getattr(m, "error", None)
    row = {
        "ts": round(float(getattr(m, "submitted_ts", 0.0) or 0.0), 6),
        "query_id": m.query_id,
        "fingerprint": str(art.get("fingerprint") or "unknown"),
        "tenant": str(getattr(m, "tenant", None) or "default"),
        "outcome": m.outcome,
        "error": (str(err)[:160] if err else None),
        "retries": int(getattr(m, "retries", 0) or 0),
        "queue_ms": round(float(m.queue_wait_ms or 0.0), 3),
        "sem_ms": round(float(getattr(m, "sem_wait_ms", 0.0) or 0.0), 3),
        "exec_ms": round(float(m.execute_ms or 0.0), 3),
        "inline_compile_ms": round(
            float(getattr(m, "inline_compile_ms", 0.0) or 0.0), 3),
        "host_drop_tax_ms": round(
            float(getattr(m, "host_drop_tax_ms", 0.0) or 0.0), 3),
        "spill_bytes": int(getattr(m, "spill_bytes", 0) or 0),
        "spill_ms": round(float(getattr(m, "spill_ms", 0.0) or 0.0), 3),
    }
    for key in ("flushes", "flushes_predicted", "device_util_pct",
                "gaps", "roofline_verdict", "achieved_GBps",
                "padding_waste_pct", "doctor_cause",
                "doctor_share_pct"):
        if key in art:
            row[key] = art[key]
    return row


def record(m) -> Optional[Dict]:
    """Fold one finished query's QueryMetrics (+ deposited engine
    artifacts) into the store.  Called by the service at every
    terminal transition — exactly once per query.  Returns the
    history row so the caller can feed the anomaly sentinel, or
    ``None`` when the plane is off."""
    global _ROWS, _DROPPED, _FP_OVERFLOW
    if not _ENABLED:
        return None
    _mt0 = _overhead.clock()
    with _LOCK:
        art = _ARTIFACTS.pop(str(m.query_id), None) or {}
    row = _build_row(m, art)
    HISTORY_ROWS.labels(outcome=row["outcome"]).inc()
    total = row["queue_ms"] + row["exec_ms"]
    with _LOCK:
        _ROWS += 1
        _RECENT.append(row)
        fp = row["fingerprint"]
        agg = _AGGS.get(fp)
        if agg is None:
            if len(_AGGS) >= _MAX_FPS:
                _FP_OVERFLOW += 1
                agg = None
            else:
                agg = _AGGS[fp] = _FpAgg()
        if agg is not None:
            agg.count += 1
            agg.outcomes[row["outcome"]] = \
                agg.outcomes.get(row["outcome"], 0) + 1
            agg.exec_ms.append(row["exec_ms"])
            agg.total_ms.append(total)
            t = row["tenant"]
            agg.tenants[t] = agg.tenants.get(t, 0) + 1
            cause = row.get("doctor_cause")
            if cause:
                agg.causes[cause] = agg.causes.get(cause, 0) + 1
            agg.last_ts = max(agg.last_ts, row["ts"])
        q = _Q
    if q is not None:
        # serialize HERE, once, so the writer thread handles opaque
        # bytes — the r16 p99 regression came from the writer doing
        # dumps+open per row while terminal transitions piled on
        data = (json.dumps(row, separators=(",", ":"), sort_keys=True)
                + "\n").encode()
        try:
            q.put_nowait((data, row["ts"]))
        except _queue.Full:
            HISTORY_DROPPED.inc()
            with _LOCK:
                _DROPPED += 1
    _overhead.note(_overhead.P_HISTORY, _mt0)
    return row


# ---------------------------------------------------------------------------
# background writer (persistence)
# ---------------------------------------------------------------------------

def _segments(d: str) -> List[str]:
    return sorted(glob.glob(os.path.join(d, "history-*.jsonl")))


def _next_segment_path(d: str) -> str:
    seq = 0
    for p in _segments(d):
        name = os.path.basename(p)
        try:
            seq = max(seq, int(name[len("history-"):-len(".jsonl")]))
        except ValueError:
            continue
    return os.path.join(d, f"history-{seq + 1:06d}.jsonl")


def _adopt_segment(d: str) -> None:
    """Resume appending to the newest existing segment (append-only
    across process restarts)."""
    global _SEG_PATH, _SEG_BYTES, _SEG_FIRST_TS
    segs = _segments(d)
    if not segs:
        _SEG_PATH, _SEG_BYTES, _SEG_FIRST_TS = None, 0, None
        return
    _SEG_PATH = segs[-1]
    try:
        _SEG_BYTES = os.path.getsize(_SEG_PATH)
        with open(_SEG_PATH, "r", encoding="utf-8") as f:
            first = f.readline().strip()
        _SEG_FIRST_TS = (float(json.loads(first).get("ts") or 0.0)
                         if first else None)
    except (OSError, ValueError):
        _SEG_BYTES, _SEG_FIRST_TS = 0, None


def _roll_segment(d: str) -> None:
    global _SEG_PATH, _SEG_BYTES, _SEG_FIRST_TS
    _SEG_PATH = _next_segment_path(d)
    _SEG_BYTES = 0
    _SEG_FIRST_TS = None
    if _MAX_SEGMENTS > 0:
        segs = _segments(d)
        while len(segs) >= _MAX_SEGMENTS:
            victim = segs.pop(0)
            try:
                os.remove(victim)
            except OSError:
                break


def _append_batch(d: str, batch: List) -> None:
    """Write one drained batch of pre-serialized ``(bytes, ts)`` rows.
    Rotation decisions stay per-row — segments split exactly where a
    row-at-a-time writer would split them — but I/O stays per-run:
    each contiguous run of rows bound for the same segment costs ONE
    ``open`` + ``writelines``, so a burst normally pays a single
    syscall pair."""
    global _SEG_BYTES, _SEG_FIRST_TS
    run: List[bytes] = []

    def _flush() -> None:
        if run:
            with open(_SEG_PATH, "ab") as f:
                f.writelines(run)
            run.clear()

    if _SEG_PATH is None:
        _roll_segment(d)
    for data, ts_raw in batch:
        ts = float(ts_raw or 0.0)
        need_new = (_MAX_SEG_BYTES > 0 and _SEG_BYTES > 0
                    and _SEG_BYTES + len(data) > _MAX_SEG_BYTES)
        if (not need_new and _MAX_SEG_AGE_S > 0
                and _SEG_FIRST_TS is not None
                and ts - _SEG_FIRST_TS > _MAX_SEG_AGE_S):
            need_new = True
        if need_new:
            _flush()
            _roll_segment(d)
        run.append(data)
        _SEG_BYTES += len(data)
        if _SEG_FIRST_TS is None:
            _SEG_FIRST_TS = ts
    _flush()


def _writer_loop(q: _queue.Queue, d: str) -> None:
    batch: List = []  # pooled drain buffer — cleared, never realloced
    while True:
        item = q.get()  # blocking: one wakeup per burst, not per row
        stop_after = item is None
        if not stop_after:
            batch.append(item)
            while True:  # drain everything already waiting
                try:
                    nxt = q.get_nowait()
                except _queue.Empty:
                    break
                if nxt is None:
                    stop_after = True
                    break
                batch.append(nxt)
        if batch:
            t0 = time.perf_counter_ns()
            try:
                _append_batch(d, batch)
            except Exception:
                pass  # persistence failure never propagates anywhere hot
            dt = time.perf_counter_ns() - t0
            per_row = dt // len(batch)
            with _LOCK:
                for _ in batch:
                    HISTORY_WRITE_SECONDS.observe(per_row / 1e9)
                    _WRITE_NS.append(per_row)
            batch.clear()
        if stop_after:
            return


def stop() -> None:
    """Drain and join the writer thread (called on Service shutdown;
    idempotent)."""
    global _Q, _WRITER
    q, w = _Q, _WRITER
    _Q, _WRITER = None, None
    if q is not None:
        try:
            q.put_nowait(None)
        except _queue.Full:
            # make room for the sentinel: the victim row is lost but
            # accounted, and shutdown never hangs
            HISTORY_DROPPED.inc()
            try:
                q.get_nowait()
            except _queue.Empty:
                pass
            q.put(None)
    if w is not None and w.is_alive():
        w.join(timeout=5.0)


# ---------------------------------------------------------------------------
# read-side views
# ---------------------------------------------------------------------------

def _pctl(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def write_p99_us() -> float:
    """p99 of background row-append durations in microseconds (the
    bench's ``history_write_p99_us`` key)."""
    with _LOCK:
        ns = sorted(_WRITE_NS)
    return round(_pctl(ns, 0.99) / 1e3, 3)


def fleet_aggregates() -> Dict[str, Dict]:
    """Per-fingerprint fleet view (dashboard + doctor trend): count,
    outcome mix, latency percentiles, tenants, doctor-cause mix."""
    with _LOCK:
        snap = {fp: (a.count, dict(a.outcomes), list(a.exec_ms),
                     list(a.total_ms), dict(a.tenants), dict(a.causes),
                     a.last_ts)
                for fp, a in _AGGS.items()}
    out: Dict[str, Dict] = {}
    for fp, (count, outcomes, execs, totals, tenants, causes,
             last_ts) in snap.items():
        execs.sort()
        totals.sort()
        out[fp] = {
            "count": count,
            "outcomes": outcomes,
            "exec_p50_ms": round(_pctl(execs, 0.5), 3),
            "exec_p95_ms": round(_pctl(execs, 0.95), 3),
            "total_p50_ms": round(_pctl(totals, 0.5), 3),
            "total_p95_ms": round(_pctl(totals, 0.95), 3),
            "tenants": tenants,
            "doctor_causes": causes,
            "last_ts": last_ts,
        }
    return out


def recent_rows(n: int = 50) -> List[Dict]:
    with _LOCK:
        rows = list(_RECENT)
    return rows[-n:]


def segment_paths() -> List[str]:
    return _segments(_DIR) if _DIR else []


def stats_section() -> Dict:
    """The ``history`` section of ``Service.stats().snapshot()``."""
    with _LOCK:
        rows, dropped, overflow = _ROWS, _DROPPED, _FP_OVERFLOW
        fps = len(_AGGS)
        depth = _Q.qsize() if _Q is not None else 0
    return {
        "enabled": _ENABLED,
        "dir": _DIR,
        "rows": rows,
        "dropped": dropped,
        "queue_depth": depth,
        "fingerprints": fps,
        "fingerprint_overflow": overflow,
        "segments": len(segment_paths()),
        "write_p99_us": write_p99_us(),
    }


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def configure(conf) -> None:
    """Apply the ``spark.rapids.tpu.obs.history.*`` conf group (called
    by QueryService.__init__; last-configured service wins — the plane
    is process-wide like the rest of the registry).  Restarts the
    background writer against the configured directory."""
    global _ENABLED, _DIR, _MAX_SEG_BYTES, _MAX_SEG_AGE_S
    global _MAX_SEGMENTS, _QUEUE_DEPTH, _MAX_FPS, _Q, _WRITER
    from ..config import (OBS_HISTORY_DIR, OBS_HISTORY_ENABLED,
                          OBS_HISTORY_MAX_FINGERPRINTS,
                          OBS_HISTORY_MAX_SEGMENT_AGE_S,
                          OBS_HISTORY_MAX_SEGMENT_BYTES,
                          OBS_HISTORY_MAX_SEGMENTS,
                          OBS_HISTORY_QUEUE_DEPTH)
    stop()
    _ENABLED = bool(conf.get(OBS_HISTORY_ENABLED))
    _DIR = str(conf.get(OBS_HISTORY_DIR) or "").strip()
    _MAX_SEG_BYTES = int(conf.get(OBS_HISTORY_MAX_SEGMENT_BYTES))
    _MAX_SEG_AGE_S = int(conf.get(OBS_HISTORY_MAX_SEGMENT_AGE_S))
    _MAX_SEGMENTS = int(conf.get(OBS_HISTORY_MAX_SEGMENTS))
    _QUEUE_DEPTH = max(1, int(conf.get(OBS_HISTORY_QUEUE_DEPTH)))
    _MAX_FPS = max(1, int(conf.get(OBS_HISTORY_MAX_FINGERPRINTS)))
    if not (_ENABLED and _DIR):
        return
    os.makedirs(_DIR, exist_ok=True)
    _adopt_segment(_DIR)
    _Q = _queue.Queue(maxsize=_QUEUE_DEPTH)
    _WRITER = threading.Thread(target=_writer_loop, args=(_Q, _DIR),
                               name="tpu-history-writer", daemon=True)
    _WRITER.start()


def reset() -> None:
    """Test hook: drop all in-memory accounting (the on-disk segments
    and the configured writer survive)."""
    global _ROWS, _DROPPED, _FP_OVERFLOW
    with _LOCK:
        _ARTIFACTS.clear()
        _AGGS.clear()
        _RECENT.clear()
        _WRITE_NS.clear()
        _ROWS = _DROPPED = _FP_OVERFLOW = 0
