"""HBM memory observability plane — the sixth plane: who owns live
device memory, why every spill happened, what a query left behind, and
how much headroom the next admission would have.

The memory tier (memory/catalog.py + memory/arena.py, the
RapidsBufferCatalog role) was the one layer the trace/flight/stats/
perf/net planes could not see into: four coarse collect-time gauges
(live/peak/limit bytes, spill bytes by direction) with no record of
*who* owns live HBM, *why* a spill happened, or *what* a query failed
to release.  ROADMAP items 1 (HBM-resident ICI shuffle), 3
(admission-aware warmup) and 7 (device-resident streaming state) all
plan to keep far more state device-resident — none of them can be
built or debugged without this plane.  Four pillars:

- **allocation provenance** — every ``BufferCatalog.register()``
  stamps an owner (query_id from the active CancelToken, operator
  class, site: superstage/exchange/broadcast/scan_cache/stream_state/
  operator/other, plus a call-site tag).  The plane keeps an
  incremental per-site / per-owner live-byte decomposition maintained
  under the catalog lock, so it sums EXACTLY to ``device_bytes`` at
  every snapshot, and the high-water mark carries the owner set that
  was live at peak time.
- **spill ledger** — every tier move (device->host, host->disk,
  unspill) is one bounded ledger record: victim id, owner, nbytes,
  trigger reason (budget / pressure / explicit, a thread-local the
  requester sets via ``spill_reason()``), victim-selection rank, and
  the measured serialize/deserialize duration.  Feeds the
  ``tpu_mem_spill_seconds{direction}`` histograms and the
  ``mem_spill`` gap cause of the utilization timeline (the spill work
  windows are the evidence, like netplane's ``shuffle_host``).
- **retention / leak detection** — at a query's terminal state
  ``leak_check()`` diffs catalog entries owned by that query_id
  against the expected survivor set (scan cache, live shuffle
  materializations); leaks are reported with their registration tag
  into the event log and diag bundle.
- **headroom forecasting** — ``headroom()`` (limit − live − pinned,
  plus spillable-at-zero-refcount bytes) for ``Service.stats()``,
  Prometheus and the per-admission forecast the service logs.

Hot-path discipline (this file is on the SYNC001/OBS002 lint scope):
no numpy, no device pulls, no formatted flight-record args.  The
note_* paths are called by memory/catalog.py UNDER the catalog RLock
(once per register/unregister/tier move) and only mutate bounded
module state under the plane lock — the lock order is
``catalog._lock -> _LOCK``, never the reverse, so the catalog-scanning
views (``owners()``, ``headroom()``, ``leak_check()``) take the
catalog lock themselves and are only ever entered outside the plane
lock.  Host-side timestamps only: zero extra device flushes by
construction (asserted as an exact FLUSH_COUNT delta, tested).
"""
from __future__ import annotations

import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import flight
from . import overhead as _overhead
from .registry import MEM_LEAKED_TOTAL, MEM_SPILL_SECONDS, MEM_SPILL_SKIPPED

# provenance sites (interned: stamped on entries and ledger rows
# verbatim; registry.MEM_SITES mirrors this tuple for the gauges)
SITE_SUPERSTAGE = "superstage"
SITE_EXCHANGE = "exchange"
SITE_BROADCAST = "broadcast"
SITE_SCAN_CACHE = "scan_cache"
SITE_STREAM_STATE = "stream_state"
SITE_OPERATOR = "operator"
SITE_OTHER = "other"
SITES = (SITE_SUPERSTAGE, SITE_EXCHANGE, SITE_BROADCAST, SITE_SCAN_CACHE,
         SITE_STREAM_STATE, SITE_OPERATOR, SITE_OTHER)

# tier-move directions (ledger rows + tpu_mem_spill_seconds labels);
# ``unspill`` covers the whole read-back path (a disk hop included)
DIR_DEVICE_TO_HOST = "device_to_host"
DIR_HOST_TO_DISK = "host_to_disk"
DIR_UNSPILL = "unspill"
DIRECTIONS = (DIR_DEVICE_TO_HOST, DIR_HOST_TO_DISK, DIR_UNSPILL)

# trigger reasons: budget = arena reserve over device_limit, pressure =
# a real allocator RESOURCE_EXHAUSTED retry, explicit = demote()/direct
# spill_device_to_fit callers, pinned = nothing spillable remained
REASON_BUDGET = "budget"
REASON_PRESSURE = "pressure"
REASON_EXPLICIT = "explicit"
REASON_PINNED = "pinned"

#: flight-event name for a leak report (EV_MEM, a=bytes, b=entries)
N_LEAK = "leak"

_ENABLED = True
_MAX_LEDGER = 1 << 16     #: ledger + spill-window bound (obs.mem.maxLedger)
_LEDGER_VIEW_CAP = 100    #: ledger rows carried per query summary

_LOCK = threading.Lock()
_TLS = threading.local()

#: incremental live device-tier bytes by site / by owner tuple
#: (query_id, site, op) — maintained by the note_* calls under the
#: catalog lock, keys pruned at <= 0, so both stay bounded by the live
#: owner set and sum exactly to catalog.device_bytes at all times
_SITE_DEV: Dict[str, int] = {}
_OWNER_DEV: Dict[Tuple[Optional[str], str, str], int] = {}
_CUR_DEV_BYTES = 0

#: high-water mark + the owner decomposition live at peak time; ``seq``
#: advances on every new peak so per-query summaries can tell whether
#: THIS query moved it
_PEAK: Dict = {"bytes": 0, "seq": 0, "sites": {}, "owners": {}}

#: cumulative registrations by (site, op): [count, bytes] — the
#: parallelism-invariant provenance surface (what ran registers the
#: same batches regardless of interleaving)
_REG_TOTALS: Dict[Tuple[str, str], List[int]] = {}

#: the spill ledger: (ts_ns, direction, buffer_id, query_id, site, op,
#: nbytes, reason, rank, dur_ns).  Append-only, bounded.
_LEDGER: List[Tuple] = []
_LEDGER_DROPPED = 0

#: active tier-move work windows (start_ns, end_ns) — the timeline's
#: ``mem_spill`` gap evidence.  Append-only, bounded by _MAX_LEDGER.
_ACTIVE: List[Tuple[int, int]] = []
_ACTIVE_DROPPED = 0

#: cumulative per-direction totals (ns / bytes / moves)
_SPILL_NS = {d: 0 for d in DIRECTIONS}
_SPILL_BYTES = {d: 0 for d in DIRECTIONS}
_SPILL_COUNT = {d: 0 for d in DIRECTIONS}

_SKIPPED = 0        #: spill_device_to_fit calls short-returned (pinned)
_LEAKED_TOTAL = 0   #: leaked entries reported across all queries


def _catalog():
    from ..memory.catalog import BufferCatalog
    return BufferCatalog.get()


# ---------------------------------------------------------------------------
# trigger-reason context (thread-local: the spill requester names why)
# ---------------------------------------------------------------------------

class _ReasonCtx:
    __slots__ = ("reason", "prev")

    def __init__(self, reason: str):
        self.reason = reason
        self.prev = None

    def __enter__(self):
        self.prev = getattr(_TLS, "reason", None)
        _TLS.reason = self.reason
        return self

    def __exit__(self, *exc):
        _TLS.reason = self.prev
        return False


def spill_reason(reason: str) -> _ReasonCtx:
    """Scope a trigger reason for tier moves on this thread (the arena
    budget path wraps its spill_device_to_fit in ``budget``, the OOM
    retry in ``pressure``; everything else defaults to ``explicit``)."""
    return _ReasonCtx(reason)


def current_reason() -> str:
    return getattr(_TLS, "reason", None) or REASON_EXPLICIT


def call_tag() -> str:
    """Registration call-site tag (``file.py:lineno``): the nearest
    frame outside the memory/obs layers, stamped on the entry so a
    leak report names the code that created the buffer."""
    if not _ENABLED:
        return ""
    f = sys._getframe(1)
    depth = 0
    while f is not None and depth < 16:
        fn = f.f_code.co_filename
        if "/memory/" not in fn and "/obs/" not in fn:
            return "%s:%d" % (fn.rsplit("/", 1)[-1], f.f_lineno)
        f = f.f_back
        depth += 1
    return ""


# ---------------------------------------------------------------------------
# hot-path notes (called by memory/catalog.py under the catalog lock)
# ---------------------------------------------------------------------------

def _inc(key, site: str, nbytes: int) -> None:
    _SITE_DEV[site] = _SITE_DEV.get(site, 0) + nbytes
    _OWNER_DEV[key] = _OWNER_DEV.get(key, 0) + nbytes


def _dec(key, site: str, nbytes: int) -> None:
    v = _SITE_DEV.get(site, 0) - nbytes
    if v > 0:
        _SITE_DEV[site] = v
    else:
        _SITE_DEV.pop(site, None)
    w = _OWNER_DEV.get(key, 0) - nbytes
    if w > 0:
        _OWNER_DEV[key] = w
    else:
        _OWNER_DEV.pop(key, None)


def _peak_update(device_bytes: int) -> None:
    # _LOCK held: snapshot the owner decomposition live right now
    # (both dicts are bounded by distinct owners, not buffers)
    _PEAK["bytes"] = device_bytes
    _PEAK["seq"] += 1
    _PEAK["sites"] = dict(_SITE_DEV)
    _PEAK["owners"] = dict(_OWNER_DEV)


def _note_active(start_ns: int, end_ns: int) -> None:
    global _ACTIVE_DROPPED
    if end_ns <= start_ns:
        return
    if len(_ACTIVE) < _MAX_LEDGER:
        _ACTIVE.append((start_ns, end_ns))
    else:
        _ACTIVE_DROPPED += 1


def note_register(nbytes: int, query_id: Optional[str], site: str,
                  op: str, device_bytes: int) -> None:
    """One device-tier registration landed (catalog lock held);
    ``device_bytes`` is the catalog total after it."""
    global _CUR_DEV_BYTES
    if not _ENABLED:
        return
    _mt0 = _overhead.clock()
    key = (query_id, site, op)
    with _LOCK:
        _inc(key, site, nbytes)
        cell = _REG_TOTALS.get((site, op))
        if cell is None:
            cell = _REG_TOTALS[(site, op)] = [0, 0]
        cell[0] += 1
        cell[1] += nbytes
        _CUR_DEV_BYTES = device_bytes
        if device_bytes > _PEAK["bytes"]:
            _peak_update(device_bytes)
    _overhead.note(_overhead.P_MEM, _mt0)


def note_unregister(nbytes: int, query_id: Optional[str], site: str,
                    op: str, device_bytes: int) -> None:
    """One DEVICE-tier entry released (catalog lock held)."""
    global _CUR_DEV_BYTES
    if not _ENABLED:
        return
    _mt0 = _overhead.clock()
    with _LOCK:
        _dec((query_id, site, op), site, nbytes)
        _CUR_DEV_BYTES = device_bytes
    _overhead.note(_overhead.P_MEM, _mt0)


def note_spill(direction: str, buffer_id: str, query_id: Optional[str],
               site: str, op: str, nbytes: int, reason: str, rank: int,
               dur_ns: int, device_bytes: int) -> None:
    """One tier move finished now, having taken ``dur_ns`` (catalog
    lock held).  Appends the ledger record, keeps the live
    decomposition in step with ``device_bytes``, and opens a
    ``mem_spill`` timeline evidence window."""
    global _LEDGER_DROPPED, _CUR_DEV_BYTES
    if not _ENABLED:
        return
    now = time.perf_counter_ns()
    key = (query_id, site, op)
    with _LOCK:
        _SPILL_NS[direction] += dur_ns
        _SPILL_BYTES[direction] += nbytes
        _SPILL_COUNT[direction] += 1
        if direction == DIR_DEVICE_TO_HOST:
            _dec(key, site, nbytes)
        elif direction == DIR_UNSPILL:
            _inc(key, site, nbytes)
        _CUR_DEV_BYTES = device_bytes
        if direction == DIR_UNSPILL and device_bytes > _PEAK["bytes"]:
            _peak_update(device_bytes)
        if len(_LEDGER) < _MAX_LEDGER:
            _LEDGER.append((now, direction, buffer_id, query_id, site,
                            op, nbytes, reason, rank, dur_ns))
        else:
            _LEDGER_DROPPED += 1
    _note_active(now - dur_ns, now)
    MEM_SPILL_SECONDS.labels(direction=direction).observe(dur_ns / 1e9)
    # self-meter (obs/overhead.py): the now stamp doubles as meter start
    _overhead.note(_overhead.P_MEM, now)


def note_spill_skipped(reason: str, pinned_count: int,
                       pinned_bytes: int) -> None:
    """``spill_device_to_fit`` could not free the requested bytes —
    only pinned (refcount>0) entries remained.  Counted so OOM
    forensics can tell 'nothing spillable' from 'spill too slow'."""
    global _SKIPPED
    if not _ENABLED:
        return
    with _LOCK:
        _SKIPPED += 1
    MEM_SPILL_SKIPPED.labels(reason=reason).inc()
    flight.record(flight.EV_MEM, reason, pinned_bytes, pinned_count)


# ---------------------------------------------------------------------------
# catalog-scanning views (cold paths; take the catalog lock themselves,
# NEVER called under _LOCK or the catalog lock)
# ---------------------------------------------------------------------------

def _owner_rows(d: Dict) -> List[Dict]:
    rows = [{"query_id": q, "site": s, "op": o, "bytes": b}
            for (q, s, o), b in d.items() if b > 0]
    rows.sort(key=lambda r: (-r["bytes"], r["site"], r["op"],
                             str(r["query_id"])))
    return rows


def owners() -> Dict:
    """Exact live decomposition: device-tier catalog entries grouped by
    (query_id, site, op) under the catalog lock, so the owner bytes sum
    to ``device_bytes`` by construction."""
    cat = _catalog()
    agg: Dict[Tuple, List[int]] = {}
    with cat._lock:
        dev = cat.device_bytes
        for e in cat._entries.values():
            if int(e.tier) == 0:
                k = (e.owner_query, e.owner_site, e.owner_op)
                cell = agg.get(k)
                if cell is None:
                    cell = agg[k] = [0, 0]
                cell[0] += e.nbytes
                cell[1] += 1
    rows = [{"query_id": q, "site": s, "op": o, "bytes": c[0],
             "buffers": c[1]} for (q, s, o), c in agg.items()]
    rows.sort(key=lambda r: (-r["bytes"], r["site"], r["op"],
                             str(r["query_id"])))
    return {"device_bytes": dev, "owners": rows}


def headroom() -> Dict:
    """Admission headroom forecast: free device bytes plus what a
    synchronous spill could reclaim (refcount==0 device entries)."""
    cat = _catalog()
    pinned = 0
    spillable = 0
    with cat._lock:
        limit = cat.device_limit
        live = cat.device_bytes
        for e in cat._entries.values():
            if int(e.tier) == 0:
                if e.refcount > 0:
                    pinned += e.nbytes
                else:
                    spillable += e.nbytes
    free = max(limit - live, 0)
    return {"device_limit": limit, "device_bytes": live,
            "pinned_bytes": pinned, "spillable_bytes": spillable,
            "free_bytes": free, "headroom_bytes": free + spillable}


def leak_check(query_id: Optional[str], survivors=()) -> List[Dict]:
    """Catalog entries still owned by ``query_id`` at its terminal
    state, minus the expected survivor set (scan-cache registrations
    and buffer ids in ``survivors`` — live shuffle materializations).
    Each leak carries the registration call-site tag."""
    global _LEAKED_TOTAL
    if not _ENABLED or not query_id:
        return []
    cat = _catalog()
    surv = frozenset(survivors)
    leaks = []
    with cat._lock:
        for bid, e in cat._entries.items():
            if getattr(e, "owner_query", None) != query_id:
                continue
            if e.owner_site == SITE_SCAN_CACHE or bid in surv:
                continue
            leaks.append({"buffer_id": bid, "tier": int(e.tier),
                          "nbytes": e.nbytes, "site": e.owner_site,
                          "op": e.owner_op, "tag": e.owner_tag,
                          "refcount": e.refcount})
    if leaks:
        nbytes = 0
        for rec in leaks:
            nbytes += rec["nbytes"]
        with _LOCK:
            _LEAKED_TOTAL += len(leaks)
        MEM_LEAKED_TOTAL.inc(len(leaks))
        flight.record(flight.EV_MEM, N_LEAK, nbytes, len(leaks))
    return leaks


# ---------------------------------------------------------------------------
# collect-time accessors (registry gauge callbacks)
# ---------------------------------------------------------------------------

def live_site_bytes(site: str) -> int:
    return _SITE_DEV.get(site, 0)


def ledger_dropped() -> int:
    return _LEDGER_DROPPED


# ---------------------------------------------------------------------------
# timeline evidence (cold path, called from obs/timeline._summarize)
# ---------------------------------------------------------------------------

def active_segments(t0: int, t1: int) -> List[Tuple[int, int]]:
    """Tier-move work windows overlapping [t0, t1] — the timeline's
    ``mem_spill`` gap-cause evidence."""
    if not _ENABLED:
        return []
    return [(s, e) for s, e in _ACTIVE[:] if e > t0 and s < t1]


# ---------------------------------------------------------------------------
# per-query roll-up (cold paths)
# ---------------------------------------------------------------------------

def begin_query() -> Dict:
    """Value/length snapshot marker for a per-query summary."""
    with _LOCK:
        m: Dict = {"peak_seq": _PEAK["seq"], "dev_bytes": _CUR_DEV_BYTES,
                   "ledger_len": len(_LEDGER), "skipped": _SKIPPED,
                   "leaked": _LEAKED_TOTAL,
                   "reg_totals": {k: tuple(v)
                                  for k, v in _REG_TOTALS.items()}}
        for d in DIRECTIONS:
            m[d + "_ns"] = _SPILL_NS[d]
            m[d + "_bytes"] = _SPILL_BYTES[d]
            m[d + "_count"] = _SPILL_COUNT[d]
        return m


def _ledger_rows(raw: List[Tuple]) -> List[Dict]:
    return [{"direction": d, "buffer_id": b, "query_id": q, "site": s,
             "op": o, "nbytes": n, "reason": r, "rank": k,
             "ms": round(ns / 1e6, 3)}
            for _ts, d, b, q, s, o, n, r, k, ns in raw]


def query_summary(marker: Optional[Dict] = None) -> Dict:
    """Memory roll-up since a ``begin_query()`` marker: peak bytes with
    the owner set live at peak (when this window advanced the peak; the
    live bytes at the marker otherwise), per-direction spill totals,
    the ledger slice, and the parallelism-invariant registration
    decomposition by (site, op)."""
    m = marker or {}
    reg0 = m.get("reg_totals", {})
    with _LOCK:
        spill = {}
        for d in DIRECTIONS:
            spill[d] = {
                "count": _SPILL_COUNT[d] - m.get(d + "_count", 0),
                "bytes": _SPILL_BYTES[d] - m.get(d + "_bytes", 0),
                "ms": round((_SPILL_NS[d] - m.get(d + "_ns", 0)) / 1e6,
                            3),
            }
        advanced = _PEAK["seq"] > m.get("peak_seq", 0) or (
            marker is None and _PEAK["seq"] > 0)
        peak_bytes = _PEAK["bytes"] if advanced \
            else m.get("dev_bytes", _CUR_DEV_BYTES)
        peak_sites = dict(_PEAK["sites"]) if advanced else {}
        peak_owners = _owner_rows(_PEAK["owners"]) if advanced else []
        reg_rows = []
        reg_count = 0
        reg_bytes = 0
        for (site, op), cell in _REG_TOTALS.items():
            c0, b0 = reg0.get((site, op), (0, 0))
            dc, db = cell[0] - c0, cell[1] - b0
            if dc > 0:
                reg_rows.append({"site": site, "op": op, "count": dc,
                                 "bytes": db})
                reg_count += dc
                reg_bytes += db
        skipped = _SKIPPED - m.get("skipped", 0)
        leaked = _LEAKED_TOTAL - m.get("leaked", 0)
        lo = m.get("ledger_len", 0)
    reg_rows.sort(key=lambda r: (r["site"], r["op"]))
    rows = _ledger_rows(_LEDGER[lo:])
    spill_ms = spill[DIR_DEVICE_TO_HOST]["ms"] + \
        spill[DIR_HOST_TO_DISK]["ms"]
    return {
        "peak_device_bytes": int(peak_bytes),
        "peak_advanced": bool(advanced),
        "peak_by_site": peak_sites,
        "peak_owners": peak_owners,
        "spill": spill,
        "spill_ms": round(spill_ms, 3),
        "unspill_ms": spill[DIR_UNSPILL]["ms"],
        "unspill_count": spill[DIR_UNSPILL]["count"],
        "spill_skipped": skipped,
        "leaked_entries": leaked,
        "registered": {"count": reg_count, "bytes": reg_bytes,
                       "by_site": reg_rows},
        "ledger": rows[:_LEDGER_VIEW_CAP],
        "ledger_records": len(rows),
    }


def ledger(limit: int = 0) -> List[Dict]:
    """Process-lifetime ledger view (diag bundles), oldest first."""
    rows = _ledger_rows(_LEDGER[:])
    return rows[-limit:] if limit else rows


def stats_section() -> Dict:
    """The ``memory`` block of ``Service.stats()``."""
    with _LOCK:
        spill = {d: {"count": _SPILL_COUNT[d], "bytes": _SPILL_BYTES[d],
                     "ms": round(_SPILL_NS[d] / 1e6, 3)}
                 for d in DIRECTIONS}
        skipped = _SKIPPED
        leaked = _LEAKED_TOTAL
        records = len(_LEDGER)
        dropped = _LEDGER_DROPPED
        peak = {"bytes": _PEAK["bytes"], "by_site": dict(_PEAK["sites"])}
        sites = dict(_SITE_DEV)
    out = {
        "enabled": bool(_ENABLED),
        "live_by_site": sites,
        "peak": peak,
        "spill": spill,
        "spill_skipped": skipped,
        "leaked_total": leaked,
        "ledger_records": records,
        "ledger_dropped": dropped,
    }
    if _ENABLED:
        out["headroom"] = headroom()
        ow = owners()
        out["device_bytes"] = ow["device_bytes"]
        out["owners"] = ow["owners"][:10]
    return out


def is_enabled() -> bool:
    return _ENABLED


def configure(conf) -> None:
    """Apply the ``spark.rapids.tpu.obs.mem.*`` conf group."""
    global _ENABLED, _MAX_LEDGER
    from ..config import OBS_MEM_ENABLED, OBS_MEM_MAX_LEDGER
    _ENABLED = bool(conf.get(OBS_MEM_ENABLED))
    cap = int(conf.get(OBS_MEM_MAX_LEDGER))
    if cap > 0:
        _MAX_LEDGER = cap


def reset() -> None:
    """Test hook: drop the decomposition, peak, ledger and counters."""
    global _LEDGER_DROPPED, _ACTIVE_DROPPED, _SKIPPED, _LEAKED_TOTAL
    global _CUR_DEV_BYTES
    with _LOCK:
        _SITE_DEV.clear()
        _OWNER_DEV.clear()
        _REG_TOTALS.clear()
        for d in DIRECTIONS:
            _SPILL_NS[d] = 0
            _SPILL_BYTES[d] = 0
            _SPILL_COUNT[d] = 0
        _PEAK.update({"bytes": 0, "seq": 0, "sites": {}, "owners": {}})
        _LEDGER_DROPPED = 0
        _ACTIVE_DROPPED = 0
        _SKIPPED = 0
        _LEAKED_TOTAL = 0
        _CUR_DEV_BYTES = 0
    del _LEDGER[:]
    del _ACTIVE[:]
