"""Prometheus text-format exposition over the metrics registry.

Renders the 0.0.4 text format (`# HELP` / `# TYPE` + samples;
histograms as cumulative `_bucket{le=...}` series plus `_sum`/`_count`)
so a scrape of ``QueryService.metrics_text()`` — or the tiny stdlib
scrape handler started by ``serve_scrapes()`` — drops straight into a
Prometheus/Grafana stack.  Stdlib-only.
"""
from __future__ import annotations

import threading
from typing import Optional, Tuple

from .registry import COUNTER, GAUGE, HISTOGRAM, MetricsRegistry, \
    get_registry


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _label_str(labels: Tuple[Tuple[str, str], ...],
               extra: Optional[Tuple[str, str]] = None) -> str:
    items = list(labels)
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    return "{" + ",".join(
        f'{k}="{_escape_label(str(v))}"' for k, v in items) + "}"


def render_text(registry: Optional[MetricsRegistry] = None) -> str:
    """The whole registry in Prometheus text exposition format."""
    registry = registry or get_registry()
    lines = []
    for fam in registry.families():
        lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.type}")
        children = fam.children() if fam.label_names else \
            [fam._default()]
        for c in children:
            if fam.type in (COUNTER, GAUGE):
                lines.append(f"{fam.name}{_label_str(c.labels)} "
                             f"{_fmt_value(c.value)}")
            elif fam.type == HISTOGRAM:
                h = c.hist_snapshot()
                for le, cum in h["buckets"].items():
                    le_s = "+Inf" if le == "+Inf" else _fmt_value(le)
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_label_str(c.labels, ('le', le_s))} {cum}")
                lines.append(f"{fam.name}_sum{_label_str(c.labels)} "
                             f"{_fmt_value(h['sum'])}")
                lines.append(f"{fam.name}_count{_label_str(c.labels)} "
                             f"{h['count']}")
    return "\n".join(lines) + "\n"


def serve_scrapes(port: int = 0, host: str = "127.0.0.1",
                  registry: Optional[MetricsRegistry] = None):
    """Start a daemon-thread HTTP scrape endpoint serving ``/metrics``.

    Returns (server, bound_port); ``server.shutdown()`` stops it.
    ``port=0`` binds an ephemeral port (tests/CI)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    reg = registry or get_registry()

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.split("?")[0] not in ("/metrics", "/"):
                self.send_error(404)
                return
            body = render_text(reg).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):   # scrapes must not spam stderr
            pass

    server = ThreadingHTTPServer((host, port), _Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="tpu-metrics-scrape")
    t.start()
    return server, server.server_address[1]
