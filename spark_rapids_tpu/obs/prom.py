"""Prometheus text-format exposition over the metrics registry.

Renders the 0.0.4 text format (`# HELP` / `# TYPE` + samples;
histograms as cumulative `_bucket{le=...}` series plus `_sum`/`_count`)
so a scrape of ``QueryService.metrics_text()`` — or the tiny stdlib
scrape handler started by ``serve_scrapes()`` — drops straight into a
Prometheus/Grafana stack.  Stdlib-only.
"""
from __future__ import annotations

import threading
from typing import Optional, Tuple

from .registry import COUNTER, GAUGE, HISTOGRAM, MetricsRegistry, \
    get_registry


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _label_str(labels: Tuple[Tuple[str, str], ...],
               extra: Optional[Tuple[str, str]] = None) -> str:
    items = list(labels)
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    return "{" + ",".join(
        f'{k}="{_escape_label(str(v))}"' for k, v in items) + "}"


def render_text(registry: Optional[MetricsRegistry] = None) -> str:
    """The whole registry in Prometheus text exposition format."""
    registry = registry or get_registry()
    lines = []
    for fam in registry.families():
        lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.type}")
        children = fam.children() if fam.label_names else \
            [fam._default()]
        for c in children:
            if fam.type in (COUNTER, GAUGE):
                lines.append(f"{fam.name}{_label_str(c.labels)} "
                             f"{_fmt_value(c.value)}")
            elif fam.type == HISTOGRAM:
                h = c.hist_snapshot()
                for le, cum in h["buckets"].items():
                    le_s = "+Inf" if le == "+Inf" else _fmt_value(le)
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_label_str(c.labels, ('le', le_s))} {cum}")
                lines.append(f"{fam.name}_sum{_label_str(c.labels)} "
                             f"{_fmt_value(h['sum'])}")
                lines.append(f"{fam.name}_count{_label_str(c.labels)} "
                             f"{h['count']}")
    return "\n".join(lines) + "\n"


class ScrapeServerBusyError(OSError):
    """The requested scrape port is already bound by another process
    (raised instead of a bare EADDRINUSE traceback so operators see
    which conf to change)."""


def serve_scrapes(port: int = 0, host: str = "127.0.0.1",
                  registry: Optional[MetricsRegistry] = None,
                  dashboard: bool = True):
    """Start a daemon-thread HTTP endpoint serving ``/metrics`` (and,
    when the dashboard plane is up, ``/dashboard``).

    Returns (server, bound_port).  The server binds with
    ``SO_REUSEADDR`` so a restart can reclaim a port still in
    TIME_WAIT, and grows an explicit :meth:`stop` that shuts the
    accept loop down AND joins the serving thread — two back-to-back
    servers on one port work (``QueryService.shutdown()`` calls it).
    A port actively bound by another listener raises
    :class:`ScrapeServerBusyError` with the offending (host, port)
    instead of a raw traceback.  ``port=0`` binds an ephemeral port
    (tests/CI)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    reg = registry or get_registry()

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?")[0]
            if dashboard and path == "/dashboard":
                try:
                    from . import dashboard as _dash
                    body = _dash.render_html().encode()
                except Exception as e:
                    self.send_error(500, explain=str(e))
                    return
                ctype = "text/html; charset=utf-8"
            elif path in ("/metrics", "/"):
                body = render_text(reg).encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):   # scrapes must not spam stderr
            pass

    class _Server(ThreadingHTTPServer):
        # reclaim TIME_WAIT ports across service restarts; a port with
        # a LIVE listener still refuses the bind (see below)
        allow_reuse_address = True
        _thread: Optional[threading.Thread] = None

        def stop(self):
            """Shut down the accept loop, close the socket and JOIN
            the serving thread (idempotent)."""
            self.shutdown()
            self.server_close()
            t, self._thread = self._thread, None
            if t is not None and t.is_alive():
                t.join(timeout=5.0)

    try:
        server = _Server((host, port), _Handler)
    except OSError as e:
        raise ScrapeServerBusyError(
            f"metrics scrape port {host}:{port} is unavailable "
            f"({e.strerror or e}): another process is listening — "
            "stop it or change the metrics port") from e
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="tpu-metrics-scrape")
    server._thread = t
    t.start()
    return server, server.server_address[1]
