"""Always-on flight recorder — the airplane-black-box counterpart to
the opt-in span tracer (obs/trace.py).

Every engine thread owns a bounded ring of preallocated event slots and
records compact structured events (span begin/end, kernel entry, retry,
spill/unspill, semaphore acquire/release, shuffle fetch, admission
transitions) *unconditionally*: when a production query OOMs, deadlocks
on the semaphore, or blows its deadline with tracing disabled, the
recent past is still in memory and lands in the diagnostic bundle
(obs/diagnostics.py) without a repro.

Overhead contract (the reason this can stay always-on):

- **no allocation on the hot path** — slots are preallocated lists and
  ``record()`` only mutates them in place; event names must be
  constant/interned strings (lint rule OBS002 polices the kernels/ and
  ``exec/tpu_*`` call sites: no f-strings or dict literals);
- **no locking on the hot path** — each ring has exactly one writer
  (its owning thread); the registry lock is taken once per thread
  lifetime, at ring creation;
- **overwrite-oldest semantics** — a ring past capacity wraps, so the
  recorder holds the recent tail forever at fixed memory.

``snapshot()`` merges every thread's tail and time-orders it on the
shared ``time.perf_counter_ns`` clock.  Readers are lock-free with
respect to writers: a slot being overwritten concurrently can surface
one torn (mixed-field) event per ring per snapshot — acceptable for a
post-mortem artifact, and impossible once the writer thread is parked
(the watchdog/diagnostics case).

Stdlib-only; imported by the service, exec, memory, shuffle and
kernels layers.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..service.cancellation import current_token

# -- event kinds (interned constants: assigning them allocates nothing) ----
EV_BEGIN = "begin"            # span/operator region entered (a=depth hint)
EV_END = "end"                # span/operator region left
EV_KERNEL = "kernel"          # kernel entry (@traced fast path)
EV_KERNEL_END = "kernel_end"
EV_RETRY = "retry"            # query retry (name=reason, a=attempt)
EV_SPILL = "spill"            # tier move down (name=edge, a=bytes)
EV_UNSPILL = "unspill"        # tier move up (name=edge, a=bytes)
EV_SEM_ACQUIRE = "sem_acquire"  # device semaphore granted (a=waited_ns)
EV_SEM_RELEASE = "sem_release"  # device semaphore released (a=permits)
EV_SHUFFLE = "shuffle"        # shuffle fetch/transfer progress (a=bytes)
EV_STATE = "state"            # service admission transition (name=state)
EV_OOM = "oom"                # device allocation failure observed
EV_WATCHDOG = "watchdog"      # stall watchdog fired (name=query_id)
EV_PIPELINE = "pipeline"      # morsel-pipeline drain progress
EV_COMPILE = "compile"        # superstage compiler (name=event, a=size)
#                               (name=stage constant, a=partition/count,
#                                b=bytes or permille ratio)
EV_STATS = "stats"            # stats plane (name=site/kind; a,b = plain
#                               ints: flush item count + duration ms, or
#                               skew permille + distinct estimate)
EV_NET = "net"                # shuffle-transport plane (name=phase
#                               constant from obs/netplane.py; a=bytes,
#                               b=duration ms)
EV_COST = "cost"              # device-compute cost plane (name=program
#                               constant; a=bucket capacity, b=flops
#                               captured, truncated to int)
EV_MEM = "mem"                # memory plane (name=direction/reason
#                               constant from obs/memplane.py; a=bytes,
#                               b=duration ms or count)
EV_FAULT = "fault"            # injected fault marker (service/faults.py;
#                               name=fault kind, a=fault sequence,
#                               query_id=fault id)

#: module fast-path flag — read directly by ``record()``; the recorder
#: is ON by default (that is the point of a flight recorder).
_ENABLED = True

#: slots preallocated per new ring (confed via ``configure``; applies
#: to rings created after the change)
_CAPACITY = 512

_TLS = threading.local()
_REG_LOCK = threading.Lock()
_RINGS: Dict[int, "_Ring"] = {}


class _Ring:
    """One thread's bounded event ring: preallocated slots, single
    writer, overwrite-oldest."""

    __slots__ = ("ident", "name", "cap", "slots", "pos", "count")

    def __init__(self, ident: int, name: str, cap: int):
        self.ident = ident
        self.name = name
        self.cap = cap
        # slot layout: [ts_ns, kind, name, query_id, a, b]
        self.slots = [[0, "", "", None, 0, 0] for _ in range(cap)]
        self.pos = 0
        self.count = 0


def _ring() -> _Ring:
    """The calling thread's ring (created on first record)."""
    r = getattr(_TLS, "ring", None)
    if r is None:
        ident = threading.get_ident()
        r = _Ring(ident, threading.current_thread().name, _CAPACITY)
        with _REG_LOCK:
            # ident reuse after a thread dies replaces the dead ring:
            # its tail has been snapshot-able since the thread parked,
            # and keeping both would grow without bound
            _RINGS[ident] = r
        _TLS.ring = r
    return r


def record(kind: str, name: str = "", a: int = 0, b: int = 0,
           query_id: Optional[str] = None):
    """Record one event into the calling thread's ring.

    Hot-path contract: callers pass constant/interned ``kind``/``name``
    strings and plain ints — no formatting, no dict building (OBS002).
    ``query_id`` defaults to the active CancelToken's; pass it
    explicitly on threads outside a query context (submit path)."""
    if not _ENABLED:
        return
    r = getattr(_TLS, "ring", None)
    if r is None:
        r = _ring()
    if query_id is None:
        tok = current_token()
        if tok is not None:
            query_id = tok.query_id
    slot = r.slots[r.pos]
    slot[0] = time.perf_counter_ns()
    slot[1] = kind
    slot[2] = name
    slot[3] = query_id
    slot[4] = a
    slot[5] = b
    pos = r.pos + 1
    r.pos = 0 if pos == r.cap else pos
    r.count += 1


# ---------------------------------------------------------------------------
# snapshot / introspection (cold paths)
# ---------------------------------------------------------------------------

def _ring_tail(r: _Ring) -> List[Dict]:
    """The ring's buffered events, oldest first."""
    n = min(r.count, r.cap)
    if n == 0:
        return []
    pos = r.pos
    if r.count <= r.cap:
        ordered = r.slots[:n]
    else:
        ordered = r.slots[pos:] + r.slots[:pos]
    out = []
    for s in ordered:
        out.append({"ts_ns": s[0], "kind": s[1], "name": s[2],
                    "query_id": s[3], "a": s[4], "b": s[5],
                    "thread": r.name})
    return out


def snapshot(query_id: Optional[str] = None,
             last: Optional[int] = None) -> List[Dict]:
    """Merge every thread's tail, time-ordered on the shared
    perf_counter_ns clock.  ``query_id`` filters to one query's events
    (plus none-attributed events are dropped); ``last`` keeps only the
    most recent N after the merge."""
    with _REG_LOCK:
        rings = list(_RINGS.values())
    events: List[Dict] = []
    for r in rings:
        events.extend(_ring_tail(r))
    if query_id is not None:
        qid = str(query_id)
        events = [e for e in events
                  if e["query_id"] is not None
                  and str(e["query_id"]) == qid]
    events.sort(key=lambda e: e["ts_ns"])
    if last is not None and len(events) > last:
        events = events[-last:]
    return events


def thread_counts() -> Dict[int, int]:
    """{thread ident: total events recorded} — the watchdog's progress
    signal: a parked thread's count stops advancing."""
    with _REG_LOCK:
        return {ident: r.count for ident, r in _RINGS.items()}


def occupancy() -> Dict[str, int]:
    """Recorder occupancy for ``Service.stats()``/monitoring."""
    with _REG_LOCK:
        rings = list(_RINGS.values())
    return {
        "enabled": bool(_ENABLED),
        "threads": len(rings),
        "capacity_per_thread": _CAPACITY,
        "events_buffered": sum(min(r.count, r.cap) for r in rings),
        "events_recorded": sum(r.count for r in rings),
    }


def enable():
    global _ENABLED
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


def is_enabled() -> bool:
    return _ENABLED


def configure(conf) -> None:
    """Apply the ``spark.rapids.tpu.obs.flightRecorder.*`` conf group.
    A capacity change applies to rings created afterwards (existing
    rings keep their preallocated slots)."""
    global _ENABLED, _CAPACITY
    from ..config import OBS_FLIGHT_ENABLED, OBS_FLIGHT_CAPACITY
    _ENABLED = bool(conf.get(OBS_FLIGHT_ENABLED))
    cap = int(conf.get(OBS_FLIGHT_CAPACITY))
    if cap > 0:
        _CAPACITY = cap


def reset():
    """Drop every ring (tests).  Threads re-register on next record."""
    global _RINGS
    with _REG_LOCK:
        _RINGS = {}
    _TLS.__dict__.pop("ring", None)
