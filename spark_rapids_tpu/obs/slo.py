"""Per-tenant SLO latency plane — what end-to-end latency each tenant
actually experiences, and when it breaches, WHY.

The admission controller (service/server.py) already measures every
query's queue wait and execution wall separately (QueryMetrics); this
module folds those per-query figures into per-tenant accounting:

- latency histograms ``tpu_slo_latency_seconds{tenant,phase}`` with
  phase = total (queue + exec), queue, exec — admission wait stays
  separable from execution in Prometheus, matching the event-log
  split;
- bounded per-tenant reservoirs feeding nearest-rank p50/p95/p99 into
  ``Service.stats()`` (the "tenant p99" number the north star's
  serving story is judged by);
- breach/burn accounting against ``spark.rapids.tpu.obs.slo.targetMs``
  (0 = no target: histograms still record, breach counters stay
  silent): every breach is attributed to EXACTLY ONE cause —

  - ``shed``             — admission rejected the query outright
    (queue depth/bytes overload);
  - ``predicted_breach`` — the predictive scheduler
    (service/scheduler.py) shed the query at admission because its
    fingerprint's learned baseline predicted it would breach —
    rejected BEFORE burning device time, distinct from load shedding;
  - ``deadline``       — cancelled by its deadline;
  - ``inline_compile`` — the query finished late and its recorded
    inline-compile time covers the overshoot (the compile WAS the
    breach — the AOT cache roadmap item's target population);
  - ``slow_exec``      — finished late for any other reason.

  Shed and deadline-cancelled queries always count as breaches when a
  target is set: the tenant asked and did not get an answer in time.
  ``tpu_slo_burn_ms_total`` accumulates the overshoot magnitude —
  breaches say how often, burn says how badly.

Latency is derived purely from QueryMetrics fields the server already
stamped (this module never reads wall clocks — obs/ lint scope HYG002
bans ``time.time()`` and nothing here needs a clock).
"""
from __future__ import annotations

import threading
from typing import Dict, List

from .registry import SLO_BREACHES, SLO_BURN_MS, SLO_LATENCY_SECONDS

#: breach causes (exactly one per breach; docs/observability.md)
BREACH_CAUSES = ("shed", "predicted_breach", "deadline",
                 "inline_compile", "slow_exec")

_RESERVOIR_CAP = 1 << 14

_ENABLED = True
_TARGET_MS = 0.0
_LOCK = threading.Lock()


class _Tenant:
    """One tenant's bounded latency reservoirs + breach accounting."""

    __slots__ = ("total_ms", "queue_ms", "exec_ms", "count",
                 "breaches", "burn_ms", "causes")

    def __init__(self):
        self.total_ms: List[float] = []
        self.queue_ms: List[float] = []
        self.exec_ms: List[float] = []
        self.count = 0
        self.breaches = 0
        self.burn_ms = 0.0
        self.causes: Dict[str, int] = {}


_TENANTS: Dict[str, _Tenant] = {}


def record(m) -> None:
    """Fold one finished query's QueryMetrics into its tenant's
    accounting.  Called by the service at every terminal transition
    (completed, failed, shed, cancelled) — exactly once per query."""
    if not _ENABLED:
        return
    tenant = str(getattr(m, "tenant", None) or "default")
    queue = float(m.queue_wait_ms or 0.0)
    execd = float(m.execute_ms or 0.0)
    total = queue + execd
    SLO_LATENCY_SECONDS.labels(tenant=tenant,
                               phase="total").observe(total / 1e3)
    SLO_LATENCY_SECONDS.labels(tenant=tenant,
                               phase="queue").observe(queue / 1e3)
    SLO_LATENCY_SECONDS.labels(tenant=tenant,
                               phase="exec").observe(execd / 1e3)

    cause = None
    if _TARGET_MS > 0:
        if m.outcome == "shed" and "predicted_breach" in (m.error or ""):
            cause = "predicted_breach"
        elif m.outcome == "shed":
            cause = "shed"
        elif m.outcome == "cancelled" and "deadline" in (m.error or ""):
            cause = "deadline"
        elif total > _TARGET_MS:
            overshoot = total - _TARGET_MS
            inline = float(getattr(m, "inline_compile_ms", 0.0) or 0.0)
            cause = "inline_compile" if inline >= overshoot \
                else "slow_exec"

    with _LOCK:
        t = _TENANTS.get(tenant)
        if t is None:
            t = _TENANTS[tenant] = _Tenant()
        t.count += 1
        if len(t.total_ms) < _RESERVOIR_CAP:
            t.total_ms.append(total)
            t.queue_ms.append(queue)
            t.exec_ms.append(execd)
        if cause is not None:
            t.breaches += 1
            t.causes[cause] = t.causes.get(cause, 0) + 1
            burn = max(total - _TARGET_MS, 0.0)
            t.burn_ms += burn
    if cause is not None:
        SLO_BREACHES.labels(tenant=tenant, cause=cause).inc()
        SLO_BURN_MS.labels(tenant=tenant).inc(
            max(total - _TARGET_MS, 0.0))


def _pctl(sorted_ms: List[float], q: float) -> float:
    """Nearest-rank percentile over a pre-sorted ms sample."""
    if not sorted_ms:
        return 0.0
    i = min(len(sorted_ms) - 1, int(q * (len(sorted_ms) - 1) + 0.5))
    return sorted_ms[i]


def stats_section() -> Dict:
    """The ``slo`` section of ``Service.stats().snapshot()``."""
    with _LOCK:
        tenants = {name: (list(t.total_ms), list(t.queue_ms),
                          list(t.exec_ms), t.count, t.breaches,
                          t.burn_ms, dict(t.causes))
                   for name, t in _TENANTS.items()}
    out: Dict = {"target_ms": _TARGET_MS, "tenants": {}}
    for name in sorted(tenants):
        total, queue, execd, count, breaches, burn, causes = tenants[name]
        total.sort()
        queue.sort()
        execd.sort()
        out["tenants"][name] = {
            "count": count,
            "p50_ms": round(_pctl(total, 0.5), 3),
            "p95_ms": round(_pctl(total, 0.95), 3),
            "p99_ms": round(_pctl(total, 0.99), 3),
            "queue_p95_ms": round(_pctl(queue, 0.95), 3),
            "exec_p95_ms": round(_pctl(execd, 0.95), 3),
            "breaches": breaches,
            "burn_ms": round(burn, 3),
            "breach_causes": causes,
        }
    return out


def configure(conf) -> None:
    """Apply the ``spark.rapids.tpu.obs.slo.*`` conf group (called by
    QueryService.__init__; last-configured service wins — the plane is
    process-wide like the rest of the registry)."""
    global _ENABLED, _TARGET_MS
    from ..config import OBS_SLO_ENABLED, OBS_SLO_TARGET_MS
    _ENABLED = bool(conf.get(OBS_SLO_ENABLED))
    _TARGET_MS = float(conf.get(OBS_SLO_TARGET_MS))


def reset() -> None:
    """Test hook: drop all tenant accounting."""
    with _LOCK:
        _TENANTS.clear()
