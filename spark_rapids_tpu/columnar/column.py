"""Device column vectors — the TPU analogue of ``GpuColumnVector``.

Role parity: reference sql-plugin/src/main/java/com/nvidia/spark/rapids/
GpuColumnVector.java (cuDF-backed device vectors) and RapidsHostColumnVector.java.

TPU-first design:
- Every column is a set of dense JAX arrays padded to a *bucketed capacity*
  (power of two).  XLA requires static shapes, so kernels are compiled per
  (schema, capacity-bucket) and reused; the live row count travels as data.
- Validity is a separate bool array (Arrow-style), True = valid.
- Strings use Arrow offsets+bytes layout.  For key operations (sort/join/group)
  strings are packed into big-endian uint64 "key words" so ordering/equality is
  exact byte-wise UTF-8 order — which equals code-point order — using only
  integer ops the MXU/VPU likes.
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence, Union

import numpy as np
import jax
import jax.numpy as jnp

from . import dtypes as T

# Minimum capacity bucket; batches are padded up to powers of two so the
# jit-cache stays small (SURVEY.md §7 "compile-cache keyed by padded size").
MIN_CAPACITY = int(os.environ.get("SPARK_RAPIDS_TPU_MIN_CAPACITY", "1024"))


#: capacity-bucketing override installed by the AOT compile subsystem
#: (compile/aot.py configure): a lattice with a conf'd growth ratio.
#: None = the classic pow2 padding below.  A plain module slot (not an
#: import) so columnar never depends on compile/.
_BUCKET_FN = None


def set_bucket_fn(fn) -> None:
    global _BUCKET_FN
    _BUCKET_FN = fn


def bucket_capacity(n: int) -> int:
    fn = _BUCKET_FN
    if fn is not None:
        return fn(n)
    cap = MIN_CAPACITY
    while cap < n:
        cap *= 2
    return cap


def _pad_np(arr: np.ndarray, capacity: int, fill=0) -> np.ndarray:
    if arr.shape[0] == capacity:
        return arr
    out = np.full((capacity,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


class Column:
    """Fixed-width device column: data[capacity] + validity[capacity]."""

    def __init__(self, dtype: T.DType, data, validity):
        self.dtype = dtype
        self.data = data
        self.validity = validity

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    # -- constructors -----------------------------------------------------------
    @staticmethod
    def from_numpy(values, dtype: Optional[T.DType] = None,
                   capacity: Optional[int] = None) -> "Column":
        """Build from a numpy array or a Python list that may contain None."""
        if isinstance(values, (list, tuple)):
            validity = np.array([v is not None for v in values], dtype=np.bool_)
            if dtype is None:
                probe = [v for v in values if v is not None]
                if probe and isinstance(probe[0], (list, tuple)):
                    return ListColumn.from_pylist(list(values),
                                                  capacity=capacity)
                np_arr = np.array(probe if probe else [0])
                dtype = T.from_numpy_dtype(np_arr.dtype)
            if isinstance(dtype, T.ArrayType):
                return ListColumn.from_pylist(
                    list(values), element_type=dtype.element_type,
                    capacity=capacity)
            if dtype == T.STRING:
                return StringColumn.from_pylist(list(values), capacity=capacity)
            clean = [v if v is not None else dtype.default_value for v in values]
            arr = np.array(clean, dtype=dtype.np_dtype)
        else:
            arr = np.asarray(values)
            if dtype is None:
                dtype = T.from_numpy_dtype(arr.dtype)
            if dtype == T.STRING:
                return StringColumn.from_pylist(list(arr), capacity=capacity)
            if arr.dtype.kind == "M":
                arr = arr.astype("datetime64[us]").astype(np.int64)
            arr = arr.astype(dtype.np_dtype)
            validity = np.ones(arr.shape[0], dtype=np.bool_)
        n = arr.shape[0]
        cap = capacity or bucket_capacity(n)
        data = jnp.asarray(_pad_np(arr, cap))
        valid = jnp.asarray(_pad_np(validity, cap, fill=False))
        return Column(dtype, data, valid)

    @staticmethod
    def all_null(dtype: T.DType, capacity: int) -> "Column":
        if dtype == T.STRING:
            return StringColumn(
                jnp.zeros(capacity + 1, jnp.int32),
                jnp.zeros(MIN_CAPACITY, jnp.uint8),
                jnp.zeros(capacity, jnp.bool_))
        if isinstance(dtype, T.ArrayType):
            return ListColumn(
                dtype, jnp.zeros(capacity + 1, jnp.int32),
                Column.all_null(dtype.element_type, MIN_CAPACITY),
                jnp.zeros(capacity, jnp.bool_))
        if isinstance(dtype, T.StructType):
            return StructColumn(
                dtype, [Column.all_null(f.dtype, capacity)
                        for f in dtype.fields],
                jnp.zeros(capacity, jnp.bool_))
        if isinstance(dtype, T.MapType):
            est = MapColumn.entry_struct_type(dtype)
            return MapColumn(
                dtype, jnp.zeros(capacity + 1, jnp.int32),
                Column.all_null(est, MIN_CAPACITY),
                jnp.zeros(capacity, jnp.bool_))
        data = jnp.zeros(capacity, dtype=dtype.np_dtype)
        return Column(dtype, data, jnp.zeros(capacity, jnp.bool_))

    @staticmethod
    def from_scalar(value, dtype: T.DType, capacity: int,
                    num_rows: Optional[int] = None) -> "Column":
        n = capacity if num_rows is None else num_rows
        if dtype == T.STRING:
            # host-built buffer: needs the concrete count (may sync)
            from ..analysis import residency  # lazy: avoids import cycle
            with residency.declared_transfer(site="size_probe"):
                n = int(n)
            return StringColumn.from_pylist(
                [value] * n, capacity=capacity)
        if dtype == T.FLOAT64:
            from .binary64 import Binary64Column, exact_double_enabled
            if exact_double_enabled():
                return Binary64Column.from_scalar_value(value, capacity, n)
        if value is None:
            return Column.all_null(dtype, capacity)
        data = jnp.full((capacity,), value, dtype=dtype.np_dtype)
        valid = (jnp.arange(capacity) < n)
        return Column(dtype, data, valid)

    # -- host interop -----------------------------------------------------------
    # Device buffer names pulled to host via the one-flush pending pool
    # (columnar/pending.py); subclasses override.
    _HOST_ATTRS = ("data", "validity")

    def _host_children(self):
        return ()

    def stage_host(self):
        """Stage every device buffer (recursively) for the next fused
        device->host flush; to_numpy/to_pylist then read the staged copy."""
        from . import pending
        cache = self.__dict__.setdefault("_host_staged", {})
        for attr in self._HOST_ATTRS:
            if attr not in cache:
                cache[attr] = pending.stage(getattr(self, attr))
        for child in self._host_children():
            child.stage_host()

    def _hnp(self, attr: str) -> np.ndarray:
        """Host copy of a device buffer, via the fused pending pool."""
        from . import pending
        cache = self.__dict__.setdefault("_host_staged", {})
        st = cache.get(attr)
        if st is None:
            st = pending.stage(getattr(self, attr))
            cache[attr] = st
        return st.np

    def to_numpy(self, num_rows: int):
        """Return (values ndarray, validity ndarray) truncated to num_rows."""
        return (self._hnp("data")[:num_rows],
                self._hnp("validity")[:num_rows])

    def to_pylist(self, num_rows: int) -> List:
        vals, valid = self.to_numpy(num_rows)
        return [v.item() if ok else None for v, ok in zip(vals, valid)]

    # -- structural ops (host-driven, device-executed) --------------------------
    def with_capacity(self, capacity: int, num_rows: int) -> "Column":
        if capacity == self.capacity:
            return self
        if capacity > self.capacity:
            pad = capacity - self.capacity
            data = jnp.pad(self.data, (0, pad))
            valid = jnp.pad(self.validity, (0, pad))
        else:
            data = self.data[:capacity]
            valid = self.validity[:capacity] & (jnp.arange(capacity) < num_rows)
        return Column(self.dtype, data, valid)

    def gather(self, indices, live=None, unique=False) -> "Column":
        """Take rows by index (device gather). indices: int array [new_cap].

        ``live``/``unique`` are sizing hints for variable-width columns
        (kernels/strings.py gather_strings); fixed-width gathers ignore
        them."""
        valid = jnp.take(self.validity, indices, axis=0, mode="clip")
        if live is not None:
            valid = valid & live
        return Column(self.dtype, jnp.take(self.data, indices, axis=0,
                                           mode="clip"), valid)

    def mask_validity(self, keep_mask) -> "Column":
        return Column(self.dtype, self.data, self.validity & keep_mask)

    def nbytes(self) -> int:
        return self.data.nbytes + self.validity.nbytes

    def device_buffers(self):
        return [self.data, self.validity]


class StringColumn(Column):
    """Arrow-layout string column: offsets int32[cap+1], bytes uint8[byte_cap].

    Reference analogue: cuDF STRING columns used throughout stringFunctions.scala.
    """

    def __init__(self, offsets, data, validity, max_bytes=None):
        self.dtype = T.STRING
        self.offsets = offsets
        self.data = data  # uint8 byte buffer
        self.validity = validity
        # host-known upper bound on any row's byte length, when cheap
        # to carry (ingest, gather, slices).  None -> computed lazily
        # with ONE device sync and cached; without the bound every
        # key-word encoding syncs the offsets buffer to host
        # (kernels/strings.needed_key_words), which serialized string
        # comparisons behind all pending device work
        self.max_bytes = max_bytes

    @property
    def capacity(self) -> int:
        return int(self.validity.shape[0])

    @property
    def byte_capacity(self) -> int:
        return int(self.data.shape[0])

    @staticmethod
    def from_pylist(values: Sequence[Optional[str]],
                    capacity: Optional[int] = None) -> "StringColumn":
        n = len(values)
        cap = capacity or bucket_capacity(n)
        validity = np.zeros(cap, dtype=np.bool_)
        encoded: List[bytes] = []
        for i, v in enumerate(values):
            if v is None:
                encoded.append(b"")
            else:
                validity[i] = True
                encoded.append(str(v).encode("utf-8"))
        offsets = np.zeros(cap + 1, dtype=np.int32)
        lens = [len(e) for e in encoded]
        offsets[1: n + 1] = np.cumsum(lens)
        offsets[n + 1:] = offsets[n]
        total = int(offsets[n])
        byte_cap = bucket_capacity(max(total, 1))
        buf = np.zeros(byte_cap, dtype=np.uint8)
        if total:
            buf[:total] = np.frombuffer(b"".join(encoded), dtype=np.uint8)
        return StringColumn(jnp.asarray(offsets), jnp.asarray(buf),
                            jnp.asarray(validity),
                            max_bytes=int(max(lens)) if lens else 0)

    _HOST_ATTRS = ("offsets", "data", "validity")

    def to_numpy(self, num_rows: int):
        offs = self._hnp("offsets")
        buf = self._hnp("data").tobytes()
        valid = self._hnp("validity")[:num_rows]
        vals = np.empty(num_rows, dtype=object)
        for i in range(num_rows):
            vals[i] = buf[offs[i]:offs[i + 1]].decode("utf-8", "replace")
        return vals, valid

    def to_pylist(self, num_rows: int) -> List:
        vals, valid = self.to_numpy(num_rows)
        return [v if ok else None for v, ok in zip(vals, valid)]

    def with_capacity(self, capacity: int, num_rows: int) -> "StringColumn":
        if capacity == self.capacity:
            return self
        if capacity > self.capacity:
            pad = capacity - self.capacity
            offsets = jnp.pad(self.offsets, (0, pad), mode="edge")
            valid = jnp.pad(self.validity, (0, pad))
        else:
            offsets = self.offsets[:capacity + 1]
            valid = self.validity[:capacity] & (jnp.arange(capacity) < num_rows)
        return StringColumn(offsets, self.data, valid,
                            max_bytes=self.max_bytes)

    def gather(self, indices, live=None,
               unique=False) -> "StringColumn":
        # Gathers are LAZY: the result is a view (row indices into this
        # column) and byte materialization is deferred until something
        # reads .offsets/.data.  Chained gathers compose into one index
        # map, so a join expansion to fact capacity followed by an
        # aggregate's 1000x row reduction never materializes the
        # intermediate gigabytes (and never pays its sizing sync) —
        # the cuDF-style dictionary/gather-map trick.
        valid = jnp.take(self.validity, indices, axis=0, mode="clip")
        if live is not None:
            valid = valid & live
        src_idx = jnp.clip(indices, 0, self.capacity - 1) \
            .astype(jnp.int32)
        return GatheredStringColumn(self, src_idx, valid, unique=unique)

    def mask_validity(self, keep_mask) -> "StringColumn":
        return StringColumn(self.offsets, self.data,
                            self.validity & keep_mask,
                            max_bytes=self.max_bytes)

    def nbytes(self) -> int:
        return self.offsets.nbytes + self.data.nbytes + self.validity.nbytes

    @staticmethod
    def combined_max_bytes(cols):
        """Upper bound for a column combined from ``cols`` (concat /
        case-when select); None when any input bound is unknown."""
        mbs = [c.max_bytes for c in cols]
        return max(mbs) if mbs and all(m is not None for m in mbs) \
            else None

    def device_buffers(self):
        return [self.offsets, self.data, self.validity]


class GatheredStringColumn(StringColumn):
    """Lazy string gather: row indices into a source StringColumn.

    Produced by StringColumn.gather.  Byte materialization — the
    expensive part of a string gather (an O(out_bytes) device windowed
    copy PLUS a host sync to size it) — is deferred until .offsets or
    .data is read.  Sort/group/join key words come straight from the
    source column's words gathered by index (kernels/canon.value_words
    fast path), so select-expand-reduce pipelines only ever materialize
    their final small outputs.  Chained gathers compose index maps.
    """

    def __init__(self, src: "StringColumn", idx, validity, unique=False):
        # deliberately no super().__init__: offsets/data are properties
        self.dtype = T.STRING
        while type(src) is GatheredStringColumn:
            if src._mat is not None:
                src = src._mat
                continue
            idx = jnp.take(src.idx, idx, axis=0, mode="clip")
            # a composed map repeats source rows unless EVERY stage was
            # repeat-free
            unique = unique and src._unique
            src = src.src
        self.src = src
        self.idx = idx
        self.validity = validity
        self.max_bytes = src.max_bytes
        self._unique = unique
        self._mat: Optional[StringColumn] = None

    def _materialize(self) -> StringColumn:
        if self._mat is None:
            from ..kernels import strings as skern
            offs, buf, valid = skern.gather_strings(
                self.src.offsets, self.src.data, self.src.validity,
                self.idx, live=self.validity, unique=self._unique,
                max_bytes=self.max_bytes)
            self._mat = StringColumn(offs, buf, valid,
                                     max_bytes=self.max_bytes)
        return self._mat

    @property
    def offsets(self):
        return self._materialize().offsets

    @property
    def data(self):
        return self._materialize().data

    # gather() is inherited: StringColumn.gather already produces a
    # composed view via this class's constructor.

    def mask_validity(self, keep_mask) -> "StringColumn":
        out = GatheredStringColumn(self.src, self.idx,
                                   self.validity & keep_mask,
                                   unique=self._unique)
        out._mat = None if self._mat is None else \
            self._mat.mask_validity(keep_mask)
        return out

    def with_capacity(self, capacity: int,
                      num_rows: int) -> "StringColumn":
        if capacity == self.capacity:
            return self
        if capacity > self.capacity:
            pad = capacity - self.capacity
            idx = jnp.pad(self.idx, (0, pad))
            valid = jnp.pad(self.validity, (0, pad))
        else:
            idx = self.idx[:capacity]
            valid = self.validity[:capacity] & \
                (jnp.arange(capacity) < num_rows)
        return GatheredStringColumn(self.src, idx, valid,
                                    unique=self._unique)

    def nbytes(self) -> int:
        # a live view PINS its source buffers: memory accounting must
        # see them or spill/coalesce budgets undercount by the whole
        # source batch (several views over one source over-count — the
        # safe direction for pressure decisions)
        own = self.idx.nbytes + self.validity.nbytes
        if self._mat is not None:
            return own + self._mat.nbytes()
        return own + self.src.nbytes()

    def device_buffers(self):
        # spill/wire serialization needs real buffers in StringColumn
        # layout (a view pins its source; a spilled copy must not) —
        # the materialized validity already folds the view's in
        return self._materialize().device_buffers()


class ListColumn(Column):
    """Arrow-layout list column: offsets int32[cap+1] + element child column.

    Reference analogue: cuDF LIST columns used by collectionOperations.scala
    and GpuGenerateExec.  The child may itself be any Column (fixed-width,
    StringColumn, or a nested ListColumn) — gathers recurse.
    Offsets are absolute indices into the child and need not start at 0
    (slices stay zero-copy); the invariant is monotonicity plus
    edge-padding past the live row count.
    """

    def __init__(self, dtype: T.ArrayType, offsets, elements: Column,
                 validity):
        self.dtype = dtype
        self.offsets = offsets
        self.elements = elements
        self.validity = validity

    @property
    def capacity(self) -> int:
        return int(self.validity.shape[0])

    @staticmethod
    def from_pylist(values: Sequence, element_type: Optional[T.DType] = None,
                    capacity: Optional[int] = None) -> "ListColumn":
        n = len(values)
        cap = capacity or bucket_capacity(n)
        validity = np.zeros(cap, dtype=np.bool_)
        flat: List = []
        offsets = np.zeros(cap + 1, dtype=np.int32)
        for i, v in enumerate(values):
            if v is not None:
                validity[i] = True
                flat.extend(v)
            offsets[i + 1] = len(flat)
        offsets[n + 1:] = offsets[n]
        if element_type is None:
            probe = [x for x in flat if x is not None]
            if probe and isinstance(probe[0], str):
                element_type = T.STRING
            elif probe and isinstance(probe[0], (list, tuple)):
                raise ValueError("nested list needs explicit element_type")
            else:
                arr = np.array(probe if probe else [0])
                element_type = T.from_numpy_dtype(arr.dtype)
        elems = _column_from_pylist(flat, element_type)
        return ListColumn(T.ArrayType(element_type), jnp.asarray(offsets),
                          elems, jnp.asarray(validity))

    @property
    def element_capacity(self) -> int:
        return self.elements.capacity

    _HOST_ATTRS = ("offsets", "validity")

    def _host_children(self):
        return (self.elements,)

    def to_pylist(self, num_rows: int) -> List:
        offs = self._hnp("offsets")
        valid = self._hnp("validity")[:num_rows]
        n_elems = int(offs[num_rows]) if num_rows else 0
        elems = self.elements.to_pylist(n_elems) if n_elems else []
        out: List = []
        for i in range(num_rows):
            if not valid[i]:
                out.append(None)
            else:
                out.append(elems[offs[i]:offs[i + 1]])
        return out

    def to_numpy(self, num_rows: int):
        vals = np.empty(num_rows, dtype=object)
        lst = self.to_pylist(num_rows)
        for i, v in enumerate(lst):
            vals[i] = v
        return vals, self._hnp("validity")[:num_rows]

    def with_capacity(self, capacity: int, num_rows: int) -> "ListColumn":
        if capacity == self.capacity:
            return self
        if capacity > self.capacity:
            pad = capacity - self.capacity
            offsets = jnp.pad(self.offsets, (0, pad), mode="edge")
            valid = jnp.pad(self.validity, (0, pad))
        else:
            offsets = self.offsets[:capacity + 1]
            valid = self.validity[:capacity] & (jnp.arange(capacity) < num_rows)
        return ListColumn(self.dtype, offsets, self.elements, valid)

    def gather(self, indices, live=None, unique=False) -> "ListColumn":
        from ..kernels import lists as lkern
        from ..analysis import residency  # lazy: avoids import cycle
        new_offsets, gvalid, src_starts, total = lkern.gather_list_offsets(
            self.offsets, self.validity, indices)
        with residency.declared_transfer(site="size_probe"):
            elem_cap = bucket_capacity(max(1, int(total)))
        src_idx, live = lkern.element_gather_indices(
            new_offsets, src_starts, elem_cap)
        elems = self.elements.gather(src_idx).mask_validity(live)
        return ListColumn(self.dtype, new_offsets, elems, gvalid)

    def mask_validity(self, keep_mask) -> "ListColumn":
        return ListColumn(self.dtype, self.offsets, self.elements,
                          self.validity & keep_mask)

    def nbytes(self) -> int:
        return (self.offsets.nbytes + self.elements.nbytes() +
                self.validity.nbytes)

    def device_buffers(self):
        return [self.offsets, self.validity] + self.elements.device_buffers()


class StructColumn(Column):
    """Struct column: one child column per field + top-level validity.

    Reference analogue: cuDF STRUCT columns (complexTypeCreator.scala /
    complexTypeExtractors.scala).  All structural ops delegate to the
    children, so structs nest freely with lists/strings/maps.
    """

    def __init__(self, dtype: T.StructType, children: List[Column],
                 validity):
        self.dtype = dtype
        self.children = children
        self.validity = validity

    @property
    def capacity(self) -> int:
        return int(self.validity.shape[0])

    @staticmethod
    def from_pylist(values: Sequence, dtype: T.StructType,
                    capacity: Optional[int] = None) -> "StructColumn":
        n = len(values)
        cap = capacity or bucket_capacity(n)
        validity = np.zeros(cap, dtype=np.bool_)
        per_field: List[List] = [[] for _ in dtype.fields]
        for i, v in enumerate(values):
            if v is None:
                for lst in per_field:
                    lst.append(None)
            else:
                validity[i] = True
                if isinstance(v, dict):
                    for lst, f in zip(per_field, dtype.fields):
                        lst.append(v.get(f.name))
                else:
                    for lst, x in zip(per_field, v):
                        lst.append(x)
        kids = [_column_from_pylist(vals, f.dtype, cap)
                for vals, f in zip(per_field, dtype.fields)]
        return StructColumn(dtype, kids, jnp.asarray(validity))

    _HOST_ATTRS = ("validity",)

    def _host_children(self):
        return tuple(self.children)

    def to_pylist(self, num_rows: int) -> List:
        valid = self._hnp("validity")[:num_rows]
        kid_vals = [c.to_pylist(num_rows) for c in self.children]
        names = [f.name for f in self.dtype.fields]
        return [dict(zip(names, vals)) if ok else None
                for ok, *vals in zip(valid, *kid_vals)] if kid_vals else \
            [{} if ok else None for ok in valid]

    def to_numpy(self, num_rows: int):
        vals = np.empty(num_rows, dtype=object)
        for i, v in enumerate(self.to_pylist(num_rows)):
            vals[i] = v
        return vals, self._hnp("validity")[:num_rows]

    def with_capacity(self, capacity: int, num_rows: int) -> "StructColumn":
        if capacity == self.capacity:
            return self
        kids = [c.with_capacity(capacity, num_rows) for c in self.children]
        if capacity > self.capacity:
            valid = jnp.pad(self.validity, (0, capacity - self.capacity))
        else:
            valid = self.validity[:capacity] & (jnp.arange(capacity) < num_rows)
        return StructColumn(self.dtype, kids, valid)

    def gather(self, indices, live=None,
               unique=False) -> "StructColumn":
        return StructColumn(
            self.dtype,
            [c.gather(indices, live=live, unique=unique)
             for c in self.children],
            jnp.take(self.validity, indices, axis=0, mode="clip"))

    def mask_validity(self, keep_mask) -> "StructColumn":
        return StructColumn(self.dtype, self.children,
                            self.validity & keep_mask)

    def nbytes(self) -> int:
        return sum(c.nbytes() for c in self.children) + self.validity.nbytes

    def device_buffers(self):
        out = [self.validity]
        for c in self.children:
            out.extend(c.device_buffers())
        return out


class MapColumn(ListColumn):
    """Map column = list<struct<key, value>> (the Arrow model).

    Reference analogue: cuDF LIST<STRUCT> maps (GetMapValue in
    complexTypeExtractors.scala).  Inherits all gather/slice mechanics
    from ListColumn; ``elements`` is a two-field StructColumn.
    """

    def __init__(self, dtype: T.MapType, offsets, elements: StructColumn,
                 validity):
        self.dtype = dtype
        self.offsets = offsets
        self.elements = elements
        self.validity = validity

    @property
    def keys(self) -> Column:
        return self.elements.children[0]

    @property
    def values(self) -> Column:
        return self.elements.children[1]

    @staticmethod
    def entry_struct_type(dtype: T.MapType) -> T.StructType:
        return T.StructType([T.StructField("key", dtype.key_type, False),
                             T.StructField("value", dtype.value_type, True)])

    @staticmethod
    def from_pylist(values: Sequence, dtype: T.MapType,
                    capacity: Optional[int] = None) -> "MapColumn":
        n = len(values)
        cap = capacity or bucket_capacity(n)
        validity = np.zeros(cap, dtype=np.bool_)
        offsets = np.zeros(cap + 1, dtype=np.int32)
        entries: List = []
        for i, v in enumerate(values):
            if v is not None:
                validity[i] = True
                items = v.items() if isinstance(v, dict) else v
                entries.extend(tuple(kv) for kv in items)
            offsets[i + 1] = len(entries)
        offsets[n + 1:] = offsets[n]
        est = MapColumn.entry_struct_type(dtype)
        elems = StructColumn.from_pylist(entries, est)
        return MapColumn(dtype, jnp.asarray(offsets), elems,
                         jnp.asarray(validity))

    def to_pylist(self, num_rows: int) -> List:
        offs = self._hnp("offsets")
        valid = self._hnp("validity")[:num_rows]
        n_elems = int(offs[num_rows]) if num_rows else 0
        keys = self.keys.to_pylist(n_elems) if n_elems else []
        vals = self.values.to_pylist(n_elems) if n_elems else []
        out: List = []
        for i in range(num_rows):
            if not valid[i]:
                out.append(None)
            else:
                out.append(dict(zip(keys[offs[i]:offs[i + 1]],
                                    vals[offs[i]:offs[i + 1]])))
        return out

    def with_capacity(self, capacity: int, num_rows: int) -> "MapColumn":
        lc = ListColumn.with_capacity(self, capacity, num_rows)
        return MapColumn(self.dtype, lc.offsets, lc.elements, lc.validity)

    def gather(self, indices, live=None, unique=False) -> "MapColumn":
        lc = ListColumn.gather(self, indices)
        return MapColumn(self.dtype, lc.offsets, lc.elements, lc.validity)

    def mask_validity(self, keep_mask) -> "MapColumn":
        return MapColumn(self.dtype, self.offsets, self.elements,
                         self.validity & keep_mask)

    def as_list(self) -> ListColumn:
        """View as list<struct<key,value>> (for MapKeys/MapValues/Size)."""
        return ListColumn(T.ArrayType(self.elements.dtype), self.offsets,
                          self.elements, self.validity)


def _column_from_pylist(values: Sequence, dtype: T.DType,
                        capacity: Optional[int] = None) -> Column:
    """Build any column type from a python list (host staging path)."""
    if isinstance(dtype, T.StructType):
        return StructColumn.from_pylist(values, dtype, capacity)
    if isinstance(dtype, T.MapType):
        return MapColumn.from_pylist(values, dtype, capacity)
    if isinstance(dtype, T.ArrayType):
        return ListColumn.from_pylist(values, dtype.element_type, capacity)
    if dtype == T.STRING:
        return StringColumn.from_pylist(values, capacity)
    return Column.from_numpy(list(values), dtype=dtype, capacity=capacity)


ColumnLike = Union[Column, StringColumn, ListColumn, StructColumn,
                   MapColumn]
