"""One-flush host-transfer pool.

On the TPU backends this framework targets, every device->host pull is a
remote-execution round trip with a large fixed latency (measured ~65-100 ms
on the tunnelled single-chip backend) plus low bandwidth, and device work
is dispatched lazily — nothing executes until a pull forces it.  The
engine therefore NEVER pulls values one at a time: every host-visible
value (row counts, shuffle bin counts, output column buffers, speculative
fit flags) is *staged* here, and the first forced value flushes the whole
pool as at most TWO fused transfers (a uint32 stream and, when doubles
are present, a float64 stream).

Encoding notes (the chip cannot bitcast 64-bit types — the XLA x64
rewriter refuses; canon.py:55 has the same constraint):
- bool/int8/uint8        -> bytes packed 4-per-u32 word (host unpacks by view)
- 16/32-bit fixed width  -> uint32 stream (16-bit widened via astype)
- int64/uint64           -> two uint32 words by shift/mask (exact)
- float64                -> its own float64 stream, pulled directly (the
  backend transfers f64 at full precision; only bitcasts are unsupported)
A one-time roundtrip self-check guards the encodings and falls back to
per-array pulls on any mismatch.

Reference analogue: the role of cuDF's stream-ordered D2H copies batched
at batch boundaries (GpuColumnVector / ColumnarToRow), redesigned for a
high-latency remote device.
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

# Staged items: weakrefs so abandoned handles are never transferred.
# The pool is process-wide and queries run concurrently under the query
# service, so stage/flush swaps are serialized by _POOL_LOCK (a lost
# append would leave a Staged unresolvable).
_POOL: List["weakref.ref"] = []
_POOL_LOCK = threading.Lock()


def _residency():
    # lazy: analysis/__init__ pulls exec.base, which would cycle back
    # through the columnar package at import time
    from ..analysis import residency
    return residency


class Staged:
    """Handle for one staged device array; resolves at the next flush."""

    __slots__ = ("dev", "_np_dtype", "_shape", "_val", "__weakref__")

    def __init__(self, dev):
        self.dev = dev
        self._np_dtype = np.dtype(dev.dtype)
        self._shape = tuple(dev.shape)
        self._val: Optional[np.ndarray] = None
        with _POOL_LOCK:
            _POOL.append(weakref.ref(self))

    @property
    def resolved(self) -> bool:
        return self._val is not None

    @property
    def np(self) -> np.ndarray:
        if self._val is None:
            flush()
        if self._val is None and self.dev is not None:
            # a concurrent flush captured this item but has not decoded
            # it yet: pull directly (same value; the duplicate transfer
            # only happens on this narrow race)
            with _residency().declared_transfer(site="pending_race"):
                self._val = np.asarray(self.dev)
        return self._val

    def _count(self) -> int:
        return int(np.prod(self._shape)) if self._shape else 1


def stage(dev) -> Staged:
    """Stage a device array for the next fused pull."""
    if not hasattr(dev, "dtype"):
        dev = jnp.asarray(dev)
    return Staged(dev)


# Encoders are jitted (cached per input shape): on the remote backend an
# EAGER jnp op costs ~7ms of client overhead while a jit dispatch is ~free
# (measured 200 chained jit calls enqueue in 2ms), so per-item encode work
# must never run eagerly.

@jax.jit
def _enc_bytes(x):
    """u8-ish[n] -> u32[ceil(n/4)] little-endian (host unpacks via .view)."""
    x = jnp.ravel(x)
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    elif x.dtype != jnp.uint8:
        x = lax.bitcast_convert_type(x, jnp.uint8)
    n = int(x.shape[0])
    pad = (-n) % 4
    if pad:
        x = jnp.concatenate([x, jnp.zeros(pad, jnp.uint8)])
    w = x.astype(jnp.uint32).reshape(-1, 4)
    return (w[:, 0] | (w[:, 1] << 8) | (w[:, 2] << 16) | (w[:, 3] << 24))


@jax.jit
def _enc_wide16(x):
    x = jnp.ravel(x)
    return (x.astype(jnp.int32).view(jnp.uint32)
            if np.dtype(x.dtype).kind == "i" else x.astype(jnp.uint32))


@jax.jit
def _enc_u32(x):
    return lax.bitcast_convert_type(jnp.ravel(x), jnp.uint32)


@jax.jit
def _enc_split64(x):
    # 64-bit ints: exact shift/mask split (the chip rejects 64-bit
    # bitcasts; masking the arithmetic-shifted high word is exact)
    x = jnp.ravel(x)
    mask = x.dtype.type(0xFFFFFFFF)
    lo = (x & mask).astype(jnp.uint32)
    hi = ((x >> x.dtype.type(32)) & mask).astype(jnp.uint32)
    return lo, hi


@jax.jit
def _enc_f64(x):
    return jnp.ravel(x)


def _encode(x) -> Tuple[str, list]:
    """Device array -> (layout, [u32 parts] or [f64 parts])."""
    dt = np.dtype(x.dtype)
    if dt == np.bool_ or dt.itemsize == 1:
        return "u8", [_enc_bytes(x)]
    if dt.itemsize == 2:
        return "u32", [_enc_wide16(x)]
    if dt.itemsize == 4:
        return "u32", [_enc_u32(x)]
    if dt.kind in "iu":
        return "split64", list(_enc_split64(x))
    assert dt == np.float64, f"unsupported staged dtype {dt}"
    return "f64", [_enc_f64(x)]


def _decode(layout: str, np_dtype, shape, parts: List[np.ndarray]):
    count = int(np.prod(shape)) if shape else 1
    if layout == "u8":
        raw = np.ascontiguousarray(parts[0]).view(np.uint8)[:count]
        if np_dtype == np.bool_:
            return (raw != 0).reshape(shape)
        return raw.view(np_dtype).reshape(shape)
    if layout == "u32":
        raw = parts[0]
        if np_dtype.itemsize == 2:
            kind = "i4" if np_dtype.kind == "i" else "u4"
            return raw.view(kind).astype(np_dtype).reshape(shape)
        return np.ascontiguousarray(raw).view(np_dtype).reshape(shape)
    if layout == "split64":
        lo, hi = parts
        u = lo.astype(np.uint64) | (hi.astype(np.uint64) << 32)
        return u.view(np_dtype).reshape(shape)
    assert layout == "f64", layout
    return np.asarray(parts[0], np.float64).reshape(shape)


# None = unverified; True = fused encoding verified; False = fall back to
# per-item pulls (safety net if a backend breaks an encoding assumption).
_ENCODING_OK: Optional[bool] = None


def _check_encoding() -> bool:
    global _ENCODING_OK
    if _ENCODING_OK is None:
        try:
            probe64 = np.array([0, 1, -1, 2**63 - 1, -2**63, 123456789012345],
                               np.int64)
            probef = np.array([0.0, -0.0, 1.5, -1e30, 1e-30,
                               3.141592653589793, np.inf, np.nan], np.float64)
            ok = True
            with _residency().declared_transfer(site="pending_probe"):
                for arr in (probe64, probef, np.array([True, False]),
                            np.arange(5, dtype=np.int32)):
                    dev = jnp.asarray(arr)
                    # reference = what the DEVICE itself round-trips
                    # (on-chip f64 is an f32 double-double — values a
                    # plain pull can't recover aren't the encoder's job
                    # to recover either)
                    want = np.asarray(dev)
                    layout, parts = _encode(dev)
                    host = [np.asarray(p) for p in parts]
                    back = _decode(layout, np.dtype(arr.dtype), arr.shape,
                                   host)
                    same = bool(np.all((back == want) |
                                       (pd_isnan(back) & pd_isnan(want))))
                    ok = ok and same
            _ENCODING_OK = ok
        except Exception:  # noqa: BLE001 — any backend quirk: safe path
            _ENCODING_OK = False
    return _ENCODING_OK


def pd_isnan(a: np.ndarray) -> np.ndarray:
    if a.dtype.kind == "f":
        return np.isnan(a)
    return np.zeros(a.shape, bool)


# observability: device round trips this process (each non-empty flush
# forces all queued device work — the per-query flush count is THE cost
# model on remote-dispatch backends; see docs/perf.md)
FLUSH_COUNT = 0

#: stats-plane hook (obs/profile.py): called as ``observer(dur_ns,
#: n_items)`` after each non-empty flush completes.  Module attribute,
#: not a registry: this is the hottest host path in the engine and one
#: global load + None-check is all it may cost when unset.
_FLUSH_OBSERVER = None


def flush():
    """Pull every staged array in at most two fused transfers."""
    global _POOL, FLUSH_COUNT
    with _POOL_LOCK:
        pool, _POOL = _POOL, []
    items: List[Staged] = []
    for w in pool:
        it = w()
        if it is not None and it._val is None:
            items.append(it)
    if not items:
        return
    FLUSH_COUNT += 1
    obs = _FLUSH_OBSERVER
    if obs is None:
        return _flush_items(items)
    t0 = time.perf_counter_ns()
    try:
        return _flush_items(items)
    finally:
        try:
            obs(time.perf_counter_ns() - t0, len(items))
        except Exception:  # noqa: BLE001 — observers never break a flush
            pass


def _flush_items(items: List[Staged]):
    # ONE declared region per flush event: the declared-transfer count
    # for this site tracks FLUSH_COUNT one-to-one, whatever the fused
    # transfer decomposes into
    with _residency().declared_transfer(site="pending_flush"):
        if len(items) == 1 or not _check_encoding():
            for it in items:
                it._val = np.asarray(it.dev)
                it.dev = None
            return
        encoded = []
        streams = {"u32": [], "f64": []}
        for it in items:
            layout, parts = _encode(it.dev)
            stream = streams["f64" if layout == "f64" else "u32"]
            idx = []
            for p in parts:
                idx.append((len(stream), int(p.shape[0])))
                stream.append(p)
            encoded.append((it, layout, idx))
        flats, offs = {}, {}
        for name, parts in streams.items():
            if parts:
                flats[name] = np.asarray(jnp.concatenate(parts)
                                         if len(parts) > 1 else parts[0])
                o, lst = 0, []
                for p in parts:
                    lst.append(o)
                    o += int(p.shape[0])
                offs[name] = lst
        for it, layout, idx in encoded:
            name = "f64" if layout == "f64" else "u32"
            flat, off = flats[name], offs[name]
            parts = [flat[off[i]:off[i] + n] for i, n in idx]
            it._val = _decode(layout, it._np_dtype, it._shape, parts)
            it.dev = None
        return


def pool_size() -> int:
    with _POOL_LOCK:
        pool = list(_POOL)
    return sum(1 for w in pool if w() is not None and not w().resolved)
