"""Schema: ordered named, typed, nullable fields.

Reference analogue: Spark ``StructType`` as consumed by the plugin's type
checks (TypeChecks.scala) and batch builders (GpuColumnVector.from(...)).
"""
from __future__ import annotations

import dataclasses
from typing import Iterable, List, Tuple

from . import dtypes as T


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    dtype: T.DType
    nullable: bool = True

    def __repr__(self):
        return f"{self.name}:{self.dtype.name}{'' if self.nullable else ' not null'}"


class Schema:
    def __init__(self, fields: Iterable[Field]):
        self.fields: Tuple[Field, ...] = tuple(fields)
        self._index = {f.name: i for i, f in enumerate(self.fields)}

    @staticmethod
    def of(*pairs) -> "Schema":
        """Schema.of(("a", T.INT64), ("b", T.STRING, False))"""
        fields = []
        for p in pairs:
            if len(p) == 2:
                fields.append(Field(p[0], p[1]))
            else:
                fields.append(Field(p[0], p[1], p[2]))
        return Schema(fields)

    @staticmethod
    def from_ddl(ddl: str) -> "Schema":
        """Parse a Spark-style DDL schema string: "a long, b double"."""
        fields = []
        for part in T._split_top(ddl):
            part = part.strip()
            name, tname = part.split(None, 1)
            fields.append(Field(name, T.dtype_from_name(tname.strip())))
        return Schema(fields)

    def __len__(self):
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __getitem__(self, key):
        if isinstance(key, str):
            return self.fields[self._index[key]]
        return self.fields[key]

    def __eq__(self, other):
        return isinstance(other, Schema) and self.fields == other.fields

    def __hash__(self):
        return hash(self.fields)

    def index_of(self, name: str) -> int:
        return self._index[name]

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    @property
    def dtypes(self) -> List[T.DType]:
        return [f.dtype for f in self.fields]

    def __repr__(self):
        return "Schema(" + ", ".join(repr(f) for f in self.fields) + ")"
