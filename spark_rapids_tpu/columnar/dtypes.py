"""Data type system for TPU columnar batches.

Role parity: the reference's type universe is Spark's ``DataType`` filtered through
``TypeSig`` (reference: sql-plugin/.../TypeChecks.scala:367).  Here we define the
engine-native dtype lattice directly: every dtype knows its device representation
(a JAX dtype for the data buffer) plus any auxiliary buffers (validity, string
offsets).  Nulls are carried in a separate validity mask, Arrow-style, matching the
reference's cuDF column layout (reference: sql-plugin/src/main/java/com/nvidia/
spark/rapids/GpuColumnVector.java).

TPU-first notes:
- Integer/float/bool columns map 1:1 onto dense device buffers.
- Decimal is DECIMAL64: unscaled int64 + (precision, scale) metadata, exactly the
  reference's supported subset (reference: GpuOverrides.scala:659).
- Strings are kept as UTF-8 bytes + int32 offsets (Arrow layout) with an optional
  dictionary encoding; byte-level kernels operate on the int buffers since XLA has
  no string type.
- Date is days-since-epoch int32; timestamp is microseconds-since-epoch int64
  (Spark semantics).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import numpy as np


class DType:
    """Base class for engine dtypes. Instances are lightweight and hashable."""

    #: short name used in schema strings and TypeSig docs
    name: str = "invalid"
    #: numpy dtype of the primary device buffer (None for nested types)
    np_dtype: Optional[np.dtype] = None

    def __repr__(self) -> str:
        return self.name

    def __eq__(self, other: Any) -> bool:
        return type(self) is type(other) and self.__dict__ == other.__dict__

    def __hash__(self) -> int:
        return hash((type(self).__name__, tuple(sorted(self.__dict__.items()))))

    # -- classification helpers -------------------------------------------------
    @property
    def is_numeric(self) -> bool:
        return isinstance(self, (IntegralType, FractionalType, DecimalType))

    @property
    def is_integral(self) -> bool:
        return isinstance(self, IntegralType)

    @property
    def is_fractional(self) -> bool:
        return isinstance(self, FractionalType)

    @property
    def is_nested(self) -> bool:
        return isinstance(self, (ArrayType, StructType, MapType))

    @property
    def default_value(self):
        """Value used to fill padding/null slots in dense buffers."""
        if self.np_dtype is None:
            return None
        return np.zeros((), dtype=self.np_dtype)[()]


class NumericType(DType):
    pass


class IntegralType(NumericType):
    pass


class FractionalType(NumericType):
    pass


class BooleanType(DType):
    name = "boolean"
    np_dtype = np.dtype(np.bool_)


class ByteType(IntegralType):
    name = "tinyint"
    np_dtype = np.dtype(np.int8)


class ShortType(IntegralType):
    name = "smallint"
    np_dtype = np.dtype(np.int16)


class IntegerType(IntegralType):
    name = "int"
    np_dtype = np.dtype(np.int32)


class LongType(IntegralType):
    name = "bigint"
    np_dtype = np.dtype(np.int64)


class FloatType(FractionalType):
    name = "float"
    np_dtype = np.dtype(np.float32)


class DoubleType(FractionalType):
    name = "double"
    np_dtype = np.dtype(np.float64)


class StringType(DType):
    """UTF-8 string; device layout is offsets int32[n+1] + bytes uint8[total]."""

    name = "string"
    np_dtype = None  # variable width; see StringColumn


class DateType(DType):
    """Days since unix epoch, int32 (Spark DateType semantics)."""

    name = "date"
    np_dtype = np.dtype(np.int32)


class TimestampType(DType):
    """Microseconds since unix epoch UTC, int64 (Spark TimestampType)."""

    name = "timestamp"
    np_dtype = np.dtype(np.int64)


class NullType(DType):
    name = "null"
    np_dtype = np.dtype(np.bool_)  # all-null placeholder buffer


@dataclasses.dataclass(frozen=True, eq=True, repr=False)
class DecimalType(NumericType):
    """Fixed-point decimal backed by an unscaled int64 (DECIMAL64 subset only,

    matching the reference's precision<=18 gate, GpuOverrides.scala:659)."""

    precision: int = 10
    scale: int = 0
    MAX_PRECISION = 18

    def __post_init__(self):
        if self.precision > self.MAX_PRECISION:
            raise ValueError(
                f"DecimalType precision {self.precision} exceeds DECIMAL64 max "
                f"{self.MAX_PRECISION}")

    @property
    def name(self) -> str:  # type: ignore[override]
        return f"decimal({self.precision},{self.scale})"

    np_dtype = np.dtype(np.int64)

    def __hash__(self):
        return hash(("DecimalType", self.precision, self.scale))


@dataclasses.dataclass(frozen=True, eq=True, repr=False)
class ArrayType(DType):
    element_type: DType = dataclasses.field(default_factory=IntegerType)
    contains_null: bool = True

    @property
    def name(self) -> str:  # type: ignore[override]
        return f"array<{self.element_type.name}>"

    np_dtype = None

    def __hash__(self):
        return hash(("ArrayType", self.element_type, self.contains_null))


@dataclasses.dataclass(frozen=True, eq=True, repr=False)
class StructField:
    name: str
    dtype: DType
    nullable: bool = True

    def __hash__(self):
        return hash((self.name, self.dtype, self.nullable))


@dataclasses.dataclass(frozen=True, eq=True, repr=False)
class StructType(DType):
    fields: Tuple[StructField, ...] = ()

    @property
    def name(self) -> str:  # type: ignore[override]
        inner = ",".join(f"{f.name}:{f.dtype.name}" for f in self.fields)
        return f"struct<{inner}>"

    np_dtype = None

    def __hash__(self):
        return hash(("StructType", self.fields))


@dataclasses.dataclass(frozen=True, eq=True, repr=False)
class MapType(DType):
    key_type: DType = dataclasses.field(default_factory=StringType)
    value_type: DType = dataclasses.field(default_factory=StringType)

    @property
    def name(self) -> str:  # type: ignore[override]
        return f"map<{self.key_type.name},{self.value_type.name}>"

    np_dtype = None

    def __hash__(self):
        return hash(("MapType", self.key_type, self.value_type))


# Canonical singletons
BOOL = BooleanType()
INT8 = ByteType()
INT16 = ShortType()
INT32 = IntegerType()
INT64 = LongType()
FLOAT32 = FloatType()
FLOAT64 = DoubleType()
STRING = StringType()
DATE = DateType()
TIMESTAMP = TimestampType()
NULL = NullType()

_BY_NAME = {
    t.name: t
    for t in [BOOL, INT8, INT16, INT32, INT64, FLOAT32, FLOAT64, STRING, DATE,
              TIMESTAMP, NULL]
}
_ALIASES = {
    "long": INT64, "integer": INT32, "short": INT16, "byte": INT8,
    "bool": BOOL, "str": STRING, "real": FLOAT32,
}


def _split_top(s: str, sep: str = ","):
    """Split on ``sep`` at angle-bracket/paren depth 0."""
    parts, depth, start = [], 0, 0
    for i, ch in enumerate(s):
        if ch in "<(":
            depth += 1
        elif ch in ">)":
            depth -= 1
        elif ch == sep and depth == 0:
            parts.append(s[start:i])
            start = i + 1
    parts.append(s[start:])
    return parts


def dtype_from_name(name: str) -> DType:
    """Parse a dtype display name back to a DType (the wire/schema-string
    decoder; inverse of ``DType.name`` incl. nested array/struct/map)."""
    raw = name.strip()
    name = raw.lower()
    if name in _BY_NAME:
        return _BY_NAME[name]
    if name in _ALIASES:
        return _ALIASES[name]
    if name.startswith("decimal"):
        inner = name[name.index("(") + 1:name.index(")")]
        p, s = inner.split(",")
        return DecimalType(int(p), int(s))
    if name.startswith("array<") and name.endswith(">"):
        return ArrayType(dtype_from_name(raw[6:-1]))
    if name.startswith("map<") and name.endswith(">"):
        k, v = _split_top(raw[4:-1])
        return MapType(dtype_from_name(k), dtype_from_name(v))
    if name.startswith("struct<") and name.endswith(">"):
        inner = raw[7:-1]
        fields = []
        if inner:
            for part in _split_top(inner):
                fname, ftype = _split_top(part, ":")
                fields.append(StructField(fname, dtype_from_name(ftype)))
        return StructType(tuple(fields))
    raise ValueError(f"unknown dtype name: {name}")


def from_numpy_dtype(dt: np.dtype) -> DType:
    dt = np.dtype(dt)
    table = {
        np.dtype(np.bool_): BOOL,
        np.dtype(np.int8): INT8,
        np.dtype(np.int16): INT16,
        np.dtype(np.int32): INT32,
        np.dtype(np.int64): INT64,
        np.dtype(np.float32): FLOAT32,
        np.dtype(np.float64): FLOAT64,
    }
    if dt in table:
        return table[dt]
    if dt.kind in ("U", "S", "O"):
        return STRING
    if dt.kind == "M":  # datetime64
        return TIMESTAMP
    raise ValueError(f"unsupported numpy dtype: {dt}")


def common_type(a: DType, b: DType) -> DType:
    """Numeric type promotion following Spark's binary-op coercion."""
    if a == b:
        return a
    order = [INT8, INT16, INT32, INT64, FLOAT32, FLOAT64]
    if a in order and b in order:
        return order[max(order.index(a), order.index(b))]
    if isinstance(a, DecimalType) and b.is_integral:
        return a
    if isinstance(b, DecimalType) and a.is_integral:
        return b
    raise ValueError(f"no common type for {a} and {b}")
