"""ColumnarBatch — a set of equal-capacity device columns + a live row count.

Reference analogue: Spark ``ColumnarBatch`` wrapping ``GpuColumnVector``s
(reference: sql-plugin/.../GpuColumnVector.java) produced/consumed by every
``GpuExec.doExecuteColumnar``.

TPU-first: capacity is a power-of-two bucket (static shape for XLA); the
number of live rows is a host int known at batch boundaries, mirroring the
reference where cuDF row counts are host-visible after each kernel.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from . import dtypes as T
from .column import Column, StringColumn, bucket_capacity
from .schema import Field, Schema


from . import pending


def _flush_pending():
    """Resolve every staged host value in one fused transfer
    (columnar/pending.py)."""
    pending.flush()


class LazyCount:
    """A row count still resident on device.

    Every device->host pull triggers a remote execution round trip on
    this backend (fully lazy dispatch), which made per-batch
    ``int(count)`` pulls the dominant cost of small queries.  Execs
    producing data-dependent row counts (filter, group count, join size)
    wrap the device scalar in a LazyCount; the first forced value
    resolves EVERY outstanding staged pull (counts, bincounts, output
    buffers — columnar/pending.py) in one fused transfer.
    """
    __slots__ = ("dev", "_staged", "_val")

    def __init__(self, dev):
        self.dev = dev
        self._staged = pending.stage(dev)
        self._val: Optional[int] = None

    @property
    def value(self) -> int:
        if self._val is None:
            self._val = int(self._staged.np.ravel()[0])
            self._staged = None
        return self._val

    def __int__(self):
        return self.value

    __index__ = __int__

    def __bool__(self):
        return self.value > 0

    def __eq__(self, o):
        return self.value == int(o)

    def __lt__(self, o):
        return self.value < int(o)

    def __le__(self, o):
        return self.value <= int(o)

    def __gt__(self, o):
        return self.value > int(o)

    def __ge__(self, o):
        return self.value >= int(o)

    def __add__(self, o):
        return self.value + o

    __radd__ = __add__

    def __sub__(self, o):
        return self.value - o

    def __rsub__(self, o):
        return o - self.value

    def __mul__(self, o):
        return self.value * o

    __rmul__ = __mul__

    def __hash__(self):
        return hash(self.value)

    def __repr__(self):
        return f"LazyCount({self._val if self._val is not None else '?'})"


class LazyArray:
    """A small device int vector resolved through the pending pool
    (e.g. per-partition bincounts in the shuffle split)."""
    __slots__ = ("dev", "_staged", "_val")

    def __init__(self, dev):
        self.dev = dev
        self._staged = pending.stage(jnp.asarray(dev))
        self._val = None

    @property
    def np(self) -> np.ndarray:
        if self._val is None:
            self._val = self._staged.np
            self._staged = None
        return self._val


class SpeculativeResult:
    """Attached (as ``batch._speculative``) to a batch computed by a
    speculative fast-path program whose data assumptions are verified by
    a device-side flag (e.g. the sort-free bucket-table aggregate,
    kernels/aggregate.py table_plan).  Consumers holding a natural flush
    barrier (the shuffle exchange, the aggregate merge) call ``ok()``
    after the fused flush and ``redo()`` for the rare non-fitting batch.
    """

    __slots__ = ("fits", "_redo")

    def __init__(self, fits, redo):
        self.fits = list(fits)   # LazyCounts: nonzero == assumption held
        self._redo = redo

    def ok(self) -> bool:
        return all(int(f) != 0 for f in self.fits)

    def redo(self) -> "ColumnarBatch":
        return self._redo()


def chain_speculative(out: "ColumnarBatch", inp: "ColumnarBatch",
                      recompute) -> "ColumnarBatch":
    """Carry ``inp``'s unverified fit flags onto ``out``, a batch computed
    FROM ``inp`` by a count-preserving device transform (project, staged
    chain, lazy sort/limit): the consumer's flush barrier then vouches
    for the whole chain at once, and a failed fit recomputes via
    ``recompute(exact_input)``.  No-op when the input is not speculative
    — the superstage sync-free paths are the only producers."""
    spec = getattr(inp, "_speculative", None)
    if spec is None:
        return out
    own = getattr(out, "_speculative", None)

    def _redo():
        return recompute(resolve_speculative(inp))
    out._speculative = SpeculativeResult(
        list(spec.fits) + (list(own.fits) if own is not None else []),
        _redo)
    return out


def resolve_speculative(batch: "ColumnarBatch") -> "ColumnarBatch":
    """Verify-and-replace helper: returns the batch itself when its
    speculative assumptions held (or it has none), else the re-computed
    exact batch.  Loops: a redo may itself return a speculative batch
    (e.g. the bucket-table redo falls back to the sort path, which can
    attach its own compaction fit flag)."""
    for _ in range(4):
        spec = getattr(batch, "_speculative", None)
        if spec is None or spec.ok():
            return batch
        batch = spec.redo()
    spec = getattr(batch, "_speculative", None)
    assert spec is None or spec.ok(), \
        "speculative redo did not converge to a verified batch"
    return batch


class ColumnarBatch:
    def __init__(self, schema: Schema, columns: Sequence[Column], num_rows):
        assert len(schema) == len(columns), (len(schema), len(columns))
        self.schema = schema
        self.columns = list(columns)
        self._rows = num_rows if isinstance(num_rows, LazyCount) \
            else int(num_rows)
        self._rows_dev = None
        if columns:
            caps = {c.capacity for c in columns}
            assert len(caps) == 1, f"mixed capacities {caps}"
            self._capacity = caps.pop()
        else:
            self._capacity = bucket_capacity(int(num_rows))

    @property
    def num_rows(self) -> int:
        r = self._rows
        return r.value if isinstance(r, LazyCount) else r

    @num_rows.setter
    def num_rows(self, v):
        self._rows = v if isinstance(v, LazyCount) else int(v)
        self._rows_dev = None

    @property
    def rows_lazy(self):
        """The count as-is (int or LazyCount) — pass to derived batches
        so one eventual pull serves the whole lineage."""
        return self._rows

    @property
    def rows_dev(self):
        """The count as a device scalar, never forcing a host pull."""
        r = self._rows
        if isinstance(r, LazyCount):
            return r.dev
        if self._rows_dev is None:
            self._rows_dev = jnp.int32(r)
        return self._rows_dev

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    def column(self, key) -> Column:
        if isinstance(key, str):
            return self.columns[self.schema.index_of(key)]
        return self.columns[key]

    # -- constructors -----------------------------------------------------------
    @staticmethod
    def from_pydict(data: Dict[str, Sequence], schema: Optional[Schema] = None,
                    capacity: Optional[int] = None) -> "ColumnarBatch":
        names = list(data.keys())
        n = len(next(iter(data.values()))) if data else 0
        cap = capacity or bucket_capacity(n)
        cols, fields = [], []
        for name in names:
            dtype = schema[name].dtype if schema is not None else None
            col = Column.from_numpy(data[name], dtype=dtype, capacity=cap)
            cols.append(col)
            fields.append(Field(name, col.dtype))
        return ColumnarBatch(schema or Schema(fields), cols, n)

    @staticmethod
    def from_numpy(arrays: Dict[str, np.ndarray],
                   capacity: Optional[int] = None) -> "ColumnarBatch":
        return ColumnarBatch.from_pydict(arrays, capacity=capacity)

    @staticmethod
    def empty(schema: Schema, capacity: int = 16) -> "ColumnarBatch":
        cols = [Column.all_null(f.dtype, capacity) for f in schema]
        return ColumnarBatch(schema, cols, 0)

    # -- host interop -----------------------------------------------------------
    def to_pydict(self) -> Dict[str, List]:
        return {f.name: c.to_pylist(self.num_rows)
                for f, c in zip(self.schema, self.columns)}

    def to_pylist(self) -> List[tuple]:
        cols = [c.to_pylist(self.num_rows) for c in self.columns]
        return list(zip(*cols)) if cols else []

    # -- structural -------------------------------------------------------------
    def select(self, names: Iterable[str]) -> "ColumnarBatch":
        names = list(names)
        cols = [self.column(n) for n in names]
        fields = [self.schema[n] for n in names]
        return ColumnarBatch(Schema(fields), cols, self.rows_lazy)

    def with_column(self, name: str, col: Column) -> "ColumnarBatch":
        if name in self.schema.names:
            idx = self.schema.index_of(name)
            cols = list(self.columns)
            cols[idx] = col
            fields = list(self.schema.fields)
            fields[idx] = Field(name, col.dtype)
            return ColumnarBatch(Schema(fields), cols, self.num_rows)
        return ColumnarBatch(
            Schema(list(self.schema.fields) + [Field(name, col.dtype)]),
            self.columns + [col], self.num_rows)

    def with_capacity(self, capacity: int) -> "ColumnarBatch":
        if capacity == self.capacity:
            return self
        cols = [c.with_capacity(capacity, self.num_rows) for c in self.columns]
        b = ColumnarBatch(self.schema, cols, self.num_rows)
        return b

    def gather(self, indices, num_rows, live=None,
               unique=False) -> "ColumnarBatch":
        cols = [c.gather(indices, live=live, unique=unique)
                for c in self.columns]
        return ColumnarBatch(self.schema, cols, num_rows)

    # jitted slice programs keyed by (out_cap,); shapes key the rest.
    # Eager per-column gathers cost ~7ms of client overhead EACH on the
    # remote backend; one jit dispatch is ~free (columnar/pending.py doc).
    _SLICE_JIT: dict = {}

    def slice(self, start: int, length: int) -> "ColumnarBatch":
        valid_rows = min(length, max(self.num_rows - start, 0))
        out_cap = bucket_capacity(length)
        if all(type(c) is Column for c in self.columns) and self.columns:
            fn = ColumnarBatch._SLICE_JIT.get(out_cap)
            if fn is None:
                import jax

                def _slice(datas, valids, start_, nvalid):
                    idx = jnp.arange(out_cap) + start_
                    live = jnp.arange(out_cap) < nvalid
                    outs = []
                    for d, v in zip(datas, valids):
                        outs.append((
                            jnp.take(d, idx, axis=0, mode="clip"),
                            jnp.take(v, idx, axis=0, mode="clip") & live))
                    return outs
                fn = jax.jit(_slice)
                ColumnarBatch._SLICE_JIT[out_cap] = fn
            pairs = fn(tuple(c.data for c in self.columns),
                       tuple(c.validity for c in self.columns),
                       start, valid_rows)
            cols = [Column(c.dtype, d, v)
                    for c, (d, v) in zip(self.columns, pairs)]
            return ColumnarBatch(self.schema, cols, valid_rows)
        idx = jnp.arange(out_cap) + start
        b = self.gather(idx, valid_rows)
        # rows past num_rows must be invalid
        mask = jnp.arange(b.capacity) < valid_rows
        cols = [c.mask_validity(mask) for c in b.columns]
        return ColumnarBatch(self.schema, cols, valid_rows)

    def nbytes(self) -> int:
        return sum(c.nbytes() for c in self.columns)

    def device_buffers(self):
        out = []
        for c in self.columns:
            out.extend(c.device_buffers())
        return out

    def __repr__(self):
        return (f"ColumnarBatch(rows={self.num_rows}, cap={self.capacity}, "
                f"schema={self.schema})")


def concat_batches(batches: Sequence[ColumnarBatch]) -> ColumnarBatch:
    """Concatenate batches of identical schema (the GpuCoalesceBatches core,

    reference: GpuCoalesceBatches.scala:195)."""
    # concat reads num_rows (a flush barrier) — the right moment to
    # verify any speculative fast-path batches before baking them in
    batches = [resolve_speculative(b) for b in batches]
    assert batches, "concat of zero batches"
    if len(batches) == 1:
        return batches[0]
    schema = batches[0].schema
    total = sum(b.num_rows for b in batches)
    cap = bucket_capacity(total)
    if all(type(c) is Column for b in batches for c in b.columns) and \
            len(schema):
        return _concat_plain_jit(batches, schema, cap, total)
    out_cols: List[Column] = []
    for ci, field in enumerate(schema):
        out_cols.append(_concat_cols(
            field.dtype, [b.columns[ci] for b in batches],
            [b.num_rows for b in batches], cap))
    return ColumnarBatch(schema, out_cols, total)


_CONCAT_JIT: dict = {}


def _concat_plain_jit(batches, schema, cap: int, total: int):
    """One jitted program for fixed-width concat (slice+concat+pad per
    column) — the eager per-column path costs ~7ms/op on the remote
    backend (columnar/pending.py doc)."""
    import jax
    nrows = tuple(b.num_rows for b in batches)
    key = (nrows, cap, len(schema))
    fn = _CONCAT_JIT.get(key)
    if fn is None:
        ncols = len(schema)

        def _concat(datas, valids):
            outs = []
            for ci in range(ncols):
                ds = [d[:n] for d, n in zip(datas[ci], nrows)]
                vs = [v[:n] for v, n in zip(valids[ci], nrows)]
                d = jnp.concatenate(ds)
                v = jnp.concatenate(vs)
                pad = cap - int(d.shape[0])
                if pad:
                    d = jnp.pad(d, (0, pad))
                    v = jnp.pad(v, (0, pad))
                outs.append((d, v))
            return outs
        fn = jax.jit(_concat)
        if len(_CONCAT_JIT) < 4096:
            _CONCAT_JIT[key] = fn
    datas = tuple(tuple(b.columns[ci].data for b in batches)
                  for ci in range(len(schema)))
    valids = tuple(tuple(b.columns[ci].validity for b in batches)
                   for ci in range(len(schema)))
    pairs = fn(datas, valids)
    cols = [Column(f.dtype, d, v) for f, (d, v) in zip(schema, pairs)]
    return ColumnarBatch(schema, cols, total)


def _concat_cols(dtype: T.DType, cols: Sequence[Column],
                 nrows: Sequence[int], cap: int) -> Column:
    if dtype == T.STRING:
        return _concat_string_cols(cols, nrows, cap)
    if isinstance(dtype, T.StructType):
        return _concat_struct_cols(dtype, cols, nrows, cap)
    if isinstance(dtype, (T.ArrayType, T.MapType)):
        return _concat_list_cols(cols, nrows, cap)
    datas = [c.data[:n] for c, n in zip(cols, nrows)]
    valids = [c.validity[:n] for c, n in zip(cols, nrows)]
    data = jnp.concatenate(datas) if datas else jnp.zeros(0)
    valid = jnp.concatenate(valids)
    pad = cap - int(data.shape[0])
    if pad:
        data = jnp.pad(data, (0, pad))
        valid = jnp.pad(valid, (0, pad))
    return Column(dtype, data, valid)


def _slice_elements(col: Column, o0: int, o1: int) -> Column:
    """Child slice covering absolute element range [o0, o1)."""
    from .column import ListColumn, MapColumn, StructColumn
    if isinstance(col, (ListColumn, MapColumn)):
        out = type(col)(col.dtype, col.offsets[o0:o1 + 1], col.elements,
                        col.validity[o0:o1])
        return out
    if isinstance(col, StructColumn):
        return StructColumn(
            col.dtype, [_slice_elements(c, o0, o1) for c in col.children],
            col.validity[o0:o1])
    if isinstance(col, StringColumn):
        return StringColumn(col.offsets[o0:o1 + 1], col.data,
                            col.validity[o0:o1], max_bytes=col.max_bytes)
    return Column(col.dtype, col.data[o0:o1], col.validity[o0:o1])


def _concat_struct_cols(dtype: T.StructType, cols: Sequence[Column],
                        nrows: Sequence[int], cap: int) -> Column:
    from .column import StructColumn
    kids = []
    for fi, f in enumerate(dtype.fields):
        kids.append(_concat_cols(f.dtype,
                                 [c.children[fi] for c in cols],
                                 nrows, cap))
    valid = jnp.concatenate([c.validity[:n] for c, n in zip(cols, nrows)])
    vpad = cap - int(valid.shape[0])
    if vpad > 0:
        valid = jnp.pad(valid, (0, vpad))
    return StructColumn(dtype, kids, valid)


def _concat_list_cols(cols: Sequence[Column], nrows: Sequence[int],
                      cap: int) -> Column:
    """Concat of List/MapColumns: rebase offsets, recursively concat
    children."""
    from ..analysis import residency  # lazy: avoids import cycle
    offsets_parts: List = []
    valid_parts: List = []
    child_cols: List[Column] = []
    child_ns: List[int] = []
    base = 0
    with residency.declared_transfer(site="batch_concat"):
        for c, n in zip(cols, nrows):
            offs = np.asarray(c.offsets)
            o0, o1 = int(offs[0]), int(offs[n])
            offsets_parts.append(
                c.offsets[:n].astype(jnp.int32) - jnp.int32(o0 - base))
            valid_parts.append(c.validity[:n])
            child_cols.append(_slice_elements(c.elements, o0, o1))
            child_ns.append(o1 - o0)
            base += o1 - o0
    child_cap = bucket_capacity(max(1, sum(child_ns)))
    elem_dtype = cols[0].elements.dtype
    elements = _concat_cols(elem_dtype, child_cols, child_ns, child_cap)
    offsets = jnp.concatenate(
        offsets_parts + [jnp.array([base], jnp.int32)])
    pad = cap + 1 - int(offsets.shape[0])
    if pad > 0:
        offsets = jnp.pad(offsets, (0, pad), mode="edge")
    valid = jnp.concatenate(valid_parts)
    vpad = cap - int(valid.shape[0])
    if vpad > 0:
        valid = jnp.pad(valid, (0, vpad))
    return type(cols[0])(cols[0].dtype, offsets.astype(jnp.int32), elements,
                         valid)


def _concat_string_cols(cols: Sequence[StringColumn], nrows: Sequence[int],
                        cap: int) -> StringColumn:
    from ..analysis import residency  # lazy: avoids import cycle
    offsets_parts, valid_parts = [], []
    base = 0
    with residency.declared_transfer(site="batch_concat"):
        for c, n in zip(cols, nrows):
            offs_np = np.asarray(c.offsets)
            o0 = int(offs_np[0])
            offsets_parts.append(c.offsets[:n] - jnp.int32(o0 - base))
            base = base + int(offs_np[n]) - o0
            valid_parts.append(c.validity[:n])
        # bytes: need exact live bytes from each column; slicing with
        # dynamic sizes is not static-shape friendly on device, so
        # gather via numpy on host (concat is a batch boundary; the
        # reference also round-trips host for shuffle concat of
        # serialized batches).
        np_bytes = []
        for c, n in zip(cols, nrows):
            offs = np.asarray(c.offsets)
            np_bytes.append(np.asarray(c.data)[int(offs[0]):int(offs[n])])
    all_bytes = np.concatenate(np_bytes) if np_bytes else np.zeros(0, np.uint8)
    byte_cap = bucket_capacity(max(1, all_bytes.shape[0]))
    buf = np.zeros(byte_cap, np.uint8)
    buf[: all_bytes.shape[0]] = all_bytes
    offsets = jnp.concatenate(offsets_parts + [jnp.array([all_bytes.shape[0]],
                                                         jnp.int32)])
    total = sum(nrows)
    pad = cap + 1 - int(offsets.shape[0])
    if pad > 0:
        offsets = jnp.pad(offsets, (0, pad), mode="edge")
    valid = jnp.concatenate(valid_parts)
    vpad = cap - int(valid.shape[0])
    if vpad > 0:
        valid = jnp.pad(valid, (0, vpad))
    mb = StringColumn.combined_max_bytes(cols)
    return StringColumn(offsets.astype(jnp.int32), jnp.asarray(buf), valid,
                        max_bytes=mb)
