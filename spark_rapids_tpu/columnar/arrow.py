"""Arrow interop: ColumnarBatch <-> pyarrow Table.

Roles: host staging for IO (GpuParquetScan reads into host memory then
device, SURVEY.md §2.6), the Python-UDF exchange format (reference:
GpuArrowEvalPythonExec), and the bridge to the CPU fallback engine
(exec/cpu.py) which executes on pyarrow compute.
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import pyarrow as pa

from . import dtypes as T
from .column import Column, StringColumn, bucket_capacity
from .schema import Field, Schema
from .batch import ColumnarBatch

_TO_ARROW = {
    T.BOOL: pa.bool_(),
    T.INT8: pa.int8(),
    T.INT16: pa.int16(),
    T.INT32: pa.int32(),
    T.INT64: pa.int64(),
    T.FLOAT32: pa.float32(),
    T.FLOAT64: pa.float64(),
    T.STRING: pa.string(),
    T.DATE: pa.date32(),
    T.TIMESTAMP: pa.timestamp("us"),
}


def to_arrow_type(dt: T.DType) -> pa.DataType:
    if isinstance(dt, T.DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, T.ArrayType):
        return pa.list_(to_arrow_type(dt.element_type))
    if isinstance(dt, T.StructType):
        return pa.struct([pa.field(f.name, to_arrow_type(f.dtype),
                                   f.nullable) for f in dt.fields])
    if isinstance(dt, T.MapType):
        return pa.map_(to_arrow_type(dt.key_type),
                       to_arrow_type(dt.value_type))
    if dt in _TO_ARROW:
        return _TO_ARROW[dt]
    raise ValueError(f"no arrow type for {dt}")


def from_arrow_type(at: pa.DataType) -> T.DType:
    if pa.types.is_boolean(at):
        return T.BOOL
    if pa.types.is_int8(at):
        return T.INT8
    if pa.types.is_int16(at):
        return T.INT16
    if pa.types.is_int32(at):
        return T.INT32
    if pa.types.is_int64(at):
        return T.INT64
    if pa.types.is_float32(at):
        return T.FLOAT32
    if pa.types.is_float64(at):
        return T.FLOAT64
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return T.STRING
    if pa.types.is_date32(at):
        return T.DATE
    if pa.types.is_timestamp(at):
        return T.TIMESTAMP
    if pa.types.is_map(at):
        return T.MapType(from_arrow_type(at.key_type),
                         from_arrow_type(at.item_type))
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return T.ArrayType(from_arrow_type(at.value_type))
    if pa.types.is_struct(at):
        return T.StructType([T.StructField(at.field(i).name,
                                           from_arrow_type(at.field(i).type),
                                           at.field(i).nullable)
                             for i in range(at.num_fields)])
    if pa.types.is_decimal(at):
        if at.precision > T.DecimalType.MAX_PRECISION:
            raise ValueError(f"decimal precision {at.precision} > 18")
        return T.DecimalType(at.precision, at.scale)
    raise ValueError(f"unsupported arrow type {at}")


def schema_from_arrow(aschema: pa.Schema) -> Schema:
    return Schema([Field(f.name, from_arrow_type(f.type), f.nullable)
                   for f in aschema])


def schema_to_arrow(schema: Schema) -> pa.Schema:
    return pa.schema([pa.field(f.name, to_arrow_type(f.dtype), f.nullable)
                      for f in schema])


def column_to_arrow(col: Column, num_rows: int) -> pa.Array:
    from .column import ListColumn, MapColumn, StructColumn
    if isinstance(col, MapColumn):
        offs = col._hnp("offsets")[:num_rows + 1].astype(np.int64)
        valid = col._hnp("validity")[:num_rows]
        n_elems = int(offs[num_rows]) if num_rows else 0
        keys = column_to_arrow(col.keys, n_elems)
        items = column_to_arrow(col.values, n_elems)
        if valid.all():
            arrow_offs = pa.array(offs, type=pa.int32())
        else:
            arrow_offs = pa.array(
                [int(offs[i]) if i == num_rows or valid[i] else None
                 for i in range(num_rows + 1)], type=pa.int32())
        return pa.MapArray.from_arrays(arrow_offs, keys, items)
    if isinstance(col, StructColumn):
        valid = col._hnp("validity")[:num_rows]
        kids = [column_to_arrow(c, num_rows) for c in col.children]
        names = [f.name for f in col.dtype.fields]
        return pa.StructArray.from_arrays(
            kids, names, mask=pa.array(~valid, type=pa.bool_()))
    if isinstance(col, ListColumn):
        offs = col._hnp("offsets")[:num_rows + 1].astype(np.int64)
        valid = col._hnp("validity")[:num_rows]
        n_elems = int(offs[num_rows]) if num_rows else 0
        values = column_to_arrow(col.elements, n_elems)
        if valid.all():
            arrow_offs = pa.array(offs, type=pa.int32())
        else:
            # a null offset entry marks that list row null (Arrow semantics
            # of ListArray.from_arrays with a nullable offsets array)
            arrow_offs = pa.array(
                [int(offs[i]) if i == num_rows or valid[i] else None
                 for i in range(num_rows + 1)], type=pa.int32())
        return pa.ListArray.from_arrays(arrow_offs, values)
    if isinstance(col, StringColumn):
        vals, valid = col.to_numpy(num_rows)
        return pa.array([v if ok else None for v, ok in zip(vals, valid)],
                        type=pa.string())
    vals, valid = col.to_numpy(num_rows)
    mask = ~valid
    at = to_arrow_type(col.dtype)
    if isinstance(col.dtype, T.DecimalType):
        from decimal import Decimal
        scale = col.dtype.scale
        items = [None if m else
                 Decimal(int(v)).scaleb(-scale)
                 for v, m in zip(vals, mask)]
        return pa.array(items, type=at)
    if col.dtype == T.DATE:
        return pa.array(vals.astype("datetime64[D]"), type=at,
                        mask=mask)
    if col.dtype == T.TIMESTAMP:
        return pa.array(vals.astype("datetime64[us]"), type=at, mask=mask)
    return pa.array(vals, type=at, mask=mask)


def to_arrow(batch: ColumnarBatch) -> pa.Table:
    stage_batch(batch)
    arrays = [column_to_arrow(c, batch.num_rows) for c in batch.columns]
    return pa.Table.from_arrays(arrays, schema=schema_to_arrow(batch.schema))


def stage_batch(batch: ColumnarBatch):
    """Stage every device buffer of a batch for one fused host pull —
    callers converting several batches stage them all first so counts,
    validity and data cross the wire in a single transfer."""
    for c in batch.columns:
        c.stage_host()


def column_from_arrow(arr: pa.ChunkedArray | pa.Array,
                      capacity: Optional[int] = None) -> Column:
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    dt = from_arrow_type(arr.type)
    n = len(arr)
    cap = capacity or bucket_capacity(n)
    if isinstance(dt, T.MapType):
        from .column import MapColumn, StructColumn
        import jax.numpy as jnp
        valid_np = np.ones(n, dtype=bool) if arr.null_count == 0 else \
            np.asarray(arr.is_valid())
        raw = np.asarray(arr.offsets.fill_null(0)).astype(np.int64)
        lens = np.where(valid_np, raw[1:] - raw[:-1], 0)
        offs = np.zeros(n + 1, np.int32)
        offs[1:] = np.cumsum(lens)
        # keys/items are unsliced child arrays addressed by raw offsets
        if arr.null_count == 0:
            # null-free fast path: live entries are one contiguous range
            start, stop = (int(raw[0]), int(raw[n])) if n else (0, 0)
            keys = arr.keys.slice(start, stop - start)
            items = arr.items.slice(start, stop - start)
        else:
            # gather the live extents per row to match rebuilt offsets
            take = np.concatenate(
                [np.arange(raw[i], raw[i + 1])
                 for i in range(n) if valid_np[i]] or
                [np.zeros(0, np.int64)])
            keys = arr.keys.take(pa.array(take)) if len(take) else \
                arr.keys.slice(0, 0)
            items = arr.items.take(pa.array(take)) if len(take) else \
                arr.items.slice(0, 0)
        est = MapColumn.entry_struct_type(dt)
        n_e = len(keys)
        ecap = bucket_capacity(max(1, n_e))
        kcol = column_from_arrow(keys, capacity=ecap)
        vcol = column_from_arrow(items, capacity=ecap)
        elems = StructColumn(est, [kcol, vcol],
                             jnp.asarray(np.arange(ecap) < n_e))
        out_offs = np.full(cap + 1, offs[n] if n else 0, np.int32)
        out_offs[:n + 1] = offs[:n + 1]
        out_valid = np.zeros(cap, bool)
        out_valid[:n] = valid_np
        return MapColumn(dt, jnp.asarray(out_offs), elems,
                         jnp.asarray(out_valid))
    if isinstance(dt, T.StructType):
        from .column import StructColumn
        import jax.numpy as jnp
        valid_np = np.ones(n, dtype=bool) if arr.null_count == 0 else \
            np.asarray(arr.is_valid())
        kids = [column_from_arrow(arr.field(i), capacity=cap)
                for i in range(arr.type.num_fields)]
        out_valid = np.zeros(cap, bool)
        out_valid[:n] = valid_np
        return StructColumn(dt, kids, jnp.asarray(out_valid))
    if isinstance(dt, T.ArrayType):
        from .column import ListColumn
        import jax.numpy as jnp
        valid_np = np.ones(n, dtype=bool) if arr.null_count == 0 else \
            np.asarray(arr.is_valid())
        raw = np.asarray(arr.offsets.fill_null(0)).astype(np.int64)
        # rebuild monotonic 0-based offsets with 0-length extents at null
        # rows so device kernels see a clean buffer; flatten() yields the
        # matching element sequence (it skips null/sliced-out extents)
        lens = np.where(valid_np, raw[1:] - raw[:-1], 0)
        offs = np.zeros(n + 1, np.int32)
        offs[1:] = np.cumsum(lens)
        flat = arr.flatten()
        elements = column_from_arrow(flat) if len(flat) else \
            column_from_arrow(pa.array([], type=arr.type.value_type))
        out_offs = np.full(cap + 1, offs[n] if n else 0, np.int32)
        out_offs[:n + 1] = offs[:n + 1]
        out_valid = np.zeros(cap, bool)
        out_valid[:n] = valid_np
        return ListColumn(dt, jnp.asarray(out_offs), elements,
                          jnp.asarray(out_valid))
    if dt == T.STRING:
        return StringColumn.from_pylist(arr.to_pylist(), capacity=cap)
    valid_np = np.ones(n, dtype=bool) if arr.null_count == 0 else \
        np.asarray(arr.is_valid())
    if dt == T.FLOAT64:
        from .binary64 import Binary64Column, exact_double_enabled
        if exact_double_enabled():
            vals = np.asarray(arr.fill_null(0.0), np.float64)
            return Binary64Column.from_f64_numpy(vals, valid_np,
                                                 capacity=cap)
    if isinstance(dt, T.DecimalType):
        scale = dt.scale
        vals = np.array(
            [int(v.scaleb(scale)) if v is not None else 0
             for v in arr.to_pylist()], dtype=np.int64)
    elif dt == T.DATE:
        vals = np.asarray(arr.cast(pa.int32()).fill_null(0))
    elif dt == T.TIMESTAMP:
        vals = np.asarray(arr.cast(pa.int64()).fill_null(0))
    elif dt == T.BOOL:
        vals = np.asarray(arr.fill_null(False))
    else:
        vals = np.asarray(arr.fill_null(0))
    col = Column.from_numpy(vals.astype(dt.np_dtype), dtype=dt, capacity=cap)
    import jax.numpy as jnp
    pad = np.zeros(cap, dtype=bool)
    pad[:n] = valid_np
    return Column(dt, col.data, jnp.asarray(pad))


def from_arrow(table: pa.Table, capacity: Optional[int] = None
               ) -> ColumnarBatch:
    from ..memory.pressure import oom_retry

    def build():
        n = table.num_rows
        cap = capacity or bucket_capacity(n)
        cols = [column_from_arrow(table.column(i), capacity=cap)
                for i in range(table.num_columns)]
        return ColumnarBatch(schema_from_arrow(table.schema), cols, n)
    # scan-side device puts can hit the real allocator's
    # RESOURCE_EXHAUSTED under fragmentation even when the logical
    # budget says there is room: spill everything spillable and retry
    # (DeviceMemoryEventHandler.onAllocFailure contract)
    return oom_retry(build)
