"""Binary64Column: exact SQL DOUBLE as IEEE-754 bits in int64.

Why: the chip has no f64 ALU — XLA's emulated ``f64`` is an f32 pair
(~48-bit precision, ~1e±38 range), so a 1e300 DOUBLE cannot even
round-trip device memory.  64-bit INTEGER ops are exact on chip, so
under ``spark.rapids.tpu.sql.exactDouble.enabled`` every DOUBLE column
holds the IEEE bit pattern in int64 and arithmetic/comparison/
aggregation route through the softfloat kernels
(kernels/binary64.py).  Reference contract: bit-for-bit DOUBLE
semantics (GpuCast.scala / arithmetic.scala; the reference gets them
from cuDF's native f64).

Bits enter HOST-SIDE (numpy view — free, exact); the chip never needs
an f64<->i64 bitcast.  Ops outside the wired set raise loudly — this
mode trades breadth for exactness and is off by default.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from . import dtypes as T
from .column import Column, bucket_capacity, _pad_np


def exact_double_enabled() -> bool:
    from ..config import get_active, EXACT_DOUBLE
    try:
        return bool(get_active().get(EXACT_DOUBLE))
    except Exception:  # noqa: BLE001 - before config init
        return False


class Binary64Column(Column):
    """dtype FLOAT64; ``data`` is int64 IEEE-754 bit patterns."""

    def __init__(self, data, validity):
        super().__init__(T.FLOAT64, data, validity)

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_f64_numpy(arr: np.ndarray, validity=None,
                       capacity=None) -> "Binary64Column":
        bits = np.ascontiguousarray(arr, np.float64).view(np.int64)
        n = bits.shape[0]
        cap = capacity or bucket_capacity(n)
        if validity is None:
            validity = np.ones(n, np.bool_)
        return Binary64Column(
            jnp.asarray(_pad_np(bits, cap)),
            jnp.asarray(_pad_np(np.asarray(validity, np.bool_), cap,
                                fill=False)))

    @staticmethod
    def from_scalar_value(value, capacity: int, num_rows=None
                          ) -> "Binary64Column":
        from ..kernels import binary64 as b64
        n = capacity if num_rows is None else num_rows
        if value is None:
            return Binary64Column(jnp.zeros(capacity, jnp.int64),
                                  jnp.zeros(capacity, bool))
        bits = b64.bits_of(float(value))
        return Binary64Column(jnp.full((capacity,), bits, jnp.int64),
                              jnp.arange(capacity) < n)

    # -- host interop -------------------------------------------------------
    def to_numpy(self, num_rows: int):
        vals = np.ascontiguousarray(
            self._hnp("data")[:num_rows]).view(np.float64)
        return vals, self._hnp("validity")[:num_rows]

    # -- structural ops (must preserve the subclass) ------------------------
    def with_capacity(self, capacity: int, num_rows: int):
        if capacity == self.capacity:
            return self
        if capacity > self.capacity:
            pad = capacity - self.capacity
            data = jnp.pad(self.data, (0, pad))
            valid = jnp.pad(self.validity, (0, pad))
        else:
            data = self.data[:capacity]
            valid = self.validity[:capacity] & \
                (jnp.arange(capacity) < num_rows)
        return Binary64Column(data, valid)

    def gather(self, indices, live=None, unique=False):
        valid = jnp.take(self.validity, indices, axis=0, mode="clip")
        if live is not None:
            valid = valid & live
        return Binary64Column(
            jnp.take(self.data, indices, axis=0, mode="clip"), valid)

    def mask_validity(self, keep_mask):
        return Binary64Column(self.data, self.validity & keep_mask)


def require_same_kind(*cols):
    """Mixed exact-bits and emulated-f64 operands would silently compare
    bit patterns against values; refuse loudly."""
    kinds = {isinstance(c, Binary64Column) for c in cols
             if c is not None and c.dtype == T.FLOAT64}
    if len(kinds) > 1:
        raise NotImplementedError(
            "exactDouble: mixed Binary64 and emulated f64 operands — "
            "a DOUBLE entered the plan outside the exact-bits paths")
