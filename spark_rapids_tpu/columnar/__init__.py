"""Columnar substrate: dtypes, schema, device columns, batches.

Reference analogue: GpuColumnVector.java / RapidsHostColumnVector.java and
the ColumnarBatch contract every GpuExec consumes (SURVEY.md §2.3)."""
from . import dtypes  # noqa: F401
from .schema import Field, Schema  # noqa: F401
from .column import Column, StringColumn, bucket_capacity, MIN_CAPACITY  # noqa: F401
from .batch import ColumnarBatch, concat_batches  # noqa: F401
