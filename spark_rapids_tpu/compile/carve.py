"""Superstage carving: the planner post-pass that splits a verified
physical plan into maximal exchange-delimited regions and wraps each
qualifying region in one TpuSuperstage dispatch.

Runs AFTER the invariant verifier (analysis/plan_verify.py) so it only
ever sees plans whose schema/dtype/partitioning/checkpoint contracts
hold; the PV-STAGE pass re-verifies the carved tree (boundaries coincide
with exchanges, cancel checkpoints survive fusion, at most one flush
barrier per stage).

A region is the maximal connected component of *member* operators
(compile/lower.classify) reachable from a region root — the first
member found under a boundary (exchange, scan, row transition, mesh
exec) or under the plan root.  Join build sides typically end at a
broadcast exchange, so the natural carve reproduces Spark's stage
graph: stages begin and end at exchanges.

Carving arms the members' sync-free device-resident paths (the join's
speculative unique-match program rides ``node._superstage``); a member
whose boundary child is NOT a natural stage delimiter is an *ejection*
— that operator keeps per-operator dispatch and the region simply does
not extend through it (``tpu_compile_superstages_total{event=
"ejected"}``).  Regions smaller than
``spark.rapids.tpu.sql.superstage.minOps`` are left uncarved: a
single-operator stage gains nothing from the wrapper.
"""
from __future__ import annotations

from typing import List

from ..exec.base import PhysicalPlan
from . import lower


def _natural_boundary(node: PhysicalPlan) -> bool:
    """Boundaries that END a stage by design (no ejection event):
    exchanges — the stage graph's edges — and leaves (scans)."""
    from ..exec import exchange as TX
    if isinstance(node, (TX.TpuShuffleExchange, TX.TpuBroadcastExchange)):
        return True
    return not node.children


def _resolving_consumer(parent) -> bool:
    """True when ``parent`` provably resolves speculative fit flags on
    the batches it consumes: the session collect sink (parent None),
    exchange finalize, and the join's build/stream intake.  Any other
    boundary consumer gets exact batches — the stage resolves its own
    output at the edge instead of trusting an unknown operator not to
    bake an unverified count."""
    if parent is None:
        return True
    from ..exec import exchange as TX
    from ..exec import tpu_join as TJ
    return isinstance(parent, (TX.TpuShuffleExchange,
                               TX.TpuBroadcastExchange,
                               TJ.TpuHashJoinBase))


def carve_plan(phys: PhysicalPlan, conf) -> PhysicalPlan:
    """Return ``phys`` with every qualifying region wrapped in a
    TpuSuperstage (in place below the wrappers; the returned root may
    be a new wrapper node)."""
    from ..config import SUPERSTAGE_MIN_OPS
    from ..exec.superstage import TpuSuperstage
    from ..obs import flight
    from ..obs.registry import superstage_event
    min_ops = int(conf.get(SUPERSTAGE_MIN_OPS))

    def _collect(node: PhysicalPlan, members: List[PhysicalPlan]):
        """DFS the connected member component under ``node``; carve the
        boundary subtrees below it in the same walk."""
        members.append(node)
        for i, c in enumerate(node.children):
            if lower.is_member(c):
                _collect(c, members)
            else:
                if not _natural_boundary(c):
                    # unfusable operator inside the would-be stage:
                    # ejected into its own dispatch, region splits here
                    superstage_event("ejected")
                    flight.record(flight.EV_COMPILE, "ejected",
                                  len(c.children))
                node.children[i] = _carve(c, node)

    def _carve(node: PhysicalPlan, parent) -> PhysicalPlan:
        if not lower.is_member(node):
            for i, c in enumerate(node.children):
                node.children[i] = _carve(c, node)
            return node
        members: List[PhysicalPlan] = []
        _collect(node, members)
        if len(members) < min_ops:
            return node
        # arm the members' sync-free paths: inside a carved region every
        # consumer provably resolves or chains speculative fit flags, so
        # the join may emit its one-dispatch speculative output
        for m in members:
            m._superstage = True
        lowering = lower.lower_region(members)
        superstage_event("carved")
        flight.record(flight.EV_COMPILE, "carved", len(members))
        return TpuSuperstage(node, members, lowering,
                             resolve_output=not _resolving_consumer(
                                 parent))

    return _carve(phys, None)
