"""AOT compile subsystem — shape buckets, persistent reuse, warmup.

The reference plugin never pays kernel compilation on the query
critical path: cuDF kernels ship AOT-compiled in the jar.  Our XLA
backend compiles per novel (shape, dtype, conf) tuple inline, which
the compile-telemetry plane (obs/compile_watch.py) measures as
``inline_compile_ms`` per victim query.  This module is the fix for
ROADMAP open item 3 ("cold traffic"), in three parts:

**Shape-bucket lattice.**  Batch capacities were already padded to
powers of two (``columnar.column.bucket_capacity``); the lattice
generalizes the growth factor.  ``bucketRatio=2`` reproduces the
classic pow2 padding bit-for-bit; a coarser ratio (4) quarters the
number of distinct shapes every engine JIT cache compiles for, so
executables are shared across queries of different sizes.  Padding is
mask-correct by construction: every padded row carries a validity
word and live-row count, so bucketed results are sha-identical to
unbucketed execution (asserted by tests/test_aot.py across
pipelineParallelism x superstage).

**Persistent executable cache.**  ``aot.cacheDir`` points the JAX
persistent compilation cache at a directory so a fresh process
deserializes prior XLA executables instead of recompiling.  Alongside
it this module keeps a *manifest*: one JSON entry per first-compile
keyed by ``sha1(program id | signature | conf fingerprint)`` — the
signature carries the dtype tuple and bucket, the fingerprint hashes
every program-affecting conf plus the jax version and lattice
geometry.  When a fresh process's first call of a program finds its
key in a manifest written by an *earlier* run, the call is a
persistent-cache load, not a compile: compile_watch counts it under
``tpu_compile_persistent_hits_total`` and keeps ``tpu_compile_seconds``
untouched (the cross-process test's "zero new XLA compiles"
assertion).

**Demand ledger + warmup registry.**  Call sites next to the JIT
caches report ``note_demand(cache, capacity, hit)`` per lookup; the
ledger keeps hit/miss counts per (program, bucket) and a thread-local
last-demand the telemetry plane uses to attribute a compile to its
bucket.  JIT caches register *warmers* — closures that call the real
jitted program with dummy arrays at a given bucket capacity (calling
is required: ``lower().compile()`` does not populate jit's C++
call-path cache).  The service's warmup daemon (service/warmup.py)
drains ``warm_missing()`` against the observed bucket mix, inside
``warmup_scope()`` so compile_watch attributes those compiles to the
``warmup`` pseudo-victim, never to a tenant query.

Hot-path discipline (SYNC001/OBS002/HYG002 lint scopes): the ledger
update is a dict poke under the GIL plus one bounded counter; no
device syncs, no wall-clock reads, manifest I/O happens outside the
module lock.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import uuid
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..obs import flight
from ..obs.registry import (AOT_BUCKET_DEMAND, AOT_HINT_COMPILES,
                            AOT_WARMUP_COMPILES)

#: every program participating in bucketed execution — the PR 10
#: auditor must keep full coverage over this registry
#: (analysis/program_audit.aot_coverage_gaps, tests/test_audit.py).
BUCKETED_PROGRAMS = frozenset({
    "fused_project",
    "staged_compute",
    "hash_aggregate_grouped",
    "hash_aggregate_whole_stage",
    "hash_aggregate_global",
    "join_probe",
    "join_spec_probe",
    "mesh_join",
    "mesh_sort",
    "mesh_aggregate",
    "pallas_hash_partition",
    "exchange_stats",
})

_MANIFEST_NAME = "aot_manifest.json"
_SIG_MAX = 160


class BucketLattice:
    """Geometric capacity buckets: min_rows * ratio^k, smallest >= n."""

    def __init__(self, min_rows: int, ratio: int):
        if min_rows < 1:
            raise ValueError(f"lattice min_rows must be >= 1: {min_rows}")
        if ratio < 2 or (ratio & (ratio - 1)) != 0:
            raise ValueError(
                f"lattice ratio must be a power of two >= 2: {ratio}")
        self.min_rows = int(min_rows)
        self.ratio = int(ratio)

    def bucket(self, n: int) -> int:
        cap = self.min_rows
        while cap < n:
            cap *= self.ratio
        return cap

    def points_up_to(self, n: int) -> List[int]:
        """Every lattice point <= bucket(n) (smallest first)."""
        pts = [self.min_rows]
        while pts[-1] < n:
            pts.append(pts[-1] * self.ratio)
        return pts

    def __repr__(self):
        return f"BucketLattice(min={self.min_rows}, ratio={self.ratio})"


# ---------------------------------------------------------------------------
# module state (process-wide, last-configure-wins like the obs planes)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_ENABLED = True
_LATTICE: Optional[BucketLattice] = None
_CACHE_DIR = ""
_XLA_CACHE_WIRED = False
_CONF_FP = ""
_RUN_ID = uuid.uuid4().hex[:12]     #: distinguishes this process's
                                    #: manifest entries from prior runs

_MANIFEST: Dict[str, Dict] = {}     #: key -> entry (see manifest_add)
_MANIFEST_DIRTY = False

#: demand ledger: (cache, bucket) -> [hits, misses]
_DEMAND: Dict[Tuple[str, int], List[int]] = {}
#: (cache, bucket) pairs already seen (demanded or warmed): a fresh
#: demand against a seen pair is a hit — warmup converts misses to
#: hits, which is the whole point
_DEMAND_SEEN: Set[Tuple[str, int]] = set()
#: bound Prometheus children so the per-batch demand poke never
#: re-resolves labels
_DEMAND_CTR: Dict[Tuple[str, int, bool], object] = {}

#: warmers: program -> {variant: fn(bucket)} calling the real jitted
#: program (bounded per program; insertion-ordered, oldest evicted)
_WARMERS: Dict[str, Dict[str, Callable[[int], None]]] = {}
_WARMER_VARIANT_CAP = 8
#: (program, variant, bucket) triples already warmed (or attempted)
_WARMED: Set[Tuple[str, str, int]] = set()
_WARMUP_TOTAL = 0
_WARMUP_FAILED = 0

#: externally hinted (program, bucket) pairs awaiting pre-warm — the
#: predictive scheduler's PREDICTED demand (service/scheduler.py via
#: service/warmup.py note_hint), as opposed to the observed demand
#: ledger above.  A compile whose pair arrived ONLY through a hint is
#: counted under tpu_compile_hint_warmup_total, separate from the
#: admission-driven warmup counter.
_HINTS: Set[Tuple[str, int]] = set()
_HINTS_NOTED = 0
_HINT_COMPILES = 0

_TLS = threading.local()


# ---------------------------------------------------------------------------
# configure
# ---------------------------------------------------------------------------

def conf_fingerprint(conf) -> str:
    """Hash of every program-affecting conf plus the environment the
    traced HLO depends on (jax version, capacity floor, lattice
    geometry).  Observability/service/aot-bookkeeping groups are
    excluded: they never change a traced program, and including e.g.
    ``cacheDir`` itself would make every directory its own cold
    start."""
    import jax
    from ..columnar import column as _col
    from ..config import all_entries
    skip = ("spark.rapids.tpu.obs.", "spark.rapids.tpu.service.",
            "spark.rapids.tpu.compile.aot.", "spark.rapids.tpu.cache.",
            "spark.rapids.tpu.test.")
    h = hashlib.sha256()
    for e in all_entries():
        if any(e.key.startswith(p) for p in skip):
            continue
        h.update(f"{e.key}={conf.get(e)}\n".encode())
    lat = _LATTICE
    geom = (lat.min_rows, lat.ratio) if lat is not None else None
    h.update(f"jax={jax.__version__};min_cap={_col.MIN_CAPACITY};"
             f"lattice={geom}\n".encode())
    return h.hexdigest()[:16]


def configure(conf) -> None:
    """Apply the ``spark.rapids.tpu.compile.aot.*`` conf group
    (process-wide, last configure wins — the obs-plane discipline)."""
    global _ENABLED, _LATTICE, _CACHE_DIR, _CONF_FP
    from ..columnar import column as _col
    from ..config import (AOT_BUCKET_RATIO, AOT_CACHE_DIR, AOT_ENABLED,
                          AOT_XLA_CACHE)
    _ENABLED = bool(conf.get(AOT_ENABLED))
    if not _ENABLED:
        _LATTICE = None
        _col.set_bucket_fn(None)
        _CONF_FP = conf_fingerprint(conf)
        return
    _LATTICE = BucketLattice(_col.MIN_CAPACITY, int(conf.get(AOT_BUCKET_RATIO)))
    _col.set_bucket_fn(_LATTICE.bucket)
    _CONF_FP = conf_fingerprint(conf)
    d = str(conf.get(AOT_CACHE_DIR) or "").strip()
    if d and d != _CACHE_DIR:
        _CACHE_DIR = d
        os.makedirs(d, exist_ok=True)
        if bool(conf.get(AOT_XLA_CACHE)):
            _wire_xla_cache(d)
        _load_manifest()


def _wire_xla_cache(cache_dir: str) -> None:
    """Point the JAX persistent compilation cache at ``cache_dir`` with
    the persistence thresholds dropped so every engine program (CPU
    test programs compile in milliseconds) is written."""
    global _XLA_CACHE_WIRED
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_enable_compilation_cache", True)
        _XLA_CACHE_WIRED = True
    except Exception:
        # older jax without a flag: manifest bookkeeping still works,
        # first-calls just recompile (and are counted as compiles)
        _XLA_CACHE_WIRED = False


def lattice() -> Optional[BucketLattice]:
    return _LATTICE


def enabled() -> bool:
    return _ENABLED


# ---------------------------------------------------------------------------
# persistent manifest
# ---------------------------------------------------------------------------

def manifest_key(cache: str, signature) -> str:
    sig = "" if signature is None else str(signature)[:_SIG_MAX]
    return hashlib.sha1(
        f"{cache}|{sig}|{_CONF_FP}".encode()).hexdigest()


def _manifest_path() -> str:
    return os.path.join(_CACHE_DIR, _MANIFEST_NAME)


def _load_manifest() -> None:
    path = _manifest_path()
    entries: Dict[str, Dict] = {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
        if isinstance(raw, dict):
            entries = {k: v for k, v in raw.get("entries", {}).items()
                       if isinstance(v, dict)}
    except (OSError, ValueError):
        entries = {}
    with _LOCK:
        _MANIFEST.clear()
        _MANIFEST.update(entries)


def _save_manifest() -> None:
    """Atomic rewrite; payload built under the lock, I/O outside it."""
    global _MANIFEST_DIRTY
    if not _CACHE_DIR:
        return
    with _LOCK:
        if not _MANIFEST_DIRTY:
            return
        payload = {"version": 1, "entries": dict(_MANIFEST)}
        _MANIFEST_DIRTY = False
    tmp = _manifest_path() + f".{_RUN_ID}.tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=0, sort_keys=True)
        os.replace(tmp, _manifest_path())
    except OSError:
        pass


def manifest_add(key: str, cache: str, signature, bucket: Optional[int],
                 dur_ms: float) -> None:
    """Record a first-compile into the manifest (and persist it)."""
    global _MANIFEST_DIRTY
    if not _CACHE_DIR:
        return
    sig = "" if signature is None else str(signature)[:_SIG_MAX]
    with _LOCK:
        _MANIFEST[key] = {"cache": cache, "signature": sig,
                          "bucket": bucket, "dur_ms": round(dur_ms, 3),
                          "run": _RUN_ID}
        _MANIFEST_DIRTY = True
    _save_manifest()


def persistent_ready(key: Optional[str]) -> bool:
    """True when this first-call should be satisfied by the persistent
    cache: the manifest entry was written by an EARLIER process run
    (same program id, signature and conf fingerprint) and the XLA
    cache is wired to the same directory."""
    if key is None or not _XLA_CACHE_WIRED:
        return False
    with _LOCK:
        e = _MANIFEST.get(key)
    return e is not None and e.get("run") != _RUN_ID


def first_call_key(cache: str, signature) -> Optional[str]:
    """Manifest key for a fresh first-call, or None when persistence
    is inactive (no cacheDir)."""
    if not _CACHE_DIR or not _ENABLED:
        return None
    return manifest_key(cache, signature)


def manifest_entries() -> int:
    with _LOCK:
        return len(_MANIFEST)


# ---------------------------------------------------------------------------
# demand ledger
# ---------------------------------------------------------------------------

def note_demand(cache: str, capacity: int,
                rows: Optional[int] = None) -> None:
    """One program invocation at a bucketed capacity (called on the
    batch path next to each JIT cache).  A first demand against an
    unseen (program, bucket) pair is a *miss* — the call that makes
    jit's shape-keyed cache build the per-bucket executable; every
    later demand (including the first, when warmup pre-compiled the
    pair) is a *hit*.  Feeds the per-bucket hit/miss ledger, the
    Prometheus bucket-demand counter, the thread-local last-demand
    the compile-telemetry plane reads to attribute a compile to its
    bucket, and the cost plane's dispatch ledger (``rows`` is the
    effective row count when the call site's host already knows it —
    obs/costplane.py padding-waste accounting)."""
    try:
        # the cost plane is its own plane with its own conf: dispatch
        # accounting runs even when the AOT ledger below is disabled
        from ..obs import costplane as _costplane
        _costplane.note_dispatch(cache, capacity, rows)
    except Exception:  # noqa: BLE001 — observability never fails a call
        pass
    if not _ENABLED:
        return
    cap = int(capacity)
    _TLS.last = (cache, cap)
    hit = (cache, cap) in _DEMAND_SEEN
    if not hit:
        _DEMAND_SEEN.add((cache, cap))
    cell = _DEMAND.get((cache, cap))
    if cell is None:
        # racy-create is benign under the GIL: two writers produce two
        # short-lived lists, the dict keeps one, counts stay plausible
        cell = [0, 0]
        _DEMAND[(cache, cap)] = cell
    cell[0 if hit else 1] += 1
    ctr = _DEMAND_CTR.get((cache, cap, hit))
    if ctr is None:
        ctr = AOT_BUCKET_DEMAND.labels(cache=cache, bucket=str(cap),
                                       outcome="hit" if hit else "miss")
        _DEMAND_CTR[(cache, cap, hit)] = ctr
    ctr.inc()


def last_demand(cache: str) -> Optional[int]:
    """The bucket of this thread's most recent demand for ``cache``
    (how note_compile learns the bucket without widening every
    wrap_miss call site)."""
    last = getattr(_TLS, "last", None)
    if last is not None and last[0] == cache:
        return last[1]
    return None


def demand_snapshot() -> Dict[str, List[int]]:
    """``{"cache|bucket": [hits, misses]}`` copy (sessions diff this
    around a query for the per-query bucket table)."""
    return {f"{c}|{b}": list(cell) for (c, b), cell in list(_DEMAND.items())}


def demanded_buckets() -> List[int]:
    """Every bucket observed in the demand mix (ascending)."""
    return sorted({b for (_c, b) in list(_DEMAND.keys())})


def note_hint(program: str, bucket: int) -> bool:
    """Predicted demand from the admission scheduler: mark a
    (program, bucket) pair worth pre-warming even though no tenant
    query has demanded it yet.  Pairs the demand ledger already saw
    are dropped (nothing left to predict).  Returns True when the
    hint was accepted."""
    if program not in BUCKETED_PROGRAMS:
        raise ValueError(f"unregistered bucketed program: {program}")
    global _HINTS_NOTED
    if not _ENABLED:
        return False
    pair = (program, int(bucket))
    if pair in _DEMAND_SEEN:
        return False
    _HINTS.add(pair)
    _HINTS_NOTED += 1
    return True


# ---------------------------------------------------------------------------
# warmup registry
# ---------------------------------------------------------------------------

def register_warmer(program: str, warm: Callable[[int], None],
                    variant: str = "default") -> None:
    """Register (or refresh) a warmer for one ``program`` variant (a
    distinct cache key — expression structure, dtype tuple): a
    closure that calls the real jitted callable with dummy arrays
    padded to a given bucket capacity.  Calling is the point — jit's
    call-path cache only populates on a real invocation.  Variants
    are bounded per program (oldest evicted), so warmup targets the
    recent program mix."""
    if program not in BUCKETED_PROGRAMS:
        raise ValueError(f"unregistered bucketed program: {program}")
    variants = _WARMERS.setdefault(program, {})
    variants.pop(variant, None)
    variants[variant] = warm
    while len(variants) > _WARMER_VARIANT_CAP:
        oldest = next(iter(variants))
        del variants[oldest]


def in_warmup() -> bool:
    return bool(getattr(_TLS, "warmup", False))


class warmup_scope:
    """Marks the calling thread as the warmup pseudo-victim: compiles
    recorded inside land under origin='warmup', never on a tenant
    query's inline_compile_ms (obs/compile_watch.py)."""

    def __enter__(self):
        self._prev = getattr(_TLS, "warmup", False)
        _TLS.warmup = True
        return self

    def __exit__(self, *exc):
        _TLS.warmup = self._prev
        return False


def warm_candidates() -> List[Tuple[str, str, int]]:
    """(program, variant, bucket) triples worth pre-compiling: every
    registered warmer crossed with every bucket in the observed
    demand mix, minus triples already warmed.  The cross product is
    the admission-aware prediction: engine pipelines run all their
    programs over the same batch buckets, so a bucket demanded by one
    program is imminent demand for the others."""
    buckets = demanded_buckets()
    out = []
    for program in sorted(_WARMERS.keys()):
        # hinted buckets extend the observed mix per program: the
        # scheduler predicted this pair, so pre-warm it even though
        # the ledger has never seen the bucket
        hinted = sorted({b for (p, b) in _HINTS if p == program})
        merged = sorted(set(buckets) | set(hinted))
        for variant in list(_WARMERS[program].keys()):
            for b in merged:
                if (program, variant, b) not in _WARMED:
                    out.append((program, variant, b))
    return out


def warm_one(program: str, variant: str, bucket: int) -> bool:
    """Run one warmer under the warmup scope.  The triple is marked
    warmed regardless of outcome so a failing warmer cannot
    retry-storm the background thread.  A successful warm also marks
    the (program, bucket) pair demand-seen: the next tenant demand
    against it counts as a hit."""
    global _WARMUP_TOTAL, _WARMUP_FAILED, _HINT_COMPILES
    warm = _WARMERS.get(program, {}).get(variant)
    _WARMED.add((program, variant, bucket))
    # hint-origin = the pair reached the candidate set ONLY through a
    # scheduler prediction (never organically demanded)
    hint_origin = (program, bucket) in _HINTS and \
        (program, bucket) not in _DEMAND
    _HINTS.discard((program, bucket))
    if warm is None:
        return False
    try:
        with warmup_scope():
            warm(bucket)
    except Exception:
        _WARMUP_FAILED += 1
        flight.record(flight.EV_COMPILE, "warmup_failed", bucket, 0)
        return False
    _WARMUP_TOTAL += 1
    _DEMAND_SEEN.add((program, bucket))
    if hint_origin:
        _HINT_COMPILES += 1
        AOT_HINT_COMPILES.labels(program=program).inc()
    else:
        AOT_WARMUP_COMPILES.labels(program=program).inc()
    flight.record(flight.EV_COMPILE, "warmup", bucket, 1)
    return True


def warm_missing(max_compiles: int) -> int:
    """Pre-compile up to ``max_compiles`` missing (program, variant,
    bucket) triples; returns how many warmers ran successfully."""
    done = 0
    for program, variant, bucket in warm_candidates():
        if done >= max_compiles:
            break
        if warm_one(program, variant, bucket):
            done += 1
    return done


def warmup_total() -> int:
    return _WARMUP_TOTAL


# ---------------------------------------------------------------------------
# surfaces
# ---------------------------------------------------------------------------

def stats_section() -> Dict:
    """The ``aot`` section of ``Service.stats().snapshot()``."""
    lat = _LATTICE
    with _LOCK:
        manifest_n = len(_MANIFEST)
    demand = {f"{c}|{b}": {"hit": cell[0], "miss": cell[1]}
              for (c, b), cell in sorted(_DEMAND.items())}
    return {
        "enabled": _ENABLED,
        "lattice": {"min_rows": lat.min_rows, "ratio": lat.ratio}
        if lat is not None else None,
        "cache_dir": _CACHE_DIR or None,
        "xla_cache_wired": _XLA_CACHE_WIRED,
        "conf_fingerprint": _CONF_FP,
        "manifest_entries": manifest_n,
        "demand": demand,
        "warmers": {p: len(v) for p, v in sorted(_WARMERS.items())},
        "warmup_compiles": _WARMUP_TOTAL,
        "warmup_failed": _WARMUP_FAILED,
        "hints_noted": _HINTS_NOTED,
        "hints_pending": len(_HINTS),
        "hint_compiles": _HINT_COMPILES,
    }


def reset() -> None:
    """Test hook: drop ledger/warmer/manifest state and detach the
    lattice (keeps the process usable for unbucketed baselines)."""
    global _ENABLED, _LATTICE, _CACHE_DIR, _XLA_CACHE_WIRED, _CONF_FP
    global _WARMUP_TOTAL, _WARMUP_FAILED, _MANIFEST_DIRTY
    global _HINTS_NOTED, _HINT_COMPILES
    from ..columnar import column as _col
    with _LOCK:
        _MANIFEST.clear()
        _MANIFEST_DIRTY = False
    _DEMAND.clear()
    _DEMAND_SEEN.clear()
    _DEMAND_CTR.clear()
    _WARMERS.clear()
    _WARMED.clear()
    _HINTS.clear()
    _HINTS_NOTED = 0
    _HINT_COMPILES = 0
    _WARMUP_TOTAL = 0
    _WARMUP_FAILED = 0
    _ENABLED = True
    _LATTICE = None
    _CACHE_DIR = ""
    _XLA_CACHE_WIRED = False
    _CONF_FP = ""
    _col.set_bucket_fn(None)
    _TLS.last = None
    _TLS.warmup = False
