"""Superstage lowering: classify each physical operator by HOW it
participates in a carved superstage's single-dispatch execution.

The compiler does not re-trace operators into one literal XLA graph —
every member already runs its hot path as ONE jitted program
(exec/fused.py row-op chains, the join's fused probe+compact+gather,
the aggregate's fused grouping core, the partitioner's fused split).
What kept a stage at one host round trip PER OPERATOR was the host
count pull between them.  Lowering therefore assigns each member a
*dispatch strategy* describing how its program chains device-resident
onto the next:

PROGRAM   the member's whole batch path is one traced program whose
          output row count stays on device (project/filter via
          FusedEval, staged chains, the speculative unique-match join,
          the fused aggregate core, the lazy sort/limit heads).
CHAIN     a count-preserving transport: it forwards batches (and any
          speculative fit flags) without forcing a host value
          (partition coalesce, top-n propagation).
BARRIER   a member that legitimately forces the fused flush — the
          single host round trip the stage is allowed (the shuffle
          map-side finalize, the collect staging).
BOUNDARY  not a member: superstages end here (exchanges, scans, row
          transitions, mesh execs).  A BOUNDARY found where a member
          was expected is an *ejection*: the region splits around it
          and the operator keeps its own per-operator dispatch.
"""
from __future__ import annotations

from typing import List, Tuple

from ..exec.base import PhysicalPlan

PROGRAM = "program"
CHAIN = "chain"
BARRIER = "barrier"
BOUNDARY = "boundary"


def classify(node: PhysicalPlan) -> str:
    """Dispatch strategy for one operator (see module doc)."""
    from ..exec import tpu_basic as TB
    from ..exec import tpu_aggregate as TA
    from ..exec import tpu_join as TJ
    from ..exec import tpu_sort as TS
    from ..exec.staged import TpuStagedCompute
    if isinstance(node, (TB.TpuProject, TB.TpuFilter, TpuStagedCompute,
                         TA.TpuHashAggregate, TJ.TpuHashJoinBase,
                         TS.TpuSort, TB.TpuLocalLimit,
                         TB.TpuGlobalLimit)):
        return PROGRAM
    if isinstance(node, TS.TpuTopN):
        return CHAIN
    if isinstance(node, TB.TpuCoalesceBatches):
        # coalesce reads host counts to pack batches: inside a stage it
        # acts as the stage's one permitted flush
        return BARRIER
    from ..exec.exchange import TpuCoalescePartitions
    if isinstance(node, TpuCoalescePartitions):
        return CHAIN
    # everything else — exchanges, scans, row transitions, windows,
    # unions, mesh/distributed execs, CPU fallbacks — delimits (or
    # ejects from) the superstage
    return BOUNDARY


def is_member(node: PhysicalPlan) -> bool:
    return classify(node) is not BOUNDARY


def lower_region(members: List[PhysicalPlan]
                 ) -> List[Tuple[str, str]]:
    """(node name, strategy) per member, region order — the stage's
    dispatch plan, surfaced by TpuSuperstage explain and the PV-STAGE
    verifier."""
    return [(m.name, classify(m)) for m in members]


def barrier_count(lowering: List[Tuple[str, str]]) -> int:
    """How many one-flush barriers the lowered stage retains (the
    per-stage flush budget PV-STAGE and ci/compile_smoke.py check
    against)."""
    return sum(1 for _n, s in lowering if s == BARRIER)
