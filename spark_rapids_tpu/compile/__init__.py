"""Superstage compiler: one device dispatch per exchange-delimited
pipeline stage, with device-resident handoff between member operators.

Planner post-pass (runs after analysis/plan_verify.py):

- :mod:`.lower` classifies each operator's dispatch strategy
  (PROGRAM / CHAIN / BARRIER / BOUNDARY);
- :mod:`.carve` splits the plan into maximal exchange-delimited member
  regions, arms the members' sync-free paths, and wraps each region in
  an :class:`~..exec.superstage.TpuSuperstage`;
- the PV-STAGE verifier pass (analysis/plan_verify.py) re-checks the
  carved tree.

Conf: ``spark.rapids.tpu.sql.superstage`` (off switch),
``...superstage.minOps``, ``...superstage.speculativeJoin``.
"""
from .carve import carve_plan
from .lower import (BARRIER, BOUNDARY, CHAIN, PROGRAM, barrier_count,
                    classify, is_member, lower_region)

__all__ = [
    "carve_plan", "classify", "is_member", "lower_region",
    "barrier_count", "PROGRAM", "CHAIN", "BARRIER", "BOUNDARY",
]
