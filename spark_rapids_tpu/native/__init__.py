"""Native (C++) runtime components, loaded via ctypes.

Reference parity (SURVEY.md §2.10): the reference's native layer is
external C++ (RMM arena, pinned staging, nvcomp, UCX).  Here the native
host arena backs the HOST spill tier; it is built on first use with g++
and cached next to the source.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRCS = [os.path.join(_DIR, "spill_arena.cpp"),
         os.path.join(_DIR, "block_codec.cpp")]
_SO = os.path.join(_DIR, "libspark_rapids_tpu_native.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _build() -> str:
    if os.path.exists(_SO) and all(
            os.path.getmtime(_SO) >= os.path.getmtime(s) for s in _SRCS):
        return _SO
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", *_SRCS, "-o",
           _SO + ".tmp"]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(_SO + ".tmp", _SO)
    return _SO


def load() -> ctypes.CDLL:
    """Build (if needed) and load the native library."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(_build())
        lib.arena_create.restype = ctypes.c_void_p
        lib.arena_create.argtypes = [ctypes.c_int64]
        lib.arena_alloc.restype = ctypes.c_int64
        lib.arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.arena_free.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.arena_base.restype = ctypes.c_void_p
        lib.arena_base.argtypes = [ctypes.c_void_p]
        lib.arena_used.restype = ctypes.c_int64
        lib.arena_used.argtypes = [ctypes.c_void_p]
        lib.arena_capacity.restype = ctypes.c_int64
        lib.arena_capacity.argtypes = [ctypes.c_void_p]
        lib.arena_num_free_blocks.restype = ctypes.c_int64
        lib.arena_num_free_blocks.argtypes = [ctypes.c_void_p]
        lib.arena_write_file.restype = ctypes.c_int
        lib.arena_write_file.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                         ctypes.c_int64, ctypes.c_char_p]
        lib.arena_read_file.restype = ctypes.c_int
        lib.arena_read_file.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                        ctypes.c_int64, ctypes.c_char_p]
        lib.arena_destroy.argtypes = [ctypes.c_void_p]
        lib.tplz_max_compressed_size.restype = ctypes.c_size_t
        lib.tplz_max_compressed_size.argtypes = [ctypes.c_size_t]
        lib.tplz_compress.restype = ctypes.c_size_t
        lib.tplz_compress.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                      ctypes.c_void_p, ctypes.c_size_t]
        lib.tplz_decompress.restype = ctypes.c_size_t
        lib.tplz_decompress.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                        ctypes.c_void_p, ctypes.c_size_t]
        _lib = lib
        return lib


def tplz_compress(data: bytes) -> bytes:
    """Native LZ block compression (the nvcomp-LZ4 role)."""
    lib = load()
    cap = lib.tplz_max_compressed_size(len(data))
    out = ctypes.create_string_buffer(cap)
    n = lib.tplz_compress(data, len(data), out, cap)
    if n == 0 and len(data):
        raise RuntimeError("tplz compression failed")
    return out.raw[:n]


def tplz_decompress(data: bytes, uncompressed_size: int) -> bytes:
    lib = load()
    out = ctypes.create_string_buffer(max(uncompressed_size, 1))
    n = lib.tplz_decompress(data, len(data), out, uncompressed_size)
    if n != uncompressed_size:
        raise RuntimeError(
            f"tplz decompression produced {n} bytes, "
            f"expected {uncompressed_size}")
    return out.raw[:n]


class HostArena:
    """Python wrapper over the native slab arena.

    Buffers are exposed as zero-copy numpy views into the slab, so
    device->host staging is a single jax device_get into arena memory.
    """

    def __init__(self, capacity: int):
        import numpy as np
        self._lib = load()
        self._h = self._lib.arena_create(capacity)
        if not self._h:
            raise MemoryError(f"cannot create {capacity}-byte host arena")
        base = self._lib.arena_base(self._h)
        self._np = np
        self._view = (ctypes.c_uint8 * self.capacity).from_address(base)

    @property
    def capacity(self) -> int:
        return self._lib.arena_capacity(self._h)

    @property
    def used(self) -> int:
        return self._lib.arena_used(self._h)

    @property
    def num_free_blocks(self) -> int:
        return self._lib.arena_num_free_blocks(self._h)

    def alloc(self, nbytes: int) -> int:
        off = self._lib.arena_alloc(self._h, nbytes)
        if off < 0:
            raise MemoryError(
                f"host arena exhausted ({self.used}/{self.capacity})")
        return off

    def free(self, offset: int):
        self._lib.arena_free(self._h, offset)

    def view(self, offset: int, nbytes: int):
        """Zero-copy numpy uint8 view of [offset, offset+nbytes)."""
        arr = self._np.frombuffer(self._view, dtype=self._np.uint8,
                                  count=nbytes, offset=offset)
        return arr

    def write_file(self, offset: int, nbytes: int, path: str):
        rc = self._lib.arena_write_file(self._h, offset, nbytes,
                                       path.encode())
        if rc != 0:
            raise OSError(rc, f"spill write failed: {path}")

    def read_file(self, offset: int, nbytes: int, path: str):
        rc = self._lib.arena_read_file(self._h, offset, nbytes,
                                      path.encode())
        if rc != 0:
            raise OSError(rc, f"spill read failed: {path}")

    def close(self):
        if self._h:
            self._lib.arena_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
