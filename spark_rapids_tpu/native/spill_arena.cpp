// Host staging + spill arena.
//
// Role parity (SURVEY.md §2.10): the reference's native memory layer is
// RMM (device pool) + pinned host staging buffers + RapidsDiskStore file
// IO.  On TPU, XLA/PJRT owns HBM, so the native layer owns the *host*
// side: a slab arena for staged/spilled buffers (no per-buffer malloc
// churn, stable addresses for zero-copy numpy views) and streaming
// spill-file IO for the disk tier.
//
// C API (ctypes-friendly), all thread-safe:
//   arena_create(capacity)                -> handle
//   arena_alloc(h, nbytes)               -> offset (or -1)
//   arena_free(h, offset)
//   arena_base(h)                        -> void* slab base
//   arena_used(h) / arena_capacity(h)
//   arena_write_file(h, off, n, path)    -> 0/errno  (spill to disk)
//   arena_read_file(h, off, n, path)     -> 0/errno  (unspill)
//   arena_destroy(h)
//
// Allocation strategy: first-fit free list with coalescing on free —
// the same shape as RMM's arena allocator (SURVEY.md §2.3), simple and
// predictable for large columnar buffers.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cerrno>
#include <map>
#include <mutex>
#include <new>

namespace {

struct Arena {
  uint8_t* slab = nullptr;
  int64_t capacity = 0;
  int64_t used = 0;
  // offset -> size of free block (ordered for coalescing)
  std::map<int64_t, int64_t> free_blocks;
  // offset -> size of live allocations
  std::map<int64_t, int64_t> live;
  std::mutex mu;
};

constexpr int64_t kAlign = 64;

int64_t align_up(int64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

}  // namespace

extern "C" {

void* arena_create(int64_t capacity) {
  Arena* a = new (std::nothrow) Arena();
  if (a == nullptr) return nullptr;
  a->capacity = align_up(capacity);
  a->slab = static_cast<uint8_t*>(std::malloc(a->capacity));
  if (a->slab == nullptr) {
    delete a;
    return nullptr;
  }
  a->free_blocks[0] = a->capacity;
  return a;
}

int64_t arena_alloc(void* handle, int64_t nbytes) {
  Arena* a = static_cast<Arena*>(handle);
  int64_t need = align_up(nbytes > 0 ? nbytes : 1);
  std::lock_guard<std::mutex> lock(a->mu);
  for (auto it = a->free_blocks.begin(); it != a->free_blocks.end(); ++it) {
    if (it->second >= need) {
      int64_t off = it->first;
      int64_t remaining = it->second - need;
      a->free_blocks.erase(it);
      if (remaining > 0) a->free_blocks[off + need] = remaining;
      a->live[off] = need;
      a->used += need;
      return off;
    }
  }
  return -1;  // caller must spill (DeviceMemoryEventHandler contract)
}

void arena_free(void* handle, int64_t offset) {
  Arena* a = static_cast<Arena*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->live.find(offset);
  if (it == a->live.end()) return;
  int64_t size = it->second;
  a->live.erase(it);
  a->used -= size;
  // insert and coalesce with neighbours
  auto ins = a->free_blocks.emplace(offset, size).first;
  if (ins != a->free_blocks.begin()) {
    auto prev = std::prev(ins);
    if (prev->first + prev->second == ins->first) {
      prev->second += ins->second;
      a->free_blocks.erase(ins);
      ins = prev;
    }
  }
  auto next = std::next(ins);
  if (next != a->free_blocks.end() &&
      ins->first + ins->second == next->first) {
    ins->second += next->second;
    a->free_blocks.erase(next);
  }
}

void* arena_base(void* handle) {
  return static_cast<Arena*>(handle)->slab;
}

int64_t arena_used(void* handle) {
  Arena* a = static_cast<Arena*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  return a->used;
}

int64_t arena_capacity(void* handle) {
  return static_cast<Arena*>(handle)->capacity;
}

int64_t arena_num_free_blocks(void* handle) {
  Arena* a = static_cast<Arena*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  return static_cast<int64_t>(a->free_blocks.size());
}

int arena_write_file(void* handle, int64_t offset, int64_t nbytes,
                     const char* path) {
  Arena* a = static_cast<Arena*>(handle);
  if (offset < 0 || offset + nbytes > a->capacity) return EINVAL;
  FILE* f = std::fopen(path, "wb");
  if (f == nullptr) return errno;
  size_t written = std::fwrite(a->slab + offset, 1,
                               static_cast<size_t>(nbytes), f);
  int rc = (written == static_cast<size_t>(nbytes)) ? 0 : EIO;
  if (std::fclose(f) != 0 && rc == 0) rc = errno;
  return rc;
}

int arena_read_file(void* handle, int64_t offset, int64_t nbytes,
                    const char* path) {
  Arena* a = static_cast<Arena*>(handle);
  if (offset < 0 || offset + nbytes > a->capacity) return EINVAL;
  FILE* f = std::fopen(path, "rb");
  if (f == nullptr) return errno;
  size_t got = std::fread(a->slab + offset, 1,
                          static_cast<size_t>(nbytes), f);
  int rc = (got == static_cast<size_t>(nbytes)) ? 0 : EIO;
  std::fclose(f);
  return rc;
}

void arena_destroy(void* handle) {
  Arena* a = static_cast<Arena*>(handle);
  std::free(a->slab);
  delete a;
}

}  // extern "C"
