// Native LZ-class block codec for shuffle buffers.
//
// Reference parity (SURVEY.md §2.10 item 4): the reference compresses
// shuffle tables with nvcomp's batched LZ4 behind the
// TableCompressionCodec SPI (TableCompressionCodec.scala:378,
// NvcompLZ4CompressionCodec.scala).  This is the TPU build's native
// equivalent: a byte-oriented LZ77 with an LZ4-style token stream,
// tuned for the host-side shuffle bounce path (we own both wire ends,
// so the format is our own — "tplz1").
//
// Format per token:
//   1 byte   token = (literal_len:4 | match_len:4)
//   varint   extra literal length  (if literal_len == 15)
//   N bytes  literals
//   2 bytes  little-endian match offset (0 => end of stream, no match)
//   varint   extra match length    (if match_len == 15)
// Matches are >= 4 bytes within a 64 KiB window.
//
// Build: g++ -O2 -fPIC -shared (see native/__init__.py).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int kMinMatch = 4;
constexpr uint32_t kHashBits = 16;
constexpr uint32_t kWindow = 65535;

inline uint32_t hash4(const uint8_t* p) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return (v * 2654435761u) >> (32 - kHashBits);
}

inline uint8_t* put_varint(uint8_t* dst, size_t v) {
    while (v >= 255) {
        *dst++ = 255;
        v -= 255;
    }
    *dst++ = static_cast<uint8_t>(v);
    return dst;
}

inline const uint8_t* get_varint(const uint8_t* src, const uint8_t* end,
                                 size_t* v) {
    size_t out = 0;
    while (src < end) {
        uint8_t b = *src++;
        out += b;
        if (b != 255) break;
    }
    *v = out;
    return src;
}

}  // namespace

extern "C" {

// worst case: all literals + token/length overhead
size_t tplz_max_compressed_size(size_t n) {
    return n + n / 255 + 16;
}

// returns compressed size, or 0 if dst_cap is too small
size_t tplz_compress(const uint8_t* src, size_t n, uint8_t* dst,
                     size_t dst_cap) {
    if (dst_cap < tplz_max_compressed_size(n)) return 0;
    std::vector<int64_t> table(1u << kHashBits, -1);
    uint8_t* out = dst;
    size_t pos = 0;
    size_t lit_start = 0;

    auto emit = [&](size_t match_pos, size_t match_len, size_t offset) {
        size_t lit_len = match_pos - lit_start;
        size_t ml = match_len ? match_len - kMinMatch : 0;
        uint8_t token =
            static_cast<uint8_t>((lit_len < 15 ? lit_len : 15) << 4 |
                                 (ml < 15 ? ml : 15));
        *out++ = token;
        if (lit_len >= 15) out = put_varint(out, lit_len - 15);
        std::memcpy(out, src + lit_start, lit_len);
        out += lit_len;
        uint16_t off16 = static_cast<uint16_t>(offset);
        std::memcpy(out, &off16, 2);
        out += 2;
        if (match_len && ml >= 15) out = put_varint(out, ml - 15);
    };

    if (n >= kMinMatch + 1) {
        while (pos + kMinMatch < n) {
            uint32_t h = hash4(src + pos);
            int64_t cand = table[h];
            table[h] = static_cast<int64_t>(pos);
            if (cand >= 0 && pos - cand <= kWindow &&
                std::memcmp(src + cand, src + pos, kMinMatch) == 0) {
                size_t len = kMinMatch;
                size_t max_len = n - pos;
                while (len < max_len &&
                       src[cand + len] == src[pos + len]) {
                    ++len;
                }
                emit(pos, len, pos - cand);
                lit_start = pos + len;
                // index a few positions inside the match for chains
                size_t step = len > 64 ? 8 : 1;
                for (size_t i = pos + 1; i + kMinMatch < lit_start;
                     i += step) {
                    table[hash4(src + i)] = static_cast<int64_t>(i);
                }
                pos = lit_start;
            } else {
                ++pos;
            }
        }
    }
    // trailing literals with offset 0 terminator
    {
        size_t lit_len = n - lit_start;
        uint8_t token = static_cast<uint8_t>(
            (lit_len < 15 ? lit_len : 15) << 4);
        *out++ = token;
        if (lit_len >= 15) out = put_varint(out, lit_len - 15);
        std::memcpy(out, src + lit_start, lit_len);
        out += lit_len;
        uint16_t zero = 0;
        std::memcpy(out, &zero, 2);
        out += 2;
    }
    return static_cast<size_t>(out - dst);
}

// returns decompressed size, or 0 on malformed input / small dst
size_t tplz_decompress(const uint8_t* src, size_t n, uint8_t* dst,
                       size_t dst_cap) {
    const uint8_t* in = src;
    const uint8_t* end = src + n;
    uint8_t* out = dst;
    uint8_t* out_end = dst + dst_cap;
    while (in < end) {
        uint8_t token = *in++;
        size_t lit_len = token >> 4;
        size_t match_len = token & 0xF;
        if (lit_len == 15) {
            size_t extra;
            in = get_varint(in, end, &extra);
            lit_len += extra;
        }
        if (in + lit_len > end || out + lit_len > out_end) return 0;
        std::memcpy(out, in, lit_len);
        in += lit_len;
        out += lit_len;
        if (in + 2 > end) return 0;
        uint16_t off16;
        std::memcpy(&off16, in, 2);
        in += 2;
        if (off16 == 0) {
            // stream terminator (trailing-literal token)
            break;
        }
        size_t ml = match_len;
        if (ml == 15) {
            size_t extra;
            in = get_varint(in, end, &extra);
            ml += extra;
        }
        ml += kMinMatch;
        if (out - dst < off16 || out + ml > out_end) return 0;
        const uint8_t* from = out - off16;
        // overlapping copies must go byte-by-byte
        for (size_t i = 0; i < ml; ++i) out[i] = from[i];
        out += ml;
    }
    return static_cast<size_t>(out - dst);
}

}  // extern "C"
