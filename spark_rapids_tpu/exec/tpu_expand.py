"""TPU expand operator (grouping sets) — reference: GpuExpandExec.scala.

Each input row is replicated once per projection list; implemented as a
tiled gather (row i of projection p reads input row i), fully static.
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ..columnar.batch import ColumnarBatch
from ..columnar.column import bucket_capacity
from ..expr import core as ec
from ..plan.logical import Expand
from .base import PhysicalPlan, NUM_OUTPUT_ROWS
from .tpu_basic import TpuExec


class TpuExpand(TpuExec):
    def __init__(self, logical: Expand, child: PhysicalPlan):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def execute(self):
        child_schema = self.children[0].output_schema
        bound = [[e.bind(child_schema) for e in proj]
                 for proj in self.logical.projections]

        def run(part):
            for batch in part:
                for proj in bound:
                    cols = [ec.eval_as_column(e, batch) for e in proj]
                    out = ColumnarBatch(self.output_schema, cols,
                                        batch.num_rows)
                    self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
                    yield out
        return [run(p) for p in self.children[0].execute()]
