"""Morsel-parallel intra-query execution: a bounded per-process worker
pool plus a prefetching partition-drain primitive.

Reference pattern: the accelerator gets much of its throughput from
keeping the device busy — multithreaded readers, async spill, the
GpuSemaphore arbitrating concurrent tasks per device (SURVEY.md §1).
Morsel-driven parallelism (Leis et al., SIGMOD 2014) is the engine-side
analogue: instead of one thread draining a query's partitions serially,
a small pool pulls N partition iterators concurrently so host-side work
(arrow staging, partition-split prep, spill/unspill, speculative
redo) overlaps in-flight device compute.

``drain_parallel(parts, sink, ...)`` is the single drain primitive the
serial loops were rewritten onto (shuffle map-side materialization and
broadcast build in exec/exchange.py, the collect loop in
api/session.py).  Contract:

- **deterministic order** — the consumer receives ``(partition_index,
  item)`` in exactly the order the serial loop would have produced:
  partition 0's items first, in pull order, then partition 1's, ...
  Since every item is computed by the same functional device program
  regardless of which thread pulled it, output is bit-identical to the
  serial drain (tested in tests/test_pipeline.py).
- **bounded buffering** — each partition prefetches at most
  ``pipelinePrefetchDepth`` items ahead of the consumer, and the drain
  as a whole parks producers past a byte budget
  (``pipelineBufferBytes``, capped at drain start to half the free
  device tier so prefetch cannot out-buffer the arena).  The head
  partition may always buffer one item when it has nothing queued —
  without that bypass a full budget would deadlock against a consumer
  blocked on the head.
- **semaphore discipline** — workers hold the DeviceSemaphore only
  around the pull + sink (the device-dispatch region), release between
  items, ``release_all()`` on exit, and attribute their blocked-wait
  time to the owning query's token (``sem_wait_ms``).  A pool worker
  never parks on the semaphore unboundedly: past ``_SEM_TRY_S`` it
  hands its partition back (``_UNSTARTED``) and moves on, so a claimed
  partition cannot wedge behind permits pinned elsewhere.
- **liveness under nesting** — pool workers themselves may hit a nested
  drain (a collect pull forces a shuffle materialization).  The
  consumer never depends on the pool: when it reaches a partition no
  worker has claimed, it produces that partition inline
  (consumer-assist), so an exhausted pool degrades to the serial drain
  instead of deadlocking.  The permit handback above keeps this true
  even when IDLE workers grab a nested drain's partitions while every
  permit is pinned by the outer drain: they time out, hand back, and
  the nested consumer (holding its permit re-entrantly) assists.
- **cancellation** — producers and the consumer run cooperative cancel
  checkpoints; a mid-drain cancel (or any producer error) fails the
  drain once, wakes everybody, and the workers unwind — semaphore
  permits released, buffered batches dropped.

Observability: every stage records allocation-free ``EV_PIPELINE``
flight events, drains export queue-depth/buffered-bytes/busy-worker
gauges + a per-batch busy histogram + an overlap-ratio gauge
(obs/registry.py), and the stall watchdog aggregates pipeline-worker
flight progress into the owning query via ``worker_idents()``.
"""
from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Set

from ..obs import flight as _flight
from ..obs.registry import (PIPELINE_BATCHES, PIPELINE_DRAINS,
                            PIPELINE_OVERLAP_RATIO,
                            PIPELINE_WORKER_BUSY_SECONDS)
from ..service.cancellation import (CancelToken, cancel_checkpoint,
                                    current_token, observe, query_context)

# drain-stage name constants for EV_PIPELINE records (interned: the
# recorder is always-on, so call sites pass these + plain ints only)
_N_DISPATCH = "dispatch"
_N_PULL = "pull"
_N_INLINE = "inline"
_N_PART_DONE = "part_done"
_N_DRAIN_END = "drain_end"
_N_HANDBACK = "sem_handback"

#: producer/consumer park-poll period; every wakeup re-runs the cancel
#: checkpoint, so cancellation latency is bounded by it
_POLL_S = 0.05

#: how long a pool worker tries for a device permit before handing its
#: partition back to the drain.  Normal permit waits are per-batch
#: (milliseconds — producers release between items); a wait this long
#: means the permits are pinned by threads that may themselves be
#: waiting on THIS drain (a nested drain under an outer pull region),
#: so the worker must yield the partition to the consumer instead of
#: parking forever
_SEM_TRY_S = 0.25

# partition drain states
_UNSTARTED, _RUNNING, _DONE = 0, 1, 2


def _auto_parallelism() -> int:
    return min(4, os.cpu_count() or 1)


# ---------------------------------------------------------------------------
# process-wide introspection (gauges, watchdog, service stats)
# ---------------------------------------------------------------------------

_INTROSPECT_LOCK = threading.Lock()
_LIVE_DRAINS: Set["_ParallelDrain"] = set()
#: pipeline-worker thread ident -> query_id currently served (watchdog
#: progress attribution: a pipelined query's heartbeat lives on these
#: threads while its service worker blocks in the drain consumer)
_ACTIVE_WORKERS: Dict[int, Optional[str]] = {}


def buffered_items() -> int:
    """Prefetched items buffered across all live drains (gauge)."""
    with _INTROSPECT_LOCK:
        drains = list(_LIVE_DRAINS)
    return sum(d._buffered for d in drains)


def buffered_bytes() -> int:
    """Bytes of prefetched items buffered across all live drains."""
    with _INTROSPECT_LOCK:
        drains = list(_LIVE_DRAINS)
    return sum(d._buffered_bytes for d in drains)


def busy_workers() -> int:
    """Pool workers currently serving a drain."""
    with _INTROSPECT_LOCK:
        return len(_ACTIVE_WORKERS)


def worker_idents(query_id: Optional[str]) -> List[int]:
    """Thread idents of pool workers currently serving ``query_id`` —
    read by the stall watchdog to fold pipeline-worker flight progress
    into the owning query's heartbeat."""
    with _INTROSPECT_LOCK:
        return [ident for ident, qid in _ACTIVE_WORKERS.items()
                if qid == query_id]


def pool_stats() -> Dict:
    """Pool + drain occupancy for ``Service.stats()``."""
    pool = PipelinePool._instance
    with _INTROSPECT_LOCK:
        live = len(_LIVE_DRAINS)
        busy = len(_ACTIVE_WORKERS)
    out = {"threads": 0, "queued": 0, "busy": busy, "live_drains": live,
           "buffered_items": buffered_items(),
           "buffered_bytes": buffered_bytes()}
    if pool is not None:
        out.update(pool.stats())
        out["busy"] = busy
    return out


# ---------------------------------------------------------------------------
# the pool
# ---------------------------------------------------------------------------

class PipelinePool:
    """Per-process bounded worker pool serving drain requests.

    Threads are created lazily up to the largest parallelism any drain
    has requested (conf ``spark.rapids.tpu.exec.pipelineParallelism``)
    and then persist, parked on the task queue.  The park — a plain
    ``queue.get()`` — happens with **no engine lock held**; LOCK001's
    queue-receive rule allowlists this file for exactly that intentional
    idle wait (analysis/lint.py ``_LOCK001_QUEUE_GET_ALLOWLIST``).

    A task is "serve this drain": the worker claims unstarted
    partitions from the drain until none remain.  Tasks enqueued for a
    drain that already finished (the consumer drained it inline) no-op
    immediately, so stale entries cannot wedge the pool.
    """

    _instance: Optional["PipelinePool"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._tasks: "queue.SimpleQueue" = queue.SimpleQueue()
        self._threads: List[threading.Thread] = []
        self._seq = 0

    @classmethod
    def get(cls) -> "PipelinePool":
        inst = cls._instance
        if inst is None:
            with cls._instance_lock:
                inst = cls._instance
                if inst is None:
                    inst = cls._instance = PipelinePool()
        return inst

    def dispatch(self, fn: Callable[[], None], copies: int, size: int):
        """Enqueue ``copies`` runs of ``fn``, growing the pool to at
        most ``size`` threads (never shrinks: the largest request wins)."""
        with self._lock:
            while len(self._threads) < max(1, size):
                self._seq += 1
                t = threading.Thread(
                    target=self._worker_loop,
                    name=f"tpu-pipeline-{self._seq}", daemon=True)
                self._threads.append(t)
                t.start()
        for _ in range(copies):
            self._tasks.put(fn)

    def _worker_loop(self):
        while True:
            # the pool's idle state: parked on the task queue, holding
            # no lock (LOCK001 queue-receive allowlist, see class doc)
            fn = self._tasks.get()
            if fn is None:
                return
            try:
                fn()
            except BaseException:
                # a drain records its own failure and re-raises it on
                # the consumer thread; the pool thread must survive
                pass

    def stats(self) -> Dict:
        with self._lock:
            threads = len(self._threads)
        return {"threads": threads, "queued": self._tasks.qsize()}


# ---------------------------------------------------------------------------
# one drain
# ---------------------------------------------------------------------------

def _item_nbytes(item) -> int:
    """Best-effort size of a produced item for the byte budget.

    Sinks return containers, not just batches — the shuffle map sink
    yields ``(batch, (sorted_batch, counts))`` and pieces may arrive in
    lists — so every common container recurses; an unsized leaf counts
    as 0 (best effort, never a raise)."""
    if isinstance(item, (tuple, list)):
        return sum(_item_nbytes(x) for x in item)
    if isinstance(item, dict):
        return sum(_item_nbytes(v) for v in item.values())
    try:
        nb = getattr(item, "nbytes", None)
        if nb is None:
            return 0
        if callable(nb):
            return int(nb())
        return int(nb)
    except Exception:
        return 0


class _ParallelDrain:
    """State of one in-flight parallel drain: per-partition prefetch
    queues + one condition, claimed by pool workers lowest-index-first,
    consumed in partition order."""

    def __init__(self, parts: List, sink, depth: int, budget: int,
                 token: Optional[CancelToken], conf, label: str):
        self._parts = [iter(p) for p in parts]
        self._sink = sink
        self._depth = max(1, depth)
        self._budget = max(1, budget)
        self._token = token
        self._conf = conf
        self._label = label
        n = len(self._parts)
        self._n = n
        self._cond = threading.Condition()
        self._queues: List[deque] = [deque() for _ in range(n)]
        self._state = [_UNSTARTED] * n
        self._head = 0
        self._buffered = 0
        self._buffered_bytes = 0
        self._error: Optional[BaseException] = None
        self._closed = False
        self._busy_ns = 0
        self._t0 = time.perf_counter_ns()

    # -- producer side (pool workers + consumer-assist) --------------------

    def _stalled(self, pid: int) -> bool:
        """Backpressure predicate (under self._cond)."""
        if len(self._queues[pid]) >= self._depth:
            return True
        if self._buffered_bytes >= self._budget:
            # head-partition bypass: when the consumer's current
            # partition has nothing queued, its producer may always add
            # one more item — otherwise a full budget (held by later
            # partitions' buffers) would park the only producer the
            # consumer can make progress on
            return not (pid == self._head and not self._queues[pid])
        return False

    def _claim_next(self, skip=()) -> Optional[int]:
        with self._cond:
            if self._closed or self._error is not None:
                return None
            for pid in range(self._head, self._n):
                if self._state[pid] == _UNSTARTED and pid not in skip:
                    self._state[pid] = _RUNNING
                    return pid
        return None

    def _fail(self, exc: BaseException):
        with self._cond:
            if self._error is None:
                self._error = exc
            self._cond.notify_all()

    @staticmethod
    def _try_acquire_bounded(sem) -> bool:
        """Permit acquire for pool workers: bounded at ``_SEM_TRY_S``,
        cancel-checkpointed each poll.  False = hand the partition back."""
        deadline = time.monotonic() + _SEM_TRY_S
        while True:
            cancel_checkpoint()
            if sem.try_acquire(timeout=_POLL_S):
                return True
            if time.monotonic() >= deadline:
                return False

    def _produce_loop(self, pid: int, sem, inline: bool) -> bool:
        """Pull ``pid``'s iterator until exhausted (or one item when
        ``inline`` — the consumer produces exactly what it needs).

        Returns False when the partition was handed back instead of
        finished: a pool worker that cannot obtain a device permit
        within ``_SEM_TRY_S`` reverts ``pid`` to ``_UNSTARTED`` and
        yields it — every permit may be pinned by threads that are
        themselves waiting on this drain (nested drains), so only the
        consumer, which holds its permit re-entrantly across the nested
        pull, is guaranteed able to produce.  Handover is safe at any
        point: the iterator keeps its position in ``self._parts`` and
        exactly one owner pulls it at a time (the state machine under
        ``self._cond``)."""
        it = self._parts[pid]
        while True:
            with self._cond:
                while not self._closed and self._error is None and \
                        self._stalled(pid):
                    self._cond.wait(_POLL_S)
                    cancel_checkpoint()
                if self._closed or self._error is not None:
                    return True
            cancel_checkpoint()
            # DeviceSemaphore held only around the device-dispatch
            # region (the pull + sink), released between items so
            # prefetch never starves concurrent queries of permits.
            # The consumer (inline) may block — everyone else's
            # progress funnels through it — but pool workers must not:
            # they hand back on timeout (see docstring)
            if inline:
                sem.acquire_if_necessary()
            elif not self._try_acquire_bounded(sem):
                with self._cond:
                    if self._closed or self._error is not None:
                        return True
                    self._state[pid] = _UNSTARTED
                    self._cond.notify_all()
                _flight.record(_flight.EV_PIPELINE, _N_HANDBACK, a=pid)
                return False
            t0 = time.perf_counter_ns()
            produced = True
            try:
                try:
                    item = next(it)
                except StopIteration:
                    produced = False
                else:
                    if self._sink is not None:
                        item = self._sink(item)
            finally:
                sem.release()
            dt = time.perf_counter_ns() - t0
            if not produced:
                with self._cond:
                    self._state[pid] = _DONE
                    self._busy_ns += dt
                    self._cond.notify_all()
                _flight.record(_flight.EV_PIPELINE, _N_PART_DONE, a=pid)
                return True
            nb = _item_nbytes(item)
            PIPELINE_WORKER_BUSY_SECONDS.observe(dt / 1e9)
            _flight.record(_flight.EV_PIPELINE,
                           _N_INLINE if inline else _N_PULL, a=pid, b=nb)
            with self._cond:
                self._queues[pid].append((item, nb))
                self._buffered += 1
                self._buffered_bytes += nb
                self._busy_ns += dt
                self._cond.notify_all()
            if inline:
                return True

    def _serve(self):
        """Pool-worker entry: claim partitions until none remain."""
        ident = threading.get_ident()
        qid = self._token.query_id if self._token is not None else None
        with _INTROSPECT_LOCK:
            _ACTIVE_WORKERS[ident] = qid
        from ..config import set_active
        from ..memory.arena import DeviceManager
        sem = DeviceManager.get().semaphore
        try:
            # the caller's conf (incl. per-query service overlays) and
            # token travel to the worker: sinks read the right batch
            # sizes, checkpoints see the right cancellation state
            set_active(self._conf, thread_only=True)
            # transfer-guard parity with the collect thread: JAX's
            # guard is thread-local, so every pool worker arms its own
            # scoped disallow (analysis/residency.py)
            from ..analysis import residency as _residency
            with _residency.guard_scope(self._conf), \
                    query_context(self._token):
                try:
                    handed_back = set()
                    while True:
                        pid = self._claim_next(handed_back)
                        if pid is None:
                            break
                        if not self._produce_loop(pid, sem,
                                                  inline=False):
                            # handed back for want of a device permit:
                            # never re-claim it here (re-claiming would
                            # shut the consumer-assist window back out)
                            # — the consumer or a luckier worker takes
                            # it over
                            handed_back.add(pid)
                finally:
                    # ownership unwind + per-query wait attribution:
                    # permits this worker still holds are returned and
                    # its blocked-acquire time lands on the query token
                    sem.release_all()
                    waited = sem.pop_wait_ns()
                    if waited:
                        observe("sem_wait_ms", waited / 1e6)
        except BaseException as e:
            self._fail(e)
        finally:
            with _INTROSPECT_LOCK:
                _ACTIVE_WORKERS.pop(ident, None)

    # -- consumer side -----------------------------------------------------

    def results(self):
        from ..memory.arena import DeviceManager
        sem = DeviceManager.get().semaphore
        inline_owned: Set[int] = set()
        try:
            for pid in range(self._n):
                while True:
                    item = None
                    got = done = claim_inline = False
                    with self._cond:
                        if self._head != pid:
                            self._head = pid
                            self._cond.notify_all()
                        q = self._queues[pid]
                        if q:
                            item, nb = q.popleft()
                            self._buffered -= 1
                            self._buffered_bytes -= nb
                            got = True
                            self._cond.notify_all()
                        elif self._error is not None:
                            raise self._error
                        elif self._state[pid] == _DONE:
                            done = True
                        elif self._state[pid] == _UNSTARTED or \
                                pid in inline_owned:
                            self._state[pid] = _RUNNING
                            inline_owned.add(pid)
                            claim_inline = True
                        else:
                            self._cond.wait(_POLL_S)
                            cancel_checkpoint()
                    if got:
                        PIPELINE_BATCHES.labels(source="worker").inc()
                        yield pid, item
                    elif done:
                        break
                    elif claim_inline:
                        # consumer-assist: no worker claimed this
                        # partition (pool exhausted or a nested drain)
                        # — produce it inline so the drain always makes
                        # progress without depending on the pool
                        self._produce_loop(pid, sem, inline=True)
                        with self._cond:
                            q = self._queues[pid]
                            if q:
                                item, nb = q.popleft()
                                self._buffered -= 1
                                self._buffered_bytes -= nb
                                got = True
                        if got:
                            PIPELINE_BATCHES.labels(source="inline").inc()
                            yield pid, item
        finally:
            self._close()

    def _close(self):
        with self._cond:
            self._closed = True
            for q in self._queues:
                q.clear()
            self._buffered = 0
            self._buffered_bytes = 0
            self._cond.notify_all()
            busy_ns = self._busy_ns
        wall = time.perf_counter_ns() - self._t0
        ratio = busy_ns / wall if wall > 0 else 0.0
        PIPELINE_OVERLAP_RATIO.set(ratio)
        _flight.record(_flight.EV_PIPELINE, _N_DRAIN_END, a=self._n,
                       b=int(ratio * 1000))


# ---------------------------------------------------------------------------
# the drain primitive
# ---------------------------------------------------------------------------

def resolve_parallelism(conf=None) -> int:
    """The effective pipeline parallelism under ``conf`` (0 = auto)."""
    from ..config import (PIPELINE_ENABLED, PIPELINE_PARALLELISM,
                          get_active)
    conf = conf if conf is not None else get_active()
    if not conf.get(PIPELINE_ENABLED):
        return 1
    par = int(conf.get(PIPELINE_PARALLELISM))
    return par if par > 0 else _auto_parallelism()


def _effective_budget(conf) -> int:
    from ..config import PIPELINE_BUFFER_BYTES
    budget = int(conf.get(PIPELINE_BUFFER_BYTES))
    # spill-aware cap: buffered prefetch is not yet catalog-registered
    # (not spillable), so never plan to buffer past half the free
    # device tier — the catalog can spill registered peers to make
    # room, but headroom is the honest guard
    try:
        from ..memory.catalog import BufferCatalog
        cat = BufferCatalog.get()
        headroom = max(64 << 20,
                       (cat.device_limit - cat.device_bytes) // 2)
        budget = min(budget, headroom)
    except Exception:
        pass
    return budget


def drain_parallel(parts: Iterable, sink: Optional[Callable] = None, *,
                   parallelism: Optional[int] = None,
                   prefetch_depth: Optional[int] = None,
                   byte_budget: Optional[int] = None,
                   token: Optional[CancelToken] = None,
                   label: str = "drain"):
    """Drain ``parts`` (partition iterators), yielding
    ``(partition_index, item)`` in deterministic partition order.

    ``sink`` maps each pulled item on the producing thread (under the
    DeviceSemaphore) — put per-batch device/host staging work there so
    it overlaps across partitions.  Defaults come from the active conf;
    ``token`` defaults to the calling thread's CancelToken.  With
    parallelism 1 (or a single partition) this is exactly the serial
    loop the call site replaced — no threads, no buffering.
    """
    from ..config import PIPELINE_PREFETCH_DEPTH, get_active
    parts = [p for p in parts]
    conf = get_active()
    if token is None:
        token = current_token()
    par = parallelism if parallelism is not None \
        else resolve_parallelism(conf)
    par = min(par, len(parts))
    if par <= 1 or len(parts) <= 1:
        return _drain_serial(parts, sink)
    depth = prefetch_depth if prefetch_depth is not None \
        else int(conf.get(PIPELINE_PREFETCH_DEPTH))
    budget = byte_budget if byte_budget is not None \
        else _effective_budget(conf)
    return _drain_pipelined(parts, sink, par, depth, budget, token,
                            conf, label)


def _drain_serial(parts: List, sink):
    PIPELINE_DRAINS.labels(mode="serial").inc()
    for pid, part in enumerate(parts):
        for item in part:
            cancel_checkpoint()
            yield pid, (sink(item) if sink is not None else item)


def _drain_pipelined(parts: List, sink, par: int, depth: int,
                     budget: int, token, conf, label: str):
    drain = _ParallelDrain(parts, sink, depth, budget, token, conf,
                           label)
    PIPELINE_DRAINS.labels(mode="parallel").inc()
    _flight.record(_flight.EV_PIPELINE, _N_DISPATCH, a=len(parts),
                   b=par)
    with _INTROSPECT_LOCK:
        _LIVE_DRAINS.add(drain)
    try:
        PipelinePool.get().dispatch(drain._serve, copies=par, size=par)
        for out in drain.results():
            yield out
    finally:
        with _INTROSPECT_LOCK:
            _LIVE_DRAINS.discard(drain)
