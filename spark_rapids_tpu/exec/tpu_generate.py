"""TPU generate operator: explode/posexplode (+outer variants).

Reference: GpuGenerateExec.scala (498 LoC) — explode via cuDF
``explode``/``explode_position`` kernels.  TPU-first: the output row plan
is pure offsets arithmetic (kernels/lists.py explode_offsets/
explode_indices); the single dynamic scalar (output row count) is pulled
to host to choose the power-of-two output bucket, then one gather per
column materializes the result — the same two-phase pattern as filter.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..columnar import dtypes as T
from ..columnar.schema import Field, Schema
from ..columnar.column import Column, bucket_capacity
from ..columnar.batch import ColumnarBatch
from ..expr import core as ec
from ..kernels import lists as lk
from .base import PhysicalPlan, NUM_OUTPUT_ROWS, NUM_OUTPUT_BATCHES, \
    OP_TIME, timed
from .tpu_basic import TpuExec


class TpuGenerate(TpuExec):
    def __init__(self, logical, child: PhysicalPlan):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def execute(self):
        gen = self.logical.generator
        child_schema = self.children[0].output_schema
        bound = gen.children[0].bind(child_schema)
        out_schema = self.output_schema
        pos = gen.pos
        outer = gen.outer

        def run(part):
            for batch in part:
                with timed(self.metrics[OP_TIME], self):
                    out = self._generate(batch, bound, pos, outer,
                                         out_schema)
                self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
                self.metrics[NUM_OUTPUT_BATCHES] += 1
                yield out
        return [run(p) for p in self.children[0].execute()]

    def _generate(self, batch: ColumnarBatch, bound, pos: bool, outer: bool,
                  out_schema: Schema) -> ColumnarBatch:
        fast = self._literal_array_fast_path(batch, bound, pos, out_schema)
        if fast is not None:
            return fast
        lcol = ec.eval_as_column(bound, batch)
        out_offsets, total = lk.explode_offsets(
            lcol.offsets, lcol.validity, batch.num_rows, outer)
        from ..analysis import residency  # lazy: avoids import cycle
        with residency.declared_transfer(site="size_probe"):
            n = int(total)
        out_cap = bucket_capacity(max(1, n))
        row_idx, elem_idx, posv, elem_valid, live = lk.explode_indices(
            lcol.offsets, lcol.validity, out_offsets, out_cap)
        cols = [c.gather(row_idx).mask_validity(live)
                for c in batch.columns]
        if pos:
            # outer's synthetic null row has a null position (Spark
            # PosExplode outer semantics)
            cols.append(Column(T.INT32, posv.astype(jnp.int32),
                               elem_valid if outer else live))
        gen_col = lcol.elements.gather(elem_idx).mask_validity(elem_valid)
        if gen_col.capacity != out_cap:
            gen_col = gen_col.with_capacity(out_cap, n)
        cols.append(gen_col)
        return ColumnarBatch(out_schema, cols, n)

    def _literal_array_fast_path(self, batch: ColumnarBatch, bound,
                                 pos: bool, out_schema: Schema):
        """explode(array(lit...)) is a pure k-way row repeat: out[j] =
        in[j // k], value[j] = consts[j % k].  The reference's mortgage
        ETL leans on exactly this idiom ("explode ... is actually
        slightly more efficient than a cross join",
        MortgageSpark.scala:271) — no offsets machinery, one gather.
        """
        from ..expr.collections import CreateArray
        if not isinstance(bound, CreateArray) or not bound.children or \
                not all(isinstance(c, ec.Literal) for c in bound.children):
            return None
        values = [c.value for c in bound.children]
        if any(v is None for v in values):
            return None
        k = len(values)
        n = batch.num_rows * k
        out_cap = bucket_capacity(max(1, n))
        j = jnp.arange(out_cap, dtype=jnp.int32)
        row_idx = j // k
        posv = j % k
        live = j < n
        cols = [c.gather(row_idx).mask_validity(live)
                for c in batch.columns]
        if pos:
            cols.append(Column(T.INT32, posv, live))
        et = bound.dtype().element_type
        consts = Column.from_numpy(values, dtype=et,
                                   capacity=bucket_capacity(k))
        gen = consts.gather(posv).mask_validity(live)
        cols.append(gen)
        return ColumnarBatch(out_schema, cols, n)

    def _node_string(self):
        g = self.logical.generator
        kind = "posexplode" if g.pos else "explode"
        if g.outer:
            kind += "_outer"
        return f"TpuGenerate[{kind}]"
