"""Mesh-distributed shuffled hash join: the whole join as ONE SPMD program.

Reference role: GpuShuffledHashJoinBase.scala:28 +
GpuShuffleExchangeExec.scala:176 — the reference realizes a distributed
equi-join as [hash exchange left] + [hash exchange right] + local hash
join per partition, with the exchange riding UCX.  On a TPU mesh the
same pipeline is a single jitted shard_map program: both sides shard
across devices, rows hash-route by canonical key words to owner devices
via ``lax.all_to_all`` (co-partitioning both sides on the SAME hash),
and each owner runs the local sort + binary-search probe + static-shape
cumsum expansion (kernels/join.py — already fully device-pure).  XLA
schedules the ICI collectives; no transport code on the hot path.

Row-producing: the program returns the gathered output COLUMNS (left
payload at probe indices, right payload at build indices), per-device
match totals, and an overflow flag.  Join types inner / left outer /
semi / anti lower to count surgery exactly like the in-process join.
Overflow (receive region or output capacity) falls back loudly to the
in-process join on the materialized inputs — never silent truncation.

Enabled by ``spark.rapids.tpu.shuffle.mode=mesh`` with >1 device, equi
conditions, and fixed-width key/payload dtypes (strings route later).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..columnar import dtypes as T
from ..columnar.schema import Schema
from ..columnar.column import Column, bucket_capacity
from ..columnar.batch import ColumnarBatch, concat_batches
from ..expr import core as ec
from ..kernels import canon
from ..kernels import join as join_k
from ..obs import compile_watch as _compile_watch
from ..obs import timeline as _timeline
from ..obs.registry import compile_cache_event
from ..parallel.mesh import MIX, _route_to_owners, make_mesh
from .base import PhysicalPlan, JOIN_TIME, NUM_OUTPUT_ROWS, timed
from .tpu_basic import TpuExec
from .tpu_mesh_aggregate import _SINGLE_WORD

_AXIS = "data"

_MESH_JOIN_TYPES = ("inner", "left", "right", "semi", "anti")


def mesh_join_supported(p, n_devices: int) -> bool:
    """Mesh-joinable: equi condition, inner/left/right/semi/anti, and
    fixed-width OUTPUT columns.  Keys may be STRINGS (multi-word): key
    words are computed eagerly per batch with statically-unified widths
    and routed through the all_to_all as plain u64 arrays; only the
    PAYLOAD columns must be fixed-width (a string key that is also
    projected into the output still blocks, via out_ts)."""
    if n_devices < 2 or p.condition is not None or not p.left_keys:
        return False
    if p.join_type not in _MESH_JOIN_TYPES:
        return False
    try:
        key_ts = [e.dtype() for e in p.left_keys] + \
                 [e.dtype() for e in p.right_keys]
        out_ts = [f.dtype for f in p.schema]
    except (ValueError, NotImplementedError):
        return False
    if not all(isinstance(t, _SINGLE_WORD) or t == T.STRING
               for t in key_ts):
        return False
    required = getattr(p, "required_out", None)
    if required is not None:
        out_ts = [f.dtype for f in p.schema if f.name in set(required)]
    return all(isinstance(t, _SINGLE_WORD) for t in out_ts)


class TpuMeshShuffledJoin(TpuExec):
    _PROGRAM_CACHE: dict = {}

    def __init__(self, logical, left: PhysicalPlan, right: PhysicalPlan,
                 mesh: Optional[Mesh] = None):
        super().__init__(left, right)
        self.logical = logical
        self.mesh = mesh

    @property
    def output_schema(self) -> Schema:
        required = getattr(self.logical, "required_out", None)
        if required is None:
            return self.logical.schema
        req = set(required)
        return Schema([f for f in self.logical.schema.fields
                       if f.name in req])

    def _node_string(self):
        n = self.mesh.devices.size if self.mesh is not None else "?"
        return (f"TpuMeshShuffledJoin[{self.logical.join_type}, "
                f"{n} devices]")

    # ------------------------------------------------------------------
    def _program(self, mesh: Mesh, jt: str, key_groups, l_dts, r_dts,
                 emit_right: bool):
        """``key_groups``: static word-count of each key column's canon
        encoding (1 rank word + value words; strings contribute several
        value words).  Key words are computed EAGERLY per batch (string
        kernels need host-known widths) and routed as plain u64 inputs,
        so the shard program itself is dtype-agnostic about keys."""
        from ..shims import get_shard_map
        shard_map = get_shard_map()
        key = (id(mesh), jt, tuple(key_groups),
               tuple(d.name for d in l_dts), tuple(d.name for d in r_dts),
               emit_right)
        hit = TpuMeshShuffledJoin._PROGRAM_CACHE.get(key)
        compile_cache_event("mesh_join", hit is not None)
        if hit is not None:
            return hit
        n_dev = mesh.devices.size
        nw = sum(key_groups)
        rank_pos = []
        off = 0
        for g in key_groups:
            rank_pos.append(off)
            off += g

        def side_route(words, datas, valids, live):
            words = list(words)
            words[0] = jnp.where(live, words[0], jnp.uint64(2))
            h = jnp.zeros_like(words[0])
            for w in words:
                h = (h ^ w) * jnp.uint64(MIX)
            owner = (h >> jnp.uint64(33)) % jnp.uint64(n_dev)
            owner = jnp.where(live, owner.astype(jnp.int32), n_dev)
            payload = list(words) + list(datas) + list(valids)
            fills = ([jnp.uint64(2)] + [jnp.uint64(0)] * (len(words) - 1)
                     + [jnp.zeros((), d.dtype)[()] for d in datas]
                     + [False] * len(valids))
            routed, rlive, ovf = _route_to_owners(
                owner, payload, fills, n_dev, _AXIS, slack=2)
            rwords = [jnp.asarray(w) for w in routed[:len(words)]]
            rwords[0] = jnp.where(rlive, rwords[0], jnp.uint64(2))
            nd = len(datas)
            rdatas = routed[len(words):len(words) + nd]
            rvalids = [v & rlive for v in routed[len(words) + nd:]]
            return rwords, rdatas, rvalids, rlive, ovf

        def step(*flat):
            pos = 0
            lwords = list(flat[pos:pos + nw]); pos += nw
            ld = list(flat[pos:pos + len(l_dts)]); pos += len(l_dts)
            lv = list(flat[pos:pos + len(l_dts)]); pos += len(l_dts)
            llive = flat[pos]; pos += 1
            rwords = list(flat[pos:pos + nw]); pos += nw
            rd = list(flat[pos:pos + len(r_dts)]); pos += len(r_dts)
            rv = list(flat[pos:pos + len(r_dts)]); pos += len(r_dts)
            rlive = flat[pos]

            lw, lrd, lrv, lrl, ovf_l = side_route(lwords, ld, lv, llive)
            rw, rrd, rrv, rrl, ovf_r = side_route(rwords, rd, rv, rlive)

            # local join on the owner shard: sorted build + binary probe
            bt = join_k.build(rw)
            lo = join_k._bsearch(bt.sorted_words, lw, upper=False)
            hi = join_k._bsearch(bt.sorted_words, lw, upper=True)
            counts = (hi - lo).astype(jnp.int32)
            # null keys never match: each key group leads with its
            # null/range rank word, rank 1 == valid
            usable = lrl
            for rp in rank_pos:
                usable = usable & (lw[rp] == jnp.uint64(1))
            counts = jnp.where(usable, counts, 0)

            if jt == "inner":
                counts_eff = counts
            elif jt == "left":
                counts_eff = jnp.where(lrl & (counts == 0), 1, counts)
            elif jt == "semi":
                counts_eff = jnp.where(counts > 0, 1, 0)
            else:   # anti: live probe rows with no match (incl. null key)
                counts_eff = jnp.where(lrl & (counts == 0), 1, 0)

            pcap = lw[0].shape[0]
            out_cap = pcap * 2
            pc, build_idx, live_out, total = join_k.expand_matches(
                lo, counts_eff, bt.perm, out_cap)
            ovf_out = total > out_cap
            matched_slot = jnp.take(counts, pc) > 0

            # live output slots are contiguous at the front by
            # construction (expand fills t = 0..total-1)
            out_flat = []
            for d, v in zip(lrd, lrv):
                out_flat.append(jnp.take(d, pc, mode="clip"))
                out_flat.append(jnp.take(v, pc, mode="clip") & live_out)
            if emit_right:
                for d, v in zip(rrd, rrv):
                    out_flat.append(jnp.take(d, build_idx, mode="clip"))
                    out_flat.append(jnp.take(v, build_idx, mode="clip")
                                    & live_out & matched_slot)
            ovf = ovf_l | ovf_r | ovf_out
            out_flat.append(total.astype(jnp.int32)[None])
            out_flat.append(ovf[None])
            return tuple(out_flat)

        n_in = nw + 2 * len(l_dts) + 1 + nw + 2 * len(r_dts) + 1
        n_out = 2 * len(l_dts) + (2 * len(r_dts) if emit_right else 0) + 2
        fn = jax.jit(shard_map(
            step, mesh=mesh,
            in_specs=tuple(P(_AXIS) for _ in range(n_in)),
            out_specs=tuple(P(_AXIS) for _ in range(n_out))))
        # perf plane: each dispatch window is busy time on every mesh
        # device; the first call (jit compile) lands in compile_watch
        # with the cache key (minus the unstable id(mesh)) as signature
        fn = _timeline.device_busy_wrap(
            fn, tuple(str(d.id) for d in mesh.devices.ravel()))
        fn = _compile_watch.wrap_miss("mesh_join", fn, str(key[1:]))
        TpuMeshShuffledJoin._PROGRAM_CACHE[key] = fn
        return fn

    # ------------------------------------------------------------------
    def _gather_side(self, child, keys, n_dev):
        batches = [b for part in child.execute() for b in part]
        batches = [b for b in batches if b.num_rows > 0]
        if not batches:
            batches = [ColumnarBatch.empty(child.output_schema)]
        batch = concat_batches(batches) if len(batches) > 1 else batches[0]
        schema = batch.schema
        key_cols = [ec.eval_as_column(e.bind(schema), batch)
                    for e in keys]
        out_cols = list(batch.columns)
        cap = batch.capacity
        # capacities are bucket powers of two and mesh sizes are powers
        # of two, so the shard constraint holds (same invariant as
        # TpuMeshAggregate.execute)
        assert cap % n_dev == 0, (cap, n_dev)
        live = np.zeros(cap, bool)
        live[:batch.num_rows] = True
        return batch, key_cols, out_cols, jnp.asarray(live)

    def execute(self):
        p = self.logical
        mesh = self.mesh or make_mesh()
        n_dev = mesh.devices.size
        jt = p.join_type
        # RIGHT outer = LEFT outer with the sides swapped: the probe
        # side is the row-preserving one, so probe on the original
        # RIGHT and reorder output columns back afterwards
        swapped = jt == "right"
        prog_jt = "left" if swapped else jt
        emit_right = prog_jt in ("inner", "left")

        def run():
            from ..kernels import strings as skern
            if swapped:
                lbatch, lkeys, lcols, llive = self._gather_side(
                    self.children[1], p.right_keys, n_dev)
                rbatch, rkeys, rcols, rlive = self._gather_side(
                    self.children[0], p.left_keys, n_dev)
            else:
                lbatch, lkeys, lcols, llive = self._gather_side(
                    self.children[0], p.left_keys, n_dev)
                rbatch, rkeys, rcols, rlive = self._gather_side(
                    self.children[1], p.right_keys, n_dev)
            # only the REQUIRED output columns ride the all_to_all
            # (a string join key the parent projects away is words-only)
            required = getattr(p, "required_out", None)
            if required is not None:
                req = set(required)
                lcols_f, rcols_f = [], []
                for c, f in zip(lcols, lbatch.schema.fields):
                    if f.name in req:
                        lcols_f.append(c)
                for c, f in zip(rcols, rbatch.schema.fields):
                    if f.name in req:
                        rcols_f.append(c)
                lcols, rcols = lcols_f, rcols_f
            # key WORDS are computed eagerly with statically-unified
            # string widths (strings are multi-word; the program routes
            # words, not key columns)
            str_widths = []
            for lk, rk in zip(lkeys, rkeys):
                if lk.dtype == T.STRING:
                    w = max(skern.needed_key_words(lk, lbatch.num_rows),
                            skern.needed_key_words(rk, rbatch.num_rows))
                    str_widths.append(w)
                else:
                    str_widths.append(None)
            lparts = [canon.batch_key_words([c], lbatch.num_rows,
                                            str_words=[w])
                      for c, w in zip(lkeys, str_widths)]
            rparts = [canon.batch_key_words([c], rbatch.num_rows,
                                            str_words=[w])
                      for c, w in zip(rkeys, str_widths)]
            key_groups = tuple(len(ws) for ws in lparts)
            assert key_groups == tuple(len(ws) for ws in rparts), \
                (key_groups, [len(ws) for ws in rparts])
            lwords = [w for ws in lparts for w in ws]
            rwords = [w for ws in rparts for w in ws]
            l_dts = [c.dtype for c in lcols]
            r_dts = [c.dtype for c in rcols]

            sharding = NamedSharding(mesh, P(_AXIS))
            flat = (list(lwords) + [c.data for c in lcols] +
                    [c.validity for c in lcols] + [llive] +
                    list(rwords) + [c.data for c in rcols] +
                    [c.validity for c in rcols] + [rlive])
            from ..analysis import residency  # lazy: avoids import cycle
            with residency.declared_transfer(site="mesh_reshard"):
                flat = [jax.device_put(a, sharding) for a in flat]

            program = self._program(mesh, prog_jt, key_groups,
                                    l_dts, r_dts, emit_right)
            from ..compile import aot as _aot
            _aot.note_demand("mesh_join", flat[0].shape[0])
            with timed(self.metrics[JOIN_TIME], self):
                out = program(*flat)
            from ..analysis import residency  # lazy: avoids import cycle
            with residency.declared_transfer(site="mesh_collect"):
                overflowed = bool(np.asarray(out[-1]).any())
            if overflowed:
                yield from self._fallback(lbatch, rbatch, swapped)
                return
            with residency.declared_transfer(site="mesh_collect"):
                totals = np.asarray(out[-2]).reshape(-1)
            per = out[0].shape[0] // n_dev
            out_schema = self.output_schema
            # program output layout: probe payload then build payload;
            # output schema wants original-left columns then
            # original-right — for a swapped (right outer) run the
            # build side (original left) comes FIRST in the schema
            probe_slots = [2 * i for i in range(len(lcols))]
            build_slots = [2 * len(lcols) + 2 * i
                           for i in range(len(rcols))] if emit_right \
                else []
            col_slots = (build_slots + probe_slots) if swapped else \
                (probe_slots + build_slots)
            for d in range(n_dev):
                nr = int(totals[d])
                if nr == 0:
                    continue
                lo_ = d * per
                seg = bucket_capacity(max(nr, 1))
                idx = jnp.arange(seg) + lo_
                cols = []
                for f, slot in zip(out_schema, col_slots):
                    data = jnp.take(out[slot], idx, mode="clip")
                    valid = jnp.take(out[slot + 1], idx, mode="clip") \
                        & (jnp.arange(seg) < nr)
                    cols.append(Column(f.dtype, data, valid))
                ob = ColumnarBatch(out_schema, cols, nr)
                self.metrics[NUM_OUTPUT_ROWS] += nr
                yield ob
        return [run()]

    # ------------------------------------------------------------------
    def _fallback(self, lbatch: ColumnarBatch, rbatch: ColumnarBatch,
                  swapped: bool = False):
        """Receive/output region overflowed: rerun via the in-process
        join on the materialized inputs (loud fallback, never silent)."""
        from .tpu_join import TpuShuffledHashJoin
        if swapped:
            # the swapped (right outer) run gathered sides reversed
            lbatch, rbatch = rbatch, lbatch

        class _One(PhysicalPlan):
            columnar = True

            def __init__(self, b):
                super().__init__()
                self._b = b

            @property
            def output_schema(self):
                return self._b.schema

            def execute(self):
                return [iter([self._b])]

        j = TpuShuffledHashJoin(
            self.logical, _One(lbatch), _One(rbatch),
            # the in-process join realizes RIGHT outer by building on
            # the LEFT (planner contract: build opposite the preserved
            # side)
            build_right=self.logical.join_type != "right")
        out_schema = self.output_schema
        prune = len(out_schema) != len(self.logical.schema)
        for part in j.execute():
            for b in part:
                if prune:
                    keep = {f.name for f in out_schema.fields}
                    cols = [c for c, f in zip(b.columns, b.schema.fields)
                            if f.name in keep]
                    b = ColumnarBatch(out_schema, cols, b.rows_lazy)
                yield b


# ---------------------------------------------------------------------------
# program audit registration (analysis/program_audit.py)
# ---------------------------------------------------------------------------

def _audit_specs():
    from ..analysis.program_audit import AuditSpec

    def _build():
        import jax
        import numpy as np
        from ..parallel.mesh import make_mesh
        # 2-device mesh: 1 device degenerates the splitter /
        # routing structure (empty splitter gathers); the test harness
        # and ci/audit.py force >=2 host devices via XLA_FLAGS
        mesh = make_mesh(2)
        j = object.__new__(TpuMeshShuffledJoin)
        fn = j._program(mesh, "inner", (2,), (T.INT64,), (T.INT64,),
                        True)
        cap = 64
        w = jax.ShapeDtypeStruct((cap,), np.uint64)
        d = jax.ShapeDtypeStruct((cap,), np.int64)
        v = jax.ShapeDtypeStruct((cap,), np.bool_)
        # flat layout: lwords + l payload (data, valid) + l live, then
        # the same for the right side
        args = (w, w, d, v, v, w, w, d, v, v)
        return fn, args, {}

    return [AuditSpec(
        "mesh_join", "mesh_join", _build,
        notes="2-device mesh, inner join, one int64 payload per side",
        budgets={"gather": 66, "scatter": 24, "transpose": 4,
                 "sort": 8})]
