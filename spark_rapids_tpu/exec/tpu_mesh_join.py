"""Mesh-distributed shuffled hash join: the whole join as ONE SPMD program.

Reference role: GpuShuffledHashJoinBase.scala:28 +
GpuShuffleExchangeExec.scala:176 — the reference realizes a distributed
equi-join as [hash exchange left] + [hash exchange right] + local hash
join per partition, with the exchange riding UCX.  On a TPU mesh the
same pipeline is a single jitted shard_map program: both sides shard
across devices, rows hash-route by canonical key words to owner devices
via ``lax.all_to_all`` (co-partitioning both sides on the SAME hash),
and each owner runs the local sort + binary-search probe + static-shape
cumsum expansion (kernels/join.py — already fully device-pure).  XLA
schedules the ICI collectives; no transport code on the hot path.

Row-producing: the program returns the gathered output COLUMNS (left
payload at probe indices, right payload at build indices), per-device
match totals, and an overflow flag.  Join types inner / left outer /
semi / anti lower to count surgery exactly like the in-process join.
Overflow (receive region or output capacity) falls back loudly to the
in-process join on the materialized inputs — never silent truncation.

Enabled by ``spark.rapids.tpu.shuffle.mode=mesh`` with >1 device, equi
conditions, and fixed-width key/payload dtypes (strings route later).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..columnar import dtypes as T
from ..columnar.schema import Schema
from ..columnar.column import Column, bucket_capacity
from ..columnar.batch import ColumnarBatch, concat_batches
from ..expr import core as ec
from ..kernels import canon
from ..kernels import join as join_k
from ..parallel.mesh import MIX, _route_to_owners, make_mesh
from .base import PhysicalPlan, JOIN_TIME, NUM_OUTPUT_ROWS, timed
from .tpu_basic import TpuExec
from .tpu_mesh_aggregate import _SINGLE_WORD

_AXIS = "data"

_MESH_JOIN_TYPES = ("inner", "left", "semi", "anti")


def mesh_join_supported(p, n_devices: int) -> bool:
    if n_devices < 2 or p.condition is not None or not p.left_keys:
        return False
    if p.join_type not in _MESH_JOIN_TYPES:
        return False
    try:
        key_ts = [e.dtype() for e in p.left_keys] + \
                 [e.dtype() for e in p.right_keys]
        out_ts = [f.dtype for f in p.schema]
    except (ValueError, NotImplementedError):
        return False
    return all(isinstance(t, _SINGLE_WORD) for t in key_ts + out_ts)


class TpuMeshShuffledJoin(TpuExec):
    _PROGRAM_CACHE: dict = {}

    def __init__(self, logical, left: PhysicalPlan, right: PhysicalPlan,
                 mesh: Optional[Mesh] = None):
        super().__init__(left, right)
        self.logical = logical
        self.mesh = mesh

    @property
    def output_schema(self) -> Schema:
        return self.logical.schema

    def _node_string(self):
        n = self.mesh.devices.size if self.mesh is not None else "?"
        return (f"TpuMeshShuffledJoin[{self.logical.join_type}, "
                f"{n} devices]")

    # ------------------------------------------------------------------
    def _program(self, mesh: Mesh, jt: str, nk: int, key_dts,
                 l_dts, r_dts, emit_right: bool):
        from ..shims import get_shard_map
        shard_map = get_shard_map()
        key = (id(mesh), jt, nk, tuple(d.name for d in key_dts),
               tuple(d.name for d in l_dts), tuple(d.name for d in r_dts),
               emit_right)
        hit = TpuMeshShuffledJoin._PROGRAM_CACHE.get(key)
        if hit is not None:
            return hit
        n_dev = mesh.devices.size

        def key_words(datas, valids, live, dts):
            words: List[jnp.ndarray] = []
            for d, v, dt in zip(datas, valids, dts):
                col = Column(dt, d, v & live)
                w = canon.column_key_words(col, d.shape[0])
                words.extend(w)
            words[0] = jnp.where(live, words[0], jnp.uint64(2))
            return words

        def side_route(datas, valids, live, dts, nw):
            words = key_words(datas[:nk], valids[:nk], live, key_dts)
            h = jnp.zeros_like(words[0])
            for w in words:
                h = (h ^ w) * jnp.uint64(MIX)
            owner = (h >> jnp.uint64(33)) % jnp.uint64(n_dev)
            owner = jnp.where(live, owner.astype(jnp.int32), n_dev)
            payload = list(words) + list(datas) + list(valids)
            fills = ([jnp.uint64(2)] + [jnp.uint64(0)] * (len(words) - 1)
                     + [jnp.zeros((), d.dtype)[()] for d in datas]
                     + [False] * len(valids))
            routed, rlive, ovf = _route_to_owners(
                owner, payload, fills, n_dev, _AXIS, slack=2)
            rwords = [jnp.asarray(w) for w in routed[:len(words)]]
            rwords[0] = jnp.where(rlive, rwords[0], jnp.uint64(2))
            nd = len(datas)
            rdatas = routed[len(words):len(words) + nd]
            rvalids = [v & rlive for v in routed[len(words) + nd:]]
            return rwords, rdatas, rvalids, rlive, ovf

        def step(*flat):
            pos = 0
            ld = list(flat[pos:pos + len(l_dts)]); pos += len(l_dts)
            lv = list(flat[pos:pos + len(l_dts)]); pos += len(l_dts)
            llive = flat[pos]; pos += 1
            rd = list(flat[pos:pos + len(r_dts)]); pos += len(r_dts)
            rv = list(flat[pos:pos + len(r_dts)]); pos += len(r_dts)
            rlive = flat[pos]

            lw, lrd, lrv, lrl, ovf_l = side_route(ld, lv, llive, l_dts,
                                                  nk)
            rw, rrd, rrv, rrl, ovf_r = side_route(rd, rv, rlive, r_dts,
                                                  nk)

            # local join on the owner shard: sorted build + binary probe
            bt = join_k.build(rw)
            lo = join_k._bsearch(bt.sorted_words, lw, upper=False)
            hi = join_k._bsearch(bt.sorted_words, lw, upper=True)
            counts = (hi - lo).astype(jnp.int32)
            # null keys never match: every _SINGLE_WORD key encodes as
            # (rank, value) word pairs, rank 1 == valid
            usable = lrl
            for ki in range(nk):
                usable = usable & (lw[2 * ki] == jnp.uint64(1))
            counts = jnp.where(usable, counts, 0)

            if jt == "inner":
                counts_eff = counts
            elif jt == "left":
                counts_eff = jnp.where(lrl & (counts == 0), 1, counts)
            elif jt == "semi":
                counts_eff = jnp.where(counts > 0, 1, 0)
            else:   # anti: live probe rows with no match (incl. null key)
                counts_eff = jnp.where(lrl & (counts == 0), 1, 0)

            pcap = lw[0].shape[0]
            out_cap = pcap * 2
            pc, build_idx, live_out, total = join_k.expand_matches(
                lo, counts_eff, bt.perm, out_cap)
            ovf_out = total > out_cap
            matched_slot = jnp.take(counts, pc) > 0

            # live output slots are contiguous at the front by
            # construction (expand fills t = 0..total-1)
            out_flat = []
            for d, v in zip(lrd, lrv):
                out_flat.append(jnp.take(d, pc, mode="clip"))
                out_flat.append(jnp.take(v, pc, mode="clip") & live_out)
            if emit_right:
                for d, v in zip(rrd, rrv):
                    out_flat.append(jnp.take(d, build_idx, mode="clip"))
                    out_flat.append(jnp.take(v, build_idx, mode="clip")
                                    & live_out & matched_slot)
            ovf = ovf_l | ovf_r | ovf_out
            out_flat.append(total.astype(jnp.int32)[None])
            out_flat.append(ovf[None])
            return tuple(out_flat)

        n_in = 2 * len(l_dts) + 1 + 2 * len(r_dts) + 1
        n_out = 2 * len(l_dts) + (2 * len(r_dts) if emit_right else 0) + 2
        fn = jax.jit(shard_map(
            step, mesh=mesh,
            in_specs=tuple(P(_AXIS) for _ in range(n_in)),
            out_specs=tuple(P(_AXIS) for _ in range(n_out))))
        TpuMeshShuffledJoin._PROGRAM_CACHE[key] = fn
        return fn

    # ------------------------------------------------------------------
    def _gather_side(self, child, keys, n_dev):
        batches = [b for part in child.execute() for b in part]
        batches = [b for b in batches if b.num_rows > 0]
        if not batches:
            batches = [ColumnarBatch.empty(child.output_schema)]
        batch = concat_batches(batches) if len(batches) > 1 else batches[0]
        schema = batch.schema
        key_cols = [ec.eval_as_column(e.bind(schema), batch)
                    for e in keys]
        out_cols = list(batch.columns)
        cap = batch.capacity
        # capacities are bucket powers of two and mesh sizes are powers
        # of two, so the shard constraint holds (same invariant as
        # TpuMeshAggregate.execute)
        assert cap % n_dev == 0, (cap, n_dev)
        live = np.zeros(cap, bool)
        live[:batch.num_rows] = True
        return batch, key_cols, out_cols, jnp.asarray(live)

    def execute(self):
        p = self.logical
        mesh = self.mesh or make_mesh()
        n_dev = mesh.devices.size
        jt = p.join_type
        emit_right = jt in ("inner", "left")

        def run():
            lbatch, lkeys, lcols, llive = self._gather_side(
                self.children[0], p.left_keys, n_dev)
            rbatch, rkeys, rcols, rlive = self._gather_side(
                self.children[1], p.right_keys, n_dev)
            key_dts = [c.dtype for c in lkeys]
            # payload layout: key cols first, then the remaining output
            # columns of each side (the program probes on the first nk)
            l_all = lkeys + lcols
            r_all = rkeys + rcols
            l_dts = [c.dtype for c in l_all]
            r_dts = [c.dtype for c in r_all]

            sharding = NamedSharding(mesh, P(_AXIS))
            flat = ([c.data for c in l_all] +
                    [c.validity for c in l_all] + [llive] +
                    [c.data for c in r_all] +
                    [c.validity for c in r_all] + [rlive])
            flat = [jax.device_put(a, sharding) for a in flat]

            program = self._program(mesh, jt, len(lkeys), key_dts,
                                    l_dts, r_dts, emit_right)
            with timed(self.metrics[JOIN_TIME]):
                out = program(*flat)
            if bool(np.asarray(out[-1]).any()):
                yield from self._fallback(lbatch, rbatch)
                return
            totals = np.asarray(out[-2]).reshape(-1)
            per = out[0].shape[0] // n_dev
            out_schema = self.output_schema
            # output columns: left payload (skip the nk key dup cols),
            # then right payload (skip right keys)
            nk = len(lkeys)
            col_slots = []
            for i in range(len(l_all)):
                if i >= nk:
                    col_slots.append(2 * i)
            if emit_right:
                base = 2 * len(l_all)
                for i in range(len(r_all)):
                    if i >= nk:
                        col_slots.append(base + 2 * i)
            for d in range(n_dev):
                nr = int(totals[d])
                if nr == 0:
                    continue
                lo_ = d * per
                seg = bucket_capacity(max(nr, 1))
                idx = jnp.arange(seg) + lo_
                cols = []
                for f, slot in zip(out_schema, col_slots):
                    data = jnp.take(out[slot], idx, mode="clip")
                    valid = jnp.take(out[slot + 1], idx, mode="clip") \
                        & (jnp.arange(seg) < nr)
                    cols.append(Column(f.dtype, data, valid))
                ob = ColumnarBatch(out_schema, cols, nr)
                self.metrics[NUM_OUTPUT_ROWS] += nr
                yield ob
        return [run()]

    # ------------------------------------------------------------------
    def _fallback(self, lbatch: ColumnarBatch, rbatch: ColumnarBatch):
        """Receive/output region overflowed: rerun via the in-process
        join on the materialized inputs (loud fallback, never silent)."""
        from .tpu_join import TpuShuffledHashJoin

        class _One(PhysicalPlan):
            columnar = True

            def __init__(self, b):
                super().__init__()
                self._b = b

            @property
            def output_schema(self):
                return self._b.schema

            def execute(self):
                return [iter([self._b])]

        j = TpuShuffledHashJoin(self.logical, _One(lbatch), _One(rbatch),
                                build_right=True)
        for part in j.execute():
            yield from part
