"""Mesh-distributed global sort: sample-splitter range exchange as ONE
SPMD program.

Reference role: GpuSortExec + GpuRangePartitioning over the shuffle
(GpuSortExec.scala:219, GpuRangePartitioner) — the reference realizes a
global sort as [sample & compute range bounds] + [range exchange] +
[local sort per partition].  On a TPU mesh the same pipeline is one
jitted shard_map program:

1. each device samples evenly from its LOCALLY SORTED shard (regular
   sampling of sorted runs — the classic sample-sort recipe),
2. ``lax.all_gather`` pools the samples; every device derives the same
   n_dev-1 splitters from the pooled sorted sample,
3. rows route to ``searchsorted(splitters, row)`` owners via
   ``lax.all_to_all`` (XLA schedules the ICI),
4. each device sorts what it received; device d's rows all precede
   device d+1's, so emitting per-device segments in order IS the global
   sort.

Row-producing: the program returns every payload column routed+sorted,
a per-device count, and an overflow flag (receive region exceeded —
skewed splits fall back loudly to the in-process out-of-core sort).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..columnar.schema import Schema
from ..columnar.column import Column, bucket_capacity
from ..obs import compile_watch as _compile_watch
from ..obs import timeline as _timeline
from ..obs.registry import compile_cache_event
from ..columnar.batch import ColumnarBatch, concat_batches
from ..expr import core as ec
from ..kernels import canon
from ..kernels import join as join_k
from ..kernels.sort import sort_permutation, sorted_words
from ..parallel.mesh import _route_to_owners, make_mesh
from .base import PhysicalPlan, SORT_TIME, NUM_OUTPUT_ROWS, timed
from .tpu_basic import TpuExec
from .tpu_mesh_aggregate import _SINGLE_WORD

_AXIS = "data"


def mesh_sort_supported(p, n_devices: int) -> bool:
    if n_devices < 2 or not p.orders:
        return False
    try:
        key_ts = [o.expr.dtype() for o in p.orders]
        out_ts = [f.dtype for f in p.schema]
    except (ValueError, NotImplementedError):
        return False
    return all(isinstance(t, _SINGLE_WORD) for t in key_ts + out_ts)


class TpuMeshSort(TpuExec):
    _PROGRAM_CACHE: dict = {}
    _SAMPLES_PER_DEV = 32

    def __init__(self, orders, child: PhysicalPlan,
                 mesh: Optional[Mesh] = None):
        super().__init__(child)
        self.orders = orders
        self.mesh = mesh

    @property
    def output_schema(self) -> Schema:
        return self.children[0].output_schema

    def _node_string(self):
        n = self.mesh.devices.size if self.mesh is not None else "?"
        return f"TpuMeshSort[{n} devices]"

    # ------------------------------------------------------------------
    def _program(self, mesh: Mesh, nkeys: int, key_dts, pay_dts,
                 desc, nlast):
        from ..shims import get_shard_map
        shard_map = get_shard_map()
        key = (id(mesh), nkeys, tuple(d.name for d in key_dts),
               tuple(d.name for d in pay_dts), tuple(desc), tuple(nlast))
        hit = TpuMeshSort._PROGRAM_CACHE.get(key)
        compile_cache_event("mesh_sort", hit is not None)
        if hit is not None:
            return hit
        n_dev = mesh.devices.size
        S = TpuMeshSort._SAMPLES_PER_DEV

        def step(*flat):
            pos = 0
            kd = list(flat[pos:pos + nkeys]); pos += nkeys
            kv = list(flat[pos:pos + nkeys]); pos += nkeys
            pd = list(flat[pos:pos + len(pay_dts)]); pos += len(pay_dts)
            pv = list(flat[pos:pos + len(pay_dts)]); pos += len(pay_dts)
            live = flat[pos]
            cap = kd[0].shape[0]

            words: List[jnp.ndarray] = []
            for d, v, dt, de, nl in zip(kd, kv, key_dts, desc, nlast):
                col = Column(dt, d, v & live)
                w = canon.column_key_words(col, cap, descending=de,
                                           nulls_last=nl)
                words.extend(w)
            words[0] = jnp.where(live, words[0], jnp.uint64(2))

            # 1. local sort, 2. regular sample of the sorted run
            lperm = sort_permutation(words)
            swords = [jnp.take(w, lperm) for w in words]
            n_live = jnp.sum(live.astype(jnp.int32))
            # sample positions spread across the LIVE prefix
            spos = (jnp.arange(S, dtype=jnp.int32) *
                    jnp.maximum(n_live, 1)) // S
            spos = jnp.clip(spos, 0, cap - 1)
            samples = [jnp.take(w, spos) for w in swords]
            # dead-region samples (n_live == 0) sort last: rank 2 stays
            pooled = [jnp.ravel(jax.lax.all_gather(s, _AXIS))
                      for s in samples]
            pperm = sort_permutation(pooled)
            psorted = [jnp.take(w, pperm) for w in pooled]
            # splitters: n_dev-1 equally spaced pooled samples
            tot = n_dev * S
            cut = (jnp.arange(1, n_dev, dtype=jnp.int32) * tot) // n_dev
            splitters = [jnp.take(w, cut) for w in psorted]

            # 3. owner = lower bound of the row among the splitters
            owner = join_k._bsearch(splitters, words, upper=True) \
                .astype(jnp.int32)
            owner = jnp.where(live, owner, n_dev)

            payload = list(words) + pd + pv
            fills = ([jnp.uint64(2)] + [jnp.uint64(0)] * (len(words) - 1)
                     + [jnp.zeros((), d.dtype)[()] for d in pd]
                     + [False] * len(pv))
            routed, rlive, ovf = _route_to_owners(
                owner, payload, fills, n_dev, _AXIS, slack=2)
            rwords = [jnp.asarray(w) for w in routed[:len(words)]]
            rwords[0] = jnp.where(rlive, rwords[0], jnp.uint64(2))
            nd = len(pd)
            rpd = routed[len(words):len(words) + nd]
            rpv = [v & rlive for v in routed[len(words) + nd:]]

            # 4. local sort of the received region; dead rows (rank 2)
            # sort to the end, so live rows are the prefix
            operm = sort_permutation(rwords)
            out_flat = []
            for d, v in zip(rpd, rpv):
                out_flat.append(jnp.take(d, operm))
                out_flat.append(jnp.take(v, operm))
            count = jnp.sum(rlive.astype(jnp.int32))
            out_flat.append(count[None])
            out_flat.append(ovf[None])
            return tuple(out_flat)

        n_in = 2 * nkeys + 2 * len(pay_dts) + 1
        n_out = 2 * len(pay_dts) + 2
        fn = jax.jit(shard_map(
            step, mesh=mesh,
            in_specs=tuple(P(_AXIS) for _ in range(n_in)),
            out_specs=tuple(P(_AXIS) for _ in range(n_out))))
        # perf plane: per-device busy windows + first-call compile
        # telemetry (signature drops the unstable id(mesh))
        fn = _timeline.device_busy_wrap(
            fn, tuple(str(d.id) for d in mesh.devices.ravel()))
        fn = _compile_watch.wrap_miss("mesh_sort", fn, str(key[1:]))
        TpuMeshSort._PROGRAM_CACHE[key] = fn
        return fn

    # ------------------------------------------------------------------
    def execute(self):
        mesh = self.mesh or make_mesh()
        n_dev = mesh.devices.size
        child = self.children[0]

        def run():
            batches = [b for part in child.execute() for b in part]
            batches = [b for b in batches if b.num_rows > 0]
            if not batches:
                return
            batch = concat_batches(batches) if len(batches) > 1 else \
                batches[0]
            schema = batch.schema
            key_cols = [ec.eval_as_column(o.expr.bind(schema), batch)
                        for o in self.orders]
            desc = [not o.ascending for o in self.orders]
            nlast = [not o.effective_nulls_first for o in self.orders]
            cap = batch.capacity
            assert cap % n_dev == 0, (cap, n_dev)
            live = np.zeros(cap, bool)
            live[:batch.num_rows] = True

            flat = [c.data for c in key_cols] + \
                   [c.validity for c in key_cols] + \
                   [c.data for c in batch.columns] + \
                   [c.validity for c in batch.columns] + \
                   [jnp.asarray(live)]
            sharding = NamedSharding(mesh, P(_AXIS))
            from ..analysis import residency  # lazy: avoids import cycle
            with residency.declared_transfer(site="mesh_reshard"):
                flat = [jax.device_put(a, sharding) for a in flat]

            program = self._program(
                mesh, len(key_cols), [c.dtype for c in key_cols],
                [c.dtype for c in batch.columns], desc, nlast)
            from ..compile import aot as _aot
            _aot.note_demand("mesh_sort", flat[0].shape[0])
            with timed(self.metrics[SORT_TIME], self):
                out = program(*flat)
            from ..analysis import residency  # lazy: avoids import cycle
            with residency.declared_transfer(site="mesh_collect"):
                overflowed = bool(np.asarray(out[-1]).any())
            if overflowed:
                # skewed splitters overflowed a receive region: loud
                # fallback to the in-process out-of-core sort
                from .tpu_sort import TpuSort

                class _One(PhysicalPlan):
                    columnar = True

                    def __init__(self, b):
                        super().__init__()
                        self._b = b

                    @property
                    def output_schema(self):
                        return self._b.schema

                    def execute(self):
                        return [iter([self._b])]
                srt = TpuSort(self.orders, _One(batch))
                for part in srt.execute():
                    yield from part
                return
            with residency.declared_transfer(site="mesh_collect"):
                counts = np.asarray(out[-2]).reshape(-1)
            per = out[0].shape[0] // n_dev
            for d in range(n_dev):
                nr = int(counts[d])
                if nr == 0:
                    continue
                lo = d * per
                seg = bucket_capacity(max(nr, 1))
                idx = jnp.arange(seg) + lo
                cols = []
                for i, f in enumerate(schema):
                    data = jnp.take(out[2 * i], idx, mode="clip")
                    valid = jnp.take(out[2 * i + 1], idx, mode="clip") \
                        & (jnp.arange(seg) < nr)
                    cols.append(Column(f.dtype, data, valid))
                ob = ColumnarBatch(schema, cols, nr)
                self.metrics[NUM_OUTPUT_ROWS] += nr
                yield ob
        return [run()]


# ---------------------------------------------------------------------------
# program audit registration (analysis/program_audit.py)
# ---------------------------------------------------------------------------

def _audit_specs():
    from ..analysis.program_audit import AuditSpec

    def _build():
        import jax
        import numpy as np
        from ..columnar import dtypes as T
        from ..parallel.mesh import make_mesh
        # 2-device mesh: 1 device degenerates the splitter /
        # routing structure (empty splitter gathers); the test harness
        # and ci/audit.py force >=2 host devices via XLA_FLAGS
        mesh = make_mesh(2)
        s = object.__new__(TpuMeshSort)
        fn = s._program(mesh, 1, (T.INT64,), (T.INT64,), (False,),
                        (False,))
        cap = 64
        d = jax.ShapeDtypeStruct((cap,), np.int64)
        v = jax.ShapeDtypeStruct((cap,), np.bool_)
        # flat layout: key datas, key valids, payload datas, payload
        # valids, live
        args = (d, v, d, v, v)
        return fn, args, {}

    return [AuditSpec(
        "mesh_sort", "mesh_sort", _build,
        notes="2-device mesh, one int64 asc key, one int64 payload",
        budgets={"gather": 52, "scatter": 12, "transpose": 4,
                 "sort": 14})]
