"""Mesh-distributed hash aggregate: the whole group-by as ONE SPMD program.

Reference role: BASELINE.json config 4 — "RapidsShuffleManager over
multi-host ICI".  The reference realizes a distributed aggregation as
partial agg -> UCX shuffle (catalog + client/server state machines +
bounce buffers) -> final agg.  On a TPU mesh the same pipeline is a
single jitted shard_map program: rows shard across devices, each device
partially groups its shard, key groups hash-route to an owner device via
``lax.all_to_all`` (XLA schedules the ICI), and the owner merges and
finalizes.  No transport code on the hot path.

Enabled with ``spark.rapids.tpu.shuffle.mode=mesh`` when more than one
device is visible (tests use the 8-device virtual CPU mesh; the driver's
``dryrun_multichip`` exercises the same kernels).  Row counts that
overflow a device's receive region fall back to the in-process path —
the same "fail loudly, never silently drop" contract as
parallel/mesh.py's overflow flag.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..columnar import dtypes as T
from ..columnar.schema import Field, Schema
from ..columnar.column import Column, bucket_capacity
from ..columnar.batch import ColumnarBatch, concat_batches
from ..expr import core as ec
from ..kernels import canon, aggregate as agg_k
from ..obs import compile_watch as _compile_watch
from ..obs import timeline as _timeline
from ..obs.registry import compile_cache_event
from ..parallel.mesh import MIX, _route_to_owners, make_mesh
from .base import PhysicalPlan, AGG_TIME, NUM_OUTPUT_ROWS, timed
from .tpu_basic import TpuExec

_AXIS = "data"

# dtypes whose canonical encoding is (rank word, one value word)
_SINGLE_WORD = (T.BooleanType, T.ByteType, T.ShortType, T.IntegerType,
                T.LongType, T.FloatType, T.DoubleType, T.DateType,
                T.TimestampType)


def mesh_aggregate_supported(p, n_devices: int) -> bool:
    from ..expr import aggregates as ea
    if n_devices < 2 or not p.group_exprs:
        return False
    try:
        key_ts = [e.dtype() for e in p.group_exprs]
        in_ts = [c.dtype() for a in p.aggs for c in a.func.children]
    except (ValueError, NotImplementedError):
        return False
    if not all(isinstance(t, _SINGLE_WORD) for t in key_ts):
        return False
    if not all(isinstance(t, _SINGLE_WORD) for t in in_ts):
        return False
    return all(isinstance(a.func, (ea.Sum, ea.Count, ea.Min, ea.Max,
                                   ea.Average, ea.First, ea.Last))
               for a in p.aggs)


class TpuMeshAggregate(TpuExec):
    _PROGRAM_CACHE: dict = {}

    def __init__(self, logical, child: PhysicalPlan,
                 mesh: Optional[Mesh] = None):
        super().__init__(child)
        self.logical = logical
        self.mesh = mesh

    @property
    def output_schema(self):
        p = self.logical
        fields = [Field(ec.output_name(e), e.dtype(), True)
                  for e in p.group_exprs]
        fields += [Field(a.alias, a.func.dtype(), a.func.nullable)
                   for a in p.aggs]
        return Schema(fields)

    def _node_string(self):
        n = self.mesh.devices.size if self.mesh is not None else "?"
        return f"TpuMeshAggregate[{n} devices]"

    # ------------------------------------------------------------------
    def _program(self, mesh: Mesh, nkeys: int, key_dts, in_layout,
                 in_dts):
        """Build (or fetch) the jitted SPMD program.

        in_layout: per agg, number of input columns (0 for count(*)).
        The traced signature: flat key (data, valid) pairs, flat input
        (data, valid) pairs, per-shard live mask.
        """
        from ..shims import get_shard_map
        shard_map = get_shard_map()
        p = self.logical
        key = (id(mesh), nkeys, tuple(d.name for d in key_dts),
               tuple(in_layout), tuple(d.name for d in in_dts),
               tuple((type(a.func).__name__, repr(a.func),
                      getattr(a.func, "ignore_nulls", None))
                     for a in p.aggs))
        hit = TpuMeshAggregate._PROGRAM_CACHE.get(key)
        compile_cache_event("mesh_aggregate", hit is not None)
        if hit is not None:
            return hit
        n_dev = mesh.devices.size
        aggs = p.aggs

        def step(*flat):
            pos = 0
            kdatas, kvalids = [], []
            for _ in range(nkeys):
                kdatas.append(flat[pos])
                kvalids.append(flat[pos + 1])
                pos += 2
            idatas, ivalids = [], []
            for _ in range(sum(in_layout)):
                idatas.append(flat[pos])
                ivalids.append(flat[pos + 1])
                pos += 2
            live = flat[pos]

            # canonical words per key (rank + value) for routing+grouping
            words: List[jnp.ndarray] = []
            for d, v, dt in zip(kdatas, kvalids, key_dts):
                col = Column(dt, d, v & live)
                cap = d.shape[0]
                w = canon.column_key_words(
                    col, jnp.sum(live.astype(jnp.int32)))
                words.extend(w)
            # rows past the live count were masked invalid, not dead:
            # re-mark dead rows in the FIRST word (rank 2 == padding)
            words[0] = jnp.where(live, words[0], jnp.uint64(2))

            h = jnp.zeros_like(words[0])
            for w in words:
                h = (h ^ w) * jnp.uint64(MIX)
            owner = (h >> jnp.uint64(33)) % jnp.uint64(n_dev)
            owner = jnp.where(live, owner.astype(jnp.int32), n_dev)

            payload = list(words) + kdatas + kvalids + idatas + ivalids
            fills = ([jnp.uint64(2)] + [jnp.uint64(0)] * (len(words) - 1)
                     + [jnp.zeros((), d.dtype)[()] for d in kdatas]
                     + [False] * len(kvalids)
                     + [jnp.zeros((), d.dtype)[()] for d in idatas]
                     + [False] * len(ivalids))
            routed, rlive, overflow = _route_to_owners(
                owner, payload, fills, n_dev, _AXIS, slack=2)
            rwords = routed[:len(words)]
            pos = len(words)
            rkd = routed[pos:pos + nkeys]
            pos += nkeys
            rkv = [v & rlive for v in routed[pos:pos + nkeys]]
            pos += nkeys
            rid = routed[pos:pos + sum(in_layout)]
            pos += sum(in_layout)
            riv = [v & rlive for v in routed[pos:pos + sum(in_layout)]]

            rwords = [jnp.asarray(w) for w in rwords]
            rwords[0] = jnp.where(rlive, rwords[0], jnp.uint64(2))
            plan = agg_k.groupby_plan(rwords)

            outs = []
            it = 0
            for a, n_in in zip(aggs, in_layout):
                if n_in == 0:
                    cols = [None]
                else:
                    cols = [Column(dt, rid[it + j], riv[it + j])
                            for j, dt in enumerate(
                                in_dts[it:it + n_in])]
                    it += n_in
                bufs = a.func.update(plan, cols)
                final = a.func.finalize(bufs)
                outs.append((final.data, final.validity))

            cap = rwords[0].shape[0]
            ng = plan.num_groups
            sel = jnp.where(jnp.arange(cap) < ng,
                            jnp.pad(plan.rep_indices,
                                    (0, max(0, cap -
                                            plan.rep_indices.shape[0])
                                     ))[:cap], 0)
            glive = jnp.arange(cap) < ng
            out_flat = []
            for d, v in zip(rkd, rkv):
                out_flat.append(jnp.take(d, sel))
                out_flat.append(jnp.take(v, sel) & glive)
            for d, v in outs:
                seg_take = jnp.where(glive, jnp.arange(cap), 0)
                out_flat.append(jnp.take(d, seg_take))
                out_flat.append(jnp.take(v, seg_take) & glive)
            out_flat.append(ng[None])
            out_flat.append(overflow[None])
            return tuple(out_flat)

        n_out = 2 * nkeys + 2 * len(aggs) + 2
        fn = jax.jit(shard_map(
            step, mesh=mesh,
            in_specs=tuple(P(_AXIS) for _ in
                           range(2 * (nkeys + sum(in_layout)) + 1)),
            out_specs=tuple(P(_AXIS) for _ in range(n_out))))
        # perf plane: per-device busy windows + first-call compile
        # telemetry (signature drops the unstable id(mesh))
        fn = _timeline.device_busy_wrap(
            fn, tuple(str(d.id) for d in mesh.devices.ravel()))
        fn = _compile_watch.wrap_miss("mesh_aggregate", fn,
                                      str(key[1:]))
        TpuMeshAggregate._PROGRAM_CACHE[key] = fn
        return fn

    # ------------------------------------------------------------------
    def execute(self):
        p = self.logical
        mesh = self.mesh or make_mesh()
        n_dev = mesh.devices.size
        child = self.children[0]

        def run():
            batches = [b for part in child.execute() for b in part]
            batch = concat_batches(batches) if len(batches) > 1 else \
                batches[0]
            schema = batch.schema
            key_cols = [ec.eval_as_column(e.bind(schema), batch)
                        for e in p.group_exprs]
            in_cols, in_layout, in_dts = [], [], []
            for a in p.aggs:
                bound = [c.bind(schema) for c in a.func.children]
                cols = [ec.eval_as_column(b, batch) for b in bound]
                in_layout.append(len(cols))
                in_cols.extend(cols)
                in_dts.extend(c.dtype for c in cols)

            # shard over devices: capacity must divide evenly
            cap = batch.capacity
            if cap % n_dev != 0:
                cap = bucket_capacity(cap * n_dev)  # unreachable for 2^k
            live = np.zeros(cap, bool)
            live[:batch.num_rows] = True
            flat = []
            for c in key_cols:
                flat.append(c.data)
                flat.append(c.validity)
            for c in in_cols:
                flat.append(c.data)
                flat.append(c.validity)
            flat.append(jnp.asarray(live))
            sharding = NamedSharding(mesh, P(_AXIS))
            from ..analysis import residency  # lazy: avoids import cycle
            with residency.declared_transfer(site="mesh_reshard"):
                flat = [jax.device_put(a, sharding) for a in flat]

            program = self._program(mesh, len(key_cols),
                                    [c.dtype for c in key_cols],
                                    in_layout, in_dts)
            from ..compile import aot as _aot
            _aot.note_demand("mesh_aggregate", flat[0].shape[0])
            with timed(self.metrics[AGG_TIME], self):
                out = program(*flat)
            from ..analysis import residency  # lazy: avoids import cycle
            with residency.declared_transfer(site="mesh_collect"):
                overflow = bool(np.asarray(out[-1]).any())
            if overflow:
                # receive region overflowed: rerun via the in-process
                # aggregate on the materialized input (loud fallback)
                from .tpu_aggregate import TpuHashAggregate

                class _One(PhysicalPlan):
                    columnar = True

                    def __init__(self, b, s):
                        super().__init__()
                        self._b, self._s = b, s

                    @property
                    def output_schema(self):
                        return self._s

                    def execute(self):
                        return [iter([self._b])]
                agg = TpuHashAggregate(p.group_exprs, p.aggs,
                                       _One(batch, schema))
                for part in agg.execute():
                    yield from part
                return
            with residency.declared_transfer(site="mesh_collect"):
                ngs = np.asarray(out[-2])      # [n_dev] group counts
            per = out[0].shape[0] // n_dev
            out_schema = self.output_schema
            for d in range(n_dev):
                ng = int(ngs[d])
                if ng == 0:
                    continue
                cols = []
                lo = d * per
                seg_cap = bucket_capacity(max(ng, 1))
                idx = jnp.arange(seg_cap) + lo
                for i, f in enumerate(out_schema):
                    data = jnp.take(out[2 * i], idx, mode="clip")
                    valid = jnp.take(out[2 * i + 1], idx, mode="clip") \
                        & (jnp.arange(seg_cap) < ng)
                    cols.append(Column(f.dtype, data, valid))
                ob = ColumnarBatch(out_schema, cols, ng)
                self.metrics[NUM_OUTPUT_ROWS] += ng
                yield ob
        return [run()]


# ---------------------------------------------------------------------------
# program audit registration (analysis/program_audit.py)
# ---------------------------------------------------------------------------

def _audit_specs():
    from types import SimpleNamespace
    from ..analysis.program_audit import AuditSpec

    def _build():
        import jax
        import numpy as np
        from ..expr import aggregates as ea
        from ..expr import core as ec
        from ..parallel.mesh import make_mesh
        from ..plan.logical import AggExpr
        # 2-device mesh: 1 device degenerates the splitter /
        # routing structure (empty splitter gathers); the test harness
        # and ci/audit.py force >=2 host devices via XLA_FLAGS
        mesh = make_mesh(2)
        a = object.__new__(TpuMeshAggregate)
        a.logical = SimpleNamespace(
            aggs=[AggExpr(ea.Sum(ec.BoundReference(1, T.INT64)), "s")])
        fn = a._program(mesh, 1, (T.INT64,), (1,), (T.INT64,))
        cap = 64
        d = jax.ShapeDtypeStruct((cap,), np.int64)
        v = jax.ShapeDtypeStruct((cap,), np.bool_)
        # interleaved flat layout: (key data, key valid) per key, then
        # (input data, input valid) per agg input, then live
        args = (d, v, d, v, v)
        return fn, args, {}

    return [AuditSpec(
        "mesh_aggregate", "mesh_aggregate", _build,
        notes="2-device mesh, sum(v) group by one int64 key",
        budgets={"gather": 50, "scatter": 18, "transpose": 4,
                 "sort": 8})]
