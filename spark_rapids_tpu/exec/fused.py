"""Fused expression evaluation: whole projection trees under one jit.

TPU-first rationale (SURVEY.md §7 / pallas guide): the engine's eager
mode dispatches every jnp op separately — on real hardware each dispatch
is a host->device round trip, so a 20-op projection pays 20 RPCs.  Under
``jax.jit`` the entire bound expression tree traces into ONE XLA
computation: elementwise ops fuse, intermediates never materialize in
HBM, and a batch is processed with a single dispatch.  This is the
moral equivalent of the reference running a whole projection as one
fused cuDF AST kernel instead of op-by-op JNI calls
(GpuProjectExec + cuDF compute-on-columns).

Fusion is per-expression: the fusable subset of a projection jits as one
computation; the rest (strings/lists size buffers host-side; UDF/rand/
partition-id expressions carry host state, flagged via
``Expression.trace_safe``) evaluates eagerly, and outputs merge by
position — one string passthrough column doesn't forfeit fusion for the
numeric expressions beside it.
"""
from __future__ import annotations

import logging
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..columnar import dtypes as T
from ..columnar.column import Column
from ..columnar.batch import ColumnarBatch
from ..compile import aot as _aot
from ..expr import core as ec
from ..obs import compile_watch as _compile_watch
from ..obs import costplane as _costplane
from ..obs.registry import compile_cache_event

_LOG = logging.getLogger("spark_rapids_tpu.exec.fused")

# (expr signatures, schema dtypes, needed ordinals) -> jitted callable
_JIT_CACHE: dict = {}


def _tree_fusable(expr: ec.Expression) -> bool:
    """Conservative gate: every node must be fixed-width (strings/nested
    kernels size outputs on host and cannot trace) AND declared
    trace-safe (no host state: UDFs, rand, partition ids)."""
    if not expr.trace_safe:
        return False
    try:
        dt = expr.dtype()
    except (ValueError, NotImplementedError):
        return False
    if dt == T.STRING or dt.is_nested or dt == T.NULL:
        return False
    return all(_tree_fusable(c) for c in expr.children)


def expr_signature(e: ec.Expression) -> Optional[str]:
    """Stable structural signature of an expression tree: identical
    signatures trace to identical computations, so jitted callables can
    be shared ACROSS query plans (a new FusedEval per query would
    otherwise re-trace + re-lower every run — ~20ms per jit even on a
    persistent-cache hit, dozens of jits per query).  Returns None when
    any attribute is opaque (functions, host objects) — id()-based keys
    would be unsound after GC address reuse, so such trees are simply
    not shared."""
    extras = []
    for k in sorted(vars(e)):
        if k in ("children", "_name"):
            continue
        sv = _sig_value(getattr(e, k))
        if sv is None:
            return None
        extras.append(f"{k}={sv}")
    kids = []
    for c in e.children:
        sc = expr_signature(c)
        if sc is None:
            return None
        kids.append(sc)
    return f"{type(e).__name__}({';'.join(extras)})[{','.join(kids)}]"


def _sig_value(v) -> Optional[str]:
    if isinstance(v, (int, float, str, bool, type(None), bytes)):
        return repr(v)
    if isinstance(v, T.DType):
        return v.name
    if isinstance(v, ec.Expression):
        return expr_signature(v)
    if isinstance(v, (list, tuple)):
        parts = [_sig_value(x) for x in v]
        if any(p is None for p in parts):
            return None
        return "[" + ",".join(parts) + "]"
    return None


def _needed_ordinals(exprs: Sequence[ec.Expression]) -> List[int]:
    out = set()
    for e in exprs:
        for r in e.collect(lambda n: isinstance(n, ec.BoundReference)):
            out.add(r.ordinal)
    return sorted(out)


class FusedEval:
    """One jitted computation for the fusable subset of bound exprs.

    ``__call__(batch) -> Optional[List[Column]]`` returns one Column per
    input expression (fused and eager results merged by position), or
    None when nothing could fuse — callers then use their own eager
    path unchanged.  jax.jit's shape-keyed cache handles
    per-capacity-bucket compilation automatically.
    """

    def __init__(self, bound_exprs: Sequence[ec.Expression], child_schema):
        self.exprs = list(bound_exprs)
        self.schema = child_schema
        self.fusable = [_tree_fusable(e) for e in self.exprs]
        self.fused_idx = [i for i, ok in enumerate(self.fusable) if ok]
        self.out_dtypes = []
        for e in self.exprs:
            try:
                self.out_dtypes.append(e.dtype())
            except (ValueError, NotImplementedError):
                self.out_dtypes.append(None)
        self.needed = _needed_ordinals(
            [self.exprs[i] for i in self.fused_idx])
        self.ok = bool(self.fused_idx)
        self._jitted = None
        if self.ok:
            # share one jitted callable across all query plans with the
            # same expression structure (process-level trace cache);
            # trees with opaque attributes (signature None) get a
            # private jit instead of an unsound id()-keyed entry
            sigs = [expr_signature(self.exprs[i]) for i in self.fused_idx]
            if any(s is None for s in sigs):
                self._jitted = _compile_watch.wrap_miss(
                    "fused_project",
                    jax.jit(self._eval, static_argnums=(0,)), "opaque")
            else:
                key = (tuple(sigs),
                       tuple(f.dtype.name for f in self.schema),
                       tuple(self.needed))
                self._jitted = _JIT_CACHE.get(key)
                compile_cache_event("fused_project",
                                    self._jitted is not None)
                if self._jitted is None:
                    self._jitted = _compile_watch.wrap_miss(
                        "fused_project",
                        jax.jit(self._eval, static_argnums=(0,)),
                        str(key))
                    if len(_JIT_CACHE) < 4096:
                        _JIT_CACHE[key] = self._jitted
                self._register_warmer(str(hash(key)))

    def _register_warmer(self, variant: str) -> None:
        """Hand the AOT subsystem a closure that drives this cached
        program at an arbitrary bucket capacity with zero-filled
        columns and num_rows=0 (every padded row invalid — the
        masking contract makes the dummy batch safe for any fused
        tree)."""
        jitted = self._jitted
        dts = tuple(self.schema[i].dtype.np_dtype for i in self.needed)
        if jitted is None or any(d is None for d in dts):
            return
        def warm(bucket: int) -> None:
            datas = tuple(jnp.zeros(bucket, d) for d in dts)
            valids = tuple(jnp.zeros(bucket, jnp.bool_) for _ in dts)
            jitted(bucket, datas, valids, jnp.int32(0))
        _aot.register_warmer("fused_project", warm, variant)

    # traced function: capacity static; column buffers + live row count
    # are device values
    def _eval(self, capacity: int, datas, valids, num_rows):
        by_ordinal = {}
        for i, d, v in zip(self.needed, datas, valids):
            by_ordinal[i] = Column(self.schema[i].dtype, d, v)
        # only referenced ordinals are real; BoundReference never touches
        # the rest
        filled = [by_ordinal.get(i) for i in range(len(self.schema))]
        batch = _TracedBatch(self.schema, filled, num_rows, capacity)
        outs = []
        for i in self.fused_idx:
            r = self.exprs[i].columnar_eval(batch)
            if isinstance(r, ec.Scalar):
                r = r.to_column(capacity, None)
                # scalar fills are valid only on live rows
                live = jnp.arange(capacity) < num_rows
                r = Column(r.dtype, r.data, r.validity & live)
            outs.append((r.data, r.validity))
        return outs

    def __call__(self, batch: ColumnarBatch) -> Optional[List[Column]]:
        if not self.ok:
            return None
        from ..columnar.binary64 import exact_double_enabled
        if exact_double_enabled():
            # exactDouble: expressions may CREATE Binary64Columns inside
            # the trace; reassembling traced arrays as plain Columns
            # would silently reinterpret bit patterns as values, so the
            # fused path stands down (exactness over fusion)
            return None
        if not all(type(batch.columns[i]) is Column for i in self.needed):
            return None
        datas = tuple(batch.columns[i].data for i in self.needed)
        valids = tuple(batch.columns[i].validity for i in self.needed)
        _aot.note_demand("fused_project", batch.capacity,
                         _costplane.rows_if_resolved(batch))
        try:
            fused_out = self._jitted(batch.capacity, datas, valids,
                                     batch.rows_dev)
        except Exception:  # noqa: BLE001 - fall back, but loudly
            _LOG.warning(
                "fused evaluation failed for %s; falling back to eager",
                [repr(self.exprs[i]) for i in self.fused_idx],
                exc_info=True)
            self.ok = False
            return None
        cols: List[Optional[Column]] = [None] * len(self.exprs)
        for i, (d, v) in zip(self.fused_idx, fused_out):
            cols[i] = Column(self.out_dtypes[i], d, v)
        for i, c in enumerate(cols):
            if c is None:
                cols[i] = ec.eval_as_column(self.exprs[i], batch)
        return cols

class _TracedBatch(ColumnarBatch):
    """ColumnarBatch whose num_rows is a traced scalar (no host int)."""

    def __init__(self, schema, columns, num_rows, capacity):
        self.schema = schema
        self.columns = list(columns)
        self._rows = num_rows           # jnp scalar under trace
        self._rows_dev = num_rows
        self._capacity = capacity


# ---------------------------------------------------------------------------
# program audit registration (analysis/program_audit.py): the audited
# object is the REAL cached program (wrap_miss + jit), traced over
# representative avals — never a re-implementation.
# ---------------------------------------------------------------------------

def _audit_specs():
    from ..analysis.program_audit import AuditSpec

    def _build():
        import jax
        import numpy as np
        from ..columnar.schema import Field, Schema
        from ..expr.arithmetic import Add
        schema = Schema([Field("a", T.INT64, True),
                         Field("b", T.INT64, True)])
        fe = FusedEval(
            [Add(ec.BoundReference(0, T.INT64),
                 ec.BoundReference(1, T.INT64))], schema)
        assert fe.ok, "representative fused projection did not fuse"
        cap = 64
        d = jax.ShapeDtypeStruct((cap,), np.int64)
        v = jax.ShapeDtypeStruct((cap,), np.bool_)
        args = (cap, tuple(d for _ in fe.needed),
                tuple(v for _ in fe.needed),
                jax.ShapeDtypeStruct((), np.int32))
        return fe._jitted, args, {"static_argnums": (0,)}

    return [AuditSpec(
        "fused_project", "fused_project", _build,
        notes="int64 a+b projection over a 64-row bucket",
        budgets={"gather": 2, "scatter": 2, "transpose": 2, "sort": 1})]
