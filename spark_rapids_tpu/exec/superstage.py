"""TpuSuperstage: one carved, exchange-delimited region executing with
device-resident handoff between its member operators.

The carve pass (compile/carve.py) wraps each qualifying region root in
this exec and arms the members' sync-free paths (the join's speculative
unique-match program, the aggregate's deferred fit flags, the lazy
sort/limit heads, fit-flag chaining through projections).  Intermediates
never pull a host count between members: size-dependent shapes ride the
speculative fit-flag/redo machinery (columnar/batch.py) to the stage's
single barrier — the exchange finalize or the collect staging — where
ONE fused flush (columnar/pending.py) resolves every count, fit flag and
output buffer of the stage.

Fallback layers, outermost to innermost:
- stage setup: if arming/executing the region raises during setup, the
  member flags are stripped and the region re-executes with plain
  per-operator dispatch (``tpu_compile_superstages_total{event=
  "fallback"}``).
- per node: the carve pass ejects unfusable operators into their own
  dispatch by splitting the region around them (event="ejected").
- per batch: each sync-free program falls back to its operator's exact
  sized path when its jit fails (the _SPEC_JIT/_PROBE_JIT False
  sentinels) or its fit flag fails at the barrier (redo closures).

Each pulled batch passes a ``timed`` region, so cancel checkpoints and
flight/trace coverage survive fusion (the PV-STAGE verifier pass checks
this statically).
"""
from __future__ import annotations

from typing import List

from .base import OP_TIME, NUM_OUTPUT_BATCHES, timed
from .tpu_basic import TpuExec

# per-stage flush tally (resolved lazily like every Metric)
STAGE_FLUSHES = "superstageFlushes"

_SENTINEL = object()


class TpuSuperstage(TpuExec):
    def __init__(self, region_root, members: List, lowering,
                 resolve_output: bool = False):
        super().__init__(region_root)
        self.members = list(members)
        self.lowering = lowering   # [(node name, strategy)] region order
        # True when the stage's consumer is not a known speculative-
        # resolving boundary (exchange finalize / collect sink / join
        # intake): the stage then verifies its own fit flags at the edge
        # rather than handing unresolved counts to an unknown operator
        self.resolve_output = resolve_output

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        return self.children[0].num_partitions_hint()

    def _node_string(self):
        progs = sum(1 for _n, s in self.lowering if s == "program")
        return (f"TpuSuperstage[{len(self.members)} ops, "
                f"{progs} programs]")

    def _disarm(self):
        """Strip the members' sync-free flags: the region then executes
        exactly as the uncarved plan would."""
        for m in self.members:
            if getattr(m, "_superstage", False):
                m._superstage = False

    def execute(self):
        from ..obs import flight
        from ..obs.registry import superstage_event
        try:
            parts = self.children[0].execute()
        except Exception:
            # eager fallback: per-operator dispatch, one retry
            self._disarm()
            superstage_event("fallback")
            flight.record(flight.EV_COMPILE, "fallback",
                          len(self.members))
            parts = self.children[0].execute()
        return [self._drain(p, pid) for pid, p in enumerate(parts)]

    def _drain(self, part, pid: int):
        from ..columnar import pending
        from ..obs import flight, profile
        from ..obs.registry import COMPILE_SUPERSTAGE_FLUSHES
        f0 = pending.FLUSH_COUNT
        flight.record(flight.EV_COMPILE, "stage_begin", pid,
                      len(self.members))
        it = iter(part)
        while True:
            # the timed region is the stage's cancel checkpoint + span:
            # one entry per pulled batch, like any member operator; the
            # attrib scope makes this stage the owner of any flush the
            # chain step forces (stats plane, obs/profile.py)
            with timed(self.metrics[OP_TIME], self), \
                    profile.attrib_scope(self), \
                    profile.dispatch(profile.SITE_CHAIN_STEP):
                batch = next(it, _SENTINEL)
            if batch is _SENTINEL:
                break
            if self.resolve_output:
                from ..columnar.batch import resolve_speculative
                # residency-audited: the speculative-redo resolve pulls
                # its fit flags through the one-flush pending pool
                # (declared pending_flush region), not inline — RES003
                # does not apply to this drain loop
                with profile.attrib_scope(self):
                    batch = resolve_speculative(batch)
            self.metrics[NUM_OUTPUT_BATCHES] += 1
            yield batch
        flushes = pending.FLUSH_COUNT - f0
        self.metrics[STAGE_FLUSHES] += flushes
        COMPILE_SUPERSTAGE_FLUSHES.inc(flushes)
        flight.record(flight.EV_COMPILE, "stage_end", pid, flushes)
