"""CPU window operator (pandas-backed) — fallback + oracle for

window functions until the TPU window exec lands.
Reference counterpart: stock Spark WindowExec.
"""
from __future__ import annotations

from typing import List

import numpy as np
import pyarrow as pa

from ..columnar.arrow import schema_to_arrow
from ..expr import core as ec
from ..expr.cpu_eval import cpu_eval, _arr
from ..plan import logical as L
from .cpu import CpuExec, _concat_tables


class CpuWindow(CpuExec):
    def __init__(self, logical: L.Window, child):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def num_partitions_hint(self):
        return 1

    def execute(self):
        child_schema = schema_to_arrow(self.children[0].output_schema)
        parts = self.children[0].execute()

        def run():
            t = _concat_tables([x for p in parts for x in p], child_schema)
            yield self._apply(t)
        return [run()]

    @staticmethod
    def _bounded_frame(grouped, work, src, okey, kind, lo, hi, agg,
                       ascending: bool, nulls_first: bool = True):
        """Exact per-row frame aggregation (rows or range bounds)."""
        import pandas as pd

        def _as_num(x):
            """Temporal order keys compare as epoch numbers so integer
            range offsets add cleanly (dates: days; timestamps: us)."""
            import datetime
            if isinstance(x, pd.Timestamp):
                return x.value // 1000
            if isinstance(x, datetime.datetime):
                return int(x.timestamp() * 1e6)
            if isinstance(x, datetime.date):
                return (x - datetime.date(1970, 1, 1)).days
            return x

        def one_group(g: "pd.DataFrame") -> "pd.Series":
            vals = g[src].to_numpy(dtype=object)
            n = len(g)
            out = []
            if kind == "range" and okey is not None:
                order = [None if x is None or (isinstance(x, float) and
                                               np.isnan(x))
                         else _as_num(x)
                         for x in g[okey].to_numpy(dtype=object)]
            for i in range(n):
                if kind == "rows":
                    a = 0 if lo is None else max(0, i + lo)
                    b = n - 1 if hi is None else min(n - 1, i + hi)
                    window = vals[a:b + 1] if a <= b else []
                else:
                    v = order[i]
                    if v is None:
                        # null current row: null+offset = null, and null
                        # sorts at the partition edge, so the bounded
                        # side toward the values keeps only null peers —
                        # unless that side is UNBOUNDED, which takes the
                        # whole partition (Spark RangeBoundOrdering)
                        if (hi is None) if nulls_first else (lo is None):
                            window = list(vals)
                        else:
                            window = [vals[j] for j in range(n)
                                      if order[j] is None]
                    else:
                        d1 = lo if lo is not None else None
                        d2 = hi if hi is not None else None
                        if not ascending:
                            d1, d2 = (None if d2 is None else -d2,
                                      None if d1 is None else -d1)
                        # an UNBOUNDED side reaches the partition edge,
                        # so it takes the null-order block in with it
                        # (Spark RANGE semantics; matches the TPU
                        # rank-search encoding of nulls)
                        incl_null = (lo is None) if nulls_first \
                            else (hi is None)
                        window = [
                            vals[j] for j in range(n)
                            if ((order[j] is None and incl_null) or
                                (order[j] is not None and
                                 (d1 is None or order[j] >= v + d1) and
                                 (d2 is None or order[j] <= v + d2)))]
                clean = [x for x in window
                         if x is not None and not (
                             isinstance(x, float) and np.isnan(x))]
                if agg == "count":
                    out.append(len(clean))
                elif agg == "collect_list":
                    out.append(list(clean))
                elif not clean:
                    out.append(None)
                elif agg == "sum":
                    out.append(sum(clean))
                elif agg == "mean":
                    out.append(sum(clean) / len(clean))
                elif agg == "min":
                    out.append(min(clean))
                else:
                    out.append(max(clean))
            return pd.Series(out, index=g.index, dtype=object)

        parts = [one_group(g) for _, g in grouped]
        return pd.concat(parts).reindex(work.index)

    def _apply(self, t: pa.Table) -> pa.Table:
        import pandas as pd
        df = t.to_pandas()
        out_schema = schema_to_arrow(self.output_schema)
        for wf in self.logical.window_funcs:
            spec = wf.spec
            pkeys = []
            for i, e in enumerate(spec.partition_by):
                name = f"__wp_{i}"
                df[name] = _arr(cpu_eval(e, t), t.num_rows).to_pandas()
                pkeys.append(name)
            skeys, ascs = [], []
            for i, o in enumerate(spec.order_by):
                name = f"__ws_{i}"
                df[name] = _arr(cpu_eval(o.expr, t), t.num_rows).to_pandas()
                skeys.append(name)
                ascs.append(o.ascending)
            # per-key stable sorts (last key first) so each order key gets
            # its own null placement (Spark: asc->nulls first)
            work = df
            for name, o in reversed(list(zip(skeys, spec.order_by))):
                work = work.sort_values(
                    name, ascending=o.ascending, kind="stable",
                    na_position="first" if o.effective_nulls_first
                    else "last")
            grouped = work.groupby(pkeys, dropna=False, sort=False) \
                if pkeys else work.groupby(np.zeros(len(work)))
            fname = type(wf.func).__name__
            from ..expr import aggregates as eagg
            from ..expr.window_funcs import (RowNumber, Rank, DenseRank,
                                             Lead, Lag, NTile,
                                             PercentRank, CumeDist)

            def _rank_stats(gdf):
                """(rank_min, rank_max, size) per row of a sorted group,
                via order-key run boundaries — exact for any key count,
                ORDER BY direction and null placement (the rows arrive
                already sorted; only EQUALITY between neighbors is
                used, so direction cannot invert ranks the way
                value-based pandas rank does)."""
                m = len(gdf)
                newrun = np.zeros(m, bool)
                if m:
                    newrun[0] = True
                for kcol in skeys:
                    colv = gdf[kcol].to_numpy(dtype=object)
                    if m > 1:
                        a, b = colv[1:], colv[:-1]
                        both_na = pd.isna(a.astype(object)) & \
                            pd.isna(b.astype(object))
                        neq = np.array([x != y for x, y in zip(a, b)],
                                       dtype=bool)
                        newrun[1:] |= neq & ~both_na
                runid = np.cumsum(newrun)
                pos = np.arange(m, dtype=np.int64)
                first = np.zeros(m, np.int64)
                last = np.zeros(m, np.int64)
                if m:
                    # first/last position of each run, broadcast back
                    starts = pos[newrun]
                    ends = np.r_[starts[1:] - 1, m - 1]
                    first = starts[runid - 1]
                    last = ends[runid - 1]
                return first + 1, last + 1, m

            if isinstance(wf.func, (NTile, PercentRank, CumeDist)):
                fn = wf.func
                outs = []
                for _, g in grouped:
                    if isinstance(fn, NTile):
                        m = len(g)
                        r = np.arange(m, dtype=np.int64)
                        base, rem = divmod(m, fn.n)
                        cut = rem * (base + 1)
                        vals = np.where(
                            r < cut, r // max(base + 1, 1),
                            rem + (r - cut) // max(base, 1)) + 1
                    else:
                        rmin, rmax, m = _rank_stats(g)
                        if isinstance(fn, PercentRank):
                            vals = (rmin - 1) / (m - 1) if m > 1 \
                            else np.zeros(m)
                        else:
                            vals = rmax / m
                    outs.append(pd.Series(vals, index=g.index))
                res = pd.concat(outs).reindex(work.index) if outs \
                    else pd.Series([], dtype=object)
            elif isinstance(wf.func, RowNumber):
                res = grouped.cumcount() + 1
            elif isinstance(wf.func, (Rank, DenseRank)):
                # exact multi-key ranking via order-key run boundaries
                # (column-wise pandas rank ties only on the FIRST key)
                dense = isinstance(wf.func, DenseRank)
                outs = []
                for _, g in grouped:
                    rmin, _, m = _rank_stats(g)
                    if dense:
                        newrun = np.zeros(m, bool)
                        if m:
                            newrun[0] = True
                            newrun[1:] = rmin[1:] != rmin[:-1]
                        vals = np.cumsum(newrun).astype(np.int64)
                    else:
                        vals = rmin
                    outs.append(pd.Series(vals, index=g.index))
                res = (pd.concat(outs).reindex(work.index)
                       .astype(np.int64)) if outs else \
                    pd.Series([], dtype=np.int64)
            elif isinstance(wf.func, (Lead, Lag)):
                offset = wf.func.offset if isinstance(wf.func, Lead) \
                    else -wf.func.offset
                # shift row *indices*, then gather from the arrow array so
                # NaN values are not conflated with nulls by pandas
                pos_col = f"__wpos_{wf.alias}"
                work[pos_col] = np.arange(len(work))
                src_pos = grouped[pos_col].shift(-offset)
                work.drop(columns=[pos_col], inplace=True)
                src_arr = _arr(cpu_eval(wf.func.children[0], t),
                               t.num_rows)
                if isinstance(src_arr, pa.ChunkedArray):
                    src_arr = src_arr.combine_chunks()
                # src_pos indexes into `work` order; map to original rows
                work_orig_idx = work.index.to_numpy()
                sp = src_pos.to_numpy()
                valid = ~np.isnan(sp)
                orig_src = np.full(len(work), -1, dtype=np.int64)
                orig_src[valid] = work_orig_idx[
                    sp[valid].astype(np.int64)]
                take_idx = pa.array(
                    [int(i) if i >= 0 else None for i in orig_src],
                    pa.int64())
                gathered = src_arr.take(take_idx)
                # align gathered (in work order) back to df positions
                df[wf.alias] = None
                res_series = None
                arr_np = np.empty(len(work), dtype=object)
                for j, v in enumerate(gathered.to_pylist()):
                    arr_np[j] = v
                import pandas as pd
                res = pd.Series(arr_np, index=work.index)
            elif isinstance(wf.func, eagg.AggregateFunction):
                src = f"__wsrc_{wf.alias}"
                child = wf.func.children[0] if wf.func.children else None
                if child is None:
                    work[src] = 1
                else:
                    work[src] = _arr(cpu_eval(child, t),
                                     t.num_rows).to_pandas()
                agg = {"Sum": "sum", "Count": "count", "Min": "min",
                       "Max": "max", "Average": "mean",
                       "CollectList": "collect_list"}[fname]
                frame_kind, fstart, fend = spec.frame
                if agg == "collect_list":
                    # always the exact per-row oracle (rows kind with
                    # unbounded ends covers the whole partition)
                    res = self._bounded_frame(
                        grouped, work, src,
                        skeys[0] if skeys else None,
                        frame_kind if (fstart, fend) != (None, None)
                        else "rows",
                        fstart, fend, agg,
                        spec.order_by[0].ascending if spec.order_by
                        else True,
                        spec.order_by[0].effective_nulls_first
                        if spec.order_by else True)
                elif not skeys or (fstart is None and fend is None):
                    res = grouped[src].transform(agg)
                    if agg != "count":
                        # all-null partition: pandas yields NaN, SQL NULL
                        cnt = grouped[src].transform("count")
                        res = res.astype(object).mask(cnt == 0, None)
                elif frame_kind == "rows" and fstart is None and fend == 0:
                    # running aggregate: vectorized expanding() (the
                    # exact per-row oracle below is O(n^2) python)
                    res = grouped[src].transform(
                        lambda s_: getattr(s_.expanding(), agg)())
                    if agg != "count":
                        # all-null prefix: pandas yields NaN, SQL NULL
                        # (TPC-DS q51 full-outer cumulative windows)
                        cnt = grouped[src].transform(
                            lambda s_: s_.expanding().count())
                        res = res.astype(object).mask(cnt == 0, None)
                else:
                    # bounded frame oracle: per-row python slice (exact,
                    # O(n*frame) — oracle only)
                    okey = skeys[0] if skeys else None
                    res = self._bounded_frame(
                        grouped, work, src, okey, frame_kind, fstart,
                        fend, agg,
                        spec.order_by[0].ascending if spec.order_by
                        else True,
                        spec.order_by[0].effective_nulls_first
                        if spec.order_by else True)
                if agg == "count":
                    res = res.astype(np.int64)
                work.drop(columns=[src], inplace=True)
            else:
                raise NotImplementedError(f"window function {fname}")
            df.loc[work.index, wf.alias] = res
        # output: original columns straight from the arrow table (no
        # pandas NaN/null conflation); window columns from df
        base_names = set(t.column_names)
        arrays = []
        for f in out_schema:
            if f.name in base_names:
                arrays.append(t.column(f.name).combine_chunks())
                continue
            vals = df[f.name].tolist()
            arr = pa.array(vals, type=f.type)
            arrays.append(arr)
        return pa.Table.from_arrays(arrays, schema=out_schema)
