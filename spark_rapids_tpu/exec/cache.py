"""Columnar cache: df.cache()/persist() as parquet-encoded batches.

Reference parity: ParquetCachedBatchSerializer
(shims/spark311/ParquetCachedBatchSerializer.scala, ~1,500 LoC;
docs/additional-functionality/cache-serializer.md): Spark's
``df.cache()`` stores columnar batches as compressed Parquet bytes so
cached data is small and deserializes straight back into columnar form.

Here the cache storage holds one list of parquet blobs per partition
(host memory — compressed parquet is the compact tier, exactly the
reference's rationale).  The first full materialization fills the
storage; later executions decode blobs straight to device batches and
skip the child plan entirely.  A partially-consumed run (e.g. under a
limit) discards its partial fill rather than caching a lie.
"""
from __future__ import annotations

import io
import threading
from typing import List, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from ..columnar.arrow import from_arrow, to_arrow, schema_to_arrow
from ..columnar.batch import ColumnarBatch
from .base import PhysicalPlan, NUM_OUTPUT_ROWS
from .tpu_basic import TpuExec


class CacheStorage:
    """Materialized cache state shared by every execution of a cached
    plan (the CachedRDD/CachedBatch store role)."""

    def __init__(self, compression: str = "snappy"):
        self.compression = compression
        self._partitions: Optional[List[List[bytes]]] = None
        self._lock = threading.Lock()

    @property
    def ready(self) -> bool:
        return self._partitions is not None

    def offer(self, partitions: List[List[bytes]]):
        with self._lock:
            if self._partitions is None:
                self._partitions = partitions

    def partitions(self) -> List[List[bytes]]:
        assert self._partitions is not None
        return self._partitions

    def invalidate(self):
        with self._lock:
            self._partitions = None

    def nbytes(self) -> int:
        with self._lock:
            if self._partitions is None:
                return 0
            return sum(len(b) for p in self._partitions for b in p)


def encode_batch(table: pa.Table, compression: str) -> bytes:
    sink = io.BytesIO()
    pq.write_table(table, sink, compression=compression)
    return sink.getvalue()


def decode_blob(blob: bytes) -> pa.Table:
    return pq.read_table(io.BytesIO(blob))


def fill_while_streaming(parts, storage: CacheStorage, to_table,
                         on_batch=None):
    """Shared fill protocol: tee each partition's stream into parquet
    blobs; offer the fill only when EVERY partition was fully consumed
    (a partial run — e.g. under a limit — must not cache a lie)."""
    fill: List[List[bytes]] = [[] for _ in parts]
    done = [False] * len(parts)

    def run(part, idx):
        for item in part:
            if item.num_rows:
                fill[idx].append(encode_batch(to_table(item),
                                              storage.compression))
            if on_batch is not None:
                on_batch(item)
            yield item
        done[idx] = True
        if all(done):
            storage.offer(fill)
    return [run(p, i) for i, p in enumerate(parts)]


class TpuCachedExec(TpuExec):
    """Serve from the parquet cache, or fill it while streaming through.

    Reference: ParquetCachedBatchSerializer.convertColumnarBatchToCachedBatch
    / convertCachedBatchToColumnarBatch.
    """

    def __init__(self, storage: CacheStorage, child: PhysicalPlan):
        super().__init__(child)
        self.storage = storage

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        if self.storage.ready:
            return len(self.storage.partitions())
        return self.children[0].num_partitions_hint()

    def _node_string(self):
        state = "hit" if self.storage.ready else "fill"
        return f"TpuCachedExec[{state}, {self.storage.nbytes()}B]"

    def execute(self):
        if self.storage.ready:
            return [self._decode_part(p) for p in self.storage.partitions()]
        def count(b):
            self.metrics[NUM_OUTPUT_ROWS] += b.rows_lazy
        return fill_while_streaming(
            self.children[0].execute(), self.storage, to_arrow,
            on_batch=count)

    def _decode_part(self, blobs: List[bytes]):
        got = False
        for blob in blobs:
            b = from_arrow(decode_blob(blob))
            got = True
            self.metrics[NUM_OUTPUT_ROWS] += b.rows_lazy
            yield b
        if not got:
            yield ColumnarBatch.empty(self.output_schema)


class CpuCachedExec(PhysicalPlan):
    """CPU-engine variant: serves/fills the same parquet blobs as
    pa.Tables (the CPU codec path of the reference serializer)."""

    columnar = False

    def __init__(self, storage: CacheStorage, child: PhysicalPlan):
        super().__init__(child)
        self.storage = storage

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        if self.storage.ready:
            return len(self.storage.partitions())
        return self.children[0].num_partitions_hint()

    def execute(self):
        if self.storage.ready:
            def decode(blobs):
                got = False
                for blob in blobs:
                    got = True
                    yield decode_blob(blob)
                if not got:
                    yield schema_to_arrow(self.output_schema).empty_table()
            return [decode(p) for p in self.storage.partitions()]
        return fill_while_streaming(
            self.children[0].execute(), self.storage, lambda t: t)
