"""CPU fallback physical operators over pyarrow — the "stock Spark" role.

In the reference, anything not tagged for GPU stays a stock Spark CPU
operator.  This standalone framework supplies its own CPU engine: each
operator consumes/produces pa.Table chunks using pyarrow compute, with the
same partitioned execution model as the TPU operators.  It doubles as the
oracle engine for the equality test harness (SURVEY.md §4).
"""
from __future__ import annotations

import itertools
from typing import Iterator, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..columnar.schema import Schema
from ..columnar.arrow import schema_from_arrow, schema_to_arrow
from ..expr import core as ec
from ..expr import aggregates as eagg
from ..expr.cpu_eval import cpu_eval, _arr
from ..plan import logical as L
from .base import PhysicalPlan, NUM_OUTPUT_ROWS


def _concat_tables(tables: List[pa.Table], schema: pa.Schema) -> pa.Table:
    tables = [t for t in tables if t.num_rows >= 0]
    if not tables:
        return schema.empty_table()
    return pa.concat_tables(tables, promote_options="permissive") \
        if len(tables) > 1 else tables[0]


class CpuExec(PhysicalPlan):
    columnar = False


class CpuLocalScan(CpuExec):
    def __init__(self, table: pa.Table, num_partitions: int = 1):
        super().__init__()
        self.table = table
        self.num_partitions = max(1, num_partitions)

    @property
    def output_schema(self):
        return schema_from_arrow(self.table.schema)

    def num_partitions_hint(self):
        return self.num_partitions

    def execute(self):
        n = self.table.num_rows
        per = -(-n // self.num_partitions) if n else 0
        parts = []
        for i in range(self.num_partitions):
            lo = min(i * per, n)
            hi = min(lo + per, n)
            chunk = self.table.slice(lo, hi - lo)
            parts.append(iter([chunk]))
        return parts


class CpuRange(CpuExec):
    def __init__(self, start, end, step, num_partitions):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.num_partitions = max(1, num_partitions)

    @property
    def output_schema(self):
        from ..columnar import dtypes as T
        from ..columnar.schema import Field
        return Schema([Field("id", T.INT64, False)])

    def num_partitions_hint(self):
        return self.num_partitions

    def execute(self):
        total = max(0, -(-(self.end - self.start) // self.step))
        per = -(-total // self.num_partitions) if total else 0
        parts = []
        for i in range(self.num_partitions):
            lo, hi = i * per, min((i + 1) * per, total)
            vals = np.arange(self.start + lo * self.step,
                             self.start + hi * self.step, self.step,
                             dtype=np.int64) if hi > lo else \
                np.zeros(0, np.int64)
            parts.append(iter([pa.table({"id": vals})]))
        return parts


class CpuProject(CpuExec):
    def __init__(self, exprs: List[ec.Expression], child: PhysicalPlan):
        super().__init__(child)
        self.exprs = exprs

    @property
    def output_schema(self):
        from ..columnar.schema import Field
        return Schema([Field(ec.output_name(e), e.dtype(), e.nullable)
                       for e in self.exprs])

    def execute(self):
        out_schema = schema_to_arrow(self.output_schema)

        def run(part):
            for t in part:
                arrays = []
                for e, f in zip(self.exprs, out_schema):
                    v = _arr(cpu_eval(e, t), t.num_rows)
                    if isinstance(v, pa.ChunkedArray):
                        v = v.combine_chunks()
                    if v.type != f.type:
                        v = pc.cast(v, f.type, safe=False)
                    arrays.append(v)
                self.metrics[NUM_OUTPUT_ROWS] += t.num_rows
                yield pa.Table.from_arrays(arrays, schema=out_schema)
        return [run(p) for p in self.children[0].execute()]


class CpuFilter(CpuExec):
    def __init__(self, condition: ec.Expression, child: PhysicalPlan):
        super().__init__(child)
        self.condition = condition

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def execute(self):
        def run(part):
            for t in part:
                mask = pc.coalesce(
                    pc.cast(_arr(cpu_eval(self.condition, t), t.num_rows),
                            pa.bool_()),
                    pa.scalar(False))
                out = t.filter(mask)
                self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
                yield out
        return [run(p) for p in self.children[0].execute()]


_F64_SIGN = np.uint64(0x8000000000000000)


def _np_float_encode(arr: pa.Array) -> pa.Array:
    """Spark float total order as uint64 (NaN greatest, -0.0 == 0.0)."""
    a = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    vals = np.asarray(a.cast(pa.float64()).fill_null(0.0), dtype=np.float64)
    vals = np.where(vals == 0.0, 0.0, vals)
    bits = vals.view(np.uint64)
    neg = (bits & _F64_SIGN) != 0
    enc = np.where(neg, ~bits, bits | _F64_SIGN)
    mask = None if a.null_count == 0 else np.asarray(
        pc.is_null(a))
    return pa.array(enc, pa.uint64(), mask=mask)


def _np_float_decode(arr, out_type: pa.DataType) -> pa.Array:
    a = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    enc = np.asarray(a.fill_null(0), dtype=np.uint64)
    neg = (enc & _F64_SIGN) == 0
    bits = np.where(neg, ~enc, enc & ~_F64_SIGN)
    vals = bits.view(np.float64)
    mask = None if a.null_count == 0 else np.asarray(pc.is_null(a))
    return pa.array(vals, pa.float64(), mask=mask).cast(out_type)


def _agg_arrow(func: eagg.AggregateFunction, table: pa.Table,
               group_names: List[str], alias: str):
    """Build (input col, arrow agg name, array, decode_float, options)."""
    if isinstance(func, eagg.Count) and not func.children:
        return (group_names[0] if group_names else table.column_names[0],
                "count_all", None, False, None)
    child = func.children[0]
    colname = f"__agg_in_{alias}"
    arr = _arr(cpu_eval(child, table), table.num_rows)
    kind = {
        eagg.Sum: "sum", eagg.Count: "count", eagg.Min: "min",
        eagg.Max: "max", eagg.Average: "mean",
        eagg.First: "first", eagg.Last: "last",
        eagg.CollectList: "list", eagg.CollectSet: "distinct",
        eagg.StddevSamp: "stddev", eagg.StddevPop: "stddev",
        eagg.VarianceSamp: "variance", eagg.VariancePop: "variance",
    }[type(func)]
    options = None
    if isinstance(func, eagg.CentralMoment):
        options = pc.VarianceOptions(ddof=func.ddof)
        arr = pc.cast(arr, pa.float64(), safe=False)
    decode = False
    at = arr.type if not isinstance(arr, pa.ChunkedArray) else arr.type
    if kind in ("min", "max") and pa.types.is_floating(at):
        arr = _np_float_encode(arr)
        decode = True
    return colname, kind, arr, decode, options


class CpuAggregate(CpuExec):
    """Whole-input aggregation (single partition input) via pa group_by."""

    def __init__(self, group_exprs, aggs: List[L.AggExpr],
                 child: PhysicalPlan):
        super().__init__(child)
        self.group_exprs = group_exprs
        self.aggs = aggs

    @property
    def output_schema(self):
        from ..columnar.schema import Field
        fields = [Field(ec.output_name(e), e.dtype(), e.nullable)
                  for e in self.group_exprs]
        fields += [Field(a.alias, a.func.dtype(), a.func.nullable)
                   for a in self.aggs]
        return Schema(fields)

    def num_partitions_hint(self):
        return 1

    def execute(self):
        child_parts = self.children[0].execute()
        child_schema = schema_to_arrow(self.children[0].output_schema)

        def run():
            tables = [t for p in child_parts for t in p]
            t = _concat_tables(tables, child_schema)
            yield self._aggregate(t)
        return [run()]

    def _aggregate(self, t: pa.Table) -> pa.Table:
        out_schema = schema_to_arrow(self.output_schema)
        group_names = []
        work = t
        for i, e in enumerate(self.group_exprs):
            name = f"__key_{i}"
            arr = _arr(cpu_eval(e, t), t.num_rows)
            work = work.append_column(name, arr)
            group_names.append(name)
        agg_specs = []
        decodes = []
        for a in self.aggs:
            colname, kind, arr, decode, options = _agg_arrow(
                a.func, t, group_names, a.alias)
            decodes.append(decode)
            if arr is not None:
                work = work.append_column(colname, arr)
            if kind == "count_all":
                agg_specs.append(([], "count_all"))
            elif options is not None:
                agg_specs.append((colname, kind, options))
            else:
                agg_specs.append((colname, kind))
        if group_names:
            gb = pa.TableGroupBy(work, group_names, use_threads=False)
            res = gb.aggregate(agg_specs)
            cols = []
            for i, e in enumerate(self.group_exprs):
                cols.append(res.column(f"__key_{i}"))
            for (colname, kind), a, decode in zip(
                    [(c if not isinstance(c, list) else "", s[1])
                     for s in agg_specs for c in [s[0]]],
                    self.aggs, decodes):
                res_name = "count_all" if kind == "count_all" else \
                    f"{colname}_{kind}"
                c = res.column(res_name)
                if decode:
                    c = _np_float_decode(
                        c, schema_to_arrow(Schema([])).field if False else
                        pa.float64())
                cols.append(c)
            arrays = []
            for c, f in zip(cols, out_schema):
                c = c.combine_chunks() if isinstance(c, pa.ChunkedArray) else c
                if c.type != f.type:
                    c = pc.cast(c, f.type, safe=False)
                arrays.append(c)
            out = pa.Table.from_arrays(arrays, schema=out_schema)
        else:
            # global aggregate -> single row
            arrays = []
            for spec, a, f in zip(agg_specs, self.aggs,
                                  list(out_schema)):
                colname, kind = spec[0], spec[1]
                opts = spec[2] if len(spec) > 2 else None
                if kind == "count_all":
                    val = pa.scalar(work.num_rows, pa.int64())
                elif kind in ("stddev", "variance"):
                    col = work.column(colname)
                    fn = pc.stddev if kind == "stddev" else pc.variance
                    val = fn(col, ddof=opts.ddof if opts else 0)
                else:
                    col = work.column(colname)
                    fn = {"sum": pc.sum, "count": pc.count, "min": pc.min,
                          "max": pc.max, "mean": pc.mean,
                          "first": pc.first, "last": pc.last}[kind]
                    val = fn(col)
                    if decodes[len(arrays)]:
                        val = _np_float_decode(
                            pa.array([val.as_py()], pa.uint64()),
                            pa.float64())[0]
                arr = pa.array([val.as_py()],
                               type=val.type if val.type != pa.null()
                               else f.type)
                if arr.type != f.type:
                    arr = pc.cast(arr, f.type, safe=False)
                arrays.append(arr)
            out = pa.Table.from_arrays(arrays, schema=out_schema)
        self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
        return out


class CpuJoin(CpuExec):
    def __init__(self, logical: L.Join, left: PhysicalPlan,
                 right: PhysicalPlan):
        super().__init__(left, right)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def num_partitions_hint(self):
        return 1

    def execute(self):
        lg = self.logical
        lparts = self.children[0].execute()
        rparts = self.children[1].execute()
        lschema = schema_to_arrow(self.children[0].output_schema)
        rschema = schema_to_arrow(self.children[1].output_schema)

        def run():
            lt = _concat_tables([t for p in lparts for t in p], lschema)
            rt = _concat_tables([t for p in rparts for t in p], rschema)
            yield self._join(lt, rt)
        return [run()]

    def _join(self, lt: pa.Table, rt: pa.Table) -> pa.Table:
        lg = self.logical
        out_schema = schema_to_arrow(self.output_schema)
        if lg.condition is not None and lg.join_type != "cross":
            # residual restricts pairs, not rows: expand inner pairs,
            # filter, then derive outer/semi/anti rows from survivors
            return self._join_residual(lt, rt)
        # pyarrow's hash join rejects nested payload columns: replace them
        # with row-index surrogates, join, then gather them back
        nested_l = [n for n, f in zip(lt.column_names, lt.schema)
                    if pa.types.is_nested(f.type)]
        nested_r = [n for n, f in zip(rt.column_names, rt.schema)
                    if pa.types.is_nested(f.type)]
        if nested_l or nested_r:
            lidx = pa.array(np.arange(lt.num_rows, dtype=np.int64))
            ridx = pa.array(np.arange(rt.num_rows, dtype=np.int64))
            lsub, rsub = lt, rt
            for n in nested_l:
                i = lsub.column_names.index(n)
                lsub = lsub.set_column(
                    i, pa.field("__sur_l_" + n, pa.int64()), lidx)
            for n in nested_r:
                i = rsub.column_names.index(n)
                rsub = rsub.set_column(
                    i, pa.field("__sur_r_" + n, pa.int64()), ridx)
            joined = self._join_raw(lsub, rsub, key_src=(lt, rt))
            arrays = []
            for i, f in enumerate(out_schema):
                c = joined.column(i).combine_chunks()
                name = joined.column_names[i]
                if name.startswith("__sur_l_"):
                    c = lt.column(name[len("__sur_l_"):]) \
                        .combine_chunks().take(c)
                elif name.startswith("__sur_r_"):
                    c = rt.column(name[len("__sur_r_"):]) \
                        .combine_chunks().take(c)
                if c.type != f.type:
                    c = pc.cast(c, f.type, safe=False)
                arrays.append(c)
            out = pa.Table.from_arrays(arrays, schema=out_schema)
            self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
            return out
        return self._finish(self._join_raw(lt, rt, key_src=(lt, rt)),
                            out_schema)

    def _join_raw(self, lt: pa.Table, rt: pa.Table, key_src) -> pa.Table:
        """Hash join returning the positional result table (keys dropped);
        key expressions evaluate against ``key_src`` (the pre-surrogate
        originals)."""
        lg = self.logical
        if lg.join_type == "cross":
            # cross via dummy constant keys
            lk = lt.append_column("__ck", pa.array([1] * lt.num_rows))
            rk = rt.append_column("__ck", pa.array([1] * rt.num_rows))
            res = lk.join(rk, keys=["__ck"], join_type="inner",
                          use_threads=False)
            return res.drop_columns(["__ck"])
        lsrc, rsrc = key_src
        lkeys, rkeys = [], []
        lwork, rwork = lt, rt
        for i, (le, re) in enumerate(zip(lg.left_keys, lg.right_keys)):
            lname, rname = f"__lk_{i}", f"__rk_{i}"
            lwork = lwork.append_column(
                lname, _arr(cpu_eval(le, lsrc), lsrc.num_rows))
            rwork = rwork.append_column(
                rname, _arr(cpu_eval(re, rsrc), rsrc.num_rows))
            lkeys.append(lname)
            rkeys.append(rname)
        jt = {"inner": "inner", "left": "left outer", "right": "right outer",
              "full": "full outer", "semi": "left semi",
              "anti": "left anti"}[lg.join_type]
        res = lwork.join(rwork, keys=lkeys, right_keys=rkeys, join_type=jt,
                         use_threads=False,
                         coalesce_keys=False)
        drop = [c for c in res.column_names if c.startswith("__lk_")
                or c.startswith("__rk_")]
        return res.drop_columns(drop)

    def _join_residual(self, lt: pa.Table, rt: pa.Table) -> pa.Table:
        lg = self.logical
        out_schema = schema_to_arrow(self.output_schema)
        keys = {}
        for i, (le, re) in enumerate(zip(lg.left_keys, lg.right_keys)):
            keys[f"__k{i}"] = (_arr(cpu_eval(le, lt), lt.num_rows),
                              _arr(cpu_eval(re, rt), rt.num_rows))
        if keys:
            lkt = pa.table({**{k: v[0] for k, v in keys.items()},
                            "__lidx": pa.array(
                                np.arange(lt.num_rows, dtype=np.int64))})
            rkt = pa.table({**{f"{k}_r": v[1] for k, v in keys.items()},
                            "__ridx": pa.array(
                                np.arange(rt.num_rows, dtype=np.int64))})
            pairs = lkt.join(rkt, keys=list(keys),
                             right_keys=[f"{k}_r" for k in keys],
                             join_type="inner", use_threads=False,
                             coalesce_keys=False)
            lidx = pairs.column("__lidx").to_numpy().astype(np.int64)
            ridx = pairs.column("__ridx").to_numpy().astype(np.int64)
        else:
            # pure non-equi ON: nested-loop pairs (cartesian indices)
            lidx = np.repeat(np.arange(lt.num_rows, dtype=np.int64),
                             rt.num_rows)
            ridx = np.tile(np.arange(rt.num_rows, dtype=np.int64),
                           lt.num_rows)
        ptab = pa.Table.from_arrays(
            [lt.column(n).take(lidx) for n in lt.column_names] +
            [rt.column(n).take(ridx) for n in rt.column_names],
            names=list(lt.column_names) + list(rt.column_names))
        m = pc.fill_null(pc.cast(
            _arr(cpu_eval(lg.condition, ptab), ptab.num_rows),
            pa.bool_()), False).to_numpy(zero_copy_only=False)
        lidx, ridx = lidx[m], ridx[m]
        jt = lg.join_type
        if jt in ("semi", "anti"):
            hit = np.zeros(lt.num_rows, dtype=bool)
            hit[lidx] = True
            sel = np.nonzero(hit if jt == "semi" else ~hit)[0]
            return self._finish(lt.take(pa.array(sel)), out_schema)
        li_parts, ri_parts = [lidx], [ridx]
        lm_parts = [np.zeros(len(lidx), dtype=bool)]
        rm_parts = [np.zeros(len(ridx), dtype=bool)]
        if jt in ("left", "full"):
            un = np.setdiff1d(np.arange(lt.num_rows, dtype=np.int64), lidx)
            li_parts.append(un)
            ri_parts.append(np.zeros(len(un), dtype=np.int64))
            lm_parts.append(np.zeros(len(un), dtype=bool))
            rm_parts.append(np.ones(len(un), dtype=bool))
        if jt in ("right", "full"):
            un = np.setdiff1d(np.arange(rt.num_rows, dtype=np.int64), ridx)
            li_parts.append(np.zeros(len(un), dtype=np.int64))
            ri_parts.append(un)
            lm_parts.append(np.ones(len(un), dtype=bool))
            rm_parts.append(np.zeros(len(un), dtype=bool))
        l_take = pa.array(np.concatenate(li_parts),
                          mask=np.concatenate(lm_parts))
        r_take = pa.array(np.concatenate(ri_parts),
                          mask=np.concatenate(rm_parts))
        res = pa.Table.from_arrays(
            [lt.column(n).take(l_take) for n in lt.column_names] +
            [rt.column(n).take(r_take) for n in rt.column_names],
            names=list(lt.column_names) + list(rt.column_names))
        return self._finish(res, out_schema)

    def _finish(self, res: pa.Table, out_schema: pa.Schema) -> pa.Table:
        # positional mapping (duplicate column names are legal post-join)
        assert res.num_columns == len(out_schema), \
            f"join output width {res.num_columns} != {len(out_schema)}"
        arrays = []
        for i, f in enumerate(out_schema):
            c = res.column(i).combine_chunks()
            if c.type != f.type:
                c = pc.cast(c, f.type, safe=False)
            arrays.append(c)
        out = pa.Table.from_arrays(arrays, schema=out_schema)
        self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
        return out


class CpuSort(CpuExec):
    def __init__(self, orders: List[L.SortOrder], child: PhysicalPlan,
                 is_global: bool = True):
        super().__init__(child)
        self.orders = orders
        self.is_global = is_global

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        return 1 if self.is_global else self.children[0].num_partitions_hint()

    def execute(self):
        child_schema = schema_to_arrow(self.children[0].output_schema)

        def sort_table(t: pa.Table) -> pa.Table:
            work = t
            keys = []
            for i, o in enumerate(self.orders):
                name = f"__sort_{i}"
                arr = _arr(cpu_eval(o.expr, t), t.num_rows)
                at = arr.type
                if pa.types.is_floating(at):
                    # Spark float total order (NaN greatest); pyarrow groups
                    # NaN with nulls under at_start placement
                    arr = _np_float_encode(arr)
                # pyarrow sort_keys are (name, order) pairs with ONE
                # global null_placement; per-key placement is encoded
                # as a leading null-indicator key instead (nulls tie
                # within their group, so the value key is unaffected)
                null_ind = pc.is_null(arr)
                if o.effective_nulls_first:
                    null_ind = pc.invert(null_ind)
                work = work.append_column(
                    f"{name}_nulls", pc.cast(null_ind, pa.int8()))
                work = work.append_column(name, arr)
                keys.append((f"{name}_nulls", "ascending"))
                keys.append((name,
                             "ascending" if o.ascending else "descending"))
            idx = pc.sort_indices(work, sort_keys=keys,
                                  null_placement="at_end")
            return t.take(idx)

        if self.is_global:
            parts = self.children[0].execute()

            def run():
                t = _concat_tables([t for p in parts for t in p],
                                   child_schema)
                yield sort_table(t)
            return [run()]

        def run_local(part):
            t = _concat_tables(list(part), child_schema)
            yield sort_table(t)
        return [run_local(p) for p in self.children[0].execute()]


class CpuLimit(CpuExec):
    def __init__(self, n: int, child: PhysicalPlan, offset: int = 0):
        super().__init__(child)
        self.n = n
        self.offset = offset

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        return 1

    def execute(self):
        child_schema = schema_to_arrow(self.children[0].output_schema)
        parts = self.children[0].execute()

        def run():
            need = self.n + self.offset
            got: List[pa.Table] = []
            have = 0
            for p in parts:
                for t in p:
                    if have >= need:
                        break
                    t = t.slice(0, need - have)
                    got.append(t)
                    have += t.num_rows
            out = _concat_tables(got, child_schema)
            yield out.slice(self.offset, self.n)
        return [run()]


class CpuExpand(CpuExec):
    """Oracle for grouping-sets Expand: one output table per projection.

    Reference behavior: Spark ExpandExec (each input row emitted once per
    projection, absent grouping keys null-filled)."""

    def __init__(self, logical, child: PhysicalPlan):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def execute(self):
        out_schema = schema_to_arrow(self.output_schema)

        def run(part):
            for t in part:
                for proj in self.logical.projections:
                    arrays = []
                    for e, f in zip(proj, out_schema):
                        a = _arr(cpu_eval(e, t), t.num_rows)
                        if a.type != f.type:
                            a = pc.cast(a, f.type, safe=False)
                        arrays.append(a)
                    out = pa.Table.from_arrays(arrays, schema=out_schema)
                    self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
                    yield out
        return [run(p) for p in self.children[0].execute()]


class CpuGenerate(CpuExec):
    """Oracle for explode/posexplode — plain Python row expansion.

    Reference behavior: Spark GenerateExec with Explode/PosExplode
    generators (outer variants emit one null row for empty/null input).
    """

    def __init__(self, logical, child: PhysicalPlan):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def execute(self):
        gen = self.logical.generator
        out_schema = schema_to_arrow(self.output_schema)

        def run(part):
            for t in part:
                lists = _arr(cpu_eval(gen.children[0], t),
                             t.num_rows).to_pylist()
                base = [t.column(i).to_pylist()
                        for i in range(t.num_columns)]
                n_extra = 2 if gen.pos else 1
                out_cols = [[] for _ in range(t.num_columns + n_extra)]
                for i, lst in enumerate(lists):
                    if lst is None or len(lst) == 0:
                        if not gen.outer:
                            continue
                        items = [(None, None)]
                    else:
                        items = list(enumerate(lst))
                    for p, v in items:
                        for ci in range(t.num_columns):
                            out_cols[ci].append(base[ci][i])
                        if gen.pos:
                            out_cols[t.num_columns].append(p)
                        out_cols[-1].append(v)
                arrays = [pa.array(vals, type=f.type)
                          for vals, f in zip(out_cols, out_schema)]
                out = pa.Table.from_arrays(arrays, schema=out_schema)
                self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
                yield out
        return [run(p) for p in self.children[0].execute()]


class CpuUnion(CpuExec):
    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        return sum(c.num_partitions_hint() for c in self.children)

    def execute(self):
        parts = []
        target = schema_to_arrow(self.output_schema)
        for c in self.children:
            for p in c.execute():
                def conv(p=p):
                    for t in p:
                        if t.schema != target:
                            t = pa.Table.from_arrays(
                                [pc.cast(t.column(i).combine_chunks(),
                                         f.type, safe=False)
                                 for i, f in enumerate(target)],
                                schema=target)
                        yield t
                parts.append(conv())
        return parts


class CpuCoalescePartitions(CpuExec):
    """Merge all partitions into one (used before global ops)."""

    def __init__(self, child: PhysicalPlan):
        super().__init__(child)

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        return 1

    def execute(self):
        parts = self.children[0].execute()

        def run():
            for p in parts:
                for t in p:
                    yield t
        return [run()]


class CpuShuffleExchange(CpuExec):
    """Hash/round-robin repartition on the CPU engine."""

    def __init__(self, child: PhysicalPlan, num_partitions: int,
                 key_exprs: Optional[List[ec.Expression]] = None):
        super().__init__(child)
        self.num_partitions = num_partitions
        self.key_exprs = key_exprs

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        return self.num_partitions

    def execute(self):
        child_schema = schema_to_arrow(self.children[0].output_schema)
        in_parts = self.children[0].execute()
        buckets: List[List[pa.Table]] = [[] for _ in
                                         range(self.num_partitions)]
        rr = itertools.count()
        for p in in_parts:
            for t in p:
                if t.num_rows == 0:
                    continue
                if not self.key_exprs:
                    buckets[next(rr) % self.num_partitions].append(t)
                    continue
                pids = self._partition_ids(t)
                for pid in np.unique(pids):
                    mask = pa.array(pids == pid)
                    buckets[int(pid)].append(t.filter(mask))
        return [iter([_concat_tables(b, child_schema)]) for b in buckets]

    def _partition_ids(self, t: pa.Table) -> np.ndarray:
        # must match the TPU hash partitioner exactly so mixed CPU/TPU plans
        # agree on row placement -> reuse the device kernel on CPU jax
        from ..columnar.arrow import from_arrow
        from ..kernels import basic, canon
        batch = from_arrow(t)
        cols = []
        word_lists = []
        for e in self.key_exprs:
            bound = e.bind(batch.schema)
            col = ec.eval_as_column(bound, batch)
            for w in canon.value_words(col, batch.num_rows):
                import jax.numpy as jnp
                word_lists.append(
                    jnp.where(col.validity, w,
                              jnp.uint64(0x9E3779B97F4A7C15)))
        h = basic.hash_words(word_lists)
        pids = basic.hash_to_partition(h, self.num_partitions)
        from ..analysis import residency  # lazy: avoids import cycle
        with residency.declared_transfer(site="shuffle_serialize"):
            return np.asarray(pids)[:t.num_rows]
