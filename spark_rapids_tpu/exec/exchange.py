"""Exchange operators: shuffle + broadcast.

Reference: GpuShuffleExchangeExecBase (org/.../GpuShuffleExchangeExec.scala:98,
prepareBatchShuffleDependency :176) and GpuBroadcastExchangeExec.

Execution model: the map side runs eagerly when the reduce side first
pulls (a stage barrier, like Spark), splitting every batch with a device
partitioner and registering the slices in the shuffle catalog; reduce
partitions then stream from the catalog through the transport SPI.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

from ..columnar.batch import ColumnarBatch, concat_batches
from ..obs import flight as _flight
from ..obs import netplane as _netplane
from ..obs import trace as _trace
from ..shuffle.manager import ShuffleManager
from ..shuffle.partitioners import Partitioner, RangePartitioner
from .base import PhysicalPlan, PARTITION_TIME, NUM_OUTPUT_ROWS, timed
from .pipeline import drain_parallel
from .tpu_basic import TpuExec


class _DistWriter:
    """ShuffleManager facade writing into a ShuffleExecutorContext: map
    output lands in the executor's own catalog + its map registration in
    the (driver) tracker, so reducers in OTHER processes fetch it over
    the transport (RapidsCachingWriter + MapStatus round trip)."""

    def __init__(self, ctx, shuffle_id: int):
        self.ctx = ctx
        self.shuffle_id = shuffle_id

    def new_shuffle_id(self) -> int:
        return self.shuffle_id

    def append_map_output(self, shuffle_id, map_id, per_reduce):
        self.ctx.append_map_output(shuffle_id, map_id, per_reduce)


class TpuShuffleExchange(TpuExec):
    def __init__(self, child: PhysicalPlan, partitioner: Partitioner):
        super().__init__(child)
        self.partitioner = partitioner
        self._shuffle_id: Optional[int] = None
        # parallel reduce pulls (pipelined drains) race to trigger the
        # map stage; the barrier must run exactly once
        self._mat_lock = threading.Lock()
        self._materialized = False
        # distributed mode (executor-process split): set by
        # attach_distributed; None = in-process ShuffleManager
        self._dist_ctx = None
        self._dist_shuffle_id: Optional[int] = None
        self._dist_run_map = True

    def attach_distributed(self, ctx, shuffle_id: int, run_map: bool):
        """Split this exchange across OS processes: ``run_map=True``
        executes the map side into ``ctx``'s catalog (an executor
        serving fetches); ``run_map=False`` skips the local map stage
        (it ran in another process) and reduces via ``ctx``'s
        transport-aware read path."""
        self._dist_ctx = ctx
        self._dist_shuffle_id = shuffle_id
        self._dist_run_map = run_map

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        return self.partitioner.num_partitions

    def _node_string(self):
        return (f"TpuShuffleExchange[{type(self.partitioner).__name__}"
                f"({self.partitioner.num_partitions})]")

    def _materialize_map_side(self):
        t_map0 = time.perf_counter_ns()
        # netplane snapshot: attributes this exchange's serialize volume
        # in the map-side trace span (best-effort under concurrent
        # exchanges — the global matrix stays exact either way)
        np_marker = _netplane.begin_query()
        from ..columnar import pending
        from ..columnar.batch import resolve_speculative
        mgr = ShuffleManager.get() if self._dist_ctx is None else \
            _DistWriter(self._dist_ctx, self._dist_shuffle_id)
        self._shuffle_id = mgr.new_shuffle_id()
        in_parts = self.children[0].execute()
        # range partitioner needs bounds from a sample pass first
        if isinstance(self.partitioner, RangePartitioner) and \
                self.partitioner.bound_words is None:
            all_batches = [[b for b in p] for p in in_parts]
            sample = [b for part in all_batches for b in part]
            self.partitioner.fit(sample)
            in_parts = [iter(p) for p in all_batches]
        # Phase 1 (device-only): drain map partitions, staging the split
        # sort + boundary counts per batch — nothing pulls yet.
        # Phase 2: ONE fused flush resolves every count and every
        # speculative fit flag (columnar/pending.py); the rare batch
        # whose table-path assumptions failed is recomputed exactly here,
        # at the stage barrier, before any result is exposed.
        # Staging is BOUNDED: past mapStagingBytes of staged device data
        # (input + sorted copy) the exchange flushes, finalizes what is
        # staged, and APPENDS the pieces straight into the (spillable)
        # catalog — including the in-progress map partition — so device
        # memory held between flushes never exceeds the budget and hash
        # shuffles larger than device memory still stream.  (Range
        # exchanges materialized everything above for bound sampling;
        # the budget does not cover that path.)
        from ..config import get_active, SHUFFLE_MAP_STAGING_BYTES
        from ..obs import profile
        from ..obs import stats as obs_stats
        conf = get_active()
        budget = int(conf.get(SHUFFLE_MAP_STAGING_BYTES))
        n_red = self.partitioner.num_partitions
        stats_on = obs_stats.enabled(conf)
        if stats_on:
            acc = obs_stats.exchange_acc(
                self, n_red, obs_stats.sketch_registers(conf),
                obs_stats._row_width(self.output_schema), "shuffle",
                type(self.partitioner).__name__,
                obs_stats.sample_every(conf))
        # flushes forced at this barrier belong to the producing stage:
        # attribute to the fused superstage feeding the exchange when
        # there is one, else to the exchange itself (obs/profile.py)
        child = self.children[0]
        attrib_target = child if getattr(child, "lowering", None) \
            is not None else self
        staged = []        # (map_id, batch, (sorted_batch, counts), st)
        staged_bytes = 0

        def finalize_staged():
            nonlocal staged_bytes
            with profile.attrib_scope(attrib_target):
                # residency-audited: the map-side count pull rides this
                # one declared pending_flush region (RES001-clean) —
                # every per-batch split count resolves through the
                # fused pool, never an inline np.asarray
                pending.flush()
                per_reduce_by_map = {}
                for map_id, batch, (sorted_batch, counts), st in staged:
                    checked = resolve_speculative(batch)
                    if checked is not batch:
                        with timed(self.metrics[PARTITION_TIME], self):
                            sorted_batch, counts = \
                                self.partitioner.split_staged(checked)
                        if stats_on:
                            # the staged sketch saw the failed
                            # speculative batch; re-stage from the exact
                            # one BEFORE finalize_split forces the redo
                            # flush, which then resolves it for free.
                            # force only when a sketch was actually
                            # staged — a sampling-skipped batch stays
                            # skipped, keeping acc.sketched consistent
                            if st is not None:
                                st = obs_stats.stage_exchange_batch(
                                    self.partitioner, checked, acc.m,
                                    acc, force=True)
                    split = self.partitioner.finalize_split(sorted_batch,
                                                            counts)
                    if stats_on:
                        acc.absorb(split.offsets, st)
                    if split.offsets[-1] == 0:
                        continue
                    per_reduce = per_reduce_by_map.setdefault(map_id, {})
                    for pid in range(n_red):
                        piece = split.partition_slice(pid)
                        if piece is not None:
                            per_reduce.setdefault(pid, []).append(piece)
                staged.clear()
                staged_bytes = 0
                for map_id, per_reduce in per_reduce_by_map.items():
                    mgr.append_map_output(self._shuffle_id, map_id,
                                          per_reduce)

        def split_one(batch):
            # runs on pipeline producers (under the DeviceSemaphore):
            # the split's device dispatch + host prep for one map batch
            # overlaps the splits of other partitions in flight; the
            # stats sketch is enqueued in the SAME dispatch window so
            # it rides the finalize flush (zero extra round trips)
            with timed(self.metrics[PARTITION_TIME], self), \
                    profile.dispatch(profile.SITE_SPLIT):
                split = self.partitioner.split_staged(batch)
                st = obs_stats.stage_exchange_batch(
                    self.partitioner, batch, acc.m,
                    acc) if stats_on else None
                return batch, split, st

        # morsel-parallel map drain (exec/pipeline.py): partitions are
        # pulled + split concurrently, but arrive here in deterministic
        # (map_id, batch) order, so staging/flush boundaries — and the
        # map output — are identical to the serial drain's
        for map_id, (batch, split, st) in drain_parallel(
                in_parts, sink=split_one, label="shuffle_map"):
            staged.append((map_id, batch, split, st))
            staged_bytes += 2 * batch.nbytes()
            if staged_bytes > budget:
                finalize_staged()
        finalize_staged()
        if stats_on:
            obs_stats.finish_exchange(self, conf)
        _flight.record(_flight.EV_NET, "map_side", n_red)
        if _trace._ENABLED:
            net = _netplane.query_summary(np_marker)
            _trace.emit("exchange_map_side", "shuffle", t_map0,
                        time.perf_counter_ns() - t_map0,
                        shuffle_id=self._shuffle_id, partitions=n_red,
                        staged_bytes=net["staged_bytes"],
                        serialize_ms=net["phases_ms"]["serialize"])

    def ensure_materialized(self):
        """Run the map side once (the AQE stage-materialization barrier).

        Double-checked lock: concurrent reduce pulls (the pipelined
        collect drains partitions in parallel) must not double-run the
        map stage; losers block until the winner's outputs are fully
        registered.  ``_materialized`` is set only after the drain
        completes — ``_shuffle_id`` alone is assigned early inside
        ``_materialize_map_side`` and would leak a half-built stage.

        The whole barrier runs with the calling thread's device permits
        dropped (``sem.released()``): a reduce pull reaches here from
        inside a pipeline producer's permit-held dispatch region, and
        pinning that permit while a loser parks on ``_mat_lock`` — or
        while the winner runs the entire map-side drain, which acquires
        permits of its own — starves concurrent queries and can
        deadlock the nested drain's pool workers behind it.  Permits
        are reacquired to the same depth before returning to the pull."""
        if self._materialized:
            return
        from ..memory.arena import DeviceManager
        with DeviceManager.get().semaphore.released():
            with self._mat_lock:
                if self._materialized:
                    return
                if self._dist_ctx is not None and not self._dist_run_map:
                    # the map stage ran in another executor process; its
                    # outputs are registered in the shared tracker
                    self._shuffle_id = self._dist_shuffle_id
                else:
                    # the flush inside the map-side drain is the POINT
                    # of this barrier: stage outputs must be on device
                    # before any reduce pull proceeds, losers are
                    # SUPPOSED to park until then, and device permits
                    # are dropped for the whole region (above) so the
                    # wait cannot deadlock the dispatch pool
                    # lint: allow(LOCK003)
                    self._materialize_map_side()
                self._materialized = True

    def partition_stats(self):
        """Per-reduce-partition (bytes, rows) from the materialized map
        output — the MapOutputStatistics role AQE re-plans from."""
        self.ensure_materialized()
        # distributed mode: only THIS executor's blocks are visible
        # (remote stats would need a tracker protocol extension); AQE
        # then sees zeros for remote-only partitions and keeps the
        # static plan, which is correct if conservative
        cat = self._dist_ctx.catalog if self._dist_ctx is not None \
            else ShuffleManager.get().catalog
        stats = []
        for pid in range(self.partitioner.num_partitions):
            nbytes = rows = 0
            for block in cat.blocks_for_reduce(self._shuffle_id, pid):
                nb, nr = cat.stats_for_block(block)
                nbytes += nb
                rows += nr
            stats.append((nbytes, rows))
        return stats

    def stream_reduce(self, reduce_id: int):
        """Stream one reduce partition batch-by-batch (batches unspill
        one at a time — the memory-bounded path)."""
        self.ensure_materialized()
        _flight.record(_flight.EV_NET, "reduce_stream", reduce_id)
        if self._dist_ctx is not None:
            # transport-aware read: local blocks from this executor's
            # catalog, remote ones fetched over the wire
            for b in self._dist_ctx.read_partition(self._shuffle_id,
                                                   reduce_id):
                self.metrics[NUM_OUTPUT_ROWS] += b.rows_lazy
                yield b
            return
        mgr = ShuffleManager.get()
        for b in mgr.read_partition(self._shuffle_id, reduce_id):
            self.metrics[NUM_OUTPUT_ROWS] += b.rows_lazy
            yield b

    def read_reduce(self, reduce_id: int):
        """All batches of one reduce partition as a list — for AQE
        callers that re-group/slice partitions; plain execution streams
        via stream_reduce instead."""
        return list(self.stream_reduce(reduce_id))

    def execute(self):
        schema = self.output_schema

        def reduce_iter(reduce_id):
            got = False
            for b in self.stream_reduce(reduce_id):
                got = True
                yield b
            if not got:
                yield ColumnarBatch.empty(schema)
        return [reduce_iter(i)
                for i in range(self.partitioner.num_partitions)]


class TpuBroadcastExchange(TpuExec):
    """Concat the whole input into one batch, replicated to consumers.

    Reference: GpuBroadcastExchangeExec.scala:48."""

    def __init__(self, child: PhysicalPlan):
        super().__init__(child)
        self._result: Optional[ColumnarBatch] = None
        # concurrent probes (pipelined drains pull both join sides in
        # parallel) must build once; losers block until the winner
        # publishes — the double-checked lock below
        self._build_lock = threading.Lock()

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        return 1

    def broadcast_batch(self) -> ColumnarBatch:
        from ..columnar.batch import resolve_speculative
        from ..memory.arena import DeviceManager
        from ..service.cancellation import cancel_checkpoint
        if self._result is not None:
            return self._result
        # probes reach this barrier from inside a pipeline producer's
        # permit-held pull region; the build (and the loser park on
        # _build_lock) runs with those permits dropped — same deadlock/
        # starvation rationale as ensure_materialized — and reacquires
        # them before the probe resumes
        with DeviceManager.get().semaphore.released():
            with self._build_lock:
                if self._result is not None:
                    return self._result
                # the build side materializes in full before the first
                # probe batch: checkpoint per pulled batch so
                # cancellation can unwind the drain; the pull itself is
                # a (possibly nested) morsel-parallel drain
                raw = []
                for _pid, b in drain_parallel(self.children[0].execute(),
                                              label="broadcast_build"):
                    cancel_checkpoint()
                    raw.append(b)
                if len(raw) == 1:
                    # single-batch build side (the dominant dimension-
                    # table shape): pass through WITHOUT forcing the
                    # host count — consumers key off device counts
                    # (canon rank words mask dead rows) and resolve any
                    # speculative flag at their own flush barrier, so
                    # the broadcast costs zero round trips here
                    self._result = raw[0]
                else:
                    from ..obs import profile
                    child = self.children[0]
                    target = child if getattr(child, "lowering", None) \
                        is not None else self
                    with profile.attrib_scope(target):
                        batches = [resolve_speculative(b) for b in raw]
                        batches = [b for b in batches if b.num_rows > 0]
                    self._result = concat_batches(batches) if batches \
                        else ColumnarBatch.empty(self.output_schema)
                from ..obs import stats as obs_stats
                # unconditional: a bare attribute store, so no conf
                # lookup on this helper thread (ambient-conf fallback
                # is unreliable off the session/pipeline threads); the
                # session's own conf gates everything at profile-build
                # time, and rows read lazily there — the single-batch
                # path stays zero-round-trip
                obs_stats.note_broadcast(self, self._result)
        return self._result

    def execute(self):
        return [iter([self.broadcast_batch()])]


class TpuCoalescePartitions(TpuExec):
    """N partitions -> 1 without reordering (single partitioning exchange)."""

    def __init__(self, child: PhysicalPlan):
        super().__init__(child)

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        return 1

    def execute(self):
        parts = self.children[0].execute()

        def run():
            for p in parts:
                for b in p:
                    yield b
        return [run()]
