"""TPU physical operators: scan/project/filter/limit/union/transitions.

Reference analogues: GpuProjectExec/GpuFilterExec/GpuLocalLimitExec/
GpuUnionExec (basicPhysicalOperators.scala, limit.scala),
GpuRowToColumnarExec/GpuColumnarToRowExec (transitions),
GpuCoalesceBatches (GpuCoalesceBatches.scala:195).
"""
from __future__ import annotations

import threading
from typing import Iterator, List, Optional

import numpy as np
import jax.numpy as jnp
import pyarrow as pa

from ..columnar import dtypes as T
from ..columnar.schema import Field, Schema
from ..columnar.column import Column, bucket_capacity
from ..columnar.batch import ColumnarBatch, LazyCount, concat_batches
from ..columnar.arrow import from_arrow, to_arrow, schema_to_arrow
from ..expr import core as ec
from ..kernels import basic as bk
from .base import (PhysicalPlan, NUM_OUTPUT_ROWS, NUM_OUTPUT_BATCHES,
                   OP_TIME, CONCAT_TIME, timed)


class TpuExec(PhysicalPlan):
    columnar = True


class TpuLocalScan(TpuExec):
    def __init__(self, table: pa.Table, num_partitions: int = 1,
                 batch_rows: int = 1 << 20):
        super().__init__()
        self.table = table
        self.num_partitions = max(1, num_partitions)
        self.batch_rows = batch_rows

    @property
    def output_schema(self):
        from ..columnar.arrow import schema_from_arrow
        return schema_from_arrow(self.table.schema)

    def num_partitions_hint(self):
        return self.num_partitions

    # host->device uploads dominate repeated queries over the same local
    # table (remote-dispatch transfer bandwidth is the scarce resource),
    # so uploaded batches are kept device-resident per source table —
    # a small LRU so HBM stays bounded.
    _DEVICE_CACHE: "OrderedDict" = None
    # concurrent scans (pipelined drains + concurrent service queries)
    # mutate the class-level LRU; all get/move_to_end/set/evict steps
    # run under this lock.  The upload loop below stays OUTSIDE it:
    # from_arrow only dispatches (lazy device upload, no blocking), but
    # serializing uploads under a class-wide lock would still defeat
    # the pipeline's overlap — only the dict ops need the lock.
    _DEVICE_CACHE_LOCK = threading.Lock()
    # key -> (table, Event) while a miss is uploading: concurrent
    # misses on the same key wait for the first builder instead of each
    # uploading the full partition set (transient double HBM residency
    # for large tables, last-write-wins churn).  A builder that fails
    # pops its sentinel in the finally, so waiters retry and one of
    # them becomes the next builder.
    _DEVICE_CACHE_BUILDING: dict = {}

    def _cached_batches(self):
        from collections import OrderedDict
        from ..service.cancellation import cancel_checkpoint
        cls = TpuLocalScan
        key = (id(self.table), self.num_partitions, self.batch_rows)
        while True:
            with cls._DEVICE_CACHE_LOCK:
                if cls._DEVICE_CACHE is None:
                    cls._DEVICE_CACHE = OrderedDict()
                hit = cls._DEVICE_CACHE.get(key)
                if hit is not None and hit[0] is self.table:
                    cls._DEVICE_CACHE.move_to_end(key)
                    return hit[1]
                building = cls._DEVICE_CACHE_BUILDING.get(key)
                if building is None:
                    done = threading.Event()
                    cls._DEVICE_CACHE_BUILDING[key] = (self.table, done)
                    break
                done = building[1]
            # a peer is uploading this key (ours, or — after id reuse —
            # another table's): park OUTSIDE the lock, checkpointed so
            # cancellation unwinds a waiter, then re-check from the top
            while not done.wait(0.05):
                cancel_checkpoint()
        try:
            n = self.table.num_rows
            per = -(-n // self.num_partitions) if n else 0
            parts = []
            for i in range(self.num_partitions):
                lo = min(i * per, n)
                hi = min(lo + per, n)
                batches = []
                pos = lo
                while pos < hi:
                    k = min(self.batch_rows, hi - pos)
                    batches.append(from_arrow(self.table.slice(pos, k)))
                    pos += k
                if lo == hi and lo == 0 and self.num_partitions == 1:
                    batches.append(from_arrow(self.table.slice(0, 0)))
                parts.append(batches)
            with cls._DEVICE_CACHE_LOCK:
                cls._DEVICE_CACHE[key] = (self.table, parts)
                while len(cls._DEVICE_CACHE) > 8:
                    cls._DEVICE_CACHE.popitem(last=False)
        finally:
            with cls._DEVICE_CACHE_LOCK:
                cls._DEVICE_CACHE_BUILDING.pop(key, None)
            done.set()
        return parts

    def execute(self):
        from ..obs import stats as obs_stats
        if obs_stats.enabled():
            # exact per-partition sizes from the slicing arithmetic —
            # zero device work (stats plane, obs/stats.py)
            n = self.table.num_rows
            per = -(-n // self.num_partitions) if n else 0
            obs_stats.note_scan(self, [
                min(i * per + per, n) - min(i * per, n)
                for i in range(self.num_partitions)])
        return [iter(batches) for batches in self._cached_batches()]


class TpuRange(TpuExec):
    """Reference: GpuRangeExec (basicPhysicalOperators.scala:245)."""

    def __init__(self, start, end, step, num_partitions,
                 batch_rows: int = 1 << 20):
        super().__init__()
        self.start, self.end, self.step = start, end, step
        self.num_partitions = max(1, num_partitions)
        self.batch_rows = batch_rows

    @property
    def output_schema(self):
        return Schema([Field("id", T.INT64, False)])

    def num_partitions_hint(self):
        return self.num_partitions

    def execute(self):
        total = max(0, -(-(self.end - self.start) // self.step))
        per = -(-total // self.num_partitions) if total else 0
        from ..obs import stats as obs_stats
        if obs_stats.enabled():
            obs_stats.note_scan(self, [
                max(0, min((i + 1) * per, total) - i * per)
                for i in range(self.num_partitions)])
        parts = []
        for i in range(self.num_partitions):
            lo, hi = i * per, min((i + 1) * per, total)

            def gen(lo=lo, hi=hi):
                pos = lo
                while pos < hi:
                    k = min(self.batch_rows, hi - pos)
                    cap = bucket_capacity(k)
                    ids = (self.start +
                           (jnp.arange(cap, dtype=jnp.int64) + pos) *
                           self.step)
                    col = Column(T.INT64, ids, jnp.arange(cap) < k)
                    yield ColumnarBatch(self.output_schema, [col], k)
                    pos += k
                if hi <= lo:
                    yield ColumnarBatch.empty(self.output_schema)
            parts.append(gen())
        return parts


class TpuProject(TpuExec):
    """Reference: GpuProjectExec (basicPhysicalOperators.scala:83)."""

    def __init__(self, exprs: List[ec.Expression], child: PhysicalPlan):
        super().__init__(child)
        self.exprs = exprs
        self._bound: Optional[List[ec.Expression]] = None

    @property
    def output_schema(self):
        return Schema([Field(ec.output_name(e), e.dtype(), e.nullable)
                       for e in self.exprs])

    def execute(self):
        from .fused import FusedEval
        child_schema = self.children[0].output_schema
        bound = [e.bind(child_schema) for e in self.exprs]
        out_schema = self.output_schema
        fused = FusedEval(bound, child_schema)

        def project_one(batch):
            cols = fused(batch)
            if cols is None:
                cols = [ec.eval_as_column(b, batch) for b in bound]
            return ColumnarBatch(out_schema, cols, batch.rows_lazy)

        def run(part):
            from ..columnar.batch import chain_speculative
            for batch in part:
                with timed(self.metrics[OP_TIME], self):
                    # chain, don't drop, a speculative input's fit flags:
                    # projection preserves row identity, so the consumer's
                    # barrier can vouch for input + output together
                    out = chain_speculative(project_one(batch), batch,
                                            project_one)
                self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
                self.metrics[NUM_OUTPUT_BATCHES] += 1
                yield out
        return [run(p) for p in self.children[0].execute()]

    def _node_string(self):
        return f"TpuProject[{', '.join(ec.output_name(e) for e in self.exprs)}]"


class TpuFilter(TpuExec):
    """Reference: GpuFilterExec — boolean mask + compaction gather."""

    def __init__(self, condition: ec.Expression, child: PhysicalPlan):
        super().__init__(child)
        self.condition = condition

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def execute(self):
        from .fused import FusedEval
        child_schema = self.children[0].output_schema
        bound = self.condition.bind(child_schema)
        fused = FusedEval([bound], child_schema)

        def filter_one(batch):
            fcols = fused(batch)
            pred = fcols[0] if fcols is not None else \
                ec.eval_as_column(bound, batch)
            keep = pred.data.astype(bool) & pred.validity
            idx, cnt = bk.compact_indices(keep, batch.rows_dev)
            # keep the count on device: pulling it per batch
            # costs a full dispatch-queue sync (LazyCount doc)
            n = LazyCount(cnt)
            mask = jnp.arange(batch.capacity) < cnt
            out = batch.gather(idx, n, live=mask, unique=True)
            return ColumnarBatch(
                out.schema,
                [c.mask_validity(mask) for c in out.columns], n)

        def run(part):
            from ..columnar.batch import chain_speculative
            for batch in part:
                with timed(self.metrics[OP_TIME], self):
                    out = chain_speculative(filter_one(batch), batch,
                                            filter_one)
                self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
                self.metrics[NUM_OUTPUT_BATCHES] += 1
                yield out
        return [run(p) for p in self.children[0].execute()]

    def _node_string(self):
        return f"TpuFilter[{self.condition!r}]"


class TpuCoalesceBatches(TpuExec):
    """Concat small batches up to a rows/bytes goal.

    Reference: GpuCoalesceBatches + AbstractGpuCoalesceIterator
    (GpuCoalesceBatches.scala:195,402).
    """

    def __init__(self, child: PhysicalPlan, target_rows: int = 1 << 20,
                 target_bytes: int = 512 << 20):
        super().__init__(child)
        self.target_rows = target_rows
        self.target_bytes = target_bytes

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def execute(self):
        def run(part):
            from ..columnar.batch import resolve_speculative
            pending: List[ColumnarBatch] = []
            rows = 0
            nbytes = 0
            for batch in part:
                # the count reads below are a forcing point: verify a
                # speculative input first (forcing an unverified count
                # would bake a wrong value into the limit bookkeeping)
                batch = resolve_speculative(batch)
                if batch.num_rows == 0 and pending:
                    continue
                pending.append(batch)
                rows += batch.num_rows
                nbytes += batch.nbytes()
                if rows >= self.target_rows or nbytes >= self.target_bytes:
                    with timed(self.metrics[CONCAT_TIME], self):
                        yield concat_batches(pending)
                    pending, rows, nbytes = [], 0, 0
            if pending:
                with timed(self.metrics[CONCAT_TIME], self):
                    yield concat_batches(pending)
        return [run(p) for p in self.children[0].execute()]


def _limit_head_lazy(batch: ColumnarBatch, n: int):
    """head-n entirely on device counts — no host pull, propagating any
    speculative flag (superstage path: the collect/exchange barrier then
    resolves limit + sort + agg + join fits in ONE fused flush)."""
    from ..columnar.batch import LazyCount, chain_speculative
    from ..columnar.column import bucket_capacity
    cap = min(bucket_capacity(max(n, 1)), batch.capacity)
    out_n = jnp.minimum(batch.rows_dev, jnp.int32(n))
    take = jnp.arange(cap)
    live = take < out_n
    cols = [c.gather(take, live=live).mask_validity(live)
            for c in batch.columns]
    out = ColumnarBatch(batch.schema, cols, LazyCount(out_n))

    def redo(fixed):
        return fixed if fixed.num_rows <= n else fixed.slice(0, n)
    return chain_speculative(out, batch, redo)


def _limit_lazy_ok(batch: ColumnarBatch) -> bool:
    """A lazy head pays off (and is needed for correctness ordering)
    only when the count is still device-resident or the batch carries
    unverified fit flags."""
    return not isinstance(batch.rows_lazy, int) or \
        getattr(batch, "_speculative", None) is not None


class TpuLocalLimit(TpuExec):
    def __init__(self, n: int, child: PhysicalPlan):
        super().__init__(child)
        self.n = n

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def execute(self):
        def run(part):
            from ..columnar.batch import resolve_speculative
            it = iter(part)
            first = next(it, None)
            if first is None:
                return
            second = next(it, None)
            if second is None and _limit_lazy_ok(first):
                # single device-counted batch: take the head without a
                # host round trip
                yield _limit_head_lazy(first, self.n)
                return
            remaining = self.n
            for batch in [b for b in (first, second)
                          if b is not None] + list(it):
                if remaining <= 0:
                    break
                batch = resolve_speculative(batch)
                if batch.num_rows <= remaining:
                    remaining -= batch.num_rows
                    yield batch
                else:
                    yield batch.slice(0, remaining)
                    remaining = 0
        return [run(p) for p in self.children[0].execute()]


class TpuGlobalLimit(TpuExec):
    """Single-partition global limit with offset."""

    def __init__(self, n: int, child: PhysicalPlan, offset: int = 0):
        super().__init__(child)
        self.n = n
        self.offset = offset

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        return 1

    def execute(self):
        parts = self.children[0].execute()

        def run():
            from ..columnar.batch import resolve_speculative
            if len(parts) == 1 and self.offset == 0:
                it = iter(parts[0])
                first = next(it, None)
                if first is None:
                    return
                second = next(it, None)
                if second is None and _limit_lazy_ok(first):
                    yield _limit_head_lazy(first, self.n)
                    return
                parts[0] = [b for b in (first, second)
                            if b is not None] + list(it)
            skip = self.offset
            remaining = self.n
            for p in parts:
                for batch in p:
                    if remaining <= 0:
                        return
                    batch = resolve_speculative(batch)
                    if skip >= batch.num_rows:
                        skip -= batch.num_rows
                        continue
                    if skip > 0:
                        batch = batch.slice(skip, batch.num_rows - skip)
                        skip = 0
                    if batch.num_rows > remaining:
                        batch = batch.slice(0, remaining)
                    remaining -= batch.num_rows
                    yield batch
        return [run()]


class TpuUnion(TpuExec):
    def __init__(self, *children):
        super().__init__(*children)

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        return sum(c.num_partitions_hint() for c in self.children)

    def execute(self):
        target = self.output_schema
        parts = []
        for c in self.children:
            for p in c.execute():
                def conv(p=p, src=c.output_schema):
                    for b in p:
                        yield _align_schema(b, target)
                parts.append(conv())
        return parts


def _align_schema(batch: ColumnarBatch, target: Schema) -> ColumnarBatch:
    if batch.schema == target:
        return batch
    from ..expr.cast import Cast
    from ..expr.core import BoundReference
    cols = []
    for i, f in enumerate(target):
        src_f = batch.schema[i]
        if src_f.dtype == f.dtype:
            cols.append(batch.columns[i])
        else:
            e = Cast(BoundReference(i, src_f.dtype), f.dtype)
            cols.append(ec.eval_as_column(e, batch))
    return ColumnarBatch(target, cols, batch.num_rows)


class RowToColumnar(TpuExec):
    """CPU pa.Table partitions -> device batches.

    Reference: GpuRowToColumnarExec (GpuRowToColumnarExec.scala:788).
    """

    def __init__(self, child: PhysicalPlan):
        super().__init__(child)
        assert not child.columnar

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def execute(self):
        def run(part):
            for t in part:
                with timed(self.metrics[OP_TIME], self):
                    yield from_arrow(t)
        return [run(p) for p in self.children[0].execute()]


class ColumnarToRow(PhysicalPlan):
    """Device batches -> CPU pa.Table partitions.

    Reference: GpuColumnarToRowExec (GpuColumnarToRowExec.scala:341).
    """
    columnar = False

    def __init__(self, child: PhysicalPlan):
        super().__init__(child)
        assert child.columnar

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def execute(self):
        def run(part):
            for b in part:
                with timed(self.metrics[OP_TIME], self):
                    yield to_arrow(b)
        return [run(p) for p in self.children[0].execute()]
