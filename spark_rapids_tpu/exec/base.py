"""Physical operator base — the GpuExec role (reference: GpuExec.scala:168).

Contract: ``execute() -> List[Iterator[...]]`` (one lazy iterator per
partition).  TPU operators stream ColumnarBatch; CPU fallback operators
stream pa.Table.  ``columnar`` distinguishes them and the planner inserts
RowToColumnar/ColumnarToRow transitions exactly like
GpuTransitionOverrides (GpuTransitionOverrides.scala:40).

Metrics: every node carries leveled metrics (ESSENTIAL/MODERATE/DEBUG),
mirroring GpuMetric (GpuExec.scala:27-237).
"""
from __future__ import annotations

import time
from typing import Dict, Iterator, List

from ..columnar.schema import Schema
from ..obs import flight as _flight
from ..obs import trace as _trace
from ..service.cancellation import cancel_checkpoint

ESSENTIAL, MODERATE, DEBUG = "ESSENTIAL", "MODERATE", "DEBUG"

# standard metric names (reference: GpuExec.scala:40-95)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
OP_TIME = "opTime"
CONCAT_TIME = "concatTime"
SORT_TIME = "sortTime"
AGG_TIME = "computeAggTime"
JOIN_TIME = "joinTime"
BUILD_TIME = "buildTime"
PARTITION_TIME = "partitionTime"
SPILL_BYTES = "spillData"


class Metric:
    __slots__ = ("name", "level", "_value", "_pending")

    def __init__(self, name: str, level: str = MODERATE):
        self.name = name
        self.level = level
        self._value = 0
        self._pending = None

    @property
    def value(self):
        # resolve deferred device counts only when the metric is read
        # (pulling them eagerly would serialize the dispatch queue)
        if self._pending:
            self._value += sum(int(p) for p in self._pending)
            self._pending = None
        return self._value

    @value.setter
    def value(self, v):
        self._value = int(v)
        self._pending = None

    def add(self, v):
        if isinstance(v, int):
            self._value += v
        else:
            if self._pending is None:
                self._pending = []
            self._pending.append(v)

    def __iadd__(self, v):
        self.add(v)
        return self

    def __repr__(self):
        return f"{self.name}={self.value}"


class MetricSet:
    def __init__(self):
        self._metrics: Dict[str, Metric] = {}

    def get(self, name: str, level: str = MODERATE) -> Metric:
        if name not in self._metrics:
            self._metrics[name] = Metric(name, level)
        return self._metrics[name]

    def __getitem__(self, name):
        return self.get(name)

    def __setitem__(self, name, value):
        # supports `metrics[X] += n` (Metric.__iadd__ returns the Metric)
        assert isinstance(value, Metric)
        self._metrics[name] = value

    def snapshot(self, level: str = DEBUG) -> Dict[str, int]:
        """Stable-key-order metric snapshot at ``level``.

        Filters BEFORE reading ``.value``: a metric excluded by level
        never resolves its deferred device counts, so an ESSENTIAL
        snapshot cannot force a device sync for MODERATE/DEBUG counters
        still pending on the dispatch queue."""
        rank = {ESSENTIAL: 0, MODERATE: 1, DEBUG: 2}
        mx = rank[level]
        out: Dict[str, int] = {}
        for name in sorted(self._metrics):
            m = self._metrics[name]
            if rank[m.level] <= mx:
                out[name] = m.value
        return out


class timed:
    """Context manager adding elapsed ns to a metric (NvtxWithMetrics role).

    Doubles as the per-operator cancellation checkpoint: entering a
    timed region is exactly an operator boundary (one batch about to be
    processed by one node), so a cancelled/deadline-exceeded query
    unwinds here instead of running its remaining operators — the
    TaskContext.isInterrupted pattern at columnar granularity.

    Span-aware: with tracing on, each timed region is an "exec" span
    named after ``node`` (the operator), nesting under the service
    attempt span and over kernel/shuffle/memory spans.  Disabled, the
    extra cost is one module-flag read (no allocation)."""

    __slots__ = ("metric", "node", "t0", "_span")

    def __init__(self, metric: Metric, node: "PhysicalPlan" = None):
        self.metric = metric
        self.node = node

    def __enter__(self):
        cancel_checkpoint()
        # flight recorder shares this operator boundary (always-on;
        # interned node/metric name only, so the record is
        # allocation-free)
        _flight.record(_flight.EV_BEGIN,
                       self.node.name if self.node is not None
                       else self.metric.name)
        if _trace._ENABLED:
            self._span = _trace.Span(
                self.node.name if self.node is not None
                else self.metric.name,
                "exec", {"metric": self.metric.name})
            self._span.__enter__()
        else:
            self._span = None
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *a):
        self.metric.add(time.perf_counter_ns() - self.t0)
        _flight.record(_flight.EV_END,
                       self.node.name if self.node is not None
                       else self.metric.name)
        if self._span is not None:
            self._span.__exit__(*a)
        return False


class PhysicalPlan:
    columnar = True  # True: yields ColumnarBatch; False: pa.Table

    def __init__(self, *children: "PhysicalPlan"):
        self.children = list(children)
        self.metrics = MetricSet()

    @property
    def output_schema(self) -> Schema:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__

    def execute(self) -> List[Iterator]:
        raise NotImplementedError

    def execute_checkpointed(self) -> List[Iterator]:
        """execute() with a cooperative cancellation checkpoint at every
        batch hand-off of every partition (in addition to the per-
        operator checkpoints inside ``timed``).  The session's collect
        path drains through this so even plans whose operators never
        enter a timed region stay cancellable."""
        cancel_checkpoint()

        def wrap(it):
            for item in it:
                cancel_checkpoint()
                yield item
        return [wrap(it) for it in self.execute()]

    def num_partitions_hint(self) -> int:
        if self.children:
            return self.children[0].num_partitions_hint()
        return 1

    def tree_string(self, indent: int = 0, annotate=None) -> str:
        """Indented tree rendering (one node per line, preorder — the
        order node_metrics keys are emitted in, so consumers join
        positionally).

        ``annotate``: optional ``(preorder_index, node) -> str``; a
        non-empty result is appended after the node label (the plan
        verifier's verified/violation markers ride here).  Annotations
        never change line order or leading indentation, so positional
        consumers (tools/report.py) keep working."""
        if annotate is None:
            pad = "  " * indent
            s = f"{pad}{self._node_string()}"
            for c in self.children:
                s += "\n" + c.tree_string(indent + 1)
            return s
        lines: List[str] = []
        counter = [0]

        def walk(node, depth):
            idx = counter[0]
            counter[0] += 1
            line = f"{'  ' * (indent + depth)}{node._node_string()}"
            tag = annotate(idx, node)
            if tag:
                line += f"  {tag}"
            lines.append(line)
            for c in node.children:
                walk(c, depth + 1)
        walk(self, 0)
        return "\n".join(lines)

    def _node_string(self):
        return self.name

    def collect_nodes(self) -> List["PhysicalPlan"]:
        out = [self]
        for c in self.children:
            out.extend(c.collect_nodes())
        return out

    def __repr__(self):
        return self.tree_string()
