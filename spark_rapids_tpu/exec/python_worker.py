"""Persistent Python worker processes for pandas UDF execution.

Reference: GpuArrowEvalPythonExec.scala:470 (Arrow stream to an
out-of-process Python worker), its BatchQueue (:187 — reader and writer
sides pipeline so the JVM keeps producing while Python computes), and
PythonWorkerSemaphore.scala (bounds concurrent workers so Python heap
pressure cannot fork-bomb the host).

Design here:
- A process-wide :class:`PythonWorkerPool` keeps ``spawn``-ed workers
  alive across queries (fork-per-batch would pay interpreter + import
  startup every time).
- The wire is Arrow IPC stream bytes over the multiprocessing pipe —
  the same serialization contract as the reference's Arrow socket.
- Pipelining: a writer THREAD streams input batches to the worker
  while the consumer thread reads results — the producer stays ahead
  of the Python compute (the BatchQueue role).  The pipe buffers give
  the in-flight window.
- A semaphore caps concurrently LEASED workers
  (spark.rapids.tpu.python.concurrentPythonWorkers).

The user function must be picklable (module-level def).  Functions that
cannot pickle fall back to the in-process path transparently.
"""
from __future__ import annotations

import io
import pickle
import threading
from typing import Iterator, List, Optional

import pyarrow as pa


def _table_to_ipc(t: pa.Table) -> bytes:
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, t.schema) as w:
        w.write_table(t)
    return sink.getvalue()


def _ipc_to_table(b: bytes) -> pa.Table:
    with pa.ipc.open_stream(io.BytesIO(b)) as r:
        return r.read_all()


def cast_result(pdf, out_schema: pa.Schema) -> pa.Table:
    """User pandas result -> arrow table in the declared schema.
    Lives HERE (pyarrow-only) so worker processes never import the
    engine (python_exec pulls in jax: seconds of cold start and
    hundreds of MB RSS per worker)."""
    t = pa.Table.from_pandas(pdf, preserve_index=False)
    arrays = []
    for f in out_schema:
        if f.name not in t.column_names:
            raise ValueError(
                f"pandas UDF result is missing column {f.name!r}")
        c = t.column(f.name).combine_chunks()
        if c.type != f.type:
            c = pa.compute.cast(c, f.type, safe=False)
        arrays.append(c)
    return pa.Table.from_arrays(arrays, schema=out_schema)


def _worker_main(conn):
    """Worker process loop: ("init", mode, fn) then a stream of
    ("batch", ipc) / ("end",) per task; results stream back as
    ("result", ipc)... ("done",) or ("error", message)."""
    import pandas as pd  # noqa: F401 - the udf contract is pandas

    fn = None
    mode = "map"
    out_schema = None
    while True:
        try:
            msg = conn.recv()
        except EOFError:
            return
        kind = msg[0]
        if kind == "shutdown":
            return
        if kind == "init":
            _, mode, fn_bytes, schema_ipc = msg
            try:
                fn = pickle.loads(fn_bytes)
                out_schema = _ipc_to_table(schema_ipc).schema
                conn.send(("ok",))
            except Exception as e:  # noqa: BLE001
                conn.send(("error", f"init failed: {e}"))
            continue
        if kind == "task":
            try:
                _run_task(conn, fn, mode, out_schema)
            except Exception as e:  # noqa: BLE001
                import traceback
                conn.send(("error",
                           f"{type(e).__name__}: {e}\n"
                           + traceback.format_exc(limit=5)))


def _run_task(conn, fn, mode, out_schema):
    _cast_result = cast_result

    def batches() -> Iterator[pa.Table]:
        while True:
            msg = conn.recv()
            if msg[0] == "end":
                return
            yield _ipc_to_table(msg[1])

    if mode == "map":
        # mapInPandas: fn(iterator of pdfs) -> iterator of pdfs; results
        # stream back AS PRODUCED so the parent overlaps with compute
        def pdfs():
            for t in batches():
                if t.num_rows:
                    yield t.to_pandas()
        for pdf in fn(pdfs()):
            out = _cast_result(pdf, out_schema)
            conn.send(("result", _table_to_ipc(out)))
    else:  # grouped: one input table per group, fn(pdf) -> pdf
        import inspect
        takes_key = len(inspect.signature(fn).parameters) >= 2
        for t in batches():
            key = None
            if takes_key and t.schema.metadata and \
                    b"__group_key" in t.schema.metadata:
                key = pickle.loads(t.schema.metadata[b"__group_key"])
            pdf = t.to_pandas()
            out = fn(key, pdf) if takes_key else fn(pdf)
            conn.send(("result",
                       _table_to_ipc(_cast_result(out, out_schema))))
    conn.send(("done",))


class _Worker:
    def __init__(self):
        import multiprocessing as mp
        ctx = mp.get_context("spawn")
        self.conn, child_conn = ctx.Pipe()
        self.proc = ctx.Process(target=_worker_main, args=(child_conn,),
                                daemon=True)
        self.proc.start()
        child_conn.close()

    def alive(self) -> bool:
        return self.proc.is_alive()

    def close(self):
        try:
            self.conn.send(("shutdown",))
        except Exception:  # noqa: BLE001
            pass
        self.proc.join(timeout=5)
        if self.proc.is_alive():
            self.proc.terminate()
        self.conn.close()


class PythonWorkerError(RuntimeError):
    pass


class PythonWorkerInitError(PythonWorkerError):
    """Worker could not initialize (e.g. the fn unpickles only in the
    parent's import context); raised BEFORE any input is consumed, so
    callers can fall back in-process safely."""


class PythonWorkerPool:
    """Process-wide pool with a leasing semaphore
    (PythonWorkerSemaphore role)."""

    _instance: Optional["PythonWorkerPool"] = None
    _get_lock = threading.Lock()

    def __init__(self, max_workers: int = 2):
        self.max_workers = max_workers
        self._sem = threading.Semaphore(max_workers)
        self._idle: List[_Worker] = []
        self._lock = threading.Lock()
        self._superseded = False

    @classmethod
    def get(cls) -> "PythonWorkerPool":
        from ..config import get_active, PYTHON_WORKERS
        try:
            n = int(get_active().get(PYTHON_WORKERS))
        except Exception:  # noqa: BLE001 - before config init
            n = 2
        with cls._get_lock:
            pool = cls._instance
            if pool is None or pool.max_workers != n:
                # a session with a different cap supersedes the pool
                # (the conf is per-session; a frozen first-session cap
                # would make it silently inoperative); idle workers
                # shut down, in-flight leases close on release below
                if pool is not None:
                    pool._superseded = True
                    pool.close()
                cls._instance = pool = PythonWorkerPool(n)
            return pool

    def close(self):
        with self._lock:
            for w in self._idle:
                w.close()
            self._idle.clear()

    def _acquire(self) -> _Worker:
        self._sem.acquire()
        with self._lock:
            while self._idle:
                w = self._idle.pop()
                if w.alive():
                    return w
                w.close()
        return _Worker()

    def _release(self, w: _Worker, broken: bool):
        with self._lock:
            if broken or self._superseded or not w.alive():
                # a worker released into a superseded pool would leak
                # (nothing drains that pool's idle list again)
                w.close()
            else:
                self._idle.append(w)
        self._sem.release()

    def run_map(self, fn, input_tables: Iterator[pa.Table],
                out_schema: pa.Schema,
                fn_bytes: Optional[bytes] = None) -> Iterator[pa.Table]:
        """mapInPandas through a worker process with pipelined writes:
        a writer thread streams input while this thread consumes
        results (the BatchQueue overlap)."""
        yield from self._run(fn, "map", input_tables, out_schema,
                             fn_bytes)

    def run_grouped(self, fn, group_tables: Iterator[pa.Table],
                    out_schema: pa.Schema,
                    fn_bytes: Optional[bytes] = None
                    ) -> Iterator[pa.Table]:
        yield from self._run(fn, "grouped", group_tables, out_schema,
                             fn_bytes)

    def _run(self, fn, mode, input_tables, out_schema, fn_bytes=None):
        if fn_bytes is None:
            fn_bytes = pickle.dumps(fn)  # raises for closures: caller
        w = self._acquire()              # falls back in-process
        broken = True
        try:
            empty = pa.Table.from_arrays(
                [pa.array([], type=f.type) for f in out_schema],
                schema=out_schema)
            w.conn.send(("init", mode, fn_bytes, _table_to_ipc(empty)))
            resp = w.conn.recv()
            if resp[0] != "ok":
                raise PythonWorkerInitError(resp[1])
            w.conn.send(("task",))
            send_err = []

            def writer():
                try:
                    for t in input_tables:
                        w.conn.send(("batch", _table_to_ipc(t)))
                except Exception as e:  # noqa: BLE001
                    send_err.append(e)
                finally:
                    # ALWAYS terminate the stream: without "end" the
                    # worker blocks in recv and the parent waits for
                    # "done" forever (upstream exec errors deadlocked)
                    try:
                        w.conn.send(("end",))
                    except Exception:  # noqa: BLE001
                        pass
            wt = threading.Thread(target=writer, daemon=True)
            wt.start()
            while True:
                msg = w.conn.recv()
                if msg[0] == "done":
                    break
                if msg[0] == "error":
                    raise PythonWorkerError(msg[1])
                if send_err:
                    raise send_err[0]
                yield _ipc_to_table(msg[1])
            wt.join(timeout=10)
            if send_err:
                raise send_err[0]
            broken = False
        finally:
            self._release(w, broken)
