"""TPU hash-aggregate operator.

Reference: GpuHashAggregateExec (aggregate.scala:240,282-460): per-batch
update aggregation, then concat+merge of partials, with partial/final/
complete modes driven by the planner around exchanges.

TPU-first: grouping is the sort+segmented-reduce kernel
(kernels/aggregate.py) — no hash tables; one compiled program per
(schema, capacity) bucket.
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from ..columnar import dtypes as T
from ..columnar.schema import Field, Schema
from ..columnar.column import Column, bucket_capacity
from ..columnar.batch import (ColumnarBatch, LazyCount, SpeculativeResult,
                              concat_batches, resolve_speculative)
from ..expr import core as ec
from ..expr.aggregates import AggregateFunction
from ..compile import aot as _aot
from ..kernels import canon, aggregate as agg_k
from ..obs import compile_watch as _compile_watch
from ..obs import costplane as _costplane
from ..obs.registry import compile_cache_event
from ..plan.logical import AggExpr
from .base import PhysicalPlan, AGG_TIME, NUM_OUTPUT_ROWS, timed
from .tpu_basic import TpuExec

PARTIAL, FINAL, COMPLETE = "partial", "final", "complete"


def _assemble_group_output(plan, key_cols, aggs, agg_buffers, out_cap: int,
                           emit_buffers: bool):
    """Traced output assembly: compact keys + agg buffers to rows 0..G-1.

    Runs INSIDE the fused cores — eager per-column gathers/masks after the
    jitted plan cost ~7ms of client overhead each on the remote backend
    (columnar/pending.py doc), which dominated the reduce side."""
    ng = plan.num_groups
    rep = plan.rep_indices
    take = jnp.where(jnp.arange(out_cap) < ng,
                     rep[:out_cap] if out_cap <= rep.shape[0] else
                     jnp.pad(rep, (0, out_cap - rep.shape[0]))[:out_cap],
                     0)
    live = jnp.arange(out_cap) < ng
    outs = []
    for c in key_cols:
        g = c.gather(take, live=live, unique=True).mask_validity(live)
        outs.append((g.data, g.validity))
    seg_take = jnp.where(live, jnp.arange(out_cap), 0)
    for a, bufs in zip(aggs, agg_buffers):
        cols_out = bufs if emit_buffers else [a.func.finalize(bufs)]
        for o in cols_out:
            c2 = o.gather(seg_take, live=live, unique=True).mask_validity(live)
            outs.append((c2.data, c2.validity))
    return ng, outs


# -- 32-bit device helpers for exact-float table aggregation ----------------
# The chip's 64-bit scatters cost ~5x 32-bit ones, so exact FLOAT64 table
# aggregation works entirely in 32-bit lanes: a value's two native f32
# components decompose into signed 8-bit integer chunks (sums) or flip-
# ordered u32 words (min/max).

CH_B = 8          # bits per chunk lane
CH_LANES = 15     # window = 120 bits
CH_W0 = 88        # max chunk position (top term bit 88+23 < 120)


def _flip32(f):
    """f32 -> u32 whose unsigned order equals the float total order
    (-0.0 handled by callers; NaNs must be masked out)."""
    import jax
    u = jax.lax.bitcast_convert_type(f, jnp.uint32)
    neg = (u >> jnp.uint32(31)) != jnp.uint32(0)
    return jnp.where(neg, ~u, u | jnp.uint32(0x80000000))


def _unflip32(w):
    import jax
    neg = (w & jnp.uint32(0x80000000)) == jnp.uint32(0)
    u = jnp.where(neg, ~w, w & jnp.uint32(0x7FFFFFFF))
    return jax.lax.bitcast_convert_type(u, jnp.float32)


def _pow2f(k):
    """2^k as f32 from a traced i32 scalar, k in [-126, 127]."""
    import jax
    return jax.lax.bitcast_convert_type(
        ((k + 127).astype(jnp.uint32) << jnp.uint32(23)), jnp.float32)


def _f32_exp(f):
    """(biased exponent clamped >=1, 24-bit significand, negative) of an
    f32 array."""
    import jax
    u = jax.lax.bitcast_convert_type(f, jnp.uint32)
    neg = (u >> jnp.uint32(31)) != jnp.uint32(0)
    e = ((u >> jnp.uint32(23)) & jnp.uint32(0xFF)).astype(jnp.int32)
    m = u & jnp.uint32(0x7FFFFF)
    sig = jnp.where(e > 0, m | jnp.uint32(1 << 23), m)
    return jnp.maximum(e, 1), sig, neg


def _part_chunk_rows(f, ok, emax):
    """One f32 component -> CH_LANES signed i32 chunk rows on the
    window anchored at ``emax`` (value = sig * 2^(e-150); window bit 0
    weighs 2^(emax-150-CH_W0)).  Exact for terms within the window;
    the caller's fit flag excludes batches with wider spread."""
    ee, sig, neg = _f32_exp(f)
    p = jnp.int32(CH_W0) - (emax - ee)
    keep = ok & (p >= 0) & (sig != jnp.uint32(0))
    off = (jnp.maximum(p, 0) & jnp.int32(7)).astype(jnp.uint32)
    q = jnp.maximum(p, 0) >> jnp.int32(3)
    l32 = sig << off                       # <= 2^31: stays in u32
    sgn = jnp.where(neg, jnp.int32(-1), jnp.int32(1))
    z = jnp.int32(0)
    cks = [jnp.where(keep, ((l32 >> jnp.uint32(CH_B * k)) &
                            jnp.uint32(0xFF)).astype(jnp.int32) * sgn, z)
           for k in range(4)]
    rows = []
    qmax = CH_W0 >> 3
    for L in range(CH_LANES):
        r = z
        for k in range(4):
            if 0 <= L - k <= qmax:
                r = r + jnp.where(q == L - k, cks[k], z)
        rows.append(r)
    return rows


def _chunk_recombine(lanes_f64, emax):
    """[table, CH_LANES] per-bucket lane sums (as f64) + batch emax
    -> per-bucket f64 totals.  Scale split into two in-range f32
    powers of two."""
    out = jnp.zeros(lanes_f64.shape[0], jnp.float64)
    for L in range(CH_LANES):
        k = jnp.int32(CH_B * L) + emax - jnp.int32(CH_W0 + 150)
        k1 = k // 2
        s1 = _pow2f(k1).astype(jnp.float64)
        s2 = _pow2f(k - k1).astype(jnp.float64)
        out = out + (lanes_f64[:, L] * s1) * s2
    return out


def buffer_schema(group_exprs, aggs: List[AggExpr]) -> Schema:
    """Schema of partial-aggregation output: keys + flattened buffers."""
    fields = [Field(ec.output_name(e), e.dtype(), True) for e in group_exprs]
    for a in aggs:
        for bi, bt in enumerate(a.func.buffer_dtypes()):
            fields.append(Field(f"__{a.alias}__buf{bi}", bt, True))
    return Schema(fields)


class TpuHashAggregate(TpuExec):
    def __init__(self, group_exprs: List[ec.Expression], aggs: List[AggExpr],
                 child: PhysicalPlan, mode: str = COMPLETE):
        super().__init__(child)
        self.group_exprs = group_exprs
        self.aggs = aggs
        self.mode = mode
        # whole-stage fusion: a leading filter/project chain folded in by
        # the planner post-pass (exec/staged.py) — applied before keys
        self.pre_ops = None
        # per-exec memo for whole-stage guards/signatures (shared with
        # the throwaway inner instances _update_batch builds per batch)
        self._ws_memo = {}

    @property
    def output_schema(self):
        if self.mode == PARTIAL:
            return buffer_schema(self.group_exprs, self.aggs)
        fields = [Field(ec.output_name(e), e.dtype(), True)
                  for e in self.group_exprs]
        fields += [Field(a.alias, a.func.dtype(), a.func.nullable)
                   for a in self.aggs]
        return Schema(fields)

    def _node_string(self):
        ws = f", staged={len(self.pre_ops)} ops" if self.pre_ops else ""
        return f"TpuHashAggregate[{self.mode}{ws}]"

    def execute(self):
        child_schema = self.children[0].output_schema
        nkeys = len(self.group_exprs)

        def run(part):
            # per-batch update aggregation, then concat+merge of partials —
            # the reference's iterative model (aggregate.scala:366-390)
            # keeps memory bounded by partial size, not input size.
            partials = []
            with timed(self.metrics[AGG_TIME], self):
                batches = list(part)
                if self.mode == FINAL:
                    # FINAL inputs are post-shuffle slices with host-known
                    # counts: concat them up front (one jitted program)
                    # and run ONE merge core instead of one per piece —
                    # per-piece cores dominated the reduce side.  Falls
                    # back to the iterative path when sizes are unknown
                    # or the coalesced batch would be huge.
                    if len(batches) > 1 and all(
                            isinstance(b.rows_lazy, int) for b in batches) \
                            and sum(b.num_rows for b in batches) <= (1 << 21):
                        batches = [concat_batches(batches)]
                for batch in batches:
                    # only skip empties whose count is already host-known
                    # (checking a lazy count would force a sync per batch)
                    if isinstance(batch.rows_lazy, int) and \
                            batch.num_rows == 0 and partials:
                        continue
                    in_spec = getattr(batch, "_speculative", None)
                    p = self._update_batch(batch)
                    if in_spec is not None:
                        # the update ran on a speculative input (e.g. a
                        # superstage's sync-free join): carry the input
                        # fits so the barrier that checks this partial
                        # also vouches for the rows it aggregated, and
                        # redo the update on the exactly-recomputed input
                        own = getattr(p, "_speculative", None)

                        def _redo_update(batch=batch):
                            return self._update_batch(
                                resolve_speculative(batch))
                        p._speculative = SpeculativeResult(
                            list(in_spec.fits) +
                            (list(own.fits) if own is not None else []),
                            _redo_update)
                    partials.append(p)
                if not partials:
                    partials = [self._update_batch(
                        ColumnarBatch.empty(child_schema))]
                # A single PARTIAL passes through unverified/uncompacted
                # (zero syncs); the exchange downstream holds the flush
                # barrier that verifies speculative table-path batches
                # and slices them.  Any path that merges/finalizes here
                # must verify first (the merge would bake garbage in) —
                # EXCEPT the single-partial deferred path below, which
                # re-attaches the unverified flag to its own output so
                # the next consumer's flush barrier (join phase A, the
                # exchange, or to_arrow) performs the verification and
                # the redo closure recomputes the whole chain exactly.
                def _lazy_unresolved(v):
                    st = getattr(v, "_staged", None)
                    return st is not None and not st.resolved and \
                        getattr(v, "_val", None) is None
                spec = getattr(partials[0], "_speculative", None) \
                    if len(partials) == 1 else None
                spec_unresolved = spec is not None and any(
                    _lazy_unresolved(f) for f in spec.fits)
                count_unresolved = len(partials) == 1 and \
                    _lazy_unresolved(partials[0]._rows)
                # Deferring EITHER forcing point (the speculative fit
                # flag, or the host count the compaction slice needs)
                # saves a full device round trip — legal only when this
                # node's consumer provably holds its own flush barrier.
                defer = (self.mode != PARTIAL and len(partials) == 1 and
                         getattr(self, "allow_deferred_verify", False) and
                         (spec_unresolved or count_unresolved))
                if not defer and (len(partials) > 1 or
                                  self.mode != PARTIAL):
                    partials = [resolve_speculative(p) for p in partials]
                    partials = [self._compact_partial(p) for p in partials]
                merged = concat_batches(partials) if len(partials) > 1 \
                    else partials[0]
                out = self._merge_finalize(merged,
                                           multiple=len(partials) > 1)
                if defer and spec is not None:
                    out_spec = getattr(out, "_speculative", None)

                    def redo_chain(spec=spec):
                        fixed = resolve_speculative(spec.redo())
                        fixed = self._compact_partial(fixed)
                        return resolve_speculative(
                            self._merge_finalize(fixed, multiple=False))
                    fits = list(spec.fits) + (
                        list(out_spec.fits) if out_spec is not None else [])
                    out._speculative = SpeculativeResult(fits, redo_chain)
                elif self.mode != PARTIAL and not getattr(
                        self, "allow_deferred_verify", False):
                    # the merge itself may have attached a compaction
                    # fit flag; an unmarked consumer (e.g. a Project)
                    # would silently DROP it and consume a truncated
                    # batch, so verify here (PARTIAL outputs flow to
                    # the exchange, which always verifies)
                    out = resolve_speculative(out)
            self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
            yield out
        return [run(p) for p in self.children[0].execute()]

    @staticmethod
    def _compact_partial(b: ColumnarBatch) -> ColumnarBatch:
        """Shrink a group-compact batch (rows 0..G-1 live) to its bucket
        capacity once the group count is host-visible."""
        n = b.num_rows
        cap = bucket_capacity(max(n, 1))
        if cap >= b.capacity:
            return b
        return b.slice(0, max(n, 1))

    def _update_batch(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Partial (update) aggregation of one input batch -> buffer batch."""
        inner = TpuHashAggregate(self.group_exprs, self.aggs,
                                 self.children[0], mode=PARTIAL)
        inner.pre_ops = self.pre_ops
        inner._ws_memo = self._ws_memo
        if self.mode == FINAL:
            # input is already buffer-shaped: merge within the batch
            inner = TpuHashAggregate(self.group_exprs, self.aggs,
                                     self.children[0], mode=FINAL)
            inner_out = inner._aggregate_batch(batch, emit_buffers=True)
            return inner_out
        return inner._aggregate_batch(batch)

    def _merge_finalize(self, merged: ColumnarBatch,
                        multiple: bool) -> ColumnarBatch:
        if self.mode == PARTIAL:
            if not multiple:
                return merged
            # merge duplicate keys across partials, stay in buffer form
            inner = TpuHashAggregate(self.group_exprs, self.aggs,
                                     self.children[0], mode=FINAL)
            return inner._aggregate_batch(merged, emit_buffers=True)
        inner = TpuHashAggregate(self.group_exprs, self.aggs,
                                 self.children[0], mode=FINAL)
        return inner._aggregate_batch(merged)

    # -- fused core (one dispatch per batch) -------------------------------
    _FUSABLE_FUNCS = None   # populated lazily (class-level allowlist)
    # class-level jit cache: _update_batch/_merge_finalize build throwaway
    # TpuHashAggregate instances per batch, so the cache must outlive them
    # (keyed by everything the traced closure captures)
    _CORE_CACHE = {}

    def _fused_agg_core(self, key_cols, input_cols, update_mode: bool,
                        batch: ColumnarBatch, emit_buffers: bool,
                        out_cap: Optional[int] = None):
        """keys->words->plan->update/merge->output assembly as ONE jitted
        computation, returning (num_groups, fit, [(data, validity)])
        output pairs in schema order (``out_cap``/``fit``: speculative
        device-side compaction, see _fused_whole_stage_core).

        The whole grouping pipeline is device-pure (the only host sync is
        the group count, pulled after); fusing it collapses the ~40 eager
        dispatches per batch into one — the same rationale as
        exec/fused.py, applied to the aggregate hot loop
        (aggregate.scala:366 computeAggregate role).
        """
        import jax
        import logging
        from ..columnar.binary64 import exact_double_enabled
        if exact_double_enabled():
            # traced reassembly would strip Binary64Columns
            return None
        if TpuHashAggregate._FUSABLE_FUNCS is None:
            from ..expr import aggregates as ea
            TpuHashAggregate._FUSABLE_FUNCS = (
                ea.Sum, ea.Count, ea.Min, ea.Max, ea.Average, ea.First,
                ea.Last, ea.CentralMoment)
        if batch.capacity > (1 << 22):
            return None
        if not all(type(c) is Column for c in key_cols):
            return None
        for cols in input_cols:
            if not all(c is None or type(c) is Column for c in cols):
                return None
        if not all(isinstance(a.func, TpuHashAggregate._FUSABLE_FUNCS)
                   for a in self.aggs):
            return None
        key_dts = tuple(c.dtype for c in key_cols)
        in_dts = tuple(tuple(None if c is None else c.dtype for c in cols)
                       for cols in input_cols)
        aggs = self.aggs
        from ..kernels.aggregate import _pair_sum_enabled
        cache_key = (update_mode, emit_buffers, key_dts, in_dts, out_cap,
                     _pair_sum_enabled(),
                     tuple((type(a.func).__name__, repr(a.func),
                            getattr(a.func, "ignore_nulls", None))
                           for a in aggs))
        core = TpuHashAggregate._CORE_CACHE.get(cache_key)
        compile_cache_event("hash_aggregate", core is not None)
        if core is False:
            return None

        if core is None:
            def _core(key_arrays, in_arrays, num_rows):
                kcols = [Column(dt, d, v)
                         for dt, (d, v) in zip(key_dts, key_arrays)]
                cap = key_arrays[0][0].shape[0]
                words = canon.batch_key_words(kcols, num_rows)
                plan = agg_k.groupby_plan(words)
                agg_buffers = []
                it = iter(in_arrays)
                for a, dts in zip(aggs, in_dts):
                    cols = [None if dt is None else
                            Column(dt, *next(it)) for dt in dts] or [None]
                    bufs = a.func.update(plan, cols) if update_mode \
                        else a.func.merge(plan, cols)
                    agg_buffers.append(bufs)
                ocap = min(out_cap, cap) if out_cap else cap
                fit = (plan.num_groups <= ocap).astype(jnp.int32) \
                    if out_cap else jnp.int32(1)
                ng, outs = _assemble_group_output(plan, kcols, aggs,
                                                  agg_buffers, ocap,
                                                  emit_buffers)
                return ng, fit, outs
            core = _compile_watch.wrap_miss(
                "hash_aggregate", jax.jit(_core), str(cache_key))
            TpuHashAggregate._CORE_CACHE[cache_key] = core
            key_nps = tuple(dt.np_dtype for dt in key_dts)
            in_nps = tuple(dt.np_dtype for dts in in_dts for dt in dts
                           if dt is not None)
            if not any(d is None for d in key_nps + in_nps):
                def warm(bucket: int) -> None:
                    ka = tuple((jnp.zeros(bucket, d),
                                jnp.zeros(bucket, jnp.bool_))
                               for d in key_nps)
                    ia = tuple((jnp.zeros(bucket, d),
                                jnp.zeros(bucket, jnp.bool_))
                               for d in in_nps)
                    core(ka, ia, jnp.int32(0))
                _aot.register_warmer("hash_aggregate_grouped", warm,
                                     str(hash(cache_key)))

        # flat arg list, None inputs omitted (the dtypes tuple encodes
        # which are None — no placeholder transfers)
        in_arrays = tuple(
            (c.data, c.validity)
            for cols in input_cols for c in cols if c is not None)
        key_arrays = tuple((c.data, c.validity) for c in key_cols)
        _aot.note_demand("hash_aggregate", batch.capacity,
                         _costplane.rows_if_resolved(batch))
        try:
            return core(key_arrays, in_arrays, batch.rows_dev)
        except Exception:  # noqa: BLE001 - fall back, but loudly
            logging.getLogger("spark_rapids_tpu.exec.aggregate").warning(
                "fused aggregate core failed; falling back to eager",
                exc_info=True)
            TpuHashAggregate._CORE_CACHE[cache_key] = False
            return None

    # -- sort-free bucket-table fast path ----------------------------------
    # (kernels/aggregate.py table_plan; the cuDF-hash-groupby role done
    # the TPU way: mixed-radix bucket ids + MXU one-hot matmuls, no sort,
    # speculative dispatch verified by a device-side fit flag.)

    _TABLE_KEY_DTYPES = None   # int-family key dtypes (lazily built)

    @staticmethod
    def _table_key_ok(dt) -> bool:
        return (dt.is_integral or dt == T.BOOL or
                dt in (T.DATE, T.TIMESTAMP) or
                isinstance(dt, T.DecimalType))

    def _table_prepare(self, src_schema):
        """Guards + lowering descriptors for the table path; False when
        this (pre_ops, schema, aggs) can never use it."""
        from ..config import get_active, VARIABLE_FLOAT_AGG
        from ..expr import aggregates as ea
        from .fused import _tree_fusable, expr_signature
        from .staged import ops_fusable, ops_signature
        fast_float = get_active().get(VARIABLE_FLOAT_AGG)
        if self.pre_ops:
            if not ops_fusable(self.pre_ops):
                return False
            osig = ops_signature(self.pre_ops)
            if osig is None:
                return False
            post_schema = self.pre_ops[-1][2]
        else:
            osig = ""
            post_schema = src_schema
        try:
            bound_keys = [e.bind(post_schema) for e in self.group_exprs]
            bound_inputs = [[c.bind(post_schema) for c in a.func.children]
                            for a in self.aggs]
        except KeyError:
            return False
        if not bound_keys:
            return False
        if not all(_tree_fusable(e) and self._table_key_ok(e.dtype())
                   for e in bound_keys):
            return False
        for bs in bound_inputs:
            if not all(_tree_fusable(e) for e in bs):
                return False
        # per-agg lowering descriptor
        descs = []
        for a, bs in zip(self.aggs, bound_inputs):
            f = a.func
            cdt = bs[0].dtype() if bs else None
            if isinstance(f, ea.Count):
                descs.append(("count",))
            elif isinstance(f, ea.Sum):
                if cdt is None or not cdt.is_fractional:
                    return False    # exact int/decimal sums: sort path
                # exact (default) float mode: accumulate the row in the
                # device's full f64 representation — a 64-bit scatter
                # lane beside the f32 reduce rows, no f32 narrowing,
                # no overflow fit constraint
                descs.append(("fsum",) if fast_float else ("fsum64",))
            elif isinstance(f, ea.Average):
                if cdt is None or not cdt.is_fractional:
                    return False
                descs.append(("avg",) if fast_float else ("favg64",))
            elif isinstance(f, (ea.Min, ea.Max)):
                want_max = isinstance(f, ea.Max)
                if cdt == T.FLOAT32:
                    descs.append(("fminmax", want_max))
                elif cdt is not None and cdt.is_fractional:
                    descs.append(("fminmax", want_max) if fast_float
                                 else ("fminmax64", want_max))
                elif cdt is not None and self._table_key_ok(cdt):
                    descs.append(("iminmax", want_max))
                else:
                    return False
            elif isinstance(f, (ea.First, ea.Last)):
                if cdt is None or cdt == T.STRING or cdt.is_nested:
                    return False
                descs.append(("firstlast", isinstance(f, ea.Last),
                              getattr(f, "ignore_nulls", True)))
            else:
                return False
        ksigs = [expr_signature(e) for e in bound_keys]
        isigs = [tuple(expr_signature(e) for e in bs)
                 for bs in bound_inputs]
        if any(s is None for s in ksigs) or \
                any(s is None for t in isigs for s in t):
            return False
        cache_key = ("table", osig, tuple(ksigs),
                     tuple(x for t in isigs for x in t),
                     tuple(f.dtype.name for f in src_schema),
                     tuple(descs), fast_float)
        return cache_key, bound_keys, bound_inputs, descs

    def _fused_table_core(self, batch: ColumnarBatch):
        """pre_ops + key eval + bucket-table aggregation as ONE program.

        Returns a buffer-schema ColumnarBatch (capacity = table size)
        carrying a SpeculativeResult, or None to use the general path."""
        import jax
        import logging
        from ..config import get_active, AGG_TABLE_ENABLED, AGG_TABLE_SIZE
        from ..columnar.binary64 import exact_double_enabled
        conf = get_active()
        if not conf.get(AGG_TABLE_ENABLED) or exact_double_enabled():
            return None
        table = int(conf.get(AGG_TABLE_SIZE))
        # capacity cap is 2^24: all reduce rows are f32, so per-group
        # counts and first/last positions are exact only up to 2^24
        # (f32 integer-exact range); a larger batch could silently
        # saturate Count or round a First/Last position
        if batch.capacity < table or batch.capacity > (1 << 24) or \
                not batch.columns:
            return None
        if not all(type(c) is Column for c in batch.columns):
            return None
        if self._ws_memo.get("table_state") == "off":
            return None
        mkey = ("tprep", tuple(f.dtype.name for f in batch.schema))
        prep = self._ws_memo.get(mkey)
        if prep is None:
            prep = self._table_prepare(batch.schema)
            self._ws_memo[mkey] = prep
        if prep is False:
            return None
        cache_key, bound_keys, bound_inputs, descs = prep
        # i32 chunk-lane sums are exact only while a bucket's lane sum
        # stays under 2^31: |hr+lr| <= 510/row/lane -> max 2^22 rows
        if batch.capacity > (1 << 22) and \
                any(d[0] in ("fsum64", "favg64") for d in descs):
            return None
        core = TpuHashAggregate._CORE_CACHE.get((cache_key, table))
        if core is False:
            return None
        if core is None:
            core = jax.jit(self._build_table_core(
                batch.schema, bound_keys, bound_inputs, descs, table))
            TpuHashAggregate._CORE_CACHE[(cache_key, table)] = core
        datas = tuple(c.data for c in batch.columns)
        valids = tuple(c.validity for c in batch.columns)
        try:
            fit, ng, key_pairs, buf_groups = core(datas, valids,
                                                  batch.rows_dev)
        except Exception:  # noqa: BLE001 - fall back, but loudly
            logging.getLogger("spark_rapids_tpu.exec.aggregate").warning(
                "table aggregate core failed; falling back", exc_info=True)
            TpuHashAggregate._CORE_CACHE[(cache_key, table)] = False
            return None
        out_cols = [Column(e.dtype(), d, v)
                    for e, (d, v) in zip(bound_keys, key_pairs)]
        for a, pairs in zip(self.aggs, buf_groups):
            dts = a.func.buffer_dtypes()
            out_cols.extend(Column(dt, d, v)
                            for dt, (d, v) in zip(dts, pairs))
        out = ColumnarBatch(buffer_schema(self.group_exprs, self.aggs),
                            out_cols, LazyCount(ng))

        def redo():
            self._ws_memo["table_state"] = "off"
            return self._aggregate_batch(batch, no_table=True)
        out._speculative = SpeculativeResult([LazyCount(fit)], redo)
        return out

    def _build_table_core(self, src_schema, bound_keys, bound_inputs,
                          descs, table: int):
        """Build the traced table-aggregation program.

        One pass: mixed-radix bucket ids (kernels/aggregate.table_bucket),
        then a SINGLE fused Pallas table-reduce (pallas_ops.table_reduce)
        covering every sum/count row (MXU one-hot dots) and every min/max
        row (VPU masked reductions; mins ride negated).  Exact float mode
        adds 64-bit lanes (fsum64/favg64/fminmax64) reduced by direct
        small-output scatters in the device's full f64 representation.
        All f32 reduce rows; integer min/max and first/last positions are exact
        because the fit flag restricts them to the f32-exact integer
        range (2^24) — non-fitting batches re-run on the sort path."""
        import jax.numpy as jnp
        from ..config import get_active, AGG_TABLE_REDUCE_IMPL
        import jax
        from ..kernels.pallas_ops import table_reduce
        from .fused import _TracedBatch
        reduce_impl = get_active().get(AGG_TABLE_REDUCE_IMPL)
        pre_ops = self.pre_ops
        SIGN = 0x8000000000000000
        NEG_INF = jnp.float32(-jnp.inf)
        F32_EXACT = jnp.uint64(1 << 24)

        def apply_ops_masked(b, live):
            # Filters fold into the live mask instead of compacting — the
            # sort path needs contiguous rows, the bucket table doesn't,
            # and compaction's argsort + per-column 64-bit gathers were
            # the dominant map-side cost.
            for kind, payload, out_schema in (pre_ops or ()):
                if kind == "filter":
                    pred = ec.eval_as_column(payload, b)
                    live = live & pred.data.astype(bool) & pred.validity
                else:
                    cols = [ec.eval_as_column(e, b) for e in payload]
                    b = _TracedBatch(out_schema, cols, b.num_rows,
                                     b.capacity)
            return b, live

        def decode_word(dtype, word):
            if dtype == T.BOOL:
                return word != 0
            v = (word ^ jnp.uint64(SIGN)).astype(jnp.int64)
            return v.astype(dtype.np_dtype)

        def _core(datas, valids, num_rows):
            cap = datas[0].shape[0]
            cols = [Column(f.dtype, d, v)
                    for f, d, v in zip(src_schema, datas, valids)]
            b = _TracedBatch(src_schema, cols, num_rows, cap)
            live = jnp.arange(cap) < num_rows
            b, live = apply_ops_masked(b, live)
            kcols = [ec.eval_as_column(e, b) for e in bound_keys]
            kwords = [canon.value_words(c, b.num_rows)[0] for c in kcols]
            kvalids = [c.validity for c in kcols]
            bucket, fit, mins, cards = agg_k.table_bucket(
                kwords, kvalids, live, table)
            icols = [[ec.eval_as_column(e, b) for e in bs] or [None]
                     for bs in bound_inputs]
            live_f = jnp.where(live, 1.0, 0.0).astype(jnp.float32)

            # collect every reduce row for the ONE fused table-reduce.
            # Shared rows (counts, chunk decompositions) are keyed by the
            # bound input expression, so sum(x)+avg(x)+min(x) share one
            # count row and one 15-lane chunk decomposition.
            sum_rows, max_rows = [jnp.asarray(live_f)], []
            srow_of, mrow_of = {"__ones__": 0}, {}
            dks = [repr(bs[0]) if bs else ("*", i)
                   for i, bs in enumerate(bound_inputs)]
            chunk_of = {}            # dk -> (lane0, emax)
            # exact float mode, ALL 32-bit (64-bit scatters cost ~5x):
            # - sums: each f64 value splits into its two f32 components,
            #   each component into signed 8-bit integer chunks on a
            #   120-bit window anchored at the column's batch max
            #   exponent; the i32 chunk lanes ride ONE stacked i32
            #   scatter (exact: lane sums < 2^31), recombined per
            #   bucket in the output phase.  A fit flag sends batches
            #   with >2^63 exponent spread to the sort path.
            # - min/max: two-stage u32 scatter-max over the (hi, lo)
            #   pair order-words.
            chunk_rows = []                # i32 lanes, one scatter
            mm_hi_rows, mm_lo_src = [], []  # two-stage u32 minmax
            agg_meta = []   # per agg: lowering info for the output phase

            def add_sum(tag, arr):
                if tag not in srow_of:
                    srow_of[tag] = len(sum_rows)
                    sum_rows.append(arr)

            def add_max(tag, arr):
                mrow_of[tag] = len(max_rows)
                max_rows.append(arr)

            for ai, (a, cols_a) in enumerate(zip(self.aggs, icols)):
                kind = descs[ai][0]
                dk = dks[ai]
                c = cols_a[0]
                if kind == "count":
                    if c is not None:
                        add_sum(("cnt", dk),
                                jnp.where(live & c.validity, 1.0, 0.0)
                                .astype(jnp.float32))
                    agg_meta.append(None)
                elif kind in ("fsum", "avg"):
                    ok = live & c.validity
                    v32 = c.data.astype(jnp.float32)
                    fit = fit & jnp.all(
                        jnp.where(ok, jnp.isfinite(v32), True))
                    add_sum(("sum", dk), jnp.where(ok, v32, 0.0))
                    add_sum(("cnt", dk),
                            jnp.where(ok, 1.0, 0.0).astype(jnp.float32))
                    agg_meta.append(None)
                elif kind in ("fsum64", "favg64"):
                    ok = live & c.validity
                    v = c.data.astype(jnp.float64)
                    fin = jnp.isfinite(v)
                    okf = ok & fin
                    add_sum(("cnt", dk),
                            jnp.where(ok, 1.0, 0.0).astype(jnp.float32))
                    add_sum(("nan", dk),
                            jnp.where(ok & jnp.isnan(v), 1.0, 0.0)
                            .astype(jnp.float32))
                    add_sum(("pinf", dk),
                            jnp.where(ok & jnp.isposinf(v), 1.0, 0.0)
                            .astype(jnp.float32))
                    add_sum(("ninf", dk),
                            jnp.where(ok & jnp.isneginf(v), 1.0, 0.0)
                            .astype(jnp.float32))
                    vq = jnp.where(okf, v, 0.0)
                    hi32 = vq.astype(jnp.float32)
                    # finite f64 beyond f32 range: hi overflows to inf
                    # and the chunk lattice cannot hold it (same
                    # contract as the fminmax f32 path below)
                    fit = fit & jnp.all(
                        jnp.where(okf, jnp.isfinite(hi32), True))
                    lo32 = (vq - hi32.astype(jnp.float64)) \
                        .astype(jnp.float32)
                    ehi, sighi, _ = _f32_exp(hi32)
                    contrib = okf & (sighi != jnp.uint32(0))
                    emax = jnp.max(jnp.where(contrib, ehi, jnp.int32(0)))
                    emin = jnp.min(jnp.where(contrib, ehi,
                                             jnp.int32(255)))
                    # spread beyond the window -> exact sort path
                    fit = fit & ((emax - emin) <= jnp.int32(CH_W0 - 25))
                    hrows = _part_chunk_rows(hi32, contrib, emax)
                    lrows = _part_chunk_rows(lo32, okf, emax)
                    lane0 = len(chunk_rows)
                    for hr, lr in zip(hrows, lrows):
                        chunk_rows.append(hr + lr)
                    agg_meta.append(("chunks", lane0, emax))
                elif kind == "fminmax64":
                    want_max = descs[ai][1]
                    ok = live & c.validity
                    v = c.data.astype(jnp.float64)
                    # Spark total order: NaN greatest, -0.0 == 0.0
                    v = jnp.where(v == 0.0, jnp.float64(0.0), v)
                    nan = jnp.isnan(v)
                    okn = ok & ~nan
                    add_sum(("cnt", dk),
                            jnp.where(ok, 1.0, 0.0).astype(jnp.float32))
                    add_sum(("nn", dk),
                            jnp.where(okn, 1.0, 0.0).astype(jnp.float32))
                    hi32 = v.astype(jnp.float32)
                    # finite f64 beyond f32 range would alias real inf
                    fit = fit & jnp.all(
                        jnp.where(ok & jnp.isfinite(v),
                                  jnp.isfinite(hi32), True))
                    # +/-inf: hi carries the order; v-hi is NaN -> 0
                    lo32 = jnp.where(
                        jnp.isfinite(v),
                        (v - hi32.astype(jnp.float64)), 0.0) \
                        .astype(jnp.float32)
                    whi = _flip32(hi32)
                    if not want_max:
                        whi = ~whi
                    whi = jnp.where(okn, whi, jnp.uint32(0))
                    mi = len(mm_hi_rows)
                    mm_hi_rows.append(whi)
                    mm_lo_src.append((lo32, okn, want_max))
                    agg_meta.append(("mm", mi))
                elif kind == "fminmax":
                    want_max = descs[ai][1]
                    ok = live & c.validity
                    v32 = c.data.astype(jnp.float32)
                    # finite f64 whose f32 cast overflows to +/-inf would
                    # silently corrupt min/max: detect on device and send
                    # the batch to the exact path (same contract as
                    # fsum/avg above)
                    fit = fit & jnp.all(
                        jnp.where(ok & jnp.isfinite(c.data),
                                  jnp.isfinite(v32), True))
                    # Spark total order: NaN greatest, -0.0 == 0.0
                    v32 = jnp.where(v32 == 0.0, jnp.float32(0.0), v32)
                    nan = jnp.isnan(v32)
                    add_sum(("cnt", dk),
                            jnp.where(ok, 1.0, 0.0).astype(jnp.float32))
                    add_sum(("nn", dk),
                            jnp.where(ok & ~nan, 1.0, 0.0)
                            .astype(jnp.float32))
                    add_max(("m", ai),
                            jnp.where(ok & ~nan,
                                      v32 if want_max else -v32, NEG_INF))
                    agg_meta.append(None)
                elif kind == "iminmax":
                    want_max = descs[ai][1]
                    ok = live & c.validity
                    w = canon.value_words(c, b.num_rows)[0]
                    any_v = jnp.any(ok)
                    vmin = jnp.where(
                        any_v,
                        jnp.min(jnp.where(ok, w, jnp.uint64(2**64 - 1))),
                        jnp.uint64(0))
                    vmax = jnp.where(
                        any_v, jnp.max(jnp.where(ok, w, jnp.uint64(0))),
                        jnp.uint64(0))
                    # reduce rows are f32: exact only below 2^24
                    fit = fit & ((vmax - vmin) < F32_EXACT)
                    narrow = jnp.minimum(w - vmin, F32_EXACT) \
                        .astype(jnp.float32)
                    add_sum(("cnt", dk),
                            jnp.where(ok, 1.0, 0.0).astype(jnp.float32))
                    add_max(("m", ai),
                            jnp.where(ok, narrow if want_max else -narrow,
                                      NEG_INF))
                    agg_meta.append(("vmin", vmin))
                elif kind == "firstlast":
                    want_last, ignore_nulls = descs[ai][1], descs[ai][2]
                    ok = (live & c.validity) if ignore_nulls else live
                    pos = jnp.arange(cap, dtype=jnp.int32) \
                        .astype(jnp.float32)
                    add_max(("m", ai),
                            jnp.where(ok, pos if want_last else -pos,
                                      NEG_INF))
                    agg_meta.append(None)

            sums, maxs = table_reduce(bucket, sum_rows, max_rows, table,
                                      impl=reduce_impl)
            # i32 chunk lanes: ONE stacked scatter (multi-column scatter
            # costs the same as single-column; lane sums < 2^31, exact)
            chunk_out = None
            if chunk_rows:
                chunk_out = jax.ops.segment_sum(
                    jnp.stack(chunk_rows, 1), bucket,
                    num_segments=table + 1)[:table]
            # two-stage u32 min/max: hi words, then lo among hi-winners
            mm1 = mm2 = None
            if mm_hi_rows:
                mm1 = jax.ops.segment_max(
                    jnp.stack(mm_hi_rows, 1), bucket,
                    num_segments=table + 1)
                lo_rows = []
                for i, (lo32, okn, wmax) in enumerate(mm_lo_src):
                    win = okn & (mm_hi_rows[i] ==
                                 jnp.take(mm1[:, i], bucket))
                    wlo = _flip32(lo32)
                    if not wmax:
                        wlo = ~wlo
                    lo_rows.append(jnp.where(win, wlo, jnp.uint32(0)))
                mm2 = jax.ops.segment_max(
                    jnp.stack(lo_rows, 1), bucket,
                    num_segments=table + 1)
            counts_all = sums[0]
            present, order, ng = agg_k.table_compact(counts_all, table)
            live_g = jnp.arange(table) < ng

            def compact(tab):
                return jnp.take(tab, order)
            # keys: decode bucket digits arithmetically (no gathers)
            key_pairs = []
            strides = []
            st = jnp.int32(1)
            for card in reversed(cards):
                strides.append(st)
                st = st * card
            strides = list(reversed(strides))
            for e, wmin, card, stride in zip(bound_keys, mins, cards,
                                             strides):
                digit = (order // stride) % card
                word = wmin + (digit - 1).astype(jnp.uint64)
                data = decode_word(e.dtype(), word)
                key_pairs.append((data, (digit > 0) & live_g))
            # agg buffers
            buf_groups = []
            for ai, (a, cols_a) in enumerate(zip(self.aggs, icols)):
                kind = descs[ai][0]
                dk = dks[ai]
                c = cols_a[0]
                if kind == "count":
                    cnt = sums[srow_of[("cnt", dk)] if c is not None
                               else 0]
                    cnt = compact(cnt)
                    buf_groups.append([(
                        jnp.where(live_g, cnt, 0.0).astype(jnp.int64),
                        jnp.ones(table, bool))])
                elif kind == "fsum":
                    ssum = compact(sums[srow_of[("sum", dk)]])
                    cntv = compact(sums[srow_of[("cnt", dk)]])
                    dt = a.func.buffer_dtypes()[0]
                    buf_groups.append([(
                        ssum.astype(dt.np_dtype),
                        (cntv > 0) & live_g)])
                elif kind == "avg":
                    ssum = compact(sums[srow_of[("sum", dk)]])
                    cntv = compact(sums[srow_of[("cnt", dk)]])
                    buf_groups.append([
                        (ssum.astype(jnp.float64), live_g),
                        (cntv.astype(jnp.int64), live_g)])
                elif kind in ("fsum64", "favg64"):
                    _, lane0, emax = agg_meta[ai]
                    lanes = chunk_out[:, lane0:lane0 + CH_LANES] \
                        .astype(jnp.float64)
                    lanes = jnp.take(lanes, order, axis=0)
                    ssum = _chunk_recombine(lanes, emax)
                    nanv = compact(sums[srow_of[("nan", dk)]])
                    pinfv = compact(sums[srow_of[("pinf", dk)]])
                    ninfv = compact(sums[srow_of[("ninf", dk)]])
                    ssum = jnp.where(pinfv > 0, jnp.float64(jnp.inf),
                                     ssum)
                    ssum = jnp.where(ninfv > 0, jnp.float64(-jnp.inf),
                                     ssum)
                    ssum = jnp.where(
                        (nanv > 0) | ((pinfv > 0) & (ninfv > 0)),
                        jnp.float64(jnp.nan), ssum)
                    cntv = compact(sums[srow_of[("cnt", dk)]])
                    if kind == "fsum64":
                        buf_groups.append([(ssum, (cntv > 0) & live_g)])
                    else:
                        buf_groups.append([
                            (ssum, live_g),
                            (cntv.astype(jnp.int64), live_g)])
                elif kind == "fminmax64":
                    want_max = descs[ai][1]
                    mi = agg_meta[ai][1]
                    w1 = compact(mm1[:table, mi])
                    w2 = compact(mm2[:table, mi])
                    if not want_max:
                        w1, w2 = ~w1, ~w2
                    m = _unflip32(w1).astype(jnp.float64) + \
                        _unflip32(w2).astype(jnp.float64)
                    cntv = compact(sums[srow_of[("cnt", dk)]])
                    nnv = compact(sums[srow_of[("nn", dk)]])
                    if want_max:
                        # any NaN in the group wins
                        m = jnp.where(cntv > nnv,
                                      jnp.float64(jnp.nan), m)
                    else:
                        # min ignores NaN unless the group is all-NaN
                        m = jnp.where(nnv > 0, m, jnp.float64(jnp.nan))
                    buf_groups.append([(m, (cntv > 0) & live_g)])
                elif kind == "fminmax":
                    want_max = descs[ai][1]
                    m = compact(maxs[mrow_of[("m", ai)]])
                    if not want_max:
                        m = -m
                    cntv = compact(sums[srow_of[("cnt", dk)]])
                    nnv = compact(sums[srow_of[("nn", dk)]])
                    if want_max:
                        # any NaN in the group wins
                        m = jnp.where(cntv > nnv, jnp.float32(jnp.nan), m)
                    else:
                        # min ignores NaN unless the group is all-NaN
                        m = jnp.where(nnv > 0, m, jnp.float32(jnp.nan))
                    dt = a.func.buffer_dtypes()[0]
                    buf_groups.append([(m.astype(dt.np_dtype),
                                        (cntv > 0) & live_g)])
                elif kind == "iminmax":
                    want_max = descs[ai][1]
                    vmin = agg_meta[ai][1]
                    m = compact(maxs[mrow_of[("m", ai)]])
                    if not want_max:
                        m = -m
                    word = vmin + jnp.maximum(m, 0).astype(jnp.uint64)
                    cntv = compact(sums[srow_of[("cnt", dk)]])
                    dt = a.func.buffer_dtypes()[0]
                    buf_groups.append([(
                        decode_word(dt, word),
                        (cntv > 0) & live_g)])
                elif kind == "firstlast":
                    want_last = descs[ai][1]
                    m = compact(maxs[mrow_of[("m", ai)]])
                    has_g = (m > NEG_INF) & live_g
                    if not want_last:
                        m = -m
                    pos_g = jnp.clip(m, 0, cap - 1).astype(jnp.int32)
                    data = jnp.take(c.data, pos_g)
                    vld = jnp.take(c.validity, pos_g)
                    buf_groups.append([(data, has_g & vld)])
            return (fit.astype(jnp.int32), ng, key_pairs, buf_groups)

        return _core

    def _ws_prepare(self, src_schema):
        """One-time guards + signature derivation for the whole-stage
        core; False when this (pre_ops, schema) can never fuse."""
        from .fused import _tree_fusable, expr_signature
        from .staged import ops_fusable, ops_signature
        if not ops_fusable(self.pre_ops):
            return False
        osig = ops_signature(self.pre_ops)
        if osig is None:
            return False
        post_schema = self.pre_ops[-1][2]
        try:
            bound_keys = [e.bind(post_schema) for e in self.group_exprs]
            bound_inputs = [[c.bind(post_schema) for c in a.func.children]
                            for a in self.aggs]
        except KeyError:
            return False
        if not all(_tree_fusable(e) for e in bound_keys):
            return False
        if any(e.dtype() == T.STRING or e.dtype().is_nested
               for e in bound_keys):
            return False
        for bs in bound_inputs:
            if not all(_tree_fusable(e) for e in bs):
                return False
        if not all(isinstance(a.func, TpuHashAggregate._FUSABLE_FUNCS)
                   for a in self.aggs):
            return False
        ksigs = [expr_signature(e) for e in bound_keys]
        isigs = [tuple(expr_signature(e) for e in bs)
                 for bs in bound_inputs]
        if any(s is None for s in ksigs) or \
                any(s is None for t in isigs for s in t):
            return False
        cache_key = ("ws", osig, tuple(ksigs),
                     tuple(x for t in isigs for x in t),
                     tuple(f.dtype.name for f in src_schema),
                     tuple((type(a.func).__name__, repr(a.func),
                            getattr(a.func, "ignore_nulls", None))
                           for a in self.aggs))
        return cache_key, bound_keys, bound_inputs

    def _fused_whole_stage_core(self, batch: ColumnarBatch,
                                emit_buffers: bool = True,
                                out_cap: Optional[int] = None):
        """scan-side filter/project chain + key eval + grouping + update
        + output assembly as ONE jitted program (whole-stage codegen
        role, exec/staged.py).

        Returns (num_groups, fit, [(data, validity)] output pairs in
        schema order) or None to fall back (the caller then applies
        pre_ops eagerly).  ``out_cap`` requests speculative device-side
        compaction to that capacity; ``fit`` is the device flag that the
        group count fit (always-1 when uncompacted)."""
        import jax
        import logging
        from .fused import _TracedBatch, _tree_fusable, expr_signature
        from .staged import ops_fusable, ops_signature, apply_ops_traced
        if TpuHashAggregate._FUSABLE_FUNCS is None:
            from ..expr import aggregates as ea
            TpuHashAggregate._FUSABLE_FUNCS = (
                ea.Sum, ea.Count, ea.Min, ea.Max, ea.Average, ea.First,
                ea.Last, ea.CentralMoment)
        if batch.capacity > (1 << 22) or not batch.columns:
            return None
        if not all(type(c) is Column for c in batch.columns):
            return None
        # the guard walks + signature derivation are schema-invariant:
        # compute once per (source dtypes), not per batch
        mkey = tuple(f.dtype.name for f in batch.schema)
        prep = self._ws_memo.get(mkey)
        if prep is None:
            prep = self._ws_prepare(batch.schema)
            self._ws_memo[mkey] = prep
        if prep is False:
            return None
        from ..kernels.aggregate import _pair_sum_enabled
        cache_key, bound_keys, bound_inputs = prep
        cache_key = cache_key + (emit_buffers, out_cap,
                                 _pair_sum_enabled())
        core = TpuHashAggregate._CORE_CACHE.get(cache_key)
        if core is False:
            return None
        if core is None:
            src_schema = batch.schema
            pre_ops = self.pre_ops
            aggs = self.aggs

            def _core(datas, valids, num_rows):
                cap = datas[0].shape[0]
                cols = [Column(f.dtype, d, v)
                        for f, d, v in zip(src_schema, datas, valids)]
                b = _TracedBatch(src_schema, cols, num_rows, cap)
                b = apply_ops_traced(pre_ops, b)
                kcols = [ec.eval_as_column(e, b) for e in bound_keys]
                words = canon.batch_key_words(kcols, b.num_rows)
                plan = agg_k.groupby_plan(words)
                agg_buffers = []
                for a, bs in zip(aggs, bound_inputs):
                    cols2 = [ec.eval_as_column(e, b) for e in bs] or [None]
                    agg_buffers.append(a.func.update(plan, cols2))
                ocap = min(out_cap, cap) if out_cap else cap
                fit = (plan.num_groups <= ocap).astype(jnp.int32) \
                    if out_cap else jnp.int32(1)
                ng, outs = _assemble_group_output(plan, kcols, aggs,
                                                  agg_buffers, ocap,
                                                  emit_buffers)
                return ng, fit, outs
            core = _compile_watch.wrap_miss(
                "hash_aggregate", jax.jit(_core), str(cache_key))
            TpuHashAggregate._CORE_CACHE[cache_key] = core
            ws_nps = tuple(f.dtype.np_dtype for f in batch.schema)
            if not any(d is None for d in ws_nps):
                def warm(bucket: int) -> None:
                    ds = tuple(jnp.zeros(bucket, d) for d in ws_nps)
                    vs = tuple(jnp.zeros(bucket, jnp.bool_)
                               for _ in ws_nps)
                    core(ds, vs, jnp.int32(0))
                _aot.register_warmer("hash_aggregate_whole_stage", warm,
                                     str(hash(cache_key)))
        datas = tuple(c.data for c in batch.columns)
        valids = tuple(c.validity for c in batch.columns)
        _aot.note_demand("hash_aggregate", batch.capacity,
                         _costplane.rows_if_resolved(batch))
        try:
            return core(datas, valids, batch.rows_dev)
        except Exception:  # noqa: BLE001 - fall back, but loudly
            logging.getLogger("spark_rapids_tpu.exec.aggregate").warning(
                "whole-stage aggregate core failed; falling back",
                exc_info=True)
            TpuHashAggregate._CORE_CACHE[cache_key] = False
            return None

    # -- core -------------------------------------------------------------------
    def _aggregate_batch(self, batch: ColumnarBatch,
                         emit_buffers: bool = False,
                         no_table: bool = False,
                         no_compact: bool = False) -> ColumnarBatch:
        if not no_table and self.mode == PARTIAL and self.group_exprs:
            t = self._fused_table_core(batch)
            if t is not None:
                return t
        emit = emit_buffers or self.mode == PARTIAL
        out_schema_obj = buffer_schema(self.group_exprs, self.aggs) \
            if emit else self.output_schema
        # speculative device-side compaction: hand downstream a small-
        # capacity batch instead of the input-capacity one (group counts
        # are almost always << rows); the fit flag is verified at the
        # consumer's flush barrier, a misfit recomputes uncompacted and
        # turns compaction off for this exec
        compact_cap = None
        if not no_compact and self.group_exprs and \
                self._ws_memo.get("compact_state") != "off":
            from ..config import get_active, AGG_COMPACT_ROWS
            cc = int(get_active().get(AGG_COMPACT_ROWS))
            if cc > 0 and batch.capacity > cc:
                compact_cap = cc

        def _wrap_speculative(out: ColumnarBatch, fit) -> ColumnarBatch:
            if compact_cap is None:
                return out

            def redo():
                self._ws_memo["compact_state"] = "off"
                return resolve_speculative(self._aggregate_batch(
                    batch, emit_buffers=emit_buffers, no_table=no_table,
                    no_compact=True))
            out._speculative = SpeculativeResult([LazyCount(fit)], redo)
            return out
        if self.pre_ops and self.mode in (PARTIAL, COMPLETE):
            ws = self._fused_whole_stage_core(batch, emit,
                                              out_cap=compact_cap) \
                if self.group_exprs else None
            if ws is not None:
                ng, fit, pairs = ws
                cols = [Column(f.dtype, d, v)
                        for f, (d, v) in zip(out_schema_obj, pairs)]
                return _wrap_speculative(
                    ColumnarBatch(out_schema_obj, cols, LazyCount(ng)),
                    fit)
            from .staged import apply_ops_eager, build_fused_per_op
            fkey = ("fpo", tuple(f.dtype.name for f in batch.schema))
            fpo = self._ws_memo.get(fkey)
            if fpo is None:
                fpo = build_fused_per_op(self.pre_ops, batch.schema)
                self._ws_memo[fkey] = fpo
            batch = apply_ops_eager(self.pre_ops, batch, fpo)
        child_schema = batch.schema
        if self.mode in (PARTIAL, COMPLETE):
            key_cols = [ec.eval_as_column(e.bind(child_schema), batch)
                        for e in self.group_exprs]
            input_cols = []
            for a in self.aggs:
                bound = [c.bind(child_schema) for c in a.func.children]
                input_cols.append(
                    [ec.eval_as_column(b, batch) for b in bound] or [None])
        else:  # FINAL: input is keys + buffers laid out by buffer_schema
            key_cols = [batch.columns[i] for i in range(len(self.group_exprs))]
            input_cols = []
            pos = len(self.group_exprs)
            for a in self.aggs:
                nb = a.func.num_buffers
                input_cols.append(batch.columns[pos: pos + nb])
                pos += nb

        if not self.group_exprs:
            return self._global_agg(batch, input_cols, emit_buffers)

        update_mode = self.mode in (PARTIAL, COMPLETE)
        fused = self._fused_agg_core(key_cols, input_cols, update_mode,
                                     batch, emit, out_cap=compact_cap)
        if fused is not None:
            ng, fit, pairs = fused
            cols = [Column(f.dtype, d, v)
                    for f, (d, v) in zip(out_schema_obj, pairs)]
            return _wrap_speculative(
                ColumnarBatch(out_schema_obj, cols, LazyCount(ng)), fit)
        words = canon.batch_key_words(key_cols, batch.rows_dev)
        plan = agg_k.groupby_plan(words)
        # aggregate buffers (segment-id indexed, 0..G-1, input capacity)
        agg_buffers = []
        for a, cols in zip(self.aggs, input_cols):
            bufs = a.func.update(plan, cols) if update_mode else \
                a.func.merge(plan, cols)
            agg_buffers.append(bufs)
        # group count stays on device: per-batch int(num_groups) pulls
        # were the engine's dominant cost on remote-dispatch hardware
        # (LazyCount doc); output capacity = input capacity (groups <=
        # rows) so no host value is needed to shape the result
        ng = plan.num_groups
        lazy_groups = LazyCount(ng)
        out_cap = batch.capacity

        # compact group keys: representative original-row indices
        rep = plan.rep_indices
        take = jnp.where(jnp.arange(out_cap) < ng,
                         rep[:out_cap] if out_cap <= rep.shape[0] else
                         jnp.pad(rep, (0, out_cap - rep.shape[0]))[:out_cap],
                         0)
        live = jnp.arange(out_cap) < ng
        out_cols = [c.gather(take, live=live, unique=True)
                    for c in key_cols]
        out_cols = [c.mask_validity(live) for c in out_cols]

        # compact agg outputs: buffer arrays are already segment-indexed
        for a, bufs in zip(self.aggs, agg_buffers):
            if self.mode == PARTIAL or emit_buffers:
                outs = bufs
            else:
                outs = [a.func.finalize(bufs)]
            for o in outs:
                seg_take = jnp.where(live, jnp.arange(out_cap), 0)
                assert o.capacity >= out_cap, (o.capacity, out_cap)
                c = o.gather(seg_take, live=live, unique=True)
                out_cols.append(c.mask_validity(live))
        out_schema = buffer_schema(self.group_exprs, self.aggs) \
            if emit_buffers else self.output_schema
        return ColumnarBatch(out_schema, out_cols, lazy_groups)

    def _global_agg(self, batch: ColumnarBatch,
                    input_cols: List[List[Column]],
                    emit_buffers: bool = False) -> ColumnarBatch:
        """No group keys: aggregate everything into one row (one segment).

        The whole computation is one jitted program (eager dispatches
        cost ~7ms each on the remote backend, columnar/pending.py doc);
        falls back to the traced body run eagerly for exotic columns."""
        from ..expr.aggregates import Count
        update_mode = self.mode in (PARTIAL, COMPLETE)
        emit = emit_buffers or self.mode == PARTIAL
        out_schema = buffer_schema(self.group_exprs, self.aggs) \
            if emit else self.output_schema
        aggs = self.aggs
        in_dts = tuple(tuple(None if c is None else c.dtype for c in cols)
                       for cols in input_cols)
        cap0 = batch.capacity  # captured as int: the closure must not pin
        # the batch (jit cores are cached class-level and would leak it)

        def _core(in_arrays, num_rows):
            const = Column(T.INT64, jnp.zeros(cap0, jnp.int64),
                           jnp.arange(cap0) < num_rows)
            words = canon.batch_key_words([const], num_rows)
            plan = agg_k.groupby_plan(words)
            out_cap = bucket_capacity(1)
            has_rows = num_rows > 0
            outs = []
            it = iter(in_arrays)
            for a, dts in zip(aggs, in_dts):
                cols = [None if dt is None else Column(dt, *next(it))
                        for dt in dts] or [None]
                bufs = a.func.update(plan, cols) if update_mode \
                    else a.func.merge(plan, cols)
                cols_out = bufs if emit else [a.func.finalize(bufs)]
                for o in cols_out:
                    c = o.gather(jnp.zeros(out_cap, jnp.int32))
                    live = jnp.arange(out_cap) < 1
                    if isinstance(a.func, Count):
                        # counts are valid even over empty input (0)
                        c = Column(T.INT64,
                                   jnp.where(live,
                                             c.data.astype(jnp.int64), 0),
                                   live)
                    else:
                        c = c.mask_validity(live & has_rows)
                    outs.append((c.data, c.validity))
            return outs

        plain = all(c is None or type(c) is Column
                    for cols in input_cols for c in cols)
        in_arrays = tuple((c.data, c.validity)
                          for cols in input_cols for c in cols
                          if c is not None)
        pairs = None
        if plain:
            import jax
            import logging
            from ..kernels.aggregate import _pair_sum_enabled
            cache_key = ("global", update_mode, emit, in_dts,
                         batch.capacity, _pair_sum_enabled(),
                         tuple((type(a.func).__name__, repr(a.func),
                                getattr(a.func, "ignore_nulls", None))
                               for a in aggs))
            core = TpuHashAggregate._CORE_CACHE.get(cache_key)
            if core is not False:
                if core is None:
                    core = _compile_watch.wrap_miss(
                        "hash_aggregate", jax.jit(_core), str(cache_key))
                    TpuHashAggregate._CORE_CACHE[cache_key] = core
                _aot.note_demand("hash_aggregate", batch.capacity,
                                 _costplane.rows_if_resolved(batch))
                try:
                    pairs = core(in_arrays, batch.rows_dev)
                except Exception:  # noqa: BLE001 - fall back, but loudly
                    logging.getLogger(
                        "spark_rapids_tpu.exec.aggregate").warning(
                        "global aggregate core failed; falling back",
                        exc_info=True)
                    TpuHashAggregate._CORE_CACHE[cache_key] = False
                    pairs = None
        if pairs is None:
            pairs = _core(in_arrays, batch.rows_dev)
        out_cols = [Column(f.dtype, d, v)
                    for f, (d, v) in zip(out_schema, pairs)]
        return ColumnarBatch(out_schema, out_cols, 1)


# ---------------------------------------------------------------------------
# program audit registration (analysis/program_audit.py): the three
# hash_aggregate core sites (_fused_agg_core, _fused_whole_stage_core,
# _global_agg) build their programs per-batch inside the exec, so each
# provider DRIVES a tiny CPU batch through the real site and then pulls
# the freshly cached core out of _CORE_CACHE for abstract tracing.
# ---------------------------------------------------------------------------

def _int_col(cap, fill=None):
    data = jnp.arange(cap, dtype=jnp.int64) if fill is None \
        else jnp.full((cap,), fill, jnp.int64)
    return Column(T.INT64, data, jnp.ones((cap,), bool))


def _audit_agg(group=True):
    from ..expr import aggregates as ea
    agg = object.__new__(TpuHashAggregate)
    agg.aggs = [AggExpr(ea.Sum(ec.BoundReference(1 if group else 0,
                                                 T.INT64)), "s")]
    agg.group_exprs = [ec.BoundReference(0, T.INT64)] if group else []
    agg.pre_ops = None
    agg._ws_memo = {}
    return agg


def _cached_core(cache_key, what):
    core = TpuHashAggregate._CORE_CACHE.get(cache_key)
    if core is None or core is False:
        raise RuntimeError(
            f"audit drive did not populate the {what} core under the "
            f"reconstructed cache key {cache_key!r}")
    return core


def _audit_specs():
    import jax
    import numpy as np
    from ..analysis.program_audit import AuditSpec
    from ..kernels.aggregate import _pair_sum_enabled

    def _agg_sig(agg):
        return tuple((type(a.func).__name__, repr(a.func),
                      getattr(a.func, "ignore_nulls", None))
                     for a in agg.aggs)

    def _pair_sds(cap):
        return (jax.ShapeDtypeStruct((cap,), np.int64),
                jax.ShapeDtypeStruct((cap,), np.bool_))

    def _grouped():
        agg = _audit_agg()
        cap = 16
        key_col, val_col = _int_col(cap), _int_col(cap, 1)
        schema = Schema([Field("k", T.INT64, True),
                         Field("v", T.INT64, True)])
        batch = ColumnarBatch(schema, [key_col, val_col], 8)
        out = agg._fused_agg_core([key_col], [[val_col]], True, batch,
                                  False)
        assert out is not None, "grouped agg core fell back"
        cache_key = (True, False, (T.INT64,), ((T.INT64,),), None,
                     _pair_sum_enabled(), _agg_sig(agg))
        core = _cached_core(cache_key, "grouped")
        c = batch.capacity
        args = ((_pair_sds(c),), (_pair_sds(c),),
                jax.ShapeDtypeStruct((), np.int32))
        return core, args, {}

    def _whole_stage():
        from ..expr.predicates import GreaterThan
        agg = _audit_agg()
        schema = Schema([Field("k", T.INT64, True),
                         Field("v", T.INT64, True)])
        agg.pre_ops = [("filter",
                        GreaterThan(ec.BoundReference(1, T.INT64),
                                    ec.lit(0)), schema)]
        cap = 16
        batch = ColumnarBatch(schema, [_int_col(cap), _int_col(cap, 1)],
                              8)
        out = agg._fused_whole_stage_core(batch, emit_buffers=True)
        assert out is not None, "whole-stage agg core fell back"
        mkey = tuple(f.dtype.name for f in batch.schema)
        prep = agg._ws_memo[mkey]
        cache_key = prep[0] + (True, None, _pair_sum_enabled())
        core = _cached_core(cache_key, "whole-stage")
        c = batch.capacity
        d = jax.ShapeDtypeStruct((c,), np.int64)
        v = jax.ShapeDtypeStruct((c,), np.bool_)
        args = ((d, d), (v, v), jax.ShapeDtypeStruct((), np.int32))
        return core, args, {}

    def _global():
        agg = _audit_agg(group=False)
        agg.mode = PARTIAL
        cap = 16
        val_col = _int_col(cap, 1)
        schema = Schema([Field("v", T.INT64, True)])
        batch = ColumnarBatch(schema, [val_col], 8)
        agg._global_agg(batch, [[val_col]], emit_buffers=False)
        cache_key = ("global", True, True, ((T.INT64,),),
                     batch.capacity, _pair_sum_enabled(), _agg_sig(agg))
        core = _cached_core(cache_key, "global")
        c = batch.capacity
        args = ((_pair_sds(c),), jax.ShapeDtypeStruct((), np.int32))
        return core, args, {}

    return [
        AuditSpec("hash_aggregate_grouped", "hash_aggregate", _grouped,
                  notes="sum(v) group by k, update mode",
                  budgets={"gather": 34, "scatter": 4, "transpose": 4,
                           "sort": 6}),
        AuditSpec("hash_aggregate_whole_stage", "hash_aggregate",
                  _whole_stage,
                  notes="filter(v>0) chain folded into sum(v) by k",
                  budgets={"gather": 42, "scatter": 4, "transpose": 4,
                           "sort": 8}),
        AuditSpec("hash_aggregate_global", "hash_aggregate", _global,
                  notes="global (no group keys) sum, partial mode",
                  budgets={"gather": 30, "scatter": 4, "transpose": 4,
                           "sort": 6}),
    ]
