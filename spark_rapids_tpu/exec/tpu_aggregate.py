"""TPU hash-aggregate operator.

Reference: GpuHashAggregateExec (aggregate.scala:240,282-460): per-batch
update aggregation, then concat+merge of partials, with partial/final/
complete modes driven by the planner around exchanges.

TPU-first: grouping is the sort+segmented-reduce kernel
(kernels/aggregate.py) — no hash tables; one compiled program per
(schema, capacity) bucket.
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from ..columnar import dtypes as T
from ..columnar.schema import Field, Schema
from ..columnar.column import Column, bucket_capacity
from ..columnar.batch import ColumnarBatch, concat_batches
from ..expr import core as ec
from ..expr.aggregates import AggregateFunction
from ..kernels import canon, aggregate as agg_k
from ..plan.logical import AggExpr
from .base import PhysicalPlan, AGG_TIME, NUM_OUTPUT_ROWS, timed
from .tpu_basic import TpuExec

PARTIAL, FINAL, COMPLETE = "partial", "final", "complete"


def buffer_schema(group_exprs, aggs: List[AggExpr]) -> Schema:
    """Schema of partial-aggregation output: keys + flattened buffers."""
    fields = [Field(ec.output_name(e), e.dtype(), True) for e in group_exprs]
    for a in aggs:
        for bi, bt in enumerate(a.func.buffer_dtypes()):
            fields.append(Field(f"__{a.alias}__buf{bi}", bt, True))
    return Schema(fields)


class TpuHashAggregate(TpuExec):
    def __init__(self, group_exprs: List[ec.Expression], aggs: List[AggExpr],
                 child: PhysicalPlan, mode: str = COMPLETE):
        super().__init__(child)
        self.group_exprs = group_exprs
        self.aggs = aggs
        self.mode = mode

    @property
    def output_schema(self):
        if self.mode == PARTIAL:
            return buffer_schema(self.group_exprs, self.aggs)
        fields = [Field(ec.output_name(e), e.dtype(), True)
                  for e in self.group_exprs]
        fields += [Field(a.alias, a.func.dtype(), a.func.nullable)
                   for a in self.aggs]
        return Schema(fields)

    def _node_string(self):
        return f"TpuHashAggregate[{self.mode}]"

    def execute(self):
        child_schema = self.children[0].output_schema
        nkeys = len(self.group_exprs)

        def run(part):
            # per-batch update aggregation, then concat+merge of partials —
            # the reference's iterative model (aggregate.scala:366-390)
            # keeps memory bounded by partial size, not input size.
            partials = []
            with timed(self.metrics[AGG_TIME]):
                for batch in part:
                    if batch.num_rows == 0 and partials:
                        continue
                    partials.append(self._update_batch(batch))
                if not partials:
                    partials = [self._update_batch(
                        ColumnarBatch.empty(child_schema))]
                merged = concat_batches(partials) if len(partials) > 1 \
                    else partials[0]
                out = self._merge_finalize(merged,
                                           multiple=len(partials) > 1)
            self.metrics[NUM_OUTPUT_ROWS] += out.num_rows
            yield out
        return [run(p) for p in self.children[0].execute()]

    def _update_batch(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Partial (update) aggregation of one input batch -> buffer batch."""
        inner = TpuHashAggregate(self.group_exprs, self.aggs,
                                 self.children[0], mode=PARTIAL)
        if self.mode == FINAL:
            # input is already buffer-shaped: merge within the batch
            inner = TpuHashAggregate(self.group_exprs, self.aggs,
                                     self.children[0], mode=FINAL)
            inner_out = inner._aggregate_batch(batch, emit_buffers=True)
            return inner_out
        return inner._aggregate_batch(batch)

    def _merge_finalize(self, merged: ColumnarBatch,
                        multiple: bool) -> ColumnarBatch:
        if self.mode == PARTIAL:
            if not multiple:
                return merged
            # merge duplicate keys across partials, stay in buffer form
            inner = TpuHashAggregate(self.group_exprs, self.aggs,
                                     self.children[0], mode=FINAL)
            return inner._aggregate_batch(merged, emit_buffers=True)
        inner = TpuHashAggregate(self.group_exprs, self.aggs,
                                 self.children[0], mode=FINAL)
        return inner._aggregate_batch(merged)

    # -- core -------------------------------------------------------------------
    def _aggregate_batch(self, batch: ColumnarBatch,
                         emit_buffers: bool = False) -> ColumnarBatch:
        child_schema = batch.schema
        if self.mode in (PARTIAL, COMPLETE):
            key_cols = [ec.eval_as_column(e.bind(child_schema), batch)
                        for e in self.group_exprs]
            input_cols = []
            for a in self.aggs:
                bound = [c.bind(child_schema) for c in a.func.children]
                input_cols.append(
                    [ec.eval_as_column(b, batch) for b in bound] or [None])
        else:  # FINAL: input is keys + buffers laid out by buffer_schema
            key_cols = [batch.columns[i] for i in range(len(self.group_exprs))]
            input_cols = []
            pos = len(self.group_exprs)
            for a in self.aggs:
                nb = a.func.num_buffers
                input_cols.append(batch.columns[pos: pos + nb])
                pos += nb

        if not self.group_exprs:
            return self._global_agg(batch, input_cols, emit_buffers)

        words = canon.batch_key_words(key_cols, batch.num_rows)
        plan = agg_k.groupby_plan(words)
        num_groups = int(plan.num_groups)
        out_cap = bucket_capacity(max(num_groups, 1))

        # aggregate buffers (indexed by segment id 0..G-1 in input capacity)
        agg_buffers: List[List[Column]] = []
        for a, cols in zip(self.aggs, input_cols):
            if self.mode in (PARTIAL, COMPLETE):
                bufs = a.func.update(plan, cols)
            else:
                bufs = a.func.merge(plan, cols)
            agg_buffers.append(bufs)

        # compact group keys: representative original-row indices
        rep = plan.rep_indices
        take = jnp.where(jnp.arange(out_cap) < num_groups,
                         rep[:out_cap] if out_cap <= rep.shape[0] else
                         jnp.pad(rep, (0, out_cap - rep.shape[0]))[:out_cap],
                         0)
        out_cols = [c.gather(take) for c in key_cols]
        live = jnp.arange(out_cap) < num_groups
        out_cols = [c.with_capacity(out_cap, num_groups).mask_validity(live)
                    if c.capacity != out_cap else c.mask_validity(live)
                    for c in out_cols]

        # compact agg outputs: buffer arrays are already segment-indexed
        for a, bufs in zip(self.aggs, agg_buffers):
            if self.mode == PARTIAL or emit_buffers:
                outs = bufs
            else:
                outs = [a.func.finalize(bufs)]
            for o in outs:
                seg_take = jnp.where(live, jnp.arange(out_cap), 0)
                c = o.gather(seg_take) if o.capacity >= out_cap else \
                    o.with_capacity(out_cap, num_groups)
                if c.capacity > out_cap:
                    c = Column(c.dtype, c.data[:out_cap],
                               c.validity[:out_cap]) \
                        if not hasattr(c, "offsets") else \
                        c.with_capacity(out_cap, num_groups)
                out_cols.append(c.mask_validity(live))
        out_schema = buffer_schema(self.group_exprs, self.aggs) \
            if emit_buffers else self.output_schema
        return ColumnarBatch(out_schema, out_cols, num_groups)

    def _global_agg(self, batch: ColumnarBatch,
                    input_cols: List[List[Column]],
                    emit_buffers: bool = False) -> ColumnarBatch:
        """No group keys: aggregate everything into one row (one segment)."""
        cap = batch.capacity
        const = Column(T.INT64, jnp.zeros(cap, jnp.int64),
                       jnp.arange(cap) < batch.num_rows)
        words = canon.batch_key_words([const], batch.num_rows)
        plan = agg_k.groupby_plan(words)
        out_cap = bucket_capacity(1)
        out_cols: List[Column] = []
        has_rows = batch.num_rows > 0
        for a, cols in zip(self.aggs, input_cols):
            if self.mode in (PARTIAL, COMPLETE):
                bufs = a.func.update(plan, cols)
            else:
                bufs = a.func.merge(plan, cols)
            outs = bufs if (self.mode == PARTIAL or emit_buffers) \
                else [a.func.finalize(bufs)]
            for o in outs:
                c = o.gather(jnp.zeros(out_cap, jnp.int32))
                live = jnp.arange(out_cap) < 1
                if not has_rows:
                    # empty input: count-like aggs give 0, others null
                    from ..expr.aggregates import Count
                    if isinstance(a.func, Count):
                        c = Column(T.INT64, jnp.zeros(out_cap, jnp.int64),
                                   live)
                    else:
                        c = c.mask_validity(jnp.zeros(out_cap, bool))
                else:
                    c = c.mask_validity(live)
                out_cols.append(c)
        out_schema = buffer_schema(self.group_exprs, self.aggs) \
            if emit_buffers else self.output_schema
        return ColumnarBatch(out_schema, out_cols, 1)
