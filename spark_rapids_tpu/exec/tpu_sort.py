"""TPU sort operator — reference: GpuSortExec.scala:56 (sort-each-batch /

single-batch / out-of-core modes) + SortUtils.scala.

TPU-first: one multi-operand lax.sort over canonical key words.  Global
sorts are range-partitioned by the planner (RangePartitioner exchange)
then locally sorted here, matching the reference's
GpuRangePartitioning + GpuSortExec pipeline.
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ..columnar.batch import ColumnarBatch, concat_batches
from ..expr import core as ec
from ..kernels import canon
from ..kernels.sort import sort_permutation
from ..plan.logical import SortOrder
from .base import PhysicalPlan, SORT_TIME, NUM_OUTPUT_ROWS, timed
from .tpu_basic import TpuExec


class TpuSort(TpuExec):
    def __init__(self, orders: List[SortOrder], child: PhysicalPlan,
                 sort_each_batch: bool = False):
        super().__init__(child)
        self.orders = orders
        self.sort_each_batch = sort_each_batch

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def _sort_batch(self, batch: ColumnarBatch) -> ColumnarBatch:
        if batch.num_rows == 0:
            return batch
        schema = batch.schema
        cols = [ec.eval_as_column(o.expr.bind(schema), batch)
                for o in self.orders]
        words = canon.batch_key_words(
            cols, batch.num_rows,
            descending=[not o.ascending for o in self.orders],
            nulls_last=[not o.effective_nulls_first for o in self.orders])
        perm = sort_permutation(words)
        out = batch.gather(perm, batch.num_rows)
        mask = jnp.arange(out.capacity) < batch.num_rows
        return ColumnarBatch(out.schema,
                             [c.mask_validity(mask) for c in out.columns],
                             batch.num_rows)

    def execute(self):
        def run(part):
            if self.sort_each_batch:
                # mode 1: sort-each-batch (GpuSortExec.scala:56 first mode)
                for b in part:
                    with timed(self.metrics[SORT_TIME]):
                        out = self._sort_batch(b)
                    self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
                    yield out
                return
            # modes 2/3: buffer input as *sorted spillable runs* so device
            # pressure can push pending runs down the tiers while more
            # input streams in (the out-of-core design of
            # GpuSortExec.scala:219), then merge.
            from ..memory.spillable import SpillableBatch
            from ..memory.arena import DeviceManager
            runs = []
            for b in part:
                if b.num_rows == 0:
                    continue
                with timed(self.metrics[SORT_TIME]):
                    sorted_run = self._sort_batch(b)
                DeviceManager.get().reserve(sorted_run.nbytes())
                runs.append(SpillableBatch(sorted_run))
            if not runs:
                return
            with timed(self.metrics[SORT_TIME]):
                batches = [r.materialize() for r in runs]
                merged = concat_batches(batches) if len(batches) > 1 \
                    else batches[0]
                out = self._sort_batch(merged)
            for r in runs:
                r.close()
            self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
            yield out
        return [run(p) for p in self.children[0].execute()]


class TpuTopN(TpuExec):
    """limit-over-sort: per-partition sort + slice, then final merge.

    Reference: GpuTopN (limit.scala)."""

    def __init__(self, n: int, orders: List[SortOrder], child: PhysicalPlan):
        super().__init__(child)
        self.n = n
        self.orders = orders
        self._sorter = TpuSort(orders, child)

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        return 1

    def execute(self):
        parts = self.children[0].execute()

        def run():
            tops = []
            for p in parts:
                batches = [b for b in p]
                if not batches:
                    continue
                batch = concat_batches(batches) if len(batches) > 1 else \
                    batches[0]
                s = self._sorter._sort_batch(batch)
                if s.num_rows > self.n:
                    s = s.slice(0, self.n)
                tops.append(s)
            if not tops:
                return
            merged = concat_batches(tops) if len(tops) > 1 else tops[0]
            final = self._sorter._sort_batch(merged)
            if final.num_rows > self.n:
                final = final.slice(0, self.n)
            self.metrics[NUM_OUTPUT_ROWS] += final.rows_lazy
            yield final
        return [run()]
