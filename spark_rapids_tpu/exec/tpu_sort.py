"""TPU sort operator — reference: GpuSortExec.scala:56 (sort-each-batch /

single-batch / out-of-core modes) + SortUtils.scala.

TPU-first: one multi-operand lax.sort over canonical key words.  Global
sorts are range-partitioned by the planner (RangePartitioner exchange)
then locally sorted here, matching the reference's
GpuRangePartitioning + GpuSortExec pipeline.
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ..columnar.batch import (ColumnarBatch, concat_batches,
                              resolve_speculative)
from ..expr import core as ec
from ..kernels import canon
from ..kernels.sort import sort_permutation
from ..plan.logical import SortOrder
from ..service.cancellation import cancel_checkpoint
from .base import PhysicalPlan, SORT_TIME, NUM_OUTPUT_ROWS, timed
from .tpu_basic import TpuExec


class TpuSort(TpuExec):
    def __init__(self, orders: List[SortOrder], child: PhysicalPlan,
                 sort_each_batch: bool = False):
        super().__init__(child)
        self.orders = orders
        self.sort_each_batch = sort_each_batch

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def _key_cols(self, batch: ColumnarBatch):
        schema = batch.schema
        return [ec.eval_as_column(o.expr.bind(schema), batch)
                for o in self.orders]

    def _key_words(self, cols, num_rows, str_words=None):
        return canon.batch_key_words(
            cols, num_rows,
            descending=[not o.ascending for o in self.orders],
            nulls_last=[not o.effective_nulls_first for o in self.orders],
            str_words=str_words)

    def _sort_batch(self, batch: ColumnarBatch) -> ColumnarBatch:
        # a sort is a flush barrier: it needs the host count anyway, so
        # verifying a speculative input (superstage join/agg chain) here
        # is free — the fit flags resolve in the same fused flush the
        # count pull triggers
        batch = resolve_speculative(batch)
        if batch.num_rows == 0:
            return batch
        words = self._key_words(self._key_cols(batch), batch.num_rows)
        perm = sort_permutation(words)
        out = batch.gather(perm, batch.num_rows, unique=True)
        mask = jnp.arange(out.capacity) < batch.num_rows
        return ColumnarBatch(out.schema,
                             [c.mask_validity(mask) for c in out.columns],
                             batch.num_rows)

    def _sort_lazy_spec(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Sort on device counts — no host pull.  Dead rows carry the
        past-rows rank word (canon), so they sort last and the valid
        prefix is exactly the sorted rows.  The input's speculative fit
        flags (superstage join/agg chain) ride onto the output; a failed
        fit re-sorts the exactly-recomputed input."""
        from ..columnar.batch import chain_speculative
        nr = batch.rows_dev
        words = self._key_words(self._key_cols(batch), nr)
        perm = sort_permutation(words)
        out = batch.gather(perm, batch.rows_lazy, unique=True)
        mask = jnp.arange(out.capacity) < nr
        out = ColumnarBatch(out.schema,
                            [c.mask_validity(mask) for c in out.columns],
                            batch.rows_lazy)
        return chain_speculative(out, batch, self._sort_batch)

    def execute(self):
        def run(part):
            if self.sort_each_batch:
                # mode 1: sort-each-batch (GpuSortExec.scala:56 first mode)
                for b in part:
                    with timed(self.metrics[SORT_TIME], self):
                        out = self._sort_batch(b)
                    self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
                    yield out
                return
            # modes 2/3: buffer input as *sorted spillable runs* so device
            # pressure can push pending runs down the tiers while more
            # input streams in (the out-of-core design of
            # GpuSortExec.scala:219), then merge.
            from ..memory.spillable import SpillableBatch
            from ..memory.arena import DeviceManager
            from ..config import (get_active, SORT_OOC_CHUNK_ROWS,
                                  SUPERSTAGE)
            if get_active().get(SUPERSTAGE):
                # superstage fast path: a single device-counted batch
                # (the common post-agg shape) sorts WITHOUT the host
                # count pull, carrying any fit flags downstream so the
                # collect/exchange barrier resolves the whole chain in
                # one fused flush
                it = iter(part)
                first = next(it, None)
                if first is None:
                    return
                second = next(it, None)
                if second is None and (
                        not isinstance(first.rows_lazy, int) or
                        getattr(first, "_speculative", None) is not None):
                    out = self._sort_lazy_spec(first)
                    self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
                    yield out
                    return
                part = [b for b in (first, second)
                        if b is not None] + list(it)
            runs = []          # (SpillableBatch, n_rows)
            total = 0
            for b in part:
                b = resolve_speculative(b)
                if b.num_rows == 0:
                    continue
                with timed(self.metrics[SORT_TIME], self):
                    sorted_run = self._sort_batch(b)
                    n = int(sorted_run.num_rows)
                DeviceManager.get().reserve(sorted_run.nbytes())
                runs.append((SpillableBatch(sorted_run, op="TpuSortExec",
                                            site="operator"), n))
                total += n
            if not runs:
                return
            chunk_rows = int(get_active().get(SORT_OOC_CHUNK_ROWS))
            if len(runs) == 1 or total <= chunk_rows:
                # in-core: one concat + resort (modes 1/2)
                with timed(self.metrics[SORT_TIME], self):
                    batches = [r.materialize() for r, _ in runs]
                    merged = concat_batches(batches) if len(batches) > 1 \
                        else batches[0]
                    out = self._sort_batch(merged)
                for r, _ in runs:
                    r.close()
                self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
                yield out
                return
            # mode 3: out-of-core range merge over spillable runs.
            # Sampling happens HERE (not per-run above) so the common
            # in-core path never pays it; one run materializes at a
            # time, bounded by a single input batch.
            sampled = []
            for spill, n in runs:
                was_spilled = spill.is_spilled()
                with timed(self.metrics[SORT_TIME], self):
                    samples, strw = self._run_samples(
                        spill.materialize(), n)
                if was_spilled:
                    # push the run straight back down: sampling must not
                    # leave every run device-resident (that would defeat
                    # the out-of-core mode in exactly its target case)
                    spill.demote()
                sampled.append((spill, n, samples, strw))
            yield from self._merge_out_of_core(sampled, total, chunk_rows)
        return [run(p) for p in self.children[0].execute()]

    # -- out-of-core merge (GpuSortExec.scala:219 third mode) --------------
    def _run_samples(self, sorted_run: ColumnarBatch, n: int):
        """(sample key mini-batch positions+cols, string word counts)
        recorded while the sorted run is still on device."""
        import numpy as np
        from ..config import get_active, SORT_OOC_SAMPLES
        from ..columnar.column import StringColumn, bucket_capacity
        from ..kernels.strings import needed_key_words
        s = min(n, int(get_active().get(SORT_OOC_SAMPLES)))
        pos = np.unique(np.linspace(0, n - 1, s).astype(np.int64))
        key_cols = self._key_cols(sorted_run)
        # pad sample positions to a capacity bucket so the gather kernel
        # compiles once per bucket, not once per sample count
        cap = bucket_capacity(len(pos))
        padded = np.full(cap, pos[-1], np.int64)
        padded[:len(pos)] = pos
        idx = jnp.asarray(padded)
        sample_cols = [c.gather(idx) for c in key_cols]
        strw = [needed_key_words(c, n) if isinstance(c, StringColumn)
                else None for c in key_cols]
        return (pos, sample_cols), strw

    def _merge_out_of_core(self, runs, total: int, chunk_rows: int):
        """Range-partitioned k-way merge: choose boundary keys from the
        runs' samples, then per output chunk upload only each run's
        candidate slice (catalog.acquire_slice keeps spilled runs
        spilled), filter to the exact range, and sort.

        Exactness: a run's rows in [b_i, b_{i+1}) all lie between the
        last sample < b_i and the first sample >= b_{i+1} (runs are
        sorted), so slicing at sample positions over-covers and the
        device-side range filter trims to exact, half-open ranges.
        Keys are extended with (run index, row position) tiebreaker
        words so heavily duplicated sort keys still split into bounded
        chunks instead of collapsing every cut onto one key value."""
        import numpy as np

        # global word count per string key so words compare across runs
        nkeys = len(self.orders)
        strw_global = []
        for k in range(nkeys):
            ws = [r[3][k] for r in runs]
            strw_global.append(max(w for w in ws) if ws[0] is not None
                               else None)

        def to_void(word_arrays):
            """[n] u64 word columns -> [n] big-endian void keys whose
            memcmp order equals lexicographic word order.  byteswap AFTER
            stacking: np.stack silently casts '>u8' inputs back to
            native-endian."""
            from ..analysis import residency  # lazy: avoids import cycle
            with residency.declared_transfer(site="sort_ooc"):
                m = np.stack([np.asarray(w) for w in word_arrays],
                             axis=1).astype(np.uint64).byteswap()
            return np.ascontiguousarray(m).view(
                np.dtype((np.void, 8 * m.shape[1]))).reshape(-1)

        # sample words per run, encoded with the GLOBAL string widths,
        # extended with (run, position) tiebreakers for uniqueness
        run_sample_void = []
        all_void = []
        for ri, (spill, n, (pos, sample_cols), _) in enumerate(runs):
            words = self._key_words(sample_cols, len(pos),
                                    str_words=strw_global)
            from ..analysis import residency  # lazy: avoids import cycle
            with residency.declared_transfer(site="sort_ooc"):
                words = [np.asarray(w[:len(pos)]) for w in words]
            words.append(np.full(len(pos), ri, np.uint64))
            words.append(pos.astype(np.uint64))
            v = to_void(words)
            run_sample_void.append(v)
            all_void.append(v)
        merged_samples = np.sort(np.concatenate(all_void))
        n_chunks = max(1, -(-total // chunk_rows))
        cuts = np.unique(merged_samples[
            (np.arange(1, n_chunks) * len(merged_samples)) // n_chunks])

        bounds = [None] + list(cuts) + [None]
        try:
            yield from self._merge_chunks(runs, run_sample_void, bounds,
                                          strw_global)
        finally:
            # close even if the consumer stops early (limit over sort):
            # a leaked run keeps its catalog entry + spill files forever
            for spill, _, _, _ in runs:
                spill.close()

    def _merge_chunks(self, runs, run_sample_void, bounds, strw_global):
        import numpy as np
        for ci in range(len(bounds) - 1):
            b_lo, b_hi = bounds[ci], bounds[ci + 1]
            pieces = []
            for ri, ((spill, n, (pos, _), _), sv) in enumerate(
                    zip(runs, run_sample_void)):
                lo_i = 0 if b_lo is None else \
                    int(pos[max(np.searchsorted(sv, b_lo, "left") - 1, 0)])
                if b_hi is None:
                    hi_i = n
                else:
                    j = int(np.searchsorted(sv, b_hi, "left"))
                    hi_i = n if j >= len(pos) else int(pos[j])
                if hi_i > lo_i:
                    piece = spill.materialize_slice(lo_i, hi_i)
                    # filter per piece: the (run, position) tiebreaker
                    # words depend on the piece's run and offset
                    piece = self._range_filter(piece, b_lo, b_hi,
                                               strw_global, ri, lo_i)
                    if piece.num_rows:
                        pieces.append(piece)
            if not pieces:
                continue
            with timed(self.metrics[SORT_TIME], self):
                chunk = concat_batches(pieces) if len(pieces) > 1 \
                    else pieces[0]
                out = self._sort_batch(chunk)
            self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
            yield out

    def _range_filter(self, chunk: ColumnarBatch, b_lo, b_hi,
                      strw_global, run_idx: int,
                      row_offset: int) -> ColumnarBatch:
        """Keep rows with b_lo <= (key words, run, pos) < b_hi."""
        import numpy as np
        from ..kernels import basic as bk
        if b_lo is None and b_hi is None:
            return chunk
        cap = chunk.capacity
        words = self._key_words(self._key_cols(chunk), chunk.num_rows,
                                str_words=strw_global)
        words = list(words)
        words.append(jnp.full(cap, run_idx, jnp.uint64))
        words.append((jnp.arange(cap, dtype=jnp.int64) + row_offset)
                     .astype(jnp.uint64))

        def unpack(v):
            return np.frombuffer(bytes(v), dtype=">u8").astype(np.uint64)

        def cmp_lt(ws, bound):
            """row words < bound (lexicographic), vectorized."""
            lt = jnp.zeros(ws[0].shape[0], bool)
            eq = jnp.ones(ws[0].shape[0], bool)
            for w, b in zip(ws, bound):
                bv = jnp.uint64(int(b))
                lt = lt | (eq & (w < bv))
                eq = eq & (w == bv)
            return lt, eq
        keep = jnp.ones(words[0].shape[0], bool)
        if b_lo is not None:
            lt, _ = cmp_lt(words, unpack(b_lo))
            keep = keep & ~lt
        if b_hi is not None:
            lt, _ = cmp_lt(words, unpack(b_hi))
            keep = keep & lt
        idx, cnt = bk.compact_indices(keep, chunk.num_rows)
        from ..analysis import residency  # lazy: avoids import cycle
        with residency.declared_transfer(site="sort_ooc"):
            n = int(cnt)
        out = chunk.gather(idx, n)
        mask = jnp.arange(out.capacity) < n
        return ColumnarBatch(out.schema,
                             [c.mask_validity(mask) for c in out.columns],
                             n)


class TpuTopN(TpuExec):
    """limit-over-sort: per-partition sort + slice, then final merge.

    Reference: GpuTopN (limit.scala)."""

    def __init__(self, n: int, orders: List[SortOrder], child: PhysicalPlan):
        super().__init__(child)
        self.n = n
        self.orders = orders
        self._sorter = TpuSort(orders, child)

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        return 1

    def _sort_lazy(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Sort + head-n entirely on device counts — no host pull.
        Dead rows carry the past-rows rank word, so they sort last and
        the head-n prefix is exactly the top rows."""
        from ..columnar.batch import LazyCount
        from ..columnar.column import bucket_capacity
        nr = batch.rows_dev
        words = self._sorter._key_words(
            self._sorter._key_cols(batch), nr)
        perm = sort_permutation(words)
        srt = batch.gather(perm, batch.rows_lazy, unique=True)
        cap = min(bucket_capacity(max(self.n, 1)), srt.capacity)
        take = jnp.arange(cap)
        out_n = jnp.minimum(nr, jnp.int32(self.n))
        live = take < out_n
        cols = [c.gather(take, live=live).mask_validity(live)
                for c in srt.columns]
        return ColumnarBatch(batch.schema, cols, LazyCount(out_n))

    def execute(self):
        from ..columnar.batch import (SpeculativeResult,
                                      resolve_speculative)
        parts = self.children[0].execute()

        def run():
            # TopN drains its entire input before emitting: checkpoint
            # per pulled batch so a cancelled/deadline-exceeded service
            # query unwinds mid-drain, not after it
            if len(parts) == 1:
                batches = []
                for b in parts[0]:
                    cancel_checkpoint()
                    batches.append(b)
                if len(batches) == 1 and not (
                        isinstance(batches[0].rows_lazy, int) and
                        batches[0].num_rows == 0):
                    # single-batch fast path: sort + head-n on device
                    # counts, PROPAGATING any speculative flag so an
                    # upstream aggregate's verify merges into the root
                    # collect's flush instead of costing its own
                    b = batches[0]
                    spec = getattr(b, "_speculative", None)
                    out = self._sort_lazy(b)
                    if spec is not None:
                        def redo(spec=spec):
                            fixed = resolve_speculative(spec.redo())
                            return self._sort_lazy(fixed)
                        out._speculative = SpeculativeResult(
                            list(spec.fits), redo)
                    self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
                    yield out
                    return
                parts[0] = iter(batches)      # replay consumed batches
            tops = []
            for p in parts:
                cancel_checkpoint()
                batches = [resolve_speculative(b) for b in p]
                batches = [b for b in batches if b.num_rows > 0]
                if not batches:
                    continue
                batch = concat_batches(batches) if len(batches) > 1 else \
                    batches[0]
                s = self._sorter._sort_batch(batch)
                if s.num_rows > self.n:
                    s = s.slice(0, self.n)
                tops.append(s)
            if not tops:
                return
            merged = concat_batches(tops) if len(tops) > 1 else tops[0]
            final = self._sorter._sort_batch(merged)
            if final.num_rows > self.n:
                final = final.slice(0, self.n)
            self.metrics[NUM_OUTPUT_ROWS] += final.rows_lazy
            yield final
        return [run()]
