"""Whole-stage fusion: chains of row-wise operators as ONE jitted program.

TPU-first rationale (the engine's analog of Spark's whole-stage codegen,
and of the reference running fused cuDF AST kernels): on real hardware
every separately-dispatched program launch pays fixed overhead, so a
pipeline of filter -> project -> ... executed op-by-op is
launch-overhead-bound.  Here a chain of row-preserving/row-filtering
operators is traced into one XLA computation per (chain structure,
schema, capacity bucket): predicates compact via in-trace gathers, and
the live row count stays a traced scalar throughout.

The planner collapses physical TpuFilter/TpuProject chains into
``TpuStagedCompute`` (plan/overrides.py post-pass), and the hash
aggregate absorbs a leading chain into its own fused core
(tpu_aggregate._fused_agg_core), so scan -> filter -> project ->
partial-agg runs as a single program launch per batch.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as T
from ..columnar.column import Column
from ..columnar.batch import ColumnarBatch, LazyCount
from ..columnar.schema import Schema
from ..compile import aot as _aot
from ..expr import core as ec
from ..kernels import basic as bk
from ..obs import compile_watch as _compile_watch
from ..obs import costplane as _costplane
from ..obs.registry import compile_cache_event
from .base import NUM_OUTPUT_ROWS, OP_TIME, timed
from .fused import FusedEval, _TracedBatch, _tree_fusable, expr_signature
from .tpu_basic import TpuExec

# op = ("filter", bound_condition, out_schema) |
#      ("project", [bound_exprs], out_schema)
Op = Tuple[str, object, Schema]


def ops_signature(ops: Sequence[Op]) -> Optional[str]:
    """Stable signature of an op chain; None if any expr is opaque."""
    parts = []
    for kind, payload, out_schema in ops:
        exprs = [payload] if kind == "filter" else list(payload)
        sigs = [expr_signature(e) for e in exprs]
        if any(s is None for s in sigs):
            return None
        parts.append(f"{kind}({';'.join(sigs)})")
    return ">".join(parts)


def ops_fusable(ops: Sequence[Op]) -> bool:
    for kind, payload, out_schema in ops:
        exprs = [payload] if kind == "filter" else list(payload)
        if not all(_tree_fusable(e) for e in exprs):
            return False
        # gathers re-order every column, so the whole row must be
        # fixed-width for the filter steps
        if kind == "filter" and any(
                f.dtype == T.STRING or f.dtype.is_nested
                for f in out_schema):
            return False
    return True


def apply_ops_traced(ops: Sequence[Op], batch) -> "_TracedBatch":
    """Run the chain under trace; batch.num_rows is a traced scalar."""
    for kind, payload, out_schema in ops:
        n = batch.num_rows
        if kind == "filter":
            pred = ec.eval_as_column(payload, batch)
            cap = batch.capacity
            keep = pred.data.astype(bool) & pred.validity
            order, cnt = bk.compact_indices(keep, n)
            live = jnp.arange(cap) < cnt
            cols = [c.gather(order, live=live, unique=True)
                    for c in batch.columns]
            cols = [c.mask_validity(live) for c in cols]
            batch = _TracedBatch(out_schema, cols, cnt, cap)
        else:
            cols = [ec.eval_as_column(e, batch) for e in payload]
            batch = _TracedBatch(out_schema, cols, n, batch.capacity)
    return batch


def apply_ops_eager(ops: Sequence[Op], batch: ColumnarBatch,
                    fused_per_op: Optional[list] = None) -> ColumnarBatch:
    """Host-driven fallback (strings/nested/host-state expressions).

    Per-op FusedEval instances (pass fused_per_op from the exec so they
    are built once, not per batch) keep the fusable SUBSET of each op
    jitted even when the chain as a whole cannot trace."""
    for i, (kind, payload, out_schema) in enumerate(ops):
        fused = fused_per_op[i] if fused_per_op is not None else None
        if kind == "filter":
            pred = None
            if fused is not None:
                cols = fused(batch)
                if cols is not None:
                    pred = cols[0]
            if pred is None:
                pred = ec.eval_as_column(payload, batch)
            keep = pred.data.astype(bool) & pred.validity
            idx, cnt = bk.compact_indices(keep, batch.rows_dev)
            n = LazyCount(cnt)
            mask = jnp.arange(batch.capacity) < cnt
            out = batch.gather(idx, n, live=mask, unique=True)
            batch = ColumnarBatch(
                out_schema, [c.mask_validity(mask) for c in out.columns],
                n)
        else:
            cols = fused(batch) if fused is not None else None
            if cols is None:
                cols = [ec.eval_as_column(e, batch) for e in payload]
            batch = ColumnarBatch(out_schema, cols, batch.rows_lazy)
    return batch


def build_fused_per_op(ops: Sequence[Op], src_schema: Schema):
    """One FusedEval per op for the eager fallback path."""
    out = []
    schema = src_schema
    for kind, payload, out_schema in ops:
        exprs = [payload] if kind == "filter" else list(payload)
        out.append(FusedEval(exprs, schema))
        schema = out_schema
    return out


class TpuStagedCompute(TpuExec):
    """A collapsed chain of filters/projections (one launch per batch).

    Reference analogue: GpuProjectExec/GpuFilterExec pipelines that the
    reference executes as fused cuDF AST expressions; Spark's own
    WholeStageCodegenExec plays the same role on CPU."""

    _JIT_CACHE: dict = {}

    def __init__(self, child, ops: List[Op], src_schema: Schema):
        super().__init__(child)
        self.ops = ops
        self.src_schema = src_schema

    @property
    def output_schema(self):
        return self.ops[-1][2]

    def _node_string(self):
        kinds = "+".join(k for k, _, _ in self.ops)
        return f"TpuStagedCompute[{kinds}]"

    def _jitted(self):
        sig = ops_signature(self.ops)
        key = None
        if sig is not None:
            key = (sig, tuple(f.dtype.name for f in self.src_schema))
            hit = TpuStagedCompute._JIT_CACHE.get(key)
            compile_cache_event("staged_compute", hit is not None)
            if hit is not None:
                return hit

        ops = self.ops
        src_schema = self.src_schema

        def _eval(capacity: int, datas, valids, num_rows):
            cols = [Column(f.dtype, d, v)
                    for f, d, v in zip(src_schema, datas, valids)]
            batch = _TracedBatch(src_schema, cols, num_rows, capacity)
            out = apply_ops_traced(ops, batch)
            return ([(c.data, c.validity) for c in out.columns],
                    out.num_rows)

        fn = jax.jit(_eval, static_argnums=(0,))
        # compile telemetry: the first call (trace + XLA compile) is
        # wall-timed into the tpu_compile_seconds plane
        fn = _compile_watch.wrap_miss(
            "staged_compute", fn, "opaque" if key is None else str(key))
        if key is not None and len(TpuStagedCompute._JIT_CACHE) < 4096:
            TpuStagedCompute._JIT_CACHE[key] = fn
            dts = tuple(f.dtype.np_dtype for f in src_schema)
            if not any(d is None for d in dts):
                def warm(bucket: int) -> None:
                    datas = tuple(jnp.zeros(bucket, d) for d in dts)
                    valids = tuple(jnp.zeros(bucket, jnp.bool_)
                                   for _ in dts)
                    fn(bucket, datas, valids, jnp.int32(0))
                _aot.register_warmer("staged_compute", warm,
                                     str(hash(key)))
        return fn

    def execute(self):
        from .base import NUM_OUTPUT_BATCHES
        fusable = ops_fusable(self.ops)
        jitted = self._jitted() if fusable else None
        fused_per_op = None if fusable else \
            build_fused_per_op(self.ops, self.src_schema)
        out_schema = self.output_schema
        has_filter = any(k == "filter" for k, _, _ in self.ops)

        def run(part):
            from ..columnar.binary64 import exact_double_enabled
            from ..columnar.batch import chain_speculative

            def stage_one(batch):
                # exactDouble: traced reassembly would strip
                # Binary64Columns created inside the program
                if jitted is not None and \
                        not exact_double_enabled() and all(
                        type(c) is Column for c in batch.columns):
                    datas = tuple(c.data for c in batch.columns)
                    valids = tuple(c.validity for c in batch.columns)
                    _aot.note_demand(
                        "staged_compute", batch.capacity,
                        _costplane.rows_if_resolved(batch))
                    pairs, cnt = jitted(batch.capacity, datas, valids,
                                        batch.rows_dev)
                    n = LazyCount(cnt) if has_filter else \
                        batch.rows_lazy
                    return ColumnarBatch(
                        out_schema,
                        [Column(f.dtype, d, v) for f, (d, v) in
                         zip(out_schema, pairs)], n)
                return apply_ops_eager(self.ops, batch, fused_per_op)

            for batch in part:
                with timed(self.metrics[OP_TIME], self):
                    out = chain_speculative(stage_one(batch), batch,
                                            stage_one)
                self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
                self.metrics[NUM_OUTPUT_BATCHES] += 1
                yield out
        return [run(p) for p in self.children[0].execute()]


# ---------------------------------------------------------------------------
# program audit registration (analysis/program_audit.py)
# ---------------------------------------------------------------------------

def _audit_specs():
    from ..analysis.program_audit import AuditSpec

    def _build():
        import numpy as np
        from ..columnar.schema import Field
        from ..expr.arithmetic import Add
        from ..expr.predicates import GreaterThan
        schema = Schema([Field("a", T.INT64, True),
                         Field("b", T.INT64, True)])
        pred = GreaterThan(ec.BoundReference(0, T.INT64), ec.lit(3))
        proj = Add(ec.BoundReference(0, T.INT64),
                   ec.BoundReference(1, T.INT64))
        out_schema = Schema([Field("s", T.INT64, True)])
        ops = [("filter", pred, schema), ("project", [proj], out_schema)]
        assert ops_fusable(ops), "representative chain did not fuse"
        st = object.__new__(TpuStagedCompute)
        st.ops = ops
        st.src_schema = schema
        fn = st._jitted()
        cap = 64
        d = jax.ShapeDtypeStruct((cap,), np.int64)
        v = jax.ShapeDtypeStruct((cap,), np.bool_)
        args = (cap, (d, d), (v, v),
                jax.ShapeDtypeStruct((), np.int32))
        return fn, args, {"static_argnums": (0,)}

    return [AuditSpec(
        "staged_compute", "staged_compute", _build,
        notes="filter(a>3) -> project(a+b) chain as one program",
        budgets={"gather": 8, "scatter": 2, "transpose": 2, "sort": 2})]
