"""Pandas-exchange relational operators (mapInPandas / applyInPandas).

Reference: the Python exec family (SURVEY.md §2.4/§2.8):
GpuMapInPandasExec, GpuFlatMapGroupsInPandasExec, GpuAggregateInPandasExec
(org/apache/spark/sql/rapids/execution/python/) — device batches are
serialized to Arrow, streamed to a Python worker, and the Arrow results
come back as device batches.  In this single-process runtime the "worker"
is in-process, but the exchange contract is identical: the user function
only ever sees pandas objects built from Arrow batches, and results are
validated/cast against the declared output schema.
"""
from __future__ import annotations

from typing import Iterator, List

import pyarrow as pa

from ..columnar.arrow import from_arrow, schema_to_arrow, to_arrow
from ..expr import core as ec
from ..expr.cpu_eval import cpu_eval, _arr
from .base import NUM_OUTPUT_ROWS, PhysicalPlan
from .cpu import CpuExec
from .tpu_basic import TpuExec


def _cast_result(pdf, out_schema: pa.Schema) -> pa.Table:
    """User pandas result -> arrow table in the declared schema."""
    t = pa.Table.from_pandas(pdf, preserve_index=False)
    arrays = []
    for f in out_schema:
        if f.name not in t.column_names:
            raise ValueError(
                f"pandas UDF result is missing column {f.name!r}")
        c = t.column(f.name).combine_chunks()
        if c.type != f.type:
            c = pa.compute.cast(c, f.type, safe=False)
        arrays.append(c)
    return pa.Table.from_arrays(arrays, schema=out_schema)


def _run_map(fn, tables: Iterator[pa.Table], out_schema: pa.Schema):
    def pdfs():
        for t in tables:
            if t.num_rows:
                yield t.to_pandas()
    for pdf in fn(pdfs()):
        yield _cast_result(pdf, out_schema)


def _run_grouped(fn, keys: List[ec.Expression], table: pa.Table,
                 out_schema: pa.Schema):
    """Evaluate key expressions, group, call fn per group."""
    import numpy as np
    import inspect
    if table.num_rows == 0:
        return
    key_arrays = [_arr(cpu_eval(k, table), table.num_rows) for k in keys]
    kt = pa.table({f"__gk{i}": a for i, a in enumerate(key_arrays)})
    pdf_all = table.to_pandas()
    kdf = kt.to_pandas()
    takes_key = len(inspect.signature(fn).parameters) >= 2
    grouped = pdf_all.groupby(
        [kdf[c] for c in kdf.columns], dropna=False, sort=False)
    for key, g in grouped:
        if not isinstance(key, tuple):
            key = (key,)
        out = fn(key, g) if takes_key else fn(g)
        yield _cast_result(out, out_schema)


class CpuMapInPandas(CpuExec):
    def __init__(self, logical, child: PhysicalPlan):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def execute(self):
        out = schema_to_arrow(self.output_schema)

        def run(part):
            for t in _run_map(self.logical.fn, iter(part), out):
                self.metrics[NUM_OUTPUT_ROWS] += t.num_rows
                yield t
        return [run(p) for p in self.children[0].execute()]


class CpuGroupedMapInPandas(CpuExec):
    def __init__(self, logical, child: PhysicalPlan):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def num_partitions_hint(self):
        return 1

    def execute(self):
        out = schema_to_arrow(self.output_schema)
        parts = self.children[0].execute()

        def run():
            tables = [t for p in parts for t in p if t.num_rows]
            if not tables:
                return
            whole = pa.concat_tables(tables, promote_options="permissive")
            for t in _run_grouped(self.logical.fn, self.logical.keys,
                                  whole, out):
                self.metrics[NUM_OUTPUT_ROWS] += t.num_rows
                yield t
        return [run()]


class TpuMapInPandas(TpuExec):
    """Device batches -> Arrow -> pandas fn -> Arrow -> device batches.

    The host round-trip is inherent to the operator (the reference's GPU
    version does the same through GpuArrowPythonRunner)."""

    def __init__(self, logical, child: PhysicalPlan):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def _node_string(self):
        return f"TpuMapInPandas[{getattr(self.logical.fn, '__name__', 'fn')}]"

    def execute(self):
        out = schema_to_arrow(self.output_schema)

        def run(part):
            tables = (to_arrow(b) for b in part)
            for t in _run_map(self.logical.fn, tables, out):
                self.metrics[NUM_OUTPUT_ROWS] += t.num_rows
                yield from_arrow(t)
        return [run(p) for p in self.children[0].execute()]


class TpuGroupedMapInPandas(TpuExec):
    def __init__(self, logical, child: PhysicalPlan):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def _node_string(self):
        return ("TpuGroupedMapInPandas"
                f"[{getattr(self.logical.fn, '__name__', 'fn')}]")

    def execute(self):
        out = schema_to_arrow(self.output_schema)
        parts = self.children[0].execute()

        def run():
            tables = [to_arrow(b) for p in parts for b in p]
            tables = [t for t in tables if t.num_rows]
            if not tables:
                return
            whole = pa.concat_tables(tables, promote_options="permissive")
            for t in _run_grouped(self.logical.fn, self.logical.keys,
                                  whole, out):
                self.metrics[NUM_OUTPUT_ROWS] += t.num_rows
                yield from_arrow(t)
        return [run()]
