"""Pandas-exchange relational operators (mapInPandas / applyInPandas).

Reference: the Python exec family (SURVEY.md §2.4/§2.8):
GpuMapInPandasExec, GpuFlatMapGroupsInPandasExec, GpuAggregateInPandasExec
(org/apache/spark/sql/rapids/execution/python/) — device batches are
serialized to Arrow, streamed to a Python worker, and the Arrow results
come back as device batches.  In this single-process runtime the "worker"
is in-process, but the exchange contract is identical: the user function
only ever sees pandas objects built from Arrow batches, and results are
validated/cast against the declared output schema.
"""
from __future__ import annotations

from typing import Iterator, List

import pyarrow as pa

from ..columnar.arrow import from_arrow, schema_to_arrow, to_arrow
from ..expr import core as ec
from ..expr.cpu_eval import cpu_eval, _arr
from .base import NUM_OUTPUT_ROWS, PhysicalPlan
from .cpu import CpuExec
from .tpu_basic import TpuExec


from .python_worker import cast_result as _cast_result  # noqa: E402
# (pyarrow-only; lives in python_worker so worker processes never
# import the engine)


def _run_map(fn, tables: Iterator[pa.Table], out_schema: pa.Schema):
    def pdfs():
        for t in tables:
            if t.num_rows:
                yield t.to_pandas()
    for pdf in fn(pdfs()):
        yield _cast_result(pdf, out_schema)


def _use_workers() -> bool:
    from ..config import get_active, PYTHON_USE_WORKERS
    try:
        return bool(get_active().get(PYTHON_USE_WORKERS))
    except Exception:  # noqa: BLE001 - before config init
        return False


def _dispatch_to_worker(fn, worker_gen_factory, fallback_factory):
    """Shared worker-vs-in-process dispatch: the fn pickles ONCE (the
    bytes feed the pool), init failures fall back before any input is
    consumed, and unpicklable fns never leave the process."""
    if _use_workers():
        import pickle as _pickle
        from .python_worker import PythonWorkerInitError
        try:
            fn_bytes = _pickle.dumps(fn)
        except Exception:  # noqa: BLE001 - closures: run in-process
            fn_bytes = None
        if fn_bytes is not None:
            gen = worker_gen_factory(fn_bytes)
            try:
                first = next(gen)
            except StopIteration:
                return
            except PythonWorkerInitError:
                # fn unpickles only in the parent's import context
                # (e.g. REPL-defined): no input consumed yet
                yield from fallback_factory()
                return
            yield first
            yield from gen
            return
    yield from fallback_factory()


def _map_results(fn, tables: Iterator[pa.Table], out_schema: pa.Schema):
    """mapInPandas results: out-of-process worker with pipelined Arrow
    IPC when enabled and the fn pickles; else the in-process path
    (GpuArrowEvalPythonExec -> in-JVM eval fallback role)."""
    from .python_worker import PythonWorkerPool
    yield from _dispatch_to_worker(
        fn,
        lambda fb: PythonWorkerPool.get().run_map(fn, tables,
                                                  out_schema,
                                                  fn_bytes=fb),
        lambda: _run_map(fn, tables, out_schema))


def _grouped_results(fn, keys, table: pa.Table, out_schema: pa.Schema):
    """applyInPandas results: per-group tables stream through a worker
    process when enabled and picklable; else in-process."""
    import pickle as _pickle
    from .python_worker import PythonWorkerPool

    def group_tables():
        for key, pdf in _iter_key_groups(keys, table):
            gt = pa.Table.from_pandas(pdf, preserve_index=False)
            gt = gt.replace_schema_metadata(
                {b"__group_key": _pickle.dumps(key)})
            yield gt
    yield from _dispatch_to_worker(
        fn,
        lambda fb: PythonWorkerPool.get().run_grouped(fn,
                                                      group_tables(),
                                                      out_schema,
                                                      fn_bytes=fb),
        lambda: _run_grouped(fn, keys, table, out_schema))


def _iter_key_groups(keys: List[ec.Expression], table: pa.Table):
    """Shared group-by-keys plumbing for every pandas exec: evaluate
    key expressions, group the pandas frame, yield (key_tuple, pdf).
    Zero keys = one global group (the whole frame)."""
    pdf_all = table.to_pandas()
    if not keys:
        yield (), pdf_all
        return
    key_arrays = [_arr(cpu_eval(k, table), table.num_rows) for k in keys]
    kdf = pa.table({f"__gk{i}": a for i, a in
                    enumerate(key_arrays)}).to_pandas()
    grouped = pdf_all.groupby(
        [kdf[c] for c in kdf.columns], dropna=False, sort=False)
    for key, g in grouped:
        if not isinstance(key, tuple):
            key = (key,)
        # normalize null keys: pandas emits NaN for null numeric keys,
        # and nan != nan would break cross-side pairing (cogroup)
        key = tuple(None if (isinstance(v, float) and v != v) else v
                    for v in key)
        yield key, g


def _run_grouped(fn, keys: List[ec.Expression], table: pa.Table,
                 out_schema: pa.Schema):
    """Evaluate key expressions, group, call fn per group."""
    import inspect
    if table.num_rows == 0:
        return
    takes_key = len(inspect.signature(fn).parameters) >= 2
    for key, g in _iter_key_groups(keys, table):
        out = fn(key, g) if takes_key else fn(g)
        yield _cast_result(out, out_schema)


class CpuMapInPandas(CpuExec):
    def __init__(self, logical, child: PhysicalPlan):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def execute(self):
        out = schema_to_arrow(self.output_schema)

        def run(part):
            for t in _run_map(self.logical.fn, iter(part), out):
                self.metrics[NUM_OUTPUT_ROWS] += t.num_rows
                yield t
        return [run(p) for p in self.children[0].execute()]


class CpuGroupedMapInPandas(CpuExec):
    def __init__(self, logical, child: PhysicalPlan):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def num_partitions_hint(self):
        return 1

    def execute(self):
        out = schema_to_arrow(self.output_schema)
        parts = self.children[0].execute()

        def run():
            tables = [t for p in parts for t in p if t.num_rows]
            if not tables:
                return
            whole = pa.concat_tables(tables, promote_options="permissive")
            for t in _run_grouped(self.logical.fn, self.logical.keys,
                                  whole, out):
                self.metrics[NUM_OUTPUT_ROWS] += t.num_rows
                yield t
        return [run()]


class TpuMapInPandas(TpuExec):
    """Device batches -> Arrow -> pandas fn -> Arrow -> device batches.

    The host round-trip is inherent to the operator (the reference's GPU
    version does the same through GpuArrowPythonRunner)."""

    def __init__(self, logical, child: PhysicalPlan):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def _node_string(self):
        return f"TpuMapInPandas[{getattr(self.logical.fn, '__name__', 'fn')}]"

    def execute(self):
        out = schema_to_arrow(self.output_schema)

        def run(part):
            tables = (to_arrow(b) for b in part)
            for t in _map_results(self.logical.fn, tables, out):
                self.metrics[NUM_OUTPUT_ROWS] += t.num_rows
                yield from_arrow(t)
        return [run(p) for p in self.children[0].execute()]


class TpuGroupedMapInPandas(TpuExec):
    def __init__(self, logical, child: PhysicalPlan):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def _node_string(self):
        return ("TpuGroupedMapInPandas"
                f"[{getattr(self.logical.fn, '__name__', 'fn')}]")

    def execute(self):
        out = schema_to_arrow(self.output_schema)
        parts = self.children[0].execute()

        def run():
            tables = [to_arrow(b) for p in parts for b in p]
            tables = [t for t in tables if t.num_rows]
            if not tables:
                return
            whole = pa.concat_tables(tables, promote_options="permissive")
            for t in _grouped_results(self.logical.fn, self.logical.keys,
                                      whole, out):
                self.metrics[NUM_OUTPUT_ROWS] += t.num_rows
                yield from_arrow(t)
        return [run()]


def _grouped_frames(keys, table: pa.Table):
    """{key_tuple: pdf} for one side of a cogroup."""
    out = {}
    if table.num_rows == 0:
        return out, table.to_pandas()
    empty = None
    for key, g in _iter_key_groups(keys, table):
        out[key] = g
        empty = g.iloc[0:0] if empty is None else empty
    return out, (empty if empty is not None else table.to_pandas())


def _run_cogrouped(fn, left_keys, right_keys, ltable: pa.Table,
                   rtable: pa.Table, out_schema: pa.Schema):
    """Full-outer key union; fn(left_pdf, right_pdf) (or with key)."""
    import inspect
    lgroups, lempty = _grouped_frames(left_keys, ltable)
    rgroups, rempty = _grouped_frames(right_keys, rtable)
    takes_key = len(inspect.signature(fn).parameters) >= 3
    seen = list(lgroups)
    seen += [k for k in rgroups if k not in lgroups]
    for key in seen:
        lg = lgroups.get(key, lempty)
        rg = rgroups.get(key, rempty)
        out = fn(key, lg, rg) if takes_key else fn(lg, rg)
        yield _cast_result(out, out_schema)


class CpuCogroupedMapInPandas(CpuExec):
    def __init__(self, logical, left: PhysicalPlan, right: PhysicalPlan):
        super().__init__(left, right)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def num_partitions_hint(self):
        return 1

    def execute(self):
        out = schema_to_arrow(self.output_schema)
        lparts = self.children[0].execute()
        rparts = self.children[1].execute()

        def run():
            lt = [t for p in lparts for t in p if t.num_rows]
            rt = [t for p in rparts for t in p if t.num_rows]
            lw = pa.concat_tables(lt, promote_options="permissive") \
                if lt else schema_to_arrow(
                    self.children[0].output_schema).empty_table()
            rw = pa.concat_tables(rt, promote_options="permissive") \
                if rt else schema_to_arrow(
                    self.children[1].output_schema).empty_table()
            for t in _run_cogrouped(self.logical.fn,
                                    self.logical.left_keys,
                                    self.logical.right_keys, lw, rw, out):
                self.metrics[NUM_OUTPUT_ROWS] += t.num_rows
                yield t
        return [run()]


class TpuCogroupedMapInPandas(TpuExec):
    """Device batches -> Arrow per side -> cogrouped pandas fn -> device.

    Reference: GpuFlatMapCoGroupsInPandasExec — both sides cross to the
    Python worker as Arrow, cogrouped by the common keys."""

    def __init__(self, logical, left: PhysicalPlan, right: PhysicalPlan):
        super().__init__(left, right)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def _node_string(self):
        return ("TpuCogroupedMapInPandas"
                f"[{getattr(self.logical.fn, '__name__', 'fn')}]")

    def execute(self):
        out = schema_to_arrow(self.output_schema)
        lparts = self.children[0].execute()
        rparts = self.children[1].execute()

        def run():
            lt = [to_arrow(b) for p in lparts for b in p]
            rt = [to_arrow(b) for p in rparts for b in p]
            lt = [t for t in lt if t.num_rows]
            rt = [t for t in rt if t.num_rows]
            lw = pa.concat_tables(lt, promote_options="permissive") \
                if lt else schema_to_arrow(
                    self.children[0].output_schema).empty_table()
            rw = pa.concat_tables(rt, promote_options="permissive") \
                if rt else schema_to_arrow(
                    self.children[1].output_schema).empty_table()
            for t in _run_cogrouped(self.logical.fn,
                                    self.logical.left_keys,
                                    self.logical.right_keys, lw, rw, out):
                self.metrics[NUM_OUTPUT_ROWS] += t.num_rows
                yield from_arrow(t)
        return [run()]


def _run_window_pandas(logical, table: pa.Table, out_schema: pa.Schema):
    """Unbounded-partition window: broadcast fn(series...) per group.
    Empty partition_by = one global partition."""
    import numpy as np
    import pandas as pd
    pdf = table.to_pandas()
    if table.num_rows == 0:
        pdf[logical.out_name] = pd.Series([], dtype="float64")
        yield _cast_result(pdf, out_schema)
        return
    fn = logical.fn
    cols = logical.fn_cols
    vals = np.empty(len(pdf), dtype=object)
    for _key, g in _iter_key_groups(logical.partition_by, table):
        v = fn(*[g[c] for c in cols])
        vals[g.index.to_numpy()] = v
    pdf[logical.out_name] = vals
    yield _cast_result(pdf, out_schema)


class CpuWindowInPandas(CpuExec):
    def __init__(self, logical, child: PhysicalPlan):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def num_partitions_hint(self):
        return 1

    def execute(self):
        out = schema_to_arrow(self.output_schema)
        parts = self.children[0].execute()

        def run():
            ts = [t for p in parts for t in p if t.num_rows]
            whole = pa.concat_tables(ts, promote_options="permissive") \
                if ts else schema_to_arrow(
                    self.children[0].output_schema).empty_table()
            for t in _run_window_pandas(self.logical, whole, out):
                self.metrics[NUM_OUTPUT_ROWS] += t.num_rows
                yield t
        return [run()]


class TpuWindowInPandas(TpuExec):
    """Reference: GpuWindowInPandasExec — unbounded-partition frames."""

    def __init__(self, logical, child: PhysicalPlan):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def _node_string(self):
        return f"TpuWindowInPandas[{self.logical.out_name}]"

    def execute(self):
        out = schema_to_arrow(self.output_schema)
        parts = self.children[0].execute()

        def run():
            ts = [to_arrow(b) for p in parts for b in p]
            ts = [t for t in ts if t.num_rows]
            whole = pa.concat_tables(ts, promote_options="permissive") \
                if ts else schema_to_arrow(
                    self.children[0].output_schema).empty_table()
            for t in _run_window_pandas(self.logical, whole, out):
                self.metrics[NUM_OUTPUT_ROWS] += t.num_rows
                yield from_arrow(t)
        return [run()]
