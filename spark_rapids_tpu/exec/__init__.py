"""Physical operators: TPU columnar execs + CPU fallback engine."""
