"""Adaptive query execution over materialized shuffle statistics.

Reference parity: the AQE handling in the reference plugin —
``GpuCustomShuffleReaderExec`` (coalesced / skew-split shuffle reads),
``GpuOverrides.removeExtraneousShuffles`` and the AQE surgery in
``GpuTransitionOverrides.optimizeAdaptiveTransitions``.  Spark AQE
re-plans a query stage after its exchanges materialize; this engine's
exchanges are eager-on-first-pull, so the adaptive operators here force
the map side, read the per-partition statistics from the shuffle
catalog (the MapOutputStatistics role), and re-shape the reduce side:

- ``TpuAQEShuffleRead``: merges adjacent small reduce partitions up to
  the advisory target size (fewer, fuller partitions mean fewer XLA
  recompilations and fuller MXU batches — the TPU analogue of Spark's
  partition-coalescing rationale).
- ``TpuAdaptiveShuffledJoin``: materializes the build side first; when
  its total size is under the runtime broadcast threshold the probe
  shuffle is skipped entirely (AQE shuffled-join -> broadcast
  conversion); otherwise both sides shuffle and skewed probe partitions
  are split into batch slices, each joined against the full build
  partition (AQE skew-join mitigation).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..columnar.batch import ColumnarBatch, concat_batches
from ..shuffle.partitioners import HashPartitioner
from .base import PhysicalPlan, NUM_OUTPUT_ROWS
from .exchange import TpuShuffleExchange
from .tpu_basic import TpuExec
from . import tpu_join as TJ


def coalesce_partition_ids(stats: List[Tuple[int, int]],
                           target_bytes: int) -> List[List[int]]:
    """Greedy adjacent merge of reduce ids below the advisory size.

    Mirrors Spark's ShufflePartitionsUtil.coalescePartitions: walk the
    partitions in order, packing neighbours until the target is reached.
    """
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for pid, (nbytes, _rows) in enumerate(stats):
        if cur and cur_bytes + nbytes > target_bytes:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(pid)
        cur_bytes += nbytes
    if cur:
        groups.append(cur)
    return groups


def skew_split_sizes(stats: List[Tuple[int, int]], factor: float,
                     min_bytes: int) -> List[bool]:
    """Which partitions count as skewed (bytes > factor * median and
    above the absolute threshold)."""
    sizes = sorted(s for s, _ in stats)
    if not sizes:
        return []
    median = sizes[len(sizes) // 2]
    return [s > max(min_bytes, factor * max(median, 1)) for s, _ in stats]


class TpuAQEShuffleRead(TpuExec):
    """Coalesced shuffle read (GpuCustomShuffleReaderExec role)."""

    def __init__(self, child: TpuShuffleExchange, target_bytes: int):
        super().__init__(child)
        self.target_bytes = target_bytes
        self._groups: Optional[List[List[int]]] = None

    @property
    def output_schema(self):
        return self.children[0].output_schema

    def num_partitions_hint(self):
        # unknown until runtime; report the exchange width
        return self.children[0].num_partitions_hint()

    def _plan_groups(self) -> List[List[int]]:
        if self._groups is None:
            ex: TpuShuffleExchange = self.children[0]
            stats = ex.partition_stats()
            self._groups = coalesce_partition_ids(stats, self.target_bytes)
        return self._groups

    def execute(self):
        ex: TpuShuffleExchange = self.children[0]
        schema = self.output_schema

        def read_group(pids):
            got = False
            for pid in pids:
                for b in ex.stream_reduce(pid):
                    if b.num_rows == 0:
                        continue
                    got = True
                    self.metrics[NUM_OUTPUT_ROWS] += b.rows_lazy
                    yield b
            if not got:
                yield ColumnarBatch.empty(schema)

        groups = self._plan_groups()
        return [read_group(g) for g in groups]

    def _node_string(self):
        g = f"{len(self._groups)} groups" if self._groups else "pending"
        return f"TpuAQEShuffleRead[{g}]"


class TpuAdaptiveShuffledJoin(TpuExec):
    """Shuffled hash join with runtime stats-driven strategy.

    Holds the *pre-exchange* children; at execution time it materializes
    the build side and picks:
      1. broadcast conversion (small build): probe side never shuffles;
      2. co-partitioned shuffled join with symmetric partition
         coalescing and probe-side skew splitting.
    """

    # join types whose build side never emits unmatched rows: safe to
    # duplicate the build partition across skew slices
    _SKEW_SAFE = {"inner", "left", "semi", "anti"}

    def __init__(self, logical, left: PhysicalPlan, right: PhysicalPlan,
                 build_right: bool, num_partitions: int,
                 broadcast_bytes: int, target_bytes: int,
                 skew_factor: float, skew_min_bytes: int):
        super().__init__(left, right)
        self.logical = logical
        self.build_right = build_right
        self.num_partitions = num_partitions
        self.broadcast_bytes = broadcast_bytes
        self.target_bytes = target_bytes
        self.skew_factor = skew_factor
        self.skew_min_bytes = skew_min_bytes
        self.strategy: Optional[str] = None   # set at execute time

    @property
    def output_schema(self):
        return self.logical.schema

    def num_partitions_hint(self):
        return self.num_partitions

    def _node_string(self):
        return (f"TpuAdaptiveShuffledJoin[{self.logical.join_type}, "
                f"strategy={self.strategy or 'pending'}]")

    # -- strategy pieces ---------------------------------------------------
    def _exchange(self, side: PhysicalPlan, keys) -> TpuShuffleExchange:
        return TpuShuffleExchange(
            side, HashPartitioner(keys, self.num_partitions))

    def _decide(self):
        p = self.logical
        left, right = self.children
        bkeys = p.right_keys if self.build_right else p.left_keys
        build_side = right if self.build_right else left
        build_ex = self._exchange(build_side, bkeys)
        stats = build_ex.partition_stats()
        total_build = sum(s for s, _ in stats)
        can_broadcast = (total_build <= self.broadcast_bytes and
                         p.join_type not in ("full",) and
                         not (p.join_type == "right" and self.build_right)
                         and not (p.join_type == "left" and
                                  not self.build_right))
        return build_ex, stats, can_broadcast

    def execute(self):
        p = self.logical
        left, right = self.children
        build_ex, build_stats, can_broadcast = self._decide()

        # the join node borrows _run_partition; its children provide only
        # binding schemas (same pre- and post-exchange)
        join = TJ.TpuShuffledHashJoin(p, left, right,
                                      build_right=self.build_right)

        if can_broadcast:
            self.strategy = "broadcast"
            # the build side is already materialized in the catalog; the
            # probe side streams its ORIGINAL partitions — no shuffle
            batches = []
            for pid in range(self.num_partitions):
                batches.extend(b for b in build_ex.read_reduce(pid)
                               if b.num_rows > 0)
            build_batch = concat_batches(batches) if batches else \
                ColumnarBatch.empty(build_ex.output_schema)
            probe = left if self.build_right else right

            def run_bcast(part):
                if self.build_right:
                    yield from join._run_partition(part,
                                                   iter([build_batch]))
                else:
                    yield from join._run_partition(iter([build_batch]),
                                                   part)
            return [run_bcast(part) for part in probe.execute()]

        self.strategy = "shuffled"
        pkeys = p.left_keys if self.build_right else p.right_keys
        probe_side = left if self.build_right else right
        probe_ex = self._exchange(probe_side, pkeys)
        probe_stats = probe_ex.partition_stats()

        # symmetric coalescing: group by COMBINED size so both sides
        # stay co-partitioned
        combined = [(b1 + b2, r1 + r2) for (b1, r1), (b2, r2)
                    in zip(build_stats, probe_stats)]
        groups = coalesce_partition_ids(combined, self.target_bytes)

        skewed = skew_split_sizes(probe_stats, self.skew_factor,
                                  self.skew_min_bytes) \
            if p.join_type in self._SKEW_SAFE else \
            [False] * len(probe_stats)

        tasks = []   # list of (probe_batch_list | None, pids)
        for g in groups:
            if len(g) == 1 and skewed[g[0]]:
                pid = g[0]
                # split the skewed probe partition by batches; each
                # slice re-reads the full build partition
                probe_batches = [b for b in probe_ex.read_reduce(pid)
                                 if b.num_rows > 0]
                nsplit = max(2, min(len(probe_batches), 4))
                chunks = [probe_batches[i::nsplit] for i in range(nsplit)]
                split_any = False
                for chunk in chunks:
                    if chunk:
                        split_any = True
                        tasks.append((chunk, [pid]))
                if not split_any:
                    tasks.append(([], [pid]))
            else:
                tasks.append((None, list(g)))

        def run_task(probe_batches, pids):
            build_batches = []
            for pid in pids:
                build_batches.extend(b for b in build_ex.read_reduce(pid)
                                     if b.num_rows > 0)
            if probe_batches is None:
                pb = []
                for pid in pids:
                    pb.extend(b for b in probe_ex.read_reduce(pid)
                              if b.num_rows > 0)
            else:
                pb = probe_batches
            if self.build_right:
                yield from join._run_partition(iter(pb),
                                               iter(build_batches))
            else:
                yield from join._run_partition(iter(build_batches),
                                               iter(pb))

        return [run_task(pb, pids) for pb, pids in tasks]
