"""TPU window operator.

Reference: GpuWindowExec.scala:338 + GpuWindowExpression.scala (cuDF
rolling/scan windows, running-window optimization for row_number etc.).

TPU-first: one sort by (partition keys, order keys) per spec, then every
window function is a segmented scan/reduce over the sorted order:
  row_number        position - segment_start
  rank / dense_rank run boundaries + segment-min of run ids
  lead / lag        shifted gather with same-segment mask
  agg (whole part.) segment reduce broadcast back through seg ids
  agg (running/rows frame) prefix sums with segment clamping
Results are scattered back to the original row order (inverse perm), so
row identity is preserved for downstream operators.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as T
from ..columnar.schema import Field, Schema
from ..columnar.column import Column
from ..columnar.batch import ColumnarBatch, concat_batches
from ..expr import core as ec
from ..expr import aggregates as eagg
from ..expr import window_funcs as wfn
from ..kernels import canon
from ..kernels.sort import sorted_words
from ..plan.logical import Window, WindowFunc
from .base import PhysicalPlan, OP_TIME, NUM_OUTPUT_ROWS, timed
from .tpu_basic import TpuExec


class TpuWindow(TpuExec):
    def __init__(self, logical: Window, child: PhysicalPlan):
        super().__init__(child)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def _node_string(self):
        return f"TpuWindow[{[w.alias for w in self.logical.window_funcs]}]"

    def execute(self):
        def run(part):
            batches = [b for b in part]
            if not batches:
                return
            batch = concat_batches(batches) if len(batches) > 1 else \
                batches[0]
            with timed(self.metrics[OP_TIME], self):
                out = self._apply(batch)
            self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
            yield out
        return [run(p) for p in self.children[0].execute()]

    # ------------------------------------------------------------------
    def _apply(self, batch: ColumnarBatch) -> ColumnarBatch:
        schema = batch.schema
        new_cols: List[Column] = list(batch.columns)
        fields = list(schema.fields)
        for wf in self.logical.window_funcs:
            col = self._eval_window(batch, wf)
            new_cols.append(col)
            fields.append(Field(wf.alias, col.dtype, True))
        return ColumnarBatch(Schema(fields), new_cols, batch.num_rows)

    def _eval_window(self, batch: ColumnarBatch, wf: WindowFunc) -> Column:
        spec = wf.spec
        cap = batch.capacity
        n = batch.num_rows
        pcols = [ec.eval_as_column(e.bind(batch.schema), batch)
                 for e in spec.partition_by]
        ocols = [ec.eval_as_column(o.expr.bind(batch.schema), batch)
                 for o in spec.order_by]

        pwords = canon.batch_key_words(pcols, n) if pcols else \
            [jnp.where(jnp.arange(cap) < n, jnp.uint64(1), jnp.uint64(2))]
        owords = canon.batch_key_words(
            ocols, n,
            descending=[not o.ascending for o in spec.order_by],
            nulls_last=[not o.effective_nulls_first
                        for o in spec.order_by]) if ocols else []

        all_words = pwords + owords
        sorted_ws, perm = sorted_words(all_words)
        live = sorted_ws[0] != jnp.uint64(2)

        npw = len(pwords)
        seg_boundary = canon.words_equal_adjacent(sorted_ws[:npw]) & live
        seg = jnp.maximum(jnp.cumsum(seg_boundary.astype(jnp.int32)) - 1, 0)
        pos = jnp.arange(cap, dtype=jnp.int64)
        # position of segment start, broadcast per row
        seg_start = jax.ops.segment_min(
            jnp.where(live, pos, jnp.int64(cap)), seg, num_segments=cap)
        row_in_seg = pos - jnp.take(seg_start, seg)

        func = wf.func
        if isinstance(func, wfn.RowNumber):
            vals = (row_in_seg + 1).astype(jnp.int64)
            out_valid = live
            out_dtype = T.INT64
        elif isinstance(func, (wfn.Rank, wfn.DenseRank)):
            run_boundary = canon.words_equal_adjacent(sorted_ws) & live
            run_id = jnp.maximum(
                jnp.cumsum(run_boundary.astype(jnp.int32)) - 1, 0)
            if isinstance(func, wfn.Rank):
                run_first = jax.ops.segment_min(
                    jnp.where(live, pos, jnp.int64(cap)), run_id,
                    num_segments=cap)
                vals = (jnp.take(run_first, run_id) -
                        jnp.take(seg_start, seg) + 1).astype(jnp.int64)
            else:
                seg_first_run = jax.ops.segment_min(
                    jnp.where(live, run_id.astype(jnp.int64),
                              jnp.int64(cap)), seg, num_segments=cap)
                vals = (run_id - jnp.take(seg_first_run, seg) + 1
                        ).astype(jnp.int64)
            out_valid = live
            out_dtype = T.INT64
        elif isinstance(func, (wfn.NTile, wfn.PercentRank, wfn.CumeDist)):
            seg_len = jax.ops.segment_sum(
                jnp.where(live, jnp.int64(1), jnp.int64(0)), seg,
                num_segments=cap)
            L = jnp.take(seg_len, seg)
            if isinstance(func, wfn.NTile):
                # Spark NTile: first (L % n) buckets hold ceil(L/n) rows
                nb = jnp.int64(func.n)
                base = L // nb
                rem = L % nb
                cut = rem * (base + 1)
                vals = jnp.where(
                    row_in_seg < cut,
                    row_in_seg // jnp.maximum(base + 1, 1),
                    rem + (row_in_seg - cut) // jnp.maximum(base, 1)) + 1
                out_valid = live
                out_dtype = T.INT64
            else:
                run_boundary = canon.words_equal_adjacent(sorted_ws) & live
                run_id = jnp.maximum(
                    jnp.cumsum(run_boundary.astype(jnp.int32)) - 1, 0)
                if isinstance(func, wfn.PercentRank):
                    run_first = jax.ops.segment_min(
                        jnp.where(live, pos, jnp.int64(cap)), run_id,
                        num_segments=cap)
                    rank = (jnp.take(run_first, run_id) -
                            jnp.take(seg_start, seg) + 1)
                    vals = jnp.where(
                        L > 1,
                        (rank - 1).astype(jnp.float64) /
                        jnp.maximum(L - 1, 1).astype(jnp.float64), 0.0)
                else:   # CumeDist: rows <= current / partition rows
                    run_last = jax.ops.segment_max(
                        jnp.where(live, pos, jnp.int64(-1)), run_id,
                        num_segments=cap)
                    vals = (jnp.take(run_last, run_id) -
                            jnp.take(seg_start, seg) + 1).astype(
                        jnp.float64) / jnp.maximum(L, 1).astype(
                        jnp.float64)
                out_valid = live
                out_dtype = T.FLOAT64
        elif isinstance(func, (wfn.Lead, wfn.Lag)):
            src = ec.eval_as_column(func.children[0].bind(batch.schema),
                                    batch)
            off = func.offset if isinstance(func, wfn.Lead) else -func.offset
            shifted_pos = pos + off
            inb = (shifted_pos >= 0) & (shifted_pos < cap)
            sp = jnp.clip(shifted_pos, 0, cap - 1).astype(jnp.int32)
            same_seg = inb & (jnp.take(seg, sp) == seg) & \
                jnp.take(live, sp) & live
            src_sorted_idx = jnp.take(perm, sp)
            sorted_vals = src.gather(src_sorted_idx)
            valid = sorted_vals.validity & same_seg
            # scatter back to original order
            inv = jnp.argsort(perm)
            out = sorted_vals.gather(inv)
            return out.mask_validity(jnp.take(valid, inv) &
                                     (jnp.arange(cap) < n))
        elif isinstance(func, eagg.AggregateFunction):
            return self._window_agg(batch, func, spec, perm, seg, live,
                                    row_in_seg, seg_start, n)
        else:
            raise NotImplementedError(f"window function {func.name}")

        inv = jnp.argsort(perm)
        vals_orig = jnp.take(vals, inv)
        valid_orig = jnp.take(out_valid, inv) & (jnp.arange(cap) < n)
        return Column(out_dtype, vals_orig.astype(out_dtype.np_dtype),
                      valid_orig)

    # ------------------------------------------------------------------
    def _window_agg(self, batch, func, spec, perm, seg, live, row_in_seg,
                    seg_start, n) -> Column:
        cap = batch.capacity
        if isinstance(func, eagg.CollectList):
            return self._window_collect(batch, func, spec, perm, seg,
                                        live, row_in_seg, seg_start, n)
        child = func.children[0] if func.children else None
        if child is not None:
            src = ec.eval_as_column(child.bind(batch.schema), batch)
            sv = jnp.take(src.data, perm) if not hasattr(src, "offsets") \
                else None
            if sv is None:
                raise NotImplementedError("string window aggregates")
            sok = jnp.take(src.validity, perm) & live
        else:
            sv = jnp.ones(cap, jnp.int64)
            sok = live

        kind, frame_lo, frame_hi = spec.frame
        unbounded = frame_lo is None and frame_hi is None
        out_dtype = func.dtype()

        if unbounded or not spec.order_by:
            # whole-partition aggregate broadcast back
            vals, ok = self._seg_reduce(func, sv, sok, seg, cap)
            vals = jnp.take(vals, seg)
            ok = jnp.take(ok, seg) & live
        elif kind == "range":
            lo_pos, hi_pos = self._range_positions(
                batch, spec, perm, seg, seg_start, live, cap,
                frame_lo, frame_hi)
            vals, ok = self._frame_agg(func, sv, sok, seg, row_in_seg,
                                       seg_start, cap, None, None,
                                       lo_pos=lo_pos, hi_pos=hi_pos,
                                       lo_unbounded=frame_lo is None,
                                       hi_unbounded=frame_hi is None)
            ok = ok & live
        else:
            lo = frame_lo  # None = unbounded preceding
            hi = frame_hi if frame_hi is not None else None
            vals, ok = self._frame_agg(func, sv, sok, seg, row_in_seg,
                                       seg_start, cap, lo, hi)
            ok = ok & live
        inv = jnp.argsort(perm)
        vals_orig = jnp.take(vals, inv)
        ok_orig = jnp.take(ok, inv) & (jnp.arange(cap) < n)
        return Column(out_dtype, vals_orig.astype(out_dtype.np_dtype),
                      ok_orig)

    def _window_collect(self, batch, func, spec, perm, seg, live,
                        row_in_seg, seg_start, n) -> Column:
        """collect_list over a window frame -> ListColumn.

        Elements come from the globally valid-compacted sorted rows:
        row i's list is vpos[c_lo_i .. c_hi_i) where cnt is the prefix
        count of valid sorted rows — one cumsum + one expand, no
        per-row loops (GpuWindowExpression collect_list role)."""
        from ..columnar.column import ListColumn, bucket_capacity
        from ..kernels import basic as bk
        from ..kernels import join as join_k
        cap = batch.capacity
        src = ec.eval_as_column(func.children[0].bind(batch.schema),
                                batch)
        sorted_src = src.gather(perm)
        valid = sorted_src.validity & live
        kind, frame_lo, frame_hi = spec.frame
        seg_start_pos, seg_end_pos = self._seg_extents(seg, seg_start,
                                                       cap)
        pos = jnp.arange(cap, dtype=jnp.int64)
        if (frame_lo is None and frame_hi is None) or not spec.order_by:
            lo_pos, hi_pos = seg_start_pos, seg_end_pos
        elif kind == "range":
            lo_pos, hi_pos = self._range_positions(
                batch, spec, perm, seg, seg_start, live, cap,
                frame_lo, frame_hi)
        else:
            lo_pos = seg_start_pos if frame_lo is None else \
                jnp.maximum(pos + frame_lo, seg_start_pos)
            hi_pos = seg_end_pos if frame_hi is None else \
                jnp.minimum(pos + frame_hi, seg_end_pos)
        cnt = jnp.cumsum(valid.astype(jnp.int64))
        hi_c = jnp.clip(hi_pos, 0, cap - 1).astype(jnp.int32)
        lo_c = jnp.clip(lo_pos - 1, -1, cap - 1)
        c_hi = jnp.take(cnt, hi_c)
        c_lo = jnp.where(lo_c < 0, 0, jnp.take(cnt, jnp.maximum(lo_c, 0)))
        m_sorted = jnp.where(hi_pos < lo_pos, 0, c_hi - c_lo)
        vpos, _ = bk.compact_indices(valid, cap)
        inv = jnp.argsort(perm)
        m_orig = jnp.where(jnp.arange(cap) < n,
                           jnp.take(m_sorted, inv), 0)
        c_lo_orig = jnp.take(c_lo, inv)
        from ..analysis import residency  # lazy: avoids import cycle
        with residency.declared_transfer(site="size_probe"):
            total = int(jnp.sum(m_orig))
        out_cap = bucket_capacity(max(total, 1))
        _, elem_pos, live_e, _ = join_k.expand_matches(
            c_lo_orig.astype(jnp.int32), m_orig.astype(jnp.int32),
            vpos.astype(jnp.int32), out_cap)
        elements = sorted_src.gather(elem_pos)
        elements = elements.mask_validity(live_e)
        offsets = jnp.concatenate(
            [jnp.zeros(1, jnp.int64),
             jnp.cumsum(m_orig)]).astype(jnp.int32)
        out_valid = jnp.arange(cap) < n
        return ListColumn(T.ArrayType(src.dtype), offsets, elements,
                          out_valid)

    @staticmethod
    def _seg_extents(seg, seg_start, cap):
        """(per-row segment start position, per-row segment end
        position) — shared by every frame kind."""
        seg_start_pos = jnp.take(seg_start, seg)
        seg_len = jax.ops.segment_sum(
            jnp.ones(cap, jnp.int64), seg, num_segments=cap)
        seg_end_pos = seg_start_pos + jnp.take(seg_len, seg) - 1
        return seg_start_pos, seg_end_pos

    @staticmethod
    def _minmax_ident(is_min: bool, dtype):
        if jnp.issubdtype(dtype, jnp.floating):
            return jnp.asarray(jnp.inf if is_min else -jnp.inf, dtype)
        info = jnp.iinfo(dtype)
        return jnp.asarray(info.max if is_min else info.min, dtype)

    def _seg_reduce(self, func, sv, sok, seg, cap):
        contrib_ok = sok
        if isinstance(func, eagg.Sum):
            vals = jax.ops.segment_sum(
                jnp.where(contrib_ok, sv.astype(jnp.float64)
                          if func.dtype().is_fractional else
                          sv.astype(jnp.int64), 0), seg, num_segments=cap)
            cnt = jax.ops.segment_sum(contrib_ok.astype(jnp.int64), seg,
                                      num_segments=cap)
            return vals, cnt > 0
        if isinstance(func, eagg.Count):
            vals = jax.ops.segment_sum(contrib_ok.astype(jnp.int64), seg,
                                       num_segments=cap)
            return vals, jnp.ones_like(vals, bool)
        if isinstance(func, eagg.Average):
            s = jax.ops.segment_sum(
                jnp.where(contrib_ok, sv.astype(jnp.float64), 0.0), seg,
                num_segments=cap)
            c = jax.ops.segment_sum(contrib_ok.astype(jnp.int64), seg,
                                    num_segments=cap)
            return s / jnp.maximum(c, 1), c > 0
        if isinstance(func, eagg.Min):
            big = jnp.asarray(jnp.inf if jnp.issubdtype(sv.dtype,
                                                        jnp.floating)
                              else jnp.iinfo(sv.dtype).max, sv.dtype)
            vals = jax.ops.segment_min(jnp.where(contrib_ok, sv, big), seg,
                                       num_segments=cap)
            cnt = jax.ops.segment_sum(contrib_ok.astype(jnp.int64), seg,
                                      num_segments=cap)
            return vals, cnt > 0
        if isinstance(func, eagg.Max):
            small = jnp.asarray(-jnp.inf if jnp.issubdtype(sv.dtype,
                                                           jnp.floating)
                                else jnp.iinfo(sv.dtype).min, sv.dtype)
            vals = jax.ops.segment_max(jnp.where(contrib_ok, sv, small), seg,
                                       num_segments=cap)
            cnt = jax.ops.segment_sum(contrib_ok.astype(jnp.int64), seg,
                                      num_segments=cap)
            return vals, cnt > 0
        raise NotImplementedError(f"window aggregate {func.name}")

    def _range_positions(self, batch, spec, perm, seg, seg_start, live,
                         cap, frame_lo, frame_hi):
        """RANGE frame bounds as sorted-row positions via rank search.

        Reference: cuDF range-window support behind GpuWindowExec.  For
        each row with order value v the frame covers rows of its
        partition with value in [v+lo, v+hi] (direction-corrected for
        DESC).  Computed without per-row loops: encode values as
        order-preserving uint64 words, rank every row's word in the
        batch-wide sorted word array, and binary-search composite
        (segment, rank) keys — all vectorized searchsorted.
        """
        order = spec.order_by[0]
        odt = order.expr.dtype()
        if isinstance(odt, T.DecimalType):
            # decimal order key: data is unscaled int64, so literal
            # frame offsets scale by 10^scale (exact when the offset
            # has no more fractional digits than the key's scale)
            sf = 10 ** odt.scale
            frame_lo = None if frame_lo is None else \
                int(round(frame_lo * sf))
            frame_hi = None if frame_hi is None else \
                int(round(frame_hi * sf))
        ocol = ec.eval_as_column(order.expr.bind(batch.schema), batch)
        vals_sorted = jnp.take(ocol.data, perm).astype(jnp.int64)
        ovalid = jnp.take(ocol.validity, perm) & live

        def enc(x):
            w = canon._ints_to_words(x, 64)
            return ~w if not order.ascending else w

        words = jnp.where(ovalid, enc(vals_sorted),
                          jnp.uint64(0xFFFFFFFFFFFFFFFF))
        v_sorted = jnp.sort(words)
        lo_off = jnp.int64(0 if frame_lo is None else frame_lo)
        hi_off = jnp.int64(0 if frame_hi is None else frame_hi)
        if order.ascending:
            t1, t2 = vals_sorted + lo_off, vals_sorted + hi_off
        else:
            # DESC: "preceding" rows hold LARGER values, so the value
            # interval flips to [v - hi, v - lo] (Spark range semantics)
            t1, t2 = vals_sorted - hi_off, vals_sorted - lo_off
        e1 = enc(t1)
        e2 = enc(t2)
        wlo = jnp.minimum(e1, e2)
        whi = jnp.maximum(e1, e2)
        r_lo = jnp.searchsorted(v_sorted, wlo, side="left")
        r_hi = jnp.searchsorted(v_sorted, whi, side="right")
        # composite (seg, rank) keys: valid rows at 1+rank, null-order
        # rows pinned to the null end of their segment
        BIG = jnp.int64(1) << jnp.int64(33)
        nulls_first = order.effective_nulls_first
        null_slot = jnp.int64(0) if nulls_first else BIG - 1
        rank_row = jnp.where(
            ovalid,
            1 + jnp.searchsorted(v_sorted, words, side="left"), null_slot)
        C = seg.astype(jnp.int64) * BIG + rank_row.astype(jnp.int64)
        # padding rows past num_rows sort AFTER every live row: pin their
        # composite to +inf or the searchsorted precondition breaks
        C = jnp.where(live, C, jnp.int64(2 ** 62))
        seg64 = seg.astype(jnp.int64)
        t_lo = jnp.where(ovalid, seg64 * BIG + 1 + r_lo,
                         seg64 * BIG + null_slot)
        t_hi = jnp.where(ovalid, seg64 * BIG + 1 + r_hi,
                         seg64 * BIG + null_slot + 1)
        lo_pos = jnp.searchsorted(C, t_lo, side="left")
        hi_pos = jnp.searchsorted(C, t_hi, side="left") - 1
        # unbounded ends widen to the partition
        seg_start_pos = jnp.take(seg_start, seg)
        seg_len = jax.ops.segment_sum(
            jnp.ones(cap, jnp.int64), seg, num_segments=cap)
        seg_end_pos = seg_start_pos + jnp.take(seg_len, seg) - 1
        if frame_lo is None:
            lo_pos = seg_start_pos
        if frame_hi is None:
            hi_pos = seg_end_pos
        lo_pos = jnp.maximum(lo_pos, seg_start_pos)
        hi_pos = jnp.minimum(hi_pos, seg_end_pos)
        return lo_pos, hi_pos

    def _frame_agg(self, func, sv, sok, seg, row_in_seg, seg_start, cap,
                   lo: Optional[int], hi: Optional[int],
                   lo_pos=None, hi_pos=None,
                   lo_unbounded: bool = False,
                   hi_unbounded: bool = False):
        """Frame [lo, hi] row offsets, or explicit positions
        (lo_pos/hi_pos from a RANGE frame)."""
        pos = jnp.arange(cap, dtype=jnp.int64)
        explicit = lo_pos is not None
        if isinstance(func, (eagg.Sum, eagg.Count, eagg.Average)):
            acc_dtype = jnp.float64 if not isinstance(func, eagg.Count) \
                else jnp.int64
            contrib = jnp.where(sok, sv.astype(acc_dtype)
                                if not isinstance(func, eagg.Count)
                                else jnp.ones(cap, jnp.int64),
                                jnp.zeros(cap, acc_dtype))
            ps = jnp.cumsum(contrib)          # inclusive prefix sum
            cnt = jnp.cumsum(sok.astype(jnp.int64))
            seg_start_pos, seg_end_pos = self._seg_extents(
                seg, seg_start, cap)
            if not explicit:
                lo_pos = seg_start_pos if lo is None else \
                    jnp.maximum(pos + lo, seg_start_pos)
                hi_pos = seg_end_pos if hi is None else \
                    jnp.minimum(pos + hi, seg_end_pos)
            hi_c = jnp.clip(hi_pos, 0, cap - 1).astype(jnp.int32)
            lo_c = jnp.clip(lo_pos - 1, -1, cap - 1)
            ps_hi = jnp.take(ps, hi_c)
            ps_lo = jnp.where(lo_c < 0, 0,
                              jnp.take(ps, jnp.maximum(lo_c, 0)))
            cnt_hi = jnp.take(cnt, hi_c)
            cnt_lo = jnp.where(lo_c < 0, 0,
                               jnp.take(cnt, jnp.maximum(lo_c, 0)))
            s = ps_hi - ps_lo
            c = cnt_hi - cnt_lo
            empty = hi_pos < lo_pos
            if isinstance(func, eagg.Count):
                return jnp.where(empty, 0, c), jnp.ones(cap, bool)
            if isinstance(func, eagg.Average):
                return s / jnp.maximum(c, 1), (c > 0) & ~empty
            return s, (c > 0) & ~empty
        if isinstance(func, (eagg.Min, eagg.Max)) and lo is None and \
                hi == 0:
            # running min/max: segmented inclusive scan
            is_min = isinstance(func, eagg.Min)
            ident = self._minmax_ident(is_min, sv.dtype)
            x = jnp.where(sok, sv, ident)
            reset = row_in_seg == 0

            def combine(a, b):
                av, ar = a
                bv, br = b
                merged = jnp.where(br, bv,
                                   jnp.minimum(av, bv) if is_min
                                   else jnp.maximum(av, bv))
                return merged, ar | br
            scanned, _ = jax.lax.associative_scan(combine, (x, reset))
            cnt = jnp.cumsum(sok.astype(jnp.int64))
            seg_start_pos = jnp.take(seg_start, seg)
            cnt_before = jnp.where(
                seg_start_pos > 0,
                jnp.take(cnt, jnp.clip(seg_start_pos - 1, 0, cap - 1)), 0)
            has = (cnt - cnt_before) > 0
            return scanned, has
        if isinstance(func, (eagg.Min, eagg.Max)):
            is_min = isinstance(func, eagg.Min)
            ident = self._minmax_ident(is_min, sv.dtype)
            seg_start_pos, seg_end_pos = self._seg_extents(
                seg, seg_start, cap)
            x = jnp.where(sok, sv, ident)
            comb = jnp.minimum if is_min else jnp.maximum

            def seg_scan(values, reverse=False):
                reset = (row_in_seg == 0) if not reverse else \
                    (pos == seg_end_pos)
                v = values[::-1] if reverse else values
                r = reset[::-1] if reverse else reset

                def combine(a, b):
                    av, ar = a
                    bv, br = b
                    return jnp.where(br, bv, comb(av, bv)), ar | br
                scanned, _ = jax.lax.associative_scan(combine, (v, r))
                return scanned[::-1] if reverse else scanned
            if not explicit:
                lo_pos = seg_start_pos if lo is None else \
                    jnp.maximum(pos + lo, seg_start_pos)
                hi_pos = seg_end_pos if hi is None else \
                    jnp.minimum(pos + hi, seg_end_pos)
            if (not explicit and (lo is None or hi is None)) or \
                    (explicit and (lo_unbounded or hi_unbounded)):
                # half-unbounded frame (ROWS offsets or RANGE with one
                # unbounded side): one segmented scan + a gather,
                # O(cap) memory, no host sync (no sparse table needed)
                if lo is None if not explicit else lo_unbounded:
                    scanned = seg_scan(x)            # prefix from start
                    vals = jnp.take(scanned,
                                    jnp.clip(hi_pos, 0, cap - 1))
                else:
                    scanned = seg_scan(x, reverse=True)  # suffix to end
                    vals = jnp.take(scanned,
                                    jnp.clip(lo_pos, 0, cap - 1))
            else:
                # general bounded frame: log-doubling range-min/max
                # table; range [l, r] = combine of the two overlapping
                # 2^k blocks at its ends (sparse-table RMQ).  Levels
                # stop at the widest frame actually present.
                if not explicit:
                    max_window = max(hi - lo + 1, 1)
                else:
                    # RANGE frame: one host sync learns the widest window
                    from ..analysis import residency  # lazy import
                    with residency.declared_transfer(site="size_probe"):
                        max_window = max(
                            int(jnp.max(hi_pos - lo_pos + 1)), 1)
                tables = [x]
                step = 1
                while step < max_window:
                    prev = tables[-1]
                    shifted = jnp.concatenate(
                        [prev[step:], jnp.full(step, ident, prev.dtype)])
                    tables.append(comb(prev, shifted))
                    step *= 2
                rmq = jnp.stack(tables)            # [levels, cap]
                length = jnp.maximum(hi_pos - lo_pos + 1, 0)
                # k = floor(log2(length)) via static comparisons (no
                # float log on the emulated-f64 chip); 2^k <= length
                k = jnp.zeros(cap, jnp.int32)
                for j in range(1, len(tables)):
                    k = jnp.where(length >= (1 << j), j, k)
                k = jnp.minimum(k, len(tables) - 1)
                two_k = jnp.left_shift(jnp.int64(1),
                                       k.astype(jnp.int64))
                a_idx = jnp.clip(lo_pos, 0, cap - 1)
                b_idx = jnp.clip(hi_pos - two_k + 1, 0, cap - 1)
                flat = rmq.reshape(-1)
                a = jnp.take(flat, k.astype(jnp.int64) * cap + a_idx)
                b = jnp.take(flat, k.astype(jnp.int64) * cap + b_idx)
                vals = comb(a, b)
            cnt = jnp.cumsum(sok.astype(jnp.int64))
            hi_c = jnp.clip(hi_pos, 0, cap - 1).astype(jnp.int32)
            lo_c = jnp.clip(lo_pos - 1, -1, cap - 1)
            cnt_hi = jnp.take(cnt, hi_c)
            cnt_lo = jnp.where(lo_c < 0, 0,
                               jnp.take(cnt, jnp.maximum(lo_c, 0)))
            has = (cnt_hi - cnt_lo) > 0
            empty = hi_pos < lo_pos
            return vals, has & ~empty
        raise NotImplementedError(
            f"window frame ({lo},{hi}) for {func.name}")
