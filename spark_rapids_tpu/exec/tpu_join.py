"""TPU join operators.

Reference: GpuHashJoin.scala:62 (build+probe core), JoinGatherer.scala
(bounded gather maps), GpuShuffledHashJoinBase / GpuBroadcastHashJoinExec /
GpuBroadcastNestedLoopJoinExec / GpuCartesianProductExec.

TPU-first: the build side is sorted once per partition by canonical key
words; every probe batch runs a vectorized binary search + cumsum
expansion (kernels/join.py).  Join types are realized by count surgery:
  outer  -> unmatched probe rows get one null-extended output row
  semi   -> filter probe rows with count > 0
  anti   -> filter probe rows with count == 0
  full   -> left-outer + unmatched build rows appended
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from ..columnar import dtypes as T
from ..columnar.schema import Field, Schema
from ..columnar.column import Column, StringColumn, bucket_capacity
from ..columnar.batch import ColumnarBatch, concat_batches
from ..expr import core as ec
from ..kernels import canon, join as join_k
from ..kernels import strings as skern
from .base import (PhysicalPlan, BUILD_TIME, JOIN_TIME, NUM_OUTPUT_ROWS,
                   timed)
from .tpu_basic import TpuExec


def _host_int(x) -> int:
    """Declared d2h pull of one count scalar (join verify barrier)."""
    from ..analysis import residency  # lazy: avoids import cycle
    with residency.declared_transfer(site="join_verify"):
        return int(x)


def _key_words(cols: List[Column], num_rows: int,
               str_words: List[Optional[int]]):
    return canon.batch_key_words(cols, num_rows, str_words=str_words)


def _null_column(dtype: T.DType, capacity: int) -> Column:
    return Column.all_null(dtype, capacity)


class TpuHashJoinBase(TpuExec):
    """Shared build/probe logic.  children = [left, right]; the build side

    is chosen by the subclass (broadcast: the broadcast side; shuffled:
    right for inner/left, left for right joins)."""

    def __init__(self, logical, left: PhysicalPlan, right: PhysicalPlan,
                 build_right: bool = True):
        super().__init__(left, right)
        self.logical = logical
        self.build_right = build_right

    @property
    def output_schema(self) -> Schema:
        return self.logical.schema

    def _node_string(self):
        return (f"{self.name}[{self.logical.join_type}, "
                f"build={'right' if self.build_right else 'left'}]")

    # ------------------------------------------------------------------
    def _run_partition(self, left_iter, right_iter):
        lg = self.logical
        lschema = self.children[0].output_schema
        rschema = self.children[1].output_schema
        from ..columnar.batch import resolve_speculative as _resolve
        if self.build_right:
            build_batches = [_resolve(b) for b in right_iter]
            stream_iter = left_iter
            build_schema, stream_schema = rschema, lschema
            build_keys = [e.bind(rschema) for e in lg.right_keys]
            stream_keys = [e.bind(lschema) for e in lg.left_keys]
        else:
            build_batches = [_resolve(b) for b in left_iter]
            stream_iter = right_iter
            build_schema, stream_schema = lschema, rschema
            build_keys = [e.bind(lschema) for e in lg.left_keys]
            stream_keys = [e.bind(rschema) for e in lg.right_keys]

        with timed(self.metrics[BUILD_TIME], self):
            # broadcast joins run every stream partition against the SAME
            # build batches: sort the build table once per exec.  The memo
            # retains build_batches itself so the id()s in the key cannot
            # be recycled by a later partition's freshly-allocated batches
            # (a stale id()-only key could silently probe against the
            # wrong build table).
            bb_key = tuple(id(b) for b in build_batches)
            memo = getattr(self, "_build_memo", None)
            if memo is not None and memo["key"] == bb_key:
                build, bkey_cols = memo["build"], memo["bkey_cols"]
            else:
                if build_batches:
                    build = concat_batches(build_batches)
                else:
                    build = ColumnarBatch.empty(build_schema)
                bkey_cols = [ec.eval_as_column(e, build)
                             for e in build_keys]
                self._build_memo = {"key": bb_key,
                                    "batches": build_batches,
                                    "build": build,
                                    "bkey_cols": bkey_cols}

        stream_batches = list(stream_iter)
        if not stream_batches:
            stream_batches = [ColumnarBatch.empty(stream_schema)]

        # unify string key widths across sides per key position
        skey_cols_per_batch = []
        str_words: List[Optional[int]] = []
        for b in stream_batches:
            skey_cols_per_batch.append(
                [ec.eval_as_column(e, b) for e in stream_keys])
        for ki in range(len(build_keys)):
            if bkey_cols and isinstance(bkey_cols[ki], StringColumn):
                w = skern.needed_key_words(bkey_cols[ki], build.num_rows)
                for b, scols in zip(stream_batches, skey_cols_per_batch):
                    w = max(w, skern.needed_key_words(scols[ki], b.num_rows))
                str_words.append(w)
            else:
                str_words.append(None)

        memo = getattr(self, "_build_memo", None)
        if (memo is not None and "bt" in memo and memo["key"] == bb_key
                and memo.get("str_words") == str_words):
            bt = memo["bt"]
        else:
            # non-string keys never need the host count: the canon rank
            # word masks dead rows with the device count, keeping a
            # lazily-counted broadcast build sync-free
            b_nr = build.num_rows if any(w is not None
                                         for w in str_words) \
                else build.rows_dev
            bwords = _key_words(bkey_cols, b_nr, str_words)
            bt = join_k.build(bwords)
            memo = {"key": bb_key,
                    "batches": build_batches,
                    "build": build,
                    "bkey_cols": bkey_cols,
                    "str_words": list(str_words),
                    "bt": bt, "direct": None, "direct_done": False}
            self._build_memo = memo
        # the direct-address table costs ONE host sync to learn the
        # build key range (it sizes the table) — worth it only when the
        # probe side is large enough to amortize the round trip; small
        # streams (dimension-sized post-agg probes) keep the sync-free
        # binary search.  The decision is PER PARTITION (a broadcast
        # join's first small partition must not freeze the strategy for
        # later large ones); once built, the table is memoized.
        stream_cap = sum(b.capacity for b in stream_batches)
        if (not memo["direct_done"] and lg.condition is None
                and lg.join_type != "full"
                and stream_cap >= (1 << 19)):
            memo["direct"] = self._prepare_direct(bt, bkey_cols, build)
            memo["direct_done"] = True
        direct = memo["direct"]

        build_matched = np.zeros(build.capacity, dtype=bool) \
            if lg.join_type == "full" else None

        # Superstage path (compile/): sync-free speculative unique-match
        # join — no flush barrier at all; the fit flag rides to the next
        # superstage boundary.  Only the carve pass sets _superstage, and
        # only under a consumer that resolves speculative batches.
        if getattr(self, "_superstage", False) and lg.join_type == "inner" \
                and lg.condition is None and build_matched is None \
                and all(w is None for w in str_words) \
                and build.capacity > 0:
            from ..config import get_active, SUPERSTAGE_SPEC_JOIN
            if get_active().get(SUPERSTAGE_SPEC_JOIN):
                from ..obs import profile
                spec_outs = []
                for sb, skey_cols in zip(stream_batches,
                                         skey_cols_per_batch):
                    with timed(self.metrics[JOIN_TIME], self), \
                            profile.dispatch(profile.SITE_SPEC_PROBE):
                        out = self._spec_join_batch(
                            sb, skey_cols, bt, build, direct,
                            stream_keys, str_words)
                    if out is None:
                        spec_outs = None
                        break
                    spec_outs.append(out)
                if spec_outs is not None:
                    for out in spec_outs:
                        self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
                        yield out
                    return

        # Phase A: probe counts for EVERY stream batch first; the output
        # sizes (total matches) stage into the pending pool so one fused
        # flush covers all of them (columnar/pending.py).  Phase B then
        # expands/gathers with host-known output capacities.
        phase_a = []
        for sb, skey_cols in zip(stream_batches, skey_cols_per_batch):
            with timed(self.metrics[JOIN_TIME], self):
                phase_a.append(self._probe_phase(sb, skey_cols, bt,
                                                 str_words,
                                                 build_matched, direct))
        from ..columnar import pending
        from ..columnar.batch import resolve_speculative
        pending.flush()
        for (sb, skey_cols), pa in zip(
                zip(stream_batches, skey_cols_per_batch), phase_a):
            # this flush is a verification barrier: upstream (the FINAL
            # aggregate) may defer its speculative fit flag to here; the
            # flags resolved in the fused flush above, so checking is
            # free — the rare misfit batch recomputes exactly, and its
            # probe phase re-runs on the exact rows
            checked = resolve_speculative(sb)
            if checked is not sb:
                sb = checked
                skey_cols = [ec.eval_as_column(e, sb)
                             for e in stream_keys]
                with timed(self.metrics[JOIN_TIME], self):
                    pa = self._probe_phase(sb, skey_cols, bt, str_words,
                                           build_matched, direct)
                pending.flush()
            if pa is None:   # legacy eager path (full/residual/etc)
                with timed(self.metrics[JOIN_TIME], self):
                    outs = [self._join_batch(sb, skey_cols, build, bt,
                                             str_words, build_matched)]
            else:
                # generator: each chunk's expansion times itself
                outs = self._expand_phases(sb, build, bt, *pa)
            for out in outs:
                if out is not None:
                    self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
                    yield out

        if lg.join_type == "full" and build is not None:
            out = self._unmatched_build_rows(build, build_matched,
                                             stream_schema)
            if out is not None and out.num_rows > 0:
                self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
                yield out

    # -- fused probe/expand (one program each; totals via pending pool) --
    _PROBE_JIT: dict = {}
    _EXPAND_JIT: dict = {}
    _SPEC_JIT: dict = {}

    # max entries in the direct-address probe table (64 MB of i32 HBM)
    _DIRECT_MAX_RANGE = 1 << 24

    def _prepare_direct(self, bt, bkey_cols, build):
        """Direct-address probe tables for single fixed-width int keys.

        The general probe is a vectorized binary search — ~2*log2(build)
        random 64-bit gathers per probe batch, the dominant join cost on
        TPU.  When the build side has ONE int-family key whose value
        range fits a table, matching becomes two i32 gathers: per key k,
        hist[k - min] = #build rows, excl[k - min] = first position in
        the SORTED build.  Dimension keys are dense ints in practice
        (TPC-DS/mortgage), so this covers the hot joins; wide/multi/string
        keys keep the binary search.  One host sync per build (cached).
        """
        if len(bkey_cols) != 1 or type(bkey_cols[0]) is not Column:
            return None
        dt = bkey_cols[0].dtype
        if not (dt.is_integral or dt in (T.DATE, T.TIMESTAMP) or
                isinstance(dt, T.DecimalType)):
            return None
        import jax
        c = bkey_cols[0]
        w = canon.value_words(c, build.num_rows)[0]

        @jax.jit
        def _minmax(w, validity, num_rows):
            valid = validity & (jnp.arange(validity.shape[0]) < num_rows)
            any_v = jnp.any(valid)
            wmin = jnp.where(any_v,
                             jnp.min(jnp.where(valid, w,
                                               jnp.uint64(2**64 - 1))),
                             jnp.uint64(0))
            wmax = jnp.where(any_v,
                             jnp.max(jnp.where(valid, w, jnp.uint64(0))),
                             jnp.uint64(0))
            nvalid = jnp.sum(valid)
            return wmin, wmax, nvalid
        wmin, wmax, nvalid = _minmax(w, c.validity,
                                     jnp.int32(build.num_rows))
        # one host pull per build table (cached on the exec)
        import numpy as _np
        from ..analysis import residency  # lazy: avoids import cycle
        with residency.declared_transfer(site="join_verify"):
            wmin_h, wmax_h = int(_np.asarray(wmin)), int(_np.asarray(wmax))
            nnull_h = build.num_rows - int(_np.asarray(nvalid))
        rng = wmax_h - wmin_h + 1
        if rng <= 0 or rng > self._DIRECT_MAX_RANGE:
            return None
        tbl = bucket_capacity(rng)

        @jax.jit
        def _tables(w, validity, num_rows, wmin, nnull):
            valid = validity & (jnp.arange(validity.shape[0]) < num_rows)
            idx = jnp.clip((w - wmin).astype(jnp.int32), 0, tbl - 1)
            contrib = jnp.where(valid, idx, tbl)
            hist = jnp.bincount(contrib, length=tbl + 1)[:tbl] \
                .astype(jnp.int32)
            excl = (jnp.cumsum(hist) - hist + nnull).astype(jnp.int32)
            return hist, excl
        hist, excl = _tables(w, c.validity, jnp.int32(build.num_rows),
                             wmin, jnp.int32(nnull_h))
        return (jnp.uint64(wmin_h), jnp.uint64(wmax_h), hist, excl, tbl)

    def _probe_phase(self, sb, skey_cols, bt, str_words, build_matched,
                     direct=None):
        """Phase A: key eval + match lookup + join-type count surgery as
        ONE jitted program; the total output size stages into the pending
        pool.  The lookup is the direct-address table when available
        (two i32 gathers) else the vectorized binary search.  Returns
        None to use the legacy eager path."""
        import jax
        from ..columnar.batch import LazyCount
        lg = self.logical
        jt = lg.join_type
        if jt == "full" or lg.condition is not None or build_matched \
                is not None:
            return None
        if not all(type(c) is Column for c in skey_cols):
            return None
        key = ("probe", jt, tuple(c.dtype.name for c in skey_cols),
               sb.capacity, bt.capacity, len(bt.sorted_words),
               self.build_right, direct is not None and direct[4])
        fn = TpuHashJoinBase._PROBE_JIT.get(key)
        if fn is False:
            return None
        outer_stream = ((jt == "left" and self.build_right) or
                        (jt == "right" and not self.build_right))
        if fn is None:
            key_dts = tuple(c.dtype for c in skey_cols)
            tbl = direct[4] if direct is not None else 0

            def _core(bws, dparams, key_arrays, num_rows):
                kcols = [Column(dt, d, v)
                         for dt, (d, v) in zip(key_dts, key_arrays)]
                cap = key_arrays[0][0].shape[0]
                in_range = jnp.arange(cap) < num_rows
                if dparams is not None:
                    wmin, wmax, hist, excl = dparams
                    w = canon.value_words(kcols[0], num_rows)[0]
                    idx = jnp.clip((w - wmin).astype(jnp.int32), 0,
                                   tbl - 1)
                    hit = (w >= wmin) & (w <= wmax) & \
                        kcols[0].validity & in_range
                    counts = jnp.where(hit, jnp.take(hist, idx), 0)
                    lo = jnp.take(excl, idx)
                else:
                    swords = canon.batch_key_words(kcols, num_rows)
                    bt2 = join_k.BuildTable(list(bws), None, None)
                    jc = join_k.probe_counts(bt2, swords, num_rows)
                    counts, lo = jc.counts, jc.lo
                if jt in ("semi", "anti"):
                    keep = (counts > 0) if jt == "semi" else \
                        ((counts == 0) & in_range)
                    eff = keep.astype(jnp.int32)
                elif outer_stream:
                    eff = jnp.where((counts == 0) & in_range, 1, counts)
                else:
                    eff = counts
                total = jnp.sum(eff.astype(jnp.int64))
                return lo, counts, eff, total
            from ..obs import costplane as _costplane
            fn = _costplane.wrap_capture(
                "join_probe", jax.jit(_core, static_argnames=()))
            TpuHashJoinBase._PROBE_JIT[key] = fn
        key_arrays = tuple((c.data, c.validity) for c in skey_cols)
        dparams = tuple(direct[:4]) if direct is not None else None
        from ..compile import aot as _aot
        from ..obs import costplane as _costplane
        _aot.note_demand("join_probe", sb.capacity,
                         _costplane.rows_if_resolved(sb))
        try:
            lo, counts, eff, total = fn(tuple(bt.sorted_words), dparams,
                                        key_arrays, sb.rows_dev)
        except Exception:  # noqa: BLE001 - fall back, but loudly
            import logging
            logging.getLogger("spark_rapids_tpu.exec.join").warning(
                "fused probe failed; falling back", exc_info=True)
            TpuHashJoinBase._PROBE_JIT[key] = False
            return None
        return (jt, outer_stream, lo, counts, eff, LazyCount(total))

    def _spec_join_batch(self, sb, skey_cols, bt, build, direct,
                         stream_keys, str_words):
        """Speculative unique-match inner join: probe + compact + ALL
        output gathers as ONE program with a STATIC output capacity (the
        probe capacity), so no host round trip sizes the result.

        Valid when every probe row matches at most one build row — the
        star-schema dimension case.  The match total stays a LazyCount
        and a fit flag (max matches per probe row <= 1) rides the
        speculative redo machinery to the consumer's flush barrier; a
        violating batch (duplicate build keys) recomputes on the exact
        sized path.  Returns None to use the barrier path."""
        import jax
        from ..kernels import basic as bk
        from ..columnar.batch import (LazyCount, SpeculativeResult,
                                      resolve_speculative)
        if not all(type(c) is Column for c in skey_cols):
            return None
        # plain columns gather inside the program; strings gather as lazy
        # views outside it (zero dispatches); nested gathers host-sync,
        # so their presence keeps the exact path
        for c in list(sb.columns) + list(build.columns):
            if not isinstance(c, (Column, StringColumn)):
                return None
        plain_s = [i for i, c in enumerate(sb.columns)
                   if type(c) is Column]
        plain_b = [i for i, c in enumerate(build.columns)
                   if type(c) is Column]
        key = ("spec", tuple(c.dtype.name for c in skey_cols),
               sb.capacity, bt.capacity, len(bt.sorted_words),
               tuple(sb.columns[i].dtype.name for i in plain_s),
               tuple(build.columns[i].dtype.name for i in plain_b),
               tuple(plain_s), tuple(plain_b), self.build_right,
               direct is not None and direct[4])
        fn = TpuHashJoinBase._SPEC_JIT.get(key)
        if fn is False:
            return None
        if fn is None:
            key_dts = tuple(c.dtype for c in skey_cols)
            tbl = direct[4] if direct is not None else 0

            def _core(bws, dparams, key_arrays, num_rows, perm,
                      sdatas, svalids, bdatas, bvalids):
                kcols = [Column(dt, d, v)
                         for dt, (d, v) in zip(key_dts, key_arrays)]
                cap = key_arrays[0][0].shape[0]
                in_range = jnp.arange(cap) < num_rows
                if dparams is not None:
                    wmin, wmax, hist, excl = dparams
                    w = canon.value_words(kcols[0], num_rows)[0]
                    idx = jnp.clip((w - wmin).astype(jnp.int32), 0,
                                   tbl - 1)
                    hit = (w >= wmin) & (w <= wmax) & \
                        kcols[0].validity & in_range
                    counts = jnp.where(hit, jnp.take(hist, idx), 0)
                    lo = jnp.take(excl, idx)
                else:
                    swords = canon.batch_key_words(kcols, num_rows)
                    bt2 = join_k.BuildTable(list(bws), None, None)
                    jc = join_k.probe_counts(bt2, swords, num_rows)
                    counts, lo = jc.counts, jc.lo
                eff = jnp.where(in_range, counts, 0)
                fit = (jnp.max(eff) <= 1).astype(jnp.int32)
                p_idx, cnt = bk.compact_indices(eff > 0, num_rows)
                live = jnp.arange(cap) < cnt
                b_pos = jnp.clip(jnp.take(lo, p_idx, mode="clip"), 0,
                                 perm.shape[0] - 1)
                b_idx = jnp.take(perm, b_pos)
                souts = [(jnp.take(d, p_idx, axis=0, mode="clip"),
                          jnp.take(v, p_idx, axis=0, mode="clip") & live)
                         for d, v in zip(sdatas, svalids)]
                bouts = [(jnp.take(d, b_idx, axis=0, mode="clip"),
                          jnp.take(v, b_idx, axis=0, mode="clip") & live)
                         for d, v in zip(bdatas, bvalids)]
                return souts, bouts, p_idx, b_idx, live, \
                    cnt.astype(jnp.int64), fit
            from ..obs import costplane as _costplane
            fn = _costplane.wrap_capture("join_spec_probe",
                                         jax.jit(_core))
            if len(TpuHashJoinBase._SPEC_JIT) < 4096:
                TpuHashJoinBase._SPEC_JIT[key] = fn
        key_arrays = tuple((c.data, c.validity) for c in skey_cols)
        dparams = tuple(direct[:4]) if direct is not None else None
        from ..compile import aot as _aot
        from ..obs import costplane as _costplane
        _aot.note_demand("join_spec_probe", sb.capacity,
                         _costplane.rows_if_resolved(sb))
        try:
            souts, bouts, p_idx, b_idx, live, cnt, fit = fn(
                tuple(bt.sorted_words), dparams, key_arrays, sb.rows_dev,
                bt.perm,
                tuple(sb.columns[i].data for i in plain_s),
                tuple(sb.columns[i].validity for i in plain_s),
                tuple(build.columns[i].data for i in plain_b),
                tuple(build.columns[i].validity for i in plain_b))
        except Exception:  # noqa: BLE001 - fall back, but loudly
            import logging
            logging.getLogger("spark_rapids_tpu.exec.join").warning(
                "speculative join failed; falling back", exc_info=True)
            TpuHashJoinBase._SPEC_JIT[key] = False
            return None
        s_it = iter(souts)
        scols = []
        for c in sb.columns:
            if type(c) is Column:
                d, v = next(s_it)
                scols.append(Column(c.dtype, d, v))
            else:
                scols.append(c.gather(p_idx, live=live))
        b_it = iter(bouts)
        bcols = []
        for c in build.columns:
            if type(c) is Column:
                d, v = next(b_it)
                bcols.append(Column(c.dtype, d, v))
            else:
                bcols.append(c.gather(b_idx, live=live))
        out = self._assemble(scols, bcols, LazyCount(cnt))
        # the probe ran on possibly-speculative input: compose its fits
        # with ours so one failed assumption anywhere redoes the chain
        in_spec = getattr(sb, "_speculative", None)
        fits = (list(in_spec.fits) if in_spec is not None else []) \
            + [LazyCount(fit)]

        def _redo(sb=sb, skey_cols=skey_cols):
            from ..columnar import pending
            from ..obs import profile
            from ..obs.registry import superstage_event
            superstage_event("spec_redo")
            with profile.dispatch(profile.SITE_SPEC_REDO):
                fixed = resolve_speculative(sb)
                kc = skey_cols if fixed is sb else \
                    [ec.eval_as_column(e, fixed) for e in stream_keys]
                with timed(self.metrics[JOIN_TIME], self):
                    pa = self._probe_phase(fixed, kc, bt, str_words,
                                           None, direct)
                pending.flush()
                if pa is None:
                    with timed(self.metrics[JOIN_TIME], self):
                        return self._join_batch(fixed, kc, build, bt,
                                                str_words, None)
                outs = [o for o in
                        self._expand_phases(fixed, build, bt, *pa)
                        if o is not None]
                if not outs:
                    return ColumnarBatch.empty(self.output_schema)
                return outs[0] if len(outs) == 1 \
                    else concat_batches(outs)

        out._speculative = SpeculativeResult(fits, _redo)
        return out

    def _expand_phases(self, sb, build, bt, jt, outer_stream, lo, counts,
                       eff, total_lazy):
        """Bounded incremental gather (JoinGatherer.scala:1 role).

        A skewed key can explode one (stream batch, build) pair far past
        device memory; when the total exceeds the chunk budget, expand in
        probe-row ranges — splitting even a single probe row's matches
        across chunks by advancing its ``lo`` offset — so no single
        output allocation exceeds the budget.  Yields chunks lazily so
        downstream can consume (or spill) chunk k before chunk k+1's
        gather allocates."""
        from ..config import get_active, JOIN_GATHER_CHUNK_ROWS
        total = int(total_lazy)
        limit = int(get_active().get(JOIN_GATHER_CHUNK_ROWS))
        if total <= limit or jt in ("semi", "anti"):
            with timed(self.metrics[JOIN_TIME], self):
                out = self._expand_phase(sb, build, bt, jt, outer_stream,
                                         lo, counts, eff, total)
            if out is not None:
                yield out
            return
        from ..analysis import residency  # lazy: avoids import cycle
        with timed(self.metrics[JOIN_TIME], self):
            with residency.declared_transfer(site="join_verify"):
                eff_np = np.asarray(eff).astype(np.int64)
                lo_np = np.asarray(lo).astype(np.int32)
        nrows = eff_np.shape[0]
        p0 = 0
        off0 = 0          # matches of row p0 already emitted
        while p0 < nrows:
            budget = limit
            chunk_eff = np.zeros(nrows, np.int64)
            chunk_lo = lo_np.copy()
            p, off = p0, off0
            chunk_total = 0
            while p < nrows and budget > 0:
                avail = int(eff_np[p]) - off
                if avail <= 0:
                    p += 1
                    off = 0
                    continue
                take = min(avail, budget)
                chunk_eff[p] = take
                if off:
                    chunk_lo[p] = lo_np[p] + off
                chunk_total += take
                budget -= take
                if take == avail:
                    p += 1
                    off = 0
                else:
                    off += take
            if chunk_total == 0:
                break
            with timed(self.metrics[JOIN_TIME], self):
                out = self._expand_phase(
                    sb, build, bt, jt, outer_stream,
                    jnp.asarray(chunk_lo), counts,
                    jnp.asarray(chunk_eff.astype(np.int32)), chunk_total)
            if out is not None:
                yield out
            p0, off0 = p, off

    def _expand_phase(self, sb, build, bt, jt, outer_stream, lo, counts,
                      eff, total_lazy) -> Optional[ColumnarBatch]:
        """Phase B: expansion + all output gathers as ONE jitted program
        with a host-known output capacity."""
        import jax
        total = int(total_lazy)
        if total == 0:
            return ColumnarBatch.empty(self.output_schema)
        out_cap = bucket_capacity(total)
        if jt in ("semi", "anti"):
            out = sb.slice_by_mask(eff > 0, total) if hasattr(
                sb, "slice_by_mask") else None
            if out is None:
                from ..kernels import basic as bk
                idx, _ = bk.compact_indices(eff > 0, sb.rows_dev)
                out = sb.gather(idx[:out_cap] if out_cap <= sb.capacity
                                else jnp.pad(idx, (0, out_cap -
                                                   sb.capacity))[:out_cap],
                                total)
                mask = jnp.arange(out.capacity) < total
                out = ColumnarBatch(
                    self.output_schema,
                    [c.mask_validity(mask) for c in out.columns], total)
            return out
        if not all(type(c) is Column for c in sb.columns) or \
                not all(type(c) is Column for c in build.columns):
            return self._expand_eager(sb, build, bt, outer_stream, lo,
                                      counts, eff, total)
        key = ("expand", out_cap, outer_stream,
               tuple(f.dtype.name for f in sb.schema),
               tuple(f.dtype.name for f in build.schema),
               sb.capacity, build.capacity)
        fn = TpuHashJoinBase._EXPAND_JIT.get(key)
        if fn is None:
            def _core(lo, counts, eff, perm, sdatas, svalids, bdatas,
                      bvalids):
                p_idx, b_idx, live, _ = join_k.expand_matches(
                    lo, eff, perm, out_cap)
                souts = [(jnp.take(d, p_idx, axis=0, mode="clip"),
                          jnp.take(v, p_idx, axis=0, mode="clip") & live)
                         for d, v in zip(sdatas, svalids)]
                bvalid_mask = live
                if outer_stream:
                    matched = jnp.take(counts > 0, jnp.clip(
                        p_idx, 0, counts.shape[0] - 1))
                    bvalid_mask = live & matched
                bouts = [(jnp.take(d, b_idx, axis=0, mode="clip"),
                          jnp.take(v, b_idx, axis=0, mode="clip") &
                          bvalid_mask)
                         for d, v in zip(bdatas, bvalids)]
                return souts, bouts
            fn = jax.jit(_core)
            if len(TpuHashJoinBase._EXPAND_JIT) < 4096:
                TpuHashJoinBase._EXPAND_JIT[key] = fn
        souts, bouts = fn(
            lo, counts, eff, bt.perm,
            tuple(c.data for c in sb.columns),
            tuple(c.validity for c in sb.columns),
            tuple(c.data for c in build.columns),
            tuple(c.validity for c in build.columns))
        scols = [Column(c.dtype, d, v)
                 for c, (d, v) in zip(sb.columns, souts)]
        bcols = [Column(c.dtype, d, v)
                 for c, (d, v) in zip(build.columns, bouts)]
        return self._assemble(scols, bcols, total)

    def _expand_eager(self, sb, build, bt, outer_stream, lo, counts, eff,
                      total):
        """Non-plain columns (strings/nested): the original eager
        expansion."""
        out_cap = bucket_capacity(total)
        p_idx, b_idx, live, _ = join_k.expand_matches(lo, eff, bt.perm,
                                                      out_cap)
        stream_out = sb.gather(p_idx, total)
        build_out = build.gather(b_idx, total)
        if outer_stream:
            row_matched = jnp.take(counts > 0,
                                   jnp.clip(p_idx, 0, sb.capacity - 1))
            build_out = ColumnarBatch(
                build_out.schema,
                [c.mask_validity(row_matched)
                 for c in build_out.columns], total)
        live_mask = jnp.arange(out_cap) < total
        scols = [c.mask_validity(live_mask) for c in stream_out.columns]
        bcols = [c.mask_validity(live_mask) for c in build_out.columns]
        return self._assemble(scols, bcols, total)

    # ------------------------------------------------------------------
    def _join_batch(self, sb: ColumnarBatch, skey_cols, build, bt,
                    str_words, build_matched) -> Optional[ColumnarBatch]:
        lg = self.logical
        jt = lg.join_type
        swords = _key_words(skey_cols, sb.num_rows, str_words)
        jc = join_k.probe_counts(bt, swords, sb.num_rows)

        if lg.condition is not None:
            # residual restricts which PAIRS match; outer/semi/anti row
            # semantics are decided on the surviving pairs (a plain
            # post-filter would wrongly drop null-extended outer rows)
            return self._join_batch_residual(sb, jc, build, bt,
                                             build_matched)

        if jt in ("semi", "anti"):
            from ..kernels import basic as bk
            in_range = jnp.arange(sb.capacity) < sb.num_rows
            keep = (jc.counts > 0) if jt == "semi" else \
                ((jc.counts == 0) & in_range)
            idx, cnt = bk.compact_indices(keep, sb.num_rows)
            n = _host_int(cnt)
            out = sb.gather(idx, n)
            mask = jnp.arange(out.capacity) < n
            return ColumnarBatch(
                self.output_schema,
                [c.mask_validity(mask) for c in out.columns], n)

        outer_stream = ((jt == "left" and self.build_right) or
                        (jt == "right" and not self.build_right) or
                        jt == "full")
        counts = jc.counts
        if outer_stream:
            in_range = jnp.arange(sb.capacity) < sb.num_rows
            unmatched = (counts == 0) & in_range
            counts = jnp.where(unmatched, 1, counts)

        total = join_k.total_matches(counts)
        if total == 0:
            return ColumnarBatch.empty(self.output_schema)
        out_cap = bucket_capacity(total)
        p_idx, b_idx, live, _ = join_k.expand_matches(
            jc.lo, counts, bt.perm, out_cap)

        stream_out = sb.gather(p_idx, total)
        build_out = build.gather(b_idx, total)
        if outer_stream:
            # rows that came from the unmatched path carry null build side
            row_matched = jnp.take(jc.counts > 0, jnp.clip(p_idx, 0,
                                                           sb.capacity - 1))
            build_out = ColumnarBatch(
                build_out.schema,
                [c.mask_validity(row_matched) for c in build_out.columns],
                total)
        if build_matched is not None:
            from ..analysis import residency  # lazy: avoids import cycle
            with residency.declared_transfer(site="join_verify"):
                matched_idx = np.asarray(jnp.where(
                    live & jnp.take(jc.counts > 0,
                                    jnp.clip(p_idx, 0, sb.capacity - 1)),
                    b_idx, 0))
                flags = np.zeros(build.capacity, dtype=bool)
                lv = np.asarray(live)
                mi = np.asarray(matched_idx)
                ok = np.asarray(jnp.take(jc.counts > 0,
                                         jnp.clip(p_idx, 0,
                                                  sb.capacity - 1)))
            flags[mi[lv & ok]] = True
            build_matched |= flags

        live_mask = jnp.arange(out_cap) < total
        scols = [c.mask_validity(live_mask) for c in stream_out.columns]
        bcols = [c.mask_validity(live_mask) for c in build_out.columns]
        return self._assemble(scols, bcols, total)

    def _join_batch_residual(self, sb, jc, build, bt,
                             build_matched) -> Optional[ColumnarBatch]:
        """Join with a residual (non-equi) condition: expand the INNER
        pairs, evaluate the condition per pair, then derive the join
        type's row set from the surviving pairs."""
        from ..kernels import basic as bk
        lg = self.logical
        jt = lg.join_type
        lschema = self.children[0].output_schema
        rschema = self.children[1].output_schema
        pair_schema = Schema(
            [Field(f.name, f.dtype, True) for f in lschema] +
            [Field(f.name, f.dtype, True) for f in rschema])

        total = int(join_k.total_matches(jc.counts))
        out_cap = bucket_capacity(max(total, 1))
        p_idx, b_idx, _live, _ = join_k.expand_matches(
            jc.lo, jc.counts, bt.perm, out_cap)
        stream_out = sb.gather(p_idx, total)
        build_out = build.gather(b_idx, total)
        live_mask = jnp.arange(out_cap) < total
        scols = [c.mask_validity(live_mask) for c in stream_out.columns]
        bcols = [c.mask_validity(live_mask) for c in build_out.columns]
        if self.build_right:
            pair_cols = scols + bcols
        else:
            pair_cols = bcols + scols
        pairs = ColumnarBatch(pair_schema, pair_cols, total)
        pred = ec.eval_as_column(lg.condition.bind(pair_schema), pairs)
        keep = pred.data.astype(bool) & pred.validity & live_mask

        # per-stream-row "has a surviving pair"
        surv = jnp.zeros(sb.capacity, dtype=bool).at[
            jnp.where(keep, p_idx, 0)].max(keep)
        in_range = jnp.arange(sb.capacity) < sb.num_rows

        if jt in ("semi", "anti"):
            sel = surv if jt == "semi" else (~surv & in_range)
            idx, cnt = bk.compact_indices(sel, sb.num_rows)
            n = _host_int(cnt)
            out = sb.gather(idx, n)
            mask = jnp.arange(out.capacity) < n
            return ColumnarBatch(
                self.output_schema,
                [c.mask_validity(mask) for c in out.columns], n)

        if build_matched is not None and total:
            from ..analysis import residency  # lazy: avoids import cycle
            with residency.declared_transfer(site="join_verify"):
                midx = np.asarray(jnp.where(keep, b_idx, 0))
                keep_np = np.asarray(keep)
            flags = np.zeros(build.capacity, dtype=bool)
            flags[midx[keep_np]] = True
            build_matched |= flags

        # surviving pairs
        pidx2, pcnt = bk.compact_indices(keep, total)
        n_pairs = _host_int(pcnt)
        sp = stream_out.gather(pidx2, n_pairs)
        bp = build_out.gather(pidx2, n_pairs)
        pmask = jnp.arange(sp.capacity) < n_pairs
        sp_cols = [c.mask_validity(pmask) for c in sp.columns]
        bp_cols = [c.mask_validity(pmask) for c in bp.columns]
        parts = []
        if n_pairs:
            parts.append(self._assemble(sp_cols, bp_cols, n_pairs))

        outer_stream = ((jt == "left" and self.build_right) or
                        (jt == "right" and not self.build_right) or
                        jt == "full")
        if outer_stream:
            un = ~surv & in_range
            uidx, ucnt = bk.compact_indices(un, sb.num_rows)
            n_un = _host_int(ucnt)
            if n_un:
                su = sb.gather(uidx, n_un)
                umask = jnp.arange(su.capacity) < n_un
                su_cols = [c.mask_validity(umask) for c in su.columns]
                nulls = [_null_column(f.dtype, su.capacity)
                         for f in build.schema]
                parts.append(self._assemble(su_cols, nulls, n_un))
        if not parts:
            return ColumnarBatch.empty(self.output_schema)
        if len(parts) == 1:
            return parts[0]
        return concat_batches(parts)

    def _assemble(self, stream_cols, build_cols, total) -> ColumnarBatch:
        if self.build_right:
            cols = stream_cols + build_cols
        else:
            cols = build_cols + stream_cols
        return ColumnarBatch(self.output_schema, cols, total)

    def _unmatched_build_rows(self, build, build_matched,
                              stream_schema) -> Optional[ColumnarBatch]:
        from ..kernels import basic as bk
        in_range = np.arange(build.capacity) < build.num_rows
        keep = jnp.asarray(~build_matched & in_range)
        idx, cnt = bk.compact_indices(keep, build.num_rows)
        n = _host_int(cnt)
        if n == 0:
            return None
        b_out = build.gather(idx, n)
        mask = jnp.arange(b_out.capacity) < n
        bcols = [c.mask_validity(mask) for c in b_out.columns]
        scols = [_null_column(f.dtype, b_out.capacity)
                 for f in stream_schema]
        return self._assemble(scols, bcols, n)

    def execute(self):
        lparts = self.children[0].execute()
        rparts = self.children[1].execute()
        assert len(lparts) == len(rparts), \
            f"join partition mismatch {len(lparts)} vs {len(rparts)}"
        return [self._run_partition(lp, rp)
                for lp, rp in zip(lparts, rparts)]


class TpuShuffledHashJoin(TpuHashJoinBase):
    """Both sides hash-partitioned by key (planner inserts exchanges).

    Reference: GpuShuffledHashJoinBase.scala:28."""


class TpuBroadcastHashJoin(TpuHashJoinBase):
    """Build side broadcast (single concat batch replicated to every

    stream partition).  Reference: GpuBroadcastHashJoinExec."""

    def execute(self):
        # broadcast side: materialize once, replicate per stream partition
        if self.build_right:
            stream_parts = self.children[0].execute()
            bparts = self.children[1].execute()
            build_batches = [b for p in bparts for b in p]
            return [self._run_partition(sp, iter(list(build_batches)))
                    for sp in stream_parts]
        else:
            stream_parts = self.children[1].execute()
            bparts = self.children[0].execute()
            build_batches = [b for p in bparts for b in p]
            return [self._run_partition(iter(list(build_batches)), sp)
                    for sp in stream_parts]


class TpuNestedLoopJoin(TpuExec):
    """Cartesian / nested-loop join for cross joins and non-equi conditions.

    Reference: GpuBroadcastNestedLoopJoinExec, GpuCartesianProductExec."""

    def __init__(self, logical, left: PhysicalPlan, right: PhysicalPlan):
        super().__init__(left, right)
        self.logical = logical

    @property
    def output_schema(self):
        return self.logical.schema

    def execute(self):
        from ..service.cancellation import cancel_checkpoint
        lparts = self.children[0].execute()
        rparts = self.children[1].execute()
        # the whole right side materializes before the first output
        # batch: checkpoint per pulled batch so service cancellation
        # can unwind the drain
        right_batches = []
        for p in rparts:
            for b in p:
                cancel_checkpoint()
                right_batches.append(b)
        if self.logical.join_type in ("right", "full"):
            # unmatched-right emission must observe EVERY left row, so
            # the left side collapses to one partition
            def all_left():
                for p in lparts:
                    yield from p
            return [self._run(all_left(), right_batches)]
        return [self._run(lp, right_batches) for lp in lparts]

    def _run(self, left_iter, right_batches):
        """Pair-level semantics for every join type: the condition
        restricts MATCHES; outer rows null-extend, semi/anti select left
        rows by surviving-pair existence (a plain post-filter would
        silently degrade outer/semi/anti to inner)."""
        from ..kernels import basic as bk
        jt = self.logical.join_type
        lschema = self.children[0].output_schema
        rschema = self.children[1].output_schema
        pair_schema = Schema(
            [Field(f.name, f.dtype, True) for f in lschema] +
            [Field(f.name, f.dtype, True) for f in rschema])
        rb = concat_batches(right_batches) if right_batches else \
            ColumnarBatch.empty(rschema)
        n_r = rb.num_rows
        right_matched = np.zeros(rb.capacity, dtype=bool) \
            if jt in ("right", "full") else None

        def select_left(lb, sel, n_hint):
            idx, cnt = bk.compact_indices(sel, n_hint)
            n = _host_int(cnt)
            out = lb.gather(idx, n)
            m = jnp.arange(out.capacity) < n
            return ColumnarBatch(self.output_schema,
                                 [c.mask_validity(m) for c in out.columns],
                                 n)

        from ..service.cancellation import cancel_checkpoint
        for lb in left_iter:
            cancel_checkpoint()
            n_l = lb.num_rows
            total = n_l * n_r
            if total == 0:
                if n_l and jt in ("left", "full", "anti"):
                    # empty right side: anti keeps everything, outer
                    # null-extends everything
                    in_range = jnp.arange(lb.capacity) < n_l
                    if jt == "anti":
                        yield select_left(lb, in_range, n_l)
                    else:
                        nulls = [_null_column(f.dtype, lb.capacity)
                                 for f in rschema]
                        cols = [c.mask_validity(in_range)
                                for c in lb.columns] + nulls
                        yield ColumnarBatch(self.output_schema, cols, n_l)
                continue
            out_cap = bucket_capacity(total)
            t = jnp.arange(out_cap)
            li = (t // max(n_r, 1)).astype(jnp.int32)
            ri = (t % max(n_r, 1)).astype(jnp.int32)
            lout = lb.gather(li, total)
            rout = rb.gather(ri, total)
            live = t < total
            pair_cols = ([c.mask_validity(live) for c in lout.columns] +
                         [c.mask_validity(live) for c in rout.columns])
            pairs = ColumnarBatch(pair_schema, pair_cols, total)
            if self.logical.condition is not None:
                cond = self.logical.condition.bind(pair_schema)
                pred = ec.eval_as_column(cond, pairs)
                keep = pred.data.astype(bool) & pred.validity & live
            else:
                keep = live

            if right_matched is not None:
                hit = jnp.zeros(rb.capacity, dtype=bool).at[
                    jnp.where(keep, ri, 0)].max(keep)
                from ..analysis import residency  # lazy import
                with residency.declared_transfer(site="join_verify"):
                    right_matched |= np.asarray(hit)

            if jt in ("semi", "anti"):
                surv = jnp.zeros(lb.capacity, dtype=bool).at[
                    jnp.where(keep, li, 0)].max(keep)
                in_range = jnp.arange(lb.capacity) < n_l
                sel = surv if jt == "semi" else (~surv & in_range)
                out = select_left(lb, sel, n_l)
                self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
                yield out
                continue

            idx, cnt = bk.compact_indices(keep, total)
            n_pairs = _host_int(cnt)
            parts = []
            if n_pairs:
                g = pairs.gather(idx, n_pairs)
                m = jnp.arange(g.capacity) < n_pairs
                parts.append(ColumnarBatch(
                    self.output_schema,
                    [c.mask_validity(m) for c in g.columns], n_pairs))
            if jt in ("left", "full"):
                surv = jnp.zeros(lb.capacity, dtype=bool).at[
                    jnp.where(keep, li, 0)].max(keep)
                un = ~surv & (jnp.arange(lb.capacity) < n_l)
                uidx, ucnt = bk.compact_indices(un, n_l)
                n_un = _host_int(ucnt)
                if n_un:
                    lu = lb.gather(uidx, n_un)
                    um = jnp.arange(lu.capacity) < n_un
                    nulls = [_null_column(f.dtype, lu.capacity)
                             for f in rschema]
                    parts.append(ColumnarBatch(
                        self.output_schema,
                        [c.mask_validity(um) for c in lu.columns] + nulls,
                        n_un))
            for out in parts:
                self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
                yield out

        if right_matched is not None:
            un = jnp.asarray(~right_matched) & \
                (jnp.arange(rb.capacity) < n_r)
            uidx, ucnt = bk.compact_indices(un, n_r)
            n_un = _host_int(ucnt)
            if n_un:
                ru = rb.gather(uidx, n_un)
                um = jnp.arange(ru.capacity) < n_un
                nulls = [_null_column(f.dtype, ru.capacity)
                         for f in lschema]
                out = ColumnarBatch(
                    self.output_schema,
                    nulls + [c.mask_validity(um) for c in ru.columns],
                    n_un)
                self.metrics[NUM_OUTPUT_ROWS] += out.rows_lazy
                yield out


# ---------------------------------------------------------------------------
# program audit registration (analysis/program_audit.py): the probe and
# speculative-probe programs build per (shape, dtype) signature inside
# _run_partition, so each provider drives a tiny CPU build+probe and
# pulls the freshly cached program for abstract tracing.
# ---------------------------------------------------------------------------

def _audit_specs():
    import jax
    from types import SimpleNamespace
    from ..analysis.program_audit import AuditSpec

    def _fixture():
        cap = 16
        sschema = Schema([Field("sk", T.INT64, True)])
        bschema = Schema([Field("bk", T.INT64, True)])
        j = object.__new__(TpuHashJoinBase)
        j.logical = SimpleNamespace(
            join_type="inner", condition=None,
            schema=Schema(list(sschema.fields) + list(bschema.fields)))
        j.build_right = True
        bcol = Column(T.INT64, jnp.arange(cap, dtype=jnp.int64),
                      jnp.ones((cap,), bool))
        build = ColumnarBatch(bschema, [bcol], cap)
        bt = join_k.build(_key_words([bcol], build.rows_dev, [None]))
        scol = Column(T.INT64, jnp.arange(cap, dtype=jnp.int64),
                      jnp.ones((cap,), bool))
        sb = ColumnarBatch(sschema, [scol], cap)
        return j, sb, scol, bt, build

    def _sds_args(sb, bt):
        import numpy as np
        sws = tuple(jax.ShapeDtypeStruct(w.shape, w.dtype)
                    for w in bt.sorted_words)
        ka = ((jax.ShapeDtypeStruct((sb.capacity,), np.int64),
               jax.ShapeDtypeStruct((sb.capacity,), np.bool_)),)
        return sws, ka, jax.ShapeDtypeStruct((), np.int32)

    def _probe_build():
        j, sb, scol, bt, _build_b = _fixture()
        out = j._probe_phase(sb, [scol], bt, [None], None, None)
        assert out is not None, "probe phase fell back"
        key = ("probe", "inner", (T.INT64.name,), sb.capacity,
               bt.capacity, len(bt.sorted_words), True, False)
        fn = TpuHashJoinBase._PROBE_JIT[key]
        sws, ka, nr = _sds_args(sb, bt)
        return fn, (sws, None, ka, nr), {}

    def _spec_build():
        import numpy as np
        j, sb, scol, bt, build = _fixture()
        out = j._spec_join_batch(sb, [scol], bt, build, None,
                                 [ec.BoundReference(0, T.INT64)],
                                 [None])
        assert out is not None, "speculative join fell back"
        key = ("spec", (T.INT64.name,), sb.capacity, bt.capacity,
               len(bt.sorted_words), (T.INT64.name,), (T.INT64.name,),
               (0,), (0,), True, False)
        fn = TpuHashJoinBase._SPEC_JIT[key]
        sws, ka, nr = _sds_args(sb, bt)
        perm = jax.ShapeDtypeStruct(bt.perm.shape, bt.perm.dtype)
        d = jax.ShapeDtypeStruct((sb.capacity,), np.int64)
        v = jax.ShapeDtypeStruct((sb.capacity,), np.bool_)
        args = (sws, None, ka, nr, perm, (d,), (v,), (d,), (v,))
        return fn, args, {}

    return [
        AuditSpec("join_probe", "join_probe", _probe_build,
                  notes="phase-A probe counts, inner join, int64 key",
                  budgets={"gather": 16, "scatter": 2, "transpose": 2,
                           "sort": 2}),
        AuditSpec("join_spec_probe", "join_spec_probe", _spec_build,
                  notes="speculative unique-match inner join program",
                  budgets={"gather": 28, "scatter": 2, "transpose": 2,
                           "sort": 2}),
    ]
