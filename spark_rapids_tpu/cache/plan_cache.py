"""Fingerprint-keyed plan cache — repeat shapes skip the expensive
planner tail.

Production traffic is repeat-heavy: the same dashboard/report shapes
arrive all day with only their literals changing.  PR 15 gave every
shape a stable identity (``obs/fingerprint.py``); this module consumes
it.  Entries are keyed by the literal-normalized **logical** shape
digest — computable before any planning work — and scoped to the conf
fingerprint they were planned under.

What a hit actually replays — the certificate contract
------------------------------------------------------
A physical plan OBJECT cannot be reused across queries: its nodes
embed the query's literal values, accumulate runtime metrics, and
shuffle exchanges carry materialization state and locks.  The cache
therefore stores a shape's **analysis certificates** — the verifier
verdict (implicit: only verified plans are stored), the physical
``plan_fingerprint``, the PV-FLUSH prediction's contributions, the
planner's fallback and parallelism decisions, and the cold planner
latency.  A hit re-runs only the cheap structural pipeline
(prune → tag → CBO → convert → collapse → carve) on the INCOMING
logical plan — fresh literals are correct by construction — while the
two invariant-verifier passes (PV defaults + PV-STAGE) and the
flush-budget walk are skipped, and the stored ``FlushPrediction`` is
re-attached to the rebuilt tree so the PV-FLUSH exactness contract
holds unchanged on the cached path.

Safety net: the rebuilt plan's fingerprint must equal the stored one;
any divergence drops the entry and falls back to the full cold path
(counted as ``validation_miss``, never trusted).

Invalidation: a plan-affecting conf change under a cached shape drops
the entry (``invalidated``) and the cold path re-runs the verifier
from scratch.  Capacity: a bounded LRU (``maxEntries``), oldest-use
evicted first.

Pure host arithmetic; lock discipline: dict bookkeeping under
``_LOCK``, planning always outside it (LOCK001).
"""
from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..obs.fingerprint import (conf_fingerprint, logical_shape,
                               plan_fingerprint)
from ..obs.registry import PLAN_CACHE_EVENTS

_LOCK = threading.Lock()
_ENTRIES: "OrderedDict[str, Dict]" = OrderedDict()
_ENABLED = True
_MAX_ENTRIES = 256
_HITS = 0
_MISSES = 0
_VALIDATION_MISSES = 0
_INVALIDATED = 0
_EVICTED = 0


def shape_key(logical) -> str:
    """Conf-independent cache key: digest of the literal-normalized
    logical shape text (``WHERE x > 5`` and ``WHERE x > 7`` share a
    key; any structural change moves it).  Conf scoping lives in the
    entry's stored ``conf_fp``, so a conf change is an explicit
    invalidation event rather than a silent key miss."""
    return hashlib.sha256(
        logical_shape(logical).encode()).hexdigest()[:16]


def _limits(conf) -> Tuple[bool, int]:
    from ..config import CACHE_PLAN_ENABLED, CACHE_PLAN_MAX_ENTRIES
    return (_ENABLED and bool(conf.get(CACHE_PLAN_ENABLED)),
            max(1, int(conf.get(CACHE_PLAN_MAX_ENTRIES))))


def plan_with_cache(logical, conf):
    """Plan ``logical`` under ``conf`` through the cache.  Returns
    ``(phys, planner)`` — the planner for its ``fallbacks`` /
    ``parallelism_warnings``, exactly like a direct ``Planner`` use
    (the structural pipeline runs on BOTH paths, so both are always
    populated for the actual incoming plan).

    Stamps on the returned physical root:

    - ``_plan_cache_flush_pred``: the :class:`FlushPrediction` to
      replay — stored contributions re-attached on a hit, freshly
      computed once on a miss; ``api/session.py`` prefers this over
      re-running ``predict_flushes``.
    - ``_plan_cache_status``: ``(status, planner_path_ms)`` for the
      event log and report header (absent when the cache is off).
    """
    global _HITS, _MISSES, _VALIDATION_MISSES, _INVALIDATED, _EVICTED
    from ..analysis.flush_budget import FlushPrediction, predict_flushes
    from ..plan.overrides import Planner
    enabled, max_entries = _limits(conf)
    if not enabled:
        planner = Planner(conf)
        return planner.plan(logical), planner
    key = shape_key(logical)
    cfp = conf_fingerprint(conf)
    invalidated_now = False
    with _LOCK:
        entry = _ENTRIES.get(key)
        if entry is not None and entry["conf_fp"] != cfp:
            # a plan-affecting conf moved under this shape: the stored
            # certificates no longer apply — drop them; the cold path
            # below re-runs the invariant verifier from scratch
            del _ENTRIES[key]
            _INVALIDATED += 1
            invalidated_now = True
            entry = None
        snap = dict(entry) if entry is not None else None
    if invalidated_now:
        PLAN_CACHE_EVENTS.labels(event="invalidated").inc()
    t0 = time.perf_counter()
    if snap is not None:
        planner = Planner(conf)
        phys = planner.plan(logical, skip_verify=True)
        if plan_fingerprint(phys, conf) == snap["plan_fingerprint"]:
            ms = (time.perf_counter() - t0) * 1000.0
            phys._plan_cache_flush_pred = FlushPrediction(
                phys, snap["contributions"])
            phys._plan_cache_status = ("hit", ms)
            with _LOCK:
                live = _ENTRIES.get(key)
                if live is not None:
                    live["hits"] += 1
                    live["warm_ms"] = ms
                    _ENTRIES.move_to_end(key)
                _HITS += 1
            PLAN_CACHE_EVENTS.labels(event="hit").inc()
            return phys, planner
        # the rebuilt plan diverged from its certificate — never trust
        # it: drop the entry and take the fully verified cold path
        with _LOCK:
            _ENTRIES.pop(key, None)
            _VALIDATION_MISSES += 1
        PLAN_CACHE_EVENTS.labels(event="validation_miss").inc()
        t0 = time.perf_counter()
    planner = Planner(conf)
    phys = planner.plan(logical)
    pred: Optional[FlushPrediction] = None
    try:
        pred = predict_flushes(phys, conf=conf)
    except Exception:  # noqa: BLE001 - prediction is observability
        pred = None
    ms = (time.perf_counter() - t0) * 1000.0
    phys._plan_cache_status = ("miss", ms)
    evicted = 0
    if pred is not None:
        # only shapes with an exact flush certificate are cacheable:
        # a hit MUST replay a prediction, so a shape the predictor
        # cannot cover is re-planned cold every time
        phys._plan_cache_flush_pred = pred
        entry = {
            "conf_fp": cfp,
            "plan_fingerprint": plan_fingerprint(phys, conf),
            "contributions": list(pred.contributions),
            "fallbacks": list(planner.fallbacks),
            "parallelism_warnings": list(planner.parallelism_warnings),
            "cold_ms": ms,
            "warm_ms": None,
            "hits": 0,
        }
        with _LOCK:
            _ENTRIES[key] = entry
            _ENTRIES.move_to_end(key)
            _MISSES += 1
            while len(_ENTRIES) > max_entries:
                _ENTRIES.popitem(last=False)
                _EVICTED += 1
                evicted += 1
    else:
        with _LOCK:
            _MISSES += 1
    PLAN_CACHE_EVENTS.labels(event="miss").inc()
    for _ in range(evicted):
        PLAN_CACHE_EVENTS.labels(event="evicted").inc()
    return phys, planner


def entry_for(logical, conf) -> Optional[Dict]:
    """Read-only peek for the admission scheduler: the certificate
    record cached for this logical shape under this conf, or None (no
    entry, or the conf fingerprint moved).  Never mutates LRU order or
    counters — admission-time prediction must not perturb the cache."""
    enabled, _ = _limits(conf)
    if not enabled:
        return None
    key = shape_key(logical)
    cfp = conf_fingerprint(conf)
    with _LOCK:
        e = _ENTRIES.get(key)
        if e is None or e["conf_fp"] != cfp:
            return None
        return dict(e)


def entry_count() -> int:
    """Resident shapes — the ``tpu_plan_cache_entries`` gauge."""
    with _LOCK:
        return len(_ENTRIES)


def top_entries(n: int = 5) -> List[Dict]:
    """Most-hit cached shapes, for the dashboard panel and report."""
    with _LOCK:
        snap = [(k, dict(e)) for k, e in _ENTRIES.items()]
    snap.sort(key=lambda kv: kv[1]["hits"], reverse=True)
    return [{
        "digest": k,
        "plan_fingerprint": e["plan_fingerprint"],
        "hits": e["hits"],
        "cold_ms": round(e["cold_ms"], 3),
        "warm_ms": (round(e["warm_ms"], 3)
                    if e["warm_ms"] is not None else None),
    } for k, e in snap[:max(0, n)]]


def stats_section() -> Dict:
    """The ``plan_cache`` section of ``Service.stats().snapshot()``."""
    with _LOCK:
        entries = len(_ENTRIES)
        hits, misses = _HITS, _MISSES
        vmiss, inval, evict = _VALIDATION_MISSES, _INVALIDATED, _EVICTED
    lookups = hits + misses
    return {
        "enabled": _ENABLED,
        "entries": entries,
        "max_entries": _MAX_ENTRIES,
        "hits": hits,
        "misses": misses,
        "validation_misses": vmiss,
        "invalidated": inval,
        "evicted": evict,
        "hit_pct": round(hits / lookups * 100.0, 1) if lookups else 0.0,
        "top": top_entries(5),
    }


def configure(conf) -> None:
    """Apply the ``spark.rapids.tpu.cache.plan.*`` conf group (called
    by QueryService.__init__; the flags are ALSO honored per planning
    call from the query's own conf, so a session overlay can opt out
    without touching process-wide state)."""
    global _ENABLED, _MAX_ENTRIES
    from ..config import CACHE_PLAN_ENABLED, CACHE_PLAN_MAX_ENTRIES
    _ENABLED = bool(conf.get(CACHE_PLAN_ENABLED))
    _MAX_ENTRIES = max(1, int(conf.get(CACHE_PLAN_MAX_ENTRIES)))
    evicted = 0
    with _LOCK:
        while len(_ENTRIES) > _MAX_ENTRIES:
            _ENTRIES.popitem(last=False)
            evicted += 1
    for _ in range(evicted):
        PLAN_CACHE_EVENTS.labels(event="evicted").inc()


def reset() -> None:
    """Test hook: drop all entries and counters."""
    global _HITS, _MISSES, _VALIDATION_MISSES, _INVALIDATED, _EVICTED
    with _LOCK:
        _ENTRIES.clear()
        _HITS = _MISSES = _VALIDATION_MISSES = 0
        _INVALIDATED = _EVICTED = 0
