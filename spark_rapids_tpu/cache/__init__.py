"""Plan-reuse layer: fingerprint-keyed caches over planning artifacts
(cache/plan_cache.py) — the consumer side of the obs/fingerprint.py
identity plane."""
