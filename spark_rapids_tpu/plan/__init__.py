"""Planner layer: logical IR, wrap/tag/convert overrides, type checks."""
