"""Logical-plan rewrites: predicate pushdown + equi-join extraction.

Role note: the reference plugs into Spark *after* Catalyst's optimizer has
already pushed predicates and chosen join keys (SparkPlan arrives
optimized; GpuOverrides.scala:3100 only re-maps physical ops).  This
standalone framework owns the front end, so the classical rewrites live
here: conjuncts of a Filter over an inner/cross Join are split into
per-side filters, cross-side equalities become hash-join keys (turning a
cross join into an equi join the TPU hash-join exec can run), and the
remainder stays as a residual filter.
"""
from __future__ import annotations

import copy
from typing import List, Optional, Set

from ..expr import core as ec
from ..expr import predicates as ep
from . import logical as L


def _flatten_and(e: ec.Expression) -> List[ec.Expression]:
    if isinstance(e, ep.And):
        return _flatten_and(e.children[0]) + _flatten_and(e.children[1])
    return [e]


def _and_all(conjuncts: List[ec.Expression]) -> ec.Expression:
    out = conjuncts[0]
    for c in conjuncts[1:]:
        out = ep.And(out, c)
    return out


def _refs(e: ec.Expression) -> Optional[Set[str]]:
    """Names of AttributeReferences in e; None if e contains anything
    (BoundReference, subquery-ish) that makes pushdown unsafe."""
    if isinstance(e, ec.BoundReference):
        return None
    if isinstance(e, ec.AttributeReference):
        return {e.col_name}
    out: Set[str] = set()
    for c in e.children:
        r = _refs(c)
        if r is None:
            return None
        out |= r
    return out


def _filter_over(conjuncts: List[ec.Expression],
                 plan: L.LogicalPlan) -> L.LogicalPlan:
    if not conjuncts:
        return plan
    return L.Filter(_and_all(conjuncts), plan)


def _flatten_or(e: ec.Expression) -> List[ec.Expression]:
    if isinstance(e, ep.Or):
        return _flatten_or(e.children[0]) + _flatten_or(e.children[1])
    return [e]


def _or_all(disjuncts: List[ec.Expression]) -> ec.Expression:
    out = disjuncts[0]
    for d in disjuncts[1:]:
        out = ep.Or(out, d)
    return out


def _factor_or(e: ec.Expression) -> List[ec.Expression]:
    """Factor conjuncts common to every OR arm out of the disjunction:
    ``(A and B) or (A and C)  ->  A and (B or C)``.

    Sound in SQL's three-valued logic (Kleene distributivity), and
    load-bearing for the TPC-DS q13/q48 shape where the JOIN
    EQUALITIES live inside each OR arm — without factoring they never
    become hash-join keys and the plan degenerates to a cross join."""
    disjuncts = _flatten_or(e)
    if len(disjuncts) < 2:
        return [e]
    conj_lists = [_flatten_and(d) for d in disjuncts]
    first_keys = {repr(c): c for c in conj_lists[0]}
    common_keys = [k for k in first_keys
                   if all(any(repr(x) == k for x in cl)
                          for cl in conj_lists[1:])]
    if not common_keys:
        return [e]
    common_set = set(common_keys)
    remainders = []
    for cl in conj_lists:
        removed: Set[str] = set()
        rem = []
        for x in cl:
            rx = repr(x)
            if rx in common_set and rx not in removed:
                removed.add(rx)
                continue
            rem.append(x)
        remainders.append(_and_all(rem) if rem else
                          ec.Literal(True))
    return [first_keys[k] for k in common_keys] + [_or_all(remainders)]


def _rewrite_filter_join(f: L.Filter) -> L.LogicalPlan:
    j = f.children[0]
    if not isinstance(j, L.Join) or j.join_type not in ("inner", "cross"):
        return f
    left, right = j.children
    lnames = set(left.schema.names)
    rnames = set(right.schema.names)
    if lnames & rnames:
        return f  # ambiguous column names: leave untouched
    lpush: List[ec.Expression] = []
    rpush: List[ec.Expression] = []
    lkeys = list(j.left_keys)
    rkeys = list(j.right_keys)
    rest: List[ec.Expression] = []
    conjuncts = [x for c in _flatten_and(f.condition)
                 for x in _factor_or(c)]
    for c in conjuncts:
        refs = _refs(c)
        if refs is None or not refs:
            rest.append(c)
        elif refs <= lnames:
            lpush.append(c)
        elif refs <= rnames:
            rpush.append(c)
        elif isinstance(c, ep.EqualTo):
            a, b = c.children
            ra, rb = _refs(a), _refs(b)
            if ra and rb and ra <= lnames and rb <= rnames:
                lkeys.append(a)
                rkeys.append(b)
            elif ra and rb and ra <= rnames and rb <= lnames:
                lkeys.append(b)
                rkeys.append(a)
            else:
                rest.append(c)
        else:
            rest.append(c)
    if not lpush and not rpush and len(lkeys) == len(j.left_keys):
        return f
    new_left = optimize(_filter_over(lpush, left))
    new_right = optimize(_filter_over(rpush, right))
    jt = "inner" if lkeys else j.join_type
    nj = L.Join(new_left, new_right, jt, lkeys, rkeys, j.condition)
    return _filter_over(rest, nj)


def _rewrite_filter_semi(f: L.Filter) -> L.LogicalPlan:
    """Filter over a semi/anti join: conjuncts that reference only the
    left side commute with the join (its output IS the left rows), so
    they push into the left child — where the inner/cross rewrite can
    then lift equalities into hash-join keys.  Load-bearing for the
    ``x IN (subquery)`` lowering, which stacks a semi join between the
    WHERE filter and the comma-join chain it must decompose."""
    j = f.children[0]
    if not isinstance(j, L.Join) or j.join_type not in ("semi", "anti"):
        return f
    left = j.children[0]
    lnames = set(left.schema.names)
    push: List[ec.Expression] = []
    rest: List[ec.Expression] = []
    for c in _flatten_and(f.condition):
        refs = _refs(c)
        if refs is not None and refs and refs <= lnames:
            push.append(c)
        else:
            rest.append(c)
    if not push:
        return f
    new_left = optimize(_filter_over(push, left))
    nj = L.Join(new_left, j.children[1], j.join_type, j.left_keys,
                j.right_keys, j.condition)
    return _filter_over(rest, nj)


def _rewrite_filter_project(f: L.Filter) -> L.LogicalPlan:
    """Push Filter conjuncts through a pass-through/renaming Project so
    they can keep sinking into the join below (the scalar-subquery
    decorrelation emits Project(Filter(Join(cross...))) shapes whose
    outer WHERE conjuncts must still reach the cross join)."""
    pj = f.children[0]
    if not isinstance(pj, L.Project):
        return f
    # out name -> source name, only for pure column pass-throughs
    mapping = {}
    for e in pj.exprs:
        src = e
        name = None
        if isinstance(e, ec.Alias):
            name = e.alias
            src = e.children[0]
        if isinstance(src, ec.AttributeReference):
            mapping[name or src.col_name] = src.col_name
    push: List[ec.Expression] = []
    rest: List[ec.Expression] = []

    def rewrite(e: ec.Expression):
        if isinstance(e, ec.AttributeReference):
            if e.col_name not in mapping:
                return None
            return ec.AttributeReference(mapping[e.col_name], e._dtype,
                                         e._nullable)
        kids = []
        for c in e.children:
            r = rewrite(c)
            if r is None:
                return None
            kids.append(r)
        return e.with_children(kids) if kids else e

    for c in _flatten_and(f.condition):
        refs = _refs(c)
        if refs is None:
            rest.append(c)
            continue
        r = rewrite(c)
        if r is not None:
            push.append(r)
        else:
            rest.append(c)
    if not push:
        return f
    new_child = optimize(_filter_over(push, pj.children[0]))
    npj = L.Project(pj.exprs, new_child)
    return _filter_over(rest, npj)


def _collect_cross_tree(p: L.LogicalPlan, rels: List[L.LogicalPlan]
                        ) -> bool:
    """Flatten a left-deep keyless cross/inner join tree into its
    relations; False if the tree has keys/conditions (already shaped)."""
    if isinstance(p, L.Join) and p.join_type in ("cross", "inner") and \
            not p.left_keys and p.condition is None:
        return _collect_cross_tree(p.children[0], rels) and \
            _collect_cross_tree(p.children[1], rels)
    rels.append(p)
    return True


def _reorder_cross_joins(f: L.Filter) -> L.Filter:
    """Connectivity-first join ordering over a FROM comma-list.

    The lowerer builds a left-deep cross-join tree in FROM order; when
    a relation's only equi predicates reference relations that appear
    LATER (TPC-DS q64 lists date_dim d2/d3 before customer), the
    pairwise rewrite leaves a cartesian behind and the plan explodes.
    Greedy fix (the classical heuristic): start from the first
    relation, repeatedly attach a relation linked to the joined set by
    an equality predicate; fall back to FROM order only when nothing
    connects.  The pairwise _rewrite_filter_join pass then distributes
    the predicates over the reordered tree."""
    j = f.children[0]
    rels: List[L.LogicalPlan] = []
    if not (isinstance(j, L.Join) and _collect_cross_tree(j, rels)) or \
            len(rels) < 3:
        return f
    names = [set(r.schema.names) for r in rels]
    if len(set().union(*names)) != sum(len(n) for n in names):
        return f                      # ambiguous columns: leave alone
    # equality edges between relation indices
    edges = []
    for c in _flatten_and(f.condition):
        if isinstance(c, ep.EqualTo):
            ra = _refs(c.children[0])
            rb = _refs(c.children[1])
            if not ra or not rb:
                continue
            ia = [i for i, n in enumerate(names) if ra <= n]
            ib = [i for i, n in enumerate(names) if rb <= n]
            if len(ia) == 1 and len(ib) == 1 and ia[0] != ib[0]:
                edges.append((ia[0], ib[0]))
    joined = {0}
    order = [0]
    remaining = list(range(1, len(rels)))
    while remaining:
        pick = None
        for i in remaining:           # FROM order among connected
            if any((a in joined) != (b in joined) and i in (a, b)
                   for a, b in edges):
                pick = i
                break
        if pick is None:
            pick = remaining[0]       # nothing connects: cross join
        joined.add(pick)
        order.append(pick)
        remaining.remove(pick)
    if order == list(range(len(rels))):
        return f
    tree: L.LogicalPlan = rels[order[0]]
    for i in order[1:]:
        tree = L.Join(tree, rels[i], "cross", [], [], None)
    return L.Filter(f.condition, tree)


# ---------------------------------------------------------------------------
# scan column pruning (Spark's ColumnPruning rule; the reference relies on
# Catalyst doing this before the plugin sees the plan — without it every
# file scan decodes AND uploads all columns, and host->device bandwidth is
# the scarcest resource on this backend)
# ---------------------------------------------------------------------------

def _u(*sets: "Optional[Set[str]]") -> "Optional[Set[str]]":
    """Union of required-name sets; None ("need everything") poisons."""
    out: Set[str] = set()
    for s in sets:
        if s is None:
            return None
        out |= s
    return out


def _refs_many(exprs) -> "Optional[Set[str]]":
    return _u(*[_refs(e) for e in exprs]) if exprs else set()


def _narrowest_field(fields):
    """Cheapest single column to keep for pure-count scans."""
    def width(f):
        w = getattr(f.dtype, "itemsize", None)
        if w is None:
            w = 16 if f.dtype.name in ("string", "binary") else 8
        return w
    return min(fields, key=width)


def prune_scan_columns(plan: L.LogicalPlan,
                       need: "Optional[Set[str]]" = None) -> L.LogicalPlan:
    """Top-down required-column propagation narrowing file scans.

    ``need=None`` means the parent requires every output column (the
    root, and any opaque consumer: pandas execs, writers, DISTINCT).
    Nodes are copied, never mutated — Scan nodes are shared across
    queries via registered views.
    """
    import copy as _copy

    def rec(p: L.LogicalPlan, need, parent=None):
        if isinstance(p, L.Scan):
            if need is None:
                return p
            kept = [f for f in p.schema.fields if f.name in need]
            if len(kept) == len(p.schema.fields):
                return p
            if not kept:
                kept = [_narrowest_field(p.schema.fields)]
            from ..columnar.schema import Schema
            out = _copy.copy(p)
            out._schema = Schema(kept)
            return out
        if isinstance(p, (L.LocalRelation, L.Range, L.CachedRelation)) or \
                not p.children:
            return p

        dropped = None            # replacement exprs/aggs when narrowed
        if isinstance(p, L.Filter):
            needs = [_u(need, _refs(p.condition))]
        elif isinstance(p, L.Project):
            kept = p.exprs if need is None else \
                [e for e in p.exprs if L.output_name(e) in need]
            if not kept:
                kept = p.exprs[:1]
            if len(kept) != len(p.exprs):
                dropped = ("exprs", kept)
            needs = [_refs_many(kept)]
        elif isinstance(p, L.Aggregate):
            kept_aggs = p.aggs if need is None else \
                [a for a in p.aggs if a.alias in need]
            if len(kept_aggs) != len(p.aggs):
                dropped = ("aggs", kept_aggs)
            needs = [_u(_refs_many(p.group_exprs),
                        _refs_many([a.func for a in kept_aggs]))]
        elif isinstance(p, L.Join):
            cn = _u(need, _refs_many(p.left_keys),
                    _refs_many(p.right_keys),
                    _refs(p.condition) if p.condition is not None
                    else set())
            needs = [cn, cn]
            if need is not None and isinstance(parent,
                                               (L.Project, L.Aggregate)):
                # record which OUTPUT columns the parent actually
                # consumes: execs that can emit a subset (the mesh
                # join routes fixed-width payloads) key off this —
                # e.g. a string JOIN KEY the parent projects away
                # stops blocking the mesh path.  Only name-binding
                # parents (Project/Aggregate) qualify: positional
                # consumers (another Join's output assembly) pair
                # columns with the full logical schema.  Never prune
                # to zero columns — batches need a capacity carrier.
                names = [f.name for f in p.schema.fields]
                if len(names) == len(set(names)):
                    req = sorted(n for n in need if n in set(names))
                    if not req:
                        req = [_narrowest_field(p.schema.fields).name]
                    if len(req) < len(names):
                        dropped = ("required_out", req)
        elif isinstance(p, L.Sort):
            needs = [_u(need, _refs_many([o.expr for o in p.orders]))]
        elif isinstance(p, L.Limit):
            needs = [need]
        elif isinstance(p, L.Repartition):
            needs = [_u(need, _refs_many(p.by_exprs or []))]
        elif isinstance(p, L.Window):
            aliases = {wf.alias for wf in p.window_funcs}
            base = None if need is None else \
                {n for n in need if n not in aliases}
            wrefs = []
            for wf in p.window_funcs:
                wrefs.append(_refs(wf.func))
                wrefs.append(_refs_many(wf.spec.partition_by))
                wrefs.append(_refs_many([o.expr for o in wf.spec.order_by]))
            needs = [_u(base, *wrefs)]
        elif isinstance(p, L.Expand):
            needs = [_refs_many([e for proj in p.projections for e in proj])]
        elif isinstance(p, L.Generate):
            gen_names = set(p.output_names)
            base = None if need is None else \
                {n for n in need if n not in gen_names}
            needs = [_u(base, _refs(p.generator))]
        elif isinstance(p, L.Union):
            if need is None:
                needs = [None] * len(p.children)
            else:
                try:
                    pos = [i for i, f in enumerate(p.schema.fields)
                           if f.name in need]
                    needs = [{c.schema.fields[i].name for i in pos}
                             for c in p.children]
                except Exception:
                    needs = [None] * len(p.children)
        else:
            # Distinct (whole-row semantics), writers, pandas execs,
            # and anything unknown: require every column below
            needs = [None] * len(p.children)

        new_children = [rec(c, n, parent=p)
                        for c, n in zip(p.children, needs)]
        if dropped is None and all(n is o for n, o in
                                   zip(new_children, p.children)):
            return p
        out = _copy.copy(p)
        out.children = new_children
        if dropped is not None:
            setattr(out, dropped[0], dropped[1])
        return out

    return rec(plan, need)


def optimize(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Bottom-up: push Filter conjuncts through inner/cross joins and
    promote cross-side equalities to join keys."""
    new_children = [optimize(c) for c in plan.children]
    if any(n is not o for n, o in zip(new_children, plan.children)):
        plan = copy.copy(plan)
        plan.children = new_children
    if isinstance(plan, L.Filter):
        # collapse Filter(Filter(..)) so conjuncts see the join below
        child = plan.children[0]
        if isinstance(child, L.Filter):
            merged = L.Filter(
                ep.And(plan.condition, child.condition), child.children[0])
            return optimize(merged)
        plan = _reorder_cross_joins(plan)
        out = _rewrite_filter_join(plan)
        if out is not plan:
            return out
        out = _rewrite_filter_semi(plan)
        if out is not plan:
            return out
        out = _rewrite_filter_project(plan)
        if out is not plan:
            return out
    return plan
