"""TypeSig — the type-support algebra driving tagging and docs.

Reference: TypeChecks.scala:367 (TypeSig), ExecChecks/ExprChecks, and the
generated docs/supported_ops.md.  A TypeSig describes which dtypes an op
supports; tagging intersects the actual plan types against it and records
human-readable reasons on mismatch (RapidsMeta.explain role).
"""
from __future__ import annotations

from typing import Iterable, Optional, Set, Type

from ..columnar import dtypes as T


class TypeSig:
    def __init__(self, kinds: Iterable[type] = (), decimal: bool = False,
                 note: str = ""):
        self.kinds: Set[type] = set(kinds)
        self.decimal = decimal
        self.note = note

    def __add__(self, other: "TypeSig") -> "TypeSig":
        out = TypeSig(self.kinds | other.kinds,
                      self.decimal or other.decimal)
        return out

    def supports(self, dt: T.DType) -> bool:
        if isinstance(dt, T.DecimalType):
            return self.decimal
        # nested types are supported when listed AND their leaves are
        if isinstance(dt, T.ArrayType):
            return T.ArrayType in self.kinds and self.supports(
                dt.element_type)
        if isinstance(dt, T.StructType):
            return T.StructType in self.kinds and all(
                self.supports(f.dtype) for f in dt.fields)
        if isinstance(dt, T.MapType):
            return T.MapType in self.kinds and self.supports(dt.key_type) \
                and self.supports(dt.value_type)
        return type(dt) in self.kinds

    def reason(self, dt: T.DType, context: str) -> Optional[str]:
        if self.supports(dt):
            return None
        return f"{context}: type {dt.name} is not supported on TPU"

    def describe(self) -> str:
        names = sorted(k().name if k not in (T.DecimalType,) else "decimal"
                       for k in self.kinds)
        if self.decimal:
            names.append("decimal64")
        return ", ".join(names)


BOOLEAN = TypeSig([T.BooleanType])
INTEGRAL = TypeSig([T.ByteType, T.ShortType, T.IntegerType, T.LongType])
FP = TypeSig([T.FloatType, T.DoubleType])
NUMERIC = INTEGRAL + FP
DECIMAL_64 = TypeSig([], decimal=True)
NUMERIC_WITH_DECIMAL = NUMERIC + DECIMAL_64
STRING_SIG = TypeSig([T.StringType])
DATETIME = TypeSig([T.DateType, T.TimestampType])
NULL_SIG = TypeSig([T.NullType])

# scalar types every op can handle
ALL_SUPPORTED = (BOOLEAN + NUMERIC + DECIMAL_64 + STRING_SIG + DATETIME +
                 NULL_SIG)
ARRAY_SIG = TypeSig([T.ArrayType])
STRUCT_SIG = TypeSig([T.StructType])
MAP_SIG = TypeSig([T.MapType])
# scalars + arrays of them: only for ops that understand ListColumn
# (references, aliases, the collection expressions)
WITH_ARRAYS = ALL_SUPPORTED + ARRAY_SIG
# everything device-resident incl. structs and maps (nested leaves must
# themselves be supported — TypeSig.supports recurses)
WITH_NESTED = WITH_ARRAYS + STRUCT_SIG + MAP_SIG
# orderable == groupable == joinable (canonical key words cover scalars
# only; nested types cannot be sort/join keys yet)
ORDERABLE = ALL_SUPPORTED
