"""TypeSig — the type-support algebra driving tagging and docs.

Reference: TypeChecks.scala:367 (TypeSig), ExecChecks/ExprChecks, and the
generated docs/supported_ops.md.  A TypeSig describes which dtypes an op
supports; tagging intersects the actual plan types against it and records
human-readable reasons on mismatch (RapidsMeta.explain role).
"""
from __future__ import annotations

from typing import Iterable, Optional, Set, Type

from ..columnar import dtypes as T


class TypeSig:
    def __init__(self, kinds: Iterable[type] = (), decimal: bool = False,
                 note: str = ""):
        self.kinds: Set[type] = set(kinds)
        self.decimal = decimal
        self.note = note

    def __add__(self, other: "TypeSig") -> "TypeSig":
        out = TypeSig(self.kinds | other.kinds,
                      self.decimal or other.decimal)
        return out

    def supports(self, dt: T.DType) -> bool:
        if isinstance(dt, T.DecimalType):
            return self.decimal
        # nested types are supported when listed AND their leaves are
        if isinstance(dt, T.ArrayType):
            return T.ArrayType in self.kinds and self.supports(
                dt.element_type)
        if isinstance(dt, T.StructType):
            return T.StructType in self.kinds and all(
                self.supports(f.dtype) for f in dt.fields)
        if isinstance(dt, T.MapType):
            return T.MapType in self.kinds and self.supports(dt.key_type) \
                and self.supports(dt.value_type)
        return type(dt) in self.kinds

    def reason(self, dt: T.DType, context: str) -> Optional[str]:
        if self.supports(dt):
            return None
        return f"{context}: type {dt.name} is not supported on TPU"

    def describe(self) -> str:
        names = sorted(k().name if k not in (T.DecimalType,) else "decimal"
                       for k in self.kinds)
        if self.decimal:
            names.append("decimal64")
        return ", ".join(names)


BOOLEAN = TypeSig([T.BooleanType])
INTEGRAL = TypeSig([T.ByteType, T.ShortType, T.IntegerType, T.LongType])
FP = TypeSig([T.FloatType, T.DoubleType])
NUMERIC = INTEGRAL + FP
DECIMAL_64 = TypeSig([], decimal=True)
NUMERIC_WITH_DECIMAL = NUMERIC + DECIMAL_64
STRING_SIG = TypeSig([T.StringType])
DATETIME = TypeSig([T.DateType, T.TimestampType])
NULL_SIG = TypeSig([T.NullType])

# scalar types every op can handle
ALL_SUPPORTED = (BOOLEAN + NUMERIC + DECIMAL_64 + STRING_SIG + DATETIME +
                 NULL_SIG)
ARRAY_SIG = TypeSig([T.ArrayType])
STRUCT_SIG = TypeSig([T.StructType])
MAP_SIG = TypeSig([T.MapType])
# scalars + arrays of them: only for ops that understand ListColumn
# (references, aliases, the collection expressions)
WITH_ARRAYS = ALL_SUPPORTED + ARRAY_SIG
# everything device-resident incl. structs and maps (nested leaves must
# themselves be supported — TypeSig.supports recurses)
WITH_NESTED = WITH_ARRAYS + STRUCT_SIG + MAP_SIG
# orderable == groupable == joinable (canonical key words cover scalars
# only; nested types cannot be sort/join keys yet)
ORDERABLE = ALL_SUPPORTED


# ---------------------------------------------------------------------------
# per-parameter signatures (ExprChecks role, TypeChecks.scala:879)
# ---------------------------------------------------------------------------

class ParamSig:
    """One named parameter's accepted types (+ partial-support note)."""

    def __init__(self, name: str, sig: TypeSig, note: str = ""):
        self.name = name
        self.sig = sig
        self.note = note


class ExprSig:
    """Per-parameter + output type contract for one expression class.

    Reference: ExprChecks (TypeChecks.scala:879) — each GPU expression
    declares what each input parameter accepts and what it produces;
    tagging walks ACTUAL child dtypes against the matching parameter
    instead of only checking the output type.  ``repeat_last`` covers
    variadic tails (Coalesce, Least, CreateArray...).
    """

    def __init__(self, params: list, output: TypeSig,
                 repeat_last: bool = False, note: str = "",
                 check_params: bool = True):
        self.params = list(params)
        self.output = output
        self.repeat_last = repeat_last
        self.note = note
        self.check_params = check_params

    @classmethod
    def uniform(cls, sig: TypeSig) -> "ExprSig":
        """Back-compat wrapper: output-type check only (legacy rules
        never constrained parameters; per-param contracts register an
        explicit ExprSig instead)."""
        return cls([ParamSig("input", sig)], sig, repeat_last=True,
                   check_params=False)

    def _param_for(self, i: int) -> Optional[ParamSig]:
        if i < len(self.params):
            return self.params[i]
        if self.repeat_last and self.params:
            return self.params[-1]
        return None

    def describe(self) -> str:
        if not self.check_params:
            return self.output.describe()
        parts = [f"{p.name}: {p.sig.describe()}" for p in self.params]
        return "; ".join(parts) + f" -> {self.output.describe()}"

    def reasons_for(self, expr) -> list:
        out = []
        cls_name = type(expr).__name__
        try:
            dt = expr.dtype()
        except (ValueError, NotImplementedError) as e:
            return [f"{cls_name}: {e}"]
        r = self.output.reason(dt, f"{cls_name} output")
        if r:
            out.append(r)
        if not self.check_params:
            return out
        for i, c in enumerate(expr.children):
            p = self._param_for(i)
            if p is None:
                out.append(f"{cls_name}: unexpected argument {i}")
                continue
            try:
                cdt = c.dtype()
            except (ValueError, NotImplementedError):
                continue
            if not p.sig.supports(cdt):
                note = f" ({p.note})" if p.note else ""
                out.append(f"{cls_name} parameter '{p.name}': type "
                           f"{cdt.name} is not supported on TPU{note}")
        return out


# ---------------------------------------------------------------------------
# cast-pair support matrix (CastChecks role, TypeChecks.scala:367)
# ---------------------------------------------------------------------------

def _family(dt: T.DType) -> str:
    if isinstance(dt, T.DecimalType):
        return "decimal"
    if isinstance(dt, (T.ArrayType, T.StructType, T.MapType)):
        return "nested"
    if dt == T.BOOL:
        return "bool"
    if dt.is_integral:
        return "integral"
    if dt.is_fractional:
        return "fp"
    if dt == T.STRING:
        return "string"
    if dt == T.DATE:
        return "date"
    if dt == T.TIMESTAMP:
        return "timestamp"
    if dt == T.NULL:
        return "null"
    return "other"


#: (from_family, to_family) -> None (supported) | reason note.
#: Mirrors the reference's sparse cast matrix: everything listed as a
#: key is a cast the engine has an implementation for; absent pairs tag
#: the plan node to the CPU engine.
CAST_MATRIX = {}


def _allow(src: str, dsts: str, note: str = ""):
    for d in dsts.split():
        CAST_MATRIX[(src, d)] = note or None


_allow("bool", "bool integral fp string")
_allow("integral", "bool integral fp decimal string timestamp")
_allow("fp", "bool integral fp decimal string",
       "fp->string formats with Spark's toString rules")
_allow("decimal", "integral fp decimal string")
_allow("string", "bool integral fp decimal date timestamp string",
       "string->fp/date/timestamp follow Spark parsing; malformed "
       "values become NULL")
_allow("date", "date timestamp string integral")
_allow("timestamp", "date timestamp string integral fp")
_allow("null", "bool integral fp decimal string date timestamp null "
               "nested")


def cast_reason(src: T.DType, dst: T.DType) -> Optional[str]:
    """None when CAST(src AS dst) runs on the TPU; else the reason."""
    key = (_family(src), _family(dst))
    if key[0] == key[1] and key[0] == "nested":
        return "nested-to-nested casts are not supported on TPU"
    if key in CAST_MATRIX:
        return None
    return (f"Cast {src.name} -> {dst.name} is not supported on TPU")


def cast_note(src: T.DType, dst: T.DType) -> Optional[str]:
    return CAST_MATRIX.get((_family(src), _family(dst)))
