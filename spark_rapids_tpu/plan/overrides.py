"""The planner: wrap -> tag -> convert, with explain and CPU fallback.

Reference: GpuOverrides.scala:3100 (apply/applyOverrides), RapidsMeta.scala
(wrapping/tagging framework), GpuTransitionOverrides.scala (transition
insertion).  Differences are structural, not conceptual: the logical plan
is ours (no Catalyst), and the CPU engine is the pyarrow fallback rather
than stock Spark.

Pipeline:
  1. wrap every logical node in a PlanMeta; every expression in ExprMeta
  2. tag: type checks (TypeSig), conf enables, per-op constraints; record
     human-readable reasons (spark.rapids.tpu.sql.explain)
  3. convert: tagged-ok nodes become TPU execs with exchanges inserted
     (partial/final aggregation, hash-partitioned joins, range-partitioned
     global sorts); tagged-out nodes become CPU execs with
     RowToColumnar/ColumnarToRow transitions fused at the boundaries
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Type

from ..columnar import dtypes as T
from ..config import (TpuConf, SQL_ENABLED, EXPLAIN, SHUFFLE_PARTITIONS,
                      TEST_ENABLED, DECIMAL_ENABLED, CAST_STRING_TO_FLOAT,
                      BATCH_SIZE_ROWS, SHUFFLE_MODE, ADAPTIVE_ENABLED,
                      ADAPTIVE_TARGET_PARTITION_BYTES,
                      ADAPTIVE_BROADCAST_BYTES, ADAPTIVE_SKEW_FACTOR,
                      ADAPTIVE_SKEW_MIN_BYTES)
from ..expr import core as ec
from ..expr import (aggregates as eagg, arithmetic as ea, cast as ecast,
                    conditional as econd, datetime as edt, misc as emisc,
                    predicates as ep, string_ops as es)
from . import logical as L
from . import typesig as TS
from ..exec import cpu as X
from ..exec import tpu_basic as TB
from ..exec import tpu_aggregate as TA
from ..exec import tpu_join as TJ
from ..exec import tpu_sort as TSOR
from ..exec import exchange as EX
from ..exec.base import PhysicalPlan
from ..shuffle.partitioners import (HashPartitioner, RangePartitioner,
                                    RoundRobinPartitioner,
                                    SinglePartitioner)

BROADCAST_ROW_THRESHOLD = 1 << 20  # rows; stand-in for byte-size stats


def _scan_row_estimate(p, conf=None) -> "Optional[int]":
    """Row-count estimate for file scans (parquet metadata is cheap)."""
    if getattr(p, "_row_estimate", None) is not None:
        return p._row_estimate
    try:
        if p.fmt == "parquet":
            import pyarrow.parquet as papq
            from ..io.readers import expand_paths
            total = 0
            for f in expand_paths(p.paths, conf):
                total += papq.ParquetFile(f).metadata.num_rows
            p._row_estimate = total
            return total
    except Exception:
        pass
    p._row_estimate = None
    return None


# ---------------------------------------------------------------------------
# expression rules (the expr[...] registry, GpuOverrides.scala:773)
# ---------------------------------------------------------------------------

_EXPR_RULES: Dict[Type[ec.Expression], "TS.ExprSig"] = {}


def expr_rule(cls, sig):
    """Register an expression rule: a plain TypeSig (uniform across
    params, back-compat) or a per-parameter ExprSig
    (TypeChecks.scala:879 ExprChecks role)."""
    _EXPR_RULES[cls] = sig if isinstance(sig, TS.ExprSig) else \
        TS.ExprSig.uniform(sig)


for _cls in [ec.AttributeReference, ec.BoundReference, ec.Literal, ec.Alias]:
    expr_rule(_cls, TS.WITH_NESTED)
for _cls in [ea.Add, ea.Subtract, ea.Multiply, ea.Divide, ea.IntegralDivide,
             ea.Remainder, ea.Pmod, ea.UnaryMinus, ea.UnaryPositive, ea.Abs,
             ea.Least, ea.Greatest, ea.Round]:
    expr_rule(_cls, TS.NUMERIC_WITH_DECIMAL)
for _cls in [ea.Sqrt, ea.Exp, ea.Expm1, ea.Log, ea.Log1p, ea.Log2, ea.Log10,
             ea.Sin, ea.Cos, ea.Tan, ea.Asin, ea.Acos, ea.Atan, ea.Sinh,
             ea.Cosh, ea.Tanh, ea.Asinh, ea.Acosh, ea.Atanh, ea.Cbrt,
             ea.ToDegrees, ea.ToRadians, ea.Rint, ea.Signum, ea.Floor,
             ea.Ceil, ea.Pow, ea.Atan2]:
    expr_rule(_cls, TS.NUMERIC)
for _cls in [ea.BitwiseAnd, ea.BitwiseOr, ea.BitwiseXor, ea.BitwiseNot,
             ea.ShiftLeft, ea.ShiftRight, ea.ShiftRightUnsigned]:
    expr_rule(_cls, TS.INTEGRAL)
for _cls in [ep.EqualTo, ep.EqualNullSafe, ep.LessThan, ep.LessThanOrEqual,
             ep.GreaterThan, ep.GreaterThanOrEqual, ep.In]:
    expr_rule(_cls, TS.ORDERABLE)
for _cls in [ep.Not, ep.And, ep.Or]:
    expr_rule(_cls, TS.BOOLEAN)
for _cls in [ep.IsNull, ep.IsNotNull]:
    expr_rule(_cls, TS.ALL_SUPPORTED)
expr_rule(ep.IsNaN, TS.FP)
for _cls in [econd.If, econd.CaseWhen, econd.Coalesce, econd.NaNvl]:
    expr_rule(_cls, TS.ALL_SUPPORTED)
expr_rule(ecast.Cast, TS.ALL_SUPPORTED)
for _cls in [es.Upper, es.Lower, es.Length, es.Substring, es.StartsWith,
             es.EndsWith, es.Contains, es.Like, es.RLike, es.ConcatStrings,
             es.StringTrim, es.StringTrimLeft, es.StringTrimRight,
             es.Replace, es.Reverse, es.StringRepeat, es.Lpad, es.Rpad,
             es.InitCap, es.StringLocate, es.ConcatWs, es.RegexpReplace,
             es.RegexpExtract]:
    expr_rule(_cls, TS.STRING_SIG)
for _cls in [edt.Year, edt.Month, edt.DayOfMonth, edt.Quarter, edt.DayOfWeek,
             edt.WeekDay, edt.DayOfYear, edt.LastDay, edt.Hour, edt.Minute,
             edt.Second, edt.DateAdd, edt.DateSub, edt.DateDiff,
             edt.UnixTimestampToSeconds, edt.ToDate]:
    expr_rule(_cls, TS.DATETIME + TS.INTEGRAL)
for _cls in [emisc.Murmur3Hash, emisc.Md5, emisc.MonotonicallyIncreasingID,
             emisc.SparkPartitionID, emisc.Rand]:
    expr_rule(_cls, TS.ALL_SUPPORTED)

# -- refined per-parameter contracts (ExprChecks role, the rules above
# keep the legacy output-only check; these override with full param
# signatures like TypeChecks.scala:879 declares per GPU expression) ----
_P = TS.ParamSig
expr_rule(es.Substring, TS.ExprSig(
    [_P("str", TS.STRING_SIG), _P("pos", TS.INTEGRAL),
     _P("len", TS.INTEGRAL)], TS.STRING_SIG))
expr_rule(es.StringLocate, TS.ExprSig(
    [_P("substr", TS.STRING_SIG), _P("str", TS.STRING_SIG),
     _P("start", TS.INTEGRAL)], TS.INTEGRAL))
expr_rule(es.Lpad, TS.ExprSig(
    [_P("str", TS.STRING_SIG), _P("len", TS.INTEGRAL),
     _P("pad", TS.STRING_SIG)], TS.STRING_SIG,
    note="pad runs on the host string path"))
expr_rule(es.Rpad, TS.ExprSig(
    [_P("str", TS.STRING_SIG), _P("len", TS.INTEGRAL),
     _P("pad", TS.STRING_SIG)], TS.STRING_SIG,
    note="pad runs on the host string path"))
expr_rule(es.StringRepeat, TS.ExprSig(
    [_P("str", TS.STRING_SIG), _P("n", TS.INTEGRAL)], TS.STRING_SIG))
expr_rule(es.RegexpExtract, TS.ExprSig(
    [_P("str", TS.STRING_SIG), _P("regexp", TS.STRING_SIG),
     _P("idx", TS.INTEGRAL)], TS.STRING_SIG,
    note="pattern must be a literal; host regex engine"))
expr_rule(es.RegexpReplace, TS.ExprSig(
    [_P("str", TS.STRING_SIG), _P("regexp", TS.STRING_SIG),
     _P("rep", TS.STRING_SIG)], TS.STRING_SIG,
    note="pattern must be a literal; host regex engine"))
expr_rule(edt.DateAdd, TS.ExprSig(
    [_P("start", TS.DATETIME), _P("days", TS.INTEGRAL)], TS.DATETIME))
expr_rule(edt.DateSub, TS.ExprSig(
    [_P("start", TS.DATETIME), _P("days", TS.INTEGRAL)], TS.DATETIME))
expr_rule(edt.DateDiff, TS.ExprSig(
    [_P("end", TS.DATETIME), _P("start", TS.DATETIME)], TS.INTEGRAL))
expr_rule(ep.And, TS.ExprSig(
    [_P("lhs", TS.BOOLEAN), _P("rhs", TS.BOOLEAN)], TS.BOOLEAN))
expr_rule(ep.Or, TS.ExprSig(
    [_P("lhs", TS.BOOLEAN), _P("rhs", TS.BOOLEAN)], TS.BOOLEAN))
expr_rule(ep.Not, TS.ExprSig([_P("input", TS.BOOLEAN)], TS.BOOLEAN))
expr_rule(econd.If, TS.ExprSig(
    [_P("predicate", TS.BOOLEAN), _P("trueValue", TS.ALL_SUPPORTED),
     _P("falseValue", TS.ALL_SUPPORTED)], TS.ALL_SUPPORTED))
for _cls in [eagg.Sum, eagg.Count, eagg.Min, eagg.Max, eagg.Average,
             eagg.First, eagg.Last, eagg.StddevSamp, eagg.StddevPop,
             eagg.VarianceSamp, eagg.VariancePop, eagg.PivotFirst]:
    expr_rule(_cls, TS.ALL_SUPPORTED)
# device collect: lists assemble from the sort+segment plan; set dedupe
# needs single-word value encoding, so string elements stay on CPU
expr_rule(eagg.CollectList, TS.ExprSig(
    [TS.ParamSig("input", TS.ALL_SUPPORTED)], TS.WITH_ARRAYS))
expr_rule(eagg.CollectSet, TS.ExprSig(
    [TS.ParamSig("input", TS.BOOLEAN + TS.NUMERIC + TS.DATETIME +
                 TS.DECIMAL_64,
                 note="string elements run on the CPU engine")],
    TS.WITH_ARRAYS))
# collection expressions (collectionOperations.scala registrations,
# GpuOverrides.scala:773+)
from ..expr import collections as ecoll  # noqa: E402
for _cls in [ecoll.CreateArray, ecoll.SortArray, ecoll.Explode]:
    expr_rule(_cls, TS.WITH_ARRAYS)
expr_rule(ecoll.GetArrayItem, TS.ExprSig(
    [_P("array", TS.WITH_ARRAYS), _P("ordinal", TS.INTEGRAL)],
    TS.WITH_ARRAYS + TS.ALL_SUPPORTED))
expr_rule(ecoll.ElementAt, TS.ExprSig(
    [_P("array", TS.WITH_ARRAYS), _P("index", TS.INTEGRAL)],
    TS.WITH_ARRAYS + TS.ALL_SUPPORTED))
expr_rule(ecoll.Size, TS.WITH_ARRAYS + TS.INTEGRAL)
# struct/map expressions (complexTypeCreator/Extractors.scala)
for _cls in [ecoll.CreateNamedStruct, ecoll.GetStructField,
             ecoll.CreateMap, ecoll.GetMapValue, ecoll.MapKeys,
             ecoll.MapValues, ecoll.ExtractValue]:
    expr_rule(_cls, TS.WITH_NESTED)
expr_rule(ecoll.ArrayContains, TS.BOOLEAN)
expr_rule(ecoll.ArrayMin, TS.NUMERIC + TS.DATETIME + TS.BOOLEAN)
expr_rule(ecoll.ArrayMax, TS.NUMERIC + TS.DATETIME + TS.BOOLEAN)

# Python UDFs stay on the columnar plan with an Arrow host exchange,
# the GpuArrowEvalPythonExec model (SURVEY.md §2.8)
from ..udf.python_udf import PythonUDF as _PyUDF, PandasUDF as _PdUDF  # noqa: E402
expr_rule(_PyUDF, TS.ALL_SUPPORTED)
expr_rule(_PdUDF, TS.ALL_SUPPORTED)
# native device UDFs (RapidsUDF.java / GpuScalaUDF role)
from ..udf.native_udf import TpuUDFExpression as _TpuUDF  # noqa: E402
expr_rule(_TpuUDF, TS.WITH_NESTED)
from ..expr import window_funcs as _wfn  # noqa: E402
for _cls in [_wfn.RowNumber, _wfn.Rank, _wfn.DenseRank, _wfn.Lead,
             _wfn.Lag]:
    expr_rule(_cls, TS.ALL_SUPPORTED)


class ExprMeta:
    """Per-expression tagging (BaseExprMeta role, RapidsMeta.scala:686)."""

    def __init__(self, expr: ec.Expression, conf: TpuConf):
        self.expr = expr
        self.conf = conf
        self.reasons: List[str] = []
        self.children = [ExprMeta(c, conf) for c in expr.children]

    # ops that canonical-key-encode their inputs: inputs must be ORDERABLE
    # scalars (the per-param TypeSig role of the reference's ExprChecks)
    _KEY_ENCODING = (ep.EqualTo, ep.EqualNullSafe, ep.LessThan,
                     ep.LessThanOrEqual, ep.GreaterThan,
                     ep.GreaterThanOrEqual, ep.In, emisc.Murmur3Hash)

    def tag(self):
        cls = type(self.expr)
        rule = _EXPR_RULES.get(cls)
        if rule is None:
            self.reasons.append(
                f"expression {cls.__name__} has no TPU implementation")
        else:
            self.reasons.extend(rule.reasons_for(self.expr))
        if isinstance(self.expr, self._KEY_ENCODING):
            for c in self.expr.children:
                try:
                    cdt = c.dtype()
                except (ValueError, NotImplementedError):
                    continue
                if not TS.ORDERABLE.supports(cdt):
                    self.reasons.append(
                        f"{cls.__name__}: input type {cdt.name} cannot be "
                        f"key-encoded on TPU")
        if isinstance(self.expr, ecast.Cast):
            src = self.expr.children[0].dtype()
            # cast-pair matrix (CastChecks role, TypeChecks.scala:367):
            # pairs absent from the matrix tag the node to the CPU
            r = TS.cast_reason(src, self.expr.to)
            if r:
                self.reasons.append(r)
            if (src == T.STRING and self.expr.to.is_fractional and
                    not self.conf.get(CAST_STRING_TO_FLOAT)):
                self.reasons.append(
                    "Cast string->float disabled: set "
                    "spark.rapids.tpu.sql.castStringToFloat.enabled=true")
        if isinstance(self.expr.dtype() if not self.reasons else None,
                      T.DecimalType) and not self.conf.get(DECIMAL_ENABLED):
            self.reasons.append("decimal support disabled by conf")
        for c in self.children:
            c.tag()

    @property
    def can_replace(self) -> bool:
        return not self.reasons and all(c.can_replace for c in self.children)

    def all_reasons(self) -> List[str]:
        out = list(self.reasons)
        for c in self.children:
            out.extend(c.all_reasons())
        return out


# ---------------------------------------------------------------------------
# plan metas
# ---------------------------------------------------------------------------

class PlanMeta:
    """SparkPlanMeta role (RapidsMeta.scala:512)."""

    def __init__(self, plan: L.LogicalPlan, conf: TpuConf):
        self.plan = plan
        self.conf = conf
        self.reasons: List[str] = []
        self.children = [PlanMeta(c, conf) for c in plan.children]
        self.expr_metas: List[ExprMeta] = [
            ExprMeta(e, conf) for e in self._expressions()]

    def _expressions(self) -> List[ec.Expression]:
        p = self.plan
        if isinstance(p, L.Project):
            return list(p.exprs)
        if isinstance(p, L.Filter):
            return [p.condition]
        if isinstance(p, L.Aggregate):
            return list(p.group_exprs) + [a.func for a in p.aggs]
        if isinstance(p, L.Join):
            out = list(p.left_keys) + list(p.right_keys)
            if p.condition is not None:
                out.append(p.condition)
            return out
        if isinstance(p, L.Sort):
            return [o.expr for o in p.orders]
        if isinstance(p, L.Repartition):
            return list(p.by_exprs or [])
        if isinstance(p, L.Generate):
            return [p.generator]
        if isinstance(p, L.GroupedMapInPandas):
            return list(p.keys)
        if isinstance(p, L.Expand):
            return [e for proj in p.projections for e in proj]
        if isinstance(p, L.Window):
            out = []
            for wf in p.window_funcs:
                out.append(wf.func)
                out.extend(wf.spec.partition_by)
                out.extend(o.expr for o in wf.spec.order_by)
            return out
        return []

    def tag(self):
        if not self.conf.get(SQL_ENABLED):
            self.reasons.append("spark.rapids.tpu.sql.enabled is false")
        for em in self.expr_metas:
            em.tag()
            self.reasons.extend(em.all_reasons())
        # per-node checks
        p = self.plan
        for f in p.schema:
            if not TS.WITH_NESTED.supports(f.dtype) and \
                    f.dtype.is_nested:
                self.reasons.append(
                    f"output column {f.name}: nested type {f.dtype.name} "
                    f"not yet device-resident")
        # array columns may flow through, but cannot be sort/group/join/
        # partition keys (canonical key words cover scalars only)
        def _keys_orderable(exprs, what):
            for e in exprs:
                try:
                    dt = e.dtype()
                except (ValueError, NotImplementedError):
                    continue
                if not TS.ORDERABLE.supports(dt):
                    self.reasons.append(
                        f"{what} key of type {dt.name} not supported on TPU")
        if isinstance(p, L.Aggregate):
            _keys_orderable(p.group_exprs, "group-by")
        if isinstance(p, L.Distinct):
            _keys_orderable(
                [ec.AttributeReference(f.name, f.dtype, f.nullable)
                 for f in p.schema], "distinct")
        if isinstance(p, L.Sort):
            _keys_orderable([o.expr for o in p.orders], "sort")
        if isinstance(p, L.Join):
            _keys_orderable(list(p.left_keys) + list(p.right_keys), "join")
        if isinstance(p, L.Repartition):
            _keys_orderable(list(p.by_exprs or []), "partition")
        if isinstance(p, L.Window):
            for wf in p.window_funcs:
                _keys_orderable(wf.spec.partition_by, "window partition")
                _keys_orderable([o.expr for o in wf.spec.order_by],
                                "window order")
        if isinstance(p, L.Window):
            from ..expr import window_funcs as wfn
            for wf in p.window_funcs:
                f = wf.func
                ok = isinstance(f, (wfn.RowNumber, wfn.Rank, wfn.DenseRank,
                                    wfn.Lead, wfn.Lag, wfn.NTile,
                                    wfn.PercentRank, wfn.CumeDist,
                                    eagg.Sum, eagg.Count,
                                    eagg.Min, eagg.Max, eagg.Average,
                                    eagg.CollectList))
                if not ok:
                    self.reasons.append(
                        f"window function {f.name} not implemented on TPU")
                if f.children and f.children[0].dtype() == T.STRING and \
                        isinstance(f, (eagg.Sum, eagg.Min, eagg.Max,
                                       eagg.Average)):
                    self.reasons.append(
                        "string window aggregates not on TPU yet")
                kind, lo, hi = wf.spec.frame
                if kind == "range" and not (lo is None and hi is None) \
                        and isinstance(f, eagg.AggregateFunction):
                    # frames only bind aggregate window functions;
                    # the rank family ignores them (Spark semantics) —
                    # SQL's default RANGE frame must not knock
                    # row_number/rank/lead/lag off the TPU
                    # bounded RANGE: rank-search covers a single
                    # integral/decimal/date/timestamp order key with
                    # sum/count/avg/min/max/collect_list
                    # (tpu_window._range_positions; the reference's own
                    # bounded-RANGE support is one numeric key,
                    # GpuWindowExpression.scala)
                    ok_range = (
                        len(wf.spec.order_by) == 1 and
                        isinstance(f, (eagg.Sum, eagg.Count,
                                       eagg.Average, eagg.Min, eagg.Max,
                                       eagg.CollectList)))
                    if ok_range:
                        odt = wf.spec.order_by[0].expr.dtype()
                        ok_range = odt.is_integral or odt in (
                            T.DATE, T.TIMESTAMP) or isinstance(
                            odt, T.DecimalType)
                    if not ok_range:
                        self.reasons.append(
                            "RANGE frame limited to one "
                            "integral/decimal/date order key on TPU")
        for c in self.children:
            c.tag()

    @property
    def can_replace(self) -> bool:
        return not self.reasons

    # -- explain (RapidsMeta.explain role) ---------------------------------
    def explain(self, all_nodes: bool = False, indent: int = 0) -> str:
        pad = "  " * indent
        mark = "*" if self.can_replace else "!"
        line = f"{pad}{mark} {self.plan._node_string()}"
        if not self.can_replace:
            for r in self.reasons:
                line += f"\n{pad}    cannot run on TPU: {r}"
        out = [line] if (all_nodes or not self.can_replace) else []
        for c in self.children:
            sub = c.explain(all_nodes, indent + 1)
            if sub:
                out.append(sub)
        return "\n".join(out)


# ---------------------------------------------------------------------------
# conversion
# ---------------------------------------------------------------------------

def _as_columnar(p: PhysicalPlan) -> PhysicalPlan:
    return p if p.columnar else TB.RowToColumnar(p)


def _as_cpu(p: PhysicalPlan) -> PhysicalPlan:
    return TB.ColumnarToRow(p) if p.columnar else p


class Planner:
    """applyOverrides + transitions, producing an executable physical plan."""

    def __init__(self, conf: TpuConf):
        self.conf = conf
        self.default_partitions = conf.get(SHUFFLE_PARTITIONS)
        self.batch_rows = conf.get(BATCH_SIZE_ROWS)
        self.fallbacks: List[str] = []
        # plan decisions that silently REDUCE parallelism (a coalesce
        # to one partition): surfaced in explain + logged, so a query
        # that just went single-stream says so (round-3 Weak #9)
        self.parallelism_warnings: List[str] = []
        self._placement = None

    def _warn_collapse(self, why: str):
        self.parallelism_warnings.append(why)
        import logging
        logging.getLogger(__name__).warning(
            "parallelism collapse: %s (plan coalesces to ONE "
            "partition)", why)

    def plan(self, logical: L.LogicalPlan, *,
             skip_verify: bool = False) -> PhysicalPlan:
        # ``skip_verify=True`` is the plan cache's certificate-replay
        # path (cache/plan_cache.py): the full structural pipeline
        # still runs on the INCOMING logical plan (fresh literals are
        # correct by construction), but the invariant verifier passes
        # are skipped because the cached entry carries the verdict of a
        # fingerprint-identical plan — the caller MUST validate the
        # rebuilt plan_fingerprint against the stored one before
        # trusting the result.
        #
        # ColumnPruning (Catalyst does this before the reference plugin
        # sees the plan): narrow file scans to referenced columns so the
        # readers neither decode nor upload dead columns
        from .logical_opt import prune_scan_columns
        logical = prune_scan_columns(logical)
        meta = PlanMeta(logical, self.conf)
        meta.tag()
        from ..config import CBO_ENABLED
        self._placement = None
        if self.conf.get(CBO_ENABLED):
            from .cbo import choose_placement
            self._placement = choose_placement(logical, self.conf)
        mode = self.conf.get(EXPLAIN).upper()
        explain_on = mode in ("NOT_ON_TPU", "ALL")
        if explain_on:
            text = meta.explain(all_nodes=(mode == "ALL"))
            if text:
                print(text)
        phys = self._convert(meta)
        if explain_on:
            for w in self.parallelism_warnings:
                print(f"! parallelism: {w}")
        phys = self._collapse_stages(phys)
        self._mark_deferred_verify(phys, parent=None)
        if self.conf.get(TEST_ENABLED):
            self._assert_all_tpu(phys)
        from ..config import PLAN_VERIFY
        verify_on = (not skip_verify) and (
            self.conf.get(PLAN_VERIFY) or os.environ.get(
                "SPARK_RAPIDS_TPU_FORCE_PLAN_VERIFY"))
        if verify_on:
            from ..analysis.plan_verify import verify_or_raise
            verify_or_raise(phys)
        # superstage carving is a post-pass over the VERIFIED plan: it
        # only rearranges dispatch (wrappers + sync-free flags), so the
        # invariant passes above see the uncarved operator tree and the
        # PV-STAGE re-verify below checks the carving contracts
        from ..config import SUPERSTAGE
        if self.conf.get(SUPERSTAGE):
            from ..compile import carve_plan
            phys = carve_plan(phys, self.conf)
            if verify_on:
                from ..analysis.plan_verify import STAGE, verify_or_raise
                verify_or_raise(phys, passes=[STAGE])
        return phys

    # -- deferred-verification marking ------------------------------------
    def _mark_deferred_verify(self, node: PhysicalPlan, parent):
        """Allow a FINAL/COMPLETE aggregate to hand its speculative fit
        flag and unresolved group count to the NEXT flush barrier
        instead of forcing a round trip of its own — but only when its
        direct consumer provably verifies: the session collect (root),
        an exchange (verify-at-flush), or a hash join (verifies stream
        batches after its phase-A flush).  Everything else — including
        projections, which re-evaluate columns into fresh batches and
        would silently DROP the speculative flag — consumes the batch
        without verifying, so the aggregate keeps its own barrier
        there."""
        from ..exec import tpu_aggregate as TA
        from ..exec import tpu_join as TJ
        from ..exec import exchange as TX
        from ..exec import tpu_sort as TS
        safe_types = [TX.TpuShuffleExchange,
                      TX.TpuBroadcastExchange,
                      TJ.TpuHashJoinBase,
                      # TopN re-attaches the speculative
                      # flag to its own (sorted, head-n)
                      # output with a redo chain, so the
                      # verify rides the NEXT barrier
                      TS.TpuTopN]
        from ..config import SUPERSTAGE
        if self.conf.get(SUPERSTAGE):
            # superstage mode: TpuSort resolves speculative inputs at
            # its own count pull (same fused flush), so an aggregate
            # under a sort may defer too — the quartet's agg->sort edge
            safe_types.append(TS.TpuSort)
        safe = parent is None or isinstance(parent, tuple(safe_types))
        if isinstance(node, TA.TpuHashAggregate) and \
                node.mode in (TA.FINAL, TA.COMPLETE):
            node.allow_deferred_verify = safe
        for c in node.children:
            self._mark_deferred_verify(c, parent=node)

    # -- whole-stage collapse (GpuTransitionOverrides-style post-pass) ----
    def _collapse_stages(self, node: PhysicalPlan) -> PhysicalPlan:
        """Fuse TpuFilter/TpuProject chains into TpuStagedCompute, and
        fold a leading chain into the hash aggregate's fused core — one
        program launch per batch per stage (exec/staged.py)."""
        from ..exec.staged import TpuStagedCompute
        from ..exec import tpu_aggregate as TA
        node.children = [self._collapse_stages(c) for c in node.children]
        chain = []
        cur = node
        while isinstance(cur, (TB.TpuFilter, TB.TpuProject)):
            chain.append(cur)
            cur = cur.children[0]
        # children were collapsed first, so an already-built staged node
        # below the chain merges in (a 3+-op chain must stay ONE launch)
        absorbed = None
        if chain and isinstance(cur, TpuStagedCompute):
            absorbed = cur
            cur = cur.children[0]
        if len(chain) >= 2 or (chain and absorbed is not None):
            ops = list(absorbed.ops) if absorbed is not None else []
            for n in reversed(chain):
                src = n.children[0].output_schema
                if isinstance(n, TB.TpuFilter):
                    ops.append(("filter", n.condition.bind(src),
                                n.output_schema))
                else:
                    ops.append(("project",
                                [e.bind(src) for e in n.exprs],
                                n.output_schema))
            node = TpuStagedCompute(cur, ops, cur.output_schema)
        if isinstance(node, TA.TpuHashAggregate) and \
                node.mode in (TA.PARTIAL, TA.COMPLETE):
            child = node.children[0]
            ops = None
            if isinstance(child, TpuStagedCompute):
                ops = child.ops
                src = child.children[0]
            elif isinstance(child, (TB.TpuFilter, TB.TpuProject)):
                s = child.children[0].output_schema
                if isinstance(child, TB.TpuFilter):
                    ops = [("filter", child.condition.bind(s),
                            child.output_schema)]
                else:
                    ops = [("project", [e.bind(s) for e in child.exprs],
                            child.output_schema)]
                src = child.children[0]
            if ops is not None:
                node.pre_ops = ops
                node.children = [src]
        return node

    # ------------------------------------------------------------------
    def _convert(self, meta: PlanMeta) -> PhysicalPlan:
        p = meta.plan
        if not meta.can_replace:
            self.fallbacks.append(
                f"{p.name}: {'; '.join(meta.reasons[:3])}")
            return self._convert_cpu(meta)
        if self._placement is not None and \
                self._placement.get(id(p)) == "cpu":
            self.fallbacks.append(
                f"{p.name}: cost model placed this subtree on CPU "
                f"(transition-aware placement)")
            return self._convert_cpu(meta)
        children = [self._convert(c) for c in meta.children]
        return self._convert_tpu(meta, p, children)

    def _convert_cpu(self, meta: PlanMeta) -> PhysicalPlan:
        """Run this node on the CPU engine; children still plan normally."""
        p = meta.plan
        children = [_as_cpu(self._convert(c)) for c in meta.children]
        if isinstance(p, L.LocalRelation):
            return X.CpuLocalScan(p.table, p.num_partitions)
        if isinstance(p, L.Range):
            return X.CpuRange(p.start, p.end, p.step, p.num_partitions)
        if isinstance(p, L.Project):
            return X.CpuProject(p.exprs, children[0])
        if isinstance(p, L.Filter):
            return X.CpuFilter(p.condition, children[0])
        if isinstance(p, L.Aggregate):
            return X.CpuAggregate(p.group_exprs, p.aggs, children[0])
        if isinstance(p, L.Join):
            return X.CpuJoin(p, children[0], children[1])
        if isinstance(p, L.Sort):
            return X.CpuSort(p.orders, children[0], p.is_global)
        if isinstance(p, L.Limit):
            return X.CpuLimit(p.n, children[0], p.offset)
        if isinstance(p, L.Union):
            return X.CpuUnion(*children)
        if isinstance(p, L.Distinct):
            agg = L.Aggregate(
                [ec.AttributeReference(f.name, f.dtype, f.nullable)
                 for f in p.schema], [], p.children[0])
            return X.CpuAggregate(agg.group_exprs, [], children[0])
        if isinstance(p, L.Repartition):
            return X.CpuShuffleExchange(children[0], p.num_partitions,
                                        p.by_exprs)
        if isinstance(p, L.Window):
            from ..exec.cpu_window import CpuWindow
            return CpuWindow(p, children[0])
        if isinstance(p, L.Generate):
            return X.CpuGenerate(p, children[0])
        if isinstance(p, L.Expand):
            return X.CpuExpand(p, children[0])
        if isinstance(p, L.CachedRelation):
            from ..exec.cache import CpuCachedExec
            return CpuCachedExec(p.storage, children[0])
        if isinstance(p, L.MapInPandas):
            from ..exec.python_exec import CpuMapInPandas
            return CpuMapInPandas(p, children[0])
        if isinstance(p, L.GroupedMapInPandas):
            from ..exec.python_exec import CpuGroupedMapInPandas
            return CpuGroupedMapInPandas(p, children[0])
        if isinstance(p, L.CogroupedMapInPandas):
            from ..exec.python_exec import CpuCogroupedMapInPandas
            return CpuCogroupedMapInPandas(p, children[0], children[1])
        if isinstance(p, L.WindowInPandas):
            from ..exec.python_exec import CpuWindowInPandas
            return CpuWindowInPandas(p, children[0])
        if isinstance(p, L.Scan):
            from ..io.planner import cpu_scan_exec
            return cpu_scan_exec(p, self.conf)
        if isinstance(p, L.WriteFile):
            from ..io.planner import cpu_write_exec
            return cpu_write_exec(p, _as_cpu(children[0]), self.conf)
        raise NotImplementedError(f"no CPU conversion for {p.name}")

    # ------------------------------------------------------------------
    def _convert_tpu(self, meta: PlanMeta, p: L.LogicalPlan,
                     children: List[PhysicalPlan]) -> PhysicalPlan:
        children = [_as_columnar(c) for c in children]
        if isinstance(p, L.LocalRelation):
            return TB.TpuLocalScan(p.table, p.num_partitions,
                                   self.batch_rows)
        if isinstance(p, L.Range):
            return TB.TpuRange(p.start, p.end, p.step, p.num_partitions,
                               self.batch_rows)
        if isinstance(p, L.Scan):
            from ..io.planner import tpu_scan_exec
            return tpu_scan_exec(p, self.conf)
        if isinstance(p, L.Project):
            return TB.TpuProject(p.exprs, children[0])
        if isinstance(p, L.Filter):
            child = children[0]
            if isinstance(p.children[0], L.Scan) and \
                    p.children[0].fmt == "parquet":
                from ..io.pushdown import to_arrow_filters
                pushed = to_arrow_filters(p.condition)
                if pushed and hasattr(child, "set_pushed_filters"):
                    child.set_pushed_filters(pushed)
            return TB.TpuFilter(p.condition, child)
        if isinstance(p, L.Aggregate):
            return self._plan_aggregate(p, children[0])
        if isinstance(p, L.Distinct):
            keys = [ec.AttributeReference(f.name, f.dtype, f.nullable)
                    for f in p.schema]
            agg = L.Aggregate(keys, [], p.children[0])
            return self._plan_aggregate(agg, children[0])
        if isinstance(p, L.Join):
            return self._plan_join(p, children[0], children[1])
        if isinstance(p, L.Sort):
            return self._plan_sort(p, children[0])
        if isinstance(p, L.Limit):
            child = p.children[0]
            if isinstance(child, L.Sort) and child.is_global and \
                    p.offset == 0:
                # fuse into TopN over the sort's input
                return TSOR.TpuTopN(p.n, child.orders, children[0].children[0]
                                    if isinstance(children[0], TSOR.TpuSort)
                                    else children[0])
            local = TB.TpuLocalLimit(p.n + p.offset, children[0])
            return TB.TpuGlobalLimit(p.n, EX.TpuCoalescePartitions(local),
                                     p.offset)
        if isinstance(p, L.Union):
            return TB.TpuUnion(*children)
        if isinstance(p, L.Repartition):
            if p.by_exprs:
                part = HashPartitioner(p.by_exprs, p.num_partitions)
            else:
                part = RoundRobinPartitioner(p.num_partitions)
            return EX.TpuShuffleExchange(children[0], part)
        if isinstance(p, L.WriteFile):
            from ..io.planner import tpu_write_exec
            return tpu_write_exec(p, children[0], self.conf)
        if isinstance(p, L.Window):
            return self._plan_window(p, children[0])
        if isinstance(p, L.Expand):
            from ..exec.tpu_expand import TpuExpand
            return TpuExpand(p, children[0])
        if isinstance(p, L.Generate):
            from ..exec.tpu_generate import TpuGenerate
            return TpuGenerate(p, children[0])
        if isinstance(p, L.CachedRelation):
            from ..exec.cache import TpuCachedExec
            return TpuCachedExec(p.storage, children[0])
        if isinstance(p, L.MapInPandas):
            from ..exec.python_exec import TpuMapInPandas
            return TpuMapInPandas(p, children[0])
        if isinstance(p, L.CogroupedMapInPandas):
            from ..exec.python_exec import TpuCogroupedMapInPandas
            return TpuCogroupedMapInPandas(p, children[0], children[1])
        if isinstance(p, L.WindowInPandas):
            from ..exec.python_exec import TpuWindowInPandas
            return TpuWindowInPandas(p, children[0])
        if isinstance(p, L.GroupedMapInPandas):
            from ..exec.python_exec import TpuGroupedMapInPandas
            return TpuGroupedMapInPandas(p, children[0])
        raise NotImplementedError(f"no TPU conversion for {p.name}")

    def _plan_window(self, p: L.Window, child: PhysicalPlan) -> PhysicalPlan:
        from ..exec.tpu_window import TpuWindow
        nparts = child.num_partitions_hint()
        pby = p.window_funcs[0].spec.partition_by
        same_keys = all(
            [repr(e) for e in wf.spec.partition_by] ==
            [repr(e) for e in pby] for wf in p.window_funcs)
        if nparts > 1:
            if pby and same_keys:
                part = HashPartitioner(pby, min(self.default_partitions,
                                                nparts))
                child = self._aqe_read(EX.TpuShuffleExchange(child, part))
            else:
                self._warn_collapse(
                    "window functions with "
                    + ("mixed partition keys" if pby else
                       "no PARTITION BY")
                    + " run single-stream")
                child = EX.TpuCoalescePartitions(child)
        return TpuWindow(p, child)

    def _aqe_read(self, exchange):
        """Wrap an exchange in a coalescing AQE read when enabled
        (GpuCustomShuffleReaderExec insertion, GpuTransitionOverrides
        role)."""
        if not self.conf.get(ADAPTIVE_ENABLED):
            return exchange
        from ..exec.adaptive import TpuAQEShuffleRead
        return TpuAQEShuffleRead(
            exchange, self.conf.get(ADAPTIVE_TARGET_PARTITION_BYTES))

    # -- aggregate: partial -> exchange -> final (aggregate.scala modes) ---
    def _plan_aggregate_mesh(self, p: L.Aggregate, child):
        """shuffle.mode=mesh: the whole group-by as one SPMD program
        (exec/tpu_mesh_aggregate.py) when the shapes allow it."""
        import jax
        from ..exec.tpu_mesh_aggregate import (TpuMeshAggregate,
                                               mesh_aggregate_supported)
        if self.conf.get(SHUFFLE_MODE) != "mesh":
            return None
        try:
            n_dev = jax.device_count()
        except Exception:
            return None
        if not mesh_aggregate_supported(p, n_dev):
            return None
        return TpuMeshAggregate(p, child)

    def _plan_aggregate(self, p: L.Aggregate,
                        child: PhysicalPlan) -> PhysicalPlan:
        mesh_plan = self._plan_aggregate_mesh(p, child)
        if mesh_plan is not None:
            return mesh_plan
        nparts = child.num_partitions_hint()
        if nparts <= 1:
            return TA.TpuHashAggregate(p.group_exprs, p.aggs, child,
                                       mode=TA.COMPLETE)
        partial = TA.TpuHashAggregate(p.group_exprs, p.aggs, child,
                                      mode=TA.PARTIAL)
        buf_schema = partial.output_schema
        if p.group_exprs:
            keys = [ec.AttributeReference(f.name, f.dtype, f.nullable)
                    for f in list(buf_schema)[:len(p.group_exprs)]]
            n = min(self._pick_partitions(p), nparts)
            part = HashPartitioner(keys, n)
            shuffled: PhysicalPlan = self._aqe_read(
                EX.TpuShuffleExchange(partial, part))
        else:
            shuffled = EX.TpuCoalescePartitions(partial)
        return TA.TpuHashAggregate(p.group_exprs, p.aggs, shuffled,
                                   mode=TA.FINAL)

    # -- join strategy selection (GpuOverrides join metas role) ------------
    def _plan_join(self, p: L.Join, left: PhysicalPlan,
                   right: PhysicalPlan) -> PhysicalPlan:
        if p.join_type == "cross" or not p.left_keys:
            return TJ.TpuNestedLoopJoin(p, left, right)
        mesh_plan = self._plan_join_mesh(p, left, right)
        if mesh_plan is not None:
            return mesh_plan
        lsize = self._estimate_rows(p.children[0])
        rsize = self._estimate_rows(p.children[1])
        build_right = p.join_type != "right"
        # inner joins may build on EITHER side: pick the smaller one
        # (GpuShuffledHashJoinMeta's buildSide choice) — building the
        # fact side of a star join forces a full fact-table shuffle
        # where building the dimension side broadcasts it
        if p.join_type == "inner" and lsize is not None and \
                rsize is not None:
            build_right = rsize <= lsize
        # broadcast the build side when it is provably small
        build_size = rsize if build_right else lsize
        if build_size is not None and build_size <= BROADCAST_ROW_THRESHOLD \
                and p.join_type not in ("full",):
            if build_right:
                bcast = EX.TpuBroadcastExchange(right)
                return TJ.TpuBroadcastHashJoin(p, left, bcast,
                                               build_right=True)
            bcast = EX.TpuBroadcastExchange(left)
            return TJ.TpuBroadcastHashJoin(p, bcast, right,
                                           build_right=False)
        n = self._pick_partitions(p.children[0], p.children[1])
        if self.conf.get(ADAPTIVE_ENABLED):
            from ..exec.adaptive import TpuAdaptiveShuffledJoin
            return TpuAdaptiveShuffledJoin(
                p, left, right, build_right=build_right, num_partitions=n,
                broadcast_bytes=self.conf.get(ADAPTIVE_BROADCAST_BYTES),
                target_bytes=self.conf.get(ADAPTIVE_TARGET_PARTITION_BYTES),
                skew_factor=self.conf.get(ADAPTIVE_SKEW_FACTOR),
                skew_min_bytes=self.conf.get(ADAPTIVE_SKEW_MIN_BYTES))
        lpart = HashPartitioner(p.left_keys, n)
        rpart = HashPartitioner(p.right_keys, n)
        lex = EX.TpuShuffleExchange(left, lpart)
        rex = EX.TpuShuffleExchange(right, rpart)
        return TJ.TpuShuffledHashJoin(p, lex, rex, build_right=build_right)

    def _estimate_rows(self, p: L.LogicalPlan) -> Optional[int]:
        if isinstance(p, L.LocalRelation):
            return p.table.num_rows
        if isinstance(p, L.Range):
            return max(0, -(-(p.end - p.start) // p.step))
        if isinstance(p, (L.Project, L.Filter, L.Sort, L.Window)):
            return self._estimate_rows(p.children[0])
        if isinstance(p, L.Limit):
            return p.n
        if isinstance(p, L.Scan):
            return _scan_row_estimate(p, self.conf)
        if isinstance(p, L.Join):
            l = self._estimate_rows(p.children[0])
            r = self._estimate_rows(p.children[1])
            if l is None or r is None:
                return None
            return max(l, r)
        if isinstance(p, L.Aggregate):
            return self._estimate_rows(p.children[0])
        return None

    def _pick_partitions(self, *plans: L.LogicalPlan) -> int:
        """Exchange width from size estimates: avoid many tiny partitions

        (each distinct slice size is a separate XLA compilation)."""
        est = 0
        for p in plans:
            r = self._estimate_rows(p)
            if r is None:
                return self.default_partitions
            est = max(est, r)
        need = max(1, -(-est // max(self.batch_rows, 1)))
        return max(1, min(self.default_partitions, need))

    # -- mesh-collective join/sort (shuffle.mode=mesh) ---------------------
    def _plan_join_mesh(self, p: L.Join, left, right):
        """shuffle.mode=mesh: the whole shuffled equi-join as one SPMD
        program (exec/tpu_mesh_join.py) when the shapes allow it."""
        if self.conf.get(SHUFFLE_MODE) != "mesh":
            return None
        import jax
        from ..exec.tpu_mesh_join import (TpuMeshShuffledJoin,
                                          mesh_join_supported)
        n_dev = len(jax.devices())
        if not mesh_join_supported(p, n_dev):
            return None
        return TpuMeshShuffledJoin(p, left, right)

    def _plan_sort_mesh(self, p: L.Sort, child):
        """shuffle.mode=mesh: sample-splitter global sort as one SPMD
        program (exec/tpu_mesh_sort.py) when the shapes allow it."""
        if self.conf.get(SHUFFLE_MODE) != "mesh":
            return None
        import jax
        from ..exec.tpu_mesh_sort import TpuMeshSort, mesh_sort_supported
        n_dev = len(jax.devices())
        if not mesh_sort_supported(p, n_dev):
            return None
        return TpuMeshSort(p.orders, child)

    # -- global sort: range exchange + local sort --------------------------
    def _plan_sort(self, p: L.Sort, child: PhysicalPlan) -> PhysicalPlan:
        if p.is_global:
            mesh_plan = self._plan_sort_mesh(p, child)
            if mesh_plan is not None:
                return mesh_plan
        nparts = child.num_partitions_hint()
        if not p.is_global or nparts <= 1:
            return TSOR.TpuSort(p.orders, child)
        part = RangePartitioner(p.orders, nparts)
        ex = EX.TpuShuffleExchange(child, part)
        return TSOR.TpuSort(p.orders, ex)

    # -- test-mode assertion (spark.rapids.sql.test.enabled role) ----------
    def _assert_all_tpu(self, phys: PhysicalPlan):
        allowed = set(self.conf.allowed_non_tpu)
        bad = [n.name for n in phys.collect_nodes()
               if not n.columnar and n.name not in allowed
               and not isinstance(n, TB.ColumnarToRow)]
        if bad:
            raise AssertionError(
                f"test mode: operators fell back to CPU: {bad}; "
                f"fallback reasons: {self.fallbacks}")
