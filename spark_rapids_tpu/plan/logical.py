"""Logical plan IR.

Role note: the reference is a plugin over Spark Catalyst, so its "logical
plan" arrives from Spark.  This standalone framework owns the front end:
the DataFrame API (api/dataframe.py) builds these nodes, and the planner
(plan/overrides.py) wraps/tags/converts them into physical operators —
exactly the GpuOverrides wrap->tag->convert pipeline (GpuOverrides.scala:3100),
with the CPU (pyarrow) engine playing the role of stock Spark operators.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from ..columnar import dtypes as T
from ..columnar.schema import Field, Schema
from ..expr.core import Expression, AttributeReference, output_name
from ..expr.aggregates import AggregateFunction


class LogicalPlan:
    children: List["LogicalPlan"] = []

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    @property
    def name(self):
        return type(self).__name__

    def __repr__(self):
        return self._tree_string()

    def _tree_string(self, indent=0):
        s = "  " * indent + self._node_string()
        for c in self.children:
            s += "\n" + c._tree_string(indent + 1)
        return s

    def _node_string(self):
        return self.name


class LocalRelation(LogicalPlan):
    """In-memory data (pa.Table), possibly pre-split into partitions."""

    def __init__(self, table, num_partitions: int = 1):
        import pyarrow as pa
        assert isinstance(table, pa.Table)
        self.table = table
        self.num_partitions = num_partitions
        self.children = []

    @property
    def schema(self):
        from ..columnar.arrow import schema_from_arrow
        return schema_from_arrow(self.table.schema)

    def _node_string(self):
        return f"LocalRelation[rows={self.table.num_rows}]"


class Range(LogicalPlan):
    def __init__(self, start: int, end: int, step: int = 1,
                 num_partitions: int = 1):
        self.start, self.end, self.step = start, end, step
        self.num_partitions = num_partitions
        self.children = []

    @property
    def schema(self):
        return Schema([Field("id", T.INT64, nullable=False)])

    def _node_string(self):
        return f"Range({self.start}, {self.end}, {self.step})"


class Scan(LogicalPlan):
    """File scan (parquet/csv/orc) — reference: GpuParquetScan et al."""

    def __init__(self, fmt: str, paths: List[str], schema: Schema,
                 options: Optional[Dict[str, Any]] = None,
                 pushed_filters: Optional[List[Expression]] = None):
        self.fmt = fmt
        self.paths = paths
        self._schema = schema
        self.options = options or {}
        self.pushed_filters = pushed_filters or []
        self.children = []

    @property
    def schema(self):
        return self._schema

    def _node_string(self):
        return f"Scan[{self.fmt}]({len(self.paths)} files)"


class Project(LogicalPlan):
    def __init__(self, exprs: List[Expression], child: LogicalPlan):
        self.exprs = exprs
        self.children = [child]

    @property
    def schema(self):
        return Schema([Field(output_name(e), e.dtype(), e.nullable)
                       for e in self.exprs])

    def _node_string(self):
        return f"Project[{', '.join(output_name(e) for e in self.exprs)}]"


class Filter(LogicalPlan):
    def __init__(self, condition: Expression, child: LogicalPlan):
        self.condition = condition
        self.children = [child]

    @property
    def schema(self):
        return self.children[0].schema

    def _node_string(self):
        return f"Filter[{self.condition!r}]"


@dataclasses.dataclass
class AggExpr:
    func: AggregateFunction
    alias: str
    distinct: bool = False


def build_aggregate(group_exprs, aggs: List["AggExpr"],
                    child: "LogicalPlan") -> "LogicalPlan":
    """Aggregate builder handling DISTINCT aggregate functions.

    Reference: Spark rewrites distinct aggregates before the plugin sees
    them (RewriteDistinctAggregates); standalone, the rewrite lives here:
    a two-level aggregate — inner groups by (keys, distinct-arg) while
    partially aggregating the non-distinct functions, outer re-aggregates
    with the distinct functions applied to the now-unique arg.  All
    distinct functions must share one argument expression (the common
    count(distinct x) case; multi-arg distinct needs the Expand rewrite).
    """
    from ..expr import aggregates as eagg
    from ..expr import core as ec
    if not any(a.distinct for a in aggs):
        return Aggregate(group_exprs, aggs, child)
    dargs = {repr(a.func.children[0]) for a in aggs if a.distinct}
    if len(dargs) != 1:
        raise NotImplementedError(
            "DISTINCT aggregates must share one argument expression")
    dexpr = next(a.func.children[0] for a in aggs if a.distinct)
    dalias = "__distinct_key"

    key_names = [output_name(e) for e in group_exprs]
    inner_keys = list(group_exprs) + [ec.Alias(dexpr, dalias)]
    inner_aggs: List[AggExpr] = []
    outer_aggs: List[AggExpr] = []
    final_exprs: List[ec.Expression] = []

    def key_ref(plan_schema_name, dtype, nullable=True):
        return ec.AttributeReference(plan_schema_name, dtype, nullable)

    for e, name in zip(group_exprs, key_names):
        final_exprs.append(ec.AttributeReference(name, e.dtype(),
                                                 e.nullable))
    dref = ec.AttributeReference(dalias, dexpr.dtype(), True)
    for i, a in enumerate(aggs):
        f = a.func
        if a.distinct:
            outer_aggs.append(AggExpr(f.with_children([dref]), a.alias))
            final_exprs.append(ec.AttributeReference(a.alias, f.dtype(),
                                                     True))
            continue
        pname = f"__p{i}"
        if isinstance(f, (eagg.Sum, eagg.Min, eagg.Max, eagg.First,
                          eagg.Last)):
            inner_aggs.append(AggExpr(f, pname))
            pref = ec.AttributeReference(pname, f.dtype(), True)
            merge = {eagg.Sum: eagg.Sum, eagg.Min: eagg.Min,
                     eagg.Max: eagg.Max, eagg.First: eagg.First,
                     eagg.Last: eagg.Last}[type(f)](pref)
            outer_aggs.append(AggExpr(merge, a.alias))
            final_exprs.append(ec.AttributeReference(a.alias, f.dtype(),
                                                     True))
        elif isinstance(f, eagg.Count):
            inner_aggs.append(AggExpr(f, pname))
            pref = ec.AttributeReference(pname, f.dtype(), False)
            outer_aggs.append(AggExpr(eagg.Sum(pref), a.alias))
            from ..expr import conditional as econd
            from ..expr.cast import Cast
            from ..columnar import dtypes as T
            final_exprs.append(ec.Alias(econd.Coalesce(
                Cast(ec.AttributeReference(a.alias, T.INT64, True),
                     T.INT64), ec.Literal(0)), a.alias))
        elif isinstance(f, eagg.Average):
            sname, cname = f"__ps{i}", f"__pc{i}"
            arg = f.children[0]
            inner_aggs.append(AggExpr(eagg.Sum(arg), sname))
            inner_aggs.append(AggExpr(eagg.Count(arg), cname))
            sref = ec.AttributeReference(sname, eagg.Sum(arg).dtype(),
                                         True)
            cref = ec.AttributeReference(cname, eagg.Count(arg).dtype(),
                                         False)
            outer_aggs.append(AggExpr(eagg.Sum(sref), f"__s{i}"))
            outer_aggs.append(AggExpr(eagg.Sum(cref), f"__c{i}"))
            from ..expr import arithmetic as ea
            from ..expr.cast import Cast
            from ..columnar import dtypes as T
            final_exprs.append(ec.Alias(ea.Divide(
                Cast(ec.AttributeReference(f"__s{i}", sref.dtype(), True),
                     T.FLOAT64),
                Cast(ec.AttributeReference(f"__c{i}", cref.dtype(), True),
                     T.FLOAT64)), a.alias))
        else:
            raise NotImplementedError(
                f"{f.name} cannot combine with DISTINCT aggregates")

    inner = Aggregate(inner_keys, inner_aggs, child)
    outer_keys = []
    for e, name in zip(group_exprs, key_names):
        outer_keys.append(ec.AttributeReference(name, e.dtype(),
                                                e.nullable))
    outer = Aggregate(outer_keys, outer_aggs, inner)
    return Project(final_exprs, outer)


class Aggregate(LogicalPlan):
    def __init__(self, group_exprs: List[Expression], aggs: List[AggExpr],
                 child: LogicalPlan):
        self.group_exprs = group_exprs
        self.aggs = aggs
        self.children = [child]

    @property
    def schema(self):
        fields = [Field(output_name(e), e.dtype(), e.nullable)
                  for e in self.group_exprs]
        fields += [Field(a.alias, a.func.dtype(), a.func.nullable)
                   for a in self.aggs]
        return Schema(fields)

    def _node_string(self):
        return (f"Aggregate[keys={[output_name(e) for e in self.group_exprs]},"
                f" aggs={[a.alias for a in self.aggs]}]")


def build_grouping_sets(group_cols, sets, aggs: List["AggExpr"],
                        child: "LogicalPlan",
                        keep_gid: bool = False) -> "LogicalPlan":
    """GROUP BY ROLLUP/CUBE/GROUPING SETS via the Expand exec.

    Reference: Spark lowers grouping sets to Expand (one projection per
    set, absent keys null-filled, plus a grouping id) before the plugin
    replaces it with GpuExpandExec; the same rewrite lives here.
    group_cols must be plain column references.
    """
    from ..expr import core as ec
    from ..columnar import dtypes as T

    key_names = [output_name(e) for e in group_cols]
    gid_name = "__gid"

    # Pre-project: every grouping key AND every aggregate input gets its
    # own column.  Aggregate inputs must NOT read the null-filled key
    # copies (Spark's Expand rewrite does the same separation), and
    # expression keys become named columns here.
    pre_exprs: List[Expression] = []
    key_fields: List[Field] = []
    for e, n in zip(group_cols, key_names):
        pre_exprs.append(e if isinstance(e, ec.Alias) else ec.Alias(e, n))
        key_fields.append(Field(n, e.dtype(), True))
    ain_fields: List[Field] = []
    aggs2: List[AggExpr] = []
    for i, a in enumerate(aggs):
        new_children = []
        for j, chx in enumerate(a.func.children):
            nm = f"__ain{i}_{j}"
            pre_exprs.append(ec.Alias(chx, nm))
            new_children.append(ec.AttributeReference(nm, chx.dtype(),
                                                      True))
            ain_fields.append(Field(nm, chx.dtype(), True))
        f2 = a.func.with_children(new_children) if a.func.children \
            else a.func
        aggs2.append(AggExpr(f2, a.alias, a.distinct))
    base = Project(pre_exprs, child)

    projections: List[List[Expression]] = []
    for gid, s in enumerate(sets):
        proj: List[Expression] = []
        for f in key_fields:
            if f.name in s:
                proj.append(ec.AttributeReference(f.name, f.dtype, True))
            else:
                proj.append(ec.Alias(ec.Literal(None, f.dtype), f.name))
        for f in ain_fields:
            proj.append(ec.AttributeReference(f.name, f.dtype, True))
        proj.append(ec.Alias(ec.Literal(gid), gid_name))
        projections.append(proj)
    out_fields = key_fields + ain_fields + [Field(gid_name, T.INT64,
                                                  False)]
    expand = Expand(projections, Schema(out_fields), base)

    keys2 = [ec.AttributeReference(f.name, f.dtype, True)
             for f in key_fields]
    keys2.append(ec.AttributeReference(gid_name, T.INT64, False))
    agg = build_aggregate(keys2, aggs2, expand)
    final = [ec.AttributeReference(f.name, f.dtype, True)
             for f in key_fields]
    final += [ec.AttributeReference(a.alias, a.func.dtype(), True)
              for a in aggs]
    if keep_gid:
        # grouping() indicator expressions read the set id downstream
        final.append(ec.AttributeReference(gid_name, T.INT64, False))
    return Project(final, agg)


def rollup_sets(names: List[str]) -> List[tuple]:
    return [tuple(names[:i]) for i in range(len(names), -1, -1)]


def cube_sets(names: List[str]) -> List[tuple]:
    import itertools
    out = []
    for r in range(len(names), -1, -1):
        out.extend(itertools.combinations(names, r))
    return out


JOIN_TYPES = ("inner", "left", "right", "full", "semi", "anti", "cross")


class Join(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 join_type: str, left_keys: List[Expression],
                 right_keys: List[Expression],
                 condition: Optional[Expression] = None):
        assert join_type in JOIN_TYPES, join_type
        self.join_type = join_type
        # analyzer-role coercion: key pairs must share one dtype or their
        # canonical key words are not comparable across sides
        from ..expr.predicates import promote_comparison_sides
        lk, rk = [], []
        for le, re in zip(left_keys, right_keys):
            le, re = promote_comparison_sides(le, re)
            lk.append(le)
            rk.append(re)
        self.left_keys = lk
        self.right_keys = rk
        self.condition = condition
        self.children = [left, right]

    @property
    def schema(self):
        left, right = self.children
        if self.join_type in ("semi", "anti"):
            return left.schema
        lfields = list(left.schema.fields)
        rfields = list(right.schema.fields)
        if self.join_type in ("left", "full"):
            rfields = [Field(f.name, f.dtype, True) for f in rfields]
        if self.join_type in ("right", "full"):
            lfields = [Field(f.name, f.dtype, True) for f in lfields]
        return Schema(lfields + rfields)

    def _node_string(self):
        return f"Join[{self.join_type}]"


@dataclasses.dataclass
class SortOrder:
    expr: Expression
    ascending: bool = True
    nulls_first: Optional[bool] = None  # default: asc->first, desc->last

    @property
    def effective_nulls_first(self) -> bool:
        if self.nulls_first is None:
            return self.ascending
        return self.nulls_first


class Sort(LogicalPlan):
    def __init__(self, orders: List[SortOrder], child: LogicalPlan,
                 is_global: bool = True):
        self.orders = orders
        self.is_global = is_global
        self.children = [child]

    @property
    def schema(self):
        return self.children[0].schema

    def _node_string(self):
        return f"Sort[global={self.is_global}]"


class Limit(LogicalPlan):
    def __init__(self, n: int, child: LogicalPlan, offset: int = 0):
        self.n = n
        self.offset = offset
        self.children = [child]

    @property
    def schema(self):
        return self.children[0].schema

    def _node_string(self):
        return f"Limit[{self.n}]"


class Union(LogicalPlan):
    def __init__(self, children: List[LogicalPlan]):
        self.children = list(children)

    @property
    def schema(self):
        return self.children[0].schema


class Distinct(LogicalPlan):
    def __init__(self, child: LogicalPlan):
        self.children = [child]

    @property
    def schema(self):
        return self.children[0].schema


class Repartition(LogicalPlan):
    def __init__(self, num_partitions: int, child: LogicalPlan,
                 by_exprs: Optional[List[Expression]] = None):
        self.num_partitions = num_partitions
        self.by_exprs = by_exprs
        self.children = [child]

    @property
    def schema(self):
        return self.children[0].schema

    def _node_string(self):
        by = "" if not self.by_exprs else \
            f" by {[output_name(e) for e in self.by_exprs]}"
        return f"Repartition[{self.num_partitions}{by}]"


@dataclasses.dataclass
class WindowSpec:
    partition_by: List[Expression]
    order_by: List[SortOrder]
    # frame: ("rows"|"range", start, end) with None = unbounded
    frame: Tuple[str, Optional[int], Optional[int]] = ("rows", None, None)


class WindowFunc:
    """Marker wrapper for a window function + its spec."""

    def __init__(self, func: Expression, spec: WindowSpec, alias: str):
        self.func = func
        self.spec = spec
        self.alias = alias


class Window(LogicalPlan):
    def __init__(self, window_funcs: List[WindowFunc], child: LogicalPlan):
        self.window_funcs = window_funcs
        self.children = [child]

    @property
    def schema(self):
        base = list(self.children[0].schema.fields)
        for wf in self.window_funcs:
            base.append(Field(wf.alias, wf.func.dtype(), True))
        return Schema(base)

    def _node_string(self):
        return f"Window[{[w.alias for w in self.window_funcs]}]"


class Expand(LogicalPlan):
    """Grouping-sets expand (reference: GpuExpandExec)."""

    def __init__(self, projections: List[List[Expression]],
                 output: Schema, child: LogicalPlan):
        self.projections = projections
        self._schema = output
        self.children = [child]

    @property
    def schema(self):
        return self._schema


class Generate(LogicalPlan):
    """explode/posexplode (reference: GpuGenerateExec.scala).

    Output = the required child columns followed by [pos,] value columns
    of the generator, mirroring Spark's GenerateExec contract.
    """

    def __init__(self, generator, output_names: List[str],
                 child: LogicalPlan):
        # generator: expr.collections.Explode (pos/outer flags live on it)
        self.generator = generator
        self.output_names = list(output_names)
        self.children = [child]

    @property
    def schema(self):
        from ..columnar import dtypes as T
        base = [f for f in self.children[0].schema.fields]
        names = list(self.output_names)
        if self.generator.pos:
            base.append(Field(names.pop(0), T.INT32, self.generator.outer))
        elem = self.generator.dtype()
        base.append(Field(names.pop(0), elem, True))
        return Schema(base)


class MapInPandas(LogicalPlan):
    """df.mapInPandas(fn, schema): fn(Iterator[pd.DataFrame]) ->
    Iterator[pd.DataFrame] per partition.

    Reference: GpuMapInPandasExec (SURVEY.md §2.4 Python execs) — batches
    cross to the Python worker as Arrow; here the worker is in-process
    but the Arrow exchange contract is the same."""

    def __init__(self, fn, out_schema: Schema, child: LogicalPlan):
        self.fn = fn
        self._schema = out_schema
        self.children = [child]

    @property
    def schema(self):
        return self._schema

    def _node_string(self):
        return f"MapInPandas[{getattr(self.fn, '__name__', 'fn')}]"


class GroupedMapInPandas(LogicalPlan):
    """df.groupBy(keys).applyInPandas(fn, schema): fn(pdf) -> pdf per
    key group (fn may also take (key_tuple, pdf)).

    Reference: GpuFlatMapGroupsInPandasExec."""

    def __init__(self, keys: List[Expression], fn, out_schema: Schema,
                 child: LogicalPlan):
        self.keys = keys
        self.fn = fn
        self._schema = out_schema
        self.children = [child]

    @property
    def schema(self):
        return self._schema

    def _node_string(self):
        return f"GroupedMapInPandas[{getattr(self.fn, '__name__', 'fn')}]"


class CogroupedMapInPandas(LogicalPlan):
    """df1.groupBy(k).cogroup(df2.groupBy(k)).applyInPandas(fn, schema):
    fn(left_pdf, right_pdf) (or (key, left, right)) per key present on
    EITHER side (full-outer key union, empty frame for the absent side).

    Reference: GpuFlatMapCoGroupsInPandasExec (SURVEY.md §2.4)."""

    def __init__(self, left_keys: List[Expression],
                 right_keys: List[Expression], fn, out_schema: Schema,
                 left: LogicalPlan, right: LogicalPlan):
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.fn = fn
        self._schema = out_schema
        self.children = [left, right]

    @property
    def schema(self):
        return self._schema

    def _node_string(self):
        return (f"CogroupedMapInPandas"
                f"[{getattr(self.fn, '__name__', 'fn')}]")


class WindowInPandas(LogicalPlan):
    """Pandas aggregate UDF evaluated over an UNBOUNDED window
    partition: every row of a partition gets the UDF's value over the
    whole partition (the common pandas-window shape).

    Reference: GpuWindowInPandasExec (SURVEY.md §2.4); bounded frames
    are not yet lowered (the planner rejects them loudly)."""

    def __init__(self, out_name: str, fn, fn_cols: List[str], out_dtype,
                 partition_by: List[Expression], child: LogicalPlan):
        self.out_name = out_name
        self.fn = fn
        self.fn_cols = list(fn_cols)
        self.out_dtype = out_dtype
        self.partition_by = partition_by
        self.children = [child]

    @property
    def schema(self):
        from ..columnar.schema import Field
        return Schema(list(self.children[0].schema.fields) +
                      [Field(self.out_name, self.out_dtype, True)])

    def _node_string(self):
        return f"WindowInPandas[{self.out_name}]"


class CachedRelation(LogicalPlan):
    """df.cache(): parquet-encoded columnar cache over the child.

    Reference: ParquetCachedBatchSerializer (shims/spark311) behind
    Spark's InMemoryRelation."""

    def __init__(self, child: LogicalPlan, storage):
        self.children = [child]
        self.storage = storage   # exec.cache.CacheStorage

    @property
    def schema(self):
        return self.children[0].schema


class WriteFile(LogicalPlan):
    def __init__(self, fmt: str, path: str, child: LogicalPlan,
                 mode: str = "overwrite", options: Dict[str, Any] = None,
                 partition_by: Optional[List[str]] = None):
        self.fmt = fmt
        self.path = path
        self.mode = mode
        self.options = options or {}
        self.partition_by = list(partition_by or [])
        self.children = [child]

    @property
    def schema(self):
        return Schema([])

    def _node_string(self):
        return f"WriteFile[{self.fmt}]({self.path})"
