"""Cost-based optimizer — reference: CostBasedOptimizer.scala:52

(CpuCostModel/GpuCostModel/RowCountPlanVisitor): estimates CPU-vs-TPU cost
per subtree and forces sections back to the CPU engine when host<->device
transitions outweigh the speedup.  Off by default
(spark.rapids.tpu.sql.optimizer.enabled), like the reference.
"""
from __future__ import annotations

from typing import Dict, Optional

from . import logical as L

# relative per-row operator costs (device is assumed ~8x faster on
# compute-bound ops; transitions cost per byte-ish per row)
TPU_SPEEDUP: Dict[type, float] = {
    L.Project: 6.0, L.Filter: 6.0, L.Aggregate: 10.0, L.Join: 10.0,
    L.Sort: 8.0, L.Window: 10.0, L.Expand: 6.0,
}
TRANSITION_COST_PER_ROW = 3.0
CPU_COST_PER_ROW = 1.0


def _scan_rows(p: "L.Scan", conf=None) -> Optional[float]:
    """Exact file-level cardinality from parquet footers.

    The reference's RowCountPlanVisitor walks Spark's statistics, which
    for file sources come from the same footer metadata.  Delegates to
    the planner's estimator (handles directory/glob path expansion and
    memoizes on the node itself)."""
    from .overrides import _scan_row_estimate
    total = _scan_row_estimate(p, conf)
    return None if total is None else float(total)


def _filter_selectivity(cond) -> float:
    """Predicate-shape selectivity (the reference's filter default is
    a flat multiplier; we refine by comparison kind)."""
    name = type(cond).__name__
    if name == "And":
        return (_filter_selectivity(cond.children[0]) *
                _filter_selectivity(cond.children[1]))
    if name == "Or":
        a = _filter_selectivity(cond.children[0])
        b = _filter_selectivity(cond.children[1])
        return min(1.0, a + b - a * b)
    if name == "Not":
        return max(0.0, 1.0 - _filter_selectivity(cond.children[0]))
    if name in ("EqualTo", "EqualNullSafe"):
        return 0.1
    if name in ("LessThan", "LessThanOrEqual", "GreaterThan",
                "GreaterThanOrEqual"):
        return 0.33
    if name == "In":
        return 0.2
    if name in ("IsNull",):
        return 0.05
    if name in ("IsNotNull",):
        return 0.95
    return 0.5


def estimate_rows(p: L.LogicalPlan, conf=None) -> Optional[float]:
    """RowCountPlanVisitor role: best-effort cardinality estimates."""
    if isinstance(p, L.LocalRelation):
        return float(p.table.num_rows)
    if isinstance(p, L.Scan):
        return _scan_rows(p, conf)
    if isinstance(p, L.Range):
        return float(max(0, -(-(p.end - p.start) // p.step)))
    if isinstance(p, L.Filter):
        r = estimate_rows(p.children[0], conf)
        if r is None:
            return None
        try:
            return r * _filter_selectivity(p.condition)
        except Exception:
            return r * 0.5
    if isinstance(p, L.Limit):
        return float(p.n)
    if isinstance(p, L.Aggregate):
        r = estimate_rows(p.children[0], conf)
        if r is None:
            return None
        if not p.group_exprs:
            return 1.0
        return min(r, r * 0.1 + 100)
    if isinstance(p, L.Join):
        left = estimate_rows(p.children[0], conf)
        right = estimate_rows(p.children[1], conf)
        if left is None or right is None:
            return None
        jt = getattr(p, "join_type", "inner")
        # per-join-type cardinalities (RowCountPlanVisitor role): equi
        # joins against the smaller side behave like lookups; semi/anti
        # filter the left; outer joins keep at least the outer side
        if jt in ("semi", "anti"):
            return left * 0.5
        if jt == "cross" or not getattr(p, "left_keys", None):
            return left * right
        if jt == "full":
            return left + right
        return max(left, right)          # inner / left / right
    if isinstance(p, L.Union):
        vals = [estimate_rows(c, conf) for c in p.children]
        return sum(v for v in vals if v is not None) or None
    if p.children:
        return estimate_rows(p.children[0], conf)
    return None


def tpu_worthwhile(p: L.LogicalPlan, conf=None) -> bool:
    """Would accelerating this node pay for its transitions?

    Used by the planner when the CBO is enabled: tiny inputs stay on the
    CPU engine (the reference forces subtrees back to CPU the same way).
    """
    rows = estimate_rows(p, conf)
    if rows is None:
        return True  # unknown: assume big (matches reference default-on)
    speedup = TPU_SPEEDUP.get(type(p), 4.0)
    cpu_cost = rows * CPU_COST_PER_ROW
    tpu_cost = rows * CPU_COST_PER_ROW / speedup + \
        rows * 0.0 + 2 * TRANSITION_COST_PER_ROW * min(rows, 1024) + 500
    return tpu_cost < cpu_cost


# ---------------------------------------------------------------------------
# transition-aware subtree placement (CostBasedOptimizer.scala:52,246)
# ---------------------------------------------------------------------------

#: fixed cost per host<->device boundary crossing (dispatch + copy setup)
BOUNDARY_COST = 500.0
#: unknown-cardinality default (assume big; matches reference default-on)
DEFAULT_ROWS = 1 << 20


# -- expression-level cost (GpuExpressionCost role, :296) -------------------
# Host-round-trip expressions (general regex, python UDFs, host string
# ops) erase the device advantage for the node that evaluates them; wide
# expression trees add per-row work on both engines.

_HOST_FALLBACK_EXPRS = {"RLike", "RegexpReplace", "RegexpExtract",
                        "Replace", "StringRepeat", "Lpad", "Rpad",
                        "InitCap", "PythonUDF"}


def _expr_weight(e) -> float:
    """(cpu_mult, tpu_penalty) folded into one weight: each node of the
    expression tree costs ~0.1 row-units; host-fallback expressions cost
    the device side a transfer per batch (modeled as a flat row tax)."""
    total = 0.1
    host = 0.0
    stack = [e]
    while stack:
        x = stack.pop()
        total += 0.1
        if type(x).__name__ in _HOST_FALLBACK_EXPRS:
            host += 3.0
        stack.extend(getattr(x, "children", []) or [])
    return total, host


def _node_exprs(p: L.LogicalPlan):
    if isinstance(p, L.Project):
        return list(p.exprs)
    if isinstance(p, L.Filter):
        return [p.condition]
    if isinstance(p, L.Aggregate):
        return [a.func for a in p.aggs] + list(p.group_exprs)
    if isinstance(p, L.Join) and getattr(p, "condition", None) is not None:
        return [p.condition]
    return []


def _node_costs(p: L.LogicalPlan, conf=None):
    """(cpu_cost, tpu_cost) of running THIS node on each engine.

    Per-op tables (CostBasedOptimizer.scala:246,296 roles): base
    per-row cost scaled by expression-tree weight; sorts pay log(n);
    host-fallback expressions tax the device side per row."""
    import math
    rows = estimate_rows(p, conf)
    if rows is None:
        rows = float(DEFAULT_ROWS)
    speedup = TPU_SPEEDUP.get(type(p), 4.0)
    ew, host_tax = 0.0, 0.0
    for e in _node_exprs(p):
        w, h = _expr_weight(e)
        ew += w
        host_tax += h
    per_row = CPU_COST_PER_ROW * (1.0 + ew)
    if isinstance(p, L.Sort):
        per_row *= max(1.0, math.log2(max(rows, 2.0)) / 4.0)
    cpu = rows * per_row
    tpu = rows * per_row / speedup + rows * host_tax
    return cpu, tpu


def _transition(rows, same_side: bool) -> float:
    if same_side:
        return 0.0
    return BOUNDARY_COST + TRANSITION_COST_PER_ROW * min(
        rows if rows is not None else DEFAULT_ROWS, 1 << 16)


def choose_placement(root: L.LogicalPlan,
                     conf=None) -> Dict[int, str]:
    """Two-state DP over the plan tree (the reference's
    ``optimizeGpuPlanTransitions`` recursion, CostBasedOptimizer:246):
    ``best(node, parent_side)`` = cheapest cost of the subtree when the
    parent consumes its output on ``parent_side``, charging a
    host<->device transition whenever node and parent sides differ.
    Returns {id(node): 'cpu'|'tpu'} — the planner forces 'cpu' nodes to
    the CPU engine even when a TPU conversion exists, exactly like the
    reference forcing cheap sections back to the CPU plan."""
    memo: Dict[tuple, tuple] = {}

    def best(p: L.LogicalPlan, parent_side: str):
        key = (id(p), parent_side)
        hit = memo.get(key)
        if hit is not None:
            return hit
        rows = estimate_rows(p, conf)
        cpu_c, tpu_c = _node_costs(p, conf)
        totals = {}
        for side, own in (("cpu", cpu_c), ("tpu", tpu_c)):
            t = own + _transition(rows, side == parent_side)
            for c in p.children:
                t += best(c, side)[0]
            totals[side] = t
        side = "cpu" if totals["cpu"] <= totals["tpu"] else "tpu"
        out = (totals[side], side)
        memo[key] = out
        return out

    placement: Dict[int, str] = {}

    def assign(p: L.LogicalPlan, parent_side: str):
        _, side = best(p, parent_side)
        placement[id(p)] = side
        for c in p.children:
            assign(c, side)

    # the root hands rows to the session collector (host side)
    assign(root, "cpu")
    return placement
