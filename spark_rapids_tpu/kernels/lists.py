"""List (ArrayType) kernels over Arrow offsets+elements device layout.

Reference analogue: cuDF list kernels used by collectionOperations.scala
(Size/ElementAt/ArrayContains/SortArray) and GpuGenerateExec.scala
(explode/posexplode).  TPU-first: lists have no native XLA type, so every
op is integer arithmetic over the offsets buffer — searchsorted row
assignment, segmented reductions (jax.ops.segment_*), and gathers —
all static-shape, mirroring the string kernels (kernels/strings.py).

The one dynamic quantity (total element count of a gather/explode result)
is a single scalar pulled to host to pick the power-of-two output bucket,
the same "size on host, fill on device" two-phase pattern gather_strings
uses.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..columnar.column import bucket_capacity


@jax.jit
def list_lengths(offsets) -> jnp.ndarray:
    return (offsets[1:] - offsets[:-1]).astype(jnp.int32)


@jax.jit
def gather_list_offsets(offsets, validity, indices):
    """Phase 1 of a list-column row gather: new offsets + element total.

    Returns (new_offsets[ncap+1], gathered_validity[ncap],
    src_starts[ncap], total_elements scalar).
    """
    starts = offsets[:-1]
    lens = offsets[1:] - starts
    src = jnp.clip(indices, 0, starts.shape[0] - 1)
    glens = jnp.take(lens, src)
    gvalid = jnp.take(validity, src)
    glens = jnp.where(gvalid, glens, 0)
    new_offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(glens).astype(jnp.int32)])
    return new_offsets, gvalid, jnp.take(starts, src), new_offsets[-1]


@functools.partial(jax.jit, static_argnames=("elem_cap",))
def element_gather_indices(new_offsets, src_starts, elem_cap: int):
    """Phase 2: for each output element slot, the source element index.

    Returns (src_idx[elem_cap], live[elem_cap]): slot j belongs to output
    row r = searchsorted(new_offsets, j); its source element is
    src_starts[r] + (j - new_offsets[r]).
    """
    j = jnp.arange(elem_cap, dtype=jnp.int32)
    row = jnp.searchsorted(new_offsets[1:], j, side="right").astype(jnp.int32)
    row = jnp.clip(row, 0, new_offsets.shape[0] - 2)
    within = j - new_offsets[row]
    src_idx = jnp.take(src_starts, row) + within
    live = j < new_offsets[-1]
    return jnp.where(live, src_idx, 0), live


@functools.partial(jax.jit, static_argnames=("num_rows", "outer"))
def explode_offsets(offsets, validity, num_rows: int, outer: bool):
    """Per-row output counts for explode (GpuGenerateExec.scala role).

    explode emits one output row per element; null/empty lists emit 0 rows
    (or exactly 1 all-null row when ``outer``).  Returns
    (out_offsets[cap+1], total scalar).
    """
    cap = offsets.shape[0] - 1
    lens = offsets[1:] - offsets[:-1]
    live_row = jnp.arange(cap) < num_rows
    counts = jnp.where(validity & live_row, lens, 0)
    if outer:
        counts = jnp.where(live_row & (counts == 0), 1, counts)
    out_offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts).astype(jnp.int32)])
    return out_offsets, out_offsets[-1]


@functools.partial(jax.jit, static_argnames=("out_cap",))
def explode_indices(offsets, validity, out_offsets, out_cap: int):
    """Row/element/position indices for each exploded output row.

    Returns (row_idx, elem_idx, pos, elem_valid, live) each [out_cap]:
    output slot j came from input row row_idx[j], source element
    elem_idx[j] (= offsets[row]+pos), at list position pos[j].
    ``elem_valid`` is False for the synthetic null row of outer-explode
    on an empty/null list.
    """
    j = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.searchsorted(out_offsets[1:], j, side="right").astype(jnp.int32)
    row = jnp.clip(row, 0, out_offsets.shape[0] - 2)
    pos = j - out_offsets[row]
    starts = offsets[:-1]
    lens = offsets[1:] - starts
    elem_idx = jnp.take(starts, row) + pos
    elem_valid = jnp.take(validity, row) & (pos < jnp.take(lens, row))
    live = j < out_offsets[-1]
    return row, jnp.where(elem_valid & live, elem_idx, 0), pos, \
        elem_valid & live, live


def segment_ids_for(offsets, elem_cap: int):
    """Row id [elem_cap] of each element; n_lists for dead slots."""
    return _segment_ids(offsets, elem_cap)


@functools.partial(jax.jit, static_argnames=("elem_cap",))
def _segment_ids(offsets, elem_cap: int):
    j = jnp.arange(elem_cap, dtype=jnp.int32)
    row = jnp.searchsorted(offsets[1:], j, side="right").astype(jnp.int32)
    n_lists = offsets.shape[0] - 1
    # offsets may start past 0 for sliced columns; leading slots are dead
    live = (j >= offsets[0]) & (j < offsets[-1])
    return jnp.where(live, jnp.clip(row, 0, n_lists - 1), n_lists)


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segmented_any(flags, seg_ids, num_segments: int):
    """OR-reduce boolean flags per segment."""
    return jax.ops.segment_max(flags.astype(jnp.int32), seg_ids,
                               num_segments=num_segments) > 0


