"""Join kernels — the device core of GpuHashJoin/JoinGatherer

(reference: GpuHashJoin.scala:62, JoinGatherer.scala).

TPU-first: instead of cuDF's GPU hash table build+probe, the build side is
sorted by canonical key words and every probe row runs a vectorized binary
search (lower/upper bound) — O(log n) integer compares per row, fully
static-shape, no data-dependent control flow.  Match expansion ("gather
maps") is a cumsum + searchsorted expansion with host-sized output capacity,
playing the JoinGatherer role of bounding output batch size.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import List

import jax
import jax.numpy as jnp
from jax import lax

from . import canon
from .sort import sorted_words
from ..obs.trace import traced


@dataclasses.dataclass
class BuildTable:
    """Sorted build side: canonical words + permutation back to original rows."""
    sorted_words: List[jnp.ndarray]
    perm: jnp.ndarray
    capacity: int


@traced("join_build")
def build(words: List[jnp.ndarray]) -> BuildTable:
    ws, perm = sorted_words(words)
    return BuildTable(ws, perm, int(perm.shape[0]))


def _bsearch(build_words: List[jnp.ndarray], probe_words: List[jnp.ndarray],
             upper: bool):
    """Vectorized lower/upper bound of each probe tuple in sorted build words."""
    bcap = build_words[0].shape[0]
    pcap = probe_words[0].shape[0]
    steps = max(1, (bcap - 1).bit_length() + 1)
    # zero derived from the probe words so the fori_loop carry keeps
    # their varying-manual-axes type under shard_map (a plain
    # jnp.zeros carry is unvarying and the loop rejects the mismatch)
    lo = (probe_words[0] ^ probe_words[0]).astype(jnp.int32)
    hi = lo + jnp.int32(bcap)
    prows = jnp.arange(pcap, dtype=jnp.int32)

    def body(_, state):
        lo, hi = state
        mid = (lo + hi) // 2
        midc = jnp.clip(mid, 0, bcap - 1)
        if upper:
            # first index where probe < build[mid]
            plt = canon.words_less(probe_words, prows, build_words, midc)
            go_right = ~plt
        else:
            # first index where NOT build[mid] < probe
            blt = canon.words_less(build_words, midc, probe_words, prows)
            go_right = blt
        active = lo < hi
        new_lo = jnp.where(active & go_right, mid + 1, lo)
        new_hi = jnp.where(active & ~go_right, mid, hi)
        return new_lo, new_hi

    lo, hi = lax.fori_loop(0, steps, body, (lo, hi))
    return lo


@dataclasses.dataclass
class JoinCounts:
    lo: jnp.ndarray            # per-probe-row first build position
    counts: jnp.ndarray        # per-probe-row match count
    matched: jnp.ndarray       # counts > 0 (valid probe rows only)


@traced("join_probe_counts")
def probe_counts(bt: BuildTable, probe_words: List[jnp.ndarray],
                 probe_num_rows: int,
                 null_equals_null: bool = False) -> JoinCounts:
    pcap = probe_words[0].shape[0]
    lo = _bsearch(bt.sorted_words, probe_words, upper=False)
    hi = _bsearch(bt.sorted_words, probe_words, upper=True)
    counts = (hi - lo).astype(jnp.int32)
    in_range = jnp.arange(pcap) < probe_num_rows
    # probe rows with any null key never match (rank word 0), unless
    # null-safe equality is requested (reference: GpuEqualNullSafe)
    if null_equals_null:
        usable = in_range
    else:
        all_valid = probe_words[0] == jnp.uint64(1)
        usable = in_range & all_valid
    counts = jnp.where(usable, counts, 0)
    return JoinCounts(lo, counts, counts > 0)


@functools.partial(jax.jit, static_argnames=("out_cap",))
@traced("join_expand_matches")
def expand_matches(lo, counts, perm, out_cap: int):
    """Expand (lo, counts) into flat (probe_idx, build_idx) gather maps.

    Output row t belongs to probe row p where exclusive-cumsum[p] <= t <
    inclusive-cumsum[p]; its build position is lo[p] + (t - excl[p]).
    """
    incl = jnp.cumsum(counts.astype(jnp.int64))
    excl = incl - counts
    total = incl[-1]
    t = jnp.arange(out_cap, dtype=jnp.int64)
    p = jnp.searchsorted(incl, t, side="right").astype(jnp.int32)
    pc = jnp.clip(p, 0, counts.shape[0] - 1)
    build_pos = jnp.take(lo, pc) + (t - jnp.take(excl, pc)).astype(jnp.int32)
    build_pos = jnp.clip(build_pos, 0, perm.shape[0] - 1)
    build_idx = jnp.take(perm, build_pos)
    live = t < total
    return pc, build_idx, live, total


def total_matches(counts) -> int:
    """Host sync: total output rows (sizes the output capacity bucket)."""
    from ..analysis import residency  # lazy: avoids import cycle
    with residency.declared_transfer(site="size_probe"):
        return int(jnp.sum(counts.astype(jnp.int64)))
