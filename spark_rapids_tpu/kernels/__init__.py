"""Relational compute kernels — the cuDF/libcudf role (SURVEY.md §2.10.1),

implemented as JAX/XLA computations with Pallas reserved for ops XLA can't
express well. Modules: canon (sortable key words), sort, aggregate (sort +
segmented reduce), join (sorted binary-search probe), strings, basic
(compaction, hashing)."""
from . import basic, canon, sort, aggregate, join, strings  # noqa: F401
