"""Pallas TPU kernels for hot ops XLA doesn't fuse well.

Reference analogue: the hand-written CUDA kernels inside libcudf that the
plugin leans on for hashing/partitioning (GpuHashPartitioning ->
murmur3 + contiguousSplit).  Here the fused hash+partition-id kernel is
written in Pallas so the multi-word mixing chain stays in VMEM in one
pass instead of N elementwise HLOs round-tripping through HBM.

Falls back to interpret mode off-TPU (CPU tests) and to the plain jnp
path on any Pallas failure — behavior is identical by construction.
"""
from __future__ import annotations

import functools
from typing import List

import jax
import jax.numpy as jnp

from ..obs import compile_watch as _compile_watch
from ..obs.registry import compile_cache_event
from .basic import M1, M2, mix64, hash_words as _hash_words_jnp

_BLOCK = 1024


def _mix_body(h, w):
    x = h ^ w
    x = x ^ (x >> jnp.uint64(33))
    x = x * jnp.uint64(M1)
    x = x ^ (x >> jnp.uint64(33))
    x = x * jnp.uint64(M2)
    x = x ^ (x >> jnp.uint64(33))
    return x


def _make_kernel(num_words: int, num_parts: int):
    from jax.experimental import pallas as pl

    def kernel(*refs):
        word_refs = refs[:num_words]
        out_ref = refs[num_words]
        h = jnp.full(word_refs[0].shape, jnp.uint64(42))
        for wr in word_refs:
            h = _mix_body(h, wr[...])
        out_ref[...] = (h % jnp.uint64(num_parts)).astype(jnp.int32)

    @functools.partial(jax.jit, static_argnames=())
    def run(*words):
        n = words[0].shape[0]
        grid = (n // _BLOCK,) if n % _BLOCK == 0 and n >= _BLOCK else None
        interpret = jax.default_backend() != "tpu"
        if grid is None:
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
                interpret=interpret,
            )(*words)
        spec = pl.BlockSpec((_BLOCK,), lambda i: (i,))
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[spec] * num_words,
            out_specs=spec,
            out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
            interpret=interpret,
        )(*words)

    return run


_KERNEL_CACHE = {}


def hash_partition_ids(word_lists: List[jnp.ndarray],
                       num_parts: int) -> jnp.ndarray:
    """Fused murmur-mix + mod over N key words -> partition id per row.

    Pallas fast path with jnp fallback (identical math either way).
    """
    key = (len(word_lists), num_parts)
    from ..compile import aot as _aot
    _aot.note_demand("pallas_hash_partition", word_lists[0].shape[0])
    try:
        if key not in _KERNEL_CACHE:
            compile_cache_event("pallas_hash_partition", False)
            _KERNEL_CACHE[key] = _compile_watch.wrap_miss(
                "pallas_hash_partition", _make_kernel(*key), str(key))
            kfn, nw = _KERNEL_CACHE[key], key[0]
            def _warm(bucket: int) -> None:
                kfn(*[jnp.zeros(bucket, jnp.uint64) for _ in range(nw)])
            _aot.register_warmer("pallas_hash_partition", _warm,
                                 str(key))
        else:
            compile_cache_event("pallas_hash_partition", True)
        return _KERNEL_CACHE[key](*word_lists)
    except Exception:
        h = _hash_words_jnp(word_lists)
        return (h % jnp.uint64(num_parts)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Bucket-table reduce: the device core of the sort-free group-by
# (kernels/aggregate.py table_plan).  For each of k f32 rows, reduce row
# values into `table` buckets with a per-row op ('sum' | 'max').
#
# Why Pallas: XLA lowers the equivalent one-hot einsum to a convolution
# that MATERIALIZES the (n, table) one-hot in HBM (measured 39 GB of
# traffic at n=1M, table=4096).  Here the one-hot tile lives only in
# VMEM: sums ride the MXU as (rows, C) @ (C, Gt) dots, maxes are VPU
# masked reductions, and HBM traffic is just inputs x (table/Gt) passes.
# Reference analogue: the hand-rolled cuDF hash-aggregate kernels.
# ---------------------------------------------------------------------------

_TR_C = 512      # chunk columns (x8 chunk-rows = 4096 rows per step)
_TR_G = 512      # bucket chunk for the in-kernel one-hot loop
# VMEM budget: the transient one-hot chunk is (4096, 512) f32 = 8 MB,
# reused across the g-loop; accumulators are (rows, table) f32 = <100 KB.


def _z(i):
    """An i32 zero derived from a program id (index maps must not return
    python-int literals: under jax_enable_x64 they trace as i64 and
    Mosaic cannot legalize the index-map function's i64 return)."""
    return i - i


def _table_reduce_kernel(nsum: int, nmax: int, gt: int):
    from jax.experimental import pallas as pl

    def kernel(bucket_ref, sums_in_ref, maxs_in_ref, sum_out_ref,
               max_out_ref):
        # All tensors stay 2-D with contractions on the lane (last) dim —
        # Mosaic cannot shape-cast across lanes, so no reshapes; the
        # bucket-chunk/sub-row loops are fori_loops so the (G_t, C)
        # transients are reused, not stacked (VMEM is 16 MB scoped).
        r = pl.program_id(0)
        rb = bucket_ref.shape[0]

        @pl.when(r == 0)
        def _init():
            sum_out_ref[...] = jnp.zeros_like(sum_out_ref)
            max_out_ref[...] = jnp.full_like(max_out_ref, -jnp.inf)

        def g_body(gi, _):
            iot = jax.lax.broadcasted_iota(
                jnp.int32, (_TR_G, _TR_C), 0) + gi * _TR_G
            sl = pl.dslice(gi * _TR_G, _TR_G)

            def r_body(rr, _):
                b = bucket_ref[pl.dslice(rr, 1), :]       # (1, C)
                oht = (b == iot)                          # (G_t, C) bool
                if nsum:
                    sv = sums_in_ref[:, rr, :]            # (nsum, C)
                    contrib = jax.lax.dot_general(
                        sv, oht.astype(jnp.float32),
                        (((1,), (1,)), ((), ())),
                        precision=jax.lax.Precision.HIGHEST)
                    sum_out_ref[:, sl] += contrib         # (nsum, G_t)
                if nmax:
                    for i in range(nmax):
                        mv = maxs_in_ref[i, pl.dslice(rr, 1), :]  # (1, C)
                        masked = jnp.where(oht, mv, -jnp.inf)
                        max_out_ref[pl.dslice(i, 1), sl] = jnp.maximum(
                            max_out_ref[pl.dslice(i, 1), sl],
                            jnp.max(masked, axis=1)[None, :])
                return 0
            return jax.lax.fori_loop(0, rb, r_body, 0)
        jax.lax.fori_loop(0, gt // _TR_G, g_body, 0)

    return kernel


@functools.partial(jax.jit, static_argnames=("table", "nsum", "nmax"))
def _table_reduce_tpu(bucket, sums_in, maxs_in, table: int, nsum: int,
                      nmax: int):
    # Trace with x64 OFF: every kernel type here is 32-bit, and pallas
    # fori_loop tracing under jax_enable_x64 hits an infinite promotion
    # recursion (i64 loop indices vs i32 vector math).
    with jax.enable_x64(False):
        return _table_reduce_tpu_32(bucket, sums_in, maxs_in, table,
                                    nsum, nmax)


def _table_reduce_tpu_32(bucket, sums_in, maxs_in, table: int, nsum: int,
                         nmax: int):
    from jax.experimental import pallas as pl
    n = bucket.shape[0]
    gt = (table + _TR_G) // _TR_G * _TR_G          # cover table+1 dead slot
    rows_step = 8 * _TR_C
    pad = (-n) % rows_step
    if pad:
        bucket = jnp.concatenate(
            [bucket, jnp.full(pad, table, jnp.int32)])
        zs = jnp.zeros((sums_in.shape[0], pad), jnp.float32)
        sums_in = jnp.concatenate([sums_in, zs], axis=1)
        zm = jnp.full((maxs_in.shape[0], pad), -jnp.inf, jnp.float32)
        maxs_in = jnp.concatenate([maxs_in, zm], axis=1)
    npad = bucket.shape[0]
    r_steps = npad // rows_step
    bucket2 = bucket.reshape(r_steps * 8, _TR_C)
    sums2 = sums_in.reshape(sums_in.shape[0], r_steps * 8, _TR_C)
    maxs2 = maxs_in.reshape(maxs_in.shape[0], r_steps * 8, _TR_C)
    grid = (r_steps,)
    kernel = _table_reduce_kernel(nsum, nmax, gt)
    sum_out, max_out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((8, _TR_C), lambda r: (r, _z(r))),
            pl.BlockSpec((max(nsum, 1), 8, _TR_C),
                         lambda r: (_z(r), r, _z(r))),
            pl.BlockSpec((max(nmax, 1), 8, _TR_C),
                         lambda r: (_z(r), r, _z(r))),
        ],
        out_specs=[
            pl.BlockSpec((max(nsum, 1), gt), lambda r: (_z(r), _z(r))),
            pl.BlockSpec((max(nmax, 1), gt), lambda r: (_z(r), _z(r))),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((max(nsum, 1), gt), jnp.float32),
            jax.ShapeDtypeStruct((max(nmax, 1), gt), jnp.float32),
        ],
    )(bucket2, sums2, maxs2)
    return sum_out, max_out


def table_reduce(bucket, sum_rows, max_rows, table: int,
                 impl: str = "scatter"):
    """Reduce f32 rows into `table` buckets (+1 dead slot dropped).

    sum_rows: list of f32[n] contribution rows (dead rows must be 0).
    max_rows: list of f32[n] rows (dead rows must be -inf); min via
    caller-side negation.  Returns (sums: list of f32[table],
    maxs: list of f32[table]).

    impl='scatter' (default): one multi-column XLA scatter-add for all
    sum rows + per-row scatter-max — measured ~80ms/4M rows on v5e, and
    the multi-column scatter costs the same as a single-column one.
    impl='pallas': the hand-written one-hot MXU kernel above — currently
    slower (~150ms/4M: Mosaic's scoped-VMEM limit forces small dot
    tiles whose loop overhead dominates); kept selectable via
    spark.rapids.tpu.sql.agg.tableReduceImpl for kernel tuning work.
    """
    nsum, nmax = len(sum_rows), len(max_rows)
    if impl == "pallas" and jax.default_backend() == "tpu":
        sums_in = jnp.stack(sum_rows, 0) if nsum else \
            jnp.zeros((1, bucket.shape[0]), jnp.float32)
        maxs_in = jnp.stack(max_rows, 0) if nmax else \
            jnp.full((1, bucket.shape[0]), -jnp.inf, jnp.float32)
        sum_out, max_out = _table_reduce_tpu(
            bucket, sums_in, maxs_in, table, nsum, nmax)
        return ([sum_out[i][:table] for i in range(nsum)],
                [max_out[i][:table] for i in range(nmax)])
    sums = []
    if nsum:
        stacked = jnp.stack(sum_rows, 1)            # (n, nsum)
        out = jax.ops.segment_sum(stacked, bucket,
                                  num_segments=table + 1)
        sums = [out[:, i][:table] for i in range(nsum)]
    maxs = [jax.ops.segment_max(r, bucket, num_segments=table + 1)[:table]
            for r in max_rows]
    return sums, maxs


# ---------------------------------------------------------------------------
# program audit registration (analysis/program_audit.py)
# ---------------------------------------------------------------------------

def _audit_specs():
    from ..analysis.program_audit import AuditSpec

    def _build():
        import jax
        import numpy as np
        key = (1, 8)
        fn = _KERNEL_CACHE.get(key)
        if fn is None:
            fn = _compile_watch.wrap_miss(
                "pallas_hash_partition", _make_kernel(*key), str(key))
            _KERNEL_CACHE[key] = fn
        args = (jax.ShapeDtypeStruct((256,), np.uint64),)
        return fn, args, {}

    return [AuditSpec(
        "pallas_hash_partition", "pallas_hash_partition", _build,
        notes="1 key word -> 8 partitions over a 256-row block",
        budgets={"gather": 2, "scatter": 2, "transpose": 2, "sort": 1})]
