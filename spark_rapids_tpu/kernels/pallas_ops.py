"""Pallas TPU kernels for hot ops XLA doesn't fuse well.

Reference analogue: the hand-written CUDA kernels inside libcudf that the
plugin leans on for hashing/partitioning (GpuHashPartitioning ->
murmur3 + contiguousSplit).  Here the fused hash+partition-id kernel is
written in Pallas so the multi-word mixing chain stays in VMEM in one
pass instead of N elementwise HLOs round-tripping through HBM.

Falls back to interpret mode off-TPU (CPU tests) and to the plain jnp
path on any Pallas failure — behavior is identical by construction.
"""
from __future__ import annotations

import functools
from typing import List

import jax
import jax.numpy as jnp

from .basic import M1, M2, mix64, hash_words as _hash_words_jnp

_BLOCK = 1024


def _mix_body(h, w):
    x = h ^ w
    x = x ^ (x >> jnp.uint64(33))
    x = x * jnp.uint64(M1)
    x = x ^ (x >> jnp.uint64(33))
    x = x * jnp.uint64(M2)
    x = x ^ (x >> jnp.uint64(33))
    return x


def _make_kernel(num_words: int, num_parts: int):
    from jax.experimental import pallas as pl

    def kernel(*refs):
        word_refs = refs[:num_words]
        out_ref = refs[num_words]
        h = jnp.full(word_refs[0].shape, jnp.uint64(42))
        for wr in word_refs:
            h = _mix_body(h, wr[...])
        out_ref[...] = (h % jnp.uint64(num_parts)).astype(jnp.int32)

    @functools.partial(jax.jit, static_argnames=())
    def run(*words):
        n = words[0].shape[0]
        grid = (n // _BLOCK,) if n % _BLOCK == 0 and n >= _BLOCK else None
        interpret = jax.default_backend() != "tpu"
        if grid is None:
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
                interpret=interpret,
            )(*words)
        spec = pl.BlockSpec((_BLOCK,), lambda i: (i,))
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[spec] * num_words,
            out_specs=spec,
            out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
            interpret=interpret,
        )(*words)

    return run


_KERNEL_CACHE = {}


def hash_partition_ids(word_lists: List[jnp.ndarray],
                       num_parts: int) -> jnp.ndarray:
    """Fused murmur-mix + mod over N key words -> partition id per row.

    Pallas fast path with jnp fallback (identical math either way).
    """
    key = (len(word_lists), num_parts)
    try:
        if key not in _KERNEL_CACHE:
            _KERNEL_CACHE[key] = _make_kernel(*key)
        return _KERNEL_CACHE[key](*word_lists)
    except Exception:
        h = _hash_words_jnp(word_lists)
        return (h % jnp.uint64(num_parts)).astype(jnp.int32)
